// In-band fleet observability plane — see fleetobs.h for the design
// contract and docs/fleet.md for the operator view.
#include "tpucoll/common/fleetobs.h"

#include <algorithm>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <utility>

#include "tpucoll/common/env.h"
#include "tpucoll/common/flightrec.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/common/metrics.h"
#include "tpucoll/common/tracer.h"
#include "tpucoll/context.h"
#include "tpucoll/group/topology.h"
#include "tpucoll/transport/unbound_buffer.h"
#include "tpucoll/types.h"

namespace tpucoll {
namespace fleetobs {

namespace {

using Value = JsonReader::Value;

// Detector kinds. The flight-recorder opcodes must be static strings
// (the ring stores the pointer); keeping kind and opcode side by side
// here is what guarantees /fleet and /flightrec spell them the same.
constexpr const char* kKindStraggler = "persistent_straggler";
constexpr const char* kKindSlowLink = "slow_link";
constexpr const char* kKindLeaseJitter = "lease_jitter";

const char* anomalyOpcode(const char* kind) {
  if (std::strcmp(kind, kKindStraggler) == 0) {
    return "anomaly:persistent_straggler";
  }
  if (std::strcmp(kind, kKindSlowLink) == 0) {
    return "anomaly:slow_link";
  }
  if (std::strcmp(kind, kKindLeaseJitter) == 0) {
    return "anomaly:lease_jitter";
  }
  return "anomaly:unknown";
}

// Relay slots: member -> leader reports under tag 0, leader -> rank 0
// host documents under tag 1, each offset by the SENDER's global rank
// so concurrent senders never share a (slot, src) stream.
uint64_t memberSlot(int senderRank) {
  return Slot::build(SlotPrefix::kFleetObs, 0)
      .offset(static_cast<uint64_t>(senderRank))
      .value();
}
uint64_t leaderSlot(int senderRank) {
  return Slot::build(SlotPrefix::kFleetObs, 1)
      .offset(static_cast<uint64_t>(senderRank))
      .value();
}

double numField(const Value& obj, const char* name, double dflt) {
  const Value* f = obj.field(name);
  return f != nullptr && f->kind == Value::Kind::kNumber ? f->number : dflt;
}

// Trim the space padding a fixed-size report rides in.
std::string trimmed(const char* data, size_t n) {
  while (n > 0 && (data[n - 1] == ' ' || data[n - 1] == '\0')) {
    n--;
  }
  return std::string(data, n);
}

// How stale a relayed document may get (in the RECEIVER's rounds)
// before it stops counting as coverage. Receiver-side by design:
// steady clocks are not comparable across processes.
constexpr int64_t kStaleRounds = 5;

}  // namespace

Options Options::fromEnv() {
  Options o;
  o.enabled = envFlag("TPUCOLL_FLEETOBS", true);
  o.intervalMs = envCount("TPUCOLL_FLEETOBS_INTERVAL_MS", 1000, 10, 600000);
  o.maxBytes = std::max<size_t>(
      envBytes("TPUCOLL_FLEETOBS_MAX_BYTES", 32768), 4096);
  o.opsTail = static_cast<int>(envCount("TPUCOLL_FLEETOBS_OPS", 64, 0, 4096));
  o.windowRounds =
      static_cast<int>(envCount("TPUCOLL_FLEETOBS_WINDOW", 30, 2, 10000));
  o.stragglerMs =
      envCount("TPUCOLL_FLEETOBS_STRAGGLER_MS", 200, 1, 86400000);
  return o;
}

FleetObs::FleetObs(Context* ctx) : ctx_(ctx) {}

FleetObs::~FleetObs() { stop(); }

void FleetObs::start() {
  opts_ = Options::fromEnv();
  if (!opts_.enabled) {
    TC_INFO("fleetobs: disabled by TPUCOLL_FLEETOBS=0");
    return;
  }
  if (running()) {
    return;
  }
  std::shared_ptr<const Topology> topo = ctx_->topology();
  TC_ENFORCE(topo != nullptr,
             "fleetobs: start() requires a connected context");

  isLeader_ = topo->isLeader;
  leaderRank_ = topo->leader;
  hostIndex_ = topo->hostIndex;
  localMembers_.clear();
  otherLeaders_.clear();
  for (int r : topo->hosts[topo->hostIndex]) {
    if (r != ctx_->rank() && isLeader_) {
      localMembers_.push_back(r);
    }
  }
  if (ctx_->rank() == 0) {
    for (int h = 1; h < topo->nHosts(); h++) {
      otherLeaders_.push_back(topo->hosts[h][0]);
    }
  }

  // Wire buffers. Registered up front (one ubuf_create per endpoint,
  // never per round) and reused for the lifetime of the service.
  auto makeLink = [&](int rank, uint64_t slot, size_t nbytes) {
    PeerLink p;
    p.rank = rank;
    p.slot = slot;
    p.bytes.assign(nbytes, ' ');
    p.ubuf = ctx_->createUnboundBuffer(p.bytes.data(), nbytes);
    return p;
  };
  // Uplinks carry OUR rank in the slot (sender-keyed streams);
  // downlinks carry the sender's.
  if (!isLeader_) {
    up_ = makeLink(leaderRank_, memberSlot(ctx_->rank()), opts_.maxBytes);
  } else if (ctx_->rank() != 0) {
    up_ = makeLink(0, leaderSlot(ctx_->rank()), hostDocBytes(hostIndex_));
  }
  members_.clear();
  for (int m : localMembers_) {
    members_.push_back(makeLink(m, memberSlot(m), opts_.maxBytes));
    PeerLink& p = members_.back();
    p.ubuf->recv(p.rank, p.slot, 0, p.bytes.size());
    p.posted = true;
  }
  leaders_.clear();
  if (ctx_->rank() == 0) {
    for (int l : otherLeaders_) {
      leaders_.push_back(
          makeLink(l, leaderSlot(l), hostDocBytes(topo->hostOf[l])));
      PeerLink& p = leaders_.back();
      p.ubuf->recv(p.rank, p.slot, 0, p.bytes.size());
      p.posted = true;
    }
  }

  {
    std::lock_guard<std::mutex> guard(stopMu_);
    stopRequested_ = false;
  }
  running_.store(true, std::memory_order_release);
  thread_ = std::thread([this] { runLoop(); });
}

void FleetObs::stop() {
  {
    std::lock_guard<std::mutex> guard(stopMu_);
    if (stopRequested_ && !thread_.joinable()) {
      return;
    }
    stopRequested_ = true;
  }
  stopCv_.notify_all();
  // Unblock any wire wait the tick is sitting in.
  auto abortLink = [](PeerLink& p) {
    if (p.ubuf != nullptr) {
      p.ubuf->abortWaitSend();
      p.ubuf->abortWaitRecv();
    }
  };
  abortLink(up_);
  for (auto& p : members_) {
    abortLink(p);
  }
  for (auto& p : leaders_) {
    abortLink(p);
  }
  if (thread_.joinable()) {
    thread_.join();
  }
  running_.store(false, std::memory_order_release);
  // Release the buffers while the transport is still alive: a posted
  // recv is cancelled by ~UnboundBuffer, which needs the mesh.
  up_ = PeerLink();
  members_.clear();
  leaders_.clear();
}

void FleetObs::setAux(std::string auxJson) {
  if (!auxJson.empty()) {
    JsonReader(auxJson, "fleetobs aux").parse();  // throws on malformed
  }
  std::lock_guard<std::mutex> guard(auxMu_);
  auxJson_ = std::move(auxJson);
}

std::string FleetObs::fleetJson() {
  {
    std::lock_guard<std::mutex> guard(fleetMu_);
    if (!fleetJson_.empty()) {
      return fleetJson_;
    }
  }
  std::ostringstream out;
  out << "{\"version\":1,\"kind\":\"fleet\",\"rank\":" << ctx_->rank()
      << ",\"size\":" << ctx_->size() << ",\"enabled\":"
      << (opts_.enabled && running() ? "true" : "false") << ",\"role\":\""
      << (ctx_->rank() == 0 ? "root" : (isLeader_ ? "leader" : "member"))
      << "\",\"hosts\":[],\"coverage\":{\"expected\":" << ctx_->size()
      << ",\"reported\":0,\"missing\":[";
  // An honest stub: nobody has reported, so every rank is missing
  // (consumers must never read "missing: []" as complete coverage).
  for (int r = 0; r < ctx_->size(); r++) {
    out << (r == 0 ? "" : ",") << r;
  }
  out << "]},\"note\":"
      << (ctx_->rank() == 0
              ? "\"no aggregation round has completed yet\""
              : "\"fleet view is aggregated at rank 0\"")
      << "}";
  return out.str();
}

size_t FleetObs::hostDocBytes(int hostIndex) const {
  // Deterministic on both ends of the leader -> rank 0 relay: wrapper
  // slack plus one report slot per member of that host. Both sides
  // compute it from the same topology, so the posted recv size always
  // matches the sent document size.
  std::shared_ptr<const Topology> topo = ctx_->topology();
  const size_t members = topo != nullptr && hostIndex < topo->nHosts()
                             ? topo->hosts[hostIndex].size()
                             : 1;
  return 8192 + opts_.maxBytes * members;
}

void FleetObs::runLoop() {
  while (true) {
    {
      std::unique_lock<std::mutex> lock(stopMu_);
      stopCv_.wait_for(lock, std::chrono::milliseconds(opts_.intervalMs),
                       [&] { return stopRequested_; });
      if (stopRequested_) {
        return;
      }
    }
    try {
      round_++;
      tick();
    } catch (const std::exception& e) {
      // A torn round must never kill the plane (and never the process:
      // this is a detached-from-collectives background thread). The
      // next tick retries from scratch.
      TC_WARN("fleetobs: round ", round_, " failed: ", e.what());
    }
  }
}

void FleetObs::drainPeer(PeerLink& p) {
  if (p.dead || p.ubuf == nullptr) {
    return;
  }
  try {
    while (true) {
      if (!p.posted) {
        p.ubuf->recv(p.rank, p.slot, 0, p.bytes.size());
        p.posted = true;
      }
      int src = -1;
      if (!p.ubuf->waitRecv(&src, std::chrono::milliseconds(0))) {
        return;  // abort: stop() is tearing us down
      }
      p.posted = false;
      p.latestRaw = trimmed(p.bytes.data(), p.bytes.size());
      p.lastSeenRound = round_;
    }
  } catch (const TimeoutException&) {
    // Nothing (more) arrived this tick; the posted recv stays armed.
  } catch (const IoException& e) {
    TC_WARN("fleetobs: link to rank ", p.rank,
            " failed, dropping it from aggregation: ", e.what());
    p.dead = true;
  }
}

std::string FleetObs::buildReportAttempt(int opsTail, int maxLinks) {
  const int64_t nowUs = Tracer::nowUs();
  std::ostringstream out;
  out << "{\"v\":1,\"rank\":" << ctx_->rank() << ",\"round\":" << round_
      << ",\"t_us\":" << nowUs;

  // Health + op totals from the canonical metrics snapshot (no drain:
  // the fleet plane observes, it never consumes). Parsing our own JSON
  // keeps the report in lockstep with the snapshot schema instead of
  // duplicating accessors for every field.
  Value snap = JsonReader(ctx_->metricsJson(false), "fleetobs metrics")
                   .parse();
  uint64_t calls = 0;
  uint64_t errors = 0;
  if (const Value* ops = snap.field("ops")) {
    for (const auto& f : ops->fields) {
      calls += static_cast<uint64_t>(numField(f.second, "calls", 0));
      errors += static_cast<uint64_t>(numField(f.second, "errors", 0));
    }
  }
  uint64_t stalls = 0;
  int64_t stallAgeUs = -1;
  if (const Value* wd = snap.field("watchdog")) {
    stalls = static_cast<uint64_t>(numField(*wd, "stalls", 0));
    if (const Value* last = wd->field("last")) {
      if (last->kind == Value::Kind::kObject) {
        stallAgeUs = static_cast<int64_t>(numField(*last, "age_us", -1));
      }
    }
  }
  int failurePeer = -1;
  const Value* failure = snap.field("transport_failure");
  if (failure != nullptr && failure->kind == Value::Kind::kObject) {
    failurePeer = static_cast<int>(numField(*failure, "peer", -1));
  }
  uint64_t anoms = 0;
  if (const Value* an = snap.field("anomalies")) {
    anoms = static_cast<uint64_t>(numField(*an, "total", 0));
  }
  out << ",\"ok\":" << (failurePeer < 0 ? "true" : "false")
      << ",\"stalls\":" << stalls << ",\"stall_age_us\":" << stallAgeUs
      << ",\"failure_peer\":" << failurePeer << ",\"calls\":" << calls
      << ",\"errors\":" << errors << ",\"anoms\":" << anoms;

  // Link telemetry: the busiest links' EWMA estimates, [peer, bw_bps,
  // rtt_us, bytes], most-traffic first so a bounded list keeps the
  // links that matter.
  struct Link {
    int peer;
    uint64_t bw, rtt, bytes;
  };
  std::vector<Link> links;
  if (const Value* tp = snap.field("transport")) {
    for (const auto& f : tp->fields) {
      Link l;
      l.peer = std::atoi(f.first.c_str());
      l.bw = static_cast<uint64_t>(numField(f.second, "bw_ewma_bps", 0));
      l.rtt = static_cast<uint64_t>(numField(f.second, "rtt_ewma_us", 0));
      l.bytes =
          static_cast<uint64_t>(numField(f.second, "sent_bytes", 0)) +
          static_cast<uint64_t>(numField(f.second, "recv_bytes", 0));
      if (l.bw != 0 || l.rtt != 0) {
        links.push_back(l);
      }
    }
  }
  std::sort(links.begin(), links.end(),
            [](const Link& a, const Link& b) { return a.bytes > b.bytes; });
  if (static_cast<int>(links.size()) > maxLinks) {
    links.resize(maxLinks);
  }
  out << ",\"links\":[";
  for (size_t i = 0; i < links.size(); i++) {
    out << (i == 0 ? "" : ",") << "[" << links[i].peer << ","
        << links[i].bw << "," << links[i].rtt << "," << links[i].bytes
        << "]";
  }
  out << "]";

  // Profile ring tail keyed by the cross-rank collective sequence:
  // [cseq, total_us, wire_wait_us] triples rank 0 joins into the
  // in-band straggler leaderboard (profile.py attribute() semantics).
  out << ",\"ops\":[";
  if (opsTail > 0) {
    Value prof = JsonReader(ctx_->profileJson(), "fleetobs profile")
                     .parse();
    const Value* ops = prof.field("ops");
    if (ops != nullptr && ops->kind == Value::Kind::kArray) {
      const int n = static_cast<int>(ops->items.size());
      const int begin = n > opsTail ? n - opsTail : 0;
      bool first = true;
      for (int i = begin; i < n; i++) {
        const Value& op = ops->items[i];
        const int64_t cseq =
            static_cast<int64_t>(numField(op, "cseq", -1));
        if (cseq < 0) {
          continue;  // p2p / unsequenced: no cross-rank join possible
        }
        uint64_t waitUs = 0;
        if (const Value* phases = op.field("phases")) {
          waitUs = static_cast<uint64_t>(
              numField(*phases, "wire_wait", 0));
        }
        out << (first ? "" : ",") << "[" << cseq << ","
            << static_cast<uint64_t>(numField(op, "total_us", 0)) << ","
            << waitUs << "]";
        first = false;
      }
    }
  }
  out << "]";

  // Causal critical-edge votes, span plane (common/span.h): for each
  // recent collective, this rank's nominee for the op's critical edge —
  // the peer of its longest recv span — as [cseq, owner] pairs. Rank 0
  // tallies the fleet's votes into WindowOp::critOwner, upgrading the
  // persistent-straggler detector from "most wire_wait excess" to "owns
  // the critical edge in most of the window's ops". Empty (and free)
  // when spans are disabled.
  if (opsTail > 0 && ctx_->spans().enabled()) {
    std::map<int64_t, std::pair<int64_t, int>> best;  // cseq->(us,peer)
    Value sp = JsonReader(ctx_->spansJson(), "fleetobs spans").parse();
    const Value* spans = sp.field("spans");
    if (spans != nullptr && spans->kind == Value::Kind::kArray) {
      for (const Value& s : spans->items) {
        const Value* kind = s.field("kind");
        if (kind == nullptr || kind->str != "recv") {
          continue;
        }
        const int64_t cseq = static_cast<int64_t>(numField(s, "cseq", -1));
        const int peer = static_cast<int>(numField(s, "peer", -1));
        if (cseq < 0 || peer < 0) {
          continue;
        }
        const int64_t us =
            static_cast<int64_t>(numField(s, "t1_us", 0)) -
            static_cast<int64_t>(numField(s, "t0_us", 0));
        auto it = best.find(cseq);
        if (it == best.end() || us > it->second.first) {
          best[cseq] = {us, peer};
        }
      }
    }
    out << ",\"crit\":[";
    // Same tail bound as "ops": the most recent opsTail collectives.
    size_t skip = best.size() > static_cast<size_t>(opsTail)
                      ? best.size() - static_cast<size_t>(opsTail)
                      : 0;
    bool first = true;
    for (const auto& kv : best) {
      if (skip > 0) {
        skip--;
        continue;
      }
      out << (first ? "" : ",") << "[" << kv.first << ","
          << kv.second.second << "]";
      first = false;
    }
    out << "]";
  }

  {
    std::lock_guard<std::mutex> guard(auxMu_);
    if (!auxJson_.empty()) {
      out << ",\"aux\":" << auxJson_;
    }
  }
  out << "}";
  return out.str();
}

std::string FleetObs::buildReport() {
  int opsTail = opts_.opsTail;
  int maxLinks = 16;
  while (true) {
    std::string report = buildReportAttempt(opsTail, maxLinks);
    if (report.size() <= opts_.maxBytes) {
      return report;
    }
    if (opsTail == 0 && maxLinks == 0) {
      // Minimal skeleton (aux was the offender): health only.
      std::ostringstream out;
      out << "{\"v\":1,\"rank\":" << ctx_->rank() << ",\"round\":"
          << round_ << ",\"t_us\":" << Tracer::nowUs()
          << ",\"ok\":true,\"truncated\":true,\"links\":[],\"ops\":[]}";
      return out.str();
    }
    opsTail /= 2;
    maxLinks /= 2;
  }
}

std::string FleetObs::buildHostDoc() {
  std::shared_ptr<const Topology> topo = ctx_->topology();
  std::ostringstream out;
  out << "{\"v\":1,\"host_index\":" << hostIndex_ << ",\"fingerprint\":";
  appendJsonString(out, topo != nullptr
                            ? topo->fingerprints[hostIndex_]
                            : std::string());
  out << ",\"leader\":" << ctx_->rank() << ",\"ranks\":{";
  uint64_t calls = 0;
  uint64_t errors = 0;
  std::vector<int> unhealthy;
  std::vector<int> missing;
  int reported = 0;
  bool first = true;
  auto embed = [&](int rank, const std::string& raw) {
    out << (first ? "" : ",") << "\"" << rank << "\":" << raw;
    first = false;
    reported++;
    try {
      Value v = JsonReader(raw, "fleetobs report").parse();
      calls += static_cast<uint64_t>(numField(v, "calls", 0));
      errors += static_cast<uint64_t>(numField(v, "errors", 0));
      const Value* ok = v.field("ok");
      if (ok != nullptr && ok->kind == Value::Kind::kBool && !ok->boolean) {
        unhealthy.push_back(rank);
      }
    } catch (const std::exception&) {
      unhealthy.push_back(rank);  // unparseable counts as unhealthy
    }
  };
  embed(ctx_->rank(), buildReport());
  for (auto& p : members_) {
    if (!p.latestRaw.empty() && !p.dead &&
        p.lastSeenRound >= round_ - kStaleRounds) {
      embed(p.rank, p.latestRaw);
    } else {
      missing.push_back(p.rank);
    }
  }
  out << "},\"missing\":[";
  for (size_t i = 0; i < missing.size(); i++) {
    out << (i == 0 ? "" : ",") << missing[i];
  }
  out << "],\"summary\":{\"ranks\":"
      << (topo != nullptr ? topo->hosts[hostIndex_].size() : 1)
      << ",\"reported\":" << reported << ",\"calls\":" << calls
      << ",\"errors\":" << errors << ",\"unhealthy\":[";
  for (size_t i = 0; i < unhealthy.size(); i++) {
    out << (i == 0 ? "" : ",") << unhealthy[i];
  }
  out << "]}}";
  return out.str();
}

void FleetObs::tick() {
  // 1) Leaders pull whatever members pushed since the last tick.
  for (auto& p : members_) {
    drainPeer(p);
  }
  if (ctx_->rank() == 0) {
    for (auto& p : leaders_) {
      drainPeer(p);
    }
    mergeAndDetect(buildHostDoc());
    return;
  }

  // 2) Everyone below rank 0 pushes one fixed-size document upward,
  // never rewriting a buffer with a send still in flight.
  if (up_.dead || up_.ubuf == nullptr) {
    return;
  }
  try {
    if (up_.sendPending) {
      if (!up_.ubuf->waitSend(std::chrono::milliseconds(0))) {
        return;  // aborted: shutting down
      }
      up_.sendPending = false;
    }
    const std::string doc = isLeader_ ? buildHostDoc() : buildReport();
    if (doc.size() > up_.bytes.size()) {
      TC_WARN("fleetobs: document (", doc.size(),
              "B) exceeds the wire slot (", up_.bytes.size(),
              "B); skipping round ", round_);
      return;
    }
    std::fill(up_.bytes.begin(), up_.bytes.end(), ' ');
    std::memcpy(up_.bytes.data(), doc.data(), doc.size());
    up_.ubuf->send(up_.rank, up_.slot, 0, up_.bytes.size());
    up_.sendPending = true;
  } catch (const TimeoutException&) {
    // Send still in flight: the parent is slow, not gone. Skip the
    // round; the pending flag keeps the buffer untouched.
  } catch (const IoException& e) {
    TC_WARN("fleetobs: uplink to rank ", up_.rank,
            " failed, reporting stops: ", e.what());
    up_.dead = true;
  }
}

void FleetObs::ingestStragglerOps(int rank, const Value& report) {
  const Value* ops = report.field("ops");
  if (ops == nullptr || ops->kind != Value::Kind::kArray) {
    return;
  }
  for (const Value& triple : ops->items) {
    if (triple.kind != Value::Kind::kArray || triple.items.size() < 3) {
      continue;
    }
    const int64_t cseq = static_cast<int64_t>(triple.items[0].number);
    if (cseq <= processedThroughCseq_) {
      continue;  // already finalized (ring tails resend old entries)
    }
    PendingOp& p = pendingOps_[cseq];
    if (p.perRank.empty()) {
      p.firstRound = round_;
    }
    p.perRank[rank] = {static_cast<uint64_t>(triple.items[1].number),
                       static_cast<uint64_t>(triple.items[2].number)};
  }
}

void FleetObs::ingestCritVotes(int rank, const Value& report) {
  const Value* crit = report.field("crit");
  if (crit == nullptr || crit->kind != Value::Kind::kArray) {
    return;
  }
  for (const Value& pair : crit->items) {
    if (pair.kind != Value::Kind::kArray || pair.items.size() < 2) {
      continue;
    }
    const int64_t cseq = static_cast<int64_t>(pair.items[0].number);
    const int owner = static_cast<int>(pair.items[1].number);
    if (cseq <= processedThroughCseq_ || owner < 0 ||
        owner >= ctx_->size()) {
      continue;
    }
    PendingOp& p = pendingOps_[cseq];
    if (p.perRank.empty() && p.critVotes.empty()) {
      p.firstRound = round_;
    }
    p.critVotes[rank] = owner;  // keyed by voter: resends stay idempotent
  }
}

void FleetObs::finalizePendingOps() {
  // Finalize in ascending cseq order: an op closes when every rank
  // answered, or after a 2-round grace with at least two answers (the
  // join needs a comparison, not a census). The watermark stops ring
  // resends from double counting.
  constexpr int64_t kGraceRounds = 2;
  for (auto it = pendingOps_.begin(); it != pendingOps_.end();) {
    PendingOp& p = it->second;
    const bool complete =
        static_cast<int>(p.perRank.size()) >= ctx_->size();
    const bool graceOver = round_ - p.firstRound >= kGraceRounds &&
                           p.perRank.size() >= 2;
    if (!complete && !graceOver) {
      ++it;
      continue;
    }
    // profile.py attribute(): straggler = argmin wire_wait (lowest rank
    // wins ties), excess_r = wait_r - min wait, blame the straggler for
    // the total excess.
    uint64_t minWait = UINT64_MAX;
    int straggler = -1;
    for (const auto& rw : p.perRank) {
      if (rw.second.second < minWait) {
        minWait = rw.second.second;
        straggler = rw.first;
      }
    }
    uint64_t totalExcess = 0;
    for (const auto& rw : p.perRank) {
      totalExcess += rw.second.second - minWait;
    }
    // Plurality of the ranks' critical-edge nominations (lowest rank
    // wins ties); -1 when the fleet voted nothing (spans disabled).
    int critOwner = -1;
    {
      std::map<int, int> tally;
      for (const auto& vote : p.critVotes) {
        tally[vote.second]++;
      }
      int bestVotes = 0;
      for (const auto& t : tally) {
        if (t.second > bestVotes) {
          bestVotes = t.second;
          critOwner = t.first;
        }
      }
    }
    if (straggler >= 0 && totalExcess > 0) {
      window_.push_back(WindowOp{round_, straggler, totalExcess,
                                 critOwner});
    }
    processedThroughCseq_ = std::max(processedThroughCseq_, it->first);
    it = pendingOps_.erase(it);
  }
  while (!window_.empty() &&
         window_.front().round < round_ - opts_.windowRounds) {
    window_.pop_front();
  }
}

bool FleetObs::debounced(const std::string& kind, int rank) {
  int64_t& last = lastFiredRound_[kind][rank];
  if (last != 0 && round_ - last < opts_.windowRounds) {
    return true;
  }
  last = round_;
  return false;
}

void FleetObs::fireAnomaly(const char* kind, int rank, uint64_t detail) {
  ctx_->metrics().recordAnomaly(kind, rank);
  ctx_->flightrec().noteEvent(anomalyOpcode(kind), rank, detail);
  recent_.push_back(AnomalyEvent{kind, rank, Tracer::nowUs(), detail});
  while (recent_.size() > 64) {
    recent_.pop_front();
  }
  TC_WARN("fleetobs: anomaly ", kind, " rank ", rank, " detail ", detail);
}

void FleetObs::runDetectors(
    const std::map<int, const Value*>& reports) {
  // --- persistent straggler: dominant blame over the sliding window ---
  std::map<int, std::pair<uint64_t, uint64_t>> blame;  // rank -> (us, ops)
  uint64_t windowExcess = 0;
  uint64_t votedOps = 0;
  std::map<int, uint64_t> critOwn;  // rank -> window ops owned causally
  for (const WindowOp& w : window_) {
    blame[w.straggler].first += w.excessUs;
    blame[w.straggler].second += 1;
    windowExcess += w.excessUs;
    if (w.critOwner >= 0) {
      votedOps++;
      critOwn[w.critOwner]++;
    }
  }
  const uint64_t thresholdUs =
      static_cast<uint64_t>(opts_.stragglerMs) * 1000;
  // With enough causally-voted ops in the window (a spans-enabled
  // fleet), the firing rule upgrades from "most wire_wait excess" to
  // "owns the critical edge in at least half of the voted ops" — the
  // wait-excess heuristic can blame a rank that merely sits next to
  // the slow one on the ring, the causal vote follows the actual edge.
  // The blamed-time floor stays either way; without votes the excess
  // rule stands unchanged.
  constexpr uint64_t kMinVotedOps = 4;
  for (const auto& b : blame) {
    if (b.second.first < thresholdUs) {
      continue;
    }
    const bool fires =
        votedOps >= kMinVotedOps
            ? critOwn[b.first] * 2 >= votedOps
            : b.second.first * 2 >= windowExcess;
    if (fires && !debounced(kKindStraggler, b.first)) {
      fireAnomaly(kKindStraggler, b.first, b.second.first);
    }
  }

  // --- slow link: pair EWMA bandwidth far below the fleet median ---
  struct LinkSample {
    int rank, peer;
    uint64_t bw;
  };
  std::vector<LinkSample> samples;
  std::vector<uint64_t> bws;
  constexpr uint64_t kMinLinkBytes = 1 << 20;
  for (const auto& rr : reports) {
    const Value* links = rr.second->field("links");
    if (links == nullptr || links->kind != Value::Kind::kArray) {
      continue;
    }
    for (const Value& l : links->items) {
      if (l.kind != Value::Kind::kArray || l.items.size() < 4) {
        continue;
      }
      const uint64_t bw = static_cast<uint64_t>(l.items[1].number);
      const uint64_t bytes = static_cast<uint64_t>(l.items[3].number);
      if (bw == 0 || bytes < kMinLinkBytes) {
        continue;
      }
      samples.push_back(LinkSample{
          rr.first, static_cast<int>(l.items[0].number), bw});
      bws.push_back(bw);
    }
  }
  slowLinks_.clear();
  if (bws.size() >= 4) {
    std::sort(bws.begin(), bws.end());
    const uint64_t median = bws[bws.size() / 2];
    for (const LinkSample& s : samples) {
      if (s.bw * 8 < median) {
        slowLinks_.push_back(SlowLink{s.rank, s.peer, s.bw, median});
        if (!debounced(kKindSlowLink, s.rank)) {
          fireAnomaly(kKindSlowLink, s.rank, s.bw);
        }
      }
    }
  }

  // --- lease jitter: renewal cadence far off the elastic plane's own
  // lease period (aux.elastic, fed through tc_fleetobs_set_aux) ---
  for (const auto& rr : reports) {
    const Value* aux = rr.second->field("aux");
    if (aux == nullptr) {
      continue;
    }
    const Value* elastic = aux->field("elastic");
    if (elastic == nullptr) {
      continue;
    }
    const double leaseMs = numField(*elastic, "lease_ms", 0);
    const double renewed = numField(*elastic, "leases_renewed", -1);
    if (leaseMs <= 0 || renewed < 0) {
      continue;
    }
    auto& hist = leaseHistory_[rr.first];
    hist.emplace_back(round_, static_cast<uint64_t>(renewed));
    while (!hist.empty() &&
           hist.front().first < round_ - opts_.windowRounds) {
      hist.pop_front();
    }
    const int64_t spanRounds = hist.back().first - hist.front().first;
    if (spanRounds * opts_.intervalMs < 4 * leaseMs) {
      continue;  // window too short to judge a renewal cadence
    }
    const double expected =
        static_cast<double>(spanRounds) * opts_.intervalMs / leaseMs;
    const double observed = static_cast<double>(hist.back().second) -
                            static_cast<double>(hist.front().second);
    if (observed * 2 < expected && !debounced(kKindLeaseJitter,
                                              rr.first)) {
      fireAnomaly(kKindLeaseJitter, rr.first,
                  static_cast<uint64_t>(observed));
    }
  }
}

void FleetObs::mergeAndDetect(const std::string& ownHostDoc) {
  // Parse the fresh host documents (own + relayed) once, then reuse the
  // parse for coverage, the detectors, and the embedded output.
  std::vector<std::pair<const std::string*, Value>> hostDocs;
  Value own = JsonReader(ownHostDoc, "fleetobs host doc").parse();
  hostDocs.emplace_back(&ownHostDoc, std::move(own));
  for (auto& p : leaders_) {
    if (p.latestRaw.empty() || p.dead ||
        p.lastSeenRound < round_ - kStaleRounds) {
      continue;
    }
    try {
      Value v = JsonReader(p.latestRaw, "fleetobs host doc").parse();
      hostDocs.emplace_back(&p.latestRaw, std::move(v));
    } catch (const std::exception& e) {
      TC_WARN("fleetobs: unparseable host doc from rank ", p.rank, ": ",
              e.what());
    }
  }

  std::map<int, const Value*> reports;  // rank -> report (fresh docs)
  for (const auto& hd : hostDocs) {
    const Value* ranks = hd.second.field("ranks");
    if (ranks == nullptr) {
      continue;
    }
    for (const auto& f : ranks->fields) {
      reports[std::atoi(f.first.c_str())] = &f.second;
    }
  }
  for (const auto& rr : reports) {
    ingestStragglerOps(rr.first, *rr.second);
    ingestCritVotes(rr.first, *rr.second);
  }
  finalizePendingOps();
  runDetectors(reports);

  // Straggler leaderboard over the window (blamed time descending).
  std::map<int, std::pair<uint64_t, uint64_t>> blame;
  for (const WindowOp& w : window_) {
    blame[w.straggler].first += w.excessUs;
    blame[w.straggler].second += 1;
  }
  std::vector<std::pair<int, std::pair<uint64_t, uint64_t>>> board(
      blame.begin(), blame.end());
  std::sort(board.begin(), board.end(),
            [](const auto& a, const auto& b) {
              return a.second.first != b.second.first
                         ? a.second.first > b.second.first
                         : a.first < b.first;
            });

  std::vector<int> missing;
  for (int r = 0; r < ctx_->size(); r++) {
    if (reports.find(r) == reports.end()) {
      missing.push_back(r);
    }
  }

  std::ostringstream out;
  out << "{\"version\":1,\"kind\":\"fleet\",\"rank\":0,\"size\":"
      << ctx_->size() << ",\"group\":";
  appendJsonString(out, ctx_->groupTag());
  out << ",\"enabled\":true,\"now_us\":" << Tracer::nowUs()
      << ",\"round\":" << round_ << ",\"interval_ms\":" << opts_.intervalMs
      << ",\"hosts\":[";
  for (size_t i = 0; i < hostDocs.size(); i++) {
    out << (i == 0 ? "" : ",") << *hostDocs[i].first;
  }
  out << "],\"coverage\":{\"expected\":" << ctx_->size()
      << ",\"reported\":" << reports.size() << ",\"missing\":[";
  for (size_t i = 0; i < missing.size(); i++) {
    out << (i == 0 ? "" : ",") << missing[i];
  }
  out << "]},\"straggler\":{\"window_rounds\":" << opts_.windowRounds
      << ",\"ops_window\":" << window_.size() << ",\"leaderboard\":[";
  for (size_t i = 0; i < board.size(); i++) {
    out << (i == 0 ? "" : ",") << "{\"rank\":" << board[i].first
        << ",\"blamed_us\":" << board[i].second.first
        << ",\"blamed_ops\":" << board[i].second.second << "}";
  }
  // Causal critical-edge ownership over the window (span votes; empty
  // with spans disabled). `ops` counts window ops the rank's edge
  // gated, per the fleet's plurality vote.
  std::map<int, uint64_t> critOwn;
  uint64_t votedOps = 0;
  for (const WindowOp& w : window_) {
    if (w.critOwner >= 0) {
      votedOps++;
      critOwn[w.critOwner]++;
    }
  }
  std::vector<std::pair<int, uint64_t>> owners(critOwn.begin(),
                                               critOwn.end());
  std::sort(owners.begin(), owners.end(),
            [](const auto& a, const auto& b) {
              return a.second != b.second ? a.second > b.second
                                          : a.first < b.first;
            });
  out << "]},\"critpath\":{\"voted_ops\":" << votedOps << ",\"owners\":[";
  for (size_t i = 0; i < owners.size(); i++) {
    out << (i == 0 ? "" : ",") << "{\"rank\":" << owners[i].first
        << ",\"ops\":" << owners[i].second << "}";
  }
  out << "]},\"slow_links\":[";
  for (size_t i = 0; i < slowLinks_.size(); i++) {
    out << (i == 0 ? "" : ",") << "{\"rank\":" << slowLinks_[i].rank
        << ",\"peer\":" << slowLinks_[i].peer << ",\"bw_bps\":"
        << slowLinks_[i].bwBps << ",\"median_bps\":"
        << slowLinks_[i].medianBps << "}";
  }
  out << "],\"anomalies\":{\"total\":" << ctx_->metrics().anomaliesTotal()
      << ",\"recent\":[";
  for (size_t i = 0; i < recent_.size(); i++) {
    out << (i == 0 ? "" : ",") << "{\"kind\":";
    appendJsonString(out, recent_[i].kind);
    out << ",\"rank\":" << recent_[i].rank << ",\"t_us\":"
        << recent_[i].tUs << ",\"detail\":" << recent_[i].detail << "}";
  }
  out << "]}}";

  std::lock_guard<std::mutex> guard(fleetMu_);
  fleetJson_ = out.str();
}

}  // namespace fleetobs
}  // namespace tpucoll
