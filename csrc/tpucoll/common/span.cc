#include "tpucoll/common/span.h"

#include <sstream>

#include "tpucoll/common/env.h"
#include "tpucoll/common/flightrec.h"
#include "tpucoll/common/json.h"
#include "tpucoll/common/metrics.h"
#include "tpucoll/common/profile.h"

namespace tpucoll {
namespace span {

const char* kindName(Kind k) {
  switch (k) {
    case Kind::kSend:
      return "send";
    case Kind::kRecv:
      return "recv";
    case Kind::kWait:
      return "wait";
    case Kind::kLocal:
      return "local";
    case Kind::kCount:
      break;
  }
  return "unknown";
}

namespace {

// Same single-threaded-op contract as the profiler's accumulator head:
// collectives run synchronously on the issuing thread, so the active
// op state is a per-thread stack head with no synchronization.
thread_local OpState* t_currentOp = nullptr;

size_t capacityFromEnv() {
  const size_t cap = static_cast<size_t>(
      envCount("TPUCOLL_SPANS_RING", 4096, 1, 1 << 20));
  size_t pow2 = 8;
  while (pow2 < cap) {
    pow2 <<= 1;
  }
  return pow2;
}

}  // namespace

OpState* currentOp() { return t_currentOp; }

Recorder::Recorder(int rank, int size, Metrics* metrics)
    : rank_(rank), size_(size), metrics_(metrics) {
  const size_t cap = capacityFromEnv();
  mask_ = cap - 1;
  entries_.reset(new Entry[cap]);
  enabled_.store(envFlag("TPUCOLL_SPANS", false),
                 std::memory_order_relaxed);
}

void Recorder::record(const OpState& op, uint32_t id, Kind kind,
                      uint8_t phase, int peer, uint64_t slot,
                      uint64_t bytes, int64_t t0Us, int64_t t1Us) {
  const uint64_t seq = nextSeq_.fetch_add(1, std::memory_order_relaxed);
  Entry& e = entries_[seq & mask_];
  e.seq.store(kNoSeq, std::memory_order_relaxed);
  e.cseq.store(op.cseq, std::memory_order_relaxed);
  e.id.store(id, std::memory_order_relaxed);
  e.kind.store(static_cast<uint8_t>(kind), std::memory_order_relaxed);
  e.phase.store(phase, std::memory_order_relaxed);
  e.peer.store(peer, std::memory_order_relaxed);
  e.slot.store(slot, std::memory_order_relaxed);
  e.bytes.store(bytes, std::memory_order_relaxed);
  e.t0Us.store(t0Us, std::memory_order_relaxed);
  e.t1Us.store(t1Us, std::memory_order_relaxed);
  e.opcode.store(op.opcode, std::memory_order_relaxed);
  e.seq.store(seq, std::memory_order_relaxed);
}

std::string Recorder::toJson() const {
  std::ostringstream out;
  const uint64_t next = nextSeq_.load(std::memory_order_relaxed);
  const uint64_t cap = mask_ + 1;
  const uint64_t first = next > cap ? next - cap : 0;
  out << "{\"version\":1,\"kind\":\"tpucoll_spans\",\"rank\":" << rank_
      << ",\"size\":" << size_ << ",\"group\":";
  appendJsonString(out, metrics_ != nullptr ? metrics_->group()
                                            : std::string());
  out << ",\"enabled\":" << (enabled() ? "true" : "false")
      << ",\"now_us\":" << FlightRecorder::nowUs()
      << ",\"next_seq\":" << next << ",\"capacity\":" << cap
      << ",\"dropped\":" << first << ",\"spans\":[";
  bool firstRow = true;
  for (uint64_t seq = first; seq < next; seq++) {
    const Entry& e = entries_[seq & mask_];
    if (e.seq.load(std::memory_order_relaxed) != seq) {
      continue;  // torn row: mid-overwrite by a racing writer
    }
    const char* op = e.opcode.load(std::memory_order_relaxed);
    const int64_t cseq = e.cseq.load(std::memory_order_relaxed);
    const uint8_t kind = e.kind.load(std::memory_order_relaxed);
    const uint8_t phase = e.phase.load(std::memory_order_relaxed);
    const int peer = e.peer.load(std::memory_order_relaxed);
    out << (firstRow ? "" : ",") << "\n{\"seq\":" << seq << ",\"cseq\":";
    if (cseq >= 0) {
      out << cseq;
    } else {
      out << "null";
    }
    out << ",\"id\":" << e.id.load(std::memory_order_relaxed)
        << ",\"kind\":\""
        << kindName(kind < static_cast<uint8_t>(Kind::kCount)
                        ? static_cast<Kind>(kind)
                        : Kind::kCount)
        << "\",\"phase\":\""
        << profile::phaseName(phase < profile::kPhaseCount
                                  ? static_cast<profile::Phase>(phase)
                                  : profile::Phase::kCount)
        << "\",\"peer\":";
    if (peer >= 0) {
      out << peer;
    } else {
      out << "null";
    }
    out << ",\"slot\":" << e.slot.load(std::memory_order_relaxed)
        << ",\"bytes\":" << e.bytes.load(std::memory_order_relaxed)
        << ",\"t0_us\":" << e.t0Us.load(std::memory_order_relaxed)
        << ",\"t1_us\":" << e.t1Us.load(std::memory_order_relaxed)
        << ",\"op\":";
    if (op != nullptr) {
      out << "\"" << op << "\"";
    } else {
      out << "null";
    }
    out << "}";
    firstRow = false;
  }
  out << "\n]}\n";
  return out.str();
}

OpScope::OpScope(Recorder* rec, const char* opcode, int64_t cseq)
    : prev_(t_currentOp) {
  if (rec == nullptr || !rec->enabled()) {
    // Disabled path: one relaxed load plus parking the thread-local at
    // null — a disabled nested op (hier sub-context with spans off
    // while the parent's are on) must not interleave its instances
    // into the parent's ordinal stream.
    t_currentOp = nullptr;
    return;
  }
  st_.rec = rec;
  st_.cseq = cseq;
  st_.opcode = opcode;
  t_currentOp = &st_;
}

OpScope::~OpScope() { t_currentOp = prev_; }

void emit(Kind kind, uint8_t phase, int peer, uint64_t slot,
          uint64_t bytes, int64_t t0Us, int64_t t1Us) {
  OpState* op = t_currentOp;
  if (op == nullptr) {
    return;
  }
  op->rec->record(*op, op->nextId++, kind, phase, peer, slot, bytes,
                  t0Us, t1Us);
}

}  // namespace span
}  // namespace tpucoll
