// Per-rank identity keyring: the transport's answer to the reference's
// per-process key/cert TLS identity (gloo/transport/tcp/tls/context.h:
// 25-42 — each process holds its OWN private key, so one leaked worker
// credential does not impersonate the fleet).
//
// Model: a launcher holding a root secret derives, for worker r, the
// keyring {K[r,s] = HKDF(root, "tpucoll-pairkey-v1", pair(r,s)) for all
// s}. Workers receive ONLY their keyring, never the root. Connection
// (a,b) authenticates with the pairwise key K[a,b], which exactly the
// two legitimate endpoints hold. Leaking worker r's keyring therefore
// lets an attacker impersonate r (to anyone) and impersonate other
// ranks only TO r — it does NOT let them impersonate rank s to rank t.
// That is strictly stronger than the single mesh PSK (where one leak
// impersonates every rank to every rank) and covers the reference's
// leak-containment property without an in-tree PKI; rotation = new
// root, re-derive, restart (same operational cost as redistributing
// certs). Trust anchor: the launcher and its channel to the workers —
// the same anchor the reference's CA file distribution relies on.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace tpucoll {

class Keyring {
 public:
  static constexpr size_t kKeyBytes = 32;

  Keyring() = default;

  // Launcher side: derive rank r's keyring from the root secret.
  static Keyring derive(const std::string& rootKey, int rank, int size);

  // Worker side: parse a serialized keyring ("tcring1:<rank>:<size>:
  // <hex of size*32 key bytes>"; slot [rank] is zeros). Throws
  // EnforceError on malformed input.
  static Keyring parse(const std::string& blob);

  std::string serialize() const;

  bool valid() const { return rank_ >= 0; }
  int rank() const { return rank_; }
  int size() const { return size_; }

  // K[rank, peer] as a string usable as an HMAC/HKDF key. Throws on
  // out-of-range or self.
  std::string keyFor(int peer) const;

 private:
  int rank_{-1};
  int size_{0};
  std::vector<uint8_t> keys_;  // size * kKeyBytes, slot [rank] zeroed
};

}  // namespace tpucoll
