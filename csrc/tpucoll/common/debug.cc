#include "tpucoll/common/debug.h"

#include <mutex>
#include <utility>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace {

std::mutex g_mu;
std::function<void(const ConnectDebugData&)> g_logger;

}  // namespace

void setConnectDebugLogger(
    std::function<void(const ConnectDebugData&)> fn) {
  std::lock_guard<std::mutex> guard(g_mu);
  g_logger = std::move(fn);
}

void logConnectAttempt(const ConnectDebugData& data) {
  TC_DEBUG("connect rank ", data.selfRank, " -> ", data.peerRank, " (",
           data.remote, ", local ", data.local, ") attempt ", data.attempt,
           data.ok ? ": ok" : ": failed", data.ok ? "" : " - ",
           data.error, data.willRetry ? " (will retry)" : "");
  std::function<void(const ConnectDebugData&)> fn;
  {
    std::lock_guard<std::mutex> guard(g_mu);
    fn = g_logger;
  }
  if (fn) {
    fn(data);
  }
}

}  // namespace tpucoll
