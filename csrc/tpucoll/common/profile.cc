#include "tpucoll/common/profile.h"

#include <sstream>

#include "tpucoll/common/env.h"
#include "tpucoll/common/flightrec.h"
#include "tpucoll/common/json.h"
#include "tpucoll/common/metrics.h"
#include "tpucoll/common/span.h"

namespace tpucoll {
namespace profile {

const char* phaseName(Phase p) {
  switch (p) {
    case Phase::kPack:
      return "pack";
    case Phase::kPost:
      return "post";
    case Phase::kWireWait:
      return "wire_wait";
    case Phase::kReduce:
      return "reduce";
    case Phase::kUnpack:
      return "unpack";
    case Phase::kIntra:
      return "intra";
    case Phase::kInter:
      return "inter";
    case Phase::kFanout:
      return "fanout";
    case Phase::kCount:
      break;
  }
  return "unknown";
}

namespace {

// Collectives run synchronously on the issuing thread, so the active
// accumulator is a per-thread stack head with no synchronization;
// nested collectives (hier phases) save/restore through ProfileOpScope.
thread_local OpAccumulator* t_currentOp = nullptr;

size_t capacityFromEnv() {
  // Strict count (common/env.h): a typo'd ring size must fail loudly,
  // not silently fall back (same contract as TPUCOLL_FLIGHTREC_EVENTS).
  const size_t cap = static_cast<size_t>(
      envCount("TPUCOLL_PROFILE_RING", 256, 1, 1 << 20));
  size_t pow2 = 8;
  while (pow2 < cap) {
    pow2 <<= 1;
  }
  return pow2;
}

}  // namespace

OpAccumulator* currentOp() { return t_currentOp; }

Profiler::Profiler(int rank, int size, Metrics* metrics)
    : rank_(rank), size_(size), metrics_(metrics) {
  const size_t cap = capacityFromEnv();
  mask_ = cap - 1;
  entries_.reset(new Entry[cap]);
  enabled_.store(envFlag("TPUCOLL_PROFILE", true),
                 std::memory_order_relaxed);
}

void Profiler::record(const char* opcode, const char* algorithm,
                      int64_t cseq, uint64_t bytes, int64_t startUs,
                      int64_t totalUs, const OpAccumulator& acc) {
  const uint64_t seq = nextSeq_.fetch_add(1, std::memory_order_relaxed);
  Entry& e = entries_[seq & mask_];
  // Claim-then-publish (flightrec.h): park kNoSeq while fields are being
  // rewritten so a concurrent toJson skips the torn row, then publish
  // the real seq as the LAST store.
  e.seq.store(kNoSeq, std::memory_order_relaxed);
  e.cseq.store(cseq, std::memory_order_relaxed);
  e.opcode.store(opcode, std::memory_order_relaxed);
  e.algorithm.store(algorithm, std::memory_order_relaxed);
  e.bytes.store(bytes, std::memory_order_relaxed);
  e.startUs.store(startUs, std::memory_order_relaxed);
  e.totalUs.store(totalUs, std::memory_order_relaxed);
  for (int p = 0; p < kPhaseCount; p++) {
    e.phaseUs[p].store(acc.phaseUs[p], std::memory_order_relaxed);
  }
  e.seq.store(seq, std::memory_order_relaxed);

  // The aggregate flush honors the metrics registry's own gate: with
  // ctx.metrics_enable(False) every other recorder freezes, and a
  // "phases" section that kept growing would make the snapshot
  // inconsistent (and pay mutex+map cost the disabled path promises
  // not to). The per-op ring above is the profiler's own surface and
  // is governed solely by the profiler gate.
  if (metrics_ != nullptr && metrics_->enabled()) {
    for (int p = 0; p < kPhaseCount; p++) {
      if (acc.phaseUs[p] <= 0) {
        continue;
      }
      metrics_
          ->phaseHistogram(opcode, algorithm != nullptr ? algorithm : "",
                           phaseName(static_cast<Phase>(p)))
          ->record(acc.phaseUs[p]);
    }
  }
}

std::string Profiler::toJson() const {
  std::ostringstream out;
  const uint64_t next = nextSeq_.load(std::memory_order_relaxed);
  const uint64_t cap = mask_ + 1;
  const uint64_t first = next > cap ? next - cap : 0;
  out << "{\"version\":1,\"kind\":\"tpucoll_profile\",\"rank\":" << rank_
      << ",\"size\":" << size_ << ",\"group\":";
  appendJsonString(out, metrics_ != nullptr ? metrics_->group()
                                            : std::string());
  out << ",\"enabled\":" << (enabled() ? "true" : "false")
      << ",\"now_us\":" << FlightRecorder::nowUs()
      << ",\"next_seq\":" << next << ",\"capacity\":" << cap
      << ",\"dropped\":" << first << ",\"ops\":[";
  bool firstRow = true;
  for (uint64_t seq = first; seq < next; seq++) {
    const Entry& e = entries_[seq & mask_];
    if (e.seq.load(std::memory_order_relaxed) != seq) {
      continue;  // torn row: mid-overwrite by a racing writer
    }
    const char* op = e.opcode.load(std::memory_order_relaxed);
    if (op == nullptr) {
      continue;
    }
    const char* algo = e.algorithm.load(std::memory_order_relaxed);
    const int64_t cseq = e.cseq.load(std::memory_order_relaxed);
    out << (firstRow ? "" : ",") << "\n{\"seq\":" << seq << ",\"cseq\":";
    if (cseq >= 0) {
      out << cseq;
    } else {
      out << "null";
    }
    out << ",\"op\":\"" << op << "\",\"algo\":";
    if (algo != nullptr) {
      out << "\"" << algo << "\"";
    } else {
      out << "null";
    }
    out << ",\"bytes\":" << e.bytes.load(std::memory_order_relaxed)
        << ",\"start_us\":" << e.startUs.load(std::memory_order_relaxed)
        << ",\"total_us\":" << e.totalUs.load(std::memory_order_relaxed)
        << ",\"phases\":{";
    bool firstPhase = true;
    for (int p = 0; p < kPhaseCount; p++) {
      const int64_t us = e.phaseUs[p].load(std::memory_order_relaxed);
      if (us <= 0) {
        continue;
      }
      out << (firstPhase ? "" : ",") << "\""
          << phaseName(static_cast<Phase>(p)) << "\":" << us;
      firstPhase = false;
    }
    out << "}}";
    firstRow = false;
  }
  out << "\n]}\n";
  return out.str();
}

ProfileOpScope::ProfileOpScope(Profiler* profiler, const char* opcode,
                               int64_t cseq, uint64_t bytes)
    : profiler_(profiler), opcode_(opcode), cseq_(cseq), bytes_(bytes),
      startUs_(0), prev_(t_currentOp) {
  if (profiler_ == nullptr || !profiler_->enabled()) {
    // Disabled path: one relaxed load plus parking the thread-local at
    // null. The park is NOT optional — a disabled op nested inside an
    // enabled one (a hier sub-context whose profiler is off while the
    // parent's is on) must not let its own PhaseScopes keep charging
    // the PARENT's accumulator on top of the parent's intra/inter
    // phase, which would double-count the same wall time.
    profiler_ = nullptr;
    t_currentOp = nullptr;
    return;
  }
  startUs_ = FlightRecorder::nowUs();
  t_currentOp = &acc_;
}

ProfileOpScope::~ProfileOpScope() {
  t_currentOp = prev_;
  if (profiler_ == nullptr) {
    return;
  }
  profiler_->record(opcode_, algorithm_, cseq_, bytes_, startUs_,
                    FlightRecorder::nowUs() - startUs_, acc_);
}

PhaseScope::PhaseScope(Phase phase)
    : op_(t_currentOp), spanOp_(span::currentOp()), phase_(phase),
      peer_(-1), slot_(0), bytes_(0), startUs_(0) {
  if (op_ != nullptr || spanOp_ != nullptr) {
    startUs_ = FlightRecorder::nowUs();
  }
}

PhaseScope::PhaseScope(Phase phase, int peer, uint64_t slot,
                       uint64_t bytes)
    : op_(t_currentOp), spanOp_(span::currentOp()), phase_(phase),
      peer_(peer), slot_(slot), bytes_(bytes), startUs_(0) {
  if (op_ != nullptr || spanOp_ != nullptr) {
    startUs_ = FlightRecorder::nowUs();
  }
}

PhaseScope::~PhaseScope() {
  if (op_ == nullptr && spanOp_ == nullptr) {
    return;
  }
  const int64_t endUs = FlightRecorder::nowUs();
  if (op_ != nullptr) {
    op_->phaseUs[static_cast<int>(phase_)] += endUs - startUs_;
  }
  if (spanOp_ != nullptr) {
    // Causal role from (annotation, phase): annotated posts are wire
    // sends, annotated waits are arrivals from `peer`; unannotated
    // waits are drains ("wait"), everything else is local work.
    span::Kind kind = span::Kind::kLocal;
    if (peer_ >= 0) {
      kind = phase_ == Phase::kPost ? span::Kind::kSend
                                    : span::Kind::kRecv;
    } else if (phase_ == Phase::kWireWait) {
      kind = span::Kind::kWait;
    }
    spanOp_->rec->record(*spanOp_, spanOp_->nextId++, kind,
                         static_cast<uint8_t>(phase_), peer_, slot_,
                         bytes_, startUs_, endUs);
  }
}

}  // namespace profile
}  // namespace tpucoll
