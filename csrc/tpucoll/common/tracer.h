// First-class tracing for the host data plane.
//
// The reference has no tracer (SURVEY.md §5: its only introspection is the
// benchmark harness); this is a deliberate capability addition. Each
// Context owns a Tracer; when enabled it records one span per collective /
// p2p wait with wall-clock bounds and payload metadata, and dumps Chrome
// trace-event JSON loadable in Perfetto / chrome://tracing alongside a
// jax profiler trace from the device plane.
//
// Overhead when disabled: one relaxed atomic load per span.
//
// The event vector is BOUNDED: at most `cap()` events are retained
// between drains (TPUCOLL_TRACE_MAX_EVENTS, default 262144 ~ 12 MiB).
// Overflow drops the newest span and counts it in the metrics registry
// (`trace_events_dropped`) instead of growing without limit on long
// runs; draining via toJson() frees the budget again.
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

namespace tpucoll {

class Metrics;

class Tracer {
 public:
  struct Event {
    const char* name;     // static string (collective name)
    int64_t startUs;
    int64_t endUs;
    uint64_t bytes;
    int peer;             // -1 for collectives
    const char* detail;   // static string (algorithm etc.), may be null
  };

  void start() { enabled_.store(true, std::memory_order_relaxed); }
  void stop() { enabled_.store(false, std::memory_order_relaxed); }
  bool enabled() const {
    return enabled_.load(std::memory_order_relaxed);
  }

  // RAII span: records on destruction if the tracer was enabled at
  // construction.
  class Span {
   public:
    Span() = default;
    Span(Tracer* tracer, const char* name, uint64_t bytes, int peer,
         const char* detail)
        : tracer_(tracer),
          event_{name, nowUs(), 0, bytes, peer, detail} {}
    ~Span() {
      if (tracer_ != nullptr) {
        event_.endUs = nowUs();
        tracer_->record(event_);
      }
    }
    Span(const Span&) = delete;
    Span& operator=(const Span&) = delete;

    // Fill in a peer learned only at completion (e.g. recv-from-any
    // resolves its source when the message lands). No-op on a disabled
    // span.
    void setPeer(int peer) { event_.peer = peer; }

   private:
    Tracer* tracer_{nullptr};
    Event event_{};
  };

  Span span(const char* name, uint64_t bytes = 0, int peer = -1,
            const char* detail = nullptr) {
    if (!enabled()) {
      return Span();
    }
    return Span(this, name, bytes, peer, detail);
  }

  // Drop-counter sink (owning Context wires its registry in); also the
  // event-cap override hook for tests. Set before tracing starts.
  void setMetrics(Metrics* metrics) { metrics_ = metrics; }
  void setCap(size_t cap) { cap_ = cap; }
  size_t cap() const { return cap_; }

  void record(const Event& event);

  // Serialize to Chrome trace-event JSON. `pid` labels this process's
  // lane (use the rank). Clears recorded events when `drain` is true.
  std::string toJson(int pid, bool drain = true);

  static int64_t nowUs() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

 private:
  static size_t capFromEnv();

  std::atomic<bool> enabled_{false};
  Metrics* metrics_{nullptr};
  size_t cap_{capFromEnv()};
  std::mutex mu_;
  std::vector<Event> events_;
};

}  // namespace tpucoll
