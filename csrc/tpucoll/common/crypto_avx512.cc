// AVX-512 ChaCha20 keystream and fused ChaCha20+Poly1305 AEAD bulk
// kernels: 16 blocks (1024 bytes) per pass, words held "vertically"
// (one zmm = word i of blocks 0..15) so the scalar round function maps
// 1:1 onto vector ops, with single-instruction 32-bit rotates (vprold —
// the reason this tier exists: the AVX2 path spends a shuffle or a
// shift+shift+or per rotate).
//
// The fused kernels interleave Poly1305 4-block groups between ChaCha
// double-rounds IN THE SAME LOOP BODY: poly's 64x64 scalar multiplies
// and chacha's zmm ALU ops retire on different execution ports, so the
// out-of-order core runs them concurrently — measured materially faster
// than running the two passes back-to-back, where the ~224-entry OOO
// window can only overlap the seams. Seal lags poly one chunk behind
// the cipher (poly eats ciphertext); open runs both on the same chunk.
//
// Compiled in its own TU with -mavx512f only when the toolchain
// supports it (TPUCOLL_HAVE_AVX512); callers dispatch at runtime via
// __builtin_cpu_supports (crypto.cc).
#include <cstddef>
#include <cstdint>

#include <immintrin.h>

#include "tpucoll/common/poly1305_impl.h"

namespace tpucoll {
namespace crypto_detail {

namespace {

#define TC_ZQR(a, b, c, d)                          \
  a = _mm512_add_epi32(a, b);                       \
  d = _mm512_rol_epi32(_mm512_xor_si512(d, a), 16); \
  c = _mm512_add_epi32(c, d);                       \
  b = _mm512_rol_epi32(_mm512_xor_si512(b, c), 12); \
  a = _mm512_add_epi32(a, b);                       \
  d = _mm512_rol_epi32(_mm512_xor_si512(d, a), 8);  \
  c = _mm512_add_epi32(c, d);                       \
  b = _mm512_rol_epi32(_mm512_xor_si512(b, c), 7)

// Transpose the 16x16 u32 matrix "v[word] lane block" into
// "out[j] = 64-byte block j" order: 32-bit and 64-bit unpacks build,
// per 128-bit lane l, the column 4l+c of a 4-row group; two
// shuffle_i32x4 levels then gather one column across the four groups.
inline void transpose16x16(__m512i v[16], __m512i out[16]) {
  __m512i t[16], u[16];
  for (int g = 0; g < 4; g++) {
    t[4 * g + 0] = _mm512_unpacklo_epi32(v[4 * g + 0], v[4 * g + 1]);
    t[4 * g + 1] = _mm512_unpackhi_epi32(v[4 * g + 0], v[4 * g + 1]);
    t[4 * g + 2] = _mm512_unpacklo_epi32(v[4 * g + 2], v[4 * g + 3]);
    t[4 * g + 3] = _mm512_unpackhi_epi32(v[4 * g + 2], v[4 * g + 3]);
  }
  for (int g = 0; g < 4; g++) {
    // u[4g+c] lane l = words 4g..4g+3 of block 4l+c.
    u[4 * g + 0] = _mm512_unpacklo_epi64(t[4 * g + 0], t[4 * g + 2]);
    u[4 * g + 1] = _mm512_unpackhi_epi64(t[4 * g + 0], t[4 * g + 2]);
    u[4 * g + 2] = _mm512_unpacklo_epi64(t[4 * g + 1], t[4 * g + 3]);
    u[4 * g + 3] = _mm512_unpackhi_epi64(t[4 * g + 1], t[4 * g + 3]);
  }
  for (int c = 0; c < 4; c++) {
    const __m512i a0 = _mm512_shuffle_i32x4(u[c], u[4 + c], 0x44);
    const __m512i a1 = _mm512_shuffle_i32x4(u[c], u[4 + c], 0xee);
    const __m512i b0 = _mm512_shuffle_i32x4(u[8 + c], u[12 + c], 0x44);
    const __m512i b1 = _mm512_shuffle_i32x4(u[8 + c], u[12 + c], 0xee);
    out[c] = _mm512_shuffle_i32x4(a0, b0, 0x88);
    out[4 + c] = _mm512_shuffle_i32x4(a0, b0, 0xdd);
    out[8 + c] = _mm512_shuffle_i32x4(a1, b1, 0x88);
    out[12 + c] = _mm512_shuffle_i32x4(a1, b1, 0xdd);
  }
}

inline void initVectors(const uint32_t state[16], uint32_t counter,
                        __m512i init[16]) {
  for (int i = 0; i < 16; i++) {
    init[i] = _mm512_set1_epi32(static_cast<int>(state[i]));
  }
  init[12] = _mm512_add_epi32(
      _mm512_set1_epi32(static_cast<int>(counter)),
      _mm512_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14,
                        15));
}

// 20 ChaCha rounds over v[16]; when kPoly, also absorb 1024 bytes at
// polySrc into the poly accumulator as 16 4-block groups, two per
// double-round for the first eight double-rounds — adjacent in the
// instruction stream with the vector ops they overlap.
template <bool kPoly>
inline void rounds(__m512i v[16], Poly1305* mac, const uint8_t* polySrc,
                   uint64_t* a0, uint64_t* a1, uint64_t* a2) {
  for (int round = 0; round < 10; round++) {
    TC_ZQR(v[0], v[4], v[8], v[12]);
    TC_ZQR(v[1], v[5], v[9], v[13]);
    TC_ZQR(v[2], v[6], v[10], v[14]);
    TC_ZQR(v[3], v[7], v[11], v[15]);
    if (kPoly && round < 8) {
      mac->group4(polySrc + round * 128, a0, a1, a2);
    }
    TC_ZQR(v[0], v[5], v[10], v[15]);
    TC_ZQR(v[1], v[6], v[11], v[12]);
    TC_ZQR(v[2], v[7], v[8], v[13]);
    TC_ZQR(v[3], v[4], v[9], v[14]);
    if (kPoly && round < 8) {
      mac->group4(polySrc + round * 128 + 64, a0, a1, a2);
    }
  }
}

// Rebuild the init vectors from scalar state instead of keeping 16 more
// zmm registers live across the rounds (v[16] + init[16] would be the
// entire register file; the resulting spills inside the round loop cost
// more than 16 broadcasts here).
inline void xorStore(const uint32_t state[16], uint32_t counter,
                     __m512i v[16], const uint8_t* in, uint8_t* out) {
  __m512i init[16], ks[16];
  initVectors(state, counter, init);
  for (int i = 0; i < 16; i++) {
    v[i] = _mm512_add_epi32(v[i], init[i]);
  }
  transpose16x16(v, ks);
  for (int b = 0; b < 16; b++) {
    const __m512i x =
        _mm512_xor_si512(_mm512_loadu_si512(in + 64 * b), ks[b]);
    _mm512_storeu_si512(out + 64 * b, x);
  }
}

}  // namespace

// XOR `in` with keystream for full 1024-byte chunks only; returns bytes
// consumed. Same contract as the AVX2 8-block tier (crypto.cc).
size_t chacha20Xor16Avx512(const uint32_t state[16], uint32_t counter,
                           const uint8_t* in, size_t n, uint8_t* out) {
  size_t done = 0;
  while (n - done >= 1024) {
    __m512i v[16];
    initVectors(state, counter, v);
    rounds<false>(v, nullptr, nullptr, nullptr, nullptr, nullptr);
    xorStore(state, counter, v, in + done, out + done);
    counter += 16;
    done += 1024;
  }
  return done;
}

// Fused seal bulk: encrypt full 1 KiB chunks AND absorb the produced
// ciphertext into `mac`, poly running one chunk behind the cipher.
// Returns bytes consumed; mac has absorbed exactly that ciphertext
// prefix (a multiple of 16 bytes, hibit=1 blocks). in == out allowed.
size_t sealFusedAvx512(const uint32_t state[16], uint32_t counter,
                       const uint8_t* in, size_t n, uint8_t* out,
                       Poly1305* mac) {
  size_t done = 0;
  uint64_t a0 = mac->h0, a1 = mac->h1, a2 = mac->h2;
  const uint8_t* lag = nullptr;  // previous chunk's ciphertext
  while (n - done >= 1024) {
    __m512i v[16];
    initVectors(state, counter, v);
    if (lag != nullptr) {
      rounds<true>(v, mac, lag, &a0, &a1, &a2);
    } else {
      rounds<false>(v, nullptr, nullptr, nullptr, nullptr, nullptr);
    }
    xorStore(state, counter, v, in + done, out + done);
    lag = out + done;
    counter += 16;
    done += 1024;
  }
  mac->h0 = a0;
  mac->h1 = a1;
  mac->h2 = a2;
  if (lag != nullptr) {
    mac->blocks(lag, 1024, 1);  // the chunk the pipeline still owes
  }
  return done;
}

// Fused open bulk: absorb ciphertext into `mac` and decrypt, same chunk
// per iteration (poly group loads precede the chunk's stores in program
// order, so in == out in-place decryption is safe). Returns bytes
// consumed. NOTE: bytes are decrypted before the caller verifies the
// tag; on mismatch the output is unspecified, per the aeadOpen contract.
size_t openFusedAvx512(const uint32_t state[16], uint32_t counter,
                       const uint8_t* in, size_t n, uint8_t* out,
                       Poly1305* mac) {
  size_t done = 0;
  uint64_t a0 = mac->h0, a1 = mac->h1, a2 = mac->h2;
  while (n - done >= 1024) {
    __m512i v[16];
    initVectors(state, counter, v);
    rounds<true>(v, mac, in + done, &a0, &a1, &a2);
    xorStore(state, counter, v, in + done, out + done);
    counter += 16;
    done += 1024;
  }
  mac->h0 = a0;
  mac->h1 = a1;
  mac->h2 = a2;
  return done;
}

#undef TC_ZQR

}  // namespace crypto_detail
}  // namespace tpucoll
