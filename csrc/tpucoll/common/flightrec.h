// Always-on flight recorder: a per-Context, fixed-size, lock-free ring of
// collective/p2p operation records that survives to a post-mortem dump.
//
// The Tracer (tracer.h) is opt-in, unbounded, and lost with the process;
// the metrics registry (metrics.h) aggregates but forgets ordering. This
// layer is the black box in between: every operation the context issues
// gets one ring entry {seq, opcode, algorithm, slot, peer, bytes, dtype,
// state, timestamps, fingerprint}, where `seq` is a monotonic per-context
// collective sequence number stamped at the public collective entry
// points (collectives/*.cc) and the transport layer (transport/pair.cc)
// flips enqueued -> started the moment payload bytes actually move.
//
// Cost contract (always on, no enable gate): a state transition is ONE
// relaxed atomic store (a timestamp); entry allocation is one relaxed
// fetch_add plus relaxed field stores. No locks anywhere on the data
// path — the ring is preallocated and writers never block.
//
// Dump triggers (docs/flightrec.md):
//  - straggler-watchdog stall            (transport::Context::reportStall)
//  - transport failure                   (transport::Context::onPairError)
//  - fatal signal, opt-in               (installSignalHandler /
//                                        TPUCOLL_FLIGHTREC_SIGNALS=1)
//  - explicit                            (tc_flightrec_dump / Python)
// Automatic dumps go to TPUCOLL_FLIGHTREC_DIR/flightrec-rank<r>.json and
// are throttled; when the env var is unset automatic triggers are no-ops.
//
// The per-op `fingerprint` (FNV-1a over opcode/dtype/bytes/root) is what
// the cross-rank desync detector compares: ranks whose fingerprints
// differ at the same seq issued DIFFERENT collectives — the classic
// unrecoverable desync — and the merged report can say which rank ran
// what (gloo_tpu/utils/flightrec.py, resilience.stall_reports).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>

namespace tpucoll {

class FlightRecorder {
 public:
  enum State : int { kEnqueued = 0, kStarted = 1, kCompleted = 2 };

  // All fields relaxed-atomic: written by the issuing thread (or, for
  // ts[kStarted], the transport loop thread) and read by the dumper,
  // possibly from a signal handler. A dump racing a writer may see one
  // half-written row; the `seq` check below keeps it from mixing rows
  // from different laps of the ring.
  struct Entry {
    std::atomic<uint64_t> seq{0};
    // Collective sequence number: increments ONLY for collectives, so it
    // is comparable ACROSS ranks (p2p traffic is legitimately rank-
    // asymmetric — rank 1 sends while rank 0 receives — and must not
    // shift or poison the desync comparison). -1 for p2p entries.
    std::atomic<int64_t> cseq{-1};
    std::atomic<const char*> opcode{nullptr};     // static string
    std::atomic<const char*> algorithm{nullptr};  // static string or null
    std::atomic<uint64_t> slot{0};
    std::atomic<int32_t> peer{-1};  // root for rooted collectives, -1 else
    std::atomic<uint64_t> bytes{0};
    std::atomic<uint8_t> dtype{kNoDtype};
    std::atomic<uint64_t> fingerprint{0};
    std::atomic<int64_t> ts[3] = {};  // indexed by State; 0 = not reached
  };

  static constexpr uint8_t kNoDtype = 0xFF;

  // Capacity from TPUCOLL_FLIGHTREC_EVENTS (default 1024), rounded up to
  // a power of two so the ring index is a mask.
  FlightRecorder(int rank, int size);
  ~FlightRecorder();

  FlightRecorder(const FlightRecorder&) = delete;
  FlightRecorder& operator=(const FlightRecorder&) = delete;

  // ---- hot path -------------------------------------------------------
  // Allocate the next ring entry and stamp the enqueued transition.
  // Returns the op's ring sequence number. `peer` carries the root for
  // rooted collectives and the destination/source for p2p ops; `dtype`
  // is the DataType code (kNoDtype for untyped ops like barrier).
  //
  // beginCollective additionally advances the cross-rank collective
  // sequence and fingerprints the op. `fpBytes` must be RANK-INVARIANT
  // (every rank passes the same value for a matching schedule): the
  // caller's own payload share for symmetric collectives, the group
  // total for the *v forms, 0 where per-rank sizes legitimately differ
  // (alltoallv).
  uint64_t beginCollective(const char* opcode, const char* algorithm,
                           uint64_t slot, int peer, uint64_t bytes,
                           uint8_t dtype, uint64_t fpBytes);
  uint64_t beginP2p(const char* opcode, uint64_t slot, int peer,
                    uint64_t bytes);

  // Instantaneous structured event (fleet anomaly detectors,
  // common/fleetobs.cc): one ring entry enqueued/started/completed at
  // the same instant, so /flightrec post-mortems carry the detector
  // verdicts the live /fleet view showed. `opcode` must be a static
  // string like every opcode here; `peer` is the blamed rank and
  // `detail` rides the bytes field (detector-defined unit, e.g. blamed
  // microseconds).
  uint64_t noteEvent(const char* opcode, int peer, uint64_t detail);

  // Record a state transition for op `seq`: one relaxed store. A seq
  // already overwritten by a newer lap of the ring — or the kNoSeq
  // sentinel (no matched entry / row mid-rewrite) — is ignored.
  void transition(uint64_t seq, State state) {
    if (seq == kNoSeq) {
      return;
    }
    Entry& e = entries_[seq & mask_];
    if (e.seq.load(std::memory_order_relaxed) != seq) {
      return;  // lapped: this op's row was reused
    }
    e.ts[state].store(nowUs(), std::memory_order_relaxed);
  }

  // Late algorithm resolution (kAuto dispatch happens after the entry is
  // allocated).
  void setAlgorithm(uint64_t seq, const char* algorithm) {
    Entry& e = entries_[seq & mask_];
    if (e.seq.load(std::memory_order_relaxed) != seq) {
      return;
    }
    e.algorithm.store(algorithm, std::memory_order_relaxed);
  }

  // Transport progress (pair.cc): flip the most recently issued op from
  // enqueued to started the first time payload bytes move for it. Two
  // relaxed loads on the already-started common case; the transition
  // itself is the contractual single relaxed store. With concurrent
  // same-context collectives (distinct tags on several threads) the
  // attribution is approximate — acceptable for a post-mortem record.
  void markTransportProgress() {
    const uint64_t next = nextSeq_.load(std::memory_order_relaxed);
    if (next == 0) {
      return;
    }
    const uint64_t seq = next - 1;
    Entry& e = entries_[seq & mask_];
    if (e.seq.load(std::memory_order_relaxed) != seq ||
        e.ts[kStarted].load(std::memory_order_relaxed) != 0) {
      return;
    }
    e.ts[kStarted].store(nowUs(), std::memory_order_relaxed);
  }

  uint64_t nextSeq() const {
    return nextSeq_.load(std::memory_order_relaxed);
  }

  // Cross-rank collective sequence number of ring op `seq` (-1 for p2p
  // entries, lapped rows, or the kNoSeq sentinel). The phase profiler
  // (common/profile.h) keys its per-op breakdowns on this value so
  // per-rank breakdowns of the same collective are joinable.
  int64_t cseqOf(uint64_t seq) const {
    if (seq == kNoSeq) {
      return -1;
    }
    const Entry& e = entries_[seq & mask_];
    if (e.seq.load(std::memory_order_relaxed) != seq) {
      return -1;
    }
    return e.cseq.load(std::memory_order_relaxed);
  }

  // Sentinel for "no entry": also parked in a ring row's seq while its
  // fields are being rewritten, so a concurrent dump skips the torn row
  // whichever lap it expected there.
  static constexpr uint64_t kNoSeq = ~uint64_t(0);
  // Late peer resolution (recv-from-any learns its source at completion).
  void setPeer(uint64_t seq, int peer) {
    if (seq == kNoSeq) {
      return;
    }
    Entry& e = entries_[seq & mask_];
    if (e.seq.load(std::memory_order_relaxed) == seq) {
      e.peer.store(peer, std::memory_order_relaxed);
    }
  }

  // ---- dump path (slow, possibly inside a signal handler) -------------
  // Full JSON document (docs/flightrec.md "Record format").
  std::string toJson(const char* reason = "explicit",
                     int blamedPeer = -1) const;
  // Write the dump with only snprintf + write(2), usable from the fatal-
  // signal handler. Returns false on I/O error.
  bool dumpToFd(int fd, const char* reason, int blamedPeer) const;
  bool dumpToFile(const char* path, const char* reason,
                  int blamedPeer) const;

  // Automatic trigger: writes TPUCOLL_FLIGHTREC_DIR/flightrec-rank<r>.json
  // (no-op when the env var is unset). One-shot per context: the first
  // trigger is the evidence closest to the cause; later triggers are the
  // cascade and must not overwrite it (nor storm the disk). `reason`
  // must be a static string. Returns true when a file was written.
  bool autoDump(const char* reason, int blamedPeer = -1);

  // Opt-in fatal-signal dumping: installs handlers for SIGSEGV/SIGABRT/
  // SIGBUS/SIGFPE/SIGILL/SIGTERM that dump every live recorder to
  // TPUCOLL_FLIGHTREC_DIR, then re-raise with the default disposition.
  // Idempotent; also reachable via TPUCOLL_FLIGHTREC_SIGNALS=1 (checked
  // at context connect).
  static void installSignalHandler();
  static void maybeInstallFromEnv();

  int rank() const { return rank_; }

  // Dump-file tag for processes holding several recorders per rank
  // (async-engine lane contexts): when set (>= 0), automatic dumps —
  // stall / transport failure / fatal signal — go to
  // flightrec-rank<r>-lane<tag>.json instead of the plain per-rank
  // filename, so a lane's dump never clobbers (or races) the parent
  // context's. Explicit dumps name their own path and are unaffected.
  void setDumpTag(int tag) {
    dumpTag_.store(tag, std::memory_order_relaxed);
  }
  int dumpTag() const {
    return dumpTag_.load(std::memory_order_relaxed);
  }

  // Group dump-tag (split sub-communicators, Context::applyGroupTag):
  // when set, automatic dumps go to flightrec-rank<r>-g<tag>.json
  // (combined with a lane tag: ...-g<tag>-lane<k>.json) and every dump
  // document carries "group":"<tag>", so post-mortem tooling can
  // partition disjoint sub-groups BEFORE the desync comparison — two
  // groups legitimately run different schedules and must never be
  // fingerprint-compared against each other (utils/flightrec.py
  // merge_by_tag). Set once before traffic; '/' (nested splits) is
  // mapped to '.' in the filename form. Truncated at 63 bytes.
  void setGroupTag(const char* tag);
  const char* groupTag() const { return groupTag_; }
  const char* groupTagFile() const { return groupTagFile_; }

  static constexpr size_t kGroupTagBytes = 64;

  static int64_t nowUs();

 private:
  uint64_t begin(const char* opcode, const char* algorithm, uint64_t slot,
                 int peer, uint64_t bytes, uint8_t dtype, int64_t cseq,
                 uint64_t fingerprint);

  const int rank_;
  const int size_;
  uint64_t mask_;  // capacity - 1 (capacity is a power of two)
  std::unique_ptr<Entry[]> entries_;
  std::atomic<uint64_t> nextSeq_{0};
  std::atomic<int64_t> nextCollSeq_{0};
  std::atomic<int64_t> lastAutoDumpUs_{0};
  std::atomic<const char*> lastReason_{nullptr};
  std::atomic<int> dumpTag_{-1};
  // Written once at group creation, before any traffic; read by dump
  // paths (including the fatal-signal handler — plain char arrays, no
  // allocation). groupTagFile_ is the filename-safe form ('/' -> '.').
  char groupTag_[kGroupTagBytes] = {0};
  char groupTagFile_[kGroupTagBytes] = {0};
  int slotIdx_{-1};  // index into the process-global registry, -1 if full
};

// RAII op scope for the public collective entry points: allocates the
// ring entry at construction and stamps `completed` at destruction —
// unless the scope unwinds through an exception, in which case the op
// stays at its last state so the dump shows it in flight (the truthful
// post-mortem for a failed collective).
class FlightRecOp {
 public:
  // `fpBytes` defaults to `bytes`; pass the rank-invariant total for the
  // *v collectives (see beginCollective).
  FlightRecOp(FlightRecorder* rec, const char* opcode, const char* algorithm,
              uint64_t slot, int peer, uint64_t bytes, uint8_t dtype,
              uint64_t fpBytes = ~uint64_t(0));
  ~FlightRecOp();
  FlightRecOp(const FlightRecOp&) = delete;
  FlightRecOp& operator=(const FlightRecOp&) = delete;

  uint64_t seq() const { return seq_; }
  // Cross-rank collective sequence of this op (-1 for p2p scopes) — the
  // phase profiler's join key.
  int64_t cseq() const {
    return rec_ != nullptr ? rec_->cseqOf(seq_) : -1;
  }
  void setAlgorithm(const char* algorithm) {
    if (rec_ != nullptr) {
      rec_->setAlgorithm(seq_, algorithm);
    }
  }

 private:
  FlightRecorder* rec_;
  uint64_t seq_{0};
  int exceptionsAtEntry_{0};
};

}  // namespace tpucoll
