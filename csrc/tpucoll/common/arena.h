// Grow-only scratch arena: a single aligned block that only ever grows
// to its high watermark and is reused verbatim below it. The persistent
// collective plans (collectives/plan.h) hold one arena per staging slot
// so the steady-state replay of a repeated collective touches warm,
// already-registered pages — no allocation, no first-touch page faults,
// no re-registration.
//
// NOT thread-safe and NOT stable across growth: require() may move the
// block when the watermark rises, invalidating every pointer (and any
// UnboundBuffer registered over it). Owners that pair an arena with a
// registration must rebuild the registration whenever require() grows —
// plan::Plan::stage() is the reference user.
#pragma once

#include <cstddef>

namespace tpucoll {

class Arena {
 public:
  Arena() = default;
  ~Arena();

  Arena(const Arena&) = delete;
  Arena& operator=(const Arena&) = delete;
  Arena(Arena&& o) noexcept;
  Arena& operator=(Arena&&) = delete;

  // Pointer to at least minBytes of scratch, 64-byte aligned. Grows
  // (moving the block) only when minBytes exceeds the current
  // watermark; otherwise returns the existing block untouched.
  char* require(size_t minBytes);

  char* data() const { return buf_; }
  size_t capacity() const { return cap_; }

  // True when the last require() call grew (or first-allocated) the
  // block — the signal to rebuild anything registered over it.
  bool grewOnLastRequire() const { return grew_; }

 private:
  char* buf_{nullptr};
  size_t cap_{0};
  bool grew_{false};
};

}  // namespace tpucoll
