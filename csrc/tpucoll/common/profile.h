// Phase-level collective profiler: decomposes every collective into its
// canonical schedule phases and records where the time went.
//
// The metrics registry (metrics.h) answers "how long did allreduce
// take"; the flight recorder (flightrec.h) answers "what was in flight
// when we died"; neither answers "WHY was this allreduce slow" — a
// 64 MiB ring op is one histogram sample with no decomposition into
// pack/wire/reduce time and no way to tell "my reduce is slow" from
// "rank 3 is a straggler". HiCCL/GC3-style composed schedules make the
// phases first-class; this layer measures them:
//
//  - pack       local staging: input combine, wire encode (bf16/q8),
//               layout copies before bytes can move
//  - post       posting sends/recvs to the transport (includes any
//               fault-injected send delay on the posting thread)
//  - wire_wait  blocking waits for wire completions (waitSend/waitRecv;
//               on fused receive-reduce paths the combine runs inside
//               the wait and is attributed here — docs/profiling.md)
//  - reduce     explicit arithmetic: staged-arrival reduction kernels
//  - unpack     local unstaging: wire decode, result fan-out copies
//  - intra/inter/fanout   the hierarchical composition's host phases
//               (group/hier.cc): intra-host reduce, inter-host
//               exchange, intra-host result distribution
//
// Mechanism: ProfileOpScope (stamped in every public collective entry,
// next to MetricsOp/FlightRecOp) opens a per-op accumulator and parks it
// in a thread-local; PhaseScope (stamped inside the algorithm bodies)
// adds its elapsed time to the accumulator's phase bucket. Collectives
// execute synchronously on the calling thread, so the thread-local needs
// no synchronization; nested collectives (hier phases are ordinary
// collectives on split sub-contexts) save/restore it like a stack, each
// op accruing to ITS context's profiler.
//
// Cost contract (same discipline as metrics.h): disabled —
// TPUCOLL_PROFILE=0 — costs one relaxed load plus a thread-local park
// per collective entry (the park keeps a disabled nested op's phases
// from charging an enabled outer op) and one thread-local read per
// phase scope, no clock reads, no records.
// Enabled, a phase scope is two clock_gettime calls and plain stores
// into the stack accumulator; the per-op flush (ring publish + phase
// histograms) runs once per collective, off the per-segment path.
//
// Output, per op, into a bounded lock-free ring (TPUCOLL_PROFILE_RING
// entries) keyed by the flight recorder's cross-rank collective
// sequence number `cseq` — the join key that lets
// gloo_tpu/utils/profile.py line up rank 0's breakdown of collective
// #41 against rank 3's and attribute wait time to the straggler — and,
// aggregated, into per-(collective, algorithm, phase) histograms in the
// metrics registry (scraped as gloo_tpu_phase_latency_us).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>

namespace tpucoll {

class Metrics;

namespace span {
struct OpState;
}  // namespace span

namespace profile {

enum class Phase : uint8_t {
  kPack = 0,
  kPost,
  kWireWait,
  kReduce,
  kUnpack,
  kIntra,
  kInter,
  kFanout,
  kCount,
};

constexpr int kPhaseCount = static_cast<int>(Phase::kCount);

const char* phaseName(Phase p);

// Stack-allocated per-op accumulator: written only by the owning thread
// (PhaseScope dtors), read once at op end by the flush. Plain integers.
struct OpAccumulator {
  int64_t phaseUs[kPhaseCount] = {};
};

class Profiler {
 public:
  // Ring row. All fields relaxed-atomic: written by the completing op's
  // thread, read by a concurrent toJson; the claim-then-publish `seq`
  // protocol (flightrec.h) keeps a dump from mixing rows across laps.
  struct Entry {
    std::atomic<uint64_t> seq{0};
    std::atomic<int64_t> cseq{-1};
    std::atomic<const char*> opcode{nullptr};     // static string
    std::atomic<const char*> algorithm{nullptr};  // static string or null
    std::atomic<uint64_t> bytes{0};
    std::atomic<int64_t> startUs{0};
    std::atomic<int64_t> totalUs{0};
    std::atomic<int64_t> phaseUs[kPhaseCount] = {};
  };

  static constexpr uint64_t kNoSeq = ~uint64_t(0);

  // Capacity from TPUCOLL_PROFILE_RING (default 256), rounded up to a
  // power of two; enable gate from TPUCOLL_PROFILE (default 1).
  // `metrics` receives the per-(op, algorithm, phase) histogram flush;
  // may be null (standalone tests).
  Profiler(int rank, int size, Metrics* metrics);

  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  void setEnabled(bool on) {
    enabled_.store(on, std::memory_order_relaxed);
  }

  // Publish one completed op: allocate the next ring row, stamp it, and
  // flush the nonzero phases into the metrics registry's keyed
  // histograms. Called once per profiled collective, from its
  // ProfileOpScope destructor.
  void record(const char* opcode, const char* algorithm, int64_t cseq,
              uint64_t bytes, int64_t startUs, int64_t totalUs,
              const OpAccumulator& acc);

  uint64_t nextSeq() const {
    return nextSeq_.load(std::memory_order_relaxed);
  }
  // Rows overwritten because more ops completed than the ring holds.
  uint64_t dropped() const {
    const uint64_t next = nextSeq();
    const uint64_t cap = mask_ + 1;
    return next > cap ? next - cap : 0;
  }

  // Full JSON document: {"version", "kind", "rank", "size", "group",
  // "enabled", "next_seq", "capacity", "dropped", "ops": [{"seq",
  // "cseq", "op", "algo", "bytes", "start_us", "total_us",
  // "phases": {"pack": us, ...}} ...]} — nonzero phases only.
  std::string toJson() const;

  int rank() const { return rank_; }

 private:
  const int rank_;
  const int size_;
  Metrics* metrics_;
  std::atomic<bool> enabled_{true};
  uint64_t mask_;  // capacity - 1 (power of two)
  std::unique_ptr<Entry[]> entries_;
  std::atomic<uint64_t> nextSeq_{0};
};

// The thread-local accumulator stack head. Non-null exactly while an
// enabled ProfileOpScope is alive on this thread; PhaseScope reads it
// once at construction (so a hier phase scope opened before a nested
// sub-context op keeps accruing to the PARENT's accumulator).
OpAccumulator* currentOp();

// RAII op scope for the public collective entry points. Opens the
// accumulator, parks it in the thread-local (saving the previous head
// for nested collectives), and on destruction publishes the op to the
// profiler ring + metrics phase histograms. `cseq` is the flight
// recorder's cross-rank collective sequence (FlightRecOp::cseq()).
// Disabled profiler: one relaxed load, everything else skipped.
class ProfileOpScope {
 public:
  ProfileOpScope(Profiler* profiler, const char* opcode, int64_t cseq,
                 uint64_t bytes);
  ~ProfileOpScope();
  ProfileOpScope(const ProfileOpScope&) = delete;
  ProfileOpScope& operator=(const ProfileOpScope&) = delete;

  // Late algorithm resolution (kAuto dispatch), mirrors
  // FlightRecOp::setAlgorithm.
  void setAlgorithm(const char* algorithm) { algorithm_ = algorithm; }

 private:
  Profiler* profiler_;  // null when disabled at entry
  const char* opcode_;
  const char* algorithm_{nullptr};
  int64_t cseq_;
  uint64_t bytes_;
  int64_t startUs_;
  OpAccumulator acc_;
  OpAccumulator* prev_;
};

// RAII phase scope: adds its elapsed wall time to the current op's
// phase bucket, and — when a span::OpScope is live on this thread
// (common/span.h) — emits this instance as one causal span. No-op
// (two thread-local reads) when neither recorder has an active op.
//
// The annotated constructor carries the wire identity the causal
// graph needs: a kPost scope posting a SEND toward `peer` emits a
// "send" span (injected send delays run inside it); a kWireWait scope
// waiting for an arrival FROM `peer` emits a "recv" span. Recv POSTS
// and drain waits keep the plain form ("local"/"wait" spans).
class PhaseScope {
 public:
  explicit PhaseScope(Phase phase);
  PhaseScope(Phase phase, int peer, uint64_t slot, uint64_t bytes);
  ~PhaseScope();
  PhaseScope(const PhaseScope&) = delete;
  PhaseScope& operator=(const PhaseScope&) = delete;

 private:
  OpAccumulator* op_;
  span::OpState* spanOp_;
  Phase phase_;
  int32_t peer_;
  uint64_t slot_;
  uint64_t bytes_;
  int64_t startUs_;
};

}  // namespace profile
}  // namespace tpucoll
