#include "tpucoll/common/tracer.h"

#include <cstdlib>
#include <sstream>

#include "tpucoll/common/metrics.h"
#include "tpucoll/common/env.h"

namespace tpucoll {

size_t Tracer::capFromEnv() {
  // Strict count (common/env.h): atoll used to read "-5"/"lots" as
  // "keep the default" instead of failing the misconfiguration.
  return static_cast<size_t>(
      envCount("TPUCOLL_TRACE_MAX_EVENTS", 262144, 1, 1L << 31));
}

void Tracer::record(const Event& event) {
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (events_.size() < cap_) {
      events_.push_back(event);
      return;
    }
  }
  // Cap hit: drop the newest span (the retained prefix keeps its
  // uninterrupted timeline) and make the loss visible in the registry.
  if (metrics_ != nullptr) {
    metrics_->recordTraceDropped();
  }
}

std::string Tracer::toJson(int pid, bool drain) {
  std::vector<Event> events;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (drain) {
      events.swap(events_);
    } else {
      events = events_;
    }
  }
  std::ostringstream out;
  out << "[";
  bool first = true;
  for (const auto& e : events) {
    if (!first) {
      out << ",\n";
    }
    first = false;
    out << "{\"name\":\"" << e.name << "\",\"ph\":\"X\",\"ts\":" << e.startUs
        << ",\"dur\":" << (e.endUs - e.startUs) << ",\"pid\":" << pid
        << ",\"tid\":0,\"args\":{\"bytes\":" << e.bytes;
    if (e.peer >= 0) {
      out << ",\"peer\":" << e.peer;
    }
    if (e.detail != nullptr) {
      out << ",\"detail\":\"" << e.detail << "\"";
    }
    out << "}}";
  }
  out << "]";
  return out.str();
}

}  // namespace tpucoll
