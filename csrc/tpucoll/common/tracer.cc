#include "tpucoll/common/tracer.h"

#include <sstream>

namespace tpucoll {

std::string Tracer::toJson(int pid, bool drain) {
  std::vector<Event> events;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (drain) {
      events.swap(events_);
    } else {
      events = events_;
    }
  }
  std::ostringstream out;
  out << "[";
  bool first = true;
  for (const auto& e : events) {
    if (!first) {
      out << ",\n";
    }
    first = false;
    out << "{\"name\":\"" << e.name << "\",\"ph\":\"X\",\"ts\":" << e.startUs
        << ",\"dur\":" << (e.endUs - e.startUs) << ",\"pid\":" << pid
        << ",\"tid\":0,\"args\":{\"bytes\":" << e.bytes;
    if (e.peer >= 0) {
      out << ",\"peer\":" << e.peer;
    }
    if (e.detail != nullptr) {
      out << ",\"detail\":\"" << e.detail << "\"";
    }
    out << "}}";
  }
  out << "]";
  return out.str();
}

}  // namespace tpucoll
