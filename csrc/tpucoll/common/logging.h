// tpucoll L0: logging + enforcement macros + exception hierarchy.
//
// TPU-native rebuild of the reference's common layer (see
// /root/reference/gloo/common/logging.h:40-207 and gloo/common/error.h for the
// contracts being matched: leveled stderr logging gated by an env var, an
// ENFORCE family that throws with file:line context, and an exception tree
// where transport failures and timeouts are distinguishable).
#pragma once

#include <sstream>
#include <stdexcept>
#include <string>

namespace tpucoll {

enum class LogLevel : int {
  kDebug = 0,
  kInfo = 1,
  kWarn = 2,
  kError = 3,
};

// Threshold parsed once from TPUCOLL_LOG_LEVEL (DEBUG/INFO/WARN/ERROR or 0-3).
// Default WARN so library is quiet under tests.
LogLevel logThreshold();

void logMessage(LogLevel level, const char* file, int line,
                const std::string& msg);

namespace detail {

inline void strAppend(std::ostringstream&) {}

template <typename T, typename... Rest>
void strAppend(std::ostringstream& oss, const T& v, const Rest&... rest) {
  oss << v;
  strAppend(oss, rest...);
}

template <typename... Args>
std::string strCat(const Args&... args) {
  std::ostringstream oss;
  strAppend(oss, args...);
  return oss.str();
}

}  // namespace detail

#define TC_LOG(level, ...)                                                    \
  do {                                                                        \
    if (static_cast<int>(level) >=                                            \
        static_cast<int>(::tpucoll::logThreshold())) {                        \
      ::tpucoll::logMessage(level, __FILE__, __LINE__,                        \
                            ::tpucoll::detail::strCat(__VA_ARGS__));          \
    }                                                                         \
  } while (0)

#define TC_DEBUG(...) TC_LOG(::tpucoll::LogLevel::kDebug, __VA_ARGS__)
#define TC_INFO(...) TC_LOG(::tpucoll::LogLevel::kInfo, __VA_ARGS__)
#define TC_WARN(...) TC_LOG(::tpucoll::LogLevel::kWarn, __VA_ARGS__)
#define TC_ERROR(...) TC_LOG(::tpucoll::LogLevel::kError, __VA_ARGS__)

// Root of the exception hierarchy. what() always carries file:line.
class Exception : public std::runtime_error {
 public:
  explicit Exception(const std::string& msg) : std::runtime_error(msg) {}
};

// Programmer error / contract violation (bad argument, bad state).
class EnforceError : public Exception {
 public:
  using Exception::Exception;
};

// Transport-level failure: peer died, connection reset, socket error.
// Contract (matching reference docs/errors.md): after an IoException the
// context is poisoned; the caller rebuilds contexts/pairs to recover.
class IoException : public Exception {
 public:
  using Exception::Exception;
};

// A blocking wait exceeded its deadline. Subtype of IoException so generic
// "transport failed" handling catches it too.
class TimeoutException : public IoException {
 public:
  using IoException::IoException;
};

// A wait was cancelled via abort().
class AbortedException : public Exception {
 public:
  using Exception::Exception;
};

#define TC_THROW(ExcType, ...)                                                \
  throw ExcType(::tpucoll::detail::strCat("[", __FILE__, ":", __LINE__, "] ", \
                                          __VA_ARGS__))

#define TC_ENFORCE(cond, ...)                                                 \
  do {                                                                        \
    if (!(cond)) {                                                            \
      TC_THROW(::tpucoll::EnforceError, "enforce failed: " #cond " ",         \
               ##__VA_ARGS__);                                                \
    }                                                                         \
  } while (0)

#define TC_ENFORCE_EQ(a, b, ...) TC_ENFORCE((a) == (b), ##__VA_ARGS__)
#define TC_ENFORCE_NE(a, b, ...) TC_ENFORCE((a) != (b), ##__VA_ARGS__)
#define TC_ENFORCE_GE(a, b, ...) TC_ENFORCE((a) >= (b), ##__VA_ARGS__)
#define TC_ENFORCE_GT(a, b, ...) TC_ENFORCE((a) > (b), ##__VA_ARGS__)
#define TC_ENFORCE_LE(a, b, ...) TC_ENFORCE((a) <= (b), ##__VA_ARGS__)
#define TC_ENFORCE_LT(a, b, ...) TC_ENFORCE((a) < (b), ##__VA_ARGS__)

}  // namespace tpucoll
