#include "tpucoll/common/keyring.h"

#include <cstdio>
#include <cstring>

#include "tpucoll/common/crypto.h"
#include "tpucoll/common/logging.h"

namespace tpucoll {

namespace {

constexpr char kPrefix[] = "tcring1";

void le32(uint32_t v, uint8_t out[4]) {
  out[0] = static_cast<uint8_t>(v);
  out[1] = static_cast<uint8_t>(v >> 8);
  out[2] = static_cast<uint8_t>(v >> 16);
  out[3] = static_cast<uint8_t>(v >> 24);
}

int hexVal(char c) {
  if (c >= '0' && c <= '9') {
    return c - '0';
  }
  if (c >= 'a' && c <= 'f') {
    return c - 'a' + 10;
  }
  return -1;
}

}  // namespace

Keyring Keyring::derive(const std::string& rootKey, int rank, int size) {
  TC_ENFORCE(!rootKey.empty(), "keyring derivation needs a root key");
  TC_ENFORCE(rank >= 0 && rank < size && size >= 2,
             "bad rank/size for keyring: ", rank, "/", size);
  Keyring ring;
  ring.rank_ = rank;
  ring.size_ = size;
  ring.keys_.assign(static_cast<size_t>(size) * kKeyBytes, 0);
  static constexpr char kSalt[] = "tpucoll-pairkey-v1";
  for (int s = 0; s < size; s++) {
    if (s == rank) {
      continue;  // no self-key; the slot stays zeroed
    }
    // K[a,b] is symmetric in (a,b): key the pair by (min, max).
    uint8_t info[8];
    le32(static_cast<uint32_t>(rank < s ? rank : s), info);
    le32(static_cast<uint32_t>(rank < s ? s : rank), info + 4);
    hkdfSha256(rootKey.data(), rootKey.size(), kSalt, sizeof(kSalt) - 1,
               info, sizeof(info),
               ring.keys_.data() + static_cast<size_t>(s) * kKeyBytes,
               kKeyBytes);
  }
  return ring;
}

std::string Keyring::serialize() const {
  TC_ENFORCE(valid(), "cannot serialize an empty keyring");
  std::string out(kPrefix);
  out += ":" + std::to_string(rank_) + ":" + std::to_string(size_) + ":";
  static const char* hex = "0123456789abcdef";
  out.reserve(out.size() + keys_.size() * 2);
  for (uint8_t b : keys_) {
    out.push_back(hex[b >> 4]);
    out.push_back(hex[b & 0xf]);
  }
  return out;
}

Keyring Keyring::parse(const std::string& blob) {
  int rank = -1;
  int size = -1;
  int consumed = -1;
  TC_ENFORCE(
      std::sscanf(blob.c_str(), "tcring1:%d:%d:%n", &rank, &size,
                  &consumed) == 2 && consumed > 0,
      "malformed keyring (want \"tcring1:<rank>:<size>:<hex>\")");
  TC_ENFORCE(rank >= 0 && size >= 2 && rank < size && size <= (1 << 20),
             "keyring rank/size out of range: ", rank, "/", size);
  const size_t want = static_cast<size_t>(size) * kKeyBytes * 2;
  TC_ENFORCE_EQ(blob.size() - static_cast<size_t>(consumed), want,
                "keyring hex length mismatch");
  Keyring ring;
  ring.rank_ = rank;
  ring.size_ = size;
  ring.keys_.resize(static_cast<size_t>(size) * kKeyBytes);
  const char* p = blob.c_str() + consumed;
  for (size_t i = 0; i < ring.keys_.size(); i++) {
    const int hi = hexVal(p[2 * i]);
    const int lo = hexVal(p[2 * i + 1]);
    TC_ENFORCE(hi >= 0 && lo >= 0, "keyring contains non-hex bytes");
    ring.keys_[i] = static_cast<uint8_t>((hi << 4) | lo);
  }
  return ring;
}

std::string Keyring::keyFor(int peer) const {
  TC_ENFORCE(valid(), "no keyring configured");
  TC_ENFORCE(peer >= 0 && peer < size_ && peer != rank_,
             "no pairwise key for peer rank ", peer, " (self ", rank_,
             ", size ", size_, ")");
  return std::string(
      reinterpret_cast<const char*>(keys_.data()) +
          static_cast<size_t>(peer) * kKeyBytes,
      kKeyBytes);
}

}  // namespace tpucoll
