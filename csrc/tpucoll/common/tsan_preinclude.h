// ThreadSanitizer flavor workaround (forced into every TU of the
// SANITIZE=thread build via -include; never included by name).
//
// gcc-10's libtsan has no pthread_cond_clockwait interceptor, but on
// glibc >= 2.30 libstdc++-10 routes condition_variable::wait_for /
// wait_until<steady_clock> through exactly that call
// (_GLIBCXX_USE_PTHREAD_COND_CLOCKWAIT), so TSan never sees the
// unlock/relock happening inside the wait and reports false
// "double lock of a mutex" on any mutex paired with a timed condvar
// wait (GCC PR98624). Pull in the config header first, then drop the
// flag: every timed condvar wait in this flavor compiles down to the
// intercepted pthread_cond_timedwait path instead. Timed waits ride
// the realtime clock in this flavor — fine for a test rig, which is
// all SANITIZE builds are (see Makefile).
#pragma once
#include <bits/c++config.h>
#undef _GLIBCXX_USE_PTHREAD_COND_CLOCKWAIT
