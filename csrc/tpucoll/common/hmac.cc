#include "tpucoll/common/hmac.h"

#include <fcntl.h>
#include <sys/random.h>
#include <unistd.h>

#include <cstring>

#include "tpucoll/common/logging.h"

namespace tpucoll {

namespace {

constexpr uint32_t kInit[8] = {
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

constexpr uint32_t kRound[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t v, int s) { return (v >> s) | (v << (32 - s)); }

void compress(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++) {
    w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
           (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
  }
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; i++) {
    uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + s1 + ch + kRound[i] + w[i];
    uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    h = g;
    g = f;
    f = e;
    e = d + t1;
    d = c;
    c = b;
    b = a;
    a = t1 + t2;
  }
  state[0] += a;
  state[1] += b;
  state[2] += c;
  state[3] += d;
  state[4] += e;
  state[5] += f;
  state[6] += g;
  state[7] += h;
}

}  // namespace

std::array<uint8_t, 32> sha256(const void* data, size_t len) {
  uint32_t state[8];
  std::memcpy(state, kInit, sizeof(state));
  const uint8_t* p = static_cast<const uint8_t*>(data);
  size_t full = len / 64;
  for (size_t i = 0; i < full; i++) {
    compress(state, p + 64 * i);
  }
  // Final padded block(s).
  uint8_t tail[128] = {0};
  size_t rem = len % 64;
  std::memcpy(tail, p + 64 * full, rem);
  tail[rem] = 0x80;
  size_t tailLen = (rem < 56) ? 64 : 128;
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; i++) {
    tail[tailLen - 1 - i] = uint8_t(bits >> (8 * i));
  }
  compress(state, tail);
  if (tailLen == 128) {
    compress(state, tail + 64);
  }
  std::array<uint8_t, 32> out;
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(state[i] >> 24);
    out[4 * i + 1] = uint8_t(state[i] >> 16);
    out[4 * i + 2] = uint8_t(state[i] >> 8);
    out[4 * i + 3] = uint8_t(state[i]);
  }
  return out;
}

std::array<uint8_t, 32> hmacSha256(const void* key, size_t keyLen,
                                   const void* msg, size_t msgLen) {
  uint8_t k[64] = {0};
  if (keyLen > 64) {
    auto kh = sha256(key, keyLen);
    std::memcpy(k, kh.data(), 32);
  } else {
    std::memcpy(k, key, keyLen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  std::string inner(reinterpret_cast<char*>(ipad), 64);
  inner.append(static_cast<const char*>(msg), msgLen);
  auto innerHash = sha256(inner.data(), inner.size());
  std::string outer(reinterpret_cast<char*>(opad), 64);
  outer.append(reinterpret_cast<char*>(innerHash.data()), 32);
  return sha256(outer.data(), outer.size());
}

bool macEqual(const uint8_t* a, const uint8_t* b, size_t n) {
  uint8_t acc = 0;
  for (size_t i = 0; i < n; i++) {
    acc |= a[i] ^ b[i];
  }
  return acc == 0;
}

void randomBytes(void* out, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(out);
  size_t got = 0;
  while (got < n) {
    ssize_t rv = getrandom(p + got, n - got, 0);
    TC_ENFORCE_GE(rv, 0, "getrandom failed");
    got += static_cast<size_t>(rv);
  }
}

}  // namespace tpucoll
