#include "tpucoll/common/sysinfo.h"

#include <ifaddrs.h>
#include <netinet/in.h>
#include <sys/socket.h>

#include <cstdio>
#include <cstring>

namespace tpucoll {

std::string interfaceForAddress(const sockaddr* addr) {
  if (addr == nullptr) {
    return "";
  }
  ifaddrs* list = nullptr;
  if (getifaddrs(&list) != 0) {
    return "";
  }
  std::string result;
  for (ifaddrs* ifa = list; ifa != nullptr; ifa = ifa->ifa_next) {
    if (ifa->ifa_addr == nullptr ||
        ifa->ifa_addr->sa_family != addr->sa_family) {
      continue;
    }
    bool match = false;
    if (addr->sa_family == AF_INET) {
      match = std::memcmp(
                  &reinterpret_cast<const sockaddr_in*>(addr)->sin_addr,
                  &reinterpret_cast<sockaddr_in*>(ifa->ifa_addr)->sin_addr,
                  sizeof(in_addr)) == 0;
    } else if (addr->sa_family == AF_INET6) {
      match = std::memcmp(
                  &reinterpret_cast<const sockaddr_in6*>(addr)->sin6_addr,
                  &reinterpret_cast<sockaddr_in6*>(ifa->ifa_addr)->sin6_addr,
                  sizeof(in6_addr)) == 0;
    }
    if (match) {
      result = ifa->ifa_name;
      break;
    }
  }
  freeifaddrs(list);
  return result;
}

int interfaceSpeedMbps(const std::string& name) {
  if (name.empty()) {
    return -1;
  }
  char path[256];
  snprintf(path, sizeof(path), "/sys/class/net/%s/speed", name.c_str());
  FILE* f = fopen(path, "r");
  if (f == nullptr) {
    return -1;
  }
  int speed = -1;
  if (fscanf(f, "%d", &speed) != 1) {
    speed = -1;
  }
  fclose(f);
  return speed;
}

}  // namespace tpucoll
