#include "tpucoll/common/sysinfo.h"

#include <arpa/inet.h>
#include <ifaddrs.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <vector>

namespace tpucoll {

std::string interfaceForAddress(const sockaddr* addr) {
  if (addr == nullptr) {
    return "";
  }
  ifaddrs* list = nullptr;
  if (getifaddrs(&list) != 0) {
    return "";
  }
  std::string result;
  for (ifaddrs* ifa = list; ifa != nullptr; ifa = ifa->ifa_next) {
    if (ifa->ifa_addr == nullptr ||
        ifa->ifa_addr->sa_family != addr->sa_family) {
      continue;
    }
    bool match = false;
    if (addr->sa_family == AF_INET) {
      match = std::memcmp(
                  &reinterpret_cast<const sockaddr_in*>(addr)->sin_addr,
                  &reinterpret_cast<sockaddr_in*>(ifa->ifa_addr)->sin_addr,
                  sizeof(in_addr)) == 0;
    } else if (addr->sa_family == AF_INET6) {
      match = std::memcmp(
                  &reinterpret_cast<const sockaddr_in6*>(addr)->sin6_addr,
                  &reinterpret_cast<sockaddr_in6*>(ifa->ifa_addr)->sin6_addr,
                  sizeof(in6_addr)) == 0;
    }
    if (match) {
      result = ifa->ifa_name;
      break;
    }
  }
  freeifaddrs(list);
  return result;
}

int interfaceSpeedMbps(const std::string& name) {
  if (name.empty()) {
    return -1;
  }
  char path[256];
  snprintf(path, sizeof(path), "/sys/class/net/%s/speed", name.c_str());
  FILE* f = fopen(path, "r");
  if (f == nullptr) {
    return -1;
  }
  int speed = -1;
  if (fscanf(f, "%d", &speed) != 1) {
    speed = -1;
  }
  fclose(f);
  return speed;
}

std::string addressForInterface(const std::string& name) {
  if (name.empty()) {
    return "";
  }
  ifaddrs* list = nullptr;
  if (getifaddrs(&list) != 0) {
    return "";
  }
  std::string v4, v6;
  for (ifaddrs* ifa = list; ifa != nullptr; ifa = ifa->ifa_next) {
    if (ifa->ifa_addr == nullptr || name != ifa->ifa_name) {
      continue;
    }
    char buf[INET6_ADDRSTRLEN] = {0};
    if (ifa->ifa_addr->sa_family == AF_INET && v4.empty()) {
      inet_ntop(AF_INET,
                &reinterpret_cast<sockaddr_in*>(ifa->ifa_addr)->sin_addr,
                buf, sizeof(buf));
      v4 = buf;
    } else if (ifa->ifa_addr->sa_family == AF_INET6 && v6.empty()) {
      auto* sa6 = reinterpret_cast<sockaddr_in6*>(ifa->ifa_addr);
      if (IN6_IS_ADDR_LINKLOCAL(&sa6->sin6_addr)) {
        // A bare link-local string loses its scope id and cannot bind;
        // better to fall through to the clear "no usable address" error.
        continue;
      }
      inet_ntop(AF_INET6, &sa6->sin6_addr, buf, sizeof(buf));
      v6 = buf;
    }
  }
  freeifaddrs(list);
  return v4.empty() ? v6 : v4;
}

namespace {

// True for a PCI bus id in BDF form: dddd:bb:dd.f (hex fields).
bool looksLikeBdf(const std::string& s) {
  if (s.size() != 12 || s[4] != ':' || s[7] != ':' || s[10] != '.') {
    return false;
  }
  for (size_t i : {0u, 1u, 2u, 3u, 5u, 6u, 8u, 9u, 11u}) {
    const char c = s[i];
    const bool hex = (c >= '0' && c <= '9') || (c >= 'a' && c <= 'f') ||
                     (c >= 'A' && c <= 'F');
    if (!hex) {
      return false;
    }
  }
  return true;
}

}  // namespace

std::string interfacePciBusId(const std::string& name) {
  if (name.empty()) {
    return "";
  }
  // /sys/class/net/<name>/device is a symlink into the device tree. For
  // a PCI NIC the trailing component is the bus id (0000:3b:00.0); for
  // buses hanging OFF PCI (virtio3, usb endpoints) the nearest PCI
  // ancestor appears earlier in the path — take the LAST component in
  // BDF form, and report nothing for purely virtual interfaces
  // (lo/veth/tun have no device link at all).
  char link[512];
  const std::string path = "/sys/class/net/" + name + "/device";
  const ssize_t n = readlink(path.c_str(), link, sizeof(link) - 1);
  if (n <= 0) {
    return "";
  }
  link[n] = '\0';
  std::string best;
  const char* p = link;
  while (*p != '\0') {
    const char* next = strchr(p, '/');
    const size_t len = next != nullptr ? size_t(next - p) : strlen(p);
    std::string part(p, len);
    if (looksLikeBdf(part)) {
      best = std::move(part);
    }
    p += len;
    while (*p == '/') {
      p++;
    }
  }
  return best;
}

int pciDistance(const std::string& a, const std::string& b) {
  if (a.empty() || b.empty()) {
    return -1;
  }
  if (a == b) {
    return 0;
  }
  // Resolve each id's full path in the PCI tree and count the trailing
  // components that differ — devices under the same root complex /
  // switch are "close" (small distance), devices on different roots are
  // far. Mirrors the reference's use for NUMA-aware device choice.
  auto fullPath = [](const std::string& id) -> std::string {
    char buf[1024];
    const std::string p = "/sys/bus/pci/devices/" + id;
    const ssize_t n = readlink(p.c_str(), buf, sizeof(buf) - 1);
    if (n <= 0) {
      return "";
    }
    buf[n] = '\0';
    return buf;
  };
  const std::string pa = fullPath(a);
  const std::string pb = fullPath(b);
  if (pa.empty() || pb.empty()) {
    return -1;
  }
  auto split = [](const std::string& s) {
    std::vector<std::string> parts;
    size_t pos = 0;
    while (pos < s.size()) {
      size_t next = s.find('/', pos);
      if (next == std::string::npos) {
        next = s.size();
      }
      if (next > pos) {
        parts.push_back(s.substr(pos, next - pos));
      }
      pos = next + 1;
    }
    return parts;
  };
  const auto va = split(pa);
  const auto vb = split(pb);
  size_t common = 0;
  while (common < va.size() && common < vb.size() &&
         va[common] == vb[common]) {
    common++;
  }
  return static_cast<int>((va.size() - common) + (vb.size() - common));
}

}  // namespace tpucoll
