#include "tpucoll/common/sysinfo.h"

#include <arpa/inet.h>
#include <ifaddrs.h>
#include <netinet/in.h>
#include <sys/socket.h>

#include <cstdio>
#include <cstring>

namespace tpucoll {

std::string interfaceForAddress(const sockaddr* addr) {
  if (addr == nullptr) {
    return "";
  }
  ifaddrs* list = nullptr;
  if (getifaddrs(&list) != 0) {
    return "";
  }
  std::string result;
  for (ifaddrs* ifa = list; ifa != nullptr; ifa = ifa->ifa_next) {
    if (ifa->ifa_addr == nullptr ||
        ifa->ifa_addr->sa_family != addr->sa_family) {
      continue;
    }
    bool match = false;
    if (addr->sa_family == AF_INET) {
      match = std::memcmp(
                  &reinterpret_cast<const sockaddr_in*>(addr)->sin_addr,
                  &reinterpret_cast<sockaddr_in*>(ifa->ifa_addr)->sin_addr,
                  sizeof(in_addr)) == 0;
    } else if (addr->sa_family == AF_INET6) {
      match = std::memcmp(
                  &reinterpret_cast<const sockaddr_in6*>(addr)->sin6_addr,
                  &reinterpret_cast<sockaddr_in6*>(ifa->ifa_addr)->sin6_addr,
                  sizeof(in6_addr)) == 0;
    }
    if (match) {
      result = ifa->ifa_name;
      break;
    }
  }
  freeifaddrs(list);
  return result;
}

int interfaceSpeedMbps(const std::string& name) {
  if (name.empty()) {
    return -1;
  }
  char path[256];
  snprintf(path, sizeof(path), "/sys/class/net/%s/speed", name.c_str());
  FILE* f = fopen(path, "r");
  if (f == nullptr) {
    return -1;
  }
  int speed = -1;
  if (fscanf(f, "%d", &speed) != 1) {
    speed = -1;
  }
  fclose(f);
  return speed;
}

std::string addressForInterface(const std::string& name) {
  if (name.empty()) {
    return "";
  }
  ifaddrs* list = nullptr;
  if (getifaddrs(&list) != 0) {
    return "";
  }
  std::string v4, v6;
  for (ifaddrs* ifa = list; ifa != nullptr; ifa = ifa->ifa_next) {
    if (ifa->ifa_addr == nullptr || name != ifa->ifa_name) {
      continue;
    }
    char buf[INET6_ADDRSTRLEN] = {0};
    if (ifa->ifa_addr->sa_family == AF_INET && v4.empty()) {
      inet_ntop(AF_INET,
                &reinterpret_cast<sockaddr_in*>(ifa->ifa_addr)->sin_addr,
                buf, sizeof(buf));
      v4 = buf;
    } else if (ifa->ifa_addr->sa_family == AF_INET6 && v6.empty()) {
      auto* sa6 = reinterpret_cast<sockaddr_in6*>(ifa->ifa_addr);
      if (IN6_IS_ADDR_LINKLOCAL(&sa6->sin6_addr)) {
        // A bare link-local string loses its scope id and cannot bind;
        // better to fall through to the clear "no usable address" error.
        continue;
      }
      inet_ntop(AF_INET6, &sa6->sin6_addr, buf, sizeof(buf));
      v6 = buf;
    }
  }
  freeifaddrs(list);
  return v4.empty() ? v6 : v4;
}

}  // namespace tpucoll
