// Host topology probes (reference analog: gloo/common/linux.h:17-32 —
// interface speed discovery used for benchmark metadata and transport
// selection hints).
#pragma once

#include <string>

struct sockaddr;

namespace tpucoll {

// Name of the network interface owning `addr` ("" if none matches —
// e.g. 0.0.0.0 or a mismatched bind).
std::string interfaceForAddress(const sockaddr* addr);

// Link speed in Mb/s from /sys/class/net/<name>/speed; -1 when unknown
// (virtual interfaces, loopback).
int interfaceSpeedMbps(const std::string& name);

// First IPv4 (preferred) or IPv6 address owned by the named interface,
// as a numeric string ("" if the interface has no address). Lets a
// device bind by interface NAME (reference: gloo tcp/attr.h iface +
// device.cc:30-141 resolution).
std::string addressForInterface(const std::string& name);

// PCI bus id of the NIC backing the named interface, from
// /sys/class/net/<name>/device ("" for virtual/loopback interfaces).
// Reference analog: transport Device::getPCIBusID + pciDistance
// (gloo/transport/device.h:42-47, common/linux.h:17-32) — NUMA-aware
// device selection metadata.
std::string interfacePciBusId(const std::string& name);

// Hop distance between two PCI bus ids: number of path components that
// differ under /sys/bus/pci/devices (0 = same device, higher = farther
// apart in the PCI tree). -1 when either id is unknown. Reference:
// gloo/common/linux.h pciDistance.
int pciDistance(const std::string& a, const std::string& b);

}  // namespace tpucoll
