// Host topology probes (reference analog: gloo/common/linux.h:17-32 —
// interface speed discovery used for benchmark metadata and transport
// selection hints).
#pragma once

#include <string>

struct sockaddr;

namespace tpucoll {

// Name of the network interface owning `addr` ("" if none matches —
// e.g. 0.0.0.0 or a mismatched bind).
std::string interfaceForAddress(const sockaddr* addr);

// Link speed in Mb/s from /sys/class/net/<name>/speed; -1 when unknown
// (virtual interfaces, loopback).
int interfaceSpeedMbps(const std::string& name);

// First IPv4 (preferred) or IPv6 address owned by the named interface,
// as a numeric string ("" if the interface has no address). Lets a
// device bind by interface NAME (reference: gloo tcp/attr.h iface +
// device.cc:30-141 resolution).
std::string addressForInterface(const std::string& name);

}  // namespace tpucoll
