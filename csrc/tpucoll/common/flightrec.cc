#include "tpucoll/common/flightrec.h"
#include "tpucoll/common/env.h"

#include <fcntl.h>
#include <signal.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <exception>
#include <string>

namespace tpucoll {

namespace {

// DataType code -> name (types.h); kNoDtype renders as null.
const char* dtypeName(uint8_t code) {
  static const char* kNames[] = {"int8",    "uint8",    "int32",  "uint32",
                                 "int64",   "uint64",   "float16", "bfloat16",
                                 "float32", "float64"};
  if (code < sizeof(kNames) / sizeof(kNames[0])) {
    return kNames[code];
  }
  return nullptr;
}

const char* stateName(int state) {
  switch (state) {
    case FlightRecorder::kEnqueued:
      return "enqueued";
    case FlightRecorder::kStarted:
      return "started";
    default:
      return "completed";
  }
}

uint64_t fnv1a(uint64_t h, const void* data, size_t n) {
  const unsigned char* p = static_cast<const unsigned char*>(data);
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 0x100000001b3ULL;
  }
  return h;
}

size_t capacityFromEnv() {
  // Strict count (common/env.h): atoll used to read "banana" as 0 and
  // silently keep the default ring size.
  const size_t cap = static_cast<size_t>(
      envCount("TPUCOLL_FLIGHTREC_EVENTS", 1024, 1, 1 << 24));
  size_t pow2 = 8;
  while (pow2 < cap) {
    pow2 <<= 1;
  }
  return pow2;
}

// ---- process-global recorder registry (fatal-signal dumping) ----------
// Lock-free fixed slots: a signal handler cannot take the registration
// mutex, so registration CASes into a slot and the handler only ever
// reads the atomics.
constexpr int kMaxRecorders = 64;
std::atomic<FlightRecorder*> g_recorders[kMaxRecorders] = {};
// Dump directory snapshot taken at handler-install time (getenv inside a
// signal handler is not guaranteed safe against concurrent setenv).
char g_signalDir[512] = {0};
std::atomic<bool> g_handlerInstalled{false};

// Recursion guard: a crash while dumping (e.g. a recorder being torn
// down on another thread at the instant the signal lands) must re-raise
// the ORIGINAL default disposition, not loop back into this handler.
std::atomic<bool> g_inHandler{false};

// Automatic dump filename: the plain per-rank name, the lane-tagged
// variant for lane recorders (async/engine.h), and/or the group-tagged
// variant for split sub-communicators — so same-rank recorders in one
// process never overwrite each other and post-mortem tooling can
// partition by group. snprintf only — shared with the signal path.
void autoDumpPath(char* path, size_t n, const char* dir, int rank,
                  int tag, const char* group) {
  const bool grouped = group != nullptr && group[0] != '\0';
  if (grouped && tag >= 0) {
    snprintf(path, n, "%s/flightrec-rank%d-g%s-lane%d.json", dir, rank,
             group, tag);
  } else if (grouped) {
    snprintf(path, n, "%s/flightrec-rank%d-g%s.json", dir, rank, group);
  } else if (tag >= 0) {
    snprintf(path, n, "%s/flightrec-rank%d-lane%d.json", dir, rank, tag);
  } else {
    snprintf(path, n, "%s/flightrec-rank%d.json", dir, rank);
  }
}

void fatalSignalHandler(int sig) {
  if (!g_inHandler.exchange(true, std::memory_order_seq_cst) &&
      g_signalDir[0] != '\0') {
    for (int i = 0; i < kMaxRecorders; i++) {
      FlightRecorder* rec = g_recorders[i].load(std::memory_order_relaxed);
      if (rec == nullptr) {
        continue;
      }
      char path[704];
      autoDumpPath(path, sizeof(path), g_signalDir, rec->rank(),
                   rec->dumpTag(), rec->groupTagFile());
      rec->dumpToFile(path, "signal", -1);
    }
  }
  // Re-raise with the default disposition so the exit status (core dump,
  // termination signal) is what the launcher expects.
  signal(sig, SIG_DFL);
  raise(sig);
}

// Writer abstraction so the entry formatter feeds either an fd (signal
// path: snprintf + write(2) only) or a growing string (tc_flightrec_json).
struct FdSink {
  int fd;
  bool ok{true};
  void append(const char* data, size_t n) {
    while (ok && n > 0) {
      const ssize_t w = ::write(fd, data, n);
      if (w < 0) {
        if (errno == EINTR) {
          continue;
        }
        ok = false;
        return;
      }
      data += w;
      n -= static_cast<size_t>(w);
    }
  }
};

struct StringSink {
  std::string out;
  bool ok{true};
  void append(const char* data, size_t n) { out.append(data, n); }
};

}  // namespace

int64_t FlightRecorder::nowUs() {
  // CLOCK_MONOTONIC directly (async-signal-safe; same epoch as
  // std::chrono::steady_clock on Linux, so these timestamps line up with
  // Tracer spans and the metrics registry's progress stamps).
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

FlightRecorder::FlightRecorder(int rank, int size)
    : rank_(rank), size_(size) {
  const size_t cap = capacityFromEnv();
  mask_ = cap - 1;
  entries_.reset(new Entry[cap]);
  for (int i = 0; i < kMaxRecorders; i++) {
    FlightRecorder* expected = nullptr;
    if (g_recorders[i].compare_exchange_strong(
            expected, this, std::memory_order_seq_cst)) {
      slotIdx_ = i;
      break;
    }
  }
}

FlightRecorder::~FlightRecorder() {
  if (slotIdx_ >= 0) {
    g_recorders[slotIdx_].store(nullptr, std::memory_order_relaxed);
  }
}

uint64_t FlightRecorder::begin(const char* opcode, const char* algorithm,
                               uint64_t slot, int peer, uint64_t bytes,
                               uint8_t dtype, int64_t cseq,
                               uint64_t fingerprint) {
  const uint64_t seq = nextSeq_.fetch_add(1, std::memory_order_relaxed);
  Entry& e = entries_[seq & mask_];
  // Claim-then-publish: park kNoSeq while the row's fields are being
  // rewritten so a concurrent dump — expecting either the old lap's seq
  // or the new one — skips the torn row, then publish the real seq as
  // the LAST store.
  e.seq.store(kNoSeq, std::memory_order_relaxed);
  e.ts[kStarted].store(0, std::memory_order_relaxed);
  e.ts[kCompleted].store(0, std::memory_order_relaxed);
  e.cseq.store(cseq, std::memory_order_relaxed);
  e.opcode.store(opcode, std::memory_order_relaxed);
  e.algorithm.store(algorithm, std::memory_order_relaxed);
  e.slot.store(slot, std::memory_order_relaxed);
  e.peer.store(peer, std::memory_order_relaxed);
  e.bytes.store(bytes, std::memory_order_relaxed);
  e.dtype.store(dtype, std::memory_order_relaxed);
  e.fingerprint.store(fingerprint, std::memory_order_relaxed);
  e.ts[kEnqueued].store(nowUs(), std::memory_order_relaxed);
  e.seq.store(seq, std::memory_order_relaxed);
  return seq;
}

uint64_t FlightRecorder::beginCollective(const char* opcode,
                                         const char* algorithm,
                                         uint64_t slot, int peer,
                                         uint64_t bytes, uint8_t dtype,
                                         uint64_t fpBytes) {
  // Desync fingerprint: what every rank must agree on at this collective
  // seq — opcode, dtype, rank-invariant payload size, root, and the slot
  // (prefix + tag: mismatched tags hang exactly like mismatched ops and
  // must read as a desync, not a stall). Only the resolved algorithm is
  // excluded: tuning tables may legitimately differ in how they get the
  // same answer, but not in what the answer is about.
  uint64_t fp = 0xcbf29ce484222325ULL;
  fp = fnv1a(fp, opcode, strlen(opcode));
  fp = fnv1a(fp, &dtype, sizeof(dtype));
  fp = fnv1a(fp, &fpBytes, sizeof(fpBytes));
  fp = fnv1a(fp, &slot, sizeof(slot));
  const int32_t p = peer;
  fp = fnv1a(fp, &p, sizeof(p));
  const int64_t cseq = nextCollSeq_.fetch_add(1, std::memory_order_relaxed);
  return begin(opcode, algorithm, slot, peer, bytes, dtype, cseq, fp);
}

uint64_t FlightRecorder::beginP2p(const char* opcode, uint64_t slot,
                                  int peer, uint64_t bytes) {
  // No collective seq, no fingerprint: p2p traffic is legitimately
  // rank-asymmetric and never participates in the desync comparison.
  return begin(opcode, nullptr, slot, peer, bytes, kNoDtype, -1, 0);
}

uint64_t FlightRecorder::noteEvent(const char* opcode, int peer,
                                   uint64_t detail) {
  // Like p2p: no collective seq, no fingerprint (events are one-sided
  // by nature and must never read as a desync).
  const uint64_t seq = begin(opcode, nullptr, 0, peer, detail, kNoDtype,
                             -1, 0);
  transition(seq, kStarted);
  transition(seq, kCompleted);
  return seq;
}

namespace {

template <typename Sink>
void dumpImpl(Sink& sink, int rank, int size, uint64_t mask,
              const FlightRecorder::Entry* entries, uint64_t nextSeq,
              const char* reason, int blamedPeer, const char* group) {
  char buf[720];
  const uint64_t cap = mask + 1;
  const uint64_t first = nextSeq > cap ? nextSeq - cap : 0;
  // `group` needs no JSON escaping: Context group tags are built from
  // integers and [sc./] separators only.
  int n = snprintf(buf, sizeof(buf),
                   "{\"version\":1,\"kind\":\"tpucoll_flightrec\","
                   "\"rank\":%d,\"size\":%d,\"group\":\"%s\","
                   "\"reason\":\"%s\","
                   "\"blamed_peer\":%d,\"now_us\":%lld,\"next_seq\":%llu,"
                   "\"capacity\":%llu,\"dropped\":%llu,\"events\":[",
                   rank, size, group != nullptr ? group : "", reason,
                   blamedPeer,
                   static_cast<long long>(FlightRecorder::nowUs()),
                   static_cast<unsigned long long>(nextSeq),
                   static_cast<unsigned long long>(cap),
                   static_cast<unsigned long long>(first));
  sink.append(buf, static_cast<size_t>(n));
  bool firstRow = true;
  for (uint64_t seq = first; seq < nextSeq; seq++) {
    const FlightRecorder::Entry& e = entries[seq & mask];
    if (e.seq.load(std::memory_order_relaxed) != seq) {
      continue;  // mid-overwrite by a racing writer: drop the torn row
    }
    const char* op = e.opcode.load(std::memory_order_relaxed);
    if (op == nullptr) {
      continue;
    }
    const char* algo = e.algorithm.load(std::memory_order_relaxed);
    const char* dt = dtypeName(e.dtype.load(std::memory_order_relaxed));
    const int64_t tsq = e.ts[0].load(std::memory_order_relaxed);
    const int64_t tst = e.ts[1].load(std::memory_order_relaxed);
    const int64_t tsc = e.ts[2].load(std::memory_order_relaxed);
    const int64_t cseq = e.cseq.load(std::memory_order_relaxed);
    const int state = tsc != 0   ? FlightRecorder::kCompleted
                      : tst != 0 ? FlightRecorder::kStarted
                                 : FlightRecorder::kEnqueued;
    char cseqBuf[24];
    if (cseq >= 0) {
      snprintf(cseqBuf, sizeof(cseqBuf), "%lld",
               static_cast<long long>(cseq));
    } else {
      snprintf(cseqBuf, sizeof(cseqBuf), "null");
    }
    n = snprintf(
        buf, sizeof(buf),
        "%s\n{\"seq\":%llu,\"cseq\":%s,\"op\":\"%s\",\"algo\":%s%s%s,"
        "\"slot\":%llu,"
        "\"peer\":%d,\"bytes\":%llu,\"dtype\":%s%s%s,"
        "\"fp\":\"%016llx\",\"state\":\"%s\",\"ts_enqueued_us\":%lld,"
        "\"ts_started_us\":%lld,\"ts_completed_us\":%lld}",
        firstRow ? "" : ",", static_cast<unsigned long long>(seq), cseqBuf,
        op,
        algo != nullptr ? "\"" : "", algo != nullptr ? algo : "null",
        algo != nullptr ? "\"" : "",
        static_cast<unsigned long long>(
            e.slot.load(std::memory_order_relaxed)),
        e.peer.load(std::memory_order_relaxed),
        static_cast<unsigned long long>(
            e.bytes.load(std::memory_order_relaxed)),
        dt != nullptr ? "\"" : "", dt != nullptr ? dt : "null",
        dt != nullptr ? "\"" : "",
        static_cast<unsigned long long>(
            e.fingerprint.load(std::memory_order_relaxed)),
        stateName(state), static_cast<long long>(tsq),
        static_cast<long long>(tst), static_cast<long long>(tsc));
    sink.append(buf, static_cast<size_t>(n));
    firstRow = false;
  }
  sink.append("\n]}\n", 4);
}

}  // namespace

void FlightRecorder::setGroupTag(const char* tag) {
  if (tag == nullptr) {
    tag = "";
  }
  snprintf(groupTag_, sizeof(groupTag_), "%s", tag);
  snprintf(groupTagFile_, sizeof(groupTagFile_), "%s", tag);
  for (char* p = groupTagFile_; *p != '\0'; p++) {
    if (*p == '/') {
      *p = '.';  // nested-split separator is not filename-safe
    }
  }
}

std::string FlightRecorder::toJson(const char* reason,
                                   int blamedPeer) const {
  StringSink sink;
  dumpImpl(sink, rank_, size_, mask_, entries_.get(),
           nextSeq_.load(std::memory_order_relaxed), reason, blamedPeer,
           groupTag_);
  return std::move(sink.out);
}

bool FlightRecorder::dumpToFd(int fd, const char* reason,
                              int blamedPeer) const {
  FdSink sink{fd};
  dumpImpl(sink, rank_, size_, mask_, entries_.get(),
           nextSeq_.load(std::memory_order_relaxed), reason, blamedPeer,
           groupTag_);
  return sink.ok;
}

bool FlightRecorder::dumpToFile(const char* path, const char* reason,
                                int blamedPeer) const {
  const int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    return false;
  }
  const bool ok = dumpToFd(fd, reason, blamedPeer);
  ::close(fd);
  return ok;
}

bool FlightRecorder::autoDump(const char* reason, int blamedPeer) {
  const char* dir = envString("TPUCOLL_FLIGHTREC_DIR");
  if (dir == nullptr) {
    return false;
  }
  // One-shot: the FIRST trigger is the evidence closest to the cause
  // (the same principle as Metrics::recordPeerFailure keeping the first
  // failure) — later triggers are usually the teardown cascade, and a
  // re-firing watchdog must not turn into a dump storm. Explicit dumps
  // (tc_flightrec_dump) are not limited.
  int64_t expected = 0;
  if (!lastAutoDumpUs_.compare_exchange_strong(expected, nowUs(),
                                               std::memory_order_relaxed)) {
    return false;
  }
  lastReason_.store(reason, std::memory_order_relaxed);
  ::mkdir(dir, 0777);  // best-effort; EEXIST is the common case
  char path[704];
  autoDumpPath(path, sizeof(path), dir, rank_,
               dumpTag_.load(std::memory_order_relaxed), groupTagFile_);
  return dumpToFile(path, reason, blamedPeer);
}

void FlightRecorder::installSignalHandler() {
  bool expected = false;
  if (!g_handlerInstalled.compare_exchange_strong(
          expected, true, std::memory_order_seq_cst)) {
    return;
  }
  const char* dir = envString("TPUCOLL_FLIGHTREC_DIR");
  if (dir != nullptr) {
    snprintf(g_signalDir, sizeof(g_signalDir), "%s", dir);
    ::mkdir(g_signalDir, 0777);
  }
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = fatalSignalHandler;
  sigemptyset(&sa.sa_mask);
  for (int sig : {SIGSEGV, SIGABRT, SIGBUS, SIGFPE, SIGILL, SIGTERM}) {
    sigaction(sig, &sa, nullptr);
  }
}

void FlightRecorder::maybeInstallFromEnv() {
  // Strict flag (common/env.h): only 0/1 parse.
  if (envFlag("TPUCOLL_FLIGHTREC_SIGNALS", false)) {
    installSignalHandler();
  }
}

FlightRecOp::FlightRecOp(FlightRecorder* rec, const char* opcode,
                         const char* algorithm, uint64_t slot, int peer,
                         uint64_t bytes, uint8_t dtype, uint64_t fpBytes)
    : rec_(rec) {
  if (rec_ == nullptr) {
    return;
  }
  seq_ = rec_->beginCollective(opcode, algorithm, slot, peer, bytes, dtype,
                               fpBytes == ~uint64_t(0) ? bytes : fpBytes);
  exceptionsAtEntry_ = std::uncaught_exceptions();
}

FlightRecOp::~FlightRecOp() {
  if (rec_ == nullptr) {
    return;
  }
  // Unwinding through an exception leaves the op at enqueued/started:
  // the post-mortem must show it in flight, not done.
  if (std::uncaught_exceptions() > exceptionsAtEntry_) {
    return;
  }
  rec_->transition(seq_, FlightRecorder::kCompleted);
}

}  // namespace tpucoll
