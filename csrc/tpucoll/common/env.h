// Strict environment-knob parsing, shared by every layer that reads a
// TPUCOLL_* variable. Hoisted from collectives/detail.h so the
// transport knobs (shm ring/threshold, stash watermark, channel striping,
// loop-thread pool) get the same contract the schedule crossovers already
// have: accept plain digit strings only, throw EnforceError on anything
// else. atoll-style parsing swallows garbage ("8MB" -> 8, "-1" -> huge
// size_t) — exactly the misconfigurations a tuning knob must catch loudly.
//
// This header is the ONLY sanctioned caller of getenv in the core;
// tools/check enforces that (rule env-hygiene, docs/check.md), and the
// full knob matrix lives in docs/env.md.
#pragma once

#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <initializer_list>
#include <sstream>

#include "tpucoll/common/logging.h"

namespace tpucoll {

// Byte-count knob: non-negative integer, default when unset/empty.
inline size_t envBytes(const char* name, size_t dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') {
    return dflt;
  }
  char* end = nullptr;
  errno = 0;
  const unsigned long long parsed = std::strtoull(v, &end, 10);
  if (end == v || *end != '\0' ||
      !(v[0] >= '0' && v[0] <= '9') || errno == ERANGE) {
    TC_THROW(EnforceError, name, " must be a byte count, got: ", v);
  }
  return static_cast<size_t>(parsed);
}

// Small-count knob (thread/channel counts): strict parse PLUS a range
// check, so TPUCOLL_CHANNELS=0 or =100000 fails at configuration time
// instead of surfacing as a hung mesh or an OOM of loop threads.
inline long envCount(const char* name, long dflt, long lo, long hi) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') {
    return dflt;
  }
  char* end = nullptr;
  errno = 0;
  const long long parsed = std::strtoll(v, &end, 10);
  if (end == v || *end != '\0' ||
      !(v[0] >= '0' && v[0] <= '9') || errno == ERANGE) {
    TC_THROW(EnforceError, name, " must be an integer, got: ", v);
  }
  TC_ENFORCE(parsed >= lo && parsed <= hi, name, " must be in [", lo, ", ",
             hi, "], got: ", v);
  return static_cast<long>(parsed);
}

// String knob (paths, directory names): nullptr when unset or empty.
// No validation here — a path's validity is the call site's contract —
// but routing the read through this header keeps the env surface in one
// place (and under the env-hygiene check).
inline const char* envString(const char* name) {
  const char* v = std::getenv(name);
  return (v != nullptr && *v != '\0') ? v : nullptr;
}

// Boolean knob: unset/empty -> default, "0" -> false, "1" -> true,
// anything else throws. The historical lenient readings ("any set value
// means on", "anything but 0 means on") let TPUCOLL_SHM=false silently
// mean *enabled*; a flag knob must be unambiguous.
inline bool envFlag(const char* name, bool dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') {
    return dflt;
  }
  if (std::strcmp(v, "0") == 0) {
    return false;
  }
  if (std::strcmp(v, "1") == 0) {
    return true;
  }
  TC_THROW(EnforceError, name, " must be 0 or 1, got: ", v);
}

// Enumerated knob: the value must be one of `allowed` (unset/empty ->
// `dflt`, which need not be listed — e.g. an internal "auto"). Keeps
// every mode switch (engine selection, schedule overrides) from
// silently running the wrong arm on a typo.
inline const char* envChoice(const char* name, const char* dflt,
                             std::initializer_list<const char*> allowed) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') {
    return dflt;
  }
  for (const char* a : allowed) {
    if (std::strcmp(v, a) == 0) {
      return v;
    }
  }
  std::ostringstream want;
  bool first = true;
  for (const char* a : allowed) {
    want << (first ? "" : "|") << a;
    first = false;
  }
  TC_THROW(EnforceError, name, " must be ", want.str(), ", got: ", v);
}

}  // namespace tpucoll
