// Strict environment-knob parsing, shared by every layer that reads a
// numeric TPUCOLL_* variable. Hoisted from collectives/detail.h so the
// transport knobs (shm ring/threshold, stash watermark, channel striping,
// loop-thread pool) get the same contract the schedule crossovers already
// have: accept plain digit strings only, throw EnforceError on anything
// else. atoll-style parsing swallows garbage ("8MB" -> 8, "-1" -> huge
// size_t) — exactly the misconfigurations a tuning knob must catch loudly.
#pragma once

#include <cerrno>
#include <cstdlib>

#include "tpucoll/common/logging.h"

namespace tpucoll {

// Byte-count knob: non-negative integer, default when unset/empty.
inline size_t envBytes(const char* name, size_t dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') {
    return dflt;
  }
  char* end = nullptr;
  errno = 0;
  const unsigned long long parsed = std::strtoull(v, &end, 10);
  if (end == v || *end != '\0' ||
      !(v[0] >= '0' && v[0] <= '9') || errno == ERANGE) {
    TC_THROW(EnforceError, name, " must be a byte count, got: ", v);
  }
  return static_cast<size_t>(parsed);
}

// Small-count knob (thread/channel counts): strict parse PLUS a range
// check, so TPUCOLL_CHANNELS=0 or =100000 fails at configuration time
// instead of surfacing as a hung mesh or an OOM of loop threads.
inline long envCount(const char* name, long dflt, long lo, long hi) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') {
    return dflt;
  }
  char* end = nullptr;
  errno = 0;
  const long long parsed = std::strtoll(v, &end, 10);
  if (end == v || *end != '\0' ||
      !(v[0] >= '0' && v[0] <= '9') || errno == ERANGE) {
    TC_THROW(EnforceError, name, " must be an integer, got: ", v);
  }
  TC_ENFORCE(parsed >= lo && parsed <= hi, name, " must be in [", lo, ", ",
             hi, "], got: ", v);
  return static_cast<long>(parsed);
}

}  // namespace tpucoll
