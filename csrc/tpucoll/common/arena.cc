#include "tpucoll/common/arena.h"

#include <cstdlib>
#include <new>
#include <utility>

namespace tpucoll {

namespace {
// Cache-line alignment: arena blocks back wire staging that the AVX
// reduce kernels and the q8/bf16 codecs stream through.
constexpr size_t kArenaAlign = 64;
}  // namespace

Arena::~Arena() {
  std::free(buf_);
}

Arena::Arena(Arena&& o) noexcept
    : buf_(std::exchange(o.buf_, nullptr)),
      cap_(std::exchange(o.cap_, 0)),
      grew_(std::exchange(o.grew_, false)) {}

char* Arena::require(size_t minBytes) {
  if (minBytes <= cap_ && buf_ != nullptr) {
    grew_ = false;
    return buf_;
  }
  // Round up to the alignment so aligned_alloc's size contract holds.
  const size_t want =
      (minBytes + kArenaAlign - 1) / kArenaAlign * kArenaAlign;
  char* fresh = static_cast<char*>(
      std::aligned_alloc(kArenaAlign, want == 0 ? kArenaAlign : want));
  if (fresh == nullptr) {
    throw std::bad_alloc();
  }
  // Grow-only: no copy of prior contents — plan stages are scratch whose
  // lifetime is one collective call; a grown arena starts a fresh call.
  std::free(buf_);
  buf_ = fresh;
  cap_ = want == 0 ? kArenaAlign : want;
  grew_ = true;
  return buf_;
}

}  // namespace tpucoll
