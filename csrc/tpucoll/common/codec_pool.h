// Codec worker pool: the shared thread lanes the wire codecs (math.h
// q8/q4/bf16 streams) run on when a hop is large enough to shard.
//
// PROF_r15.json moved the q8 bottleneck from the wire into the encoder:
// at 64 MiB pack+unpack is ~62 ms of a ~100 ms op while wire_wait sits
// at 16 ms. The pool takes the serial codec off the caller's critical
// path two ways:
//
//   - parallelFor(): shard a stream across the caller + workers at
//     deterministic whole-unit boundaries (collectives/wire_codec.h
//     computes them), so the concatenated output is byte-identical to
//     the serial walk for ANY pool width — wire consensus never depends
//     on TPUCOLL_CODEC_THREADS.
//   - submit()/wait(): run one sub-block's encode+send (or decode)
//     asynchronously while the caller blocks in waitRecv, which is what
//     lets the pipelined ring (TPUCOLL_CODEC_PIPELINE) overlap codec
//     time with wire time and keep the op thread's pack bucket down to
//     the residual join.
//
// Sizing: TPUCOLL_CODEC_THREADS (strict, [1, 64]); unset defaults to
// the transport loop width (TPUCOLL_LOOP_THREADS, itself default 1), so
// a host provisioned with N loop threads gets N codec lanes without a
// second knob. Width 1 means no worker threads at all: submit() runs
// inline and parallelFor() degrades to the serial loop — byte-identical
// by construction, zero new threads (the default).
//
// Fork-safety: workers are spawned lazily on first use and pinned to
// the spawning pid; a forked child sees a foreign pid and runs inline
// instead of touching inherited (dead) threads.
#pragma once

#include <condition_variable>
#include <cstddef>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include <sys/types.h>

namespace tpucoll {
namespace codec {

// Resolved pool width (TPUCOLL_CODEC_THREADS, default = loop threads);
// >= 1, read once per process.
int codecThreads();

// Resolved pipeline depth for the wire rings (TPUCOLL_CODEC_PIPELINE,
// strict [1, 32], default 4): sub-blocks per ring hop. 1 restores the
// serial hop (one message per hop, the pre-pipeline wire protocol).
// Like TPUCOLL_Q8_BLOCK, the depth must match on every rank: it changes
// the per-hop message count and slot layout.
int codecPipelineDepth();

class CodecPool {
 public:
  static CodecPool& instance();

  int width() const { return width_; }
  int workers() const { return width_ - 1; }

  // Async job handle; 0 means "ran inline, nothing to wait for".
  using Ticket = uint64_t;

  // Enqueue fn on a worker; runs inline (and returns 0) when the pool
  // has no workers or the caller is a forked child. Jobs must not
  // throw — codec kernels are pure math over caller-owned memory.
  Ticket submit(std::function<void()> fn);

  // Block until the job behind `t` finished (no-op for t == 0).
  void wait(Ticket t);

  // fn(shard) for shard in [0, nShards), caller lane included; returns
  // when all shards finished. Shard->lane assignment is dynamic, so fn
  // must write only shard-owned ranges (the codec shards do).
  void parallelFor(size_t nShards, const std::function<void(size_t)>& fn);

  ~CodecPool();

 private:
  CodecPool();

  struct Job {
    std::function<void()> fn;
    Ticket id{0};
    bool done{false};
  };

  void ensureWorkers();
  void workerMain();

  const int width_;
  std::mutex mu_;
  std::condition_variable cv_;       // workers: queue not empty / stop
  std::condition_variable doneCv_;   // waiters: a job finished
  std::deque<std::shared_ptr<Job>> queue_;
  std::unordered_map<Ticket, std::shared_ptr<Job>> live_;
  Ticket nextId_{1};
  bool stop_{false};
  bool spawned_{false};
  pid_t ownerPid_{0};
  std::vector<std::thread> threads_;
};

}  // namespace codec
}  // namespace tpucoll
