// In-tree ChaCha20-Poly1305 AEAD (RFC 8439) and HKDF-SHA256 (RFC 5869)
// for the host transport's wire encryption. No OpenSSL dependency: the
// container ships no TLS headers, and the reference capability being
// covered — confidentiality + integrity of the data plane, keyed from
// the join handshake (gloo/transport/tcp/tls/pair.cc:22-53) — needs one
// AEAD, not a TLS stack. Verified against the RFC test vectors in
// csrc/tests/unit_main.cc.
#pragma once

#include <cstddef>
#include <cstdint>

namespace tpucoll {

constexpr size_t kAeadKeyBytes = 32;
constexpr size_t kAeadTagBytes = 16;
constexpr size_t kAeadNonceBytes = 12;

struct AeadKey {
  uint8_t bytes[kAeadKeyBytes];
};

// Encrypt n bytes of `in` into `out` (in == out allowed) and write the
// 16-byte authentication tag. The 12-byte nonce is formed from the
// 64-bit sequence number (4 zero bytes || seq little-endian); a key must
// never seal two messages with the same seq. `aad`/`aadLen` bind
// additional plaintext context into the tag (may be empty).
void aeadSeal(const AeadKey& key, uint64_t seq, const uint8_t* aad,
              size_t aadLen, const uint8_t* in, size_t n, uint8_t* out,
              uint8_t tag[kAeadTagBytes]);

// Open counterpart. Returns false on tag mismatch, in which case `out`
// is UNSPECIFIED — the fused bulk path decrypts while it MACs, so a
// forged message may leave (never-surfaced) decrypted bytes behind;
// callers must not release `out` to anyone until this returns true.
// in == out allowed.
bool aeadOpen(const AeadKey& key, uint64_t seq, const uint8_t* aad,
              size_t aadLen, const uint8_t* in, size_t n, uint8_t* out,
              const uint8_t tag[kAeadTagBytes]);

// Which AEAD bulk tier this process will use: 2 = fused AVX-512,
// 1 = AVX2 8-block, 0 = scalar. For tests/diagnostics (the tiers are
// wire-compatible; TPUCOLL_NO_AVX512=1 forces the fallback).
int aeadIsaTier();

// HKDF-SHA256 extract+expand. outLen <= 255 * 32.
void hkdfSha256(const void* ikm, size_t ikmLen, const void* salt,
                size_t saltLen, const void* info, size_t infoLen,
                uint8_t* out, size_t outLen);

// Exposed for unit tests (RFC 8439 section vectors).
namespace crypto_detail {
void chacha20Block(const uint8_t key[32], uint32_t counter,
                   const uint8_t nonce[12], uint8_t out[64]);
void poly1305(const uint8_t key[32], const uint8_t* msg, size_t n,
              uint8_t tag[16]);
// The AEAD with a caller-supplied 96-bit nonce (the transport always
// derives nonces from sequence numbers; the RFC vectors do not).
void aeadSealWithNonce(const AeadKey& key, const uint8_t nonce[12],
                       const uint8_t* aad, size_t aadLen, const uint8_t* in,
                       size_t n, uint8_t* out, uint8_t tag[kAeadTagBytes]);
}  // namespace crypto_detail

}  // namespace tpucoll
