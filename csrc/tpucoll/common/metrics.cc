#include "tpucoll/common/metrics.h"

#include <sstream>

#include "tpucoll/common/json.h"
#include "tpucoll/common/env.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/common/tracer.h"

namespace tpucoll {

const char* metricOpName(MetricOp op) {
  switch (op) {
    case MetricOp::kAllreduce:
      return "allreduce";
    case MetricOp::kBroadcast:
      return "broadcast";
    case MetricOp::kBarrier:
      return "barrier";
    case MetricOp::kReduce:
      return "reduce";
    case MetricOp::kGather:
      return "gather";
    case MetricOp::kGatherv:
      return "gatherv";
    case MetricOp::kScatter:
      return "scatter";
    case MetricOp::kAllgather:
      return "allgather";
    case MetricOp::kAllgatherv:
      return "allgatherv";
    case MetricOp::kAlltoall:
      return "alltoall";
    case MetricOp::kAlltoallv:
      return "alltoallv";
    case MetricOp::kReduceScatter:
      return "reduce_scatter";
    case MetricOp::kSend:
      return "send";
    case MetricOp::kRecv:
      return "recv";
    case MetricOp::kConnect:
      return "connect";
    case MetricOp::kCount:
      break;
  }
  return "unknown";
}

void Metrics::Histogram::record(int64_t us) {
  int idx = 0;
  if (us > 0) {
    idx = 63 - __builtin_clzll(static_cast<uint64_t>(us));
    if (idx >= kLatencyBuckets) {
      idx = kLatencyBuckets - 1;
    }
  }
  buckets[idx].fetch_add(1, std::memory_order_relaxed);
  count.fetch_add(1, std::memory_order_relaxed);
  sumUs.fetch_add(us > 0 ? static_cast<uint64_t>(us) : 0,
                  std::memory_order_relaxed);
  // Racy max is fine: metrics tolerate losing one concurrent update.
  uint64_t prev = maxUs.load(std::memory_order_relaxed);
  while (us > 0 && static_cast<uint64_t>(us) > prev &&
         !maxUs.compare_exchange_weak(prev, static_cast<uint64_t>(us),
                                      std::memory_order_relaxed)) {
  }
}

void Metrics::Histogram::reset() {
  for (auto& b : buckets) {
    b.store(0, std::memory_order_relaxed);
  }
  count.store(0, std::memory_order_relaxed);
  sumUs.store(0, std::memory_order_relaxed);
  maxUs.store(0, std::memory_order_relaxed);
}

Metrics::Metrics(int size) : size_(size), peers_(size) {
  // Strict count (common/env.h): atoll read "never" as 0 (watchdog
  // off) — a typo must not silently disarm the straggler detector.
  const long ms = envCount("TPUCOLL_WATCHDOG_MS", 0, 0, 1L << 40);
  if (ms > 0) {
    watchdogUs_.store(ms * 1000, std::memory_order_relaxed);
  }
}

void Metrics::recordStall(const Stall& stall) {
  // Deliberately NOT gated on enabled_: the watchdog is armed by its own
  // threshold, and a stall report must survive a counters-off config.
  stalls_.fetch_add(1, std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> guard(stallMu_);
    haveStall_ = true;
    lastStall_ = stall;
  }
  TC_WARN("watchdog: ", stall.isSend ? "send" : "recv", " blocked for ",
          stall.waitedUs / 1000, "ms on peer ", stall.peer, " slot ",
          stall.slot, " (peer last progress ",
          stall.peerLastProgressUs == 0
              ? -1
              : (stall.atUs - stall.peerLastProgressUs) / 1000,
          "ms ago)");
}

void Metrics::recordPeerFailure(int peer, const std::string& message) {
  peerFailures_.fetch_add(1, std::memory_order_relaxed);
  std::lock_guard<std::mutex> guard(stallMu_);
  if (failedPeer_ < 0) {
    failedPeer_ = peer;
    failureMessage_ = message;
  }
}

void Metrics::recordFault(const std::string& action) {
  faultsTotal_.fetch_add(1, std::memory_order_relaxed);
  std::lock_guard<std::mutex> guard(faultMu_);
  faultCounts_[action]++;
}

void Metrics::recordAnomaly(const std::string& kind, int rank) {
  anomaliesTotal_.fetch_add(1, std::memory_order_relaxed);
  std::lock_guard<std::mutex> guard(anomalyMu_);
  anomalyCounts_[kind][rank]++;
}

Metrics::Histogram* Metrics::phaseHistogram(const std::string& op,
                                            const std::string& algo,
                                            const std::string& phase) {
  std::lock_guard<std::mutex> guard(phaseMu_);
  auto& slot = phaseHists_[op][algo][phase];
  if (slot == nullptr) {
    slot.reset(new Histogram());
  }
  return slot.get();
}

bool Metrics::lastStall(Stall* out) const {
  std::lock_guard<std::mutex> guard(stallMu_);
  if (!haveStall_) {
    return false;
  }
  *out = lastStall_;
  return true;
}

namespace {

void histToJson(std::ostringstream& out, const Metrics::Histogram& h) {
  out << "{\"count\":" << h.count.load(std::memory_order_relaxed)
      << ",\"sum_us\":" << h.sumUs.load(std::memory_order_relaxed)
      << ",\"max_us\":" << h.maxUs.load(std::memory_order_relaxed)
      << ",\"buckets\":[";
  bool first = true;
  for (int i = 0; i < kLatencyBuckets; i++) {
    const uint64_t n = h.buckets[i].load(std::memory_order_relaxed);
    if (n == 0) {
      continue;
    }
    if (!first) {
      out << ",";
    }
    first = false;
    // Upper bound of bucket i is 2^(i+1) us (exclusive).
    out << "[" << (uint64_t(1) << (i + 1)) << "," << n << "]";
  }
  out << "]}";
}

}  // namespace

std::string Metrics::toJson(int rank, bool drain) {
  const int64_t nowUs = Tracer::nowUs();
  std::ostringstream out;
  out << "{\"rank\":" << rank << ",\"size\":" << size_ << ",\"group\":";
  appendJsonString(out, group());
  out << ",\"enabled\":" << (enabled() ? "true" : "false")
      << ",\"watchdog_ms\":" << watchdogUs() / 1000 << ",\"now_us\":" << nowUs
      << ",\"retries\":" << retries_.load(std::memory_order_relaxed)
      << ",\"stash_pauses\":"
      << stashPauses_.load(std::memory_order_relaxed)
      << ",\"trace_events_dropped\":"
      << traceEventsDropped_.load(std::memory_order_relaxed)
      << ",\"plan_hits\":" << planHits_.load(std::memory_order_relaxed)
      << ",\"plan_misses\":"
      << planMisses_.load(std::memory_order_relaxed)
      << ",\"plan_evictions\":"
      << planEvictions_.load(std::memory_order_relaxed)
      << ",\"ubuf_creates\":"
      << ubufCreates_.load(std::memory_order_relaxed);

  // Bootstrap plane: how the context came up (docs/bootstrap.md). The
  // pair fields are live broker gauges — the owning context refreshes
  // them right before calling toJson — so, like the configuration
  // fields above, they are never drained.
  out << ",\"boot\":{\"lazy\":"
      << (bootLazy_.load(std::memory_order_relaxed) ? "true" : "false")
      << ",\"publish_us\":" << bootPublishUs_.load(std::memory_order_relaxed)
      << ",\"topo_us\":" << bootTopoUs_.load(std::memory_order_relaxed)
      << ",\"exchange_us\":"
      << bootExchangeUs_.load(std::memory_order_relaxed)
      << ",\"store_ops\":" << bootStoreOps_.load(std::memory_order_relaxed)
      << ",\"store_bytes\":"
      << bootStoreBytes_.load(std::memory_order_relaxed)
      << ",\"pairs_connected\":"
      << bootPairsConnected_.load(std::memory_order_relaxed)
      << ",\"pairs_inbound\":"
      << bootPairsInbound_.load(std::memory_order_relaxed)
      << ",\"pairs_evicted\":"
      << bootPairsEvicted_.load(std::memory_order_relaxed)
      << ",\"lazy_dials\":" << bootLazyDials_.load(std::memory_order_relaxed)
      << "}";

  out << ",\"faults\":{\"total\":"
      << faultsTotal_.load(std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> guard(faultMu_);
    for (const auto& fc : faultCounts_) {
      out << ",";
      appendJsonString(out, fc.first);
      out << ":" << fc.second;
    }
  }
  out << "}";

  // Fleet anomaly detector firings: {"total": N, "kinds": {kind:
  // {rank: count}}}. Same shape discipline as "faults" — an empty map
  // emits {} so readers need no presence check.
  out << ",\"anomalies\":{\"total\":"
      << anomaliesTotal_.load(std::memory_order_relaxed) << ",\"kinds\":{";
  {
    std::lock_guard<std::mutex> guard(anomalyMu_);
    bool firstKind = true;
    for (const auto& kindEntry : anomalyCounts_) {
      if (!firstKind) {
        out << ",";
      }
      firstKind = false;
      appendJsonString(out, kindEntry.first);
      out << ":{";
      bool firstRank = true;
      for (const auto& rankEntry : kindEntry.second) {
        out << (firstRank ? "" : ",") << "\"" << rankEntry.first
            << "\":" << rankEntry.second;
        firstRank = false;
      }
      out << "}";
    }
  }
  out << "}}";

  out << ",\"transport_failure\":";
  {
    std::lock_guard<std::mutex> guard(stallMu_);
    if (failedPeer_ >= 0) {
      out << "{\"peer\":" << failedPeer_ << ",\"count\":"
          << peerFailures_.load(std::memory_order_relaxed)
          << ",\"message\":";
      appendJsonString(out, failureMessage_);
      out << "}";
    } else {
      out << "null";
    }
  }

  out << ",\"ops\":{";
  bool first = true;
  for (int i = 0; i < static_cast<int>(MetricOp::kCount); i++) {
    const OpStats& s = ops_[i];
    if (s.calls.load(std::memory_order_relaxed) == 0 &&
        s.errors.load(std::memory_order_relaxed) == 0) {
      continue;
    }
    if (!first) {
      out << ",";
    }
    first = false;
    out << "\"" << metricOpName(static_cast<MetricOp>(i))
        << "\":{\"calls\":" << s.calls.load(std::memory_order_relaxed)
        << ",\"bytes\":" << s.bytes.load(std::memory_order_relaxed)
        << ",\"errors\":" << s.errors.load(std::memory_order_relaxed)
        << ",\"latency_us\":";
    histToJson(out, s.latency);
    out << "}";
  }
  out << "}";

  // Phase-profiler aggregates (common/profile.h): per-(collective,
  // algorithm, phase) latency histograms. Only populated families emit;
  // an empty map emits {} so readers need no presence check.
  out << ",\"phases\":{";
  {
    std::lock_guard<std::mutex> guard(phaseMu_);
    bool firstOp = true;
    for (const auto& opEntry : phaseHists_) {
      if (!firstOp) {
        out << ",";
      }
      firstOp = false;
      appendJsonString(out, opEntry.first);
      out << ":{";
      bool firstAlgo = true;
      for (const auto& algoEntry : opEntry.second) {
        if (!firstAlgo) {
          out << ",";
        }
        firstAlgo = false;
        appendJsonString(out, algoEntry.first);
        out << ":{";
        bool firstPhase = true;
        for (const auto& phaseEntry : algoEntry.second) {
          if (!firstPhase) {
            out << ",";
          }
          firstPhase = false;
          appendJsonString(out, phaseEntry.first);
          out << ":";
          histToJson(out, *phaseEntry.second);
        }
        out << "}";
      }
      out << "}";
    }
  }
  out << "}";

  out << ",\"transport\":{";
  first = true;
  for (int p = 0; p < size_; p++) {
    const PeerStats& ps = peers_[p];
    const int64_t progress = ps.lastProgressUs.load(std::memory_order_relaxed);
    if (ps.sentMsgs.load(std::memory_order_relaxed) == 0 &&
        ps.recvMsgs.load(std::memory_order_relaxed) == 0 && progress == 0) {
      continue;
    }
    if (!first) {
      out << ",";
    }
    first = false;
    out << "\"" << p
        << "\":{\"sent_msgs\":" << ps.sentMsgs.load(std::memory_order_relaxed)
        << ",\"sent_bytes\":" << ps.sentBytes.load(std::memory_order_relaxed)
        << ",\"recv_msgs\":" << ps.recvMsgs.load(std::memory_order_relaxed)
        << ",\"recv_bytes\":" << ps.recvBytes.load(std::memory_order_relaxed)
        << ",\"last_progress_us\":" << progress
        << ",\"last_progress_age_us\":"
        << (progress == 0 ? -1 : nowUs - progress)
        << ",\"rx_pauses\":" << ps.rxPauses.load(std::memory_order_relaxed)
        << ",\"tx_posts\":" << ps.txPosts.load(std::memory_order_relaxed)
        << ",\"bw_ewma_bps\":" << ps.bwEwmaBps.load(std::memory_order_relaxed)
        << ",\"rtt_ewma_us\":" << ps.rttEwmaUs.load(std::memory_order_relaxed)
        << ",\"recv_wait_us\":";
    histToJson(out, ps.recvWaitUs);
    // Per-link channel split (fleet plane): only channels that saw
    // traffic emit, mirroring the global "channels" section.
    out << ",\"chan_tx\":{";
    bool firstChan = true;
    for (int c = 0; c < PeerStats::kMaxPairChannels; c++) {
      const uint64_t tx = ps.chanTx[c].load(std::memory_order_relaxed);
      if (tx == 0) {
        continue;
      }
      out << (firstChan ? "" : ",") << "\"" << c << "\":" << tx;
      firstChan = false;
    }
    out << "},\"chan_rx\":{";
    firstChan = true;
    for (int c = 0; c < PeerStats::kMaxPairChannels; c++) {
      const uint64_t rx = ps.chanRx[c].load(std::memory_order_relaxed);
      if (rx == 0) {
        continue;
      }
      out << (firstChan ? "" : ",") << "\"" << c << "\":" << rx;
      firstChan = false;
    }
    out << "}}";
  }
  out << "}";

  // Per-data-channel wire bytes (multi-channel striping) and per-loop
  // progress stamps. Channel 0 alone == the single-connection baseline;
  // nonzero channel >= 1 traffic is the striping-engaged evidence tests
  // and dashboards key on. Only channels/loops that saw traffic emit.
  out << ",\"channels\":{";
  first = true;
  for (int c = 0; c < kMaxChannelStats; c++) {
    const uint64_t tx = channelTx_[c].load(std::memory_order_relaxed);
    const uint64_t rx = channelRx_[c].load(std::memory_order_relaxed);
    if (tx == 0 && rx == 0) {
      continue;
    }
    if (!first) {
      out << ",";
    }
    first = false;
    out << "\"" << c << "\":{\"tx_bytes\":" << tx << ",\"rx_bytes\":" << rx
        << "}";
  }
  out << "}";

  out << ",\"loops\":{";
  first = true;
  for (int l = 0; l < kMaxLoopStats; l++) {
    const uint64_t ev = loopEvents_[l].load(std::memory_order_relaxed);
    const int64_t progress =
        loopLastProgressUs_[l].load(std::memory_order_relaxed);
    if (ev == 0 && progress == 0) {
      continue;
    }
    if (!first) {
      out << ",";
    }
    first = false;
    out << "\"" << l << "\":{\"events\":" << ev
        << ",\"last_progress_us\":" << progress
        << ",\"last_progress_age_us\":"
        << (progress == 0 ? -1 : nowUs - progress) << "}";
  }
  out << "}";

  out << ",\"watchdog\":{\"stalls\":"
      << stalls_.load(std::memory_order_relaxed) << ",\"last\":";
  Stall stall;
  if (lastStall(&stall)) {
    out << "{\"op\":\"" << (stall.isSend ? "send" : "recv")
        << "\",\"peer\":" << stall.peer << ",\"slot\":" << stall.slot
        << ",\"waited_us\":" << stall.waitedUs << ",\"at_us\":" << stall.atUs
        << ",\"age_us\":" << (nowUs - stall.atUs)
        << ",\"peer_last_progress_us\":" << stall.peerLastProgressUs << "}";
  } else {
    out << "null";
  }
  out << "}}";

  if (drain) {
    resetAll();
  }
  return out.str();
}

void Metrics::resetAll() {
  for (auto& s : ops_) {
    s.calls.store(0, std::memory_order_relaxed);
    s.bytes.store(0, std::memory_order_relaxed);
    s.errors.store(0, std::memory_order_relaxed);
    s.latency.reset();
  }
  for (auto& p : peers_) {
    p.sentMsgs.store(0, std::memory_order_relaxed);
    p.sentBytes.store(0, std::memory_order_relaxed);
    p.recvMsgs.store(0, std::memory_order_relaxed);
    p.recvBytes.store(0, std::memory_order_relaxed);
    p.rxPauses.store(0, std::memory_order_relaxed);
    p.recvWaitUs.reset();
    for (int c = 0; c < PeerStats::kMaxPairChannels; c++) {
      p.chanTx[c].store(0, std::memory_order_relaxed);
      p.chanRx[c].store(0, std::memory_order_relaxed);
    }
    p.txPosts.store(0, std::memory_order_relaxed);
    p.bwWinBytes.store(0, std::memory_order_relaxed);
    // lastProgressUs, bwWinStartUs and the EWMA estimates survive:
    // timestamps and estimators, not counters — a drain must not blind
    // the slow-link detector for the next window.
  }
  retries_.store(0, std::memory_order_relaxed);
  planHits_.store(0, std::memory_order_relaxed);
  planMisses_.store(0, std::memory_order_relaxed);
  planEvictions_.store(0, std::memory_order_relaxed);
  ubufCreates_.store(0, std::memory_order_relaxed);
  stalls_.store(0, std::memory_order_relaxed);
  stashPauses_.store(0, std::memory_order_relaxed);
  traceEventsDropped_.store(0, std::memory_order_relaxed);
  for (int c = 0; c < kMaxChannelStats; c++) {
    channelTx_[c].store(0, std::memory_order_relaxed);
    channelRx_[c].store(0, std::memory_order_relaxed);
  }
  for (int l = 0; l < kMaxLoopStats; l++) {
    loopEvents_[l].store(0, std::memory_order_relaxed);
    // loopLastProgressUs_ survives: timestamp, not a counter.
  }
  faultsTotal_.store(0, std::memory_order_relaxed);
  peerFailures_.store(0, std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> guard(faultMu_);
    faultCounts_.clear();
  }
  {
    std::lock_guard<std::mutex> guard(stallMu_);
    haveStall_ = false;
    failedPeer_ = -1;
    failureMessage_.clear();
  }
  {
    // Reset contents, never erase: phaseHistogram hands out raw
    // pointers that must survive a concurrent drain.
    std::lock_guard<std::mutex> guard(phaseMu_);
    for (auto& opEntry : phaseHists_) {
      for (auto& algoEntry : opEntry.second) {
        for (auto& phaseEntry : algoEntry.second) {
          phaseEntry.second->reset();
        }
      }
    }
  }
}

MetricsOp::MetricsOp(Metrics* metrics, MetricOp op, uint64_t bytes)
    : metrics_(metrics), op_(op), startUs_(0) {
  if (metrics_ == nullptr || !metrics_->enabled()) {
    metrics_ = nullptr;  // single disabled-path check, nothing else
    return;
  }
  metrics_->recordCall(op, bytes);
  startUs_ = Tracer::nowUs();
  exceptionsAtEntry_ = std::uncaught_exceptions();
}

MetricsOp::~MetricsOp() {
  if (metrics_ == nullptr) {
    return;
  }
  // Baseline comparison, not a plain >0 check: a collective invoked from
  // a destructor during unwinding must not count a phantom error.
  if (std::uncaught_exceptions() > exceptionsAtEntry_) {
    metrics_->recordError(op_);
  }
  metrics_->recordLatency(op_, Tracer::nowUs() - startUs_);
}

}  // namespace tpucoll
