// In-band fleet observability plane (docs/fleet.md).
//
// A background aggregation service that folds every rank's metrics /
// profile / health snapshot up the PR 13 topology the same way the
// hierarchical collectives move payload: members push a bounded,
// fixed-size report to their host leader (over the shm payload plane
// where co-hosted pairs negotiated it), leaders pre-aggregate one host
// document and relay it to rank 0 over TCP. Rank 0 therefore receives
// O(hosts) messages per interval, never O(ranks), and serves the merged
// fleet view through Context::fleetJson() -> capi tc_fleet_json -> the
// telemetry endpoint's /fleet route. Members never open a telemetry
// connection to rank 0 — relaying is structural, not a convention.
//
// Wire discipline: reports ride SlotPrefix::kFleetObs (their own slot
// namespace — no collision with user or collective traffic) as
// fixed-size space-padded JSON, so receivers post one exact-size recv
// per sender and re-arm it after every message; the transport stash
// absorbs pace skew exactly as it absorbs blind collective sends. A
// sender never rewrites its buffer while a send is in flight, and a
// wedged receiver degrades to skipped rounds, not a hang.
//
// Rank 0 additionally runs the continuous anomaly detectors on the
// aggregated stream (persistent straggler / slow link / lease jitter;
// docs/fleet.md) — each firing publishes a flight-recorder event AND a
// metrics anomaly counter so /flightrec post-mortems and the live
// /fleet view agree on what went wrong.
//
// Cost when idle: the service is its own thread doing nothing between
// ticks; the transport hot path pays the metrics registry's existing
// one-relaxed-load gate and nothing else. TPUCOLL_FLEETOBS=0 turns
// start() into a no-op.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "tpucoll/common/json.h"

namespace tpucoll {

class Context;

namespace transport {
class UnboundBuffer;
}

namespace fleetobs {

// Knobs, resolved once at start() from the strict env.h parsers
// (docs/env.md).
struct Options {
  bool enabled = true;        // TPUCOLL_FLEETOBS
  int64_t intervalMs = 1000;  // TPUCOLL_FLEETOBS_INTERVAL_MS
  size_t maxBytes = 32768;    // TPUCOLL_FLEETOBS_MAX_BYTES (per report)
  int opsTail = 64;           // TPUCOLL_FLEETOBS_OPS (profile ring tail)
  int windowRounds = 30;      // TPUCOLL_FLEETOBS_WINDOW (anomaly window)
  int64_t stragglerMs = 200;  // TPUCOLL_FLEETOBS_STRAGGLER_MS

  static Options fromEnv();
};

class FleetObs {
 public:
  explicit FleetObs(Context* ctx);
  ~FleetObs();
  FleetObs(const FleetObs&) = delete;
  FleetObs& operator=(const FleetObs&) = delete;

  // Spawn the aggregation thread for this rank's topology role. No-op
  // when TPUCOLL_FLEETOBS=0, when already running, or when the context
  // has no topology (not connected). Must be called after connect.
  void start();

  // Stop and join the thread, then release the wire buffers. Safe to
  // call repeatedly and when never started; Context::close() calls it
  // before the transport quiesces so no posted recv outlives the mesh.
  void stop();

  bool running() const { return running_.load(std::memory_order_acquire); }

  // Side-channel for state the native core cannot see (the elastic
  // agent lives behind the C ABI in Python): a JSON object merged into
  // this rank's report as its "aux" field. Validated here so a
  // malformed document fails the setter, not the aggregation thread.
  void setAux(std::string auxJson);

  // Rank 0: the latest merged fleet document (empty-coverage skeleton
  // until the first round lands). Other ranks: a role stub that points
  // the reader at rank 0. Always valid JSON.
  std::string fleetJson();

 private:
  struct PeerLink {
    // One sender or receiver endpoint: a fixed-size wire buffer plus
    // the in-flight/dead state the tick loop needs.
    int rank = -1;
    uint64_t slot = 0;  // kFleetObs slot this link sends/receives on
    std::vector<char> bytes;
    std::unique_ptr<transport::UnboundBuffer> ubuf;
    bool sendPending = false;
    bool dead = false;
    bool posted = false;
    int64_t lastSeenRound = -1;
    std::string latestRaw;  // last received report/doc, trimmed
  };

  // Finalized cross-rank op join: who stalled collective `cseq` and by
  // how much (profile.py attribute() semantics, computed in-band).
  // `critOwner` is the plurality winner of the ranks' causal
  // critical-edge votes (each rank nominates the peer of its longest
  // recv span — common/span.h); -1 when spans were off or no votes
  // arrived for the op.
  struct WindowOp {
    int64_t round = 0;
    int straggler = -1;
    uint64_t excessUs = 0;
    int critOwner = -1;
  };

  struct AnomalyEvent {
    std::string kind;
    int rank = -1;
    int64_t tUs = 0;
    uint64_t detail = 0;
  };

  // Currently-slow link (latest detector pass): rank's pair EWMA
  // bandwidth vs the fleet median. Rebuilt every round for /fleet.
  struct SlowLink {
    int rank = -1;
    int peer = -1;
    uint64_t bwBps = 0;
    uint64_t medianBps = 0;
  };

  void runLoop();
  void tick();
  // Builds this rank's report (<= opts_.maxBytes once space-padded),
  // shrinking the profile tail / link list until it fits.
  std::string buildReport();
  std::string buildReportAttempt(int opsTail, int maxLinks);
  // Leader: drain member recvs, fold the host document.
  void drainPeer(PeerLink& p);
  std::string buildHostDoc();
  // Rank 0: merge host docs, run detectors, publish fleetJson_.
  void mergeAndDetect(const std::string& ownHostDoc);
  void ingestStragglerOps(int rank, const JsonReader::Value& report);
  void ingestCritVotes(int rank, const JsonReader::Value& report);
  void finalizePendingOps();
  void runDetectors(
      const std::map<int, const JsonReader::Value*>& reports);
  void fireAnomaly(const char* kind, int rank, uint64_t detail);
  bool debounced(const std::string& kind, int rank);

  size_t hostDocBytes(int hostIndex) const;

  Context* const ctx_;
  Options opts_;
  std::thread thread_;
  std::atomic<bool> running_{false};
  bool stopRequested_ = false;
  std::mutex stopMu_;
  std::condition_variable stopCv_;

  // Role wiring, resolved at start() from the topology.
  bool isLeader_ = false;
  int leaderRank_ = -1;
  int hostIndex_ = -1;
  std::vector<int> localMembers_;  // co-hosted non-leader ranks (leader)
  std::vector<int> otherLeaders_;  // other hosts' leaders (rank 0)

  PeerLink up_;                     // member/leader: link toward parent
  std::vector<PeerLink> members_;   // leader: one per local member
  std::vector<PeerLink> leaders_;   // rank 0: one per other host leader
  int64_t round_ = 0;

  std::mutex auxMu_;
  std::string auxJson_;

  std::mutex fleetMu_;
  std::string fleetJson_;

  // ---- rank-0 detector state (aggregation thread only) ----
  // cseq -> rank -> (total_us, wait_us), joined across reports until
  // every rank answered or the grace expired.
  struct PendingOp {
    int64_t firstRound = 0;
    std::map<int, std::pair<uint64_t, uint64_t>> perRank;
    // voter rank -> nominated owner (from the voter's "crit" array).
    // Keyed by voter so ring-tail resends stay idempotent; empty when
    // the fleet runs with spans disabled.
    std::map<int, int> critVotes;
  };
  std::map<int64_t, PendingOp> pendingOps_;
  int64_t processedThroughCseq_ = -1;
  std::deque<WindowOp> window_;
  std::map<std::string, std::map<int, int64_t>> lastFiredRound_;
  std::deque<AnomalyEvent> recent_;
  std::vector<SlowLink> slowLinks_;
  // rank -> (round, leases_renewed) history for the lease-jitter
  // detector.
  std::map<int, std::deque<std::pair<int64_t, uint64_t>>> leaseHistory_;
};

}  // namespace fleetobs
}  // namespace tpucoll
