// Causal step-level span recorder: the raw material for cross-rank
// critical-path analysis (docs/critpath.md).
//
// The phase profiler (profile.h) aggregates WHERE an op's time went on
// one rank (pack/post/wire_wait/... totals); it cannot say WHICH send
// on WHICH rank gated the op's end-to-end latency, because that answer
// needs the individual phase INSTANCES — this send to that peer on
// this slot, from t0 to t1 — matched across ranks into a causal graph.
// This layer records exactly those instances:
//
//   span = {cseq, id, kind, phase, peer, slot, bytes, t0_us, t1_us}
//
// where `cseq` is the flight recorder's cross-rank collective sequence
// (the merge key), `id` the span's per-op emission ordinal (program
// order — deterministic for a given schedule, the ordinal the Python
// side uses to pair the k-th send a->b with the k-th recv b<-a), and
// `kind` the causal role:
//
//   send   a wire send post, annotated with the destination peer. The
//          posting call runs on the collective's thread, so injected
//          send delays (fault plane) and slow serialization land INSIDE
//          this span — which is what makes "rank 1's sends own the
//          critical path" attributable.
//   recv   a wire receive from `peer`: t0 = post (or wait start),
//          t1 = observed arrival. The matched remote send's end gates
//          this span's completion — the cross-rank edge.
//   wait   an unattributed wire wait (send drains, wait-any loops).
//   local  compute/copy work (reduce, pack, unpack, codec).
//
// Mechanism mirrors the profiler exactly: span::OpScope is stamped in
// every public collective entry (next to ProfileOpScope; tools/check
// rule span-coverage enforces it) and parks a per-op state in a
// thread-local; profile::PhaseScope — already present at every phase
// instance in the six native algorithm families and the schedule
// interpreter — emits one span per instance when that state is live,
// with wire sites upgraded to the annotated constructor carrying
// (peer, slot, bytes). The interpreter additionally emits recv spans
// directly (emit()) so their t0/t1 are the true post/arrival times
// rather than the demand-time wait window.
//
// Cost contract: disabled — TPUCOLL_SPANS=0, the default — costs one
// relaxed load plus a thread-local park per collective entry and one
// thread-local read per phase scope; no clock reads, no records.
// Enabled, each span is one fetch_add plus relaxed stores into the
// bounded ring (TPUCOLL_SPANS_RING rows, claim-then-publish protocol
// from flightrec.h), read concurrently by Context::spansJson().
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>

namespace tpucoll {

class Metrics;

namespace span {

enum class Kind : uint8_t {
  kSend = 0,
  kRecv,
  kWait,
  kLocal,
  kCount,
};

const char* kindName(Kind k);

class Recorder;

// Per-op state parked in a thread-local by OpScope: the recorder to
// emit into, the op identity every span row inherits, and the per-op
// ordinal counter. Owned by the OpScope on the issuing thread; only
// that thread touches it (collectives run synchronously).
struct OpState {
  Recorder* rec{nullptr};
  int64_t cseq{-1};
  const char* opcode{nullptr};  // static string
  uint32_t nextId{0};
};

// The live op state on this thread, or null when no enabled span scope
// is active (spans disabled / outside a collective).
OpState* currentOp();

class Recorder {
 public:
  // Ring row; all fields relaxed-atomic under the claim-then-publish
  // `seq` protocol (flightrec.h) so a concurrent toJson skips rows
  // that are mid-overwrite.
  struct Entry {
    std::atomic<uint64_t> seq{0};
    std::atomic<int64_t> cseq{-1};
    std::atomic<uint32_t> id{0};
    std::atomic<uint8_t> kind{0};
    std::atomic<uint8_t> phase{0};  // profile::Phase value
    std::atomic<int32_t> peer{-1};
    std::atomic<uint64_t> slot{0};
    std::atomic<uint64_t> bytes{0};
    std::atomic<int64_t> t0Us{0};
    std::atomic<int64_t> t1Us{0};
    std::atomic<const char*> opcode{nullptr};  // static string
  };

  static constexpr uint64_t kNoSeq = ~uint64_t(0);

  // Capacity from TPUCOLL_SPANS_RING (default 4096, rounded up to a
  // power of two); enable gate from TPUCOLL_SPANS (default 0 — spans
  // are opt-in: they record per-instance rows, an order of magnitude
  // more volume than the profiler's per-op summaries). Both knobs are
  // strict (common/env.h). `metrics` supplies the group tag for the
  // JSON document; may be null (standalone tests).
  Recorder(int rank, int size, Metrics* metrics);

  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  void setEnabled(bool on) {
    enabled_.store(on, std::memory_order_relaxed);
  }

  // Publish one span row. Thread-safe (ring slot claimed by fetch_add);
  // called from PhaseScope destructors and the interpreter's direct
  // emits via the thread-local op state.
  void record(const OpState& op, uint32_t id, Kind kind, uint8_t phase,
              int peer, uint64_t slot, uint64_t bytes, int64_t t0Us,
              int64_t t1Us);

  uint64_t nextSeq() const {
    return nextSeq_.load(std::memory_order_relaxed);
  }
  uint64_t capacity() const { return mask_ + 1; }

  // Full JSON document: {"version", "kind": "tpucoll_spans", "rank",
  // "size", "group", "enabled", "now_us", "next_seq", "capacity",
  // "dropped", "spans": [{"seq", "cseq", "id", "kind", "phase",
  // "peer", "slot", "bytes", "t0_us", "t1_us", "op"}, ...]}.
  std::string toJson() const;

 private:
  const int rank_;
  const int size_;
  Metrics* metrics_;
  std::atomic<bool> enabled_{false};
  uint64_t mask_;  // capacity - 1 (power of two)
  std::unique_ptr<Entry[]> entries_;
  std::atomic<uint64_t> nextSeq_{0};
};

// RAII op scope for the public collective entry points, stamped next
// to ProfileOpScope. Parks the op state in the thread-local (saving
// the previous head for nested collectives — hier phases are ordinary
// collectives on sub-contexts, each accruing to ITS recorder); a
// disabled recorder parks null, which keeps a disabled nested op's
// spans from being charged to an enabled outer op's stream.
class OpScope {
 public:
  OpScope(Recorder* rec, const char* opcode, int64_t cseq);
  ~OpScope();
  OpScope(const OpScope&) = delete;
  OpScope& operator=(const OpScope&) = delete;

 private:
  OpState st_;
  OpState* prev_;
};

// Emit one span with explicit endpoints into the current op's stream
// (no-op outside an enabled op scope). For sites where t0/t1 are not
// a lexical scope — the interpreter's recv spans (post time .. FIFO-
// attributed arrival time) are the canonical caller.
void emit(Kind kind, uint8_t phase, int peer, uint64_t slot,
          uint64_t bytes, int64_t t0Us, int64_t t1Us);

}  // namespace span
}  // namespace tpucoll
