// Structured connect diagnostics (reference: gloo/transport/tcp/
// debug_data.h ConnectDebugData + debug_logger.h DebugLogger::log): every
// outbound connection attempt produces a record — success, retryable
// failure, or terminal failure — delivered to an optional process-wide
// hook so orchestration layers can surface WHICH pair of a large mesh is
// failing to come up without scraping logs.
#pragma once

#include <cstdint>
#include <functional>
#include <string>

namespace tpucoll {

struct ConnectDebugData {
  int selfRank{-1};
  int peerRank{-1};
  std::string remote;  // peer address
  std::string local;   // local socket address ("" before bind/connect)
  int attempt{0};      // 1-based
  bool ok{false};
  bool willRetry{false};
  std::string error;  // "" on success
};

// Register (or clear, with nullptr) the process-wide hook. The callback
// runs on the connecting thread; keep it cheap and reentrant-safe.
void setConnectDebugLogger(std::function<void(const ConnectDebugData&)> fn);

// Invoked by the transport on every attempt outcome. Always emits a
// TC_DEBUG line; additionally calls the registered hook.
void logConnectAttempt(const ConnectDebugData& data);

}  // namespace tpucoll
