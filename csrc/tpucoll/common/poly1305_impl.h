// Poly1305 core shared between crypto.cc and the AVX-512 fused-AEAD TU
// (crypto_avx512.cc). Header-only so the fused seal/open kernels can
// interleave poly block groups with ChaCha rounds at statement level in
// one loop body — the whole point of the fusion is that poly's scalar
// 64x64 multiplies and ChaCha's vector ALU work retire on different
// execution ports. 44-bit limbs ("donna-64" shape), 4-block interleave
// via r^4..r powers; see crypto.cc for the RFC 8439 assembly of this
// into the AEAD.
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>

// Every member is force-inlined: this header is compiled into BOTH the
// baseline-ISA TU (crypto.cc, -mavx2) and the AVX-512 TU
// (crypto_avx512.cc, -mavx512f). An out-of-line comdat copy could come
// from either TU at the linker's whim — if the AVX-512 TU's copy won
// (it is listed first) the scalar fallback path would execute AVX-512
// instructions and SIGILL on older hosts, silently defeating the
// runtime dispatch. Force-inlining removes the out-of-line symbol
// entirely.
#define TC_POLY_INLINE inline __attribute__((always_inline))

namespace tpucoll {
namespace crypto_detail {

struct Poly1305 {
  static constexpr uint64_t kMask44 = 0xfffffffffffULL;
  static constexpr uint64_t kMask42 = 0x3ffffffffffULL;

  uint64_t r0, r1, r2;
  uint64_t s1, s2;  // r1 * 20, r2 * 20 (folded-carry multipliers)
  uint64_t h0{0}, h1{0}, h2{0};
  uint64_t pad0, pad1;

  // Powers r^4, r^3, r^2, r for the 4-block interleave (R[3] aliases
  // r0..r2). The serial h -> multiply -> h dependency chain is the
  // bottleneck of a one-block-at-a-time MAC (measured ~29 cycles per
  // block on Skylake-SP: latency-bound, not multiplier-bound), so bulk
  // input is absorbed four blocks per iteration:
  //   h = (h + m1)*r^4 + m2*r^3 + m3*r^2 + m4*r
  // — four independent products per carry propagation.
  uint64_t R0[4], R1[4], R2[4], S1[4], S2[4];

  TC_POLY_INLINE static uint64_t load64le(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;  // x86-64 is little-endian; transport is x86-only native
  }

  TC_POLY_INLINE explicit Poly1305(const uint8_t key[32]) {
    const uint64_t t0 = load64le(key) & 0x0ffffffc0fffffffULL;
    const uint64_t t1 = load64le(key + 8) & 0x0ffffffc0ffffffcULL;
    r0 = t0 & kMask44;
    r1 = ((t0 >> 44) | (t1 << 20)) & kMask44;
    r2 = (t1 >> 24) & kMask42;
    s1 = r1 * 20;
    s2 = r2 * 20;
    pad0 = load64le(key + 16);
    pad1 = load64le(key + 24);
    R0[3] = r0;
    R1[3] = r1;
    R2[3] = r2;
    for (int i = 2; i >= 0; i--) {  // r^2, r^3, r^4
      mulmod(R0[i + 1], R1[i + 1], R2[i + 1], r0, r1, r2, s1, s2,
             &R0[i], &R1[i], &R2[i]);
    }
    for (int i = 0; i < 4; i++) {
      S1[i] = R1[i] * 20;
      S2[i] = R2[i] * 20;
    }
  }

  TC_POLY_INLINE static void mulmod(uint64_t a0, uint64_t a1, uint64_t a2, uint64_t b0,
                     uint64_t b1, uint64_t b2, uint64_t t1, uint64_t t2,
                     uint64_t* o0, uint64_t* o1, uint64_t* o2) {
    using u128 = unsigned __int128;
    u128 d0 = static_cast<u128>(a0) * b0 + static_cast<u128>(a1) * t2 +
              static_cast<u128>(a2) * t1;
    u128 d1 = static_cast<u128>(a0) * b1 + static_cast<u128>(a1) * b0 +
              static_cast<u128>(a2) * t2;
    u128 d2 = static_cast<u128>(a0) * b2 + static_cast<u128>(a1) * b1 +
              static_cast<u128>(a2) * b0;
    uint64_t c = static_cast<uint64_t>(d0 >> 44);
    *o0 = static_cast<uint64_t>(d0) & kMask44;
    d1 += c;
    c = static_cast<uint64_t>(d1 >> 44);
    *o1 = static_cast<uint64_t>(d1) & kMask44;
    d2 += c;
    c = static_cast<uint64_t>(d2 >> 42);
    *o2 = static_cast<uint64_t>(d2) & kMask42;
    *o0 += c * 5;
    c = *o0 >> 44;
    *o0 &= kMask44;
    *o1 += c;
  }

  TC_POLY_INLINE static void limbs(const uint8_t* m, uint64_t hi, uint64_t out[3]) {
    const uint64_t t0 = load64le(m);
    const uint64_t t1 = load64le(m + 8);
    out[0] = t0 & kMask44;
    out[1] = ((t0 >> 44) | (t1 << 20)) & kMask44;
    out[2] = ((t1 >> 24) & kMask42) + hi;
  }

  // One 4-block group (64 bytes, hibit = 2^128 set on every block) in
  // accumulator registers — the unit the fused AEAD kernels interleave
  // with ChaCha rounds. Caller owns loading/storing h0..h2 around runs.
  TC_POLY_INLINE void group4(const uint8_t* m, uint64_t* a0, uint64_t* a1, uint64_t* a2) {
    using u128 = unsigned __int128;
    constexpr uint64_t hi = 1ULL << 40;
    uint64_t b[4][3];
    limbs(m, hi, b[0]);
    limbs(m + 16, hi, b[1]);
    limbs(m + 32, hi, b[2]);
    limbs(m + 48, hi, b[3]);
    b[0][0] += *a0;
    b[0][1] += *a1;
    b[0][2] += *a2;
    u128 d0 = 0, d1 = 0, d2 = 0;
    for (int i = 0; i < 4; i++) {
      d0 += static_cast<u128>(b[i][0]) * R0[i] +
            static_cast<u128>(b[i][1]) * S2[i] +
            static_cast<u128>(b[i][2]) * S1[i];
      d1 += static_cast<u128>(b[i][0]) * R1[i] +
            static_cast<u128>(b[i][1]) * R0[i] +
            static_cast<u128>(b[i][2]) * S2[i];
      d2 += static_cast<u128>(b[i][0]) * R2[i] +
            static_cast<u128>(b[i][1]) * R1[i] +
            static_cast<u128>(b[i][2]) * R0[i];
    }
    uint64_t c = static_cast<uint64_t>(d0 >> 44);
    *a0 = static_cast<uint64_t>(d0) & kMask44;
    d1 += c;
    c = static_cast<uint64_t>(d1 >> 44);
    *a1 = static_cast<uint64_t>(d1) & kMask44;
    d2 += c;
    c = static_cast<uint64_t>(d2 >> 42);
    *a2 = static_cast<uint64_t>(d2) & kMask42;
    *a0 += c * 5;
    c = *a0 >> 44;
    *a0 &= kMask44;
    *a1 += c;
  }

  TC_POLY_INLINE void blocks(const uint8_t* m, size_t n, uint32_t hibit) {
    const uint64_t hi = static_cast<uint64_t>(hibit & 1) << 40;  // 2^128
    uint64_t a0 = h0, a1 = h1, a2 = h2;
    if (hibit) {
      while (n >= 64) {
        group4(m, &a0, &a1, &a2);
        m += 64;
        n -= 64;
      }
    }
    while (n >= 16) {
      const uint64_t t0 = load64le(m);
      const uint64_t t1 = load64le(m + 8);
      a0 += t0 & kMask44;
      a1 += ((t0 >> 44) | (t1 << 20)) & kMask44;
      a2 += ((t1 >> 24) & kMask42) + hi;
      mulmod(a0, a1, a2, r0, r1, r2, s1, s2, &a0, &a1, &a2);
      m += 16;
      n -= 16;
    }
    h0 = a0;
    h1 = a1;
    h2 = a2;
  }

  TC_POLY_INLINE void finish(uint8_t tag[16]) {
    // Two carry sweeps bring h fully canonical-per-limb.
    uint64_t c = h1 >> 44;
    h1 &= kMask44;
    h2 += c;
    c = h2 >> 42;
    h2 &= kMask42;
    h0 += c * 5;
    c = h0 >> 44;
    h0 &= kMask44;
    h1 += c;
    c = h1 >> 44;
    h1 &= kMask44;
    h2 += c;
    c = h2 >> 42;
    h2 &= kMask42;
    h0 += c * 5;
    c = h0 >> 44;
    h0 &= kMask44;
    h1 += c;

    // Compute h - p = h + 5 - 2^130 and select it if h >= p.
    uint64_t g0 = h0 + 5;
    c = g0 >> 44;
    g0 &= kMask44;
    uint64_t g1 = h1 + c;
    c = g1 >> 44;
    g1 &= kMask44;
    const uint64_t g2 = h2 + c - (1ULL << 42);
    const uint64_t mask = (g2 >> 63) - 1;  // all-ones if h >= p
    h0 = (h0 & ~mask) | (g0 & mask);
    h1 = (h1 & ~mask) | (g1 & mask);
    h2 = (h2 & ~mask) | (g2 & mask);

    // h mod 2^128 + pad.
    using u128 = unsigned __int128;
    const uint64_t t0 = h0 | (h1 << 44);
    const uint64_t t1 = (h1 >> 20) | (h2 << 24);
    const u128 f = static_cast<u128>(t0) + pad0;
    const uint64_t lo = static_cast<uint64_t>(f);
    const uint64_t hi64 = static_cast<uint64_t>(
        static_cast<u128>(t1) + pad1 + static_cast<uint64_t>(f >> 64));
    std::memcpy(tag, &lo, 8);
    std::memcpy(tag + 8, &hi64, 8);
  }
};

#undef TC_POLY_INLINE

}  // namespace crypto_detail
}  // namespace tpucoll
