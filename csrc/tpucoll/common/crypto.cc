#include "tpucoll/common/crypto.h"

#include <cstring>

#ifdef __AVX2__
#include <immintrin.h>
#endif

#include "tpucoll/common/hmac.h"

namespace tpucoll {
namespace {

inline uint32_t rotl32(uint32_t v, int c) {
  return (v << c) | (v >> (32 - c));
}

inline uint32_t load32le(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

inline void store32le(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v);
  p[1] = static_cast<uint8_t>(v >> 8);
  p[2] = static_cast<uint8_t>(v >> 16);
  p[3] = static_cast<uint8_t>(v >> 24);
}

inline void store64le(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) {
    p[i] = static_cast<uint8_t>(v >> (8 * i));
  }
}

#define TC_QR(a, b, c, d)        \
  a += b;                        \
  d = rotl32(d ^ a, 16);         \
  c += d;                        \
  b = rotl32(b ^ c, 12);         \
  a += b;                        \
  d = rotl32(d ^ a, 8);          \
  c += d;                        \
  b = rotl32(b ^ c, 7)

void chachaBlockWords(const uint32_t state[16], uint32_t out[16]) {
  uint32_t x[16];
  std::memcpy(x, state, sizeof(x));
  for (int round = 0; round < 10; round++) {
    TC_QR(x[0], x[4], x[8], x[12]);
    TC_QR(x[1], x[5], x[9], x[13]);
    TC_QR(x[2], x[6], x[10], x[14]);
    TC_QR(x[3], x[7], x[11], x[15]);
    TC_QR(x[0], x[5], x[10], x[15]);
    TC_QR(x[1], x[6], x[11], x[12]);
    TC_QR(x[2], x[7], x[8], x[13]);
    TC_QR(x[3], x[4], x[9], x[14]);
  }
  for (int i = 0; i < 16; i++) {
    out[i] = x[i] + state[i];
  }
}

#undef TC_QR

void initState(uint32_t state[16], const uint8_t key[32], uint32_t counter,
               const uint8_t nonce[12]) {
  state[0] = 0x61707865;
  state[1] = 0x3320646e;
  state[2] = 0x79622d32;
  state[3] = 0x6b206574;
  for (int i = 0; i < 8; i++) {
    state[4 + i] = load32le(key + 4 * i);
  }
  state[12] = counter;
  state[13] = load32le(nonce);
  state[14] = load32le(nonce + 4);
  state[15] = load32le(nonce + 8);
}

#ifdef __AVX2__
inline __m256i vrot16(__m256i v) {
  const __m256i mask = _mm256_setr_epi8(
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13,
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13);
  return _mm256_shuffle_epi8(v, mask);
}

inline __m256i vrot8(__m256i v) {
  const __m256i mask = _mm256_setr_epi8(
      3, 0, 1, 2, 7, 4, 5, 6, 11, 8, 9, 10, 15, 12, 13, 14,
      3, 0, 1, 2, 7, 4, 5, 6, 11, 8, 9, 10, 15, 12, 13, 14);
  return _mm256_shuffle_epi8(v, mask);
}

inline __m256i vrot12(__m256i v) {
  return _mm256_or_si256(_mm256_slli_epi32(v, 12),
                         _mm256_srli_epi32(v, 20));
}

inline __m256i vrot7(__m256i v) {
  return _mm256_or_si256(_mm256_slli_epi32(v, 7),
                         _mm256_srli_epi32(v, 25));
}

#define TC_VQR(a, b, c, d)           \
  a = _mm256_add_epi32(a, b);        \
  d = vrot16(_mm256_xor_si256(d, a)); \
  c = _mm256_add_epi32(c, d);        \
  b = vrot12(_mm256_xor_si256(b, c)); \
  a = _mm256_add_epi32(a, b);        \
  d = vrot8(_mm256_xor_si256(d, a));  \
  c = _mm256_add_epi32(c, d);        \
  b = vrot7(_mm256_xor_si256(b, c))

// Transpose 8 vectors of 8 u32 lanes: row[i] lane b  ->  out vector b
// word i. Used to turn "word i of blocks 0..7" into contiguous blocks.
inline void transpose8x8(__m256i r[8]) {
  __m256i t[8], u[8];
  t[0] = _mm256_unpacklo_epi32(r[0], r[1]);
  t[1] = _mm256_unpackhi_epi32(r[0], r[1]);
  t[2] = _mm256_unpacklo_epi32(r[2], r[3]);
  t[3] = _mm256_unpackhi_epi32(r[2], r[3]);
  t[4] = _mm256_unpacklo_epi32(r[4], r[5]);
  t[5] = _mm256_unpackhi_epi32(r[4], r[5]);
  t[6] = _mm256_unpacklo_epi32(r[6], r[7]);
  t[7] = _mm256_unpackhi_epi32(r[6], r[7]);
  u[0] = _mm256_unpacklo_epi64(t[0], t[2]);
  u[1] = _mm256_unpackhi_epi64(t[0], t[2]);
  u[2] = _mm256_unpacklo_epi64(t[1], t[3]);
  u[3] = _mm256_unpackhi_epi64(t[1], t[3]);
  u[4] = _mm256_unpacklo_epi64(t[4], t[6]);
  u[5] = _mm256_unpackhi_epi64(t[4], t[6]);
  u[6] = _mm256_unpacklo_epi64(t[5], t[7]);
  u[7] = _mm256_unpackhi_epi64(t[5], t[7]);
  r[0] = _mm256_permute2x128_si256(u[0], u[4], 0x20);
  r[1] = _mm256_permute2x128_si256(u[1], u[5], 0x20);
  r[2] = _mm256_permute2x128_si256(u[2], u[6], 0x20);
  r[3] = _mm256_permute2x128_si256(u[3], u[7], 0x20);
  r[4] = _mm256_permute2x128_si256(u[0], u[4], 0x31);
  r[5] = _mm256_permute2x128_si256(u[1], u[5], 0x31);
  r[6] = _mm256_permute2x128_si256(u[2], u[6], 0x31);
  r[7] = _mm256_permute2x128_si256(u[3], u[7], 0x31);
}

// 8 blocks (512 bytes) of keystream per pass: each __m256i holds word i
// of blocks 0..7 ("vertical" layout), so the scalar round function maps
// 1:1 onto vector ops. Consumes full 512-byte chunks only.
size_t chacha20Xor8(const uint32_t state[16], uint32_t counter,
                    const uint8_t* in, size_t n, uint8_t* out) {
  size_t done = 0;
  while (n - done >= 512) {
    __m256i init[16], v[16];
    for (int i = 0; i < 16; i++) {
      init[i] = _mm256_set1_epi32(static_cast<int>(state[i]));
    }
    init[12] = _mm256_add_epi32(
        _mm256_set1_epi32(static_cast<int>(counter)),
        _mm256_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7));
    for (int i = 0; i < 16; i++) {
      v[i] = init[i];
    }
    for (int round = 0; round < 10; round++) {
      TC_VQR(v[0], v[4], v[8], v[12]);
      TC_VQR(v[1], v[5], v[9], v[13]);
      TC_VQR(v[2], v[6], v[10], v[14]);
      TC_VQR(v[3], v[7], v[11], v[15]);
      TC_VQR(v[0], v[5], v[10], v[15]);
      TC_VQR(v[1], v[6], v[11], v[12]);
      TC_VQR(v[2], v[7], v[8], v[13]);
      TC_VQR(v[3], v[4], v[9], v[14]);
    }
    for (int i = 0; i < 16; i++) {
      v[i] = _mm256_add_epi32(v[i], init[i]);
    }
    transpose8x8(v);      // words 0..7 of blocks 0..7
    transpose8x8(v + 8);  // words 8..15 of blocks 0..7
    for (int b = 0; b < 8; b++) {
      const uint8_t* src = in + done + b * 64;
      uint8_t* dst = out + done + b * 64;
      __m256i lo = _mm256_xor_si256(
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(src)), v[b]);
      __m256i hi = _mm256_xor_si256(
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(src + 32)),
          v[8 + b]);
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst), lo);
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + 32), hi);
    }
    counter += 8;
    done += 512;
  }
  return done;
}

#undef TC_VQR
#endif  // __AVX2__

void chacha20Xor(const uint8_t key[32], uint32_t counter,
                 const uint8_t nonce[12], const uint8_t* in, size_t n,
                 uint8_t* out) {
  uint32_t state[16];
  initState(state, key, counter, nonce);
#ifdef __AVX2__
  const size_t vec = chacha20Xor8(state, counter, in, n, out);
  in += vec;
  out += vec;
  n -= vec;
  state[12] = counter + static_cast<uint32_t>(vec / 64);
#endif
  uint8_t block[64];
  while (n > 0) {
    uint32_t words[16];
    chachaBlockWords(state, words);
    for (int i = 0; i < 16; i++) {
      store32le(block + 4 * i, words[i]);
    }
    const size_t take = n < 64 ? n : 64;
    for (size_t i = 0; i < take; i++) {
      out[i] = in[i] ^ block[i];
    }
    in += take;
    out += take;
    n -= take;
    state[12]++;
  }
}

// Poly1305 with 26-bit limbs (the well-trodden "donna" shape: carries
// stay in 64-bit intermediates, no 128-bit type needed).
struct Poly1305 {
  uint32_t r[5];
  uint32_t h[5]{0, 0, 0, 0, 0};
  uint32_t pad[4];

  explicit Poly1305(const uint8_t key[32]) {
    r[0] = load32le(key + 0) & 0x3ffffff;
    r[1] = (load32le(key + 3) >> 2) & 0x3ffff03;
    r[2] = (load32le(key + 6) >> 4) & 0x3ffc0ff;
    r[3] = (load32le(key + 9) >> 6) & 0x3f03fff;
    r[4] = (load32le(key + 12) >> 8) & 0x00fffff;
    for (int i = 0; i < 4; i++) {
      pad[i] = load32le(key + 16 + 4 * i);
    }
  }

  void blocks(const uint8_t* m, size_t n, uint32_t hibit) {
    const uint64_t r0 = r[0], r1 = r[1], r2 = r[2], r3 = r[3], r4 = r[4];
    const uint64_t s1 = r1 * 5, s2 = r2 * 5, s3 = r3 * 5, s4 = r4 * 5;
    uint64_t h0 = h[0], h1 = h[1], h2 = h[2], h3 = h[3], h4 = h[4];
    while (n >= 16) {
      h0 += load32le(m + 0) & 0x3ffffff;
      h1 += (load32le(m + 3) >> 2) & 0x3ffffff;
      h2 += (load32le(m + 6) >> 4) & 0x3ffffff;
      h3 += (load32le(m + 9) >> 6) & 0x3ffffff;
      h4 += (load32le(m + 12) >> 8) | (static_cast<uint64_t>(hibit) << 24);
      const uint64_t d0 =
          h0 * r0 + h1 * s4 + h2 * s3 + h3 * s2 + h4 * s1;
      const uint64_t d1 =
          h0 * r1 + h1 * r0 + h2 * s4 + h3 * s3 + h4 * s2;
      const uint64_t d2 =
          h0 * r2 + h1 * r1 + h2 * r0 + h3 * s4 + h4 * s3;
      const uint64_t d3 =
          h0 * r3 + h1 * r2 + h2 * r1 + h3 * r0 + h4 * s4;
      const uint64_t d4 =
          h0 * r4 + h1 * r3 + h2 * r2 + h3 * r1 + h4 * r0;
      uint64_t c = d0 >> 26;
      h0 = d0 & 0x3ffffff;
      uint64_t e1 = d1 + c;
      c = e1 >> 26;
      h1 = e1 & 0x3ffffff;
      uint64_t e2 = d2 + c;
      c = e2 >> 26;
      h2 = e2 & 0x3ffffff;
      uint64_t e3 = d3 + c;
      c = e3 >> 26;
      h3 = e3 & 0x3ffffff;
      uint64_t e4 = d4 + c;
      c = e4 >> 26;
      h4 = e4 & 0x3ffffff;
      h0 += c * 5;
      c = h0 >> 26;
      h0 &= 0x3ffffff;
      h1 += c;
      m += 16;
      n -= 16;
    }
    h[0] = static_cast<uint32_t>(h0);
    h[1] = static_cast<uint32_t>(h1);
    h[2] = static_cast<uint32_t>(h2);
    h[3] = static_cast<uint32_t>(h3);
    h[4] = static_cast<uint32_t>(h4);
  }

  void finish(uint8_t tag[16]) {
    uint32_t h0 = h[0], h1 = h[1], h2 = h[2], h3 = h[3], h4 = h[4];
    uint32_t c = h1 >> 26;
    h1 &= 0x3ffffff;
    h2 += c;
    c = h2 >> 26;
    h2 &= 0x3ffffff;
    h3 += c;
    c = h3 >> 26;
    h3 &= 0x3ffffff;
    h4 += c;
    c = h4 >> 26;
    h4 &= 0x3ffffff;
    h0 += c * 5;
    c = h0 >> 26;
    h0 &= 0x3ffffff;
    h1 += c;

    // Compute h + -p and select it if h >= p.
    uint32_t g0 = h0 + 5;
    c = g0 >> 26;
    g0 &= 0x3ffffff;
    uint32_t g1 = h1 + c;
    c = g1 >> 26;
    g1 &= 0x3ffffff;
    uint32_t g2 = h2 + c;
    c = g2 >> 26;
    g2 &= 0x3ffffff;
    uint32_t g3 = h3 + c;
    c = g3 >> 26;
    g3 &= 0x3ffffff;
    uint32_t g4 = h4 + c - (1u << 26);
    const uint32_t mask = (g4 >> 31) - 1;  // all-ones if h >= p
    h0 = (h0 & ~mask) | (g0 & mask);
    h1 = (h1 & ~mask) | (g1 & mask);
    h2 = (h2 & ~mask) | (g2 & mask);
    h3 = (h3 & ~mask) | (g3 & mask);
    h4 = (h4 & ~mask) | (g4 & mask);

    // h mod 2^128 + pad.
    h0 = (h0 | (h1 << 26)) & 0xffffffff;
    h1 = ((h1 >> 6) | (h2 << 20)) & 0xffffffff;
    h2 = ((h2 >> 12) | (h3 << 14)) & 0xffffffff;
    h3 = ((h3 >> 18) | (h4 << 8)) & 0xffffffff;
    uint64_t f = static_cast<uint64_t>(h0) + pad[0];
    store32le(tag + 0, static_cast<uint32_t>(f));
    f = static_cast<uint64_t>(h1) + pad[1] + (f >> 32);
    store32le(tag + 4, static_cast<uint32_t>(f));
    f = static_cast<uint64_t>(h2) + pad[2] + (f >> 32);
    store32le(tag + 8, static_cast<uint32_t>(f));
    f = static_cast<uint64_t>(h3) + pad[3] + (f >> 32);
    store32le(tag + 12, static_cast<uint32_t>(f));
  }
};

void polyUpdatePadded(Poly1305* mac, const uint8_t* data, size_t n) {
  // Full 16-byte blocks straight from the source, then one zero-padded
  // final block (RFC 8439 AEAD layout pads aad and ciphertext to 16).
  const size_t full = n & ~static_cast<size_t>(15);
  if (full > 0) {
    mac->blocks(data, full, 1);
  }
  if (n - full > 0) {
    uint8_t last[16] = {0};
    std::memcpy(last, data + full, n - full);
    mac->blocks(last, 16, 1);
  }
}

void aeadTag(const uint8_t otk[32], const uint8_t* aad, size_t aadLen,
             const uint8_t* ct, size_t ctLen, uint8_t tag[16]) {
  Poly1305 mac(otk);
  polyUpdatePadded(&mac, aad, aadLen);
  polyUpdatePadded(&mac, ct, ctLen);
  uint8_t lens[16];
  store64le(lens, aadLen);
  store64le(lens + 8, ctLen);
  mac.blocks(lens, 16, 1);
  mac.finish(tag);
}

void makeNonce(uint64_t seq, uint8_t nonce[12]) {
  std::memset(nonce, 0, 4);
  store64le(nonce + 4, seq);
}

}  // namespace

namespace crypto_detail {

void chacha20Block(const uint8_t key[32], uint32_t counter,
                   const uint8_t nonce[12], uint8_t out[64]) {
  uint32_t state[16];
  initState(state, key, counter, nonce);
  uint32_t words[16];
  chachaBlockWords(state, words);
  for (int i = 0; i < 16; i++) {
    store32le(out + 4 * i, words[i]);
  }
}

void poly1305(const uint8_t key[32], const uint8_t* msg, size_t n,
              uint8_t tag[16]) {
  Poly1305 mac(key);
  const size_t full = n & ~static_cast<size_t>(15);
  if (full > 0) {
    mac.blocks(msg, full, 1);
  }
  if (n - full > 0) {
    // Final partial block: append the 0x01 hibit byte, no zero padding
    // into the hibit position (plain Poly1305 semantics).
    uint8_t last[16] = {0};
    std::memcpy(last, msg + full, n - full);
    last[n - full] = 1;
    mac.blocks(last, 16, 0);
  }
  mac.finish(tag);
}

void aeadSealWithNonce(const AeadKey& key, const uint8_t nonce[12],
                       const uint8_t* aad, size_t aadLen, const uint8_t* in,
                       size_t n, uint8_t* out, uint8_t tag[kAeadTagBytes]) {
  uint8_t otk[64];
  chacha20Block(key.bytes, 0, nonce, otk);
  chacha20Xor(key.bytes, 1, nonce, in, n, out);
  aeadTag(otk, aad, aadLen, out, n, tag);
}

}  // namespace crypto_detail

void aeadSeal(const AeadKey& key, uint64_t seq, const uint8_t* aad,
              size_t aadLen, const uint8_t* in, size_t n, uint8_t* out,
              uint8_t tag[kAeadTagBytes]) {
  uint8_t nonce[12];
  makeNonce(seq, nonce);
  crypto_detail::aeadSealWithNonce(key, nonce, aad, aadLen, in, n, out, tag);
}

bool aeadOpen(const AeadKey& key, uint64_t seq, const uint8_t* aad,
              size_t aadLen, const uint8_t* in, size_t n, uint8_t* out,
              const uint8_t tag[kAeadTagBytes]) {
  uint8_t nonce[12];
  makeNonce(seq, nonce);
  uint8_t otk[64];
  crypto_detail::chacha20Block(key.bytes, 0, nonce, otk);
  uint8_t expect[kAeadTagBytes];
  aeadTag(otk, aad, aadLen, in, n, expect);
  if (!macEqual(expect, tag, kAeadTagBytes)) {
    return false;
  }
  chacha20Xor(key.bytes, 1, nonce, in, n, out);
  return true;
}

void hkdfSha256(const void* ikm, size_t ikmLen, const void* salt,
                size_t saltLen, const void* info, size_t infoLen,
                uint8_t* out, size_t outLen) {
  // Extract: PRK = HMAC(salt, IKM).
  auto prk = hmacSha256(salt, saltLen, ikm, ikmLen);
  // Expand: T(i) = HMAC(PRK, T(i-1) || info || i).
  uint8_t t[32];
  size_t tLen = 0;
  uint8_t counter = 1;
  size_t produced = 0;
  while (produced < outLen) {
    std::string block(reinterpret_cast<const char*>(t), tLen);
    block.append(static_cast<const char*>(info), infoLen);
    block.push_back(static_cast<char>(counter));
    auto digest = hmacSha256(prk.data(), prk.size(), block.data(),
                             block.size());
    std::memcpy(t, digest.data(), 32);
    tLen = 32;
    const size_t take = outLen - produced < 32 ? outLen - produced : 32;
    std::memcpy(out + produced, t, take);
    produced += take;
    counter++;
  }
}

}  // namespace tpucoll
