#include "tpucoll/common/crypto.h"

#include <cstdlib>
#include <cstring>

#ifdef __AVX2__
#include <immintrin.h>
#endif

#include "tpucoll/common/env.h"
#include "tpucoll/common/hmac.h"
#include "tpucoll/common/poly1305_impl.h"

namespace tpucoll {

#if defined(TPUCOLL_HAVE_AVX512)
namespace crypto_detail {
// crypto_avx512.cc: 16-block AVX-512 keystream tier and the fused
// ChaCha+Poly bulk seal/open (full 1 KiB chunks only; each returns
// bytes consumed).
size_t chacha20Xor16Avx512(const uint32_t state[16], uint32_t counter,
                           const uint8_t* in, size_t n, uint8_t* out);
size_t sealFusedAvx512(const uint32_t state[16], uint32_t counter,
                       const uint8_t* in, size_t n, uint8_t* out,
                       Poly1305* mac);
size_t openFusedAvx512(const uint32_t state[16], uint32_t counter,
                       const uint8_t* in, size_t n, uint8_t* out,
                       Poly1305* mac);
}  // namespace crypto_detail
#endif

namespace {

inline uint32_t rotl32(uint32_t v, int c) {
  return (v << c) | (v >> (32 - c));
}

inline uint32_t load32le(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

inline void store32le(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v);
  p[1] = static_cast<uint8_t>(v >> 8);
  p[2] = static_cast<uint8_t>(v >> 16);
  p[3] = static_cast<uint8_t>(v >> 24);
}

inline void store64le(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) {
    p[i] = static_cast<uint8_t>(v >> (8 * i));
  }
}

#define TC_QR(a, b, c, d)        \
  a += b;                        \
  d = rotl32(d ^ a, 16);         \
  c += d;                        \
  b = rotl32(b ^ c, 12);         \
  a += b;                        \
  d = rotl32(d ^ a, 8);          \
  c += d;                        \
  b = rotl32(b ^ c, 7)

void chachaBlockWords(const uint32_t state[16], uint32_t out[16]) {
  uint32_t x[16];
  std::memcpy(x, state, sizeof(x));
  for (int round = 0; round < 10; round++) {
    TC_QR(x[0], x[4], x[8], x[12]);
    TC_QR(x[1], x[5], x[9], x[13]);
    TC_QR(x[2], x[6], x[10], x[14]);
    TC_QR(x[3], x[7], x[11], x[15]);
    TC_QR(x[0], x[5], x[10], x[15]);
    TC_QR(x[1], x[6], x[11], x[12]);
    TC_QR(x[2], x[7], x[8], x[13]);
    TC_QR(x[3], x[4], x[9], x[14]);
  }
  for (int i = 0; i < 16; i++) {
    out[i] = x[i] + state[i];
  }
}

#undef TC_QR

void initState(uint32_t state[16], const uint8_t key[32], uint32_t counter,
               const uint8_t nonce[12]) {
  state[0] = 0x61707865;
  state[1] = 0x3320646e;
  state[2] = 0x79622d32;
  state[3] = 0x6b206574;
  for (int i = 0; i < 8; i++) {
    state[4 + i] = load32le(key + 4 * i);
  }
  state[12] = counter;
  state[13] = load32le(nonce);
  state[14] = load32le(nonce + 4);
  state[15] = load32le(nonce + 8);
}

#ifdef __AVX2__
inline __m256i vrot16(__m256i v) {
  const __m256i mask = _mm256_setr_epi8(
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13,
      2, 3, 0, 1, 6, 7, 4, 5, 10, 11, 8, 9, 14, 15, 12, 13);
  return _mm256_shuffle_epi8(v, mask);
}

inline __m256i vrot8(__m256i v) {
  const __m256i mask = _mm256_setr_epi8(
      3, 0, 1, 2, 7, 4, 5, 6, 11, 8, 9, 10, 15, 12, 13, 14,
      3, 0, 1, 2, 7, 4, 5, 6, 11, 8, 9, 10, 15, 12, 13, 14);
  return _mm256_shuffle_epi8(v, mask);
}

inline __m256i vrot12(__m256i v) {
  return _mm256_or_si256(_mm256_slli_epi32(v, 12),
                         _mm256_srli_epi32(v, 20));
}

inline __m256i vrot7(__m256i v) {
  return _mm256_or_si256(_mm256_slli_epi32(v, 7),
                         _mm256_srli_epi32(v, 25));
}

#define TC_VQR(a, b, c, d)           \
  a = _mm256_add_epi32(a, b);        \
  d = vrot16(_mm256_xor_si256(d, a)); \
  c = _mm256_add_epi32(c, d);        \
  b = vrot12(_mm256_xor_si256(b, c)); \
  a = _mm256_add_epi32(a, b);        \
  d = vrot8(_mm256_xor_si256(d, a));  \
  c = _mm256_add_epi32(c, d);        \
  b = vrot7(_mm256_xor_si256(b, c))

// Transpose 8 vectors of 8 u32 lanes: row[i] lane b  ->  out vector b
// word i. Used to turn "word i of blocks 0..7" into contiguous blocks.
inline void transpose8x8(__m256i r[8]) {
  __m256i t[8], u[8];
  t[0] = _mm256_unpacklo_epi32(r[0], r[1]);
  t[1] = _mm256_unpackhi_epi32(r[0], r[1]);
  t[2] = _mm256_unpacklo_epi32(r[2], r[3]);
  t[3] = _mm256_unpackhi_epi32(r[2], r[3]);
  t[4] = _mm256_unpacklo_epi32(r[4], r[5]);
  t[5] = _mm256_unpackhi_epi32(r[4], r[5]);
  t[6] = _mm256_unpacklo_epi32(r[6], r[7]);
  t[7] = _mm256_unpackhi_epi32(r[6], r[7]);
  u[0] = _mm256_unpacklo_epi64(t[0], t[2]);
  u[1] = _mm256_unpackhi_epi64(t[0], t[2]);
  u[2] = _mm256_unpacklo_epi64(t[1], t[3]);
  u[3] = _mm256_unpackhi_epi64(t[1], t[3]);
  u[4] = _mm256_unpacklo_epi64(t[4], t[6]);
  u[5] = _mm256_unpackhi_epi64(t[4], t[6]);
  u[6] = _mm256_unpacklo_epi64(t[5], t[7]);
  u[7] = _mm256_unpackhi_epi64(t[5], t[7]);
  r[0] = _mm256_permute2x128_si256(u[0], u[4], 0x20);
  r[1] = _mm256_permute2x128_si256(u[1], u[5], 0x20);
  r[2] = _mm256_permute2x128_si256(u[2], u[6], 0x20);
  r[3] = _mm256_permute2x128_si256(u[3], u[7], 0x20);
  r[4] = _mm256_permute2x128_si256(u[0], u[4], 0x31);
  r[5] = _mm256_permute2x128_si256(u[1], u[5], 0x31);
  r[6] = _mm256_permute2x128_si256(u[2], u[6], 0x31);
  r[7] = _mm256_permute2x128_si256(u[3], u[7], 0x31);
}

// 8 blocks (512 bytes) of keystream per pass: each __m256i holds word i
// of blocks 0..7 ("vertical" layout), so the scalar round function maps
// 1:1 onto vector ops. Consumes full 512-byte chunks only.
size_t chacha20Xor8(const uint32_t state[16], uint32_t counter,
                    const uint8_t* in, size_t n, uint8_t* out) {
  size_t done = 0;
  while (n - done >= 512) {
    __m256i init[16], v[16];
    for (int i = 0; i < 16; i++) {
      init[i] = _mm256_set1_epi32(static_cast<int>(state[i]));
    }
    init[12] = _mm256_add_epi32(
        _mm256_set1_epi32(static_cast<int>(counter)),
        _mm256_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7));
    for (int i = 0; i < 16; i++) {
      v[i] = init[i];
    }
    for (int round = 0; round < 10; round++) {
      TC_VQR(v[0], v[4], v[8], v[12]);
      TC_VQR(v[1], v[5], v[9], v[13]);
      TC_VQR(v[2], v[6], v[10], v[14]);
      TC_VQR(v[3], v[7], v[11], v[15]);
      TC_VQR(v[0], v[5], v[10], v[15]);
      TC_VQR(v[1], v[6], v[11], v[12]);
      TC_VQR(v[2], v[7], v[8], v[13]);
      TC_VQR(v[3], v[4], v[9], v[14]);
    }
    for (int i = 0; i < 16; i++) {
      v[i] = _mm256_add_epi32(v[i], init[i]);
    }
    transpose8x8(v);      // words 0..7 of blocks 0..7
    transpose8x8(v + 8);  // words 8..15 of blocks 0..7
    for (int b = 0; b < 8; b++) {
      const uint8_t* src = in + done + b * 64;
      uint8_t* dst = out + done + b * 64;
      __m256i lo = _mm256_xor_si256(
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(src)), v[b]);
      __m256i hi = _mm256_xor_si256(
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(src + 32)),
          v[8 + b]);
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst), lo);
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + 32), hi);
    }
    counter += 8;
    done += 512;
  }
  return done;
}

#undef TC_VQR
#endif  // __AVX2__

#if defined(TPUCOLL_HAVE_AVX512)
// crypto_avx512.cc (own TU, -mavx512f). Runtime-gated below.
bool avx512Usable() {
  static const bool v = [] {
    if (!__builtin_cpu_supports("avx512f")) {
      return false;
    }
    // Strict flag (common/env.h): only 0/1 parse; historically any
    // non-"0" value disabled the tier.
    return !envFlag("TPUCOLL_NO_AVX512", false);
  }();
  return v;
}
#endif

void chacha20Xor(const uint8_t key[32], uint32_t counter,
                 const uint8_t nonce[12], const uint8_t* in, size_t n,
                 uint8_t* out) {
  uint32_t state[16];
  initState(state, key, counter, nonce);
#if defined(TPUCOLL_HAVE_AVX512)
  if (avx512Usable()) {
    const size_t z =
        crypto_detail::chacha20Xor16Avx512(state, counter, in, n, out);
    in += z;
    out += z;
    n -= z;
    counter += static_cast<uint32_t>(z / 64);
    state[12] = counter;
  }
#endif
#ifdef __AVX2__
  const size_t vec = chacha20Xor8(state, counter, in, n, out);
  in += vec;
  out += vec;
  n -= vec;
  state[12] = counter + static_cast<uint32_t>(vec / 64);
#endif
  uint8_t block[64];
  while (n > 0) {
    uint32_t words[16];
    chachaBlockWords(state, words);
    for (int i = 0; i < 16; i++) {
      store32le(block + 4 * i, words[i]);
    }
    const size_t take = n < 64 ? n : 64;
    for (size_t i = 0; i < take; i++) {
      out[i] = in[i] ^ block[i];
    }
    in += take;
    out += take;
    n -= take;
    state[12]++;
  }
}

// Poly1305 core (donna-64 shape, 4-block interleave) lives in
// poly1305_impl.h so the AVX-512 fused-AEAD TU shares it.
using crypto_detail::Poly1305;

void polyUpdatePadded(Poly1305* mac, const uint8_t* data, size_t n) {
  // Full 16-byte blocks straight from the source, then one zero-padded
  // final block (RFC 8439 AEAD layout pads aad and ciphertext to 16).
  const size_t full = n & ~static_cast<size_t>(15);
  if (full > 0) {
    mac->blocks(data, full, 1);
  }
  if (n - full > 0) {
    uint8_t last[16] = {0};
    std::memcpy(last, data + full, n - full);
    mac->blocks(last, 16, 1);
  }
}

// RFC 8439 tag closing: the lengths block after aad and ct (each
// zero-padded to 16 by the caller via polyUpdatePadded).
void finishTag(Poly1305* mac, size_t aadLen, size_t ctLen, uint8_t tag[16]) {
  uint8_t lens[16];
  store64le(lens, aadLen);
  store64le(lens + 8, ctLen);
  mac->blocks(lens, 16, 1);
  mac->finish(tag);
}

void makeNonce(uint64_t seq, uint8_t nonce[12]) {
  std::memset(nonce, 0, 4);
  store64le(nonce + 4, seq);
}

}  // namespace

namespace crypto_detail {

void chacha20Block(const uint8_t key[32], uint32_t counter,
                   const uint8_t nonce[12], uint8_t out[64]) {
  uint32_t state[16];
  initState(state, key, counter, nonce);
  uint32_t words[16];
  chachaBlockWords(state, words);
  for (int i = 0; i < 16; i++) {
    store32le(out + 4 * i, words[i]);
  }
}

void poly1305(const uint8_t key[32], const uint8_t* msg, size_t n,
              uint8_t tag[16]) {
  Poly1305 mac(key);
  const size_t full = n & ~static_cast<size_t>(15);
  if (full > 0) {
    mac.blocks(msg, full, 1);
  }
  if (n - full > 0) {
    // Final partial block: append the 0x01 hibit byte, no zero padding
    // into the hibit position (plain Poly1305 semantics).
    uint8_t last[16] = {0};
    std::memcpy(last, msg + full, n - full);
    last[n - full] = 1;
    mac.blocks(last, 16, 0);
  }
  mac.finish(tag);
}

void aeadSealWithNonce(const AeadKey& key, const uint8_t nonce[12],
                       const uint8_t* aad, size_t aadLen, const uint8_t* in,
                       size_t n, uint8_t* out, uint8_t tag[kAeadTagBytes]) {
  uint8_t otk[64];
  chacha20Block(key.bytes, 0, nonce, otk);
  Poly1305 mac(otk);
  polyUpdatePadded(&mac, aad, aadLen);
  size_t done = 0;
#if defined(TPUCOLL_HAVE_AVX512)
  if (avx512Usable()) {
    uint32_t state[16];
    initState(state, key.bytes, 1, nonce);
    done = sealFusedAvx512(state, 1, in, n, out, &mac);
  }
#endif
  if (n - done > 0) {
    chacha20Xor(key.bytes, 1 + static_cast<uint32_t>(done / 64), nonce,
                in + done, n - done, out + done);
    polyUpdatePadded(&mac, out + done, n - done);
  }
  finishTag(&mac, aadLen, n, tag);
}

}  // namespace crypto_detail

void aeadSeal(const AeadKey& key, uint64_t seq, const uint8_t* aad,
              size_t aadLen, const uint8_t* in, size_t n, uint8_t* out,
              uint8_t tag[kAeadTagBytes]) {
  uint8_t nonce[12];
  makeNonce(seq, nonce);
  crypto_detail::aeadSealWithNonce(key, nonce, aad, aadLen, in, n, out, tag);
}

bool aeadOpen(const AeadKey& key, uint64_t seq, const uint8_t* aad,
              size_t aadLen, const uint8_t* in, size_t n, uint8_t* out,
              const uint8_t tag[kAeadTagBytes]) {
  uint8_t nonce[12];
  makeNonce(seq, nonce);
  uint8_t otk[64];
  crypto_detail::chacha20Block(key.bytes, 0, nonce, otk);
  Poly1305 mac(otk);
  polyUpdatePadded(&mac, aad, aadLen);
  size_t done = 0;
#if defined(TPUCOLL_HAVE_AVX512)
  if (avx512Usable()) {
    // Fused verify+decrypt: the bulk prefix is decrypted BEFORE the tag
    // check completes. On mismatch `out` is unspecified — exactly the
    // documented contract — and nothing is surfaced to callers.
    uint32_t state[16];
    initState(state, key.bytes, 1, nonce);
    done = crypto_detail::openFusedAvx512(state, 1, in, n, out, &mac);
  }
#endif
  // Absorb the remaining ciphertext before decrypting it (in == out
  // in-place decryption would otherwise destroy the mac input).
  polyUpdatePadded(&mac, in + done, n - done);
  uint8_t expect[kAeadTagBytes];
  finishTag(&mac, aadLen, n, expect);
  if (!macEqual(expect, tag, kAeadTagBytes)) {
    return false;
  }
  if (n - done > 0) {
    chacha20Xor(key.bytes, 1 + static_cast<uint32_t>(done / 64), nonce,
                in + done, n - done, out + done);
  }
  return true;
}

int aeadIsaTier() {
#if defined(TPUCOLL_HAVE_AVX512)
  if (avx512Usable()) {
    return 2;
  }
#endif
#ifdef __AVX2__
  return 1;
#else
  return 0;
#endif
}

void hkdfSha256(const void* ikm, size_t ikmLen, const void* salt,
                size_t saltLen, const void* info, size_t infoLen,
                uint8_t* out, size_t outLen) {
  // Extract: PRK = HMAC(salt, IKM).
  auto prk = hmacSha256(salt, saltLen, ikm, ikmLen);
  // Expand: T(i) = HMAC(PRK, T(i-1) || info || i).
  uint8_t t[32];
  size_t tLen = 0;
  uint8_t counter = 1;
  size_t produced = 0;
  while (produced < outLen) {
    std::string block(reinterpret_cast<const char*>(t), tLen);
    block.append(static_cast<const char*>(info), infoLen);
    block.push_back(static_cast<char>(counter));
    auto digest = hmacSha256(prk.data(), prk.size(), block.data(),
                             block.size());
    std::memcpy(t, digest.data(), 32);
    tLen = 32;
    const size_t take = outLen - produced < 32 ? outLen - produced : 32;
    std::memcpy(out + produced, t, take);
    produced += take;
    counter++;
  }
}

}  // namespace tpucoll
