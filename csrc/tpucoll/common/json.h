// Minimal JSON reader + string writer shared by the core's JSON surfaces.
//
// Grew out of the tuning table (tuning/tuning_table.cc): objects, arrays,
// strings with the common escapes, numbers, bools, null — everything the
// interchange formats the core must *read* (tuning tables, fault
// schedules) use, and nothing more. A dependency-free ~150-line
// recursive-descent parser beats gating those features on a JSON library
// the container doesn't ship. The `what` label prefixes every error so a
// malformed tuning table and a malformed fault schedule fail with their
// own names.
#pragma once

#include <cctype>
#include <cmath>
#include <cstdio>
#include <sstream>
#include <string>
#include <utility>
#include <vector>

#include "tpucoll/common/logging.h"

namespace tpucoll {

class JsonReader {
 public:
  // rejectDuplicateKeys: historically this reader accepted duplicate
  // object keys silently (field() returns the first, so a duplicate was
  // dead weight that masked typos in hand-edited files). Strict-mode
  // loaders (tuning tables, schedule tables) pass true to fail loudly
  // with the offending key path instead.
  explicit JsonReader(const std::string& text, const char* what = "JSON",
                      bool rejectDuplicateKeys = false)
      : text_(text), what_(what), rejectDuplicateKeys_(rejectDuplicateKeys) {}

  // Parsed value: exactly one of the members is active, by `kind`.
  struct Value {
    enum class Kind { kNull, kBool, kNumber, kString, kArray, kObject };
    Kind kind = Kind::kNull;
    bool boolean = false;
    double number = 0.0;
    std::string str;
    std::vector<Value> items;
    std::vector<std::pair<std::string, Value>> fields;

    const Value* field(const std::string& name) const {
      for (const auto& f : fields) {
        if (f.first == name) {
          return &f.second;
        }
      }
      return nullptr;
    }
  };

  Value parse() {
    Value v = parseValue();
    skipWs();
    TC_ENFORCE_EQ(pos_, text_.size(), what_, ": trailing bytes");
    return v;
  }

 private:
  void skipWs() {
    while (pos_ < text_.size() &&
           std::isspace(static_cast<unsigned char>(text_[pos_]))) {
      pos_++;
    }
  }

  char peek() {
    skipWs();
    TC_ENFORCE(pos_ < text_.size(), what_, ": unexpected end");
    return text_[pos_];
  }

  void expect(char c) {
    TC_ENFORCE(peek() == c, what_, ": expected '", c, "' at byte ", pos_);
    pos_++;
  }

  bool consume(char c) {
    if (pos_ < text_.size() && peek() == c) {
      pos_++;
      return true;
    }
    return false;
  }

  Value parseValue() {
    const char c = peek();
    if (c == '{') return parseObject();
    if (c == '[') return parseArray();
    if (c == '"') {
      Value v;
      v.kind = Value::Kind::kString;
      v.str = parseString();
      return v;
    }
    if (c == 't' || c == 'f') return parseLiteralBool();
    if (c == 'n') {
      expectWord("null");
      return Value{};
    }
    return parseNumber();
  }

  void expectWord(const char* w) {
    skipWs();
    for (const char* p = w; *p != '\0'; p++) {
      TC_ENFORCE(pos_ < text_.size() && text_[pos_] == *p, what_,
                 ": bad literal at byte ", pos_);
      pos_++;
    }
  }

  Value parseLiteralBool() {
    Value v;
    v.kind = Value::Kind::kBool;
    if (peek() == 't') {
      expectWord("true");
      v.boolean = true;
    } else {
      expectWord("false");
      v.boolean = false;
    }
    return v;
  }

  // Hand-rolled, locale-independent number scan: JSON numbers are
  // always dot-decimal, but std::stod honors LC_NUMERIC — in a
  // comma-decimal locale it would silently truncate "40.25" to 40.
  Value parseNumber() {
    skipWs();
    const size_t start = pos_;
    bool negative = false;
    if (pos_ < text_.size() &&
        (text_[pos_] == '-' || text_[pos_] == '+')) {
      negative = text_[pos_] == '-';
      pos_++;
    }
    bool anyDigit = false;
    double mantissa = 0.0;
    while (pos_ < text_.size() &&
           std::isdigit(static_cast<unsigned char>(text_[pos_]))) {
      mantissa = mantissa * 10.0 + (text_[pos_] - '0');
      anyDigit = true;
      pos_++;
    }
    if (pos_ < text_.size() && text_[pos_] == '.') {
      pos_++;
      double place = 0.1;
      while (pos_ < text_.size() &&
             std::isdigit(static_cast<unsigned char>(text_[pos_]))) {
        mantissa += (text_[pos_] - '0') * place;
        place *= 0.1;
        anyDigit = true;
        pos_++;
      }
    }
    TC_ENFORCE(anyDigit, what_, ": expected number at byte ", start);
    int exponent = 0;
    if (pos_ < text_.size() && (text_[pos_] == 'e' || text_[pos_] == 'E')) {
      pos_++;
      bool expNegative = false;
      if (pos_ < text_.size() &&
          (text_[pos_] == '-' || text_[pos_] == '+')) {
        expNegative = text_[pos_] == '-';
        pos_++;
      }
      bool anyExpDigit = false;
      while (pos_ < text_.size() &&
             std::isdigit(static_cast<unsigned char>(text_[pos_]))) {
        exponent = std::min(exponent * 10 + (text_[pos_] - '0'), 9999);
        anyExpDigit = true;
        pos_++;
      }
      TC_ENFORCE(anyExpDigit, what_, ": bad exponent at byte ", start);
      if (expNegative) {
        exponent = -exponent;
      }
    }
    Value v;
    v.kind = Value::Kind::kNumber;
    v.number = (negative ? -mantissa : mantissa) *
               std::pow(10.0, exponent);
    return v;
  }

  std::string parseString() {
    expect('"');
    std::string out;
    while (true) {
      TC_ENFORCE(pos_ < text_.size(), what_, ": unterminated string");
      char c = text_[pos_++];
      if (c == '"') {
        return out;
      }
      if (c != '\\') {
        out.push_back(c);
        continue;
      }
      TC_ENFORCE(pos_ < text_.size(), what_, ": bad escape");
      char e = text_[pos_++];
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'n': out.push_back('\n'); break;
        case 't': out.push_back('\t'); break;
        case 'r': out.push_back('\r'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'u': {
          // Interchange strings are ASCII identifiers; decode BMP
          // escapes to their low byte and reject the rest rather than
          // mis-decode.
          TC_ENFORCE(pos_ + 4 <= text_.size(), what_, ": bad \\u escape");
          unsigned code = 0;
          for (int i = 0; i < 4; i++) {
            char h = text_[pos_++];
            code <<= 4;
            if (h >= '0' && h <= '9') code |= h - '0';
            else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
            else TC_THROW(EnforceError, what_, ": bad \\u escape");
          }
          TC_ENFORCE(code < 0x80, what_,
                     ": non-ASCII \\u escape unsupported");
          out.push_back(static_cast<char>(code));
          break;
        }
        default:
          TC_THROW(EnforceError, what_, ": bad escape '\\", e, "'");
      }
    }
  }

  Value parseArray() {
    expect('[');
    Value v;
    v.kind = Value::Kind::kArray;
    if (consume(']')) {
      return v;
    }
    while (true) {
      char seg[16];
      std::snprintf(seg, sizeof(seg), "[%zu]", v.items.size());
      path_.emplace_back(seg);
      v.items.push_back(parseValue());
      path_.pop_back();
      if (consume(']')) {
        return v;
      }
      expect(',');
    }
  }

  Value parseObject() {
    expect('{');
    Value v;
    v.kind = Value::Kind::kObject;
    if (consume('}')) {
      return v;
    }
    while (true) {
      std::string key = parseString();
      if (rejectDuplicateKeys_ && v.field(key) != nullptr) {
        TC_THROW(EnforceError, what_, ": duplicate key \"", pathTo(key),
                 "\" at byte ", pos_);
      }
      expect(':');
      path_.push_back(key);
      Value parsed = parseValue();
      path_.pop_back();
      v.fields.emplace_back(std::move(key), std::move(parsed));
      if (consume('}')) {
        return v;
      }
      expect(',');
    }
  }

  // Dotted key path for error messages: "schedules[2].steps[0].op".
  std::string pathTo(const std::string& leaf) const {
    std::string out;
    for (const std::string& seg : path_) {
      if (!out.empty() && seg[0] != '[') {
        out += '.';
      }
      out += seg;
    }
    if (!out.empty()) {
      out += '.';
    }
    out += leaf;
    return out;
  }

  const std::string& text_;
  const char* what_;
  const bool rejectDuplicateKeys_;
  size_t pos_ = 0;
  std::vector<std::string> path_;
};

// Escaped JSON string literal writer (the serialization counterpart).
inline void appendJsonString(std::ostringstream& out, const std::string& s) {
  out << '"';
  for (char c : s) {
    switch (c) {
      case '"': out << "\\\""; break;
      case '\\': out << "\\\\"; break;
      case '\n': out << "\\n"; break;
      case '\t': out << "\\t"; break;
      case '\r': out << "\\r"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out << buf;
        } else {
          out << c;
        }
    }
  }
  out << '"';
}

}  // namespace tpucoll
