#include "tpucoll/common/codec_pool.h"

#include <unistd.h>

#include <atomic>
#include <utility>

#include "tpucoll/common/env.h"

namespace tpucoll {
namespace codec {

int codecThreads() {
  static const int n = [] {
    // Default = the transport loop width: a host provisioned to move
    // bytes on N threads gets N codec lanes (device.cc reads the same
    // knob with the same bounds).
    const long dflt = envCount("TPUCOLL_LOOP_THREADS", 1, 1, 64);
    return static_cast<int>(envCount("TPUCOLL_CODEC_THREADS", dflt, 1, 64));
  }();
  return n;
}

int codecPipelineDepth() {
  static const int d = static_cast<int>(
      envCount("TPUCOLL_CODEC_PIPELINE", 4, 1, 32));
  return d;
}

CodecPool& CodecPool::instance() {
  static CodecPool pool;
  return pool;
}

CodecPool::CodecPool() : width_(codecThreads()) {}

CodecPool::~CodecPool() {
  {
    std::lock_guard<std::mutex> guard(mu_);
    stop_ = true;
    cv_.notify_all();
  }
  const bool owner = ownerPid_ == ::getpid();
  for (auto& t : threads_) {
    if (owner) {
      t.join();
    } else {
      // Forked child: the underlying threads died with the parent's
      // address-space copy; just release the handles.
      t.detach();
    }
  }
}

void CodecPool::ensureWorkers() {
  // Called under mu_. Re-spawn check is pid-based: a forked child must
  // never touch threads it only inherited as dead handles.
  if (spawned_ && ownerPid_ == ::getpid()) {
    return;
  }
  if (spawned_) {
    return;  // foreign pid: caller falls back to inline execution
  }
  ownerPid_ = ::getpid();
  spawned_ = true;
  const int n = workers();
  threads_.reserve(static_cast<size_t>(n));
  for (int i = 0; i < n; i++) {
    threads_.emplace_back([this] { workerMain(); });
  }
}

void CodecPool::workerMain() {
  for (;;) {
    std::shared_ptr<Job> job;
    {
      std::unique_lock<std::mutex> lock(mu_);
      cv_.wait(lock, [&] { return stop_ || !queue_.empty(); });
      if (queue_.empty()) {
        return;  // stop_
      }
      job = queue_.front();
      queue_.pop_front();
    }
    job->fn();
    {
      std::lock_guard<std::mutex> guard(mu_);
      job->done = true;
      doneCv_.notify_all();
    }
  }
}

CodecPool::Ticket CodecPool::submit(std::function<void()> fn) {
  if (workers() == 0) {
    fn();
    return 0;
  }
  std::unique_lock<std::mutex> lock(mu_);
  ensureWorkers();
  if (ownerPid_ != ::getpid()) {
    lock.unlock();
    fn();
    return 0;
  }
  auto job = std::make_shared<Job>();
  job->fn = std::move(fn);
  job->id = nextId_++;
  queue_.push_back(job);
  live_[job->id] = job;
  cv_.notify_one();
  return job->id;
}

void CodecPool::wait(Ticket t) {
  if (t == 0) {
    return;
  }
  std::unique_lock<std::mutex> lock(mu_);
  doneCv_.wait(lock, [&] {
    auto it = live_.find(t);
    return it == live_.end() || it->second->done;
  });
  live_.erase(t);
}

void CodecPool::parallelFor(size_t nShards,
                            const std::function<void(size_t)>& fn) {
  if (nShards == 0) {
    return;
  }
  const size_t lanes =
      std::min(static_cast<size_t>(width_), nShards);
  if (lanes <= 1 || workers() == 0) {
    for (size_t i = 0; i < nShards; i++) {
      fn(i);
    }
    return;
  }
  // Dynamic shard claim: lane count changes WHO computes a shard, never
  // WHAT it computes — byte identity rides on the shard boundaries,
  // which the caller fixed before entering.
  auto next = std::make_shared<std::atomic<size_t>>(0);
  auto drain = [next, &fn, nShards] {
    size_t i;
    // relaxed: the counter only partitions shard indices; the caller's
    // wait() on every ticket is the publication point for shard output.
    while ((i = next->fetch_add(1, std::memory_order_relaxed)) < nShards) {
      fn(i);
    }
  };
  std::vector<Ticket> tickets;
  tickets.reserve(lanes - 1);
  for (size_t w = 1; w < lanes; w++) {
    tickets.push_back(submit(drain));
  }
  drain();
  for (Ticket t : tickets) {
    wait(t);
  }
}

}  // namespace codec
}  // namespace tpucoll
