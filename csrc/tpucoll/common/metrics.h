// Per-Context metrics registry: counters + fixed-bucket latency histograms
// for every collective kind and transport peer, plus the straggler
// watchdog's stall records.
//
// The reference ships no introspection beyond its benchmark harness
// (SURVEY.md §5); the Tracer (tracer.h) added spans, and this layer adds
// the always-cheap aggregate view a production deployment scrapes:
// per-collective call/byte/error counters and latency distributions,
// per-peer transport byte counters with a last-progress timestamp, and a
// record of the last stalled operation (which peer/slot a rank was
// blocked on past the watchdog deadline).
//
// Cost contract: every hot-path update is gated on ONE relaxed atomic
// load (enabled_); when enabled, an update is a handful of relaxed
// fetch_adds. No locks anywhere on the data path — the only mutex guards
// the (rare) stall record and snapshot serialization.
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace tpucoll {

// Fixed power-of-two latency buckets: bucket i counts durations in
// [2^i, 2^(i+1)) microseconds; the last bucket absorbs everything above
// ~67s. 27 buckets cover 1us .. 2^26us with no allocation.
constexpr int kLatencyBuckets = 27;

// Everything the registry tracks per operation kind. kConnect covers the
// rendezvous/bootstrap path (connectFullMesh / forkFrom).
enum class MetricOp : uint8_t {
  kAllreduce = 0,
  kBroadcast,
  kBarrier,
  kReduce,
  kGather,
  kGatherv,
  kScatter,
  kAllgather,
  kAllgatherv,
  kAlltoall,
  kAlltoallv,
  kReduceScatter,
  kSend,
  kRecv,
  kConnect,
  kCount,
};

const char* metricOpName(MetricOp op);

class Metrics {
 public:
  struct Histogram {
    std::atomic<uint64_t> buckets[kLatencyBuckets] = {};
    std::atomic<uint64_t> count{0};
    std::atomic<uint64_t> sumUs{0};
    std::atomic<uint64_t> maxUs{0};

    void record(int64_t us);
    void reset();
    bool empty() const {
      return count.load(std::memory_order_relaxed) == 0;
    }
  };

  struct OpStats {
    std::atomic<uint64_t> calls{0};
    std::atomic<uint64_t> bytes{0};
    std::atomic<uint64_t> errors{0};
    Histogram latency;
  };

  // Per-peer transport counters the transport::Context/Pair layer cannot
  // hold itself (it is torn down on close; metrics must survive for the
  // post-mortem snapshot). lastProgressUs is stamped by the pair whenever
  // payload bytes move.
  struct PeerStats {
    std::atomic<uint64_t> sentMsgs{0};
    std::atomic<uint64_t> sentBytes{0};
    std::atomic<uint64_t> recvMsgs{0};
    std::atomic<uint64_t> recvBytes{0};
    std::atomic<int64_t> lastProgressUs{0};
    // Stash-backpressure engagements: how many times this peer's socket
    // was paused because its early arrivals crossed the stash high
    // watermark (TPUCOLL_MAX_STASH_BYTES; docs/observability.md).
    std::atomic<uint64_t> rxPauses{0};
    // Latency from p2p wait start to completion against this peer
    // (recv side, where the source rank is known).
    Histogram recvWaitUs;
    // ---- link-level wire telemetry (fleet observability plane) ----
    // Per-data-channel wire bytes on THIS pair. channelTx_/channelRx_
    // fold the same movement across all peers; the per-link split is
    // what the fleet plane's slow-link detector needs (one cold stripe
    // to one peer hides inside the per-channel totals). Channels past
    // kMaxPairChannels fold into the last slot so the per-peer
    // footprint stays fixed.
    static constexpr int kMaxPairChannels = 8;
    std::atomic<uint64_t> chanTx[kMaxPairChannels] = {};
    std::atomic<uint64_t> chanRx[kMaxPairChannels] = {};
    // Wire messages enqueued toward this peer (per-pair post count;
    // sentMsgs counts completions, posts count intent — a growing gap
    // is a backed-up link).
    std::atomic<uint64_t> txPosts{0};
    // EWMA link estimates. Bandwidth folds a ~10ms byte window (both
    // directions) into bytes/sec; RTT is seeded by the connect
    // handshake and refreshed by shm credit round-trips. Zero = no
    // sample yet.
    std::atomic<uint64_t> bwEwmaBps{0};
    std::atomic<uint64_t> rttEwmaUs{0};
    std::atomic<int64_t> bwWinStartUs{0};
    std::atomic<uint64_t> bwWinBytes{0};
  };

  // Last stalled operation, as reported by the watchdog. `peer` is -1
  // when the blocked op admits several sources (recv-from-any).
  struct Stall {
    bool isSend{false};
    int peer{-1};
    uint64_t slot{0};
    int64_t waitedUs{0};
    int64_t atUs{0};            // steady-clock us when detected
    int64_t peerLastProgressUs{0};
  };

  explicit Metrics(int size);

  // ---- hot-path gate ----
  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  void setEnabled(bool on) {
    enabled_.store(on, std::memory_order_relaxed);
  }

  // Group tag of the owning communicator ("" = root context; split
  // sub-communicators carry their Context::groupTag). Emitted as the
  // snapshot's "group" field so per-group scrapes are distinguishable
  // (the Python exposition turns it into a group= Prometheus label).
  // Set once before traffic (Context::applyGroupTag), read by dumps.
  void setGroup(const std::string& group) {
    std::lock_guard<std::mutex> guard(groupMu_);
    group_ = group;
  }
  std::string group() const {
    std::lock_guard<std::mutex> guard(groupMu_);
    return group_;
  }

  // ---- collective / p2p op accounting ----
  void recordCall(MetricOp op, uint64_t bytes) {
    if (!enabled()) {
      return;
    }
    ops_[static_cast<int>(op)].calls.fetch_add(1, std::memory_order_relaxed);
    if (bytes != 0) {
      ops_[static_cast<int>(op)].bytes.fetch_add(bytes,
                                                 std::memory_order_relaxed);
    }
  }
  void recordLatency(MetricOp op, int64_t us) {
    if (!enabled()) {
      return;
    }
    ops_[static_cast<int>(op)].latency.record(us);
  }
  void recordError(MetricOp op) {
    if (!enabled()) {
      return;
    }
    ops_[static_cast<int>(op)].errors.fetch_add(1, std::memory_order_relaxed);
  }

  // Raw latency totals for one op — the tuner's measurement source
  // (tuning/tuner.cc): mean-over-iterations is the delta of two
  // (count, sumUs) snapshots, exact where the power-of-two buckets are
  // only a factor-2 bound.
  void opLatencyTotals(MetricOp op, uint64_t* count, uint64_t* sumUs) const {
    const Histogram& h = ops_[static_cast<int>(op)].latency;
    *count = h.count.load(std::memory_order_relaxed);
    *sumUs = h.sumUs.load(std::memory_order_relaxed);
  }

  // ---- transport peer accounting (Pair / transport::Context) ----
  void recordSent(int peer, uint64_t bytes) {
    if (!enabled() || peer < 0 || peer >= size_) {
      return;
    }
    peers_[peer].sentMsgs.fetch_add(1, std::memory_order_relaxed);
    peers_[peer].sentBytes.fetch_add(bytes, std::memory_order_relaxed);
  }
  void recordRecvd(int peer, uint64_t bytes) {
    if (!enabled() || peer < 0 || peer >= size_) {
      return;
    }
    peers_[peer].recvMsgs.fetch_add(1, std::memory_order_relaxed);
    peers_[peer].recvBytes.fetch_add(bytes, std::memory_order_relaxed);
  }
  // Back out sends that were counted at enqueue but cancelled before
  // touching the wire (rare teardown path).
  void uncountSent(int peer, uint64_t msgs, uint64_t bytes) {
    if (!enabled() || peer < 0 || peer >= size_) {
      return;
    }
    peers_[peer].sentMsgs.fetch_sub(msgs, std::memory_order_relaxed);
    peers_[peer].sentBytes.fetch_sub(bytes, std::memory_order_relaxed);
  }
  // Stamped on every payload movement — the watchdog's "when did this
  // link last make progress" signal. Always on (a single relaxed store)
  // so the timestamp is trustworthy even if counters were enabled late.
  void touchProgress(int peer, int64_t nowUs) {
    if (peer < 0 || peer >= size_) {
      return;
    }
    peers_[peer].lastProgressUs.store(nowUs, std::memory_order_relaxed);
  }
  void recordRecvWait(int peer, int64_t us) {
    if (!enabled() || peer < 0 || peer >= size_) {
      return;
    }
    peers_[peer].recvWaitUs.record(us);
  }
  // ---- link telemetry (Pair::touchProgress / Pair::enqueue) ----
  // Per-(peer, channel) byte counters plus the windowed EWMA bandwidth
  // estimate. Rides the existing touchProgress call: when enabled it is
  // one relaxed add per direction plus a window check; when disabled it
  // is the same single relaxed load every other hot-path hook pays.
  static constexpr int64_t kBwWindowUs = 10 * 1000;
  void recordLink(int peer, int channel, bool tx, uint64_t bytes,
                  int64_t nowUs) {
    if (!enabled() || peer < 0 || peer >= size_) {
      return;
    }
    PeerStats& p = peers_[peer];
    const int c = channel <= 0
                      ? 0
                      : (channel < PeerStats::kMaxPairChannels
                             ? channel
                             : PeerStats::kMaxPairChannels - 1);
    (tx ? p.chanTx : p.chanRx)[c].fetch_add(bytes, std::memory_order_relaxed);
    // Windowed EWMA fold. The CAS elects exactly one folder per window;
    // losers just contributed bytes. A stale winBytes read racing the
    // exchange skews one 10ms sample by one message — noise the EWMA
    // exists to absorb.
    p.bwWinBytes.fetch_add(bytes, std::memory_order_relaxed);
    int64_t start = p.bwWinStartUs.load(std::memory_order_relaxed);
    if (start == 0) {
      p.bwWinStartUs.compare_exchange_strong(start, nowUs,
                                             std::memory_order_relaxed);
      return;
    }
    const int64_t elapsed = nowUs - start;
    if (elapsed < kBwWindowUs) {
      return;
    }
    if (!p.bwWinStartUs.compare_exchange_strong(start, nowUs,
                                                std::memory_order_relaxed)) {
      return;
    }
    const uint64_t winBytes = p.bwWinBytes.exchange(0,
                                                    std::memory_order_relaxed);
    const uint64_t bps =
        winBytes * 1000000ULL / static_cast<uint64_t>(elapsed);
    const uint64_t prev = p.bwEwmaBps.load(std::memory_order_relaxed);
    p.bwEwmaBps.store(prev == 0 ? bps : (prev * 7 + bps) / 8,
                      std::memory_order_relaxed);
  }
  void recordLinkPost(int peer) {
    if (!enabled() || peer < 0 || peer >= size_) {
      return;
    }
    peers_[peer].txPosts.fetch_add(1, std::memory_order_relaxed);
  }
  void recordLinkRtt(int peer, int64_t us) {
    if (!enabled() || peer < 0 || peer >= size_ || us < 0) {
      return;
    }
    PeerStats& p = peers_[peer];
    const uint64_t prev = p.rttEwmaUs.load(std::memory_order_relaxed);
    const uint64_t sample = static_cast<uint64_t>(us);
    p.rttEwmaUs.store(prev == 0 ? sample : (prev * 7 + sample) / 8,
                      std::memory_order_relaxed);
  }
  uint64_t linkBwBps(int peer) const {
    return peer >= 0 && peer < size_
               ? peers_[peer].bwEwmaBps.load(std::memory_order_relaxed)
               : 0;
  }

  // ---- fleet anomaly detectors (common/fleetobs.cc) ----
  // Per-(kind, blamed-rank) counters behind a mutex, modeled on the
  // fault-plane map: detector firings are rare by construction, and the
  // map keeps the registry decoupled from the detector set. Not gated
  // on enabled_: an anomaly that fired must survive a counters-off
  // configuration, exactly like faults and stalls.
  void recordAnomaly(const std::string& kind, int rank);
  uint64_t anomaliesTotal() const {
    return anomaliesTotal_.load(std::memory_order_relaxed);
  }

  // ---- multi-channel transport (pair data channels + loop pool) ----
  // Wire bytes per data channel (channel 0 = the primary connection;
  // channels 1.. carry stripes of large messages when TPUCOLL_CHANNELS
  // > 1) and per event-loop thread progress stamps. Fixed small arrays:
  // channel/loop counts are tiny configuration constants, and array
  // indexing keeps the hot-path cost at one relaxed add.
  static constexpr int kMaxChannelStats = 16;
  static constexpr int kMaxLoopStats = 64;
  void recordChannelTx(int channel, uint64_t bytes) {
    if (!enabled() || channel < 0 || channel >= kMaxChannelStats) {
      return;
    }
    channelTx_[channel].fetch_add(bytes, std::memory_order_relaxed);
  }
  void recordChannelRx(int channel, uint64_t bytes) {
    if (!enabled() || channel < 0 || channel >= kMaxChannelStats) {
      return;
    }
    channelRx_[channel].fetch_add(bytes, std::memory_order_relaxed);
  }
  uint64_t channelTxBytes(int channel) const {
    return channel >= 0 && channel < kMaxChannelStats
               ? channelTx_[channel].load(std::memory_order_relaxed)
               : 0;
  }
  uint64_t channelRxBytes(int channel) const {
    return channel >= 0 && channel < kMaxChannelStats
               ? channelRx_[channel].load(std::memory_order_relaxed)
               : 0;
  }
  // Always on like touchProgress: the per-loop liveness stamp must be
  // trustworthy even when counters were enabled late.
  void touchLoop(int loop, int64_t nowUs) {
    if (loop < 0 || loop >= kMaxLoopStats) {
      return;
    }
    loopLastProgressUs_[loop].store(nowUs, std::memory_order_relaxed);
    loopEvents_[loop].fetch_add(1, std::memory_order_relaxed);
  }

  // Stash-watermark backpressure engaged against this peer (rare:
  // at most once per watermark crossing).
  void recordStashPause(int peer) {
    if (!enabled() || peer < 0 || peer >= size_) {
      return;
    }
    stashPauses_.fetch_add(1, std::memory_order_relaxed);
    peers_[peer].rxPauses.fetch_add(1, std::memory_order_relaxed);
  }

  // ---- transport failures (Context::onPairError) ----
  // Not gated on enabled_: like the watchdog's stall record, failure
  // evidence must survive a counters-off configuration — recovery
  // tooling (resilience.stall_reports) reads it to name the dead rank.
  void recordPeerFailure(int peer, const std::string& message);

  // ---- fault-injection plane (fault/fault.h) ----
  // Per-action fired-fault counters. Slow path only (a fault firing is
  // rare by construction), so a mutex-guarded map keeps the registry
  // decoupled from the fault plane's action enum. Not gated on
  // enabled_: the chaos harness asserts on these.
  void recordFault(const std::string& action);

  // ---- tracer overflow (tracer.h bounded event vector) ----
  // Spans dropped because the opt-in tracer hit TPUCOLL_TRACE_MAX_EVENTS
  // between drains. Not gated on enabled_: a silently truncated trace is
  // exactly the kind of loss this registry exists to make visible.
  void recordTraceDropped() {
    traceEventsDropped_.fetch_add(1, std::memory_order_relaxed);
  }

  // ---- persistent collective plans (collectives/plan.h) ----
  // Cache traffic plus the registration counter the plans exist to
  // flatten: ubuf_creates counts every UnboundBuffer constructed on
  // this context's transport, so a steady-state loop proving "zero new
  // registrations" is a zero delta on one number.
  void recordPlanHit() {
    if (!enabled()) {
      return;
    }
    planHits_.fetch_add(1, std::memory_order_relaxed);
  }
  void recordPlanMiss() {
    if (!enabled()) {
      return;
    }
    planMisses_.fetch_add(1, std::memory_order_relaxed);
  }
  void recordPlanEvictions(uint64_t n) {
    if (!enabled()) {
      return;
    }
    planEvictions_.fetch_add(n, std::memory_order_relaxed);
  }
  void recordUbufCreate() {
    if (!enabled()) {
      return;
    }
    ubufCreates_.fetch_add(1, std::memory_order_relaxed);
  }

  // ---- bootstrap plane (boot/, docs/bootstrap.md) ----
  // Rendezvous phase timings and store traffic are recorded once at
  // connect time; the broker pair gauges are refreshed by the owning
  // context immediately before each snapshot (they are live transport
  // state, not accumulating counters). All of these are configuration-
  // like facts about how the context came up, so they survive a drain.
  void recordBootRendezvous(bool lazy, int64_t publishUs, int64_t topoUs,
                            int64_t exchangeUs, uint64_t storeOps,
                            uint64_t storeBytes) {
    bootLazy_.store(lazy ? 1 : 0, std::memory_order_relaxed);
    bootPublishUs_.store(publishUs, std::memory_order_relaxed);
    bootTopoUs_.store(topoUs, std::memory_order_relaxed);
    bootExchangeUs_.store(exchangeUs, std::memory_order_relaxed);
    bootStoreOps_.store(storeOps, std::memory_order_relaxed);
    bootStoreBytes_.store(storeBytes, std::memory_order_relaxed);
  }
  void recordBootPairs(uint64_t connected, uint64_t inbound, uint64_t evicted,
                       uint64_t dials) {
    bootPairsConnected_.store(connected, std::memory_order_relaxed);
    bootPairsInbound_.store(inbound, std::memory_order_relaxed);
    bootPairsEvicted_.store(evicted, std::memory_order_relaxed);
    bootLazyDials_.store(dials, std::memory_order_relaxed);
  }

  // ---- phase profiler (common/profile.h) ----
  // Per-(collective, algorithm, phase) latency histogram, created on
  // first use. Slow path by design: the profiler flushes ONCE per
  // collective call (never per segment), so a mutex + nested-map lookup
  // is fine. The returned pointer stays valid for the registry's
  // lifetime — resetAll() zeroes histogram contents but never erases
  // entries, so a concurrent flush can't race a drain into a dangling
  // pointer.
  Histogram* phaseHistogram(const std::string& op, const std::string& algo,
                            const std::string& phase);

  // ---- connect retries (Pair backoff loop) ----
  void recordRetry() {
    if (!enabled()) {
      return;
    }
    retries_.fetch_add(1, std::memory_order_relaxed);
  }

  // ---- straggler watchdog ----
  // Threshold in microseconds a blocking wait may run before the stall is
  // reported; <= 0 disables the watchdog (the default unless
  // TPUCOLL_WATCHDOG_MS is set).
  int64_t watchdogUs() const {
    return watchdogUs_.load(std::memory_order_relaxed);
  }
  void setWatchdogUs(int64_t us) {
    watchdogUs_.store(us, std::memory_order_relaxed);
  }
  // Record (and log) a stall detected by a blocking wait. Not hot: fires
  // at most once per blocked wait, after `watchdogUs` of no progress.
  void recordStall(const Stall& stall);

  uint64_t stallCount() const {
    return stalls_.load(std::memory_order_relaxed);
  }
  // Copy of the last stall record; returns false when none was recorded.
  bool lastStall(Stall* out) const;

  int64_t lastProgressUs(int peer) const {
    if (peer < 0 || peer >= size_) {
      return 0;
    }
    return peers_[peer].lastProgressUs.load(std::memory_order_relaxed);
  }

  // ---- snapshot ----
  // Structured JSON snapshot of everything above. `drain` resets all
  // counters/histograms/stall records after serialization (timestamps and
  // the enabled/watchdog configuration survive a drain).
  std::string toJson(int rank, bool drain);

 private:
  void resetAll();

  const int size_;
  std::atomic<bool> enabled_{true};
  std::atomic<int64_t> watchdogUs_{0};
  OpStats ops_[static_cast<int>(MetricOp::kCount)];
  std::vector<PeerStats> peers_;
  std::atomic<uint64_t> retries_{0};
  std::atomic<uint64_t> planHits_{0};
  std::atomic<uint64_t> planMisses_{0};
  std::atomic<uint64_t> planEvictions_{0};
  std::atomic<uint64_t> ubufCreates_{0};
  std::atomic<uint64_t> stalls_{0};
  std::atomic<int> bootLazy_{0};
  std::atomic<int64_t> bootPublishUs_{0};
  std::atomic<int64_t> bootTopoUs_{0};
  std::atomic<int64_t> bootExchangeUs_{0};
  std::atomic<uint64_t> bootStoreOps_{0};
  std::atomic<uint64_t> bootStoreBytes_{0};
  std::atomic<uint64_t> bootPairsConnected_{0};
  std::atomic<uint64_t> bootPairsInbound_{0};
  std::atomic<uint64_t> bootPairsEvicted_{0};
  std::atomic<uint64_t> bootLazyDials_{0};
  std::atomic<uint64_t> stashPauses_{0};
  std::atomic<uint64_t> traceEventsDropped_{0};
  std::atomic<uint64_t> channelTx_[kMaxChannelStats] = {};
  std::atomic<uint64_t> channelRx_[kMaxChannelStats] = {};
  std::atomic<uint64_t> loopEvents_[kMaxLoopStats] = {};
  std::atomic<int64_t> loopLastProgressUs_[kMaxLoopStats] = {};

  mutable std::mutex groupMu_;
  std::string group_;
  mutable std::mutex stallMu_;
  bool haveStall_{false};
  Stall lastStall_;
  // First transport failure observed (later errors are usually the
  // cascade, not the cause) + total count.
  int failedPeer_{-1};
  std::string failureMessage_;
  std::atomic<uint64_t> peerFailures_{0};

  mutable std::mutex faultMu_;
  std::map<std::string, uint64_t> faultCounts_;
  std::atomic<uint64_t> faultsTotal_{0};

  // kind -> blamed rank -> firings (fleet anomaly detectors).
  mutable std::mutex anomalyMu_;
  std::map<std::string, std::map<int, uint64_t>> anomalyCounts_;
  std::atomic<uint64_t> anomaliesTotal_{0};

  // op -> algorithm -> phase -> histogram (phase profiler). Entries are
  // never erased (see phaseHistogram); unique_ptr keeps the Histogram
  // address stable across map rebalancing.
  mutable std::mutex phaseMu_;
  std::map<std::string,
           std::map<std::string,
                    std::map<std::string, std::unique_ptr<Histogram>>>>
      phaseHists_;
};

// RAII op-scope: counts the call + payload bytes at construction, records
// the latency at destruction, and counts an error when unwinding through
// an exception. One relaxed load when metrics are disabled.
class MetricsOp {
 public:
  MetricsOp(Metrics* metrics, MetricOp op, uint64_t bytes);
  ~MetricsOp();
  MetricsOp(const MetricsOp&) = delete;
  MetricsOp& operator=(const MetricsOp&) = delete;

 private:
  Metrics* metrics_;
  MetricOp op_;
  int64_t startUs_;
  int exceptionsAtEntry_{0};
};

}  // namespace tpucoll
