// SHA-256 and HMAC-SHA256 (FIPS 180-4 / RFC 2104), self-contained.
//
// Used by the transport's pre-shared-key connection handshake (mutual
// authentication; keeps rogue processes out of the mesh) and as the HKDF
// core that derives per-connection AEAD keys when wire encryption is
// enabled — see common/crypto.h for the ChaCha20-Poly1305 layer that
// covers the reference's TLS-tier confidentiality/integrity
// (gloo/transport/tcp/tls).
#pragma once

#include <array>
#include <cstddef>
#include <cstdint>
#include <string>

namespace tpucoll {

std::array<uint8_t, 32> sha256(const void* data, size_t len);

std::array<uint8_t, 32> hmacSha256(const void* key, size_t keyLen,
                                   const void* msg, size_t msgLen);

// Constant-time comparison (authentication tags must not leak via timing).
bool macEqual(const uint8_t* a, const uint8_t* b, size_t n);

// Fill `out` with kernel randomness (getrandom / urandom).
void randomBytes(void* out, size_t n);

}  // namespace tpucoll
