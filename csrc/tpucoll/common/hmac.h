// SHA-256 and HMAC-SHA256 (FIPS 180-4 / RFC 2104), self-contained.
//
// Used by the transport's pre-shared-key connection handshake — the
// equivalent of the reference's TLS tier (gloo/transport/tcp/tls) scoped
// to mutual authentication: it keeps rogue processes out of the mesh on a
// pod network. Payload encryption is out of scope (the image ships no
// crypto library headers; hand-rolling a cipher would be malpractice).
#pragma once

#include <array>
#include <cstddef>
#include <cstdint>
#include <string>

namespace tpucoll {

std::array<uint8_t, 32> sha256(const void* data, size_t len);

std::array<uint8_t, 32> hmacSha256(const void* key, size_t keyLen,
                                   const void* msg, size_t msgLen);

// Constant-time comparison (authentication tags must not leak via timing).
bool macEqual(const uint8_t* a, const uint8_t* b, size_t n);

// Fill `out` with kernel randomness (getrandom / urandom).
void randomBytes(void* out, size_t n);

}  // namespace tpucoll
