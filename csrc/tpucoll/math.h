// tpucoll L0 math: typed elementwise reductions over raw memory, including
// software float16 (IEEE binary16) and bfloat16.
//
// Replaces the reference's templated sum/product/max/min on raw pointers
// (gloo/math.h:15-75) and its float16 type (gloo/types.h:97-335). The
// collective schedules are untyped; they fetch a ReduceFn once per call and
// apply it to byte ranges, so the dispatch cost is off the hot loop.
#pragma once

#include <cstddef>
#include <cstdint>

#include "tpucoll/types.h"

namespace tpucoll {

// acc[i] = acc[i] OP in[i] for i in [0, n) elements.
using ReduceFn = void (*)(void* acc, const void* in, size_t n);

// Returns the builtin kernel for (dtype, op). Throws EnforceError for
// unsupported combos (e.g. product over float16 is supported; nothing is
// currently unsupported, but the check future-proofs custom dtypes).
ReduceFn getReduceFn(DataType dtype, ReduceOp op);

// IEEE 754 binary16 <-> float32 conversions (round-to-nearest-even on the
// way down). Used by the fp16 reduction kernels and exposed for tests.
float halfToFloat(uint16_t h);
uint16_t floatToHalf(float f);

// bfloat16 <-> float32 (round-to-nearest-even).
inline float bfloat16ToFloat(uint16_t b) {
  uint32_t u = static_cast<uint32_t>(b) << 16;
  float f;
  __builtin_memcpy(&f, &u, 4);
  return f;
}
uint16_t floatToBfloat16(float f);

// Bulk wire codecs (vectorized where the ISA allows): float32 <-> bfloat16
// streams for wire-compressed collectives.
void f32StreamToBf16(const float* src, uint16_t* dst, size_t n);
void bf16StreamToF32(const uint16_t* src, float* dst, size_t n);
// dst[i] += decode(src[i])
void bf16StreamAccumulate(float* dst, const uint16_t* src, size_t n);

inline uint64_t log2ceil(uint64_t n) {
  uint64_t r = 0;
  while ((uint64_t(1) << r) < n) {
    r++;
  }
  return r;
}

}  // namespace tpucoll
