// tpucoll L0 math: typed elementwise reductions over raw memory, including
// software float16 (IEEE binary16) and bfloat16.
//
// Replaces the reference's templated sum/product/max/min on raw pointers
// (gloo/math.h:15-75) and its float16 type (gloo/types.h:97-335). The
// collective schedules are untyped; they fetch a ReduceFn once per call and
// apply it to byte ranges, so the dispatch cost is off the hot loop.
#pragma once

#include <cstddef>
#include <cstdint>

#include "tpucoll/types.h"

namespace tpucoll {

// acc[i] = acc[i] OP in[i] for i in [0, n) elements.
using ReduceFn = void (*)(void* acc, const void* in, size_t n);

// Returns the builtin kernel for (dtype, op). Throws EnforceError for
// unsupported combos (e.g. product over float16 is supported; nothing is
// currently unsupported, but the check future-proofs custom dtypes).
ReduceFn getReduceFn(DataType dtype, ReduceOp op);

// IEEE 754 binary16 <-> float32 conversions (round-to-nearest-even on the
// way down). Used by the fp16 reduction kernels and exposed for tests.
float halfToFloat(uint16_t h);
uint16_t floatToHalf(float f);

// bfloat16 <-> float32 (round-to-nearest-even).
inline float bfloat16ToFloat(uint16_t b) {
  uint32_t u = static_cast<uint32_t>(b) << 16;
  float f;
  __builtin_memcpy(&f, &u, 4);
  return f;
}
uint16_t floatToBfloat16(float f);

// Bulk wire codecs (vectorized where the ISA allows): float32 <-> bfloat16
// streams for wire-compressed collectives.
void f32StreamToBf16(const float* src, uint16_t* dst, size_t n);
void bf16StreamToF32(const uint16_t* src, float* dst, size_t n);
// dst[i] += decode(src[i])
void bf16StreamAccumulate(float* dst, const uint16_t* src, size_t n);

// ---- int8 block-quantized wire codec (EQuARX-style, host plane) ----
//
// Stream layout: consecutive UNITS, one per block of `block` float32
// elements — a 4-byte little-endian float32 scale followed by `block`
// int8 codes; the final unit of a stream carries only the tail
// (n % block) codes, unpadded. Symmetric per-block quantization:
// scale = max|x| / 127, code = clip(round(x / scale), -127, 127);
// an all-zero (or all-subnormal-flushed) block stores scale 0 and zero
// codes. Decode is code * scale in float32. The scalar and AVX2 paths
// produce byte-identical streams (division, round-to-nearest-even, and
// max are computed with the same IEEE operations in both), so mixed-ISA
// groups keep wire consensus. Non-finite inputs are out of contract:
// a NaN/Inf element poisons its block's scale (documented in
// docs/errors.md with the rest of the precision contract).
constexpr size_t kQ8ScaleBytes = 4;
constexpr size_t kQ8MaxBlockElems = 2048;

// Block size in elements: TPUCOLL_Q8_BLOCK (strict count, [8, 2048],
// default 256), resolved once per process — both sides of every wire
// must agree, so the knob must match across ranks (docs/env.md).
size_t q8BlockElems();

inline size_t q8UnitBytes(size_t block) { return kQ8ScaleBytes + block; }

// Total wire bytes for an n-element stream at the given block size.
inline size_t q8WireBytes(size_t n, size_t block) {
  const size_t blocks = (n + block - 1) / block;
  return blocks * kQ8ScaleBytes + n;
}

void f32StreamToQ8(const float* src, uint8_t* dst, size_t n, size_t block);
void q8StreamToF32(const uint8_t* src, float* dst, size_t n, size_t block);
// dst[i] += decode(src unit stream); mul-then-add (no FMA) so the
// accumulated values are identical across the scalar and vector paths.
void q8StreamAccumulate(float* dst, const uint8_t* src, size_t n,
                        size_t block);

// ---- int4 block-quantized wire codec (packed nibbles) ----
//
// Same stream shape as q8 but at half the code width: consecutive UNITS
// of [4-byte little-endian float32 scale][ceil(block/2) bytes of packed
// nibbles]; the final unit carries only the tail (n % block) codes.
// Element i of a unit lives in byte i/2 — even elements in the low
// nibble, odd in the high; a dangling high nibble at an odd tail is
// written as 0 (deterministic bytes, never decoded). Codes are biased:
// nibble = clip(round(x / scale), -7, 7) + 8 with scale = max|x| / 7,
// so the stored range is [1, 15] and decode is (nibble - 8) * scale.
// Scalar and AVX2 paths are byte-identical (IEEE division +
// round-to-nearest-even in both; the nibble packing is integer-exact).
// ~8x fewer wire bytes than float32 at ~0.9 decimal digits per block
// (|x - decode(x)| <= max|block| / 14 per element per hop).
constexpr size_t kQ4ScaleBytes = 4;
constexpr size_t kQ4MaxBlockElems = 2048;

// Block size in elements: TPUCOLL_Q4_BLOCK (strict count, [8, 2048],
// default 256), resolved once per process; must match across ranks
// (both ends of every wire parse the same unit size, docs/env.md).
size_t q4BlockElems();

inline size_t q4UnitBytes(size_t block) {
  return kQ4ScaleBytes + (block + 1) / 2;
}

// Total wire bytes for an n-element stream at the given block size.
inline size_t q4WireBytes(size_t n, size_t block) {
  if (n == 0) {
    return 0;
  }
  const size_t full = n / block;
  const size_t tail = n % block;
  return full * q4UnitBytes(block) + (tail != 0 ? q4UnitBytes(tail) : 0);
}

void f32StreamToQ4(const float* src, uint8_t* dst, size_t n, size_t block);
void q4StreamToF32(const uint8_t* src, float* dst, size_t n, size_t block);
// dst[i] += decode(src unit stream); mul-then-add, like q8.
void q4StreamAccumulate(float* dst, const uint8_t* src, size_t n,
                        size_t block);

inline uint64_t log2ceil(uint64_t n) {
  uint64_t r = 0;
  while ((uint64_t(1) << r) < n) {
    r++;
  }
  return r;
}

}  // namespace tpucoll
