// C API for the tpucoll core, consumed by the gloo_tpu Python package over
// ctypes (the repo's equivalent of a pybind layer, using only the stable C
// ABI). Conventions:
//  - handles are opaque pointers; *_free releases them;
//  - functions return 0 on success or a TC_ERR_* code, with the message
//    available from tc_last_error() (thread-local);
//  - blocking calls release the GIL implicitly because ctypes drops it for
//    foreign calls.
#include <algorithm>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "tpucoll/async/engine.h"
#include "tpucoll/boot/boot.h"
#include "tpucoll/collectives/collectives.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/collectives/wire_codec.h"
#include "tpucoll/common/codec_pool.h"
#include "tpucoll/common/debug.h"
#include "tpucoll/context.h"
#include "tpucoll/fault/fault.h"
#include "tpucoll/transport/loop_uring.h"
#include "tpucoll/transport/wire.h"
#include "tpucoll/common/crypto.h"
#include "tpucoll/common/json.h"
#include "tpucoll/common/keyring.h"
#include "tpucoll/elastic/elastic.h"
#include "tpucoll/rendezvous/file_store.h"
#include "tpucoll/rendezvous/hash_store.h"
#include "tpucoll/rendezvous/store.h"
#include "tpucoll/rendezvous/tcp_store.h"
#include "tpucoll/transport/device.h"
#include "tpucoll/schedule/generators.h"
#include "tpucoll/schedule/interpreter.h"
#include "tpucoll/schedule/ir.h"
#include "tpucoll/schedule/verifier.h"
#include "tpucoll/tuning/tuner.h"
#include "tpucoll/tuning/tuning_table.h"

namespace {

using tpucoll::Context;
using tpucoll::DataType;
using tpucoll::ReduceOp;
using tpucoll::Store;
using tpucoll::transport::Device;
using tpucoll::transport::UnboundBuffer;

thread_local std::string g_lastError;

constexpr int TC_OK = 0;
constexpr int TC_ERR = 1;
constexpr int TC_ERR_TIMEOUT = 2;
constexpr int TC_ERR_IO = 3;
constexpr int TC_ERR_ABORTED = 4;

template <typename Fn>
int wrap(Fn&& fn) {
  try {
    fn();
    return TC_OK;
  } catch (const tpucoll::TimeoutException& e) {
    g_lastError = e.what();
    return TC_ERR_TIMEOUT;
  } catch (const tpucoll::IoException& e) {
    g_lastError = e.what();
    return TC_ERR_IO;
  } catch (const tpucoll::AbortedException& e) {
    g_lastError = e.what();
    return TC_ERR_ABORTED;
  } catch (const std::exception& e) {
    g_lastError = e.what();
    return TC_ERR;
  } catch (...) {
    g_lastError = "unknown error";
    return TC_ERR;
  }
}


// Handle-factory boundary: nullptr + tc_last_error on failure — the
// handle-returning mirror of wrap().
template <typename Fn>
void* wrapPtr(Fn&& fn) {
  try {
    return fn();
  } catch (const std::exception& e) {
    g_lastError = e.what();
    return nullptr;
  } catch (...) {
    g_lastError = "unknown error";
    return nullptr;
  }
}

// Value-returning boundary: `fallback` + tc_last_error on failure, for
// introspection entries whose return channel has no error code.
template <typename T, typename Fn>
T wrapVal(T fallback, Fn&& fn) {
  try {
    return fn();
  } catch (const std::exception& e) {
    g_lastError = e.what();
    return fallback;
  } catch (...) {
    g_lastError = "unknown error";
    return fallback;
  }
}

// Void boundary (teardown/config entries): failures land in
// tc_last_error and are swallowed — a free/abort path has no error
// channel, and an exception crossing the C ABI aborts the process.
template <typename Fn>
void wrapVoid(Fn&& fn) {
  try {
    fn();
  } catch (const std::exception& e) {
    g_lastError = e.what();
  } catch (...) {
    g_lastError = "unknown error";
  }
}

std::chrono::milliseconds ms(int64_t v) {
  return std::chrono::milliseconds(v);
}

using StoreHandle = std::shared_ptr<Store>;
using DeviceHandle = std::shared_ptr<Device>;

StoreHandle* asStore(void* h) { return static_cast<StoreHandle*>(h); }
DeviceHandle* asDevice(void* h) { return static_cast<DeviceHandle*>(h); }
Context* asContext(void* h) { return static_cast<Context*>(h); }
UnboundBuffer* asBuffer(void* h) { return static_cast<UnboundBuffer*>(h); }
tpucoll::elastic::ElasticAgent* asElastic(void* h) {
  return static_cast<tpucoll::elastic::ElasticAgent*>(h);
}

template <typename Opts>
void fillCommon(Opts& opts, Context* ctx, uint32_t tag, int64_t timeoutMs) {
  opts.context = ctx;
  opts.tag = tag;
  opts.timeout = ms(timeoutMs);
}

std::vector<size_t> countsVec(const size_t* counts, int size) {
  return std::vector<size_t>(counts, counts + size);
}

int copyOut(const std::string& s, uint8_t** out, size_t* outLen) {
  *outLen = s.size();
  *out = static_cast<uint8_t*>(malloc(s.size()));
  if (s.empty()) {
    return TC_OK;  // malloc(0) may be NULL; memcpy(NULL, ..., 0) is UB
  }
  if (*out == nullptr) {
    throw std::bad_alloc();
  }
  std::memcpy(*out, s.data(), s.size());
  return TC_OK;
}

// p2p wait instrumentation: span against the buffer's tracer when the
// owning context set one (standalone transport contexts have none).
tpucoll::Tracer::Span maybeSpan(UnboundBuffer* buf, const char* name) {
  tpucoll::Tracer* tracer = buf->transportContext()->tracer();
  if (tracer == nullptr) {
    return tpucoll::Tracer::Span();
  }
  return tracer->span(name, buf->size());
}

tpucoll::Metrics* bufMetrics(UnboundBuffer* buf) {
  return buf->transportContext()->metrics();
}

tpucoll::FlightRecorder* bufFlightrec(UnboundBuffer* buf) {
  return buf->transportContext()->flightrec();
}

// Flight-recorder opcodes for user-facing p2p ops.
const char kFrSend[] = "send";
const char kFrRecv[] = "recv";
const char kFrPut[] = "put";
const char kFrGet[] = "get";

// Flight-recorder p2p completion bookkeeping: each buffer's posted ops'
// ring seqs, per direction, so a wait completes exactly an op posted on
// THE BUFFER IT WAITED ON — never an older op pending on a different
// (possibly hung) buffer. Waits on one buffer count completions rather
// than naming ops, so within a buffer the oldest post of the direction
// is the honest match. A mutex here is fine: this is the Python-facing
// p2p path (a ctypes round-trip per call), not the collective hot path —
// the recorder itself stays lock-free.
struct FrPending {
  std::deque<uint64_t> send;  // send + put posts
  std::deque<uint64_t> recv;  // recv + get posts
};
std::mutex g_frPendingMu;
std::unordered_map<void*, FrPending> g_frPending;

void frPush(void* buf, bool isSend, uint64_t seq) {
  std::lock_guard<std::mutex> guard(g_frPendingMu);
  FrPending& p = g_frPending[buf];
  (isSend ? p.send : p.recv).push_back(seq);
}

uint64_t frPop(void* buf, bool isSend) {
  std::lock_guard<std::mutex> guard(g_frPendingMu);
  auto it = g_frPending.find(buf);
  if (it == g_frPending.end()) {
    return tpucoll::FlightRecorder::kNoSeq;
  }
  std::deque<uint64_t>& q = isSend ? it->second.send : it->second.recv;
  if (q.empty()) {
    return tpucoll::FlightRecorder::kNoSeq;
  }
  const uint64_t seq = q.front();
  q.pop_front();
  return seq;
}

void frErase(void* buf) {
  std::lock_guard<std::mutex> guard(g_frPendingMu);
  g_frPending.erase(buf);
}

// ---- async engine plumbing (async/engine.h) ----

tpucoll::async::Engine* asEngine(void* h) {
  return static_cast<tpucoll::async::Engine*>(h);
}

using WorkHandle = std::shared_ptr<tpucoll::async::Work>;

WorkHandle* asWork(void* h) { return static_cast<WorkHandle*>(h); }

// Heap-wrap a submitted Work as an opaque handle (NULL + tc_last_error
// when submission itself failed, e.g. after shutdown).
template <typename Fn>
void* submitWork(Fn&& fn) {
  try {
    return new WorkHandle(fn());
  } catch (const std::exception& e) {
    g_lastError = e.what();
    return nullptr;
  }
}

// tc_work_wait timeout resolution: <= 0 means "no deadline"; clamp
// everything to ~24 days so wait_for's nanosecond conversion can never
// overflow (an overflowed deadline lands in the past and reads as an
// instant spurious timeout).
std::chrono::milliseconds workTimeout(int64_t timeoutMs) {
  constexpr int64_t kMaxMs = int64_t(1) << 31;
  return ms(timeoutMs > 0 && timeoutMs < kMaxMs ? timeoutMs : kMaxMs);
}

}  // namespace

extern "C" {

const char* tc_last_error() { return g_lastError.c_str(); }

// ---- stores ----

void* tc_hash_store_new() {
  return wrapPtr([&]() -> void* {
    return new StoreHandle(std::make_shared<tpucoll::HashStore>());
  });
}

void* tc_file_store_new(const char* path) {
  return wrapPtr([&]() -> void* {
    return new StoreHandle(std::make_shared<tpucoll::FileStore>(path));
  });
}

void* tc_prefix_store_new(void* base, const char* prefix) {
  return wrapPtr([&]() -> void* {
    return new StoreHandle(
        std::make_shared<tpucoll::PrefixStore>(*asStore(base), prefix));
  });
}

void tc_store_free(void* store) {
  wrapVoid([&] { delete asStore(store); });
}

void* tc_tcp_store_server_new(const char* host, uint16_t port) {
  return wrapPtr([&]() -> void* {
    return new tpucoll::TcpStoreServer(host, port);
  });
}

uint16_t tc_tcp_store_server_port(void* server) {
  return wrapVal<uint16_t>(0, [&] {
    return static_cast<tpucoll::TcpStoreServer*>(server)->port();
  });
}

void tc_tcp_store_server_free(void* server) {
  wrapVoid([&] {
    delete static_cast<tpucoll::TcpStoreServer*>(server);
  });
}

void* tc_tcp_store_new(const char* host, uint16_t port) {
  return wrapPtr([&]() -> void* {
    return new StoreHandle(
        std::make_shared<tpucoll::TcpStore>(host, port));
  });
}

int tc_store_set(void* store, const char* key, const uint8_t* data,
                 size_t len) {
  return wrap([&] {
    (*asStore(store))->set(key, Store::Buf(data, data + len));
  });
}

int tc_store_get(void* store, const char* key, int64_t timeoutMs,
                 uint8_t** out, size_t* outLen) {
  return wrap([&] {
    auto buf = (*asStore(store))->get(key, ms(timeoutMs));
    *outLen = buf.size();
    *out = static_cast<uint8_t*>(malloc(buf.size()));
    if (*out == nullptr && !buf.empty()) {
      throw std::bad_alloc();
    }
    std::memcpy(*out, buf.data(), buf.size());
  });
}

void tc_buf_free(uint8_t* buf) { wrapVoid([&] { free(buf); }); }

int tc_store_add(void* store, const char* key, int64_t delta,
                 int64_t* result) {
  return wrap([&] { *result = (*asStore(store))->add(key, delta); });
}

// Remove a key; *deleted = 1 when it existed. Namespace hygiene (lease
// reaping, retired rebuild/epoch namespaces — docs/rendezvous.md).
int tc_store_delete(void* store, const char* key, int* deleted) {
  return wrap([&] {
    *deleted = (*asStore(store))->deleteKey(key) ? 1 : 0;
  });
}

// Keys currently present under `prefix`, as a JSON array of strings
// (malloc'd; free with tc_buf_free). Snapshot semantics only.
int tc_store_list(void* store, const char* prefix, uint8_t** out,
                  size_t* outLen) {
  return wrap([&] {
    std::ostringstream json;
    json << "[";
    bool first = true;
    for (const auto& key : (*asStore(store))->listKeys(prefix)) {
      json << (first ? "" : ",");
      tpucoll::appendJsonString(json, key);
      first = false;
    }
    json << "]";
    copyOut(json.str(), out, outLen);
  });
}

// ---- device / context ----

void* tc_device_new(const char* hostname, uint16_t port,
                    const char* authKey, int encrypt, const char* iface,
                    int busyPoll, const char* engine, const char* keyring) {
  return wrapPtr([&]() -> void* {
    tpucoll::transport::DeviceAttr attr;
    if (hostname != nullptr && hostname[0] != '\0') {
      attr.hostname = hostname;
    }
    if (iface != nullptr) {
      attr.iface = iface;
    }
    attr.port = port;
    if (authKey != nullptr) {
      attr.authKey = authKey;
    }
    if (keyring != nullptr) {
      attr.keyring = keyring;
    }
    attr.encrypt = encrypt != 0;
    attr.busyPoll = busyPoll != 0;
    if (engine != nullptr) {
      attr.engine = engine;
    }
    return new DeviceHandle(std::make_shared<Device>(attr));
  });
}

// Launcher-side helper: derive rank `rank`'s serialized keyring from the
// root secret (common/keyring.h threat model). The returned buffer is a
// NUL-terminated string; free with tc_buf_free.
int tc_derive_keyring(const char* rootKey, int rank, int size,
                      uint8_t** out) {
  return wrap([&] {
    const std::string s =
        tpucoll::Keyring::derive(rootKey != nullptr ? rootKey : "", rank,
                                 size)
            .serialize();
    *out = static_cast<uint8_t*>(malloc(s.size() + 1));
    if (*out == nullptr) {
      throw std::bad_alloc();
    }
    std::memcpy(*out, s.data(), s.size() + 1);
  });
}

void tc_device_free(void* dev) {
  wrapVoid([&] { delete asDevice(dev); });
}

// Event-engine submission counters (loop.h Loop::EngineStats): uring
// reports io_uring_enter syscalls / SQEs submitted / CQEs drained since
// device creation; epoll reports zeros. sqes > enters is the batched-
// submission evidence (readiness engines pay >=1 syscall per I/O op).
void tc_device_engine_stats(void* dev, uint64_t* enters, uint64_t* sqes,
                            uint64_t* cqes) {
  wrapVoid([&] {
    const auto s = (*asDevice(dev))->loop()->engineStats();
    *enters = s.enters;
    *sqes = s.sqes;
    *cqes = s.cqes;
  });
}

// Engine introspection: lets callers pick engine="uring" only where the
// kernel/sandbox supports it (an explicit uring request throws otherwise).
// AEAD bulk tier this process dispatches to (crypto.h aeadIsaTier):
// 2 = fused AVX-512, 1 = AVX2, 0 = scalar.
int tc_crypto_isa_tier() {
  return wrapVal(0, [&] { return tpucoll::aeadIsaTier(); });
}

int tc_uring_available() {
  return wrapVal(0, [&] {
    return tpucoll::transport::uringAvailable() ? 1 : 0;
  });
}

// Structured connect diagnostics hook (reference: tcp/debug_data.h +
// DebugLogger). The callback runs on connecting threads; pass nullptr to
// clear.
typedef void (*tc_connect_logger_fn)(int selfRank, int peerRank,
                                     const char* remote, const char* local,
                                     int attempt, int ok, int willRetry,
                                     const char* error);

void tc_set_connect_debug_logger(tc_connect_logger_fn cb) {
  wrapVoid([&] {
    if (cb == nullptr) {
      tpucoll::setConnectDebugLogger(nullptr);
      return;
    }
    tpucoll::setConnectDebugLogger(
        [cb](const tpucoll::ConnectDebugData& d) {
          cb(d.selfRank, d.peerRank, d.remote.c_str(), d.local.c_str(),
             d.attempt, d.ok ? 1 : 0, d.willRetry ? 1 : 0,
             d.error.c_str());
        });
  });
}

void* tc_context_new(int rank, int size) {
  return wrapPtr([&]() -> void* { return new Context(rank, size); });
}

void tc_context_set_timeout(void* ctx, int64_t timeoutMs) {
  wrapVoid([&] { asContext(ctx)->setTimeout(ms(timeoutMs)); });
}

int tc_context_connect(void* ctx, void* store, void* device) {
  return wrap([&] {
    asContext(ctx)->connectFullMesh(*asStore(store), *asDevice(device));
  });
}

int tc_context_fork(void* ctx, void* parent, uint32_t tag) {
  return wrap([&] { asContext(ctx)->forkFrom(*asContext(parent), tag); });
}

// ---- process-group subsystem (group/): topology + communicator split --

int tc_context_rank(void* ctx) {
  return wrapVal(-1, [&] { return asContext(ctx)->rank(); });
}

int tc_context_size(void* ctx) {
  return wrapVal(-1, [&] { return asContext(ctx)->size(); });
}

// Host-fingerprint override for topology discovery; must run before
// tc_context_connect (throws afterwards). Empty/NULL restores the
// TPUCOLL_HOST_ID / hostname+boot-id default.
int tc_context_set_host_id(void* ctx, const char* hostId) {
  return wrap([&] {
    asContext(ctx)->setHostId(hostId != nullptr ? hostId : "");
  });
}

// Discovered topology as JSON ({"rank","host_index","local_rank",
// "local_size","leader","is_leader","n_hosts","non_flat","hosts":[...]});
// malloc'd, free with tc_buf_free. Errors when the context never
// discovered one (not connected).
int tc_topology_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    auto topo = asContext(ctx)->topology();
    TC_ENFORCE(topo != nullptr, "tc_topology_json: no topology "
               "(context not connected)");
    copyOut(topo->toJson(), out, outLen);
  });
}

// Group tag namespace of this communicator ("" for a root context);
// malloc'd, free with tc_buf_free.
int tc_context_group_tag(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] { copyOut(asContext(ctx)->groupTag(), out, outLen); });
}

// Communicator split (MPI_Comm_split semantics): a COLLECTIVE over the
// parent — every rank calls concurrently with the same `tag`
// (concurrent splits need distinct tags). On success *out is the new
// context handle (owned by the caller; tc_context_free it), or NULL
// when color < 0 (this rank opted out). See Context::split.
int tc_split(void* ctx, int color, int key, uint32_t tag, void** out) {
  return wrap([&] {
    *out = asContext(ctx)->split(color, key, tag).release();
  });
}

// split(color = host index, key = rank): the intra-host communicator.
int tc_split_by_host(void* ctx, uint32_t tag, void** out) {
  return wrap([&] {
    *out = asContext(ctx)->splitByHost(tag).release();
  });
}

int tc_context_close(void* ctx) {
  return wrap([&] { asContext(ctx)->close(); });
}

void tc_context_free(void* ctx) {
  wrapVoid([&] { delete asContext(ctx); });
}

uint64_t tc_next_slot(void* ctx, uint32_t num) {
  return wrapVal<uint64_t>(0, [&] {
    return asContext(ctx)->nextSlot(num);
  });
}

void tc_debug_dump(void* ctx) {
  wrapVoid([&] { asContext(ctx)->transport()->debugDump(); });
}

void tc_context_shm_stats(void* ctx, uint64_t* txBytes, uint64_t* rxBytes,
                          int* activePairs) {
  wrapVoid([&] {
    asContext(ctx)->transport()->shmStats(txBytes, rxBytes, activePairs);
  });
}

void tc_trace_start(void* ctx) {
  wrapVoid([&] { asContext(ctx)->tracer().start(); });
}

void tc_trace_stop(void* ctx) {
  wrapVoid([&] { asContext(ctx)->tracer().stop(); });
}

// Returns a malloc'd JSON string (Chrome trace-event format); caller frees
// with tc_buf_free.
int tc_trace_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    Context* c = asContext(ctx);
    std::string json = c->tracer().toJson(c->rank());
    *outLen = json.size();
    *out = static_cast<uint8_t*>(malloc(json.size()));
    if (*out == nullptr && !json.empty()) {
      throw std::bad_alloc();
    }
    std::memcpy(*out, json.data(), json.size());
  });
}

// ---- metrics ----

void tc_metrics_enable(void* ctx, int on) {
  wrapVoid([&] { asContext(ctx)->metrics().setEnabled(on != 0); });
}

int tc_metrics_enabled(void* ctx) {
  return wrapVal(0, [&] {
    return asContext(ctx)->metrics().enabled() ? 1 : 0;
  });
}

// Straggler watchdog threshold; <= 0 disables. Overrides the
// TPUCOLL_WATCHDOG_MS environment default for this context.
void tc_metrics_set_watchdog(void* ctx, int64_t thresholdMs) {
  wrapVoid([&] {
    asContext(ctx)->metrics().setWatchdogUs(thresholdMs * 1000);
  });
}

// Returns a malloc'd JSON object (see Metrics::toJson); caller frees with
// tc_buf_free. drain != 0 resets counters/histograms after the snapshot.
int tc_metrics_json(void* ctx, int drain, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    std::string json = asContext(ctx)->metricsJson(drain != 0);
    *outLen = json.size();
    *out = static_cast<uint8_t*>(malloc(json.size()));
    if (*out == nullptr && !json.empty()) {
      throw std::bad_alloc();
    }
    std::memcpy(*out, json.data(), json.size());
  });
}

// ---- flight recorder (common/flightrec.h) ----

// Always-on flight-recorder ring as a JSON document (docs/flightrec.md);
// malloc'd, free with tc_buf_free. Never drains: the ring keeps rolling.
int tc_flightrec_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    std::string json = asContext(ctx)->flightrec().toJson();
    *outLen = json.size();
    *out = static_cast<uint8_t*>(malloc(json.size()));
    if (*out == nullptr && !json.empty()) {
      throw std::bad_alloc();
    }
    std::memcpy(*out, json.data(), json.size());
  });
}

// Explicit dump to `path` (the Python-side trigger; automatic triggers
// write to TPUCOLL_FLIGHTREC_DIR on their own).
int tc_flightrec_dump(void* ctx, const char* path) {
  return wrap([&] {
    TC_ENFORCE(path != nullptr && path[0] != '\0',
               "tc_flightrec_dump: empty path");
    TC_ENFORCE(asContext(ctx)->flightrec().dumpToFile(path, "explicit", -1),
               "tc_flightrec_dump: cannot write ", path);
  });
}

// Next per-context collective sequence number (== ops recorded so far).
uint64_t tc_flightrec_seq(void* ctx) {
  return wrapVal<uint64_t>(0, [&] {
    return asContext(ctx)->flightrec().nextSeq();
  });
}

// Opt-in fatal-signal dumping (SIGSEGV/SIGABRT/SIGBUS/SIGFPE/SIGILL/
// SIGTERM -> dump every live recorder to TPUCOLL_FLIGHTREC_DIR, then
// re-raise). Also installable via TPUCOLL_FLIGHTREC_SIGNALS=1.
void tc_flightrec_install_signal_handler() {
  wrapVoid([&] { tpucoll::FlightRecorder::installSignalHandler(); });
}

// ---- phase-level collective profiler (common/profile.h) ----

// Per-op phase-breakdown ring as JSON (docs/profiling.md); non-draining
// like the flight recorder. Malloc'd, free with tc_buf_free.
int tc_profile_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    copyOut(asContext(ctx)->profileJson(), out, outLen);
  });
}

// Runtime override of the TPUCOLL_PROFILE gate for this context.
void tc_profile_enable(void* ctx, int on) {
  wrapVoid([&] { asContext(ctx)->profiler().setEnabled(on != 0); });
}

int tc_profile_enabled(void* ctx) {
  return wrapVal(0, [&] {
    return asContext(ctx)->profiler().enabled() ? 1 : 0;
  });
}

// ---- causal span recorder (common/span.h) ----

// Per-op step/phase-instance span ring as JSON (docs/critpath.md);
// non-draining like the profiler ring. Malloc'd, free with tc_buf_free.
int tc_spans_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    copyOut(asContext(ctx)->spansJson(), out, outLen);
  });
}

// Runtime override of the TPUCOLL_SPANS gate for this context.
void tc_spans_enable(void* ctx, int on) {
  wrapVoid([&] { asContext(ctx)->spans().setEnabled(on != 0); });
}

int tc_spans_enabled(void* ctx) {
  return wrapVal(0, [&] {
    return asContext(ctx)->spans().enabled() ? 1 : 0;
  });
}

// ---- in-band fleet observability plane (common/fleetobs.h) ----

// Start the hierarchical telemetry fold for this rank's topology role
// (docs/fleet.md): members push fixed-size reports to their host leader,
// leaders pre-aggregate and relay to rank 0, which runs the anomaly
// detectors. Requires a connected context; under TPUCOLL_FLEETOBS=0 the
// start is a no-op and tc_fleetobs_running stays 0.
int tc_fleetobs_start(void* ctx) {
  return wrap([&] { asContext(ctx)->fleetObsStart(); });
}

// Stop and join the aggregation thread. Safe when never started; also
// runs automatically at context close/destruction.
int tc_fleetobs_stop(void* ctx) {
  return wrap([&] { asContext(ctx)->fleetObsStop(); });
}

int tc_fleetobs_running(void* ctx) {
  return wrapVal(0, [&] {
    return asContext(ctx)->fleetObsRunning() ? 1 : 0;
  });
}

// Merge `auxJson` (a JSON object — e.g. the Python elastic agent's
// status) into this rank's next report as its "aux" field. Validated
// here so malformed JSON fails this call, never the aggregation thread.
int tc_fleetobs_set_aux(void* ctx, const char* auxJson) {
  return wrap([&] {
    asContext(ctx)->fleetObsSetAux(auxJson != nullptr ? auxJson : "");
  });
}

// Latest merged fleet document (rank 0; a role stub elsewhere) — the
// telemetry endpoint's /fleet payload. Malloc'd, free with tc_buf_free.
int tc_fleet_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] { copyOut(asContext(ctx)->fleetJson(), out, outLen); });
}

// ---- bootstrap plane (boot/, docs/bootstrap.md) ----

// Store-choreography cost model for `bench.py --bootstrap-sweep`: run
// `nranks` in-process rank threads through ONE bootstrap rendezvous
// over a shared FileStore rooted at `storePath` (which must be fresh
// per call — the key schema is fixed). lazy != 0 runs the leader-
// relayed choreography (boot::relayedRendezvous) with `ranksPerHost`
// ranks per simulated host and `shards` key shards; lazy == 0 runs the
// full-mesh publish/multiGet-all choreography with an O(N)-sized
// synthetic pair-id table per rank. `payloadBytes` sizes the lazy arm's
// per-rank address payload. Writes a JSON summary — wall_ms plus
// aggregate/max per-phase stats — to *out (malloc'd, free with
// tc_buf_free). This measures the STORE protocol, not sockets: the
// point of the sweep is the O(N^2) -> O(hosts^2 + N) curve.
int tc_boot_rendezvous_bench(const char* storePath, int nranks,
                             int ranksPerHost, int shards, int lazy,
                             int payloadBytes, int64_t timeoutMs,
                             uint8_t** out, size_t* outLen) {
  return wrap([&] {
    TC_ENFORCE(storePath != nullptr && storePath[0] != '\0',
               "tc_boot_rendezvous_bench: empty store path");
    TC_ENFORCE(nranks > 0 && nranks <= 4096,
               "tc_boot_rendezvous_bench: nranks out of range");
    TC_ENFORCE(ranksPerHost > 0, "tc_boot_rendezvous_bench: ranksPerHost "
               "must be positive");
    TC_ENFORCE(payloadBytes >= 0 && payloadBytes <= (1 << 20),
               "tc_boot_rendezvous_bench: payloadBytes out of range");
    const auto timeout = ms(timeoutMs > 0 ? timeoutMs : 120000);
    std::vector<tpucoll::boot::RendezvousStats> stats(nranks);
    std::vector<std::string> errors(nranks);
    std::vector<int64_t> wallUs(nranks, 0);
    std::vector<std::thread> threads;
    threads.reserve(nranks);
    for (int r = 0; r < nranks; r++) {
      threads.emplace_back([&, r] {
        try {
          // Every "rank" opens its own FileStore client over the shared
          // directory, exactly like separate processes would.
          tpucoll::FileStore store(storePath);
          const std::string fp =
              "simhost-" + std::to_string(r / ranksPerHost);
          Store::Buf payload(static_cast<size_t>(payloadBytes));
          for (size_t i = 0; i < payload.size(); i++) {
            payload[i] = static_cast<uint8_t>((r + static_cast<int>(i)) & 0xff);
          }
          const auto t0 = std::chrono::steady_clock::now();
          if (lazy != 0) {
            tpucoll::boot::relayedRendezvous(store, r, nranks, fp, payload,
                                             shards, timeout, &stats[r]);
          } else {
            tpucoll::boot::fullMeshRendezvousSim(store, r, nranks, fp,
                                                 payload, timeout, &stats[r]);
          }
          wallUs[r] = std::chrono::duration_cast<std::chrono::microseconds>(
                          std::chrono::steady_clock::now() - t0)
                          .count();
        } catch (const std::exception& e) {
          errors[r] = e.what();
        }
      });
    }
    for (auto& t : threads) {
      t.join();
    }
    for (int r = 0; r < nranks; r++) {
      TC_ENFORCE(errors[r].empty(), "bootstrap bench rank ", r, ": ",
                 errors[r]);
    }
    int64_t maxWallUs = 0;
    int64_t maxPublishUs = 0;
    int64_t maxTopoUs = 0;
    int64_t maxExchangeUs = 0;
    int64_t totalOps = 0;
    int64_t totalBytes = 0;
    for (int r = 0; r < nranks; r++) {
      maxWallUs = std::max(maxWallUs, wallUs[r]);
      maxPublishUs = std::max(maxPublishUs, stats[r].publishUs);
      maxTopoUs = std::max(maxTopoUs, stats[r].topoUs);
      maxExchangeUs = std::max(maxExchangeUs, stats[r].exchangeUs);
      totalOps += stats[r].storeOps;
      totalBytes += stats[r].storeBytes;
    }
    std::ostringstream json;
    json << "{\"nranks\":" << nranks << ",\"ranks_per_host\":" << ranksPerHost
         << ",\"lazy\":" << (lazy != 0 ? "true" : "false")
         << ",\"shards\":" << shards << ",\"wall_ms\":"
         << static_cast<double>(maxWallUs) / 1000.0
         << ",\"publish_ms\":" << static_cast<double>(maxPublishUs) / 1000.0
         << ",\"topo_ms\":" << static_cast<double>(maxTopoUs) / 1000.0
         << ",\"exchange_ms\":"
         << static_cast<double>(maxExchangeUs) / 1000.0
         << ",\"store_ops\":" << totalOps
         << ",\"store_bytes\":" << totalBytes << "}";
    copyOut(json.str(), out, outLen);
  });
}

// ---- collective autotuning plane (tuning/) ----

// Run the tuner sweep (a COLLECTIVE — every rank must call concurrently
// with identical arguments), elect + publish + install rank 0's table,
// and return the installed table's JSON (malloc'd; free with
// tc_buf_free). See tuning/tuner.h.
int tc_tune(void* ctx, size_t minBytes, size_t maxBytes, int iters,
            int warmup, uint32_t tag, int64_t timeoutMs, uint8_t** out,
            size_t* outLen) {
  return wrap([&] {
    tpucoll::tuning::TunerOptions opts;
    opts.minBytes = minBytes;
    opts.maxBytes = maxBytes;
    opts.iters = iters;
    opts.warmup = warmup;
    opts.tag = tag;
    opts.timeout = ms(timeoutMs);
    auto table = tpucoll::tuning::tune(asContext(ctx), opts);
    copyOut(table->toJson(), out, outLen);
  });
}

// Install a serialized table on THIS rank only (callers own the
// all-ranks-identical contract; tc_tune handles it automatically). NULL
// or empty JSON clears the installed table, restoring fallback dispatch.
int tc_tuning_install(void* ctx, const char* json) {
  return wrap([&] {
    if (json == nullptr || json[0] == '\0') {
      asContext(ctx)->setTuningTable(nullptr);
      return;
    }
    asContext(ctx)->setTuningTable(
        std::make_shared<const tpucoll::tuning::TuningTable>(
            tpucoll::tuning::TuningTable::fromJson(json)));
  });
}

// Serialized installed table (empty string when none is installed);
// malloc'd, free with tc_buf_free.
int tc_tuning_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    auto table = asContext(ctx)->tuningTable();
    copyOut(table != nullptr ? table->toJson() : std::string(), out,
            outLen);
  });
}

// ---- collective schedule plane (schedule/) ----

// Install a serialized schedule table on THIS rank only (the
// all-ranks-identical contract is the caller's, exactly like
// tc_tuning_install). Every schedule matching the context's world size
// is verified AND resolved before the swap — malformed JSON or a
// semantically invalid schedule fails the call and leaves the previous
// plane (and the plan cache) untouched. NULL or empty JSON clears the
// plane, restoring native dispatch.
int tc_schedule_install(void* ctx, const char* json) {
  return wrap([&] {
    if (json == nullptr || json[0] == '\0') {
      asContext(ctx)->setScheduleTable(nullptr);
      return;
    }
    asContext(ctx)->setScheduleTable(
        std::make_shared<const tpucoll::schedule::ScheduleTable>(
            tpucoll::schedule::ScheduleTable::fromJson(json)));
  });
}

// Serialized installed schedule table (empty string when none);
// malloc'd, free with tc_buf_free.
int tc_schedule_json(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    auto inst = asContext(ctx)->schedules();
    copyOut(inst != nullptr ? inst->table->toJson() : std::string(), out,
            outLen);
  });
}

// Installed schedule summaries as a JSON array:
//   [{"name","collective","world_size","steps","resolved"}]
// "resolved" is 1 when the schedule matches this context's world (its
// elections can fire), 0 when it is carried for round-trip only.
int tc_schedule_list(void* ctx, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    auto inst = asContext(ctx)->schedules();
    std::ostringstream os;
    os << "[";
    if (inst != nullptr) {
      bool first = true;
      for (const auto& s : inst->table->schedules()) {
        if (!first) {
          os << ",";
        }
        first = false;
        os << "{\"name\":";
        tpucoll::appendJsonString(os, s.name);
        os << ",\"collective\":\""
           << tpucoll::schedule::collectiveName(s.collective)
           << "\",\"world_size\":" << s.worldSize
           << ",\"steps\":" << s.steps.size() << ",\"resolved\":"
           << (inst->programs.count(s.name) != 0 ? 1 : 0) << "}";
      }
    }
    os << "]";
    copyOut(os.str(), out, outLen);
  });
}

// One installed schedule in full, serialized as a single-schedule table
// (same interchange JSON as tc_schedule_json). TC_ERR for unknown names.
int tc_schedule_describe(void* ctx, const char* name, uint8_t** out,
                         size_t* outLen) {
  return wrap([&] {
    TC_ENFORCE(name != nullptr && name[0] != '\0',
               "tc_schedule_describe: empty name");
    auto inst = asContext(ctx)->schedules();
    const tpucoll::schedule::Schedule* s =
        inst != nullptr ? inst->table->find(name) : nullptr;
    TC_ENFORCE(s != nullptr, "tc_schedule_describe: no installed ",
               "schedule named \"", name, "\"");
    tpucoll::schedule::ScheduleTable one;
    one.add(*s);
    copyOut(one.toJson(), out, outLen);
  });
}

// Context-free: run `family` through the generator (paramsJson is a
// JSON object of integer parameters, e.g. {"depth":2}; NULL/empty =
// defaults), verify the result, and return it serialized as a
// single-schedule table ready to merge or install. See
// schedule/generators.h for the family list.
int tc_schedule_generate(const char* family, int worldSize,
                         const char* paramsJson, uint8_t** out,
                         size_t* outLen) {
  return wrap([&] {
    TC_ENFORCE(family != nullptr && family[0] != '\0',
               "tc_schedule_generate: empty family");
    std::map<std::string, int> params;
    if (paramsJson != nullptr && paramsJson[0] != '\0') {
      // JsonReader keeps a reference to the text; give it a named string
      // (a temporary from the char* would dangle past the constructor).
      const std::string ptext(paramsJson);
      tpucoll::JsonReader r(ptext, "schedule params",
                            /*rejectDuplicateKeys=*/true);
      using JValue = tpucoll::JsonReader::Value;
      JValue v = r.parse();
      TC_ENFORCE(v.kind == JValue::Kind::kObject,
                 "schedule params: expected a JSON object");
      for (const auto& kv : v.fields) {
        TC_ENFORCE(kv.second.kind == JValue::Kind::kNumber,
                   "schedule params: \"", kv.first,
                   "\" must be an integer");
        params[kv.first] = static_cast<int>(kv.second.number);
      }
    }
    tpucoll::schedule::Schedule s =
        tpucoll::schedule::generate(family, worldSize, params);
    tpucoll::schedule::verifyOrThrow(s);
    tpucoll::schedule::ScheduleTable one;
    one.add(std::move(s));
    copyOut(one.toJson(), out, outLen);
  });
}

// Context-free: JSON array of generator family names.
int tc_schedule_families(uint8_t** out, size_t* outLen) {
  return wrap([&] {
    std::ostringstream os;
    os << "[";
    bool first = true;
    for (const auto& f : tpucoll::schedule::generatorFamilies()) {
      if (!first) {
        os << ",";
      }
      first = false;
      tpucoll::appendJsonString(os, f);
    }
    os << "]";
    copyOut(os.str(), out, outLen);
  });
}

// Context-free: parse a schedule table and statically verify EVERY
// schedule in it (all ranks of each schedule's declared world). 0 when
// all pass; TC_ERR with the verifier's typed, step-naming message
// (tc_last_error) on the first failure.
int tc_schedule_verify(const char* json) {
  return wrap([&] {
    TC_ENFORCE(json != nullptr && json[0] != '\0',
               "tc_schedule_verify: empty JSON");
    auto table = tpucoll::schedule::ScheduleTable::fromJson(json);
    for (const auto& s : table.schedules()) {
      tpucoll::schedule::verifyOrThrow(s);
    }
  });
}

// ---- elastic membership plane (elastic/elastic.h) ----

// Create AND start an elastic agent: publishes this worker's lease
// (renewed by a background heartbeat thread every TPUCOLL_LEASE_MS),
// founds epoch 1 (rank 0, join == 0) or enqueues on the join queue
// (join != 0; `rank` is then ignored and a fresh worker id is drawn),
// and starts the membership monitor. `hostId` (nullable) overrides
// topology discovery for rebuilt meshes; `timeoutMs` bounds document
// waits and the default rebuild/collective timeout. NULL +
// tc_last_error on failure.
void* tc_elastic_new(void* store, void* device, int rank, int worldSize,
                     int minSize, int join, const char* hostId,
                     int64_t timeoutMs) {
  return wrapPtr([&]() -> void* {
    tpucoll::elastic::AgentOptions opts;
    opts.rank = rank;
    opts.worldSize = worldSize;
    opts.minSize = minSize;
    opts.join = join != 0;
    if (hostId != nullptr) {
      opts.hostId = hostId;
    }
    if (timeoutMs > 0) {
      opts.timeout = ms(timeoutMs);
    }
    return new tpucoll::elastic::ElasticAgent(*asStore(store),
                                              *asDevice(device), opts);
  });
}

// Build the communicator for the CURRENT head epoch and bind it as the
// agent's monitored context. *out is a full Context handle owned by the
// caller (tc_context_free it — but only AFTER a later tc_elastic_rebuild
// or tc_elastic_stop has unbound it). Typed failures: TC_ERR_TIMEOUT
// past `timeoutMs` (<= 0 uses the agent default), TC_ERR_IO "evicted" /
// "below min_size".
int tc_elastic_rebuild(void* agent, int64_t timeoutMs, void** out) {
  return wrap([&] {
    *out = asElastic(agent)->rebuild(ms(timeoutMs)).release();
  });
}

// Publish hard failure evidence ({"suspect_wid": w|-1, ...}) for the
// bound epoch; the coordinator folds it into the next membership bump.
int tc_elastic_note_failure(void* agent, const char* evidenceJson) {
  return wrap([&] {
    TC_ENFORCE(evidenceJson != nullptr && evidenceJson[0] != '\0',
               "tc_elastic_note_failure: empty evidence");
    asElastic(agent)->noteFailure(evidenceJson);
  });
}

// Graceful leave: stop the heartbeat + monitor threads and delete this
// worker's lease (peers observe an immediate departure). Idempotent.
int tc_elastic_stop(void* agent) {
  return wrap([&] { asElastic(agent)->stop(); });
}

void tc_elastic_free(void* agent) {
  wrapVoid([&] { delete asElastic(agent); });
}

// Epoch of the bound context (0 before the first rebuild).
uint64_t tc_elastic_epoch(void* agent) {
  return wrapVal<uint64_t>(0, [&] { return asElastic(agent)->boundEpoch(); });
}

// Latest published epoch this agent has observed.
uint64_t tc_elastic_head_epoch(void* agent) {
  return wrapVal<uint64_t>(0, [&] { return asElastic(agent)->headEpoch(); });
}

// 1 when the membership moved past the bound context's epoch (the bound
// collective surface is — or is about to be — poisoned); 0 otherwise.
int tc_elastic_poll(void* agent) {
  return wrapVal(0, [&] {
    return asElastic(agent)->epochChanged() ? 1 : 0;
  });
}

// Agent status document (the metrics()["elastic"] payload —
// docs/observability.md); malloc'd, free with tc_buf_free.
int tc_elastic_status_json(void* agent, uint8_t** out, size_t* outLen) {
  return wrap([&] { copyOut(asElastic(agent)->statusJson(), out, outLen); });
}

// ---- deterministic fault-injection plane (fault/) ----

// Install a fault schedule (JSON, docs/faults.md) for THIS process,
// replacing any previous one and resetting the firing report. The table
// is process-global: rules pin the injecting `rank` so several
// in-process ranks can share it. Returns TC_ERR on malformed input.
int tc_fault_install(const char* json) {
  return wrap([&] {
    TC_ENFORCE(json != nullptr && json[0] != '\0',
               "tc_fault_install: empty schedule (use tc_fault_clear)");
    tpucoll::fault::install(json);
  });
}

// Remove the installed schedule; the transport hot path returns to its
// single armed() pointer check costing nothing.
void tc_fault_clear() {
  wrapVoid([&] { tpucoll::fault::clear(); });
}

// Deterministic firing log as a JSON array (malloc'd; free with
// tc_buf_free). Same seed + schedule + per-rank workload => the
// per-rank subsequences are byte-identical across runs.
int tc_fault_report(uint8_t** out, size_t* outLen) {
  return wrap([&] { copyOut(tpucoll::fault::report(), out, outLen); });
}

// ---- collectives ----

// `algorithm` on barrier/broadcast/allgather: 0 = the flat schedule,
// 1 = hierarchical (HierDispatch::kHier; degrades to flat on a flat
// topology — see group/hier.h).
int tc_barrier(void* ctx, int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::BarrierOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.algorithm = static_cast<tpucoll::HierDispatch>(algorithm);
    tpucoll::barrier(opts);
  });
}

int tc_broadcast(void* ctx, void* buffer, size_t count, int dtype, int root,
                 int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::BroadcastOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.buffer = buffer;
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.root = root;
    opts.algorithm = static_cast<tpucoll::HierDispatch>(algorithm);
    tpucoll::broadcast(opts);
  });
}

int tc_allreduce(void* ctx, const void* input, void* output, size_t count,
                 int dtype, int op, int algorithm, uint32_t tag,
                 int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AllreduceOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.inputs = {input};
    opts.outputs = {output};
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.op = static_cast<ReduceOp>(op);
    opts.algorithm = static_cast<tpucoll::AllreduceAlgorithm>(algorithm);
    tpucoll::allreduce(opts);
  });
}

// ---- zero-copy in-place entries (persistent-plan hot path) ----
// One stable buffer pointer in, result written straight into it — no
// copy-out pair, no per-call output allocation on the Python side, and
// a (ptr, nbytes)-stable key for the plan cache (collectives/plan.h):
// the steady-state Nth call performs zero allocations and zero buffer
// registrations.

// In-place allreduce of `buffer` (count elements of dtype).
int tc_allreduce_inplace(void* ctx, void* buffer, size_t count, int dtype,
                         int op, int algorithm, uint32_t tag,
                         int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AllreduceOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.inputs = {buffer};
    opts.outputs = {buffer};
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.op = static_cast<ReduceOp>(op);
    opts.algorithm = static_cast<tpucoll::AllreduceAlgorithm>(algorithm);
    tpucoll::allreduce(opts);
  });
}

// In-place reduce_scatter: this rank's reduced block (recvCounts[rank]
// elements) lands at the FRONT of `buffer`; the rest of the buffer's
// contents are unspecified afterwards (the schedule works in plan
// scratch, so they are in practice left as the caller's input — but
// only the front block is contract).
int tc_reduce_scatter_inplace(void* ctx, void* buffer,
                              const size_t* recvCounts, int dtype, int op,
                              int algorithm, uint32_t tag,
                              int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::ReduceScatterOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = buffer;
    opts.output = buffer;
    opts.recvCounts = countsVec(recvCounts, asContext(ctx)->size());
    opts.dtype = static_cast<DataType>(dtype);
    opts.op = static_cast<ReduceOp>(op);
    opts.algorithm = static_cast<tpucoll::ReduceScatterAlgorithm>(algorithm);
    tpucoll::reduceScatter(opts);
  });
}

// ---- plan-cache introspection (collectives/plan.h) ----

// Entries currently cached on this context (hits/misses/evictions and
// the ubuf_creates registration counter live in tc_metrics_json).
size_t tc_plan_cache_size(void* ctx) {
  return wrapVal<size_t>(0, [&] {
    return asContext(ctx)->planCache().size();
  });
}

// Drop every cached plan (A/B measurement, tests). Safe at any point a
// collective is not concurrently running on the context.
void tc_plan_cache_clear(void* ctx) {
  wrapVoid([&] { asContext(ctx)->planCache().clear(); });
}

int tc_reduce(void* ctx, const void* input, void* output, size_t count,
              int dtype, int op, int root, int algorithm, uint32_t tag,
              int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::ReduceOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.op = static_cast<ReduceOp>(op);
    opts.root = root;
    opts.algorithm = static_cast<tpucoll::ReduceAlgorithm>(algorithm);
    tpucoll::reduce(opts);
  });
}


// Custom-reduction variants: `fn` is an arbitrary commutative-associative
// accumulate callback fn(acc, in, n_elems) invoked on the calling thread
// (reference: gloo/allreduce.h:36 arbitrary Func; gloo/algorithm.h:59-95
// ReductionFunction CUSTOM). Python passes a ctypes CFUNCTYPE here.
int tc_allreduce_fn(void* ctx, const void* input, void* output, size_t count,
                    int dtype, void (*fn)(void*, const void*, size_t),
                    int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AllreduceOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.inputs = {input};
    opts.outputs = {output};
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.customFn = fn;
    opts.algorithm = static_cast<tpucoll::AllreduceAlgorithm>(algorithm);
    tpucoll::allreduce(opts);
  });
}

int tc_reduce_fn(void* ctx, const void* input, void* output, size_t count,
                 int dtype, void (*fn)(void*, const void*, size_t), int root,
                 int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::ReduceOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.customFn = fn;
    opts.root = root;
    opts.algorithm = static_cast<tpucoll::ReduceAlgorithm>(algorithm);
    tpucoll::reduce(opts);
  });
}

int tc_reduce_scatter_fn(void* ctx, const void* input, void* output,
                         const size_t* recvCounts, int dtype,
                         void (*fn)(void*, const void*, size_t),
                         int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::ReduceScatterOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.recvCounts = countsVec(recvCounts, asContext(ctx)->size());
    opts.dtype = static_cast<DataType>(dtype);
    opts.customFn = fn;
    opts.algorithm = static_cast<tpucoll::ReduceScatterAlgorithm>(algorithm);
    tpucoll::reduceScatter(opts);
  });
}

int tc_allreduce_multi_fn(void* ctx, const void** inputs, void** outputs,
                          size_t nbufs, size_t count, int dtype,
                          void (*fn)(void*, const void*, size_t),
                          int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AllreduceOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.inputs.assign(inputs, inputs + nbufs);
    opts.outputs.assign(outputs, outputs + nbufs);
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.customFn = fn;
    opts.algorithm = static_cast<tpucoll::AllreduceAlgorithm>(algorithm);
    tpucoll::allreduce(opts);
  });
}

int tc_gather(void* ctx, const void* input, void* output, size_t count,
              int dtype, int root, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::GatherOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.root = root;
    tpucoll::gather(opts);
  });
}

int tc_gatherv(void* ctx, const void* input, void* output,
               const size_t* counts, int dtype, int root, uint32_t tag,
               int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::GathervOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.counts = countsVec(counts, asContext(ctx)->size());
    opts.dtype = static_cast<DataType>(dtype);
    opts.root = root;
    tpucoll::gatherv(opts);
  });
}

int tc_scatter(void* ctx, const void* input, void* output, size_t count,
               int dtype, int root, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::ScatterOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.root = root;
    tpucoll::scatter(opts);
  });
}

int tc_allgather(void* ctx, const void* input, void* output, size_t count,
                 int dtype, int algorithm, uint32_t tag,
                 int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AllgatherOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.algorithm = static_cast<tpucoll::HierDispatch>(algorithm);
    tpucoll::allgather(opts);
  });
}

int tc_allgatherv(void* ctx, const void* input, void* output,
                  const size_t* counts, int dtype, uint32_t tag,
                  int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AllgathervOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.counts = countsVec(counts, asContext(ctx)->size());
    opts.dtype = static_cast<DataType>(dtype);
    tpucoll::allgatherv(opts);
  });
}

int tc_alltoall(void* ctx, const void* input, void* output, size_t count,
                int dtype, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AlltoallOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    tpucoll::alltoall(opts);
  });
}

int tc_alltoallv(void* ctx, const void* input, const size_t* inCounts,
                 void* output, const size_t* outCounts, int dtype,
                 uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AlltoallvOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.inCounts = countsVec(inCounts, asContext(ctx)->size());
    opts.outCounts = countsVec(outCounts, asContext(ctx)->size());
    opts.dtype = static_cast<DataType>(dtype);
    tpucoll::alltoallv(opts);
  });
}

int tc_reduce_scatter(void* ctx, const void* input, void* output,
                      const size_t* recvCounts, int dtype, int op,
                      int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::ReduceScatterOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.input = input;
    opts.output = output;
    opts.recvCounts = countsVec(recvCounts, asContext(ctx)->size());
    opts.dtype = static_cast<DataType>(dtype);
    opts.op = static_cast<ReduceOp>(op);
    opts.algorithm = static_cast<tpucoll::ReduceScatterAlgorithm>(algorithm);
    tpucoll::reduceScatter(opts);
  });
}

int tc_allreduce_multi(void* ctx, const void** inputs, void** outputs,
                       size_t nbuffers, size_t count, int dtype, int op,
                       int algorithm, uint32_t tag, int64_t timeoutMs) {
  return wrap([&] {
    tpucoll::AllreduceOptions opts;
    fillCommon(opts, asContext(ctx), tag, timeoutMs);
    opts.inputs.assign(inputs, inputs + nbuffers);
    opts.outputs.assign(outputs, outputs + nbuffers);
    opts.count = count;
    opts.dtype = static_cast<DataType>(dtype);
    opts.op = static_cast<ReduceOp>(op);
    opts.algorithm = static_cast<tpucoll::AllreduceAlgorithm>(algorithm);
    tpucoll::allreduce(opts);
  });
}

// ---- int8 block-quantized wire codec (math.h q8 stream layout) ----
// Exposed for the Python surface and the q8 property tests: the same
// kernels AllreduceAlgorithm::kRingQ8Wire runs per hop.

// Resolved TPUCOLL_Q8_BLOCK (elements per block); 0 + tc_last_error on a
// malformed knob.
size_t tc_q8_block() {
  return wrapVal<size_t>(0, [&] { return tpucoll::q8BlockElems(); });
}

// Wire bytes a `count`-element float32 stream occupies after encoding.
size_t tc_q8_wire_bytes(size_t count) {
  return wrapVal<size_t>(0, [&] {
    return tpucoll::q8WireBytes(count, tpucoll::q8BlockElems());
  });
}

// Encode `count` float32 elements into the q8 wire stream. dstBytes must
// equal tc_q8_wire_bytes(count) — a size echo so a stale caller fails
// loudly instead of overrunning.
int tc_q8_encode(const void* src, size_t count, void* dst,
                 size_t dstBytes) {
  return wrap([&] {
    const size_t block = tpucoll::q8BlockElems();
    TC_ENFORCE_EQ(dstBytes, tpucoll::q8WireBytes(count, block));
    tpucoll::f32StreamToQ8(static_cast<const float*>(src),
                           static_cast<uint8_t*>(dst), count, block);
  });
}

// Decode a q8 wire stream back to `count` float32 elements (srcBytes
// echoes tc_q8_wire_bytes(count)).
int tc_q8_decode(const void* src, size_t srcBytes, void* dst,
                 size_t count) {
  return wrap([&] {
    const size_t block = tpucoll::q8BlockElems();
    TC_ENFORCE_EQ(srcBytes, tpucoll::q8WireBytes(count, block));
    tpucoll::q8StreamToF32(static_cast<const uint8_t*>(src),
                           static_cast<float*>(dst), count, block);
  });
}

// ---- int4 packed-nibble wire codec (math.h q4 stream layout) ----
// Same surface as q8: the kernels AllreduceAlgorithm::kRingQ4Wire runs.

// Resolved TPUCOLL_Q4_BLOCK (elements per block).
size_t tc_q4_block() {
  return wrapVal<size_t>(0, [&] { return tpucoll::q4BlockElems(); });
}

size_t tc_q4_wire_bytes(size_t count) {
  return wrapVal<size_t>(0, [&] {
    return tpucoll::q4WireBytes(count, tpucoll::q4BlockElems());
  });
}

int tc_q4_encode(const void* src, size_t count, void* dst,
                 size_t dstBytes) {
  return wrap([&] {
    const size_t block = tpucoll::q4BlockElems();
    TC_ENFORCE_EQ(dstBytes, tpucoll::q4WireBytes(count, block));
    tpucoll::f32StreamToQ4(static_cast<const float*>(src),
                           static_cast<uint8_t*>(dst), count, block);
  });
}

int tc_q4_decode(const void* src, size_t srcBytes, void* dst,
                 size_t count) {
  return wrap([&] {
    const size_t block = tpucoll::q4BlockElems();
    TC_ENFORCE_EQ(srcBytes, tpucoll::q4WireBytes(count, block));
    tpucoll::q4StreamToF32(static_cast<const uint8_t*>(src),
                           static_cast<float*>(dst), count, block);
  });
}

// ---- sharded codec surface (common/codec_pool.h + wire_codec.h) ----
// The exact kernels the pipelined wire rings shard across the codec
// pool, exposed so tests can prove byte-identity against the serial
// walk for any shard count. `kind`: 0 = bf16, 1 = q8, 2 = q4.

namespace {
const tpucoll::algorithms::WireCodec& codecFor(int kind) {
  switch (kind) {
    case tpucoll::algorithms::kWireCodecBf16:
      return tpucoll::algorithms::bf16WireCodec();
    case tpucoll::algorithms::kWireCodecQ8:
      return tpucoll::algorithms::q8WireCodec();
    case tpucoll::algorithms::kWireCodecQ4:
      return tpucoll::algorithms::q4WireCodec();
    default:
      TC_THROW(tpucoll::EnforceError, "unknown wire codec kind ", kind);
  }
}
}  // namespace

// Resolved TPUCOLL_CODEC_THREADS (pool width, >= 1).
int tc_codec_threads() {
  return wrapVal(0, [&] { return tpucoll::codec::codecThreads(); });
}

// Resolved TPUCOLL_CODEC_PIPELINE (sub-blocks per ring hop, >= 1).
int tc_codec_pipeline() {
  return wrapVal(0, [&] { return tpucoll::codec::codecPipelineDepth(); });
}

// Encode `count` float32 elements into `kind`'s wire stream across
// `shards` pool shards (dstBytes echoes the codec's wire size). Output
// is byte-identical to shards == 1 for every shard count.
int tc_codec_encode_sharded(int kind, const void* src, size_t count,
                            void* dst, size_t dstBytes, size_t shards) {
  return wrap([&] {
    const auto& codec = codecFor(kind);
    TC_ENFORCE_EQ(dstBytes, codec.wire(count));
    tpucoll::algorithms::wireEncode(codec, static_cast<const float*>(src),
                                    static_cast<uint8_t*>(dst), count,
                                    shards);
  });
}

// acc[i] += decode(wire)[i] across `shards` pool shards (the fused
// dequant-accumulate the reduce-scatter hops run).
int tc_codec_accumulate_sharded(int kind, void* acc, const void* wire,
                                size_t count, size_t wireBytes,
                                size_t shards) {
  return wrap([&] {
    const auto& codec = codecFor(kind);
    TC_ENFORCE_EQ(wireBytes, codec.wire(count));
    tpucoll::algorithms::wireAccumulate(codec, static_cast<float*>(acc),
                                        static_cast<const uint8_t*>(wire),
                                        count, shards);
  });
}

// ---- async collective engine (async/engine.h) ----

// COLLECTIVE constructor: forks `lanes` privately-tagged sub-contexts
// over `ctx`, so every rank must call concurrently with the same lane
// count and tag base (0 = the default base). Returns NULL + tc_last_error
// on failure.
void* tc_async_new(void* ctx, int lanes, uint32_t tagBase) {
  try {
    tpucoll::async::EngineOptions opts;
    opts.lanes = lanes;
    if (tagBase != 0) {
      opts.tagBase = tagBase;
    }
    return new tpucoll::async::Engine(asContext(ctx), opts);
  } catch (const std::exception& e) {
    g_lastError = e.what();
    return nullptr;
  }
}

// Fail queued work (typed, at wait), abort the in-flight op on every
// lane, join the lane threads. Idempotent; also run by tc_async_free.
int tc_async_shutdown(void* eng) {
  return wrap([&] { asEngine(eng)->shutdown(); });
}

void tc_async_free(void* eng) {
  wrapVoid([&] { delete asEngine(eng); });
}

int tc_async_lanes(void* eng) {
  return wrapVal(0, [&] { return asEngine(eng)->lanes(); });
}

// Borrowed handle to lane `lane`'s forked sub-context, usable with the
// introspection entry points (tc_metrics_json / tc_flightrec_json /
// tc_flightrec_dump). Owned by the engine — never tc_context_free it.
void* tc_async_lane_context(void* eng, int lane) {
  return wrapPtr([&]() -> void* {
    return asEngine(eng)->laneContext(lane);
  });
}

// Engine counters: {"lanes","in_flight","submitted","completed",
// "errors","per_lane":[{"submitted","completed","errors","queue_depth",
// "poisoned"}]}. malloc'd; free with tc_buf_free.
int tc_async_stats_json(void* eng, uint8_t** out, size_t* outLen) {
  return wrap([&] { copyOut(asEngine(eng)->statsJson(), out, outLen); });
}

// Async collectives: same semantics as the blocking forms, except the
// call returns a work handle immediately and the collective runs on the
// engine's deterministically-assigned lane. Buffers must stay valid
// until the work completes; on error the buffer contents are UNDEFINED
// (docs/errors.md "In-place collectives" — the undefined window opens at
// ISSUE time, not at wait). timeoutMs 0 uses the parent context default.
void* tc_async_allreduce(void* eng, const void* input, void* output,
                         size_t count, int dtype, int op, int algorithm,
                         int64_t timeoutMs) {
  return submitWork([&] {
    return asEngine(eng)->allreduce(
        input, output, count, static_cast<DataType>(dtype),
        static_cast<ReduceOp>(op), algorithm, ms(timeoutMs));
  });
}

// In-place async allreduce — the tc_allreduce_inplace analog on the
// engine's lane (stable buffer pointer -> per-lane plan-cache hits).
void* tc_async_allreduce_inplace(void* eng, void* buffer, size_t count,
                                 int dtype, int op, int algorithm,
                                 int64_t timeoutMs) {
  return submitWork([&] {
    return asEngine(eng)->allreduce(
        buffer, buffer, count, static_cast<DataType>(dtype),
        static_cast<ReduceOp>(op), algorithm, ms(timeoutMs));
  });
}

void* tc_async_reduce_scatter(void* eng, const void* input, void* output,
                              const size_t* recvCounts, int size,
                              int dtype, int op, int algorithm,
                              int64_t timeoutMs) {
  return submitWork([&] {
    return asEngine(eng)->reduceScatter(
        input, output, countsVec(recvCounts, size),
        static_cast<DataType>(dtype), static_cast<ReduceOp>(op), algorithm,
        ms(timeoutMs));
  });
}

void* tc_async_allgather(void* eng, const void* input, void* output,
                         size_t count, int dtype, int algorithm,
                         int64_t timeoutMs) {
  return submitWork([&] {
    return asEngine(eng)->allgather(input, output, count,
                                    static_cast<DataType>(dtype),
                                    algorithm, ms(timeoutMs));
  });
}

// Block until the work completes. Returns TC_OK on success; the op's own
// (lane/op-augmented) typed failure otherwise — TC_ERR_TIMEOUT both for
// an op that timed out and for a wait that gave up first (the message
// distinguishes them; the op is NOT cancelled by a wait timeout).
// timeoutMs <= 0 waits with no deadline.
int tc_work_wait(void* work, int64_t timeoutMs) {
  return wrap([&] { (*asWork(work))->wait(workTimeout(timeoutMs)); });
}

// Non-blocking status probe: 0 queued, 1 running, 2 completed ok,
// 3 completed with error (the error itself surfaces at tc_work_wait).
int tc_work_status(void* work) {
  // -1 (with tc_last_error set) when the probe itself fails.
  return wrapVal(-1, [&] {
    return static_cast<int>((*asWork(work))->status());
  });
}

// Error message of a failed work ("" when none / not finished); malloc'd,
// free with tc_buf_free.
int tc_work_error_message(void* work, uint8_t** out, size_t* outLen) {
  return wrap([&] {
    copyOut((*asWork(work))->errorMessage(), out, outLen);
  });
}

void tc_work_free(void* work) {
  wrapVoid([&] { delete asWork(work); });
}

// ---- point-to-point ----

void* tc_buffer_new(void* ctx, void* ptr, size_t size) {
  return wrapPtr([&]() -> void* {
    return asContext(ctx)->createUnboundBuffer(ptr, size).release();
  });
}

void tc_buffer_free(void* buf) {
  wrapVoid([&] {
    frErase(buf);
    delete asBuffer(buf);
  });
}

int tc_buffer_send(void* buf, int dst, uint64_t slot, size_t offset,
                   size_t nbytes) {
  return wrap([&] {
    asBuffer(buf)->send(dst, slot, offset, nbytes);
    if (auto* m = bufMetrics(asBuffer(buf))) {
      m->recordCall(tpucoll::MetricOp::kSend, nbytes);
    }
    if (auto* fr = bufFlightrec(asBuffer(buf))) {
      frPush(buf, /*isSend=*/true, fr->beginP2p(kFrSend, slot, dst, nbytes));
    }
  });
}

int tc_buffer_recv(void* buf, int src, uint64_t slot, size_t offset,
                   size_t nbytes) {
  return wrap([&] {
    asBuffer(buf)->recv(src, slot, offset, nbytes);
    if (auto* m = bufMetrics(asBuffer(buf))) {
      m->recordCall(tpucoll::MetricOp::kRecv, nbytes);
    }
    if (auto* fr = bufFlightrec(asBuffer(buf))) {
      frPush(buf, /*isSend=*/false,
             fr->beginP2p(kFrRecv, slot, src, nbytes));
    }
  });
}

int tc_buffer_recv_any(void* buf, const int* srcs, size_t nsrcs,
                       uint64_t slot, size_t offset, size_t nbytes) {
  return wrap([&] {
    asBuffer(buf)->recv(std::vector<int>(srcs, srcs + nsrcs), slot, offset,
                        nbytes);
    if (auto* m = bufMetrics(asBuffer(buf))) {
      m->recordCall(tpucoll::MetricOp::kRecv, nbytes);
    }
    if (auto* fr = bufFlightrec(asBuffer(buf))) {
      // peer resolves when the wait completes (setPeer).
      frPush(buf, /*isSend=*/false,
             fr->beginP2p(kFrRecv, slot, nsrcs == 1 ? srcs[0] : -1, nbytes));
    }
  });
}

// The p2p waits carry the user-facing instrumentation (tracer span +
// latency histogram + error counter). Collective-internal waits are NOT
// routed through here, so p2p spans never flood a collective trace.
int tc_buffer_wait_send(void* buf, int64_t timeoutMs) {
  UnboundBuffer* b = asBuffer(buf);
  tpucoll::Metrics* m = bufMetrics(b);
  const bool measured = m != nullptr && m->enabled();
  const int64_t startUs = measured ? tpucoll::Tracer::nowUs() : 0;
  int rv = TC_OK;
  int code;
  {
    auto span = maybeSpan(b, "wait_send");
    code = wrap([&] {
      if (!b->waitSend(ms(timeoutMs))) {
        rv = TC_ERR_ABORTED;
      }
    });
  }
  if (measured) {
    m->recordLatency(tpucoll::MetricOp::kSend,
                     tpucoll::Tracer::nowUs() - startUs);
    if (code != TC_OK) {
      m->recordError(tpucoll::MetricOp::kSend);
    }
  }
  if (code == TC_OK && rv == TC_OK) {
    if (auto* fr = bufFlightrec(b)) {
      fr->transition(frPop(buf, /*isSend=*/true),
                     tpucoll::FlightRecorder::kCompleted);
    }
  }
  return code != TC_OK ? code : rv;
}

int tc_buffer_wait_put(void* buf, int64_t timeoutMs, int* srcOut) {
  UnboundBuffer* b = asBuffer(buf);
  int rv = TC_OK;
  int code;
  {
    auto span = maybeSpan(b, "wait_put");
    code = wrap([&] {
      if (!b->waitPutArrival(srcOut, ms(timeoutMs))) {
        rv = TC_ERR_ABORTED;
      }
    });
    if (code == TC_OK && rv == TC_OK && srcOut != nullptr) {
      span.setPeer(*srcOut);
    }
  }
  return code != TC_OK ? code : rv;
}

int tc_buffer_wait_recv(void* buf, int64_t timeoutMs, int* srcOut) {
  UnboundBuffer* b = asBuffer(buf);
  tpucoll::Metrics* m = bufMetrics(b);
  const bool measured = m != nullptr && m->enabled();
  const int64_t startUs = measured ? tpucoll::Tracer::nowUs() : 0;
  int rv = TC_OK;
  int code;
  {
    auto span = maybeSpan(b, "wait_recv");
    code = wrap([&] {
      if (!b->waitRecv(srcOut, ms(timeoutMs))) {
        rv = TC_ERR_ABORTED;
      }
    });
    if (code == TC_OK && rv == TC_OK && srcOut != nullptr) {
      span.setPeer(*srcOut);
    }
  }
  if (measured) {
    m->recordLatency(tpucoll::MetricOp::kRecv,
                     tpucoll::Tracer::nowUs() - startUs);
    if (code != TC_OK) {
      m->recordError(tpucoll::MetricOp::kRecv);
    }
  }
  if (code == TC_OK && rv == TC_OK) {
    if (auto* fr = bufFlightrec(b)) {
      const uint64_t seq = frPop(buf, /*isSend=*/false);
      fr->transition(seq, tpucoll::FlightRecorder::kCompleted);
      if (srcOut != nullptr) {
        fr->setPeer(seq, *srcOut);
      }
    }
  }
  return code != TC_OK ? code : rv;
}

size_t tc_remote_key_size() {
  return wrapVal<size_t>(0, [&] {
    return sizeof(tpucoll::transport::WireRemoteKey);
  });
}

int tc_buffer_remote_key(void* buf, char* out, size_t outLen) {
  return wrap([&] {
    auto key = asBuffer(buf)->getRemoteKey();
    TC_ENFORCE_EQ(key.size(), outLen, "remote key buffer size mismatch");
    std::memcpy(out, key.data(), key.size());
  });
}

int tc_buffer_put(void* buf, const char* key, size_t keyLen, size_t offset,
                  size_t roffset, size_t nbytes, int notify) {
  return wrap([&] {
    asBuffer(buf)->put(std::string(key, keyLen), offset, roffset, nbytes,
                       notify != 0);
    if (auto* fr = bufFlightrec(asBuffer(buf))) {
      frPush(buf, /*isSend=*/true, fr->beginP2p(kFrPut, 0, -1, nbytes));
    }
  });
}

int tc_buffer_get(void* buf, const char* key, size_t keyLen, uint64_t slot,
                  size_t offset, size_t roffset, size_t nbytes) {
  return wrap([&] {
    asBuffer(buf)->get(std::string(key, keyLen), slot, offset, roffset,
                       nbytes);
    if (auto* fr = bufFlightrec(asBuffer(buf))) {
      frPush(buf, /*isSend=*/false, fr->beginP2p(kFrGet, slot, -1, nbytes));
    }
  });
}

void tc_buffer_abort_wait_send(void* buf) {
  wrapVoid([&] { asBuffer(buf)->abortWaitSend(); });
}

void tc_buffer_abort_wait_recv(void* buf) {
  wrapVoid([&] { asBuffer(buf)->abortWaitRecv(); });
}

}  // extern "C"
