// Bootstrap plane (docs/bootstrap.md): scales context creation and
// steady-state connection count past the full-mesh clique.
//
// Three cooperating pieces live under boot/:
//  - the lazy pair-id codec (lazy_id.h) that lets a connection broker
//    dial any pair on first use with no store round-trip;
//  - leader-relayed rendezvous (rendezvous.cc): one store write per rank,
//    host leaders batch their members' address payloads into per-host
//    blobs and exchange those inter-host, members fan in from their
//    leader's assembled table — O(hosts² + N) store operations where
//    connectFullMesh needs O(N²);
//  - the sharded key namespace (`tc/boot/s<shard>/…`) so a single store
//    server never serializes all ranks through one key prefix.
//
// The elastic per-host lease aggregation (fourth piece of the plane)
// lives with its consumer in elastic/; the env switchboard for all of it
// is here (optionsFromEnv).
#pragma once

#include <chrono>
#include <cstdint>
#include <string>
#include <vector>

#include "tpucoll/rendezvous/store.h"

namespace tpucoll {

struct Topology;

namespace boot {

enum class Mode { kFull, kLazy };
enum class Eager { kNone, kRing, kHier };

struct BootOptions {
  Mode mode{Mode::kFull};
  // Which pairs the lazy context dials at bootstrap (the rest are
  // broker-dialed on first use): ring = ±1 neighbors; hier = ring plus
  // same-host members plus (leaders only) the leader mesh — the working
  // set of the six algorithm families' default schedules.
  Eager eager{Eager::kHier};
  // LRU cap on broker-dialed pairs per rank; 0 = unbounded. Eager pairs
  // are pinned and never count against the cap.
  int maxPairs{0};
  // Key-namespace shards under tc/boot/.
  int shards{8};
};

// Reads TPUCOLL_BOOT_MODE / TPUCOLL_BOOT_EAGER / TPUCOLL_MAX_PAIRS /
// TPUCOLL_BOOT_SHARDS (strict parses; see docs/env.md).
BootOptions optionsFromEnv();

// Per-phase wall times and store-traffic counts for one rank's walk
// through rendezvous. Feeds metrics ("boot" family) and the
// --bootstrap-sweep bench.
struct RendezvousStats {
  int64_t publishUs{0};   // phase 1: write own fingerprint+payload
  int64_t topoUs{0};      // phases 2-3: rank 0 assembles, all ranks read
  int64_t exchangeUs{0};  // phases 4-6: host blobs, leader cross, fan-in
  int64_t storeOps{0};
  int64_t storeBytes{0};
};

struct RendezvousResult {
  uint64_t meshId{0};
  // Host fingerprints indexed by global rank (buildTopology input).
  std::vector<std::string> fingerprints;
  // Opaque per-rank address payloads indexed by global rank.
  std::vector<Store::Buf> payloads;
};

// Leader-relayed rendezvous over `store` (see docs/bootstrap.md for the
// key schema). Every rank calls this collectively; `payload` is this
// rank's opaque address blob (transport::Context::lazyAddressBlob).
// Blocking; throws TimeoutException past `timeout`.
RendezvousResult relayedRendezvous(Store& store, int rank, int size,
                                   const std::string& fingerprint,
                                   const Store::Buf& payload, int shards,
                                   std::chrono::milliseconds timeout,
                                   RendezvousStats* stats = nullptr);

// The full-mesh arm's store choreography (tc/topo/<r> + tc/rank/<r>
// publish-then-multiGet-all pattern of discoverTopology +
// connectFullMesh) with synthetic payloads, for apples-to-apples cost
// curves in --bootstrap-sweep without paying N² real sockets.
void fullMeshRendezvousSim(Store& store, int rank, int size,
                           const std::string& fingerprint,
                           const Store::Buf& payload,
                           std::chrono::milliseconds timeout,
                           RendezvousStats* stats = nullptr);

// eager[r] = true for peers the lazy context must dial at bootstrap
// under `opts.eager` given the discovered topology. eager[self] = false.
std::vector<char> eagerPeers(const BootOptions& opts, const Topology& topo);

// Store decorator counting operations and payload bytes (both
// directions). Used to attribute rendezvous store traffic in stats.
class CountingStore : public Store {
 public:
  explicit CountingStore(Store& inner) : inner_(inner) {}

  void set(const std::string& key, const Buf& value) override;
  Buf get(const std::string& key, std::chrono::milliseconds timeout) override;
  bool check(const std::vector<std::string>& keys) override;
  int64_t add(const std::string& key, int64_t delta) override;
  std::vector<Buf> multiGet(const std::vector<std::string>& keys,
                            std::chrono::milliseconds timeout) override;
  bool deleteKey(const std::string& key) override;
  std::vector<std::string> listKeys(const std::string& prefix) override;

  int64_t ops() const { return ops_; }
  int64_t bytes() const { return bytes_; }

 private:
  Store& inner_;
  int64_t ops_{0};
  int64_t bytes_{0};
};

}  // namespace boot
}  // namespace tpucoll
