// Lazy pair-id namespace (docs/bootstrap.md): broker-dialed connections
// carry a SELF-DESCRIBING routing id instead of one allocated by
// Device::nextPairId() and exchanged through the store. Bit 63 marks the
// namespace (the sequential allocator starts at 1 and can never reach
// it); the remaining bits encode which mesh, which initiator, which
// target, which data channel, and a redial generation — everything the
// accepting side needs to build the matching Pair on demand when the
// hello arrives, with zero store traffic at dial time.
//
// Header-only and dependency-free on purpose: transport/ (the listener
// hook and the connection broker) and boot/ (rendezvous, which picks the
// mesh id) must agree on this codec without a layering cycle.
#pragma once

#include <cstdint>

namespace tpucoll {
namespace boot {

// Layout, high to low: [63] lazy flag | [62:39] mesh id (24 bits) |
// [38:31] redial generation (8) | [30:18] initiator rank (13) |
// [17:5] target rank (13) | [4:0] channel (5).
constexpr uint64_t kLazyPairBit = uint64_t(1) << 63;
constexpr int kLazyMeshBits = 24;
constexpr int kLazyGenBits = 8;
constexpr int kLazyRankBits = 13;  // 8192 ranks per mesh
constexpr int kLazyChanBits = 5;   // 32 data channels

constexpr int kLazyMaxRanks = 1 << kLazyRankBits;
constexpr uint32_t kLazyMeshMask = (uint32_t(1) << kLazyMeshBits) - 1;

struct LazyIdParts {
  uint32_t meshId;
  uint32_t gen;
  int initiator;
  int target;
  int channel;
};

inline bool isLazyPairId(uint64_t id) { return (id & kLazyPairBit) != 0; }

inline uint64_t makeLazyPairId(uint32_t meshId, uint32_t gen, int initiator,
                               int target, int channel) {
  uint64_t id = kLazyPairBit;
  id |= uint64_t(meshId & kLazyMeshMask)
        << (kLazyGenBits + 2 * kLazyRankBits + kLazyChanBits);
  id |= uint64_t(gen & ((1u << kLazyGenBits) - 1))
        << (2 * kLazyRankBits + kLazyChanBits);
  id |= uint64_t(uint32_t(initiator) & (kLazyMaxRanks - 1))
        << (kLazyRankBits + kLazyChanBits);
  id |= uint64_t(uint32_t(target) & (kLazyMaxRanks - 1)) << kLazyChanBits;
  id |= uint64_t(uint32_t(channel) & ((1u << kLazyChanBits) - 1));
  return id;
}

inline LazyIdParts parseLazyPairId(uint64_t id) {
  LazyIdParts p;
  p.channel = static_cast<int>(id & ((1u << kLazyChanBits) - 1));
  id >>= kLazyChanBits;
  p.target = static_cast<int>(id & (kLazyMaxRanks - 1));
  id >>= kLazyRankBits;
  p.initiator = static_cast<int>(id & (kLazyMaxRanks - 1));
  id >>= kLazyRankBits;
  p.gen = static_cast<uint32_t>(id & ((1u << kLazyGenBits) - 1));
  id >>= kLazyGenBits;
  p.meshId = static_cast<uint32_t>(id & kLazyMeshMask);
  return p;
}

}  // namespace boot
}  // namespace tpucoll
