#include "tpucoll/boot/boot.h"

#include <chrono>
#include <cstring>

#include "tpucoll/common/env.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/group/topology.h"

namespace tpucoll {
namespace boot {

namespace {

using Clock = std::chrono::steady_clock;

int64_t usSince(Clock::time_point t0) {
  return std::chrono::duration_cast<std::chrono::microseconds>(Clock::now() -
                                                               t0)
      .count();
}

std::chrono::milliseconds remaining(Clock::time_point deadline) {
  const auto left =
      std::chrono::duration_cast<std::chrono::milliseconds>(deadline -
                                                            Clock::now());
  if (left.count() <= 0) {
    TC_THROW(TimeoutException, "bootstrap rendezvous timed out");
  }
  return left;
}

uint64_t fnv64(const void* data, size_t n, uint64_t h = 0xcbf29ce484222325ull) {
  const unsigned char* p = static_cast<const unsigned char*>(data);
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 0x100000001b3ull;
  }
  return h;
}

void put32(Store::Buf* b, uint32_t v) {
  const size_t off = b->size();
  b->resize(off + sizeof(v));
  std::memcpy(b->data() + off, &v, sizeof(v));
}

void put64(Store::Buf* b, uint64_t v) {
  const size_t off = b->size();
  b->resize(off + sizeof(v));
  std::memcpy(b->data() + off, &v, sizeof(v));
}

void putBytes(Store::Buf* b, const void* data, size_t n) {
  const size_t off = b->size();
  b->resize(off + n);
  if (n > 0) {
    std::memcpy(b->data() + off, data, n);
  }
}

// Cursor-style reader with bounds enforcement; a torn or foreign blob
// must fail loudly, not index out of range.
struct Reader {
  const Store::Buf& b;
  size_t off{0};

  uint32_t u32() {
    TC_ENFORCE(off + sizeof(uint32_t) <= b.size(), "short bootstrap blob");
    uint32_t v;
    std::memcpy(&v, b.data() + off, sizeof(v));
    off += sizeof(v);
    return v;
  }
  uint64_t u64() {
    TC_ENFORCE(off + sizeof(uint64_t) <= b.size(), "short bootstrap blob");
    uint64_t v;
    std::memcpy(&v, b.data() + off, sizeof(v));
    off += sizeof(v);
    return v;
  }
  Store::Buf bytes(size_t n) {
    TC_ENFORCE(off + n <= b.size(), "short bootstrap blob");
    Store::Buf out(b.begin() + off, b.begin() + off + n);
    off += n;
    return out;
  }
  std::string str(size_t n) {
    TC_ENFORCE(off + n <= b.size(), "short bootstrap blob");
    std::string out(reinterpret_cast<const char*>(b.data()) + off, n);
    off += n;
    return out;
  }
};

// Key schema (docs/bootstrap.md). Shards spread hot prefixes so one
// store server (or a future multi-store) never funnels every rank
// through a single lexicographic range.
std::string shardPrefix(int x, int shards) {
  return "tc/boot/s" + std::to_string(x % shards) + "/";
}

std::string aKey(int r, int shards) {
  return shardPrefix(r, shards) + "a/" + std::to_string(r);
}

std::string hKey(int h, int shards) {
  return shardPrefix(h, shards) + "h/" + std::to_string(h);
}

std::string xKey(int h, int shards) {
  return shardPrefix(h, shards) + "x/" + std::to_string(h);
}

constexpr const char* kTopoKey = "tc/boot/topo";
constexpr const char* kMeshCounterKey = "tc/boot/mesh";

// [u32 count][(u32 rank, u32 len, payload)×count]
Store::Buf packPayloadTable(const std::vector<int>& ranks,
                            const std::vector<Store::Buf>& payloads) {
  Store::Buf b;
  put32(&b, static_cast<uint32_t>(ranks.size()));
  for (size_t i = 0; i < ranks.size(); i++) {
    put32(&b, static_cast<uint32_t>(ranks[i]));
    put32(&b, static_cast<uint32_t>(payloads[i].size()));
    putBytes(&b, payloads[i].data(), payloads[i].size());
  }
  return b;
}

void unpackPayloadTable(const Store::Buf& b, int size,
                        std::vector<Store::Buf>* out) {
  Reader r{b};
  const uint32_t count = r.u32();
  for (uint32_t i = 0; i < count; i++) {
    const uint32_t rank = r.u32();
    TC_ENFORCE(rank < static_cast<uint32_t>(size),
               "bootstrap payload table names rank ", rank, " of ", size);
    (*out)[rank] = r.bytes(r.u32());
  }
}

}  // namespace

BootOptions optionsFromEnv() {
  BootOptions opts;
  const char* mode =
      envChoice("TPUCOLL_BOOT_MODE", "full", {"full", "lazy"});
  opts.mode = std::strcmp(mode, "lazy") == 0 ? Mode::kLazy : Mode::kFull;
  const char* eager =
      envChoice("TPUCOLL_BOOT_EAGER", "hier", {"hier", "ring", "none"});
  opts.eager = std::strcmp(eager, "ring") == 0
                   ? Eager::kRing
                   : (std::strcmp(eager, "none") == 0 ? Eager::kNone
                                                      : Eager::kHier);
  opts.maxPairs =
      static_cast<int>(envCount("TPUCOLL_MAX_PAIRS", 0, 0, 1 << 20));
  opts.shards =
      static_cast<int>(envCount("TPUCOLL_BOOT_SHARDS", 8, 1, 4096));
  return opts;
}

RendezvousResult relayedRendezvous(Store& store, int rank, int size,
                                   const std::string& fingerprint,
                                   const Store::Buf& payload, int shards,
                                   std::chrono::milliseconds timeout,
                                   RendezvousStats* stats) {
  TC_ENFORCE(size >= 1 && rank >= 0 && rank < size,
             "relayedRendezvous: bad rank ", rank, "/", size);
  CountingStore cs(store);
  const auto deadline = Clock::now() + timeout;
  RendezvousResult res;
  res.payloads.assign(static_cast<size_t>(size), Store::Buf{});
  res.payloads[rank] = payload;

  // Phase 1: publish [fp][payload] under this rank's shard — the only
  // per-rank write the whole rendezvous needs.
  auto t0 = Clock::now();
  {
    Store::Buf b;
    put32(&b, static_cast<uint32_t>(fingerprint.size()));
    putBytes(&b, fingerprint.data(), fingerprint.size());
    put32(&b, static_cast<uint32_t>(payload.size()));
    putBytes(&b, payload.data(), payload.size());
    cs.set(aKey(rank, shards), b);
  }
  if (stats != nullptr) {
    stats->publishUs = usSince(t0);
  }

  // Phases 2-3: rank 0 reads every publish blob once, derives the mesh
  // id (fingerprint digest mixed with a store-side counter so rebuilds
  // in the same namespace never reuse an id), and fans the topology out
  // through one key.
  t0 = Clock::now();
  std::vector<Store::Buf> hostPayloads;  // rank 0 keeps these for phase 4
  if (rank == 0) {
    std::vector<std::string> keys;
    keys.reserve(static_cast<size_t>(size));
    for (int r = 0; r < size; r++) {
      keys.push_back(aKey(r, shards));
    }
    auto blobs = cs.multiGet(keys, remaining(deadline));
    hostPayloads.assign(static_cast<size_t>(size), Store::Buf{});
    res.fingerprints.resize(static_cast<size_t>(size));
    uint64_t digest = 0xcbf29ce484222325ull;
    for (int r = 0; r < size; r++) {
      Reader rd{blobs[static_cast<size_t>(r)]};
      res.fingerprints[static_cast<size_t>(r)] = rd.str(rd.u32());
      hostPayloads[static_cast<size_t>(r)] = rd.bytes(rd.u32());
      digest = fnv64(res.fingerprints[static_cast<size_t>(r)].data(),
                     res.fingerprints[static_cast<size_t>(r)].size(), digest);
    }
    const int64_t epoch = cs.add(kMeshCounterKey, 1);
    res.meshId = fnv64(&epoch, sizeof(epoch), digest);
    Store::Buf b;
    put64(&b, res.meshId);
    put32(&b, static_cast<uint32_t>(size));
    for (const auto& fp : res.fingerprints) {
      put32(&b, static_cast<uint32_t>(fp.size()));
      putBytes(&b, fp.data(), fp.size());
    }
    cs.set(kTopoKey, b);
  } else {
    const Store::Buf b = cs.get(kTopoKey, remaining(deadline));
    Reader rd{b};
    res.meshId = rd.u64();
    const uint32_t n = rd.u32();
    TC_ENFORCE_EQ(static_cast<int>(n), size,
                  "bootstrap topo blob disagrees on world size");
    res.fingerprints.resize(static_cast<size_t>(size));
    for (uint32_t i = 0; i < n; i++) {
      res.fingerprints[i] = rd.str(rd.u32());
    }
  }
  if (stats != nullptr) {
    stats->topoUs = usSince(t0);
  }

  // Phases 4-6: leaders batch member payloads per host, exchange host
  // blobs among themselves, and publish the assembled table; members
  // fan in from their own leader's copy. O(hosts²) leader traffic plus
  // O(N) member reads.
  t0 = Clock::now();
  const Topology topo = buildTopology(rank, res.fingerprints);
  if (size > 1) {
    if (topo.isLeader) {
      // Phase 4: my host's blob (rank 0 already holds every payload).
      const auto& members = topo.hosts[static_cast<size_t>(topo.hostIndex)];
      std::vector<Store::Buf> memberPayloads;
      if (rank == 0) {
        for (int m : members) {
          memberPayloads.push_back(hostPayloads[static_cast<size_t>(m)]);
        }
      } else {
        std::vector<std::string> keys;
        for (int m : members) {
          keys.push_back(aKey(m, shards));
        }
        auto blobs = cs.multiGet(keys, remaining(deadline));
        for (auto& b : blobs) {
          Reader rd{b};
          rd.str(rd.u32());  // skip fingerprint
          memberPayloads.push_back(rd.bytes(rd.u32()));
        }
      }
      cs.set(hKey(topo.hostIndex, shards),
             packPayloadTable(members, memberPayloads));

      // Phase 5: read the other hosts' blobs, assemble the full table.
      std::vector<std::string> keys;
      for (int h = 0; h < topo.nHosts(); h++) {
        if (h != topo.hostIndex) {
          keys.push_back(hKey(h, shards));
        }
      }
      auto blobs = cs.multiGet(keys, remaining(deadline));
      for (const auto& b : blobs) {
        unpackPayloadTable(b, size, &res.payloads);
      }
      for (size_t i = 0; i < members.size(); i++) {
        res.payloads[static_cast<size_t>(members[i])] = memberPayloads[i];
      }
      std::vector<int> all(static_cast<size_t>(size));
      for (int r = 0; r < size; r++) {
        all[static_cast<size_t>(r)] = r;
      }
      cs.set(xKey(topo.hostIndex, shards),
             packPayloadTable(all, res.payloads));
    } else {
      // Phase 6: one read of the leader's assembled table.
      const Store::Buf b =
          cs.get(xKey(topo.hostIndex, shards), remaining(deadline));
      unpackPayloadTable(b, size, &res.payloads);
      res.payloads[static_cast<size_t>(rank)] = payload;
    }
  }
  if (stats != nullptr) {
    stats->exchangeUs = usSince(t0);
    stats->storeOps = cs.ops();
    stats->storeBytes = cs.bytes();
  }
  return res;
}

void fullMeshRendezvousSim(Store& store, int rank, int size,
                           const std::string& fingerprint,
                           const Store::Buf& payload,
                           std::chrono::milliseconds timeout,
                           RendezvousStats* stats) {
  CountingStore cs(store);
  const auto deadline = Clock::now() + timeout;

  // discoverTopology's pattern: per-rank fingerprint key, every rank
  // reads every other — O(N²) reads fleet-wide.
  auto t0 = Clock::now();
  cs.set("tc/topo/" + std::to_string(rank),
         Store::Buf(fingerprint.begin(), fingerprint.end()));
  if (stats != nullptr) {
    stats->publishUs = usSince(t0);
  }
  t0 = Clock::now();
  std::vector<std::string> keys;
  for (int r = 0; r < size; r++) {
    if (r != rank) {
      keys.push_back("tc/topo/" + std::to_string(r));
    }
  }
  cs.multiGet(keys, remaining(deadline));
  if (stats != nullptr) {
    stats->topoUs = usSince(t0);
  }

  // connectFullMesh's pattern: per-rank address blob, every rank reads
  // every other — another O(N²).
  t0 = Clock::now();
  cs.set("tc/rank/" + std::to_string(rank), payload);
  keys.clear();
  for (int r = 0; r < size; r++) {
    if (r != rank) {
      keys.push_back("tc/rank/" + std::to_string(r));
    }
  }
  cs.multiGet(keys, remaining(deadline));
  if (stats != nullptr) {
    stats->exchangeUs = usSince(t0);
    stats->storeOps = cs.ops();
    stats->storeBytes = cs.bytes();
  }
}

std::vector<char> eagerPeers(const BootOptions& opts, const Topology& topo) {
  const int size = static_cast<int>(topo.hostOf.size());
  std::vector<char> eager(static_cast<size_t>(size), 0);
  if (size <= 1 || opts.eager == Eager::kNone) {
    return eager;
  }
  // Ring neighbors in both modes.
  eager[static_cast<size_t>((topo.rank + 1) % size)] = 1;
  eager[static_cast<size_t>((topo.rank + size - 1) % size)] = 1;
  if (opts.eager == Eager::kHier) {
    for (int m : topo.hosts[static_cast<size_t>(topo.hostIndex)]) {
      if (m != topo.rank) {
        eager[static_cast<size_t>(m)] = 1;
      }
    }
    if (topo.isLeader) {
      for (const auto& members : topo.hosts) {
        const int leader = members.front();
        if (leader != topo.rank) {
          eager[static_cast<size_t>(leader)] = 1;
        }
      }
    }
  }
  eager[static_cast<size_t>(topo.rank)] = 0;
  return eager;
}

void CountingStore::set(const std::string& key, const Buf& value) {
  ops_++;
  bytes_ += static_cast<int64_t>(key.size() + value.size());
  inner_.set(key, value);
}

Store::Buf CountingStore::get(const std::string& key,
                              std::chrono::milliseconds timeout) {
  ops_++;
  Buf out = inner_.get(key, timeout);
  bytes_ += static_cast<int64_t>(key.size() + out.size());
  return out;
}

bool CountingStore::check(const std::vector<std::string>& keys) {
  ops_++;
  return inner_.check(keys);
}

int64_t CountingStore::add(const std::string& key, int64_t delta) {
  ops_++;
  bytes_ += static_cast<int64_t>(key.size() + sizeof(int64_t));
  return inner_.add(key, delta);
}

std::vector<Store::Buf> CountingStore::multiGet(
    const std::vector<std::string>& keys, std::chrono::milliseconds timeout) {
  ops_ += static_cast<int64_t>(keys.size());
  auto out = inner_.multiGet(keys, timeout);
  for (size_t i = 0; i < keys.size(); i++) {
    bytes_ += static_cast<int64_t>(keys[i].size() + out[i].size());
  }
  return out;
}

bool CountingStore::deleteKey(const std::string& key) {
  ops_++;
  bytes_ += static_cast<int64_t>(key.size());
  return inner_.deleteKey(key);
}

std::vector<std::string> CountingStore::listKeys(const std::string& prefix) {
  ops_++;
  return inner_.listKeys(prefix);
}

}  // namespace boot
}  // namespace tpucoll
