// Elastic membership plane: store-backed liveness leases + an
// epoch-based membership protocol on top of the PR 13 members-only mesh
// bootstrap, turning "a rank died" from an application-driven manual
// re-rendezvous (resilience.rebuild_after_failure) into a
// system-detected, bounded-time, automatically-agreed transition.
//
// Protocol (docs/elastic.md):
//
//  - Liveness. Every worker holds a process-lifetime worker id (`wid`:
//    founding rank, or a fresh id from a store counter for joiners) and
//    renews a lease key `tpucoll/elastic/lease/<wid>` from a background
//    heartbeat thread every TPUCOLL_LEASE_MS. Observers judge liveness
//    by CHANGE OBSERVATION against their own steady clock — a lease
//    whose counter has not moved for TPUCOLL_LEASE_GRACE ms is expired
//    — so no cross-host clock agreement is ever needed. A DELETED
//    lease that was previously observed is an immediate, graceful
//    departure (stop()).
//
//    With TPUCOLL_LEASE_AGG=1 (docs/bootstrap.md) the SCAN side of
//    liveness aggregates per host: each worker publishes its host
//    fingerprint once (`host/<wid>`), the lowest live wid per host acts
//    as host leader and folds its co-members' individual lease values
//    into one aggregate key (`agg/<fp-hash>`) every monitor pass, and
//    every monitor samples O(hosts) aggregates instead of O(N)
//    individual leases per pass. Members keep renewing their individual
//    leases (writes are already O(N) fleet-wide and shard naturally;
//    the N×N scan is the term that melts the store at P>=512), so when
//    a leader dies its aggregate goes stale and observers degrade to
//    the individual leases of that host for the grace window until the
//    next-lowest wid takes the leader role over.
//
//  - Membership. The coordinator — the lowest live wid — publishes
//    immutable epoch documents `e<N>/doc` = {epoch, members, cause} and
//    advances a `head` counter. Publication is single-writer per epoch
//    via an atomic claim counter (`e<N>/claim`); a claimant that dies
//    pre-publish is recovered by a grace-bounded takeover from the
//    next live coordinator. Bump triggers: lease expiry / graceful
//    leave (members shrink), hard failure evidence published by
//    survivors of a broken collective (`e<N>/fail/<wid>`, carrying the
//    watchdog/transport-failure/flightrec verdict — same members, fresh
//    mesh; a wid blamed twice running is excluded), and join requests
//    (`join/<wid>`) admitted at the next boundary once every current
//    member is `ready` for the head epoch.
//
//  - Transition. Every agent's monitor thread observes the head; a bump
//    CLOSES the bound Context so in-flight collectives fail typed
//    instead of hanging out their timeouts; the application (or
//    gloo_tpu.elastic.run_elastic) then calls rebuild(), which builds
//    the successor communicator for the head epoch: fresh contiguous
//    ranks ordered by the doc's member list, members-only mesh
//    bootstrapped under the epoch-scoped store namespace
//    (`e<N>/mesh/...`), group tag "e<N>" (so flight-recorder dumps,
//    metrics and the fault-plane domain carry the epoch identity), and
//    the previous epoch's tuning table re-installed.
//
// Store hygiene: publishing epoch N+1 reaps the dead wids' leases, the
// admitted join keys, the consumed failure evidence, and the whole
// `e<N-1>/` namespace (mesh bootstrap blobs are the bulk), so a
// long-running elastic job's store stays bounded at ~two epochs.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "tpucoll/context.h"
#include "tpucoll/rendezvous/store.h"
#include "tpucoll/transport/device.h"

namespace tpucoll {
namespace elastic {

struct AgentOptions {
  int rank = 0;        // founding rank (ignored when join is set)
  int worldSize = 1;   // target full size (and the founding size)
  int minSize = 1;     // rebuild() fails typed below this member count
  bool join = false;   // enqueue on the join queue instead of founding
  std::string hostId;  // topology-discovery override for rebuilt meshes
  // Bound on constructor document waits and the default rebuild() /
  // collective timeout of rebuilt contexts.
  std::chrono::milliseconds timeout{std::chrono::milliseconds(60000)};
};

class ElasticAgent {
 public:
  // Publishes this worker's first lease, founds epoch 1 (rank 0 of a
  // non-join agent) or enqueues on the join queue, waits for the first
  // visible epoch document, and starts the heartbeat + monitor
  // threads. Throws on a malformed TPUCOLL_LEASE_MS / TPUCOLL_LEASE_GRACE
  // or when no epoch document appears within opts.timeout.
  ElasticAgent(std::shared_ptr<Store> store,
               std::shared_ptr<transport::Device> device,
               const AgentOptions& opts);
  ~ElasticAgent();

  ElasticAgent(const ElasticAgent&) = delete;
  ElasticAgent& operator=(const ElasticAgent&) = delete;

  // Build (or re-build) the communicator for the CURRENT head epoch and
  // bind it as this agent's monitored context. Blocks until the mesh is
  // up — retrying through epochs that get superseded mid-bootstrap —
  // or throws typed: TimeoutException past `timeout` (<= 0 uses the
  // agent default), IoException "evicted" when this wid was voted out,
  // IoException "below min_size" when the membership shrank under the
  // floor. The caller owns the returned context; the previously bound
  // context (already closed by the monitor when the epoch moved) stays
  // owned by the caller and must outlive this call only.
  std::unique_ptr<Context> rebuild(
      std::chrono::milliseconds timeout = std::chrono::milliseconds(0));

  // Publish hard failure evidence for the bound epoch (a survivor's
  // broken-collective verdict: {"suspect_wid": w|-1, ...} plus whatever
  // the caller adds — watchdog stall record, transport_failure,
  // flightrec tail). The coordinator folds it into the next bump.
  void noteFailure(const std::string& evidenceJson);

  // Graceful leave: stop both threads, delete this wid's lease (peers
  // observe an immediate departure, no grace wait), unbind the context
  // (NOT closed — the caller still owns it). Idempotent.
  void stop();

  uint64_t boundEpoch() const;
  uint64_t headEpoch() const;
  // True when the membership moved past the bound context's epoch (the
  // bound collective surface is — or is about to be — poisoned).
  bool epochChanged() const;
  int64_t wid() const { return wid_; }

  // {"epoch","head_epoch","wid","rank","size","members","target_size",
  //  "min_size","coordinator","join_pending","leases_renewed",
  //  "rebuilds","bumps_published","last_rebuild_ms","fault_domain"} —
  // the metrics()["elastic"] payload (docs/observability.md).
  std::string statusJson() const;

 private:
  std::string k(const std::string& suffix) const;
  std::string leaseKey(int64_t wid) const;
  std::string aggKey(const std::string& hostFp) const;
  // ---- per-host lease aggregation (monitor thread only) ----
  // Lazily (re)read the member -> host-fingerprint map for the current
  // epoch: O(N) store reads once per epoch, not per pass.
  void refreshHostMap(const std::vector<int64_t>& members);
  // True when this wid should publish its host's aggregate: it is the
  // lowest same-host member wid not currently observed expired.
  bool actingHostLeader(const std::vector<int64_t>& members, int64_t now);
  // Leader duty: fold co-members' individual lease values into one
  // aggregate write.
  void publishAggregate(const std::vector<int64_t>& members);
  // Observer duty: one get per distinct member host, change-observed on
  // the embedded leader beat.
  void sampleAggregates(const std::vector<int64_t>& members, int64_t now);
  // (present, value) of member w's lease — from its host's FRESH
  // aggregate when there is one, else the individual key (the degraded
  // path while a dead leader's aggregate ages out).
  void readLease(int64_t w, int64_t now, bool* present, uint64_t* value);
  void heartbeatOnce();
  void heartbeatLoop();
  void monitorLoop();
  void monitorOnce();
  // Observe `head`; on a new epoch fetch + install its document and
  // close a stale bound context (in-flight collectives fail typed).
  void refreshHead();
  void installDoc(uint64_t epoch, const std::string& docJson);
  // Coordinator only: publish epoch `target` with `members`; reaps the
  // dead leases / admitted join keys / consumed evidence and retires
  // the e<target-2> namespace. Returns true when this agent won the
  // publication claim.
  bool publishEpoch(uint64_t target, const std::vector<int64_t>& members,
                    const char* cause, const std::vector<int64_t>& dead,
                    const std::vector<int64_t>& admitted);
  static std::string docJson(uint64_t epoch,
                             const std::vector<int64_t>& members,
                             const char* cause);

  int64_t nowMs() const;

  const std::shared_ptr<Store> store_;
  const std::shared_ptr<transport::Device> device_;
  const AgentOptions opts_;
  const long leaseMs_;
  const long graceMs_;
  const long pollMs_;
  const bool leaseAgg_;    // TPUCOLL_LEASE_AGG
  std::string hostFp_;     // this worker's host fingerprint (agg mode)
  int64_t wid_{-1};

  std::atomic<bool> stop_{false};
  std::atomic<uint64_t> leasesRenewed_{0};
  std::atomic<uint64_t> heartbeatCounter_{0};
  std::thread heartbeat_;
  std::thread monitor_;
  // Interruptible sleeps for both threads (stop() must not wait a full
  // period).
  std::mutex sleepMu_;
  std::condition_variable sleepCv_;

  mutable std::mutex mu_;
  uint64_t headEpoch_{0};          // latest epoch whose doc we installed
  std::vector<int64_t> members_;   // of headEpoch_, new-rank order
  Context* boundCtx_{nullptr};     // borrowed; owned by the caller
  uint64_t boundEpoch_{0};
  uint64_t closedEpoch_{0};        // bound epoch already closed as stale
  int boundRank_{-1};
  int boundDomain_{0};
  uint64_t rebuilds_{0};
  uint64_t bumpsPublished_{0};
  int64_t lastRebuildMs_{0};
  std::shared_ptr<const tuning::TuningTable> inheritedTable_;

  // Monitor-local lease observations: value + the steady-clock ms of the
  // last observed change (liveness is change observation, never clock
  // comparison across hosts).
  struct LeaseObs {
    uint64_t value{0};
    int64_t lastChangeMs{0};
    bool seen{false};
    bool changeSeen{false};  // observed an actual value TRANSITION
  };
  uint64_t monitorStateEpoch_{0};          // monitor thread only
  std::map<int64_t, LeaseObs> leases_;     // monitor thread only
  // Lease-aggregation state (monitor thread only). AggObs mirrors
  // LeaseObs one level up: change observation on the leader's embedded
  // beat decides whether the aggregate is trustworthy at all.
  struct AggObs {
    uint64_t leaderBeat{0};
    int64_t lastChangeMs{0};
    bool seen{false};
    // wid -> (present, lease value) as sampled by the host leader.
    std::map<int64_t, std::pair<bool, uint64_t>> values;
  };
  uint64_t hostMapEpoch_{0};               // monitor thread only
  std::map<int64_t, std::string> hostOf_;  // monitor thread only
  std::map<std::string, AggObs> aggObs_;   // monitor thread only
  uint64_t aggBeat_{0};                    // monitor thread only
  std::atomic<uint64_t> aggPublishes_{0};
  // Join-queue lease observations, kept across epoch changes (a joiner
  // is not a member) and pruned with the queue itself.
  std::map<int64_t, LeaseObs> joinLeases_;  // monitor thread only
  std::map<int64_t, int> strikes_;       // monitor thread only
  int64_t evidenceFirstMs_{0};           // monitor thread only
  // Claim-takeover bookkeeping (claimant died pre-publish).
  uint64_t pendingClaimEpoch_{0};        // monitor thread only
  int64_t pendingClaimSinceMs_{0};       // monitor thread only
};

// The members-only epoch rebuild as a first-class Context operation:
// build THE successor communicator this group continues as in `epoch`.
// `members` lists the surviving ranks of THIS context (sorted ascending;
// this rank must be a member); the child takes fresh contiguous ranks
// in that order, bootstraps its mesh under the epoch-scoped elastic
// namespace of the same store, carries group tag "e<epoch>" (epoch-
// tagged flight recorder / metrics / fault domain), and inherits the
// installed tuning table + host id. Requires a store-backed context
// (forked contexts have no store to re-rendezvous over). Defined in
// elastic/elastic.cc; ElasticAgent drives the same machinery with
// wid-based membership.
std::unique_ptr<Context> buildEpochContext(
    std::shared_ptr<Store> store, std::shared_ptr<transport::Device> device,
    int newRank, int newSize, uint64_t epoch, const std::string& hostId,
    std::shared_ptr<const tuning::TuningTable> table,
    std::chrono::milliseconds timeout);

}  // namespace elastic
}  // namespace tpucoll
