// Elastic membership plane (see elastic.h for the protocol). Lives in
// its own subsystem directory because it composes layers that must not
// know about each other: the rendezvous store (leases, epoch documents),
// the process-group bootstrap (members-only epoch meshes), and the
// post-mortem planes (fault evidence feeding membership decisions).
#include "tpucoll/elastic/elastic.h"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <utility>

#include "tpucoll/common/env.h"
#include "tpucoll/common/json.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/group/topology.h"
#include "tpucoll/tuning/tuning_table.h"

namespace tpucoll {
namespace elastic {

namespace {

constexpr const char* kNs = "tpucoll/elastic/";

std::string epochPrefix(uint64_t epoch) {
  return std::string(kNs) + "e" + std::to_string(epoch) + "/";
}

Store::Buf packCounter(uint64_t v) {
  Store::Buf buf(sizeof(v));
  std::memcpy(buf.data(), &v, sizeof(v));
  return buf;
}

uint64_t unpackCounter(const Store::Buf& buf) {
  uint64_t v = 0;
  std::memcpy(&v, buf.data(), std::min(buf.size(), sizeof(v)));
  return v;
}

// Lease/doc reads poll with short bounded gets: a missing key must
// return control to the monitor loop, never park it for the full
// default store timeout.
constexpr std::chrono::milliseconds kProbeTimeout{50};

// Aggregate-lease blob: [u32 magic][u64 leaderBeat][u32 count]
// [(i64 wid, u64 value, u8 present) x count]. The leader beat is the
// aggregate's OWN lease counter — observers change-observe it exactly
// like an individual lease to decide whether the embedded samples are
// live at all.
constexpr uint32_t kAggMagic = 0x7C0A66E5u;

void packU32(Store::Buf& buf, uint32_t v) {
  const size_t off = buf.size();
  buf.resize(off + sizeof(v));
  std::memcpy(buf.data() + off, &v, sizeof(v));
}

void packU64(Store::Buf& buf, uint64_t v) {
  const size_t off = buf.size();
  buf.resize(off + sizeof(v));
  std::memcpy(buf.data() + off, &v, sizeof(v));
}

// Host fingerprints become one path segment of the aggregate key; hash
// them so arbitrary TPUCOLL_HOST_ID strings cannot leak separators (or
// unbounded length) into the store namespace.
std::string fpHash(const std::string& fp) {
  uint64_t h = 0xcbf29ce484222325ULL;
  for (char c : fp) {
    h = (h ^ static_cast<uint8_t>(c)) * 0x100000001b3ULL;
  }
  char hex[17];
  std::snprintf(hex, sizeof(hex), "%016llx",
                static_cast<unsigned long long>(h));
  return std::string(hex);
}

}  // namespace

// ---------------------------------------------------------------------------
// Epoch-successor construction (shared by Context::rebuild and the agent)
// ---------------------------------------------------------------------------

std::unique_ptr<Context> buildEpochContext(
    std::shared_ptr<Store> store, std::shared_ptr<transport::Device> device,
    int newRank, int newSize, uint64_t epoch, const std::string& hostId,
    std::shared_ptr<const tuning::TuningTable> table,
    std::chrono::milliseconds timeout) {
  TC_ENFORCE(store != nullptr, "elastic rebuild: no store");
  TC_ENFORCE(device != nullptr, "elastic rebuild: no device");
  auto ctx = std::make_unique<Context>(newRank, newSize);
  ctx->setTimeout(timeout);
  ctx->hostId_ = hostId;
  // Group tag "e<epoch>": scopes post-bootstrap store keys, stamps the
  // flight recorder (dumps go to flightrec-rank<r>-ge<N>.json and the
  // documents carry "group":"e<N>"), the metrics "group" field, and a
  // deterministic fault-plane domain — the whole post-mortem identity
  // of the epoch.
  ctx->applyGroupTag("e" + std::to_string(epoch));
  if (table != nullptr) {
    ctx->setTuningTable(std::move(table));
  }
  auto prefix = std::make_shared<PrefixStore>(
      std::move(store), epochPrefix(epoch) + "mesh");
  ctx->connectFullMesh(std::move(prefix), std::move(device));
  return ctx;
}

}  // namespace elastic

std::unique_ptr<Context> Context::rebuild(const std::vector<int>& members,
                                          uint64_t epoch) {
  TC_ENFORCE(store_ != nullptr,
             "rebuild: store-less (forked) context cannot re-rendezvous");
  TC_ENFORCE(!members.empty(), "rebuild: empty member list");
  TC_ENFORCE(std::is_sorted(members.begin(), members.end()),
             "rebuild: members must be sorted ascending");
  auto it = std::find(members.begin(), members.end(), rank_);
  TC_ENFORCE(it != members.end(), "rebuild: rank ", rank_,
             " is not in the member list");
  const int newRank = static_cast<int>(it - members.begin());
  return elastic::buildEpochContext(
      store_, device_, newRank, static_cast<int>(members.size()), epoch,
      hostId_, tuningTable(), timeout_);
}

namespace elastic {

// ---------------------------------------------------------------------------
// ElasticAgent
// ---------------------------------------------------------------------------

ElasticAgent::ElasticAgent(std::shared_ptr<Store> store,
                           std::shared_ptr<transport::Device> device,
                           const AgentOptions& opts)
    : store_(std::move(store)),
      device_(std::move(device)),
      opts_(opts),
      leaseMs_(envCount("TPUCOLL_LEASE_MS", 500, 50, 60000)),
      graceMs_(envCount("TPUCOLL_LEASE_GRACE", 3000, 100, 600000)),
      pollMs_(std::max(20L, std::min(500L, leaseMs_ / 2))),
      leaseAgg_(envFlag("TPUCOLL_LEASE_AGG", false)) {
  TC_ENFORCE(store_ != nullptr, "elastic: no store");
  TC_ENFORCE(device_ != nullptr, "elastic: no device");
  TC_ENFORCE_GE(graceMs_, 2 * leaseMs_,
                "TPUCOLL_LEASE_GRACE must be at least 2x TPUCOLL_LEASE_MS "
                "(a single delayed renewal must not read as a death)");
  TC_ENFORCE_GT(opts_.worldSize, 0, "elastic: world size must be positive");
  TC_ENFORCE_GT(opts_.minSize, 0, "elastic: min size must be positive");
  TC_ENFORCE_LE(opts_.minSize, opts_.worldSize,
                "elastic: min size exceeds the target world size");

  if (leaseAgg_) {
    hostFp_ = hostFingerprint(opts_.hostId);
  }
  const auto deadline = std::chrono::steady_clock::now() + opts_.timeout;
  if (!opts_.join) {
    TC_ENFORCE(opts_.rank >= 0 && opts_.rank < opts_.worldSize,
               "elastic: rank ", opts_.rank, " out of range for world size ",
               opts_.worldSize);
    wid_ = opts_.rank;
    if (leaseAgg_) {
      // Host mapping before the first lease: any monitor that can see
      // this wid as a member must be able to place it on a host.
      store_->set(k("host/" + std::to_string(wid_)),
                  Store::Buf(hostFp_.begin(), hostFp_.end()));
    }
    heartbeatOnce();
    if (opts_.rank == 0) {
      // Found epoch 1. The claim keeps a restarted rank 0 from
      // re-founding over a live job's document.
      if (store_->add(epochPrefix(1) + "claim", 1) == 1) {
        std::vector<int64_t> members(opts_.worldSize);
        for (int r = 0; r < opts_.worldSize; r++) {
          members[r] = r;
        }
        store_->set(epochPrefix(1) + "doc",
                    [&] {
                      const std::string doc = docJson(1, members, "found");
                      return Store::Buf(doc.begin(), doc.end());
                    }());
        store_->add(std::string(kNs) + "head", 1);
      }
    }
  } else {
    // Joiner: allocate a never-reused wid above the founding range,
    // start heartbeating, then enqueue. The lease must exist BEFORE the
    // join key: the coordinator only admits joiners it can see alive.
    wid_ = opts_.worldSize - 1 + store_->add(std::string(kNs) + "nextwid", 1);
    if (leaseAgg_) {
      store_->set(k("host/" + std::to_string(wid_)),
                  Store::Buf(hostFp_.begin(), hostFp_.end()));
    }
    heartbeatOnce();
    store_->set(std::string(kNs) + "join/" + std::to_string(wid_),
                Store::Buf{1});
  }

  // Wait for the first visible epoch document (founders: epoch 1;
  // joiners: whatever the job is at).
  while (true) {
    refreshHead();  // best-effort: a doc still in flight retries below
    {
      std::lock_guard<std::mutex> guard(mu_);
      if (headEpoch_ >= 1) {
        break;
      }
    }
    TC_ENFORCE(std::chrono::steady_clock::now() < deadline,
               "elastic: no epoch document appeared within ",
               opts_.timeout.count(), "ms — is rank 0 (the founder) up?");
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }

  heartbeat_ = std::thread([this] { heartbeatLoop(); });
  monitor_ = std::thread([this] { monitorLoop(); });
}

ElasticAgent::~ElasticAgent() {
  try {
    stop();
  } catch (...) {
    // Destructor boundary: a store that died under us must not abort.
  }
}

std::string ElasticAgent::k(const std::string& suffix) const {
  return std::string(kNs) + suffix;
}

std::string ElasticAgent::leaseKey(int64_t wid) const {
  return std::string(kNs) + "lease/" + std::to_string(wid);
}

std::string ElasticAgent::aggKey(const std::string& hostFp) const {
  return std::string(kNs) + "agg/" + fpHash(hostFp);
}

void ElasticAgent::refreshHostMap(const std::vector<int64_t>& members) {
  if (hostMapEpoch_ == monitorStateEpoch_) {
    bool complete = true;
    for (int64_t w : members) {
      if (hostOf_.find(w) == hostOf_.end()) {
        complete = false;
        break;
      }
    }
    if (complete) {
      return;
    }
  }
  std::map<int64_t, std::string> next;
  for (int64_t w : members) {
    auto it = hostOf_.find(w);
    if (it != hostOf_.end()) {
      next.emplace(w, it->second);
      continue;
    }
    if (w == wid_) {
      next.emplace(w, hostFp_);
      continue;
    }
    try {
      Store::Buf raw =
          store_->get(k("host/" + std::to_string(w)), kProbeTimeout);
      next.emplace(w, std::string(raw.begin(), raw.end()));
    } catch (const TimeoutException&) {
      // Not published yet (write in flight, or a pre-aggregation
      // worker): the member stays on the individual-lease path until
      // its mapping appears.
    }
  }
  hostOf_ = std::move(next);
  hostMapEpoch_ = monitorStateEpoch_;
}

bool ElasticAgent::actingHostLeader(const std::vector<int64_t>& members,
                                    int64_t now) {
  // Members are wid-ascending (founders 0..N-1; joiner wids come from a
  // monotone counter and are appended), so the first same-host member
  // reached is the host's nominal leader. A lower same-host wid only
  // yields the role once OBSERVED expired — until then its (possibly
  // stale) aggregate is still the host's authority and a second writer
  // would flap the key.
  for (int64_t w : members) {
    if (w == wid_) {
      return true;
    }
    auto hit = hostOf_.find(w);
    if (hit == hostOf_.end() || hit->second != hostFp_) {
      continue;
    }
    auto lit = leases_.find(w);
    if (lit == leases_.end() || lit->second.lastChangeMs == 0 ||
        now - lit->second.lastChangeMs <= graceMs_) {
      return false;  // lower-wid leader not (yet) observed dead
    }
  }
  return false;
}

void ElasticAgent::publishAggregate(const std::vector<int64_t>& members) {
  std::vector<std::pair<int64_t, std::pair<bool, uint64_t>>> rows;
  for (int64_t w : members) {
    auto hit = hostOf_.find(w);
    if (hit == hostOf_.end() || hit->second != hostFp_) {
      continue;
    }
    bool present = false;
    uint64_t value = 0;
    if (w == wid_) {
      present = true;
      value = heartbeatCounter_.load(std::memory_order_relaxed);
    } else if (store_->check({leaseKey(w)})) {
      try {
        value = unpackCounter(store_->get(leaseKey(w), kProbeTimeout));
        present = true;
      } catch (const TimeoutException&) {
        // Deleted between check and get: report absent.
      }
    }
    rows.emplace_back(w, std::make_pair(present, value));
  }
  Store::Buf blob;
  packU32(blob, kAggMagic);
  packU64(blob, ++aggBeat_);
  packU32(blob, static_cast<uint32_t>(rows.size()));
  for (const auto& row : rows) {
    packU64(blob, static_cast<uint64_t>(row.first));
    packU64(blob, row.second.second);
    blob.push_back(row.second.first ? 1 : 0);
  }
  store_->set(aggKey(hostFp_), blob);
  aggPublishes_.fetch_add(1, std::memory_order_relaxed);
}

void ElasticAgent::sampleAggregates(const std::vector<int64_t>& members,
                                    int64_t now) {
  std::vector<std::string> fps;
  for (int64_t w : members) {
    auto hit = hostOf_.find(w);
    if (hit != hostOf_.end() &&
        std::find(fps.begin(), fps.end(), hit->second) == fps.end()) {
      fps.push_back(hit->second);
    }
  }
  for (const auto& fp : fps) {
    AggObs& obs = aggObs_[fp];
    if (obs.lastChangeMs == 0) {
      obs.lastChangeMs = now;
    }
    Store::Buf raw;
    try {
      raw = store_->get(aggKey(fp), kProbeTimeout);
    } catch (const TimeoutException&) {
      continue;  // no leader published yet: individual path covers it
    }
    constexpr size_t kHeader = 16;  // magic + beat + count
    constexpr size_t kRow = 17;     // wid + value + present
    if (raw.size() < kHeader) {
      continue;
    }
    uint32_t magic = 0;
    uint64_t beat = 0;
    uint32_t count = 0;
    std::memcpy(&magic, raw.data(), sizeof(magic));
    std::memcpy(&beat, raw.data() + 4, sizeof(beat));
    std::memcpy(&count, raw.data() + 12, sizeof(count));
    if (magic != kAggMagic || raw.size() < kHeader + size_t(count) * kRow) {
      continue;  // torn or foreign blob: degrade, never misjudge
    }
    std::map<int64_t, std::pair<bool, uint64_t>> values;
    size_t off = kHeader;
    for (uint32_t i = 0; i < count; i++) {
      int64_t w = 0;
      uint64_t v = 0;
      std::memcpy(&w, raw.data() + off, sizeof(w));
      std::memcpy(&v, raw.data() + off + 8, sizeof(v));
      values[w] = {raw[off + 16] != 0, v};
      off += kRow;
    }
    if (!obs.seen || beat != obs.leaderBeat) {
      obs.seen = true;
      obs.leaderBeat = beat;
      obs.lastChangeMs = now;
    }
    obs.values = std::move(values);
  }
  for (auto it = aggObs_.begin(); it != aggObs_.end();) {
    if (std::find(fps.begin(), fps.end(), it->first) == fps.end()) {
      it = aggObs_.erase(it);
    } else {
      ++it;
    }
  }
}

void ElasticAgent::readLease(int64_t w, int64_t now, bool* present,
                             uint64_t* value) {
  *present = false;
  *value = 0;
  if (leaseAgg_) {
    auto hit = hostOf_.find(w);
    if (hit != hostOf_.end()) {
      auto ait = aggObs_.find(hit->second);
      if (ait != aggObs_.end() && ait->second.seen &&
          now - ait->second.lastChangeMs <= graceMs_) {
        auto vit = ait->second.values.find(w);
        if (vit != ait->second.values.end()) {
          *present = vit->second.first;
          *value = vit->second.second;
          return;
        }
        // The leader's blob predates this member: fall through to the
        // individual key until the next aggregate covers it.
      }
      // Stale or absent aggregate (dead leader): degraded path below —
      // the host's members are judged by their individual leases for
      // the grace window until a successor leader takes over.
    }
  }
  if (!store_->check({leaseKey(w)})) {
    return;
  }
  try {
    *value = unpackCounter(store_->get(leaseKey(w), kProbeTimeout));
    *present = true;
  } catch (const TimeoutException&) {
    // Deleted between check and get: report absent.
  }
}

int64_t ElasticAgent::nowMs() const {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void ElasticAgent::heartbeatOnce() {
  // Relaxed: the counter's only job is to CHANGE between renewals;
  // observers compare values, never order against other memory.
  const uint64_t beat =
      heartbeatCounter_.fetch_add(1, std::memory_order_relaxed) + 1;
  store_->set(leaseKey(wid_), packCounter(beat));
  leasesRenewed_.fetch_add(1, std::memory_order_relaxed);
}

void ElasticAgent::heartbeatLoop() {
  while (!stop_.load(std::memory_order_relaxed)) {
    try {
      heartbeatOnce();
    } catch (const std::exception& e) {
      // A store hiccup must not kill the renewal thread: peers only
      // declare us dead after a full grace of NO renewals.
      TC_WARN("elastic: lease renewal failed (wid ", wid_, "): ", e.what());
    }
    std::unique_lock<std::mutex> lk(sleepMu_);
    sleepCv_.wait_for(lk, std::chrono::milliseconds(leaseMs_), [&] {
      return stop_.load(std::memory_order_relaxed);
    });
  }
}

void ElasticAgent::monitorLoop() {
  while (!stop_.load(std::memory_order_relaxed)) {
    try {
      monitorOnce();
    } catch (const std::exception& e) {
      TC_DEBUG("elastic: monitor pass failed (wid ", wid_, "): ", e.what());
    }
    std::unique_lock<std::mutex> lk(sleepMu_);
    sleepCv_.wait_for(lk, std::chrono::milliseconds(pollMs_), [&] {
      return stop_.load(std::memory_order_relaxed);
    });
  }
}

void ElasticAgent::installDoc(uint64_t epoch, const std::string& raw) {
  JsonReader reader(raw, "elastic epoch document");
  auto doc = reader.parse();
  const auto* membersField = doc.field("members");
  TC_ENFORCE(membersField != nullptr &&
                 membersField->kind == JsonReader::Value::Kind::kArray,
             "elastic epoch document: missing members array");
  std::vector<int64_t> members;
  members.reserve(membersField->items.size());
  for (const auto& item : membersField->items) {
    TC_ENFORCE(item.kind == JsonReader::Value::Kind::kNumber,
               "elastic epoch document: non-numeric member");
    members.push_back(static_cast<int64_t>(item.number));
  }
  TC_ENFORCE(!members.empty(), "elastic epoch document: empty membership");

  std::lock_guard<std::mutex> guard(mu_);
  if (epoch <= headEpoch_) {
    return;  // raced another installer
  }
  headEpoch_ = epoch;
  members_ = std::move(members);
  if (boundCtx_ != nullptr && boundEpoch_ < epoch &&
      closedEpoch_ != boundEpoch_) {
    closedEpoch_ = boundEpoch_;
    TC_INFO("elastic: epoch moved to ", epoch, " — closing the epoch-",
            boundEpoch_, " context (in-flight collectives fail typed)");
    // Closed while HOLDING mu_: the owner's rebuild() unbinds under the
    // same mutex before the context can be freed, so the pointer cannot
    // die under this close. Context::close never re-enters the agent,
    // so the nesting cannot deadlock; statusJson briefly blocks, which
    // is acceptable on an epoch transition.
    boundCtx_->close();
  }
}

void ElasticAgent::refreshHead() {
  const uint64_t head =
      static_cast<uint64_t>(store_->add(k("head"), 0));
  uint64_t observed;
  {
    std::lock_guard<std::mutex> guard(mu_);
    observed = headEpoch_;
  }
  if (head <= observed) {
    return;
  }
  // Catch up one document at a time, best-effort with SHORT probes:
  // an intermediate epoch's doc may be reaped (skip it), and the head
  // epoch's doc may not have landed yet — publication in flight, or a
  // transient counter overshoot from a raced head repair — in which
  // case we simply return and the next poll retries. Blocking or
  // throwing here would starve the rest of the monitor pass (liveness
  // scans, bump publication) behind a store state that only ever
  // resolves via those very passes.
  for (uint64_t e = observed + 1; e <= head; e++) {
    Store::Buf raw;
    try {
      raw = store_->get(epochPrefix(e) + "doc", kProbeTimeout);
    } catch (const TimeoutException&) {
      if (e == head) {
        return;  // not published yet; next poll catches it
      }
      continue;  // reaped intermediate epoch
    }
    installDoc(e, std::string(raw.begin(), raw.end()));
  }
}

std::string ElasticAgent::docJson(uint64_t epoch,
                                  const std::vector<int64_t>& members,
                                  const char* cause) {
  std::ostringstream out;
  out << "{\"epoch\":" << epoch << ",\"members\":[";
  for (size_t i = 0; i < members.size(); i++) {
    out << (i == 0 ? "" : ",") << members[i];
  }
  out << "],\"cause\":\"" << cause << "\"}";
  return out.str();
}

bool ElasticAgent::publishEpoch(uint64_t target,
                                const std::vector<int64_t>& members,
                                const char* cause,
                                const std::vector<int64_t>& dead,
                                const std::vector<int64_t>& admitted) {
  const std::string docKey = epochPrefix(target) + "doc";
  const bool docAlready = store_->check({docKey});
  if (!docAlready && store_->add(epochPrefix(target) + "claim", 1) != 1) {
    // Another monitor claimed this epoch. If its document never lands
    // (claimant died between claim and publish), take over after a
    // grace: by then the claimant's own lease has expired, so at most
    // one OTHER live monitor believes it is the coordinator.
    if (pendingClaimEpoch_ != target) {
      pendingClaimEpoch_ = target;
      pendingClaimSinceMs_ = nowMs();
      return false;
    }
    if (nowMs() - pendingClaimSinceMs_ < graceMs_ ||
        store_->check({docKey})) {
      // Document landed (or will shortly): fall through to the head
      // repair below rather than returning — a claimant that died
      // BETWEEN set(doc) and the head bump must not wedge the plane.
      if (!store_->check({docKey})) {
        return false;
      }
    } else {
      TC_WARN("elastic: epoch ", target, " claimant never published — "
              "taking over (wid ", wid_, ")");
    }
  }
  pendingClaimEpoch_ = 0;
  // Re-check before writing: the document is immutable once present
  // (a claimant paused past the takeover grace that revives here must
  // not overwrite the takeover's document with a divergent member
  // list; the remaining check-then-set window is one store round trip
  // wide and converges through the evidence path).
  if (!store_->check({docKey})) {
    store_->set(docKey, [&] {
      const std::string doc = docJson(target, members, cause);
      return Store::Buf(doc.begin(), doc.end());
    }());
  }
  // Head bump, exactly once per epoch regardless of who dies where:
  // the doc-set and the head increment are two store writes, so the
  // bump rides its own single-winner claim ("headbump"), and the
  // winner verifies head == target - 1 first — a stale reviver whose
  // epoch was already counted (or reaped) skips, while a genuine
  // repair (claimant died between doc and bump) lands it.
  if (store_->add(epochPrefix(target) + "headbump", 1) == 1 ||
      static_cast<uint64_t>(store_->add(k("head"), 0)) < target) {
    if (static_cast<uint64_t>(store_->add(k("head"), 0)) == target - 1) {
      store_->add(k("head"), 1);
    }
  }
  TC_INFO("elastic: published epoch ", target, " (", cause, "), ",
          members.size(), " member(s)");
  {
    std::lock_guard<std::mutex> guard(mu_);
    bumpsPublished_++;
  }
  // ---- reap: leases of the departed, consumed join requests, the
  // evidence that drove this bump, and the retired e<target-2>
  // namespace (whose mesh bootstrap blobs are the bulk of the keys).
  for (int64_t w : dead) {
    store_->deleteKey(leaseKey(w));
    if (leaseAgg_) {
      store_->deleteKey(k("host/" + std::to_string(w)));
    }
  }
  for (int64_t w : admitted) {
    store_->deleteKey(k("join/" + std::to_string(w)));
  }
  for (const auto& key : store_->listKeys(epochPrefix(target - 1) + "fail/")) {
    store_->deleteKey(key);
  }
  if (target >= 3) {
    for (const auto& key : store_->listKeys(epochPrefix(target - 2))) {
      store_->deleteKey(key);
    }
  }
  return true;
}

void ElasticAgent::monitorOnce() {
  refreshHead();

  uint64_t H;
  std::vector<int64_t> members;
  {
    std::lock_guard<std::mutex> guard(mu_);
    H = headEpoch_;
    members = members_;
  }
  if (std::find(members.begin(), members.end(), wid_) == members.end()) {
    return;  // join pending or evicted: nothing to monitor yet
  }
  // Epoch moved since the last pass: reset the monitor-local state
  // (this thread is its only toucher — installDoc runs on app threads
  // too and must not reach into it). Departed wids lose their lease
  // observations so a later same-wid entry never inherits a stale
  // change timestamp.
  if (monitorStateEpoch_ != H) {
    monitorStateEpoch_ = H;
    for (auto it = leases_.begin(); it != leases_.end();) {
      if (std::find(members.begin(), members.end(), it->first) ==
          members.end()) {
        it = leases_.erase(it);
      } else {
        ++it;
      }
    }
    evidenceFirstMs_ = 0;
    pendingClaimEpoch_ = 0;
  }

  // ---- liveness: change observation on every other member's lease ----
  // With TPUCOLL_LEASE_AGG the per-member sample comes from the member's
  // host aggregate (O(hosts) store reads per pass, refreshed just
  // below) instead of its individual key (O(N)); the change-observation
  // logic on the sampled value is identical either way.
  const int64_t now = nowMs();
  if (leaseAgg_) {
    refreshHostMap(members);
    if (actingHostLeader(members, now)) {
      publishAggregate(members);
    }
    sampleAggregates(members, now);
  }
  std::vector<int64_t> dead;
  for (int64_t w : members) {
    if (w == wid_) {
      continue;
    }
    LeaseObs& obs = leases_[w];
    if (obs.lastChangeMs == 0) {
      obs.lastChangeMs = now;  // first observation of this member
    }
    bool present = false;
    uint64_t value = 0;
    readLease(w, now, &present, &value);
    if (!present) {
      if (obs.seen) {
        dead.push_back(w);  // deleted lease: graceful leave, no grace
      } else if (now - obs.lastChangeMs > graceMs_) {
        dead.push_back(w);  // admitted but never heartbeated
      }
      continue;
    }
    if (!obs.seen || value != obs.value) {
      obs.seen = true;
      obs.value = value;
      obs.lastChangeMs = now;
    } else if (now - obs.lastChangeMs > graceMs_) {
      dead.push_back(w);
    }
  }

  // ---- hard failure evidence published by survivors -----------------
  const std::string failPrefix = epochPrefix(H) + "fail/";
  std::vector<std::string> failKeys = store_->listKeys(failPrefix);
  if (failKeys.empty()) {
    evidenceFirstMs_ = 0;
  } else if (evidenceFirstMs_ == 0) {
    evidenceFirstMs_ = now;
  }

  // ---- only the coordinator (lowest LIVE wid) publishes -------------
  int64_t lowestLive = -1;
  for (int64_t w : members) {
    if (std::find(dead.begin(), dead.end(), w) == dead.end()) {
      lowestLive = w;
      break;
    }
  }
  if (lowestLive != wid_) {
    return;
  }

  if (!dead.empty()) {
    // Death bump: survivors only. Evidence is subsumed (the fresh mesh
    // excludes the dead) and strikes reset with the new membership.
    std::vector<int64_t> next;
    for (int64_t w : members) {
      if (std::find(dead.begin(), dead.end(), w) == dead.end()) {
        next.push_back(w);
      }
    }
    if (!next.empty() &&
        publishEpoch(H + 1, next, "lease_expired", dead, {})) {
      strikes_.clear();
    }
    return;
  }

  if (!failKeys.empty() && now - evidenceFirstMs_ > graceMs_) {
    // Evidence with every lease alive: a broken link / poisoned mesh,
    // not a death. Wait one grace first — a SIGKILL's EOF evidence
    // arrives before its lease expires, and the death bump above is the
    // better (smaller) transition. Then rebuild with the SAME members;
    // a wid blamed twice running is excluded (persistently bad link or
    // wedged peer).
    std::map<int64_t, int> suspects;
    for (const auto& key : failKeys) {
      try {
        Store::Buf raw = store_->get(key, kProbeTimeout);
        JsonReader reader(std::string(raw.begin(), raw.end()),
                          "elastic failure evidence");
        auto doc = reader.parse();
        const auto* s = doc.field("suspect_wid");
        if (s != nullptr && s->kind == JsonReader::Value::Kind::kNumber &&
            s->number >= 0) {
          suspects[static_cast<int64_t>(s->number)]++;
        }
      } catch (const std::exception&) {
        continue;  // torn/reaped evidence: the bump itself still happens
      }
    }
    int64_t modal = -1;
    int votes = 0;
    for (const auto& kv : suspects) {
      if (kv.second > votes) {
        modal = kv.first;
        votes = kv.second;
      }
    }
    std::vector<int64_t> next = members;
    if (modal >= 0 && ++strikes_[modal] >= 2 &&
        static_cast<int>(members.size()) > 1) {
      next.erase(std::remove(next.begin(), next.end(), modal), next.end());
      TC_WARN("elastic: wid ", modal, " blamed in two consecutive "
              "evidence rounds — excluding it from epoch ", H + 1);
    }
    publishEpoch(H + 1, next, "evidence", {}, {});
    return;
  }

  // ---- grow: admit live joiners once the current epoch has settled --
  // Settled means: no unconsumed failure evidence (the epoch may be
  // about to shrink), and every member's lease FRESHLY renewed — a
  // member that stopped renewing but has not yet crossed the grace is
  // very possibly dead, and admitting a joiner now would bootstrap the
  // next mesh around a corpse (everyone would slip one full mesh
  // timeout before the death bump rescues them).
  if (!failKeys.empty()) {
    return;
  }
  const long freshMs = std::max(2 * leaseMs_ + pollMs_, 500L);
  for (int64_t w : members) {
    if (w == wid_) {
      continue;
    }
    auto it = leases_.find(w);
    if (it == leases_.end() || !it->second.seen ||
        now - it->second.lastChangeMs > freshMs) {
      return;
    }
  }
  std::vector<int64_t> joiners;
  std::vector<int64_t> joinSeen;
  for (const auto& key : store_->listKeys(k("join/"))) {
    const std::string name = key.substr(key.rfind('/') + 1);
    char* end = nullptr;
    const int64_t w = std::strtoll(name.c_str(), &end, 10);
    if (end == name.c_str() || *end != '\0') {
      continue;
    }
    joinSeen.push_back(w);
    if (std::find(members.begin(), members.end(), w) != members.end()) {
      store_->deleteKey(key);  // stale request from a current member
      continue;
    }
    // A joiner is admissible only once its lease has been OBSERVED TO
    // CHANGE recently: mere key presence could be the leftover of a
    // joiner that died right after enqueueing, and admitting a corpse
    // stalls every member in the next epoch's bootstrap. The one-
    // transition requirement costs a healthy joiner ~one lease period.
    LeaseObs& obs = joinLeases_[w];
    if (obs.lastChangeMs == 0) {
      obs.lastChangeMs = now;
    }
    if (!store_->check({leaseKey(w)})) {
      if (obs.seen || now - obs.lastChangeMs > graceMs_) {
        store_->deleteKey(key);  // died (or never lived) while queued
        joinLeases_.erase(w);
      }
      continue;
    }
    const uint64_t value =
        unpackCounter(store_->get(leaseKey(w), kProbeTimeout));
    if (!obs.seen || value != obs.value) {
      obs.changeSeen = obs.seen;  // a transition, not a first sighting
      obs.seen = true;
      obs.value = value;
      obs.lastChangeMs = now;
    } else if (now - obs.lastChangeMs > graceMs_) {
      // Queued corpse: reap its request and lease so the queue stays
      // clean and a later epoch never trips over it.
      store_->deleteKey(key);
      store_->deleteKey(leaseKey(w));
      joinLeases_.erase(w);
      continue;
    }
    if (obs.changeSeen && now - obs.lastChangeMs <= freshMs) {
      joiners.push_back(w);
    }
  }
  // Drop observations for requests that vanished (admitted elsewhere
  // or reaped) so the map cannot grow without bound.
  for (auto it = joinLeases_.begin(); it != joinLeases_.end();) {
    if (std::find(joinSeen.begin(), joinSeen.end(), it->first) ==
        joinSeen.end()) {
      it = joinLeases_.erase(it);
    } else {
      ++it;
    }
  }
  if (joiners.empty()) {
    return;
  }
  std::vector<std::string> readyKeys;
  readyKeys.reserve(members.size());
  for (int64_t w : members) {
    readyKeys.push_back(epochPrefix(H) + "ready/" + std::to_string(w));
  }
  if (!store_->check(readyKeys)) {
    return;  // the current transition has not finished — admit later
  }
  std::sort(joiners.begin(), joiners.end());
  std::vector<int64_t> next = members;  // survivors keep relative order
  next.insert(next.end(), joiners.begin(), joiners.end());
  if (publishEpoch(H + 1, next, "join", {}, joiners)) {
    strikes_.clear();
  }
}

std::unique_ptr<Context> ElasticAgent::rebuild(
    std::chrono::milliseconds timeout) {
  if (timeout.count() <= 0) {
    timeout = opts_.timeout;
  }
  const auto deadline = std::chrono::steady_clock::now() + timeout;
  {
    // Unbind first: the monitor must stop reaching the old context the
    // moment the owner is about to replace (and later free) it. Capture
    // the installed tuning table so the successor keeps the deployment's
    // measured dispatch.
    std::lock_guard<std::mutex> guard(mu_);
    if (boundCtx_ != nullptr) {
      inheritedTable_ = boundCtx_->tuningTable();
    }
    boundCtx_ = nullptr;
  }
  const int64_t t0 = nowMs();

  while (true) {
    if (std::chrono::steady_clock::now() >= deadline) {
      // Typed: callers distinguish "retry later" (timeout) from the
      // terminal evicted / below-min-size verdicts below.
      TC_THROW(TimeoutException, "elastic: rebuild did not converge "
               "within ", timeout.count(), "ms (head epoch ",
               headEpoch(), ")");
    }
    refreshHead();  // best-effort; a not-yet-published head retries below
    uint64_t H;
    std::vector<int64_t> members;
    std::shared_ptr<const tuning::TuningTable> table;
    {
      std::lock_guard<std::mutex> guard(mu_);
      H = headEpoch_;
      members = members_;
      table = inheritedTable_;
    }
    auto self = std::find(members.begin(), members.end(), wid_);
    if (self == members.end()) {
      if (opts_.join) {
        // Enqueued but not yet admitted: the coordinator admits at the
        // next boundary once the current epoch settles.
        std::this_thread::sleep_for(std::chrono::milliseconds(pollMs_));
        continue;
      }
      TC_THROW(IoException, "elastic: wid ", wid_,
               " was evicted from the membership at epoch ", H);
    }
    if (static_cast<int>(members.size()) < opts_.minSize) {
      TC_THROW(IoException, "elastic: membership shrank to ",
               members.size(), " member(s) at epoch ", H,
               ", below min_size ", opts_.minSize);
    }
    const int newRank = static_cast<int>(self - members.begin());
    const int newSize = static_cast<int>(members.size());

    // Per-attempt mesh timeout: small enough that an epoch superseded
    // mid-bootstrap (a second death during the transition) costs one
    // bounded slip, not the whole rebuild budget.
    const auto remaining =
        std::chrono::duration_cast<std::chrono::milliseconds>(
            deadline - std::chrono::steady_clock::now());
    const auto attempt = std::min(
        remaining,
        std::chrono::milliseconds(std::max(4 * graceMs_, 5000L)));
    std::unique_ptr<Context> ctx;
    try {
      ctx = buildEpochContext(store_, device_, newRank, newSize, H,
                              opts_.hostId, table, attempt);
    } catch (const std::exception& e) {
      TC_INFO("elastic: epoch ", H, " mesh bootstrap failed (", e.what(),
              ") — publishing evidence and retrying");
      try {
        store_->set(epochPrefix(H) + "fail/" + std::to_string(wid_),
                    [&] {
                      const std::string ev =
                          "{\"suspect_wid\":-1,\"kind\":\"rebuild_failed\"}";
                      return Store::Buf(ev.begin(), ev.end());
                    }());
      } catch (const std::exception&) {
        // Evidence is best-effort; the retry loop itself recovers.
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(pollMs_));
      continue;
    }

    ctx->setTimeout(opts_.timeout);  // attempt bound was bootstrap-only
    {
      std::lock_guard<std::mutex> guard(mu_);
      boundCtx_ = ctx.get();
      boundEpoch_ = H;
      boundRank_ = newRank;
      boundDomain_ = ctx->faultDomain();
      closedEpoch_ = 0;
      rebuilds_++;
      lastRebuildMs_ = nowMs() - t0;
      inheritedTable_ = ctx->tuningTable();
    }
    store_->set(epochPrefix(H) + "ready/" + std::to_string(wid_),
                Store::Buf{1});
    return ctx;
  }
}

void ElasticAgent::noteFailure(const std::string& evidenceJson) {
  uint64_t epoch;
  {
    std::lock_guard<std::mutex> guard(mu_);
    epoch = boundEpoch_ != 0 ? boundEpoch_ : headEpoch_;
  }
  store_->set(epochPrefix(epoch) + "fail/" + std::to_string(wid_),
              Store::Buf(evidenceJson.begin(), evidenceJson.end()));
}

void ElasticAgent::stop() {
  // Relaxed: pure exit flag; the joins below are the sync points.
  const bool already = stop_.exchange(true, std::memory_order_relaxed);
  sleepCv_.notify_all();
  if (heartbeat_.joinable()) {
    heartbeat_.join();
  }
  if (monitor_.joinable()) {
    monitor_.join();
  }
  {
    std::lock_guard<std::mutex> guard(mu_);
    boundCtx_ = nullptr;
  }
  if (!already && wid_ >= 0) {
    // Graceful leave: a deleted (previously seen) lease is an immediate
    // departure for every observer — no grace wait. A departing host
    // leader's aggregate simply goes stale; observers degrade to the
    // individual leases of that host until the successor publishes.
    store_->deleteKey(leaseKey(wid_));
    store_->deleteKey(k("join/" + std::to_string(wid_)));
    if (leaseAgg_) {
      store_->deleteKey(k("host/" + std::to_string(wid_)));
    }
  }
}

uint64_t ElasticAgent::boundEpoch() const {
  std::lock_guard<std::mutex> guard(mu_);
  return boundEpoch_;
}

uint64_t ElasticAgent::headEpoch() const {
  std::lock_guard<std::mutex> guard(mu_);
  return headEpoch_;
}

bool ElasticAgent::epochChanged() const {
  std::lock_guard<std::mutex> guard(mu_);
  return boundEpoch_ == 0 || headEpoch_ > boundEpoch_;
}

std::string ElasticAgent::statusJson() const {
  std::lock_guard<std::mutex> guard(mu_);
  int64_t lowest = -1;
  for (int64_t w : members_) {
    lowest = lowest < 0 ? w : std::min(lowest, w);
  }
  const bool joinPending =
      std::find(members_.begin(), members_.end(), wid_) == members_.end();
  std::ostringstream out;
  out << "{\"epoch\":" << boundEpoch_ << ",\"head_epoch\":" << headEpoch_
      << ",\"wid\":" << wid_ << ",\"rank\":" << boundRank_
      << ",\"size\":" << members_.size() << ",\"members\":[";
  for (size_t i = 0; i < members_.size(); i++) {
    out << (i == 0 ? "" : ",") << members_[i];
  }
  out << "],\"target_size\":" << opts_.worldSize
      << ",\"min_size\":" << opts_.minSize << ",\"coordinator\":"
      << (wid_ == lowest && !joinPending ? "true" : "false")
      << ",\"join_pending\":" << (joinPending ? "true" : "false")
      << ",\"leases_renewed\":"
      << leasesRenewed_.load(std::memory_order_relaxed)
      << ",\"rebuilds\":" << rebuilds_
      << ",\"bumps_published\":" << bumpsPublished_
      << ",\"last_rebuild_ms\":" << lastRebuildMs_
      << ",\"fault_domain\":" << boundDomain_
      << ",\"lease_ms\":" << leaseMs_ << ",\"lease_grace_ms\":" << graceMs_
      << ",\"lease_agg\":" << (leaseAgg_ ? "true" : "false")
      << ",\"agg_publishes\":"
      << aggPublishes_.load(std::memory_order_relaxed) << "}";
  return out.str();
}

}  // namespace elastic
}  // namespace tpucoll
