// Measured tuning tables for collective auto-dispatch.
//
// Every kAuto threshold in the collectives was measured ONCE, on one
// loopback host (collectives_ring.cc / collectives_hd.cc admit as much in
// their comments: "re-sweep on real DCN"). GC3 (arXiv:2201.11840) and
// HiCCL (arXiv:2408.05962) both make the same point: collective
// performance is won by specializing the schedule to the actual fabric
// and payload, not by one-size compile-time constants. This module holds
// the deployment-measured replacement: a table of per-(collective,
// algorithm, world-size, dtype, log2-size-bucket) costs produced by the
// tuner (tuner.h), serialized as JSON, and installed identically on every
// rank of a Context. kAuto dispatch consults the installed table first
// (tuning/dispatch.h) and falls back to the historical constants when no
// table is loaded, so untuned deployments behave exactly as before.
//
// Determinism contract: algorithm election must agree on every rank or a
// collective deadlocks (ranks would run different schedules). The table
// guarantees this structurally — all ranks install byte-identical JSON
// (rank 0's measurements, published through the rendezvous Store), and
// choose() is a pure function of (collective, world size, dtype, nbytes),
// which the collective contract already requires to match across ranks.
#pragma once

#include <cstddef>
#include <cstdint>
#include <map>
#include <optional>
#include <string>
#include <vector>

namespace tpucoll {
namespace tuning {

// One measured cell: the mean latency of `algorithm` serving `collective`
// at payloads of ~2^bucket bytes in a `worldSize`-rank group.
struct Measurement {
  std::string collective;  // "allreduce" | "reduce" | "reduce_scatter"
  std::string algorithm;   // e.g. "ring", "halving_doubling", "binomial"
  int worldSize = 0;
  std::string dtype;       // element dtype name, e.g. "float32"
  int bucket = 0;          // log2(payload bytes)
  double costUs = 0.0;     // measured mean latency, microseconds
};

class TuningTable {
 public:
  // Adds a cell; a later add with the same key overwrites the cost.
  void add(const Measurement& m);

  // Elect the cheapest algorithm for a payload of `nbytes`. Each
  // candidate's cost curve is interpolated linearly in log2-size space
  // between its measured buckets (the "interpolated crossover": where two
  // curves cross between buckets, the winner flips there, not at a bucket
  // edge), clamped flat outside the swept range. Clamped edge costs are
  // extrapolations, though: a candidate measured only octaves below the
  // query must not beat one actually measured there on the strength of
  // its small-size edge cost. Candidates whose sweep covers the query
  // bucket are therefore preferred; the flat-clamped comparison is the
  // fallback only when no candidate covers it. Only algorithms in
  // `allowed` participate (dispatch excludes opt-in variants like
  // bf16-wire whose numerics differ). An empty `dtype` matches any; a
  // non-empty dtype falls back to ignoring dtype when it has no exact
  // entries (size, not element width, dominates the crossovers — re-tune
  // with that dtype to specialize). Returns nullopt when the table holds
  // no candidate for (collective, worldSize).
  std::optional<std::string> choose(
      const std::string& collective, int worldSize, const std::string& dtype,
      size_t nbytes, const std::vector<std::string>& allowed) const;

  // Interpolated cost of one algorithm at `nbytes`; nullopt if the
  // algorithm has no measurements for the key. Same dtype semantics as
  // choose().
  std::optional<double> cost(const std::string& collective,
                             const std::string& algorithm, int worldSize,
                             const std::string& dtype, size_t nbytes) const;

  bool empty() const { return cells_.empty(); }
  size_t size() const { return cells_.size(); }
  std::vector<Measurement> measurements() const;

  // ---- transport hints (multi-channel striping knobs) ----
  // Tuned quantities for the transport plane, carried next to the
  // algorithm-crossover cells: the per-pair data-channel count and the
  // stripe threshold (docs/transport.md). 0 = unset. Installed tables
  // apply these at connect time via transport::Context::
  // setChannelConfig, unless the TPUCOLL_CHANNELS / TPUCOLL_STRIPE_BYTES
  // env overrides them. The same rank-agreement property holds: all
  // ranks install byte-identical JSON, so all ranks derive the same
  // channel count (which the bootstrap blob additionally enforces).
  struct TransportHints {
    int channels{0};
    uint64_t stripeBytes{0};
    bool set() const { return channels > 0 || stripeBytes > 0; }
  };
  const TransportHints& transportHints() const { return transport_; }
  void setTransportHints(const TransportHints& hints) {
    transport_ = hints;
  }

  // JSON round trip. The serialized form is the interchange format:
  // {"version": 1, "entries": [{"collective", "algorithm", "world_size",
  // "dtype", "bucket", "cost_us"}, ...]}, entries sorted by key so equal
  // tables serialize byte-identically (the rank-agreement check is a
  // string compare). fromJson throws EnforceError on malformed input —
  // a corrupt table file must fail loudly, never install as empty.
  std::string toJson() const;
  static TuningTable fromJson(const std::string& json);

 private:
  struct Key {
    std::string collective;
    std::string algorithm;
    int worldSize;
    std::string dtype;
    bool operator<(const Key& o) const {
      if (collective != o.collective) return collective < o.collective;
      if (algorithm != o.algorithm) return algorithm < o.algorithm;
      if (worldSize != o.worldSize) return worldSize < o.worldSize;
      return dtype < o.dtype;
    }
  };
  // bucket -> costUs, ordered for interpolation.
  using Curve = std::map<int, double>;

  std::optional<double> curveCost(const Curve& curve, double x) const;
  // The curve for (collective, algorithm, worldSize, dtype), honoring the
  // dtype-wildcard fallback documented on choose(). nullptr if none.
  const Curve* findCurve(const std::string& collective,
                         const std::string& algorithm, int worldSize,
                         const std::string& dtype) const;

  std::map<Key, Curve> cells_;
  TransportHints transport_;
};

// log2 size bucket of a payload (floor; nbytes 0 maps to bucket 0).
int sizeBucket(size_t nbytes);

}  // namespace tuning
}  // namespace tpucoll
