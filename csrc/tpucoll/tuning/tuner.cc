#include "tpucoll/tuning/tuner.h"

#include <algorithm>
#include <cstring>
#include <functional>
#include <string>
#include <vector>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/common/metrics.h"
#include "tpucoll/group/hier.h"
#include "tpucoll/tuning/dispatch.h"

namespace tpucoll {
namespace tuning {

namespace {

// The sweep dtype. Algorithm crossovers track payload BYTES, not element
// width (every schedule moves bytes; only the reduction kernel sees
// elements), so one dtype's curves generalize — choose() falls back to
// ignoring dtype for queries the sweep didn't cover.
constexpr DataType kSweepDtype = DataType::kFloat32;

// Mean latency of `body()` over opts.iters runs, measured from the
// metrics registry's (count, sumUs) delta for `op` — the PR-1 histograms
// as the measurement source, exact to the microsecond where the
// power-of-two buckets alone would only bound within 2x.
double measureArm(Context* ctx, MetricOp op, int warmup, int iters,
                  const std::function<void()>& body) {
  for (int i = 0; i < warmup; i++) {
    body();
  }
  uint64_t c0 = 0, s0 = 0, c1 = 0, s1 = 0;
  ctx->metrics().opLatencyTotals(op, &c0, &s0);
  for (int i = 0; i < iters; i++) {
    body();
  }
  ctx->metrics().opLatencyTotals(op, &c1, &s1);
  const uint64_t calls = c1 > c0 ? c1 - c0 : 1;
  return static_cast<double>(s1 - s0) / static_cast<double>(calls);
}

struct AllreduceArm {
  const char* name;
  AllreduceAlgorithm algo;
};

std::vector<AllreduceArm> allreduceArms(Context* ctx) {
  const int size = ctx->size();
  std::vector<AllreduceArm> arms = {
      {"ring", AllreduceAlgorithm::kRing},
      {"recursive_doubling", AllreduceAlgorithm::kRecursiveDoubling},
      {"bcube", AllreduceAlgorithm::kBcube},
      // Wire codecs: excluded from plain-kAuto dispatch (dispatch.h) but
      // swept so the table shows their headroom next to the elected arm
      // and so kAutoLossyWire can elect them from measurement.
      {"ring_bf16_wire", AllreduceAlgorithm::kRingBf16Wire},
      {"ring_q8_wire", AllreduceAlgorithm::kRingQ8Wire},
      {"ring_q4_wire", AllreduceAlgorithm::kRingQ4Wire},
  };
  const bool pow2 = (size & (size - 1)) == 0;
  if (pow2) {
    // fold == blocks on power-of-2 groups; one arm covers both.
    arms.push_back({"halving_doubling", AllreduceAlgorithm::kHalvingDoubling});
  } else {
    // Sweep the two np2 sub-variants separately so the table can elect
    // the cheaper one per size (collectives_hd.cc consults these curves
    // for explicit kHalvingDoubling calls too).
    arms.push_back({"hd_fold", AllreduceAlgorithm::kHdFold});
    arms.push_back({"hd_blocks", AllreduceAlgorithm::kHdBlocks});
  }
  if (group::hierEligible(ctx)) {
    // Topology-aware composition (group/hier.h), swept only where the
    // topology is non-flat so an elected "hier" entry is always
    // runnable on the topology it was measured on.
    arms.push_back({"hier", AllreduceAlgorithm::kHier});
  }
  return arms;
}

struct ReduceArm {
  const char* name;
  ReduceAlgorithm algo;
};

// The histograms are the measurement source — force them on for the
// sweep and restore the caller's setting on every exit path (a swept
// collective can throw on timeout/peer failure; the caller's explicit
// metrics-off choice must survive that).
class MetricsEnableGuard {
 public:
  explicit MetricsEnableGuard(Metrics* metrics)
      : metrics_(metrics), prev_(metrics->enabled()) {
    metrics_->setEnabled(true);
  }
  ~MetricsEnableGuard() { metrics_->setEnabled(prev_); }
  MetricsEnableGuard(const MetricsEnableGuard&) = delete;
  MetricsEnableGuard& operator=(const MetricsEnableGuard&) = delete;

 private:
  Metrics* metrics_;
  bool prev_;
};

struct RsArm {
  const char* name;
  ReduceScatterAlgorithm algo;
};

void publishAndInstall(Context* ctx, const TunerOptions& opts,
                       std::string* json) {
  const auto timeout =
      opts.timeout.count() > 0 ? opts.timeout : ctx->getTimeout();
  const uint64_t gen = ctx->nextTuneGeneration();
  Store* store = ctx->store();
  if (store != nullptr) {
    // Elected through the rendezvous plane: rank 0 publishes under a
    // generation-stamped key (all ranks advanced the same generation —
    // tune() is a collective), everyone else blocks on the key. The
    // table also stays visible in the store for external inspection.
    // Scoped by the context's group tag (Context::scopedStoreKey) so
    // two split sub-groups tuning concurrently over ONE physical store
    // publish under disjoint keys.
    const std::string key =
        ctx->scopedStoreKey("tuning/" + std::to_string(gen));
    if (ctx->rank() == 0) {
      store->set(key, Store::Buf(json->begin(), json->end()));
    } else {
      Store::Buf buf = store->get(key, timeout);
      json->assign(buf.begin(), buf.end());
    }
  } else {
    // Forked contexts have no store; the context's own collectives carry
    // the election instead.
    uint64_t len = json->size();
    {
      BroadcastOptions bo;
      bo.context = ctx;
      bo.tag = opts.tag;
      bo.timeout = timeout;
      bo.buffer = &len;
      bo.count = 1;
      bo.dtype = DataType::kUint64;
      bo.root = 0;
      broadcast(bo);
    }
    json->resize(len);
    if (len > 0) {
      BroadcastOptions bo;
      bo.context = ctx;
      bo.tag = opts.tag;
      bo.timeout = timeout;
      bo.buffer = json->data();
      bo.count = len;
      bo.dtype = DataType::kUint8;
      bo.root = 0;
      broadcast(bo);
    }
  }
}

}  // namespace

std::shared_ptr<const TuningTable> tune(Context* ctx,
                                        const TunerOptions& opts) {
  TC_ENFORCE(ctx != nullptr, "tune: null context");
  TC_ENFORCE(opts.minBytes >= sizeof(float) &&
                 opts.maxBytes >= opts.minBytes,
             "tune: need elementSize <= minBytes <= maxBytes");
  TC_ENFORCE(opts.iters > 0 && opts.warmup >= 0,
             "tune: iters must be positive, warmup non-negative");
  const int rank = ctx->rank();
  const int size = ctx->size();
  const auto timeout =
      opts.timeout.count() > 0 ? opts.timeout : ctx->getTimeout();

  if (size == 1) {
    // Nothing to measure on a group of one; an empty table keeps kAuto on
    // the fallback constants.
    auto empty = std::make_shared<const TuningTable>();
    ctx->setTuningTable(empty);
    return empty;
  }

  MetricsEnableGuard metricsGuard(&ctx->metrics());

  const size_t elsize = elementSize(kSweepDtype);
  const size_t maxCount = std::max<size_t>(opts.maxBytes / elsize, 1);
  // One zero-filled workspace reused by every cell: allreduce runs in
  // place on zeros (0+0 stays exactly representable, so repeated timed
  // iterations never overflow), reduce/reduce_scatter write into `out`.
  std::vector<float> work(maxCount, 0.0f);
  std::vector<float> out(maxCount, 0.0f);

  TuningTable table;
  const int firstBucket = sizeBucket(opts.minBytes);
  const int lastBucket = sizeBucket(opts.maxBytes);

  for (int bucket = firstBucket; bucket <= lastBucket; bucket++) {
    const size_t nbytes = size_t(1) << bucket;
    const size_t count = std::max<size_t>(nbytes / elsize, 1);

    auto record = [&](const char* collective, const char* algorithm,
                      double costUs) {
      if (rank != 0) {
        return;  // rank 0's measurements are the elected ones
      }
      table.add(Measurement{collective, algorithm, size,
                            dataTypeName(kSweepDtype), bucket, costUs});
    };

    if (opts.sweepAllreduce) {
      for (const AllreduceArm& arm : allreduceArms(ctx)) {
        const double cost = measureArm(
            ctx, MetricOp::kAllreduce, opts.warmup, opts.iters, [&] {
              AllreduceOptions o;
              o.context = ctx;
              o.tag = opts.tag;
              o.timeout = timeout;
              o.inputs = {work.data()};
              o.outputs = {work.data()};
              o.count = count;
              o.dtype = kSweepDtype;
              o.op = ReduceOp::kSum;
              o.algorithm = arm.algo;
              allreduce(o);
            });
        record("allreduce", arm.name, cost);
      }
    }

    if (opts.sweepReduce) {
      static const ReduceArm kReduceArms[] = {
          {"binomial", ReduceAlgorithm::kBinomial},
          {"ring", ReduceAlgorithm::kRing},
      };
      for (const ReduceArm& arm : kReduceArms) {
        const double cost = measureArm(
            ctx, MetricOp::kReduce, opts.warmup, opts.iters, [&] {
              ReduceOptions o;
              o.context = ctx;
              o.tag = opts.tag;
              o.timeout = timeout;
              o.input = work.data();
              o.output = rank == 0 ? out.data() : nullptr;
              o.count = count;
              o.dtype = kSweepDtype;
              o.op = ReduceOp::kSum;
              o.root = 0;
              o.algorithm = arm.algo;
              reduce(o);
            });
        record("reduce", arm.name, cost);
      }
    }

    if (opts.sweepReduceScatter) {
      std::vector<RsArm> rsArms = {
          {"ring", ReduceScatterAlgorithm::kRing},
          {"halving_doubling", ReduceScatterAlgorithm::kHalvingDoubling},
          {"direct", ReduceScatterAlgorithm::kDirect},
          // Measurement-only (never auto-elected): wire-compression
          // headroom data for the q8/q4 reduce_scatter opt-ins.
          {"ring_q8_wire", ReduceScatterAlgorithm::kRingQ8Wire},
          {"ring_q4_wire", ReduceScatterAlgorithm::kRingQ4Wire},
      };
      if (group::hierEligible(ctx)) {
        rsArms.push_back({"hier", ReduceScatterAlgorithm::kHier});
      }
      std::vector<size_t> recvCounts(size, count / size);
      for (size_t r = 0; r < count % size; r++) {
        recvCounts[r]++;
      }
      for (const RsArm& arm : rsArms) {
        const double cost = measureArm(
            ctx, MetricOp::kReduceScatter, opts.warmup, opts.iters, [&] {
              ReduceScatterOptions o;
              o.context = ctx;
              o.tag = opts.tag;
              o.timeout = timeout;
              o.input = work.data();
              o.output = out.data();
              o.recvCounts = recvCounts;
              o.dtype = kSweepDtype;
              o.op = ReduceOp::kSum;
              o.algorithm = arm.algo;
              reduceScatter(o);
            });
        record("reduce_scatter", arm.name, cost);
      }
    }
  }

  // Elect rank 0's table: serialize, publish, and re-parse the SAME bytes
  // on every rank (rank 0 included), so install is byte-identical.
  std::string json = rank == 0 ? table.toJson() : std::string();
  publishAndInstall(ctx, opts, &json);
  auto installed =
      std::make_shared<const TuningTable>(TuningTable::fromJson(json));
  ctx->setTuningTable(installed);

  // Leave the group in lockstep: no rank returns (and starts dispatching
  // off the new table) until every rank has installed it.
  BarrierOptions barrierOpts;
  barrierOpts.context = ctx;
  barrierOpts.tag = opts.tag;
  barrierOpts.timeout = timeout;
  barrier(barrierOpts);
  return installed;
}

}  // namespace tuning
}  // namespace tpucoll
