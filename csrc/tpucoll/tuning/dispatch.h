// Bridge between the installed TuningTable (string-keyed measurements)
// and the collectives' algorithm enums. Each kAuto dispatch asks here
// first; nullopt means "no table, or no data for this shape" and the
// caller falls back to its historical compile-time thresholds, so an
// untuned context behaves exactly as before this plane existed.
//
// Dispatch deliberately excludes algorithms whose numerics are opt-in
// (ring_bf16_wire accumulates in bf16): the tuner measures them so the
// table can report their headroom, but auto-dispatch must never change
// the precision contract behind the caller's back.
#pragma once

#include <optional>

#include "tpucoll/collectives/collectives.h"

namespace tpucoll {
namespace tuning {

// Canonical string names for table keys, shared by the tuner and the
// Python surface (they match gloo_tpu.core's algorithm/dtype spellings).
const char* dataTypeName(DataType dtype);
const char* allreduceAlgorithmName(AllreduceAlgorithm algo);
const char* reduceAlgorithmName(ReduceAlgorithm algo);
const char* reduceScatterAlgorithmName(ReduceScatterAlgorithm algo);

// Table-elected algorithm for a kAuto call, or nullopt to use the
// fallback constants. Deterministic across ranks: the table is
// rank-identical and (dtype, nbytes, size) match by collective contract.
// lossyWireOk widens the eligible arm set with the wire codecs
// (ring_bf16_wire / ring_q8_wire) — ONLY set for kAutoLossyWire calls
// whose shape the codecs support (float32 sum, builtin reduction); a
// plain kAuto must never change the precision contract behind the
// caller's back.
std::optional<AllreduceAlgorithm> tableAllreduce(Context* ctx,
                                                 DataType dtype,
                                                 size_t nbytes,
                                                 bool lossyWireOk = false);
std::optional<ReduceAlgorithm> tableReduce(Context* ctx, DataType dtype,
                                           size_t nbytes);
std::optional<ReduceScatterAlgorithm> tableReduceScatter(Context* ctx,
                                                         DataType dtype,
                                                         size_t nbytes);

// Fold-vs-binary-blocks election for an explicit kHalvingDoubling call on
// a non-power-of-2 group (collectives_hd.cc): true = blocks, false =
// fold, nullopt = no table data, use the TPUCOLL_HD_NP2 crossover.
std::optional<bool> tableHdUseBlocks(Context* ctx, size_t nbytes);

}  // namespace tuning
}  // namespace tpucoll
