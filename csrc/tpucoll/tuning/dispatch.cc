#include "tpucoll/tuning/dispatch.h"

#include <memory>
#include <string>
#include <vector>

#include "tpucoll/common/env.h"
#include "tpucoll/group/hier.h"
#include "tpucoll/tuning/tuning_table.h"

namespace tpucoll {
namespace tuning {

namespace {

// Dispatch-eligible arms per collective. The wire codecs (bf16/q8) are
// measured by the tuner but excluded from the default set (their
// precision contract is opt-in) — kAutoLossyWire widens the set via the
// lossy list below; hd_fold / hd_blocks appear as first-class arms so a
// tuned non-power-of-2 group can land on the cheaper variant directly.
const std::vector<std::string>& allreduceArms() {
  static const std::vector<std::string> arms = {
      "ring", "halving_doubling", "recursive_doubling",
      "bcube", "hd_fold", "hd_blocks"};
  return arms;
}

const std::vector<std::string>& allreduceArmsLossy() {
  static const std::vector<std::string> arms = [] {
    std::vector<std::string> a = allreduceArms();
    a.push_back("ring_bf16_wire");
    a.push_back("ring_q8_wire");
    a.push_back("ring_q4_wire");
    return a;
  }();
  return arms;
}

const std::vector<std::string>& reduceArms() {
  static const std::vector<std::string> arms = {"binomial", "ring"};
  return arms;
}

const std::vector<std::string>& reduceScatterArms() {
  static const std::vector<std::string> arms = {
      "ring", "halving_doubling", "direct"};
  return arms;
}

// The hierarchical arm joins the electable set only where it can run
// (non-flat topology) and the operator has not pinned dispatch flat
// (TPUCOLL_HIER_AUTO=0). The tuner sweeps it under the same condition,
// so a table loaded on a DIFFERENT topology can never elect hier where
// it would degenerate.
bool hierElectable(Context* ctx) {
  static const bool hierAuto = envFlag("TPUCOLL_HIER_AUTO", true);
  return hierAuto && group::hierEligible(ctx);
}

// Hier-augmented arm lists are function-local statics like the flat
// ones: this runs on every tuned dispatch, which PR 12 made a
// zero-allocation path — no per-op vector/string copies here.
std::vector<std::string> withHier(const std::vector<std::string>& base) {
  std::vector<std::string> arms = base;
  arms.push_back("hier");
  return arms;
}

const std::vector<std::string>& allreduceArmsWithHier(bool lossyWireOk) {
  static const std::vector<std::string> plain = withHier(allreduceArms());
  static const std::vector<std::string> lossy =
      withHier(allreduceArmsLossy());
  return lossyWireOk ? lossy : plain;
}

const std::vector<std::string>& reduceScatterArmsWithHier() {
  static const std::vector<std::string> arms =
      withHier(reduceScatterArms());
  return arms;
}

}  // namespace

const char* dataTypeName(DataType dtype) {
  switch (dtype) {
    case DataType::kInt8: return "int8";
    case DataType::kUint8: return "uint8";
    case DataType::kInt32: return "int32";
    case DataType::kUint32: return "uint32";
    case DataType::kInt64: return "int64";
    case DataType::kUint64: return "uint64";
    case DataType::kFloat16: return "float16";
    case DataType::kBFloat16: return "bfloat16";
    case DataType::kFloat32: return "float32";
    case DataType::kFloat64: return "float64";
  }
  return "unknown";
}

const char* allreduceAlgorithmName(AllreduceAlgorithm algo) {
  switch (algo) {
    case AllreduceAlgorithm::kAuto: return "auto";
    case AllreduceAlgorithm::kRing: return "ring";
    case AllreduceAlgorithm::kHalvingDoubling: return "halving_doubling";
    case AllreduceAlgorithm::kBcube: return "bcube";
    case AllreduceAlgorithm::kRingBf16Wire: return "ring_bf16_wire";
    case AllreduceAlgorithm::kRecursiveDoubling: return "recursive_doubling";
    case AllreduceAlgorithm::kHdFold: return "hd_fold";
    case AllreduceAlgorithm::kHdBlocks: return "hd_blocks";
    case AllreduceAlgorithm::kRingQ8Wire: return "ring_q8_wire";
    case AllreduceAlgorithm::kRingQ4Wire: return "ring_q4_wire";
    case AllreduceAlgorithm::kAutoLossyWire: return "auto_lossy_wire";
    case AllreduceAlgorithm::kHier: return "hier";
  }
  return "unknown";
}

const char* reduceAlgorithmName(ReduceAlgorithm algo) {
  switch (algo) {
    case ReduceAlgorithm::kAuto: return "auto";
    case ReduceAlgorithm::kBinomial: return "binomial";
    case ReduceAlgorithm::kRing: return "ring";
  }
  return "unknown";
}

const char* reduceScatterAlgorithmName(ReduceScatterAlgorithm algo) {
  switch (algo) {
    case ReduceScatterAlgorithm::kAuto: return "auto";
    case ReduceScatterAlgorithm::kRing: return "ring";
    case ReduceScatterAlgorithm::kHalvingDoubling: return "halving_doubling";
    case ReduceScatterAlgorithm::kDirect: return "direct";
    case ReduceScatterAlgorithm::kRingQ8Wire: return "ring_q8_wire";
    case ReduceScatterAlgorithm::kRingQ4Wire: return "ring_q4_wire";
    case ReduceScatterAlgorithm::kHier: return "hier";
  }
  return "unknown";
}

std::optional<AllreduceAlgorithm> tableAllreduce(Context* ctx,
                                                 DataType dtype,
                                                 size_t nbytes,
                                                 bool lossyWireOk) {
  auto table = ctx->tuningTable();
  if (table == nullptr) {
    return std::nullopt;
  }
  auto name = table->choose(
      "allreduce", ctx->size(), dataTypeName(dtype), nbytes,
      hierElectable(ctx) ? allreduceArmsWithHier(lossyWireOk)
      : lossyWireOk      ? allreduceArmsLossy()
                         : allreduceArms());
  if (!name.has_value()) {
    return std::nullopt;
  }
  if (*name == "hier") return AllreduceAlgorithm::kHier;
  if (*name == "ring") return AllreduceAlgorithm::kRing;
  if (*name == "halving_doubling") return AllreduceAlgorithm::kHalvingDoubling;
  if (*name == "recursive_doubling") {
    return AllreduceAlgorithm::kRecursiveDoubling;
  }
  if (*name == "bcube") return AllreduceAlgorithm::kBcube;
  if (*name == "hd_fold") return AllreduceAlgorithm::kHdFold;
  if (*name == "hd_blocks") return AllreduceAlgorithm::kHdBlocks;
  if (*name == "ring_bf16_wire") return AllreduceAlgorithm::kRingBf16Wire;
  if (*name == "ring_q8_wire") return AllreduceAlgorithm::kRingQ8Wire;
  if (*name == "ring_q4_wire") return AllreduceAlgorithm::kRingQ4Wire;
  return std::nullopt;
}

std::optional<ReduceAlgorithm> tableReduce(Context* ctx, DataType dtype,
                                           size_t nbytes) {
  auto table = ctx->tuningTable();
  if (table == nullptr) {
    return std::nullopt;
  }
  auto name = table->choose("reduce", ctx->size(), dataTypeName(dtype),
                            nbytes, reduceArms());
  if (!name.has_value()) {
    return std::nullopt;
  }
  if (*name == "binomial") return ReduceAlgorithm::kBinomial;
  if (*name == "ring") return ReduceAlgorithm::kRing;
  return std::nullopt;
}

std::optional<ReduceScatterAlgorithm> tableReduceScatter(Context* ctx,
                                                         DataType dtype,
                                                         size_t nbytes) {
  auto table = ctx->tuningTable();
  if (table == nullptr) {
    return std::nullopt;
  }
  auto name = table->choose("reduce_scatter", ctx->size(),
                            dataTypeName(dtype), nbytes,
                            hierElectable(ctx)
                                ? reduceScatterArmsWithHier()
                                : reduceScatterArms());
  if (!name.has_value()) {
    return std::nullopt;
  }
  if (*name == "hier") return ReduceScatterAlgorithm::kHier;
  if (*name == "ring") return ReduceScatterAlgorithm::kRing;
  if (*name == "halving_doubling") {
    return ReduceScatterAlgorithm::kHalvingDoubling;
  }
  if (*name == "direct") return ReduceScatterAlgorithm::kDirect;
  return std::nullopt;
}

std::optional<bool> tableHdUseBlocks(Context* ctx, size_t nbytes) {
  auto table = ctx->tuningTable();
  if (table == nullptr) {
    return std::nullopt;
  }
  // Empty dtype = wildcard (the caller only knows elsize); both arms must
  // have data or the comparison is meaningless.
  auto fold = table->cost("allreduce", "hd_fold", ctx->size(), "", nbytes);
  auto blocks =
      table->cost("allreduce", "hd_blocks", ctx->size(), "", nbytes);
  if (!fold.has_value() || !blocks.has_value()) {
    return std::nullopt;
  }
  return *blocks < *fold;
}

}  // namespace tuning
}  // namespace tpucoll
