#include "tpucoll/tuning/tuning_table.h"

#include <cctype>
#include <cmath>
#include <cstdint>
#include <limits>
#include <sstream>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace tuning {

namespace {

// Minimal JSON reader, scoped to the table interchange format (objects,
// arrays, strings with the common escapes, numbers, bools, null). The
// repo's other JSON surfaces only serialize; the table is the first thing
// the core must also *read* (install_table / TPUCOLL_TUNING_FILE), and a
// dependency-free ~100-line recursive-descent parser beats gating the
// feature on a library the container doesn't ship.
class JsonReader {
 public:
  explicit JsonReader(const std::string& text) : text_(text) {}

  // Parsed value: exactly one of the members is active, by `kind`.
  struct Value {
    enum class Kind { kNull, kBool, kNumber, kString, kArray, kObject };
    Kind kind = Kind::kNull;
    bool boolean = false;
    double number = 0.0;
    std::string str;
    std::vector<Value> items;
    std::vector<std::pair<std::string, Value>> fields;

    const Value* field(const std::string& name) const {
      for (const auto& f : fields) {
        if (f.first == name) {
          return &f.second;
        }
      }
      return nullptr;
    }
  };

  Value parse() {
    Value v = parseValue();
    skipWs();
    TC_ENFORCE_EQ(pos_, text_.size(), "tuning table JSON: trailing bytes");
    return v;
  }

 private:
  void skipWs() {
    while (pos_ < text_.size() &&
           std::isspace(static_cast<unsigned char>(text_[pos_]))) {
      pos_++;
    }
  }

  char peek() {
    skipWs();
    TC_ENFORCE(pos_ < text_.size(), "tuning table JSON: unexpected end");
    return text_[pos_];
  }

  void expect(char c) {
    TC_ENFORCE(peek() == c, "tuning table JSON: expected '", c, "' at byte ",
               pos_);
    pos_++;
  }

  bool consume(char c) {
    if (pos_ < text_.size() && peek() == c) {
      pos_++;
      return true;
    }
    return false;
  }

  Value parseValue() {
    const char c = peek();
    if (c == '{') return parseObject();
    if (c == '[') return parseArray();
    if (c == '"') {
      Value v;
      v.kind = Value::Kind::kString;
      v.str = parseString();
      return v;
    }
    if (c == 't' || c == 'f') return parseLiteralBool();
    if (c == 'n') {
      expectWord("null");
      return Value{};
    }
    return parseNumber();
  }

  void expectWord(const char* w) {
    skipWs();
    for (const char* p = w; *p != '\0'; p++) {
      TC_ENFORCE(pos_ < text_.size() && text_[pos_] == *p,
                 "tuning table JSON: bad literal at byte ", pos_);
      pos_++;
    }
  }

  Value parseLiteralBool() {
    Value v;
    v.kind = Value::Kind::kBool;
    if (peek() == 't') {
      expectWord("true");
      v.boolean = true;
    } else {
      expectWord("false");
      v.boolean = false;
    }
    return v;
  }

  // Hand-rolled, locale-independent number scan: JSON numbers are
  // always dot-decimal, but std::stod honors LC_NUMERIC — in a
  // comma-decimal locale it would silently truncate "40.25" to 40.
  Value parseNumber() {
    skipWs();
    const size_t start = pos_;
    bool negative = false;
    if (pos_ < text_.size() &&
        (text_[pos_] == '-' || text_[pos_] == '+')) {
      negative = text_[pos_] == '-';
      pos_++;
    }
    bool anyDigit = false;
    double mantissa = 0.0;
    while (pos_ < text_.size() &&
           std::isdigit(static_cast<unsigned char>(text_[pos_]))) {
      mantissa = mantissa * 10.0 + (text_[pos_] - '0');
      anyDigit = true;
      pos_++;
    }
    if (pos_ < text_.size() && text_[pos_] == '.') {
      pos_++;
      double place = 0.1;
      while (pos_ < text_.size() &&
             std::isdigit(static_cast<unsigned char>(text_[pos_]))) {
        mantissa += (text_[pos_] - '0') * place;
        place *= 0.1;
        anyDigit = true;
        pos_++;
      }
    }
    TC_ENFORCE(anyDigit, "tuning table JSON: expected number at byte ",
               start);
    int exponent = 0;
    if (pos_ < text_.size() && (text_[pos_] == 'e' || text_[pos_] == 'E')) {
      pos_++;
      bool expNegative = false;
      if (pos_ < text_.size() &&
          (text_[pos_] == '-' || text_[pos_] == '+')) {
        expNegative = text_[pos_] == '-';
        pos_++;
      }
      bool anyExpDigit = false;
      while (pos_ < text_.size() &&
             std::isdigit(static_cast<unsigned char>(text_[pos_]))) {
        exponent = std::min(exponent * 10 + (text_[pos_] - '0'), 9999);
        anyExpDigit = true;
        pos_++;
      }
      TC_ENFORCE(anyExpDigit, "tuning table JSON: bad exponent at byte ",
                 start);
      if (expNegative) {
        exponent = -exponent;
      }
    }
    Value v;
    v.kind = Value::Kind::kNumber;
    v.number = (negative ? -mantissa : mantissa) *
               std::pow(10.0, exponent);
    return v;
  }

  std::string parseString() {
    expect('"');
    std::string out;
    while (true) {
      TC_ENFORCE(pos_ < text_.size(),
                 "tuning table JSON: unterminated string");
      char c = text_[pos_++];
      if (c == '"') {
        return out;
      }
      if (c != '\\') {
        out.push_back(c);
        continue;
      }
      TC_ENFORCE(pos_ < text_.size(), "tuning table JSON: bad escape");
      char e = text_[pos_++];
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'n': out.push_back('\n'); break;
        case 't': out.push_back('\t'); break;
        case 'r': out.push_back('\r'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'u': {
          // Table strings are ASCII identifiers; decode BMP escapes to
          // their low byte and reject the rest rather than mis-decode.
          TC_ENFORCE(pos_ + 4 <= text_.size(),
                     "tuning table JSON: bad \\u escape");
          unsigned code = 0;
          for (int i = 0; i < 4; i++) {
            char h = text_[pos_++];
            code <<= 4;
            if (h >= '0' && h <= '9') code |= h - '0';
            else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
            else TC_THROW(EnforceError, "tuning table JSON: bad \\u escape");
          }
          TC_ENFORCE(code < 0x80,
                     "tuning table JSON: non-ASCII \\u escape unsupported");
          out.push_back(static_cast<char>(code));
          break;
        }
        default:
          TC_THROW(EnforceError, "tuning table JSON: bad escape '\\", e, "'");
      }
    }
  }

  Value parseArray() {
    expect('[');
    Value v;
    v.kind = Value::Kind::kArray;
    if (consume(']')) {
      return v;
    }
    while (true) {
      v.items.push_back(parseValue());
      if (consume(']')) {
        return v;
      }
      expect(',');
    }
  }

  Value parseObject() {
    expect('{');
    Value v;
    v.kind = Value::Kind::kObject;
    if (consume('}')) {
      return v;
    }
    while (true) {
      std::string key = parseString();
      expect(':');
      v.fields.emplace_back(std::move(key), parseValue());
      if (consume('}')) {
        return v;
      }
      expect(',');
    }
  }

  const std::string& text_;
  size_t pos_ = 0;
};

const JsonReader::Value& requireField(const JsonReader::Value& obj,
                                      const std::string& name,
                                      JsonReader::Value::Kind kind) {
  const JsonReader::Value* f = obj.field(name);
  TC_ENFORCE(f != nullptr, "tuning table JSON: entry missing \"", name, "\"");
  TC_ENFORCE(f->kind == kind, "tuning table JSON: \"", name,
             "\" has wrong type");
  return *f;
}

void appendJsonString(std::ostringstream& out, const std::string& s) {
  out << '"';
  for (char c : s) {
    switch (c) {
      case '"': out << "\\\""; break;
      case '\\': out << "\\\\"; break;
      case '\n': out << "\\n"; break;
      case '\t': out << "\\t"; break;
      case '\r': out << "\\r"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out << buf;
        } else {
          out << c;
        }
    }
  }
  out << '"';
}

// Fixed three-decimal cost serialization, built from integer pieces so
// the output is locale-independent (snprintf "%f" honors LC_NUMERIC and
// would emit "40,250" in a comma-decimal locale — invalid JSON). Costs
// are enforced non-negative at add().
void appendCost(std::ostringstream& out, double v) {
  const long long scaled = std::llround(v * 1000.0);
  char buf[8];
  std::snprintf(buf, sizeof(buf), "%03lld", scaled % 1000);
  out << scaled / 1000 << '.' << buf;
}

}  // namespace

int sizeBucket(size_t nbytes) {
  int b = 0;
  while (nbytes > 1) {
    nbytes >>= 1;
    b++;
  }
  return b;
}

void TuningTable::add(const Measurement& m) {
  TC_ENFORCE(!m.collective.empty() && !m.algorithm.empty(),
             "tuning table: measurement needs collective and algorithm");
  TC_ENFORCE(m.worldSize > 0, "tuning table: world size must be positive");
  TC_ENFORCE(m.bucket >= 0 && m.bucket < 64, "tuning table: bad bucket ",
             m.bucket);
  TC_ENFORCE(m.costUs >= 0.0 && std::isfinite(m.costUs),
             "tuning table: cost must be finite and non-negative");
  cells_[Key{m.collective, m.algorithm, m.worldSize, m.dtype}][m.bucket] =
      m.costUs;
}

std::optional<double> TuningTable::curveCost(const Curve& curve,
                                             double x) const {
  if (curve.empty()) {
    return std::nullopt;
  }
  // Clamp outside the swept range: beyond the sweep the relative order at
  // the boundary bucket is the best information the table has, and flat
  // extrapolation preserves exactly that ordering (linear extrapolation
  // in log space can invert wildly a few octaves out).
  if (x <= curve.begin()->first) {
    return curve.begin()->second;
  }
  auto last = std::prev(curve.end());
  if (x >= last->first) {
    return last->second;
  }
  auto hi = curve.upper_bound(static_cast<int>(std::floor(x)));
  auto lo = std::prev(hi);
  if (hi == curve.end()) {
    return lo->second;
  }
  const double span = hi->first - lo->first;
  const double t = (x - lo->first) / span;
  return lo->second + t * (hi->second - lo->second);
}

std::optional<double> TuningTable::cost(const std::string& collective,
                                        const std::string& algorithm,
                                        int worldSize,
                                        const std::string& dtype,
                                        size_t nbytes) const {
  const double x =
      std::log2(static_cast<double>(nbytes > 0 ? nbytes : 1));
  // Exact dtype first; fall back to dtype-agnostic aggregation (cheapest
  // curve point across dtypes would mix curves — instead use the first
  // matching curve in key order, which is deterministic on every rank).
  auto it = cells_.find(Key{collective, algorithm, worldSize, dtype});
  if (it != cells_.end()) {
    return curveCost(it->second, x);
  }
  for (const auto& cell : cells_) {
    if (cell.first.collective == collective &&
        cell.first.algorithm == algorithm &&
        cell.first.worldSize == worldSize) {
      return curveCost(cell.second, x);
    }
  }
  return std::nullopt;
}

std::optional<std::string> TuningTable::choose(
    const std::string& collective, int worldSize, const std::string& dtype,
    size_t nbytes, const std::vector<std::string>& allowed) const {
  std::optional<std::string> best;
  double bestCost = std::numeric_limits<double>::infinity();
  for (const std::string& algo : allowed) {
    auto c = cost(collective, algo, worldSize, dtype, nbytes);
    if (c.has_value() && *c < bestCost) {
      bestCost = *c;
      best = algo;
    }
  }
  return best;
}

std::vector<Measurement> TuningTable::measurements() const {
  std::vector<Measurement> out;
  for (const auto& cell : cells_) {
    for (const auto& point : cell.second) {
      out.push_back(Measurement{cell.first.collective, cell.first.algorithm,
                                cell.first.worldSize, cell.first.dtype,
                                point.first, point.second});
    }
  }
  return out;
}

std::string TuningTable::toJson() const {
  std::ostringstream out;
  out << "{\"version\":1,\"entries\":[";
  bool first = true;
  // cells_ and each Curve are ordered maps: serialization order is a pure
  // function of content, so equal tables are byte-equal JSON.
  for (const auto& cell : cells_) {
    for (const auto& point : cell.second) {
      if (!first) {
        out << ",";
      }
      first = false;
      out << "{\"collective\":";
      appendJsonString(out, cell.first.collective);
      out << ",\"algorithm\":";
      appendJsonString(out, cell.first.algorithm);
      out << ",\"world_size\":" << cell.first.worldSize << ",\"dtype\":";
      appendJsonString(out, cell.first.dtype);
      out << ",\"bucket\":" << point.first << ",\"cost_us\":";
      appendCost(out, point.second);
      out << "}";
    }
  }
  out << "]}";
  return out.str();
}

TuningTable TuningTable::fromJson(const std::string& json) {
  using Kind = JsonReader::Value::Kind;
  JsonReader reader(json);
  const JsonReader::Value root = reader.parse();
  TC_ENFORCE(root.kind == Kind::kObject,
             "tuning table JSON: root must be an object");
  const JsonReader::Value* version = root.field("version");
  TC_ENFORCE(version != nullptr && version->kind == Kind::kNumber &&
                 version->number == 1.0,
             "tuning table JSON: unsupported version");
  const JsonReader::Value& entries =
      requireField(root, "entries", Kind::kArray);
  TuningTable table;
  for (const JsonReader::Value& e : entries.items) {
    TC_ENFORCE(e.kind == Kind::kObject,
               "tuning table JSON: entry must be an object");
    Measurement m;
    m.collective = requireField(e, "collective", Kind::kString).str;
    m.algorithm = requireField(e, "algorithm", Kind::kString).str;
    m.worldSize =
        static_cast<int>(requireField(e, "world_size", Kind::kNumber).number);
    m.dtype = requireField(e, "dtype", Kind::kString).str;
    m.bucket =
        static_cast<int>(requireField(e, "bucket", Kind::kNumber).number);
    m.costUs = requireField(e, "cost_us", Kind::kNumber).number;
    table.add(m);
  }
  return table;
}

}  // namespace tuning
}  // namespace tpucoll
