#include "tpucoll/tuning/tuning_table.h"

#include "tpucoll/transport/wire.h"

#include <cctype>
#include <cmath>
#include <cstdint>
#include <limits>
#include <sstream>

#include "tpucoll/common/json.h"
#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace tuning {

namespace {

const JsonReader::Value& requireField(const JsonReader::Value& obj,
                                      const std::string& name,
                                      JsonReader::Value::Kind kind) {
  const JsonReader::Value* f = obj.field(name);
  TC_ENFORCE(f != nullptr, "tuning table JSON: entry missing \"", name, "\"");
  TC_ENFORCE(f->kind == kind, "tuning table JSON: \"", name,
             "\" has wrong type");
  return *f;
}

// Fixed three-decimal cost serialization, built from integer pieces so
// the output is locale-independent (snprintf "%f" honors LC_NUMERIC and
// would emit "40,250" in a comma-decimal locale — invalid JSON). Costs
// are enforced non-negative at add().
void appendCost(std::ostringstream& out, double v) {
  const long long scaled = std::llround(v * 1000.0);
  char buf[8];
  std::snprintf(buf, sizeof(buf), "%03lld", scaled % 1000);
  out << scaled / 1000 << '.' << buf;
}

}  // namespace

int sizeBucket(size_t nbytes) {
  int b = 0;
  while (nbytes > 1) {
    nbytes >>= 1;
    b++;
  }
  return b;
}

void TuningTable::add(const Measurement& m) {
  TC_ENFORCE(!m.collective.empty() && !m.algorithm.empty(),
             "tuning table: measurement needs collective and algorithm");
  TC_ENFORCE(m.worldSize > 0, "tuning table: world size must be positive");
  TC_ENFORCE(m.bucket >= 0 && m.bucket < 64, "tuning table: bad bucket ",
             m.bucket);
  TC_ENFORCE(m.costUs >= 0.0 && std::isfinite(m.costUs),
             "tuning table: cost must be finite and non-negative");
  cells_[Key{m.collective, m.algorithm, m.worldSize, m.dtype}][m.bucket] =
      m.costUs;
}

std::optional<double> TuningTable::curveCost(const Curve& curve,
                                             double x) const {
  if (curve.empty()) {
    return std::nullopt;
  }
  // Clamp outside the swept range: beyond the sweep the relative order at
  // the boundary bucket is the best information the table has, and flat
  // extrapolation preserves exactly that ordering (linear extrapolation
  // in log space can invert wildly a few octaves out).
  if (x <= curve.begin()->first) {
    return curve.begin()->second;
  }
  auto last = std::prev(curve.end());
  if (x >= last->first) {
    return last->second;
  }
  auto hi = curve.upper_bound(static_cast<int>(std::floor(x)));
  auto lo = std::prev(hi);
  if (hi == curve.end()) {
    return lo->second;
  }
  const double span = hi->first - lo->first;
  const double t = (x - lo->first) / span;
  return lo->second + t * (hi->second - lo->second);
}

const TuningTable::Curve* TuningTable::findCurve(
    const std::string& collective, const std::string& algorithm,
    int worldSize, const std::string& dtype) const {
  // Exact dtype first; fall back to dtype-agnostic aggregation (cheapest
  // curve point across dtypes would mix curves — instead use the first
  // matching curve in key order, which is deterministic on every rank).
  auto it = cells_.find(Key{collective, algorithm, worldSize, dtype});
  if (it != cells_.end()) {
    return &it->second;
  }
  for (const auto& cell : cells_) {
    if (cell.first.collective == collective &&
        cell.first.algorithm == algorithm &&
        cell.first.worldSize == worldSize) {
      return &cell.second;
    }
  }
  return nullptr;
}

std::optional<double> TuningTable::cost(const std::string& collective,
                                        const std::string& algorithm,
                                        int worldSize,
                                        const std::string& dtype,
                                        size_t nbytes) const {
  const double x =
      std::log2(static_cast<double>(nbytes > 0 ? nbytes : 1));
  const Curve* curve = findCurve(collective, algorithm, worldSize, dtype);
  if (curve == nullptr) {
    return std::nullopt;
  }
  return curveCost(*curve, x);
}

std::optional<std::string> TuningTable::choose(
    const std::string& collective, int worldSize, const std::string& dtype,
    size_t nbytes, const std::vector<std::string>& allowed) const {
  const double x =
      std::log2(static_cast<double>(nbytes > 0 ? nbytes : 1));
  // Two-pass election. Pass 1 considers only candidates whose measured
  // bucket range covers x: beyond its largest measured bucket a curve's
  // clamped edge cost is an extrapolation, and comparing it against a
  // curve genuinely measured at x let ragged sweeps elect an algorithm
  // octaves outside its evidence (e.g. an arm swept only to 64 KiB
  // "winning" the 16 MiB cell on its 64 KiB cost). Pass 2 — all
  // candidates out of range — falls back to the clamped comparison:
  // edge evidence beats no evidence.
  std::optional<std::string> best;
  double bestCost = std::numeric_limits<double>::infinity();
  bool bestCovered = false;
  for (const std::string& algo : allowed) {
    const Curve* curve = findCurve(collective, algo, worldSize, dtype);
    if (curve == nullptr || curve->empty()) {
      continue;
    }
    const bool covered =
        x >= curve->begin()->first && x <= std::prev(curve->end())->first;
    auto c = curveCost(*curve, x);
    if (!c.has_value()) {
      continue;
    }
    if ((covered && !bestCovered) ||
        (covered == bestCovered && *c < bestCost)) {
      bestCost = *c;
      best = algo;
      bestCovered = covered;
    }
  }
  return best;
}

std::vector<Measurement> TuningTable::measurements() const {
  std::vector<Measurement> out;
  for (const auto& cell : cells_) {
    for (const auto& point : cell.second) {
      out.push_back(Measurement{cell.first.collective, cell.first.algorithm,
                                cell.first.worldSize, cell.first.dtype,
                                point.first, point.second});
    }
  }
  return out;
}

std::string TuningTable::toJson() const {
  std::ostringstream out;
  out << "{\"version\":1,\"entries\":[";
  bool first = true;
  // cells_ and each Curve are ordered maps: serialization order is a pure
  // function of content, so equal tables are byte-equal JSON.
  for (const auto& cell : cells_) {
    for (const auto& point : cell.second) {
      if (!first) {
        out << ",";
      }
      first = false;
      out << "{\"collective\":";
      appendJsonString(out, cell.first.collective);
      out << ",\"algorithm\":";
      appendJsonString(out, cell.first.algorithm);
      out << ",\"world_size\":" << cell.first.worldSize << ",\"dtype\":";
      appendJsonString(out, cell.first.dtype);
      out << ",\"bucket\":" << point.first << ",\"cost_us\":";
      appendCost(out, point.second);
      out << "}";
    }
  }
  out << "]";
  if (transport_.set()) {
    out << ",\"transport\":{\"channels\":" << transport_.channels
        << ",\"stripe_bytes\":" << transport_.stripeBytes << "}";
  }
  out << "}";
  return out.str();
}

TuningTable TuningTable::fromJson(const std::string& json) {
  using Kind = JsonReader::Value::Kind;
  JsonReader reader(json, "tuning table JSON", /*rejectDuplicateKeys=*/true);
  const JsonReader::Value root = reader.parse();
  TC_ENFORCE(root.kind == Kind::kObject,
             "tuning table JSON: root must be an object");
  const JsonReader::Value* version = root.field("version");
  TC_ENFORCE(version != nullptr && version->kind == Kind::kNumber &&
                 version->number == 1.0,
             "tuning table JSON: unsupported version");
  const JsonReader::Value& entries =
      requireField(root, "entries", Kind::kArray);
  TuningTable table;
  for (const JsonReader::Value& e : entries.items) {
    TC_ENFORCE(e.kind == Kind::kObject,
               "tuning table JSON: entry must be an object");
    Measurement m;
    m.collective = requireField(e, "collective", Kind::kString).str;
    m.algorithm = requireField(e, "algorithm", Kind::kString).str;
    m.worldSize =
        static_cast<int>(requireField(e, "world_size", Kind::kNumber).number);
    m.dtype = requireField(e, "dtype", Kind::kString).str;
    m.bucket =
        static_cast<int>(requireField(e, "bucket", Kind::kNumber).number);
    m.costUs = requireField(e, "cost_us", Kind::kNumber).number;
    table.add(m);
  }
  if (const JsonReader::Value* t = root.field("transport")) {
    TC_ENFORCE(t->kind == Kind::kObject,
               "tuning table JSON: \"transport\" must be an object");
    TransportHints hints;
    if (const JsonReader::Value* c = t->field("channels")) {
      TC_ENFORCE(c->kind == Kind::kNumber && c->number >= 1 &&
                     c->number <= transport::kMaxStripeChannels,
                 "tuning table JSON: transport.channels must be in [1, ",
                 transport::kMaxStripeChannels, "]");
      hints.channels = static_cast<int>(c->number);
    }
    if (const JsonReader::Value* b = t->field("stripe_bytes")) {
      TC_ENFORCE(b->kind == Kind::kNumber && b->number >= 0,
                 "tuning table JSON: transport.stripe_bytes must be a "
                 "non-negative number");
      hints.stripeBytes = static_cast<uint64_t>(b->number);
    }
    table.setTransportHints(hints);
  }
  return table;
}

}  // namespace tuning
}  // namespace tpucoll
