// Collective autotuner: measure every registered algorithm variant on the
// live fabric, elect rank 0's measurements, and install the resulting
// TuningTable identically on every rank.
//
// tune() is a COLLECTIVE: every rank of the context must call it
// concurrently with identical options (it runs the real collectives to
// measure them, and publishes the elected table to the whole group). The
// measurement source is the PR-1 metrics registry's latency histograms —
// each arm's cost is the delta of (count, sumUs) around its timed
// iterations, on rank 0. The elected table is serialized, published
// through the rendezvous Store the context bootstrapped over (or
// broadcast through the context's own collectives for forked contexts,
// which have no store), parsed back from the SAME bytes on every rank —
// including rank 0 — and installed, so kAuto dispatch is byte-identical
// everywhere.
#pragma once

#include <chrono>
#include <cstddef>
#include <cstdint>
#include <memory>

#include "tpucoll/context.h"
#include "tpucoll/tuning/tuning_table.h"

namespace tpucoll {
namespace tuning {

struct TunerOptions {
  // Swept payload range: one cell per log2 bucket from
  // sizeBucket(minBytes) through sizeBucket(maxBytes).
  size_t minBytes = 1u << 10;
  size_t maxBytes = 4u << 20;
  // Timed iterations per (collective, algorithm, bucket) cell, after
  // `warmup` untimed ones.
  int iters = 8;
  int warmup = 2;
  // Collective tag the sweep's operations run under; must not collide
  // with application collectives running concurrently on this context.
  uint32_t tag = 0;
  // Per-operation timeout; zero uses the context default.
  std::chrono::milliseconds timeout{0};
  // Which collectives to sweep.
  bool sweepAllreduce = true;
  bool sweepReduce = true;
  bool sweepReduceScatter = true;
};

// Run the sweep, elect + publish + install; returns the installed table
// (already set on the context). Single-rank groups skip the sweep and
// install an empty table (dispatch falls back to the default thresholds).
std::shared_ptr<const TuningTable> tune(Context* ctx,
                                        const TunerOptions& opts);

}  // namespace tuning
}  // namespace tpucoll
