// tpucoll core types: element dtypes, reduction ops, and the slot scheme.
//
// The slot scheme mirrors the reference's contract (gloo/types.h:40-91): a
// 64-bit message tag that namespaces concurrent collectives so their
// point-to-point traffic cannot cross-match. Layout here (original design):
//   [63:56] collective prefix (8 bits)
//   [55:24] user tag          (32 bits)
//   [23:0]  op delta          (24 bits) — per-schedule message counter
// The wider 24-bit delta (reference uses 8) lets heavily pipelined schedules
// allocate one sub-slot per in-flight segment without wraparound.
#pragma once

#include <cstddef>
#include <cstdint>

#include "tpucoll/common/logging.h"

namespace tpucoll {

enum class DataType : uint8_t {
  kInt8 = 0,
  kUint8 = 1,
  kInt32 = 2,
  kUint32 = 3,
  kInt64 = 4,
  kUint64 = 5,
  kFloat16 = 6,
  kBFloat16 = 7,
  kFloat32 = 8,
  kFloat64 = 9,
};

inline size_t elementSize(DataType dt) {
  switch (dt) {
    case DataType::kInt8:
    case DataType::kUint8:
      return 1;
    case DataType::kFloat16:
    case DataType::kBFloat16:
      return 2;
    case DataType::kInt32:
    case DataType::kUint32:
    case DataType::kFloat32:
      return 4;
    case DataType::kInt64:
    case DataType::kUint64:
    case DataType::kFloat64:
      return 8;
  }
  TC_THROW(EnforceError, "unknown dtype ", static_cast<int>(dt));
}

enum class ReduceOp : uint8_t {
  kSum = 0,
  kProduct = 1,
  kMin = 2,
  kMax = 3,
};

// Per-collective slot prefixes. Every collective entry point builds its base
// slot from (prefix, user tag); concurrent collectives on one context must
// use distinct user tags, matching the reference semantics (gloo/types.h:67-74).
enum class SlotPrefix : uint8_t {
  kUser = 0,  // raw send/recv issued directly by the application
  kBarrier = 1,
  kBroadcast = 2,
  kAllreduce = 3,
  kReduce = 4,
  kGather = 5,
  kScatter = 6,
  kAllgather = 7,
  kAlltoall = 8,
  kReduceScatter = 9,
  // Fleet observability plane (common/fleetobs.cc): member -> leader
  // and leader -> rank 0 telemetry relays ride their own prefix so
  // in-band snapshots can never collide with user or collective slots.
  kFleetObs = 10,
};

class Slot {
 public:
  static constexpr int kPrefixBits = 8;
  static constexpr int kTagBits = 32;
  static constexpr int kDeltaBits = 24;

  static Slot build(SlotPrefix prefix, uint32_t tag) {
    uint64_t v = (static_cast<uint64_t>(prefix) << (kTagBits + kDeltaBits)) |
                 (static_cast<uint64_t>(tag) << kDeltaBits);
    return Slot(v);
  }

  // Derive a sub-slot for the i-th message of a schedule; bounds-checked so
  // overflow into the tag field is impossible.
  Slot offset(uint64_t delta) const {
    TC_ENFORCE_LT(delta, (uint64_t(1) << kDeltaBits), "slot delta overflow");
    TC_ENFORCE_EQ(value_ & ((uint64_t(1) << kDeltaBits) - 1), uint64_t(0),
                  "offset() must be called on a base slot");
    return Slot(value_ | delta);
  }

  uint64_t value() const { return value_; }
  explicit Slot(uint64_t v) : value_(v) {}

 private:
  uint64_t value_;
};

}  // namespace tpucoll
