// tpucoll_bench: latency/bandwidth benchmark CLI for the host data plane.
//
// Reproduces the reference's measurement methodology (gloo/benchmark/
// runner.cc, options.h, timer.h): element-count sweep, warmup iterations,
// run each point for a minimum wall time, report min/p50/p99/max per
// iteration plus algorithm bandwidth, verify the first iteration
// element-wise. Rendezvous via FileStore or TcpStore (one rank can host
// the store inline with --serve).
//
// Example (2 ranks on one host):
//   ./tpucoll_bench --rank 0 --size 2 --serve 29500 --op allreduce &
//   ./tpucoll_bench --rank 1 --size 2 --store tcp:127.0.0.1:29500
//       --op allreduce
#include <algorithm>
#include <chrono>
#include <cstdio>
#include <csignal>
#include <cstring>
#include <functional>
#include <memory>
#include <numeric>
#include <string>
#include <thread>
#include <vector>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/context.h"
#include "tpucoll/rendezvous/file_store.h"
#include "tpucoll/rendezvous/tcp_store.h"
#include "tpucoll/transport/device.h"

namespace {

using Clock = std::chrono::steady_clock;

struct Options {
  int rank = -1;
  int size = -1;
  std::string store;          // "file:/path" | "tcp:host:port"
  int servePort = -1;         // host a TcpStoreServer on this port
  std::string host = "127.0.0.1";
  std::string op = "allreduce";
  std::string algorithm = "auto";
  std::vector<size_t> elements;
  double minSeconds = 2.0;
  int warmup = 5;
  bool verify = true;
  bool json = false;
  uint32_t tagBase = 0;
  std::string authKey;
  bool encrypt = false;
  bool sync = false;        // busy-poll latency mode (reference --sync)
  int threads = 1;          // benchmark threads, each on a forked context
  int inputs = 1;           // input buffers per rank (allreduce)
  std::string dtype = "f32";  // allreduce payload: f32 | f16 | bf16
  std::string iface;        // bind device by interface name
};

void usage() {
  fprintf(stderr,
          "tpucoll_bench --rank R --size P (--store file:PATH|tcp:H:P | "
          "--serve PORT)\n"
          "  [--host H] [--op allreduce|allgather|reduce_scatter|broadcast|"
          "reduce|gather|scatter|alltoall|alltoallv|barrier|pairwise_exchange|sendrecv|\n"
          "   sendrecv_roundtrip]\n"
          "  [--algorithm auto|ring|hd|rd|bcube|ring_bf16_wire|ring_q8_wire|ring_q4_wire|auto_lossy_wire (allreduce) | auto|binomial|ring (reduce)\n"
          "   | auto|ring|hd|direct (reduce_scatter)]\n"
          "  [--elements n1,n2,...] "
          "[--min-time SECONDS] [--warmup N] [--no-verify] [--json]\n"
          "  [--auth-key K] [--encrypt]   (PSK handshake / AEAD wire)\n"
          "  [--threads N] [--inputs N] [--dtype f32|f16|bf16] "
          "[--iface NAME] [--sync]\n");
}

std::vector<size_t> parseElements(const std::string& arg) {
  std::vector<size_t> out;
  size_t pos = 0;
  while (pos < arg.size()) {
    size_t comma = arg.find(',', pos);
    if (comma == std::string::npos) {
      comma = arg.size();
    }
    out.push_back(std::stoull(arg.substr(pos, comma - pos)));
    pos = comma + 1;
  }
  return out;
}

Options parse(int argc, char** argv) {
  Options o;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      TC_ENFORCE_LT(i + 1, argc, "missing value for ", a);
      return argv[++i];
    };
    if (a == "--rank") {
      o.rank = std::stoi(next());
    } else if (a == "--size") {
      o.size = std::stoi(next());
    } else if (a == "--store") {
      o.store = next();
    } else if (a == "--serve") {
      o.servePort = std::stoi(next());
    } else if (a == "--host") {
      o.host = next();
    } else if (a == "--op") {
      o.op = next();
    } else if (a == "--algorithm") {
      o.algorithm = next();
    } else if (a == "--elements") {
      o.elements = parseElements(next());
    } else if (a == "--min-time") {
      o.minSeconds = std::stod(next());
    } else if (a == "--warmup") {
      // At least one warmup iteration: its median seeds the agreed
      // iteration count.
      o.warmup = std::max(1, std::stoi(next()));
    } else if (a == "--no-verify") {
      o.verify = false;
    } else if (a == "--json") {
      o.json = true;
    } else if (a == "--auth-key") {
      o.authKey = next();
    } else if (a == "--encrypt") {
      o.encrypt = true;
    } else if (a == "--threads") {
      o.threads = std::max(1, std::stoi(next()));
    } else if (a == "--inputs") {
      o.inputs = std::max(1, std::stoi(next()));
    } else if (a == "--dtype") {
      o.dtype = next();
      TC_ENFORCE(o.dtype == "f32" || o.dtype == "f16" || o.dtype == "bf16",
                 "--dtype must be f32|f16|bf16, got ", o.dtype);
    } else if (a == "--iface") {
      o.iface = next();
    } else if (a == "--sync") {
      o.sync = true;
    } else {
      usage();
      TC_THROW(tpucoll::EnforceError, "unknown argument ", a);
    }
  }
  TC_ENFORCE(o.rank >= 0 && o.size > 0, "--rank/--size required");
  TC_ENFORCE(!o.store.empty() || o.servePort >= 0,
             "--store or --serve required");
  if (o.elements.empty()) {
    for (size_t n = 100; n <= 4'000'000; n *= 10) {
      o.elements.push_back(n);
    }
  }
  TC_ENFORCE(o.op == "allreduce" || (o.dtype == "f32" && o.inputs == 1),
             "--dtype/--inputs apply to --op allreduce only");
  TC_ENFORCE(o.dtype == "f32" || (o.algorithm != "ring_bf16_wire" &&
                                  o.algorithm != "ring_q8_wire" &&
                                  o.algorithm != "ring_q4_wire"),
             "--dtype f16/bf16 cannot combine with a wire codec "
             "(f32-only)");
  return o;
}

std::shared_ptr<tpucoll::Store> makeStore(
    const Options& o, std::unique_ptr<tpucoll::TcpStoreServer>* server) {
  if (o.servePort >= 0) {
    *server = std::make_unique<tpucoll::TcpStoreServer>(
        "0.0.0.0", static_cast<uint16_t>(o.servePort));
    // With --serve 0 the kernel picks the port; peers need to know it.
    fprintf(stderr, "[tpucoll_bench] store serving on port %u\n",
            (*server)->port());
    return std::make_shared<tpucoll::TcpStore>("127.0.0.1",
                                               (*server)->port());
  }
  if (o.store.rfind("file:", 0) == 0) {
    return std::make_shared<tpucoll::FileStore>(o.store.substr(5));
  }
  if (o.store.rfind("tcp:", 0) == 0) {
    std::string rest = o.store.substr(4);
    size_t colon = rest.rfind(':');
    TC_ENFORCE_NE(colon, std::string::npos, "bad --store ", o.store);
    return std::make_shared<tpucoll::TcpStore>(
        rest.substr(0, colon),
        static_cast<uint16_t>(std::stoi(rest.substr(colon + 1))));
  }
  TC_THROW(tpucoll::EnforceError, "bad --store ", o.store);
}

struct Workload {
  // Returns bytes moved per iteration for bandwidth math (algorithm
  // bandwidth = payload bytes / time, the reference's definition).
  std::function<void()> run;
  std::function<bool()> verifyOnce;  // true iff verified OK
  size_t algBytes;
};

// Per-workload buffer storage: lives at the call site for the workload's
// lifetime (the lambdas capture views into it).
struct Buffers {
  std::vector<float> buf, out;
  std::vector<uint16_t> half;                 // f16/bf16 payload
  std::vector<std::vector<float>> extraF32;   // --inputs > 1
  std::vector<std::vector<uint16_t>> extraHalf;
};

tpucoll::AllreduceAlgorithm parseAllreduceAlgorithm(const std::string& a) {
  using tpucoll::AllreduceAlgorithm;
  return a == "ring"             ? AllreduceAlgorithm::kRing
         : a == "bcube"          ? AllreduceAlgorithm::kBcube
         : a == "rd"             ? AllreduceAlgorithm::kRecursiveDoubling
         : a == "ring_bf16_wire" ? AllreduceAlgorithm::kRingBf16Wire
         : a == "ring_q8_wire"   ? AllreduceAlgorithm::kRingQ8Wire
         : a == "ring_q4_wire"   ? AllreduceAlgorithm::kRingQ4Wire
         : a == "auto_lossy_wire" ? AllreduceAlgorithm::kAutoLossyWire
         : (a == "hd" || a == "halving_doubling")
             ? AllreduceAlgorithm::kHalvingDoubling
             : AllreduceAlgorithm::kAuto;
}

// Shared allreduce workload across payload dtypes: Elem is the storage
// type, enc/dec convert to/from float (identity for f32). Verification
// is tolerance-based so half formats stay valid at any rank/input count
// (bf16 integers are only exact to 256).
template <typename Elem, typename Enc, typename Dec>
Workload makeAllreduceWorkloadT(const Options& o, tpucoll::Context& ctx,
                                uint32_t tag, tpucoll::DataType dt,
                                double rtol, size_t elements,
                                std::vector<Elem>& payload,
                                std::vector<std::vector<Elem>>& extra,
                                Enc enc, Dec dec) {
  using namespace tpucoll;
  const int rank = ctx.rank();
  const int size = ctx.size();
  Workload w;
  w.algBytes = elements * sizeof(Elem);
  payload.assign(elements, enc(1.f));
  extra.assign(o.inputs - 1, std::vector<Elem>(elements, enc(1.f)));
  const auto algo = parseAllreduceAlgorithm(o.algorithm);
  auto* pp = &payload;
  auto* ep = &extra;
  std::function<void()> run = [&ctx, pp, ep, tag, dt, algo] {
    AllreduceOptions opts;
    opts.context = &ctx;
    opts.tag = tag;
    opts.inputs = {pp->data()};
    for (auto& v : *ep) {
      opts.inputs.push_back(v.data());
    }
    opts.outputs = {pp->data()};
    opts.count = pp->size();
    opts.dtype = dt;
    opts.algorithm = algo;
    allreduce(opts);
  };
  w.run = run;
  w.verifyOnce = [run, pp, ep, rank, size, enc, dec, rtol,
                  inputs = o.inputs] {
    pp->assign(pp->size(), enc(float(rank + 1)));
    for (auto& vec : *ep) {
      vec.assign(vec.size(), enc(float(rank + 1)));
    }
    run();
    const double expect = double(inputs) * size * (size + 1) / 2.0;
    bool ok = std::all_of(pp->begin(), pp->end(), [&](Elem v) {
      return std::abs(double(dec(v)) - expect) <= rtol * expect;
    });
    pp->assign(pp->size(), enc(1.f));
    for (auto& vec : *ep) {
      vec.assign(vec.size(), enc(1.f));
    }
    return ok;
  };
  return w;
}

Workload makeAllreduceWorkload(const Options& o, tpucoll::Context& ctx,
                               size_t elements, uint32_t tag,
                               Buffers& bufs) {
  using namespace tpucoll;
  if (o.dtype == "f32") {
    // Exact verification, except through the q8/q4 wires: their per-hop
    // block quantization is within one step per hop but not exact even
    // for small-integer payloads (the scale's *127/127 or *7/7
    // roundtrip double-rounds). bf16-wire stays exact here: small ints
    // are exactly representable in bf16.
    const bool lossy = o.algorithm == "ring_q8_wire" ||
                       o.algorithm == "ring_q4_wire" ||
                       o.algorithm == "auto_lossy_wire";
    return makeAllreduceWorkloadT(
        o, ctx, tag, DataType::kFloat32, lossy ? 1e-2 : 0.0, elements,
        bufs.buf, bufs.extraF32, [](float v) { return v; },
        [](float v) { return v; });
  }
  if (o.dtype == "f16") {
    return makeAllreduceWorkloadT(
        o, ctx, tag, DataType::kFloat16, 1e-3, elements, bufs.half,
        bufs.extraHalf, [](float v) { return floatToHalf(v); },
        [](uint16_t v) { return halfToFloat(v); });
  }
  return makeAllreduceWorkloadT(
      o, ctx, tag, DataType::kBFloat16, 1e-2, elements, bufs.half,
      bufs.extraHalf, [](float v) { return floatToBfloat16(v); },
      [](uint16_t v) { return bfloat16ToFloat(v); });
}

Workload makeWorkload(const Options& o, tpucoll::Context& ctx,
                      size_t elements, uint32_t tag, Buffers& bufs) {
  using namespace tpucoll;
  std::vector<float>& buf = bufs.buf;
  std::vector<float>& out = bufs.out;
  const int rank = ctx.rank();
  const int size = ctx.size();
  Workload w;
  w.algBytes = elements * sizeof(float);

  // NOTE: lambdas capture buf/out/ctx by reference (owned by the caller for
  // the workload's lifetime) and everything else by value — run/verifyOnce
  // outlive this frame.
  auto ctxp = &ctx;

  if (o.op == "allreduce") {
    return makeAllreduceWorkload(o, ctx, elements, tag, bufs);
  } else if (o.op == "allgather") {
    buf.assign(elements, float(rank));
    out.assign(elements * size, 0.f);
    std::function<void()> run = [ctxp, &buf, &out, tag] {
      AllgatherOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.input = buf.data();
      opts.output = out.data();
      opts.count = buf.size();
      allgather(opts);
    };
    w.run = run;
    w.verifyOnce = [run, &out, elements, size] {
      run();
      for (int r = 0; r < size; r++) {
        for (size_t i = 0; i < elements; i++) {
          if (out[r * elements + i] != float(r)) {
            return false;
          }
        }
      }
      return true;
    };
  } else if (o.op == "reduce_scatter") {
    buf.assign(elements, 1.f);
    out.assign(elements / size + elements % size + 1, 0.f);
    std::vector<size_t> counts(size, elements / size);
    counts[0] += elements % size;
    TC_ENFORCE(
        o.algorithm == "auto" || o.algorithm == "ring" ||
            o.algorithm == "hd" || o.algorithm == "direct",
        "--op reduce_scatter supports --algorithm auto|ring|hd|direct");
    const auto rsalgo =
        o.algorithm == "ring" ? tpucoll::ReduceScatterAlgorithm::kRing
        : o.algorithm == "hd"
            ? tpucoll::ReduceScatterAlgorithm::kHalvingDoubling
        : o.algorithm == "direct"
            ? tpucoll::ReduceScatterAlgorithm::kDirect
            : tpucoll::ReduceScatterAlgorithm::kAuto;
    std::function<void()> run = [ctxp, &buf, &out, tag, counts, rsalgo] {
      ReduceScatterOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.input = buf.data();
      opts.output = out.data();
      opts.recvCounts = counts;
      opts.algorithm = rsalgo;
      reduceScatter(opts);
    };
    w.run = run;
    // Verify: with all-ones inputs every output element must equal `size`.
    w.verifyOnce = [run, &out, counts, rank, size] {
      run();
      for (size_t i = 0; i < counts[rank]; i++) {
        if (out[i] != float(size)) {
          return false;
        }
      }
      return true;
    };
  } else if (o.op == "broadcast") {
    buf.assign(elements, rank == 0 ? 42.f : 0.f);
    std::function<void()> run = [ctxp, &buf, tag] {
      BroadcastOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.buffer = buf.data();
      opts.count = buf.size();
      broadcast(opts);
    };
    w.run = run;
    w.verifyOnce = [run, &buf] {
      run();
      return std::all_of(buf.begin(), buf.end(),
                         [](float v) { return v == 42.f; });
    };
  } else if (o.op == "alltoall") {
    buf.assign(elements * size, float(rank));
    out.assign(elements * size, 0.f);
    w.algBytes = elements * size * sizeof(float);
    std::function<void()> run = [ctxp, &buf, &out, tag, elements] {
      AlltoallOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.input = buf.data();
      opts.output = out.data();
      opts.count = elements;
      alltoall(opts);
    };
    w.run = run;
    w.verifyOnce = [run, &out, elements, size] {
      run();
      for (int r = 0; r < size; r++) {
        for (size_t i = 0; i < elements; i++) {
          if (out[r * elements + i] != float(r)) {
            return false;
          }
        }
      }
      return true;
    };
  } else if (o.op == "alltoallv") {
    // Uneven splits (reference workload: gloo/benchmark alltoallv):
    // this rank sends (elements + j - rank mod size) elements to rank j —
    // every pairwise message size differs, exercising the v-variant's
    // offset bookkeeping under the timing loop.
    std::vector<size_t> inCounts(size), outCounts(size);
    size_t inTotal = 0, outTotal = 0;
    for (int j = 0; j < size; j++) {
      inCounts[j] = elements + size_t((j - rank + size) % size);
      outCounts[j] = elements + size_t((rank - j + size) % size);
      inTotal += inCounts[j];
      outTotal += outCounts[j];
    }
    buf.assign(inTotal, float(rank));
    out.assign(outTotal, 0.f);
    w.algBytes = inTotal * sizeof(float);
    std::function<void()> run = [ctxp, &buf, &out, tag, inCounts,
                                 outCounts] {
      AlltoallvOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.input = buf.data();
      opts.output = out.data();
      opts.inCounts = inCounts;
      opts.outCounts = outCounts;
      alltoallv(opts);
    };
    w.run = run;
    w.verifyOnce = [run, &out, outCounts, size] {
      run();
      size_t off = 0;
      for (int r = 0; r < size; r++) {
        for (size_t i = 0; i < outCounts[r]; i++) {
          if (out[off + i] != float(r)) {
            return false;
          }
        }
        off += outCounts[r];
      }
      return true;
    };
  } else if (o.op == "barrier") {
    w.algBytes = 0;
    std::function<void()> run = [ctxp, tag] {
      BarrierOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      barrier(opts);
    };
    w.run = run;
    w.verifyOnce = [run] {
      run();
      return true;
    };
  } else if (o.op == "reduce") {
    buf.assign(elements, float(rank + 1));
    out.assign(elements, 0.f);
    TC_ENFORCE(o.algorithm == "auto" || o.algorithm == "ring" ||
                   o.algorithm == "binomial",
               "--op reduce supports --algorithm auto|binomial|ring");
    const auto ralgo = o.algorithm == "ring" ? tpucoll::ReduceAlgorithm::kRing
                       : o.algorithm == "binomial"
                           ? tpucoll::ReduceAlgorithm::kBinomial
                           : tpucoll::ReduceAlgorithm::kAuto;
    std::function<void()> run = [ctxp, &buf, &out, tag, rank, ralgo] {
      ReduceOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.input = buf.data();
      opts.output = rank == 0 ? out.data() : nullptr;
      opts.count = buf.size();
      opts.root = 0;
      opts.algorithm = ralgo;
      reduce(opts);
    };
    w.run = run;
    w.verifyOnce = [run, &out, rank, size, elements] {
      run();
      if (rank != 0) {
        return true;
      }
      const float expect = size * (size + 1) / 2.0f;
      for (size_t i = 0; i < elements; i++) {
        if (out[i] != expect) {
          return false;
        }
      }
      return true;
    };
  } else if (o.op == "gather") {
    buf.assign(elements, float(rank));
    out.assign(elements * size, 0.f);
    std::function<void()> run = [ctxp, &buf, &out, tag, rank] {
      GatherOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.input = buf.data();
      opts.output = rank == 0 ? out.data() : nullptr;
      opts.count = buf.size();
      opts.root = 0;
      gather(opts);
    };
    w.run = run;
    w.verifyOnce = [run, &out, rank, size, elements] {
      run();
      if (rank != 0) {
        return true;
      }
      for (int r = 0; r < size; r++) {
        if (out[r * elements] != float(r)) {
          return false;
        }
      }
      return true;
    };
  } else if (o.op == "scatter") {
    // Root's chunk r holds float(r) so misrouted/misoffset chunks are
    // detectable.
    buf.resize(elements * size);
    for (int r = 0; r < size; r++) {
      std::fill(buf.begin() + r * elements, buf.begin() + (r + 1) * elements,
                float(r));
    }
    out.assign(elements, -1.f);
    std::function<void()> run = [ctxp, &buf, &out, tag, rank] {
      ScatterOptions opts;
      opts.context = ctxp;
      opts.tag = tag;
      opts.input = rank == 0 ? buf.data() : nullptr;
      opts.output = out.data();
      opts.count = out.size();
      opts.root = 0;
      scatter(opts);
    };
    w.run = run;
    w.verifyOnce = [run, &out, elements, rank] {
      run();
      for (size_t i = 0; i < elements; i++) {
        if (out[i] != float(rank)) {
          return false;
        }
      }
      return true;
    };
  } else if (o.op == "pairwise_exchange") {
    // Reference workload (gloo/benchmark pairwise_exchange.h): every rank
    // exchanges `elements` floats with each XOR partner per iteration.
    TC_ENFORCE((size & (size - 1)) == 0,
               "pairwise_exchange needs a power-of-2 size");
    buf.assign(elements, float(rank));
    out.assign(elements, 0.f);
    std::shared_ptr<tpucoll::transport::UnboundBuffer> sb(
        ctx.createUnboundBuffer(buf.data(), buf.size() * sizeof(float))
            .release());
    std::shared_ptr<tpucoll::transport::UnboundBuffer> rb(
        ctx.createUnboundBuffer(out.data(), out.size() * sizeof(float))
            .release());
    w.algBytes = elements * sizeof(float) * (size - 1);
    std::function<void()> run = [ctxp, sb, rb, rank, size] {
      for (int step = 1; step < size; step++) {
        const int partner = rank ^ step;
        const uint64_t slot = ctxp->nextSlot();
        // Matching slot on both sides: nextSlot advances in lockstep
        // because every rank runs the same schedule.
        rb->recv(partner, slot);
        sb->send(partner, slot);
        rb->waitRecv(nullptr, std::chrono::milliseconds(30000));
        sb->waitSend(std::chrono::milliseconds(30000));
      }
    };
    w.run = run;
    w.verifyOnce = [run, &out, rank, size] {
      run();
      // After the last step, out holds the last partner's rank value.
      return out.empty() || out[0] == float(rank ^ (size - 1));
    };
  } else if (o.op == "sendrecv_roundtrip") {
    // Ping-pong: rank 0 sends, rank 1 echoes; p50 is the full round trip
    // (divide by 2 for one-way latency). Unlike `sendrecv`, completion
    // requires delivery, not just kernel-buffer acceptance.
    TC_ENFORCE_EQ(size, 2, "sendrecv_roundtrip runs with exactly 2 ranks");
    buf.assign(elements, float(rank));
    std::shared_ptr<tpucoll::transport::UnboundBuffer> ub(
        ctx.createUnboundBuffer(buf.data(), buf.size() * sizeof(float))
            .release());
    std::function<void()> run = [ctxp, &buf, ub, rank] {
      const uint64_t s1 = ctxp->nextSlot();
      const uint64_t s2 = ctxp->nextSlot();
      const auto t = std::chrono::milliseconds(30000);
      if (rank == 0) {
        ub->send(1, s1, 0, buf.size() * sizeof(float));
        ub->waitSend(t);
        ub->recv(1, s2, 0, buf.size() * sizeof(float));
        ub->waitRecv(nullptr, t);
      } else {
        ub->recv(0, s1, 0, buf.size() * sizeof(float));
        ub->waitRecv(nullptr, t);
        ub->send(0, s2, 0, buf.size() * sizeof(float));
        ub->waitSend(t);
      }
    };
    w.run = run;
    w.verifyOnce = [run] {
      run();
      return true;
    };
  } else if (o.op == "sendrecv") {
    TC_ENFORCE_EQ(size, 2, "sendrecv runs with exactly 2 ranks");
    buf.assign(elements, float(rank));
    std::shared_ptr<tpucoll::transport::UnboundBuffer> ub(
        ctx.createUnboundBuffer(buf.data(), buf.size() * sizeof(float))
            .release());
    std::function<void()> run = [ctxp, &buf, ub, rank] {
      const uint64_t slot = ctxp->nextSlot();
      if (rank == 0) {
        ub->send(1, slot, 0, buf.size() * sizeof(float));
        ub->waitSend(std::chrono::milliseconds(30000));
      } else {
        ub->recv(0, slot, 0, buf.size() * sizeof(float));
        ub->waitRecv(nullptr, std::chrono::milliseconds(30000));
      }
    };
    w.run = run;
    w.verifyOnce = [run] {
      run();
      return true;
    };
  } else {
    TC_THROW(tpucoll::EnforceError, "unknown op ", o.op);
  }
  return w;
}

}  // namespace

int runBench(int argc, char** argv);

int main(int argc, char** argv) {
  try {
    return runBench(argc, argv);
  } catch (const std::exception& e) {
    fprintf(stderr, "tpucoll_bench: %s\n", e.what());
    return 1;
  }
}

int runBench(int argc, char** argv) {
  using namespace tpucoll;
  signal(SIGPIPE, SIG_IGN);
  Options o = parse(argc, argv);
  std::unique_ptr<tpucoll::TcpStoreServer> server;
  auto store = makeStore(o, &server);

  tpucoll::transport::DeviceAttr attr;
  attr.hostname = o.host;
  attr.iface = o.iface;
  attr.authKey = o.authKey;
  attr.encrypt = o.encrypt;
  attr.busyPoll = o.sync;
  auto device = std::make_shared<tpucoll::transport::Device>(attr);
  tpucoll::Context ctx(o.rank, o.size);
  ctx.connectFullMesh(store, device);

  // --threads: each benchmark thread drives its own context, forked from
  // the connected mesh without another store round trip (reference:
  // ContextFactory per thread, gloo/benchmark/runner.cc:286-288).
  std::vector<std::unique_ptr<tpucoll::Context>> forked;
  std::vector<tpucoll::Context*> tctxs{&ctx};
  for (int t = 1; t < o.threads; t++) {
    auto c = std::make_unique<tpucoll::Context>(o.rank, o.size);
    // forkFrom consumes TWO tags on the parent (blob allgatherv +
    // length allgather), so stride by 2 to keep forks from
    // cross-matching at skewed boundaries.
    c->forkFrom(ctx, 0xFFF000u + 2 * t);
    tctxs.push_back(c.get());
    forked.push_back(std::move(c));
  }

  if (o.rank == 0 && !o.json) {
    printf("# tpucoll_bench op=%s algorithm=%s size=%d device=%s\n",
           o.op.c_str(), o.algorithm.c_str(), o.size,
           device->str().c_str());
    printf("%12s %12s %10s %10s %10s %10s %12s %8s\n", "bytes", "elements",
           "min(us)", "p50(us)", "p99(us)", "max(us)", "algbw(GB/s)",
           "iters");
  }

  uint32_t tag = o.tagBase;
  for (size_t elements : o.elements) {
    // One tag per sweep point: ranks can be a whole call skewed at the
    // boundary between points, and collectives of different shapes must
    // not cross-match (same contract as the reference's tag semantics).
    const uint32_t pointTag = tag;
    tag += 2;

    std::vector<std::vector<double>> allSamples(o.threads);
    size_t algBytes = 0;

    auto worker = [&](int t) {
      tpucoll::Context& c = *tctxs[t];
      Buffers bufs;
      Workload w = makeWorkload(o, c, elements, pointTag, bufs);
      if (t == 0) {
        algBytes = w.algBytes;  // identical across threads; single writer
      }

      if (o.verify && t == 0) {
        TC_ENFORCE(w.verifyOnce(), "verification failed for ", o.op,
                   " at ", elements, " elements");
      }
      double warmupP50 = 0;
      {
        std::vector<double> wsamples;
        for (int i = 0; i < o.warmup; i++) {
          const auto t0 = Clock::now();
          w.run();
          wsamples.push_back(
              std::chrono::duration<double>(Clock::now() - t0).count());
        }
        std::sort(wsamples.begin(), wsamples.end());
        warmupP50 = wsamples[wsamples.size() / 2];
      }

      // Agree on an iteration count (reference: median time broadcast,
      // gloo/benchmark/runner.cc:322-330) so no rank leaves the sweep
      // point before its peers — per thread-context, since each forms
      // its own lockstep group. Capped: percentile quality does not
      // improve past a few tens of thousands of samples.
      uint64_t iters = std::min<uint64_t>(
          50000, std::max<uint64_t>(1, uint64_t(o.minSeconds / warmupP50)));
      {
        BroadcastOptions opts;
        opts.context = &c;
        opts.tag = pointTag + 1;
        opts.buffer = &iters;
        opts.count = 1;
        opts.dtype = DataType::kUint64;
        broadcast(opts);
      }

      auto& samples = allSamples[t];
      samples.reserve(iters);
      for (uint64_t i = 0; i < iters; i++) {
        const auto t0 = Clock::now();
        w.run();
        samples.push_back(
            std::chrono::duration<double>(Clock::now() - t0).count());
      }
    };

    if (o.threads == 1) {
      worker(0);
    } else {
      // Capture worker exceptions: one escaping a std::thread would
      // std::terminate past main()'s catch and dump core diagnostics-free.
      std::vector<std::exception_ptr> errors(o.threads);
      std::vector<std::thread> pool;
      for (int t = 0; t < o.threads; t++) {
        pool.emplace_back([&, t] {
          try {
            worker(t);
          } catch (...) {
            errors[t] = std::current_exception();
          }
        });
      }
      for (auto& th : pool) {
        th.join();
      }
      for (auto& e : errors) {
        if (e) {
          std::rethrow_exception(e);
        }
      }
    }

    std::vector<double> samples;
    for (auto& s : allSamples) {
      samples.insert(samples.end(), s.begin(), s.end());
    }
    std::sort(samples.begin(), samples.end());
    auto pct = [&](double p) {
      return samples[std::min(samples.size() - 1,
                              size_t(p * samples.size()))] * 1e6;
    };
    const double p50 = pct(0.5);
    // Aggregate bandwidth: each thread moves algBytes per iteration
    // concurrently.
    const double algbw = double(o.threads) * algBytes / (p50 / 1e6) / 1e9;
    if (o.rank == 0) {
      if (o.json) {
        printf("{\"op\":\"%s\",\"elements\":%zu,\"bytes\":%zu,"
               "\"dtype\":\"%s\",\"threads\":%d,\"inputs\":%d,"
               "\"min_us\":%.1f,\"p50_us\":%.1f,\"p99_us\":%.1f,"
               "\"max_us\":%.1f,\"algbw_gbps\":%.3f,\"iters\":%zu}\n",
               o.op.c_str(), elements, algBytes, o.dtype.c_str(),
               o.threads, o.inputs, pct(0.0), p50, pct(0.99),
               samples.back() * 1e6, algbw, samples.size());
      } else {
        printf("%12zu %12zu %10.1f %10.1f %10.1f %10.1f %12.3f %8zu\n",
               algBytes, elements, pct(0.0), p50, pct(0.99),
               samples.back() * 1e6, algbw, samples.size());
      }
    }
  }
  for (auto& c : forked) {
    c->close();
  }
  ctx.close();
  return 0;
}
