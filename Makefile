.PHONY: native test clean

native:
	cmake -S csrc -B build -G Ninja -DCMAKE_BUILD_TYPE=RelWithDebInfo >/dev/null
	cmake --build build

test: native
	python -m pytest tests/ -x -q

clean:
	rm -rf build gloo_tpu/_native/*.so
