.PHONY: native native-cmake native-cc test check clean postmortem-demo

# Build the native core. Prefers the CMake/Ninja build (full configure
# checks, separate bench/test binaries); falls back to a plain
# compiler-driver build of just libtpucoll.so when cmake is not
# installed, so `pip install .` / `make native` work on minimal images.
# SANITIZE=address|thread|undefined always takes the fallback path: sanitizer
# flavors are a test-rig artifact of this cmake-less build (the cmake
# build has TPUCOLL_OUTPUT_DIR for the same isolation).
native:
	@if [ -n "$(SANITIZE)" ]; then \
		$(MAKE) -j$$(nproc) native-cc; \
	elif command -v cmake >/dev/null 2>&1 && command -v ninja >/dev/null 2>&1; then \
		$(MAKE) native-cmake; \
	else \
		$(MAKE) -j$$(nproc) native-cc; \
	fi

native-cmake:
	cmake -S csrc -B build -G Ninja -DCMAKE_BUILD_TYPE=RelWithDebInfo >/dev/null
	cmake --build build

# ---- fallback build (no cmake): mirrors csrc/CMakeLists.txt ----
CXX ?= g++

# Sanitizer flavors: `make SANITIZE=address` (or thread, undefined)
# compiles the whole core with -fsanitize=... into its own build dir and
# a SUFFIXED library (libtpucoll_asan.so / libtpucoll_tsan.so /
# libtpucoll_ubsan.so) so instrumented builds never clobber — or get
# clobbered by — the production .so.
# Run the Python suite against one with
#   TPUCOLL_LIB=$PWD/gloo_tpu/_native/libtpucoll_asan.so \
#   TPUCOLL_SKIP_BUILD=1 python -m pytest tests/ ...
# (tests/test_native_unit.py has a skip-unless-built ASan smoke test).
SAN_SUFFIX :=
SAN_FLAGS :=
ifeq ($(SANITIZE),address)
SAN_SUFFIX := _asan
SAN_FLAGS := -fsanitize=address -fno-omit-frame-pointer
else ifeq ($(SANITIZE),thread)
SAN_SUFFIX := _tsan
# tsan_preinclude.h: gcc-10 libtsan can't see pthread_cond_clockwait,
# which libstdc++-10 uses for timed condvar waits — without this every
# such mutex false-positives as "double lock" (GCC PR98624).
SAN_FLAGS := -fsanitize=thread -fno-omit-frame-pointer \
	-include csrc/tpucoll/common/tsan_preinclude.h
else ifeq ($(SANITIZE),undefined)
SAN_SUFFIX := _ubsan
# -fno-sanitize-recover=all: a UB report aborts the process instead of
# printing and carrying on, so the smoke test fails on the FIRST hit.
SAN_FLAGS := -fsanitize=undefined -fno-sanitize-recover=all \
	-fno-omit-frame-pointer
else ifneq ($(SANITIZE),)
$(error SANITIZE must be 'address', 'thread' or 'undefined', got '$(SANITIZE)')
endif

FB_BUILD := build-fb$(subst _,-,$(SAN_SUFFIX))
FB_LIB := gloo_tpu/_native/libtpucoll$(SAN_SUFFIX).so
FB_SRCS := $(filter-out csrc/tpucoll/common/crypto_avx512.cc,\
	$(wildcard csrc/tpucoll/*.cc csrc/tpucoll/*/*.cc))
FB_OBJS := $(patsubst csrc/%.cc,$(FB_BUILD)/%.o,$(FB_SRCS))
# -MMD/-MP: header dependency tracking, so editing a .h rebuilds the
# objects that include it (cmake gets this for free; the fallback must
# not silently package a stale .so after header edits).
FB_FLAGS := -std=c++17 -O3 -g -fPIC -Wall -Wextra -Icsrc -pthread -MMD -MP \
	$(SAN_FLAGS)

ARCH := $(shell uname -m)
ifeq ($(ARCH),x86_64)
FB_FLAGS += -mavx2 -mfma -mf16c
# AVX-512 ChaCha20 tier: own TU with -mavx512f, runtime-dispatched
# (crypto.cc), only when the compiler supports the flag.
FB_AVX512 := $(shell echo 'int main(){return 0;}' | $(CXX) -mavx512f \
	-x c++ - -o /dev/null 2>/dev/null && echo 1)
endif
ifeq ($(FB_AVX512),1)
FB_FLAGS += -DTPUCOLL_HAVE_AVX512=1
FB_OBJS += $(FB_BUILD)/tpucoll/common/crypto_avx512.o
endif

# The cmake build also produces the native test binaries; the fallback
# builds them too (same objects, one extra link each) so the pytest
# wrappers in tests/test_native_unit.py run on cmake-less images instead
# of failing on a missing build/tpucoll_unit. Sanitizer flavors skip
# them: their pytest entry points are the LD_PRELOAD smokes, not these.
ifeq ($(SAN_SUFFIX),)
native-cc: $(FB_LIB) build/tpucoll_unit build/tpucoll_integration \
	build/tpucoll_bench
else
native-cc: $(FB_LIB)
endif

$(FB_LIB): $(FB_OBJS)
	@mkdir -p gloo_tpu/_native
	$(CXX) -shared $(SAN_FLAGS) -o $@ $(FB_OBJS) -lpthread -lrt

build/tpucoll_unit: $(FB_BUILD)/tests/unit_main.o $(FB_OBJS)
	@mkdir -p build
	$(CXX) -o $@ $^ -lpthread -lrt

build/tpucoll_integration: $(FB_BUILD)/tests/integration_main.o $(FB_OBJS)
	@mkdir -p build
	$(CXX) -o $@ $^ -lpthread -lrt

# The benchmark CLI (csrc/benchmark/main.cc) — the measurement source of
# tools/bench_sweep.py and the native-bench pytest wrapper; the cmake
# build produces it as a first-class target, so the fallback must too.
build/tpucoll_bench: $(FB_BUILD)/benchmark/main.o $(FB_OBJS)
	@mkdir -p build
	$(CXX) -o $@ $^ -lpthread -lrt

$(FB_BUILD)/tpucoll/common/crypto_avx512.o: \
		csrc/tpucoll/common/crypto_avx512.cc
	@mkdir -p $(dir $@)
	$(CXX) $(FB_FLAGS) -mavx512f -c $< -o $@

$(FB_BUILD)/%.o: csrc/%.cc
	@mkdir -p $(dir $@)
	$(CXX) $(FB_FLAGS) -c $< -o $@

-include $(FB_OBJS:.o=.d) $(FB_BUILD)/tests/unit_main.d \
	$(FB_BUILD)/tests/integration_main.d $(FB_BUILD)/benchmark/main.d

test: native
	python -m pytest tests/ -x -q

# Static-analysis suite (docs/check.md): the project-native invariants —
# C-ABI mirroring, exception tightness, env hygiene, explicit atomics,
# flightrec coverage, metrics name agreement, lock-order discipline,
# no bare asserts. `make check JSON=report.json` also writes the
# machine-readable report CI annotations consume.
check:
	python -m tools.check $(if $(JSON),--json $(JSON))

# Post-mortem walkthrough (docs/flightrec.md): inject a stall with the
# fault plane, let the watchdog auto-dump the always-on flight recorder,
# provoke a schedule desync, then merge the per-rank dumps and print the
# blame — the whole chaos -> recorder -> merge -> blame chain.
postmortem-demo: native
	JAX_PLATFORMS=cpu python examples/example_flightrec.py

clean:
	rm -rf build build-fb build-fb-asan build-fb-tsan build-fb-ubsan \
		gloo_tpu/_native/*.so
