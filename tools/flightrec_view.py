#!/usr/bin/env python
"""Post-mortem viewer for flight-recorder dumps (docs/flightrec.md).

Point it at a dump directory, individual dump files, or LIVE ranks'
telemetry endpoints (``http://host:port`` sources fetch ``/flightrec``
from gloo_tpu.utils.telemetry.serve_telemetry — post-mortem and live
tooling share this one CLI); it merges the per-rank rings, prints the
cross-rank timeline tail and the verdict — desync (who ran what at the
diverging seq), stall (who everyone blames), or clean — and can emit a
Perfetto/chrome://tracing file of the merged timeline.

    python tools/flightrec_view.py flightrec-dump/
    python tools/flightrec_view.py dump/flightrec-rank*.json --tail 30
    python tools/flightrec_view.py http://10.0.0.1:9401 http://10.0.0.2:9401
    python tools/flightrec_view.py flightrec-dump/ --perfetto out.json
    python tools/flightrec_view.py flightrec-dump/ --check   # exit 2 on desync

Exit status: 0 clean, 1 stall, 2 desync (with --check; otherwise 0
unless the input is unusable).

With ``--fleet`` the sources are rank 0 endpoints (or saved fleet
documents) and the merged in-band ``/fleet`` view is rendered instead
(docs/fleet.md). Endpoint handling (timeout, auth token) is shared with
profile_view via tools/_telemetry_client.py.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _telemetry_client  # noqa: E402
from gloo_tpu.utils import flightrec  # noqa: E402


def _resolve_source(src: str, timeout: float = 10.0, token=None):
    """A CLI source -> something flightrec.merge understands: http(s)
    URLs fetch the live /flightrec ring (loaded dict; unreachable ranks
    degrade to None, exactly like a missing dump file), everything else
    passes through as a path."""
    if not _telemetry_client.is_url(src):
        return src
    return _telemetry_client.fetch(src, "/flightrec",
                                   timeout=timeout, token=token)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dumps", nargs="+",
                    help="dump directory, flightrec-rank*.json files, "
                         "or live http://host:port telemetry endpoints")
    ap.add_argument("--tail", type=int, default=20,
                    help="timeline rows to print (default 20)")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="write merged Chrome trace-event JSON here")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on stall, 2 on desync")
    _telemetry_client.add_endpoint_args(ap)
    args = ap.parse_args()

    if args.fleet:
        return _telemetry_client.run_fleet_mode(
            args.dumps, timeout=args.timeout, token=args.token)

    # A directory may hold dumps from several communicators — the root
    # context plus split sub-groups (tagged -g<group>) and async lanes
    # (-lane<k>). Each tag is an independent schedule: merge + analyze
    # PER TAG, never across (disjoint groups legitimately run different
    # collectives; comparing their fingerprints would invent a desync).
    if len(args.dumps) == 1 and os.path.isdir(args.dumps[0]):
        groups = flightrec.merge_by_tag(args.dumps[0])
    else:
        sources = [_resolve_source(s, timeout=args.timeout,
                                   token=args.token)
                   for s in args.dumps]
        groups = {"": flightrec.merge(sources)}
    groups = {tag: m for tag, m in groups.items() if m["ranks"]}
    if not groups:
        print("no usable dumps found", file=sys.stderr)
        return 1

    worst = 0
    for tag, merged in groups.items():
        label = f" [group {tag}]" if tag else ""
        print(f"ranks{label}: {sorted(merged['ranks'])} of "
              f"{merged['size']}"
              + (f"  MISSING: {merged['missing']}"
                 if merged["missing"] else ""))
        for rank, doc in sorted(merged["ranks"].items()):
            print(f"  rank {rank}: reason={doc.get('reason')} "
                  f"next_seq={doc.get('next_seq')} "
                  f"blamed_peer={doc.get('blamed_peer')} "
                  f"dropped={doc.get('dropped')}")

        print(f"\ntimeline{label} (last {args.tail} of "
              f"{len(merged['timeline'])}):")
        for e in merged["timeline"][-args.tail:]:
            print(f"  seq {e.get('seq'):>5}  rank {e.get('rank')}  "
                  f"{e.get('state', '?'):>9}  "
                  f"{flightrec.describe_event(e)}  "
                  f"slot={e.get('slot')} fp={e.get('fp')}")

        verdict = flightrec.analyze(merged)
        print(f"\nverdict{label}: {verdict['kind'].upper()}")
        print(f"  {verdict['message']}")
        if verdict["blamed_ranks"]:
            print(f"  blamed rank(s): {verdict['blamed_ranks']}")
        for rank, f in sorted(verdict.get("frontier", {}).items()):
            print(f"  rank {rank} frontier: seq {f['seq']} ({f['desc']}, "
                  f"{f['state']})")
        print()
        worst = max(worst,
                    {"ok": 0, "stall": 1, "desync": 2}.get(
                        verdict["kind"], 1))

        if args.perfetto:
            out = args.perfetto if not tag else \
                f"{args.perfetto}.{tag.replace('/', '.')}"
            with open(out, "w") as f:
                f.write(flightrec.to_perfetto(merged))
            print(f"wrote {out} (open in ui.perfetto.dev)")

    if args.check:
        return worst
    return 0


if __name__ == "__main__":
    sys.exit(main())
