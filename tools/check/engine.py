"""Rule engine for tpucoll-check: corpus loading, baselines, reporting.

A rule examines the corpus (the repo's csrc/ + gloo_tpu/ + docs/ trees)
and emits Violations keyed by a *stable* identifier — symbol names, env
vars, mutex pairs — never line numbers, so baselines survive unrelated
edits. Baselines live one file per rule under tools/check/baselines/:

    # comment
    <violation-key> -- <one-line justification>

A baselined violation is suppressed (reported separately); a baseline
entry with no live violation is *stale* and fails the run — fixed
violations must leave the baseline, or the file rots into a blanket
mute. See docs/check.md.
"""

from __future__ import annotations

import fnmatch
import json
import os
import time
from dataclasses import dataclass, field, asdict
from typing import Dict, Iterable, List, Optional, Tuple

from .cpp import CppFile


@dataclass(frozen=True)
class Violation:
    rule: str
    key: str          # stable id, unique within the rule
    path: str         # repo-relative file the violation anchors to
    line: int         # best-effort anchor (not part of identity)
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Rule:
    """Base class. Subclasses set `name`/`description` and implement
    run(corpus) -> List[Violation]."""

    name: str = ""
    description: str = ""

    def run(self, corpus: "Corpus") -> List[Violation]:
        raise NotImplementedError

    def violation(self, key: str, path: str, line: int,
                  message: str) -> Violation:
        return Violation(self.name, key, path, line, message)


class Corpus:
    """Cached file access rooted at the repo (or a test fixture tree)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._text: Dict[str, Optional[str]] = {}
        self._cpp: Dict[str, CppFile] = {}

    def exists(self, rel: str) -> bool:
        return os.path.isfile(os.path.join(self.root, rel))

    def text(self, rel: str) -> Optional[str]:
        if rel not in self._text:
            p = os.path.join(self.root, rel)
            try:
                with open(p, "r", encoding="utf-8", errors="replace") as f:
                    self._text[rel] = f.read()
            except OSError:
                self._text[rel] = None
        return self._text[rel]

    def cpp(self, rel: str) -> Optional[CppFile]:
        if rel not in self._cpp:
            raw = self.text(rel)
            if raw is None:
                return None
            self._cpp[rel] = CppFile.parse(rel, raw)
        return self._cpp.get(rel)

    def glob(self, pattern: str,
             exclude: Iterable[str] = ()) -> List[str]:
        """Repo-relative paths under root matching a '**'-style glob."""
        out = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames
                           if d not in (".git", "__pycache__",
                                        ".pytest_cache")]
            for fn in filenames:
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                rel = rel.replace(os.sep, "/")
                if fnmatch.fnmatch(rel, pattern) and not any(
                        fnmatch.fnmatch(rel, e) for e in exclude):
                    out.append(rel)
        return sorted(out)

    def cpp_sources(self) -> List[str]:
        """Production C++ TUs: csrc/tpucoll, excluding the test/bench
        mains (csrc/tests, csrc/benchmark) — those live by different
        rules (bare assert is fine in a test main). Deduplicated:
        fnmatch's '*' crosses '/', so the nested and top-level patterns
        overlap."""
        return sorted(set(self.glob("csrc/tpucoll/**/*.cc")
                          + self.glob("csrc/tpucoll/*.cc")
                          + self.glob("csrc/tpucoll/**/*.h")
                          + self.glob("csrc/tpucoll/*.h")))


# -- baselines ----------------------------------------------------------


@dataclass
class Baseline:
    entries: Dict[str, str] = field(default_factory=dict)  # key -> why

    @classmethod
    def load(cls, path: str) -> "Baseline":
        b = cls()
        if not os.path.isfile(path):
            return b
        with open(path, "r", encoding="utf-8") as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if " -- " not in line:
                    raise ValueError(
                        f"{path}:{ln}: baseline entries are "
                        f"'<key> -- <justification>', got: {line}")
                key, why = line.split(" -- ", 1)
                key, why = key.strip(), why.strip()
                if not why:
                    raise ValueError(
                        f"{path}:{ln}: suppression of {key!r} needs a "
                        f"one-line justification after ' -- '")
                b.entries[key] = why
        return b


# -- runner -------------------------------------------------------------


@dataclass
class RuleResult:
    rule: str
    description: str
    violations: List[Violation]
    suppressed: List[Tuple[Violation, str]]   # (violation, justification)
    stale: List[str]                          # baseline keys with no hit
    duration_s: float


@dataclass
class Report:
    root: str
    results: List[RuleResult]

    @property
    def ok(self) -> bool:
        return not any(r.violations or r.stale for r in self.results)

    def render(self, verbose: bool = False) -> str:
        lines = []
        for r in self.results:
            status = "ok" if not (r.violations or r.stale) else "FAIL"
            lines.append(
                f"[{status}] {r.rule}: {len(r.violations)} violation(s), "
                f"{len(r.suppressed)} suppressed, {len(r.stale)} stale "
                f"baseline entr{'y' if len(r.stale) == 1 else 'ies'} "
                f"({r.duration_s * 1000:.0f} ms)")
            for v in r.violations:
                lines.append("  " + v.render())
            for key in r.stale:
                lines.append(
                    f"  baseline entry {key!r} matches no live violation "
                    f"— the fix landed, now delete the entry "
                    f"(tools/check/baselines/{r.rule}.txt)")
            if verbose:
                for v, why in r.suppressed:
                    lines.append(f"  suppressed: {v.render()} [{why}]")
        total = sum(len(r.violations) for r in self.results)
        stale = sum(len(r.stale) for r in self.results)
        lines.append(
            f"tpucoll-check: {len(self.results)} rule(s), {total} "
            f"violation(s), {stale} stale baseline entr"
            f"{'y' if stale == 1 else 'ies'}"
            + (" — clean" if self.ok else ""))
        return "\n".join(lines)

    def to_json(self) -> str:
        doc = {
            "tool": "tpucoll-check",
            "root": self.root,
            "ok": self.ok,
            "rules": [
                {
                    "rule": r.rule,
                    "description": r.description,
                    "ok": not (r.violations or r.stale),
                    "duration_s": round(r.duration_s, 4),
                    "violations": [asdict(v) for v in r.violations],
                    "suppressed": [
                        dict(asdict(v), justification=why)
                        for v, why in r.suppressed
                    ],
                    "stale_baseline_entries": list(r.stale),
                }
                for r in self.results
            ],
        }
        return json.dumps(doc, indent=2, sort_keys=True)


def run_rules(root: str, rules: Iterable[Rule],
              baseline_dir: Optional[str] = None) -> Report:
    corpus = Corpus(root)
    results: List[RuleResult] = []
    for rule in rules:
        t0 = time.monotonic()
        found = rule.run(corpus)
        baseline = Baseline()
        if baseline_dir:
            baseline = Baseline.load(
                os.path.join(baseline_dir, rule.name + ".txt"))
        live_keys = {v.key for v in found}
        dupes = len(found) - len(live_keys)
        if dupes:
            raise AssertionError(
                f"rule {rule.name} produced {dupes} duplicate violation "
                f"key(s); keys must be unique to be baselineable")
        violations = [v for v in found if v.key not in baseline.entries]
        suppressed = [(v, baseline.entries[v.key]) for v in found
                      if v.key in baseline.entries]
        stale = [k for k in baseline.entries if k not in live_keys]
        results.append(RuleResult(
            rule=rule.name,
            description=rule.description,
            violations=sorted(violations, key=lambda v: (v.path, v.line,
                                                         v.key)),
            suppressed=suppressed,
            stale=sorted(stale),
            duration_s=time.monotonic() - t0,
        ))
    return Report(root=corpus.root, results=results)
