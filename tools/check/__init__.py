"""tpucoll-check: project-native static analysis for the tpucoll core.

Entry point: `python -m tools.check` (or `make check`). See
docs/check.md for the rule catalog and baseline format."""

from .engine import Baseline, Corpus, Report, Rule, Violation, run_rules

__all__ = ["Baseline", "Corpus", "Report", "Rule", "Violation",
           "run_rules"]
