"""Lightweight C++ scanner for tpucoll-check.

No clang on this image (g++ 10 only), so rules work from a
tokenizer-level view of each translation unit rather than an AST:

- comments and string/char literals are blanked (position-preserving)
  into `code`, so structural regexes never match inside either;
- string literal values are kept with their line numbers in `strings`
  (env-var names, JSON keys, and Prometheus families all live in
  literals);
- preprocessor conditionals are tracked far enough to drop `#if 0`
  blocks and to know each line's conditional depth;
- function definitions are extracted by signature regex + brace
  matching, with `Class::method` qualification preserved, so rules can
  ask "does the body of tc_allreduce contain wrap(" or "which mutexes
  does Pair::write acquire, in order".

This is deliberately not a parser: it only needs to be exact about the
constructs the rules in tools/check/rules/ key on, and those were
chosen to be recognizable at this level.
"""

from __future__ import annotations

import bisect
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class FunctionDef:
    name: str           # qualified: "Pair::write", "tc_allreduce"
    line: int           # 1-based line of the signature
    params: str         # raw parameter list text
    body: str           # body text with comments/strings blanked
    body_line: int      # 1-based line where the body's '{' sits
    ret: str            # raw return-type text (may be empty for ctors)


@dataclass
class CppFile:
    path: str
    raw: str
    code: str = ""                  # comments + literals blanked
    code_keep_strings: str = ""     # comments blanked, literals kept
    strings: List[Tuple[int, str]] = field(default_factory=list)
    line_starts: List[int] = field(default_factory=list)
    if0_lines: frozenset = frozenset()
    _functions: Optional[List[FunctionDef]] = None

    @classmethod
    def parse(cls, path: str, raw: str) -> "CppFile":
        f = cls(path=path, raw=raw)
        f._blank()
        f._preprocess()
        return f

    # -- construction ---------------------------------------------------

    def _blank(self) -> None:
        """Single pass over the source replacing comment bodies and
        literal bodies with spaces (newlines kept, so offsets and line
        numbers stay valid in both derived views)."""
        raw = self.raw
        n = len(raw)
        code = list(raw)
        keep = list(raw)
        strings: List[Tuple[int, str]] = []
        self.line_starts = [0] + [m.end() for m in re.finditer("\n", raw)]
        i = 0
        while i < n:
            c = raw[i]
            nxt = raw[i + 1] if i + 1 < n else ""
            if c == "/" and nxt == "/":
                j = raw.find("\n", i)
                j = n if j < 0 else j
                for k in range(i, j):
                    code[k] = keep[k] = " "
                i = j
            elif c == "/" and nxt == "*":
                j = raw.find("*/", i + 2)
                j = n - 2 if j < 0 else j
                for k in range(i, j + 2):
                    if code[k] != "\n":
                        code[k] = keep[k] = " "
                i = j + 2
            elif c == '"' or c == "'":
                quote = c
                j = i + 1
                while j < n and raw[j] != quote:
                    j += 2 if raw[j] == "\\" else 1
                if quote == '"':
                    strings.append((self.line_of(i), raw[i + 1:j]))
                for k in range(i + 1, min(j, n)):
                    if code[k] != "\n":
                        code[k] = " "
                i = j + 1
            else:
                i += 1
        self.code = "".join(code)
        self.code_keep_strings = "".join(keep)
        self.strings = strings

    def _preprocess(self) -> None:
        """Track #if nesting; record lines inside an `#if 0` block so
        rules skip intentionally dead code."""
        dead: set = set()
        stack: List[bool] = []   # per level: is this an "#if 0" level
        for ln, line in enumerate(self.code.splitlines(), 1):
            stripped = line.lstrip()
            if stripped.startswith("#"):
                directive = stripped[1:].lstrip()
                if directive.startswith(("if ", "ifdef", "ifndef", "if(")):
                    stack.append(bool(re.match(r"if\s*\(?\s*0\s*\)?\s*$",
                                               directive)))
                elif directive.startswith(("else", "elif")) and stack:
                    stack[-1] = False
                elif directive.startswith("endif") and stack:
                    stack.pop()
            if any(stack):
                dead.add(ln)
        self.if0_lines = frozenset(dead)

    # -- queries --------------------------------------------------------

    def line_of(self, offset: int) -> int:
        return bisect.bisect_right(self.line_starts, offset)

    _SIG = re.compile(
        r"(?:^|\n)"
        r"(?P<ret>[ \t]*(?:[\w:~&<>,\*\s]|\[\[\w+\]\])*?)"
        r"\b(?P<name>~?\w[\w]*(?:::~?\w+)?)\s*"
        r"\((?P<params>[^;{}()]*(?:\([^()]*\)[^;{}()]*)*)\)\s*"
        r"(?:const\s*)?(?:noexcept\s*)?(?:override\s*)?(?:->\s*[\w:<>]+\s*)?"
        r"(?::\s*[^;{}]*)?"        # constructor initializer list
        r"\{")

    _NOT_FUNCS = frozenset({
        "if", "for", "while", "switch", "catch", "return", "do", "else",
        "sizeof", "alignas", "alignof", "new", "delete", "defined",
        "static_assert", "decltype", "namespace",
    })

    def functions(self) -> List[FunctionDef]:
        """Function definitions via signature regex + brace matching.
        Good enough for the rule set: misses lambdas-as-values and
        heavily-macro'd definitions, neither of which the checked
        invariants live in."""
        if self._functions is not None:
            return self._functions
        out: List[FunctionDef] = []
        for m in self._SIG.finditer(self.code):
            name = m.group("name")
            base = name.split("::")[-1]
            if base in self._NOT_FUNCS or name in self._NOT_FUNCS:
                continue
            ret = m.group("ret").strip()
            # Control-flow keywords ending the "return type" mean this
            # brace belongs to a statement, not a function definition.
            if re.search(r"\b(?:return|else|do|=|\bthrow)\s*$", ret):
                continue
            open_brace = m.end() - 1
            body_end = self._match_brace(open_brace)
            if body_end < 0:
                continue
            out.append(FunctionDef(
                name=name,
                line=self.line_of(m.start("name")),
                params=m.group("params"),
                body=self.code[open_brace + 1:body_end],
                body_line=self.line_of(open_brace),
                ret=ret,
            ))
        self._functions = out
        return out

    def function(self, name: str) -> Optional[FunctionDef]:
        for f in self.functions():
            if f.name == name or f.name.split("::")[-1] == name:
                return f
        return None

    def _match_brace(self, open_off: int) -> int:
        depth = 0
        for i in range(open_off, len(self.code)):
            c = self.code[i]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    return i
        return -1

    def call_argument_span(self, open_paren_off: int) -> str:
        """Text of a call's argument list given the offset of its '(' in
        `code` — spans newlines, so multi-line calls are seen whole."""
        depth = 0
        for i in range(open_paren_off, len(self.code)):
            c = self.code[i]
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    return self.code[open_paren_off + 1:i]
        return self.code[open_paren_off + 1:]

    def string_args(self, callee: str) -> List[Tuple[int, str]]:
        """(line, first-string-literal-argument) for each call of
        `callee` — e.g. every envBytes("TPUCOLL_X", ...) site."""
        out = []
        pat = re.compile(r"\b" + re.escape(callee) + r'\s*\(\s*"([^"]*)"')
        for m in pat.finditer(self.code_keep_strings):
            out.append((self.line_of(m.start()), m.group(1)))
        return out
