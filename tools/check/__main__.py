"""CLI for tpucoll-check.

    python -m tools.check                 # full suite, human output
    python -m tools.check --json out.json # plus machine-readable report
    python -m tools.check --rules abi-drift,env-hygiene
    python -m tools.check --list

Exit code 0 iff every rule is clean: no unsuppressed violations AND no
stale baseline entries (a fixed violation must leave the baseline)."""

from __future__ import annotations

import argparse
import os
import sys

from .engine import run_rules
from .rules import ALL_RULES, make_rules

_DEFAULT_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.check",
        description="tpucoll static-analysis suite (docs/check.md)")
    ap.add_argument("--root", default=_DEFAULT_ROOT,
                    help="repo root to scan (default: this checkout)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule names (default: all)")
    ap.add_argument("--json", metavar="PATH", default="",
                    help="write a machine-readable JSON report "
                         "('-' for stdout)")
    ap.add_argument("--no-baselines", action="store_true",
                    help="ignore baseline files (report everything)")
    ap.add_argument("--verbose", action="store_true",
                    help="also print suppressed violations")
    ap.add_argument("--list", action="store_true",
                    help="list rules and exit")
    args = ap.parse_args(argv)

    if args.list:
        for cls in ALL_RULES:
            print(f"{cls.name:20s} {cls.description}")
        return 0

    rules = make_rules([r.strip() for r in args.rules.split(",")
                        if r.strip()] or None)
    baseline_dir = None if args.no_baselines else os.path.join(
        args.root, "tools", "check", "baselines")
    report = run_rules(args.root, rules, baseline_dir=baseline_dir)

    if args.json == "-":
        print(report.to_json())
    else:
        print(report.render(verbose=args.verbose))
        if args.json:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(report.to_json() + "\n")
            print(f"json report: {args.json}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
