"""Metrics name drift across the three layers that each spell the
names by hand:

- the C++ registry emits snapshot JSON keys (metrics.cc, engine.cc);
- gloo_tpu/utils/metrics.py reads those keys and renders Prometheus
  families (gloo_tpu_*);
- docs/observability.md documents the families operators alert on.

A rename in any one layer silently zeroes dashboards (dict.get defaults
swallow the mismatch), so: every key the Python layer reads must be
emitted somewhere (C++ JSON or a Python-side dict literal), every
Prometheus family emitted must be documented, and every family the docs
mention must still exist."""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from ..engine import Corpus, Rule, Violation

_FAMILY = re.compile(r"\bgloo_tpu_\w+")
_TYPE_LINE = re.compile(r"#\s*TYPE\s+(gloo_tpu_\w+)\s+\w+")
# JSON keys in C++ string literals: the emitters write  "...\"key\":..."
_CPP_JSON_KEY = re.compile(r'\\"(\w+)\\":')
# Python-side snapshot reads: x.get("key"...) / x["key"]
_PY_READ = re.compile(r"""(?:\.get\(\s*|\[)\s*['"](\w+)['"]""")
_PY_DICT_KEY = re.compile(r"""['"](\w+)['"]\s*:""")
# Python-side attachment: snap["async"] = ... is an emission too.
_PY_ASSIGN_KEY = re.compile(r"""\[\s*['"](\w+)['"]\s*\]\s*=[^=]""")
# Histogram families expand to _bucket/_sum/_count series.
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


class MetricsDriftRule(Rule):
    name = "metrics-drift"
    description = ("snapshot keys, Prometheus families, and "
                   "docs/observability.md agree on every metric name")

    cpp_emitters = ("csrc/tpucoll/**/*.cc", "csrc/tpucoll/*.cc")
    exposition = "gloo_tpu/utils/metrics.py"
    py_emitters = ("gloo_tpu/**/*.py", "gloo_tpu/*.py")
    doc_roots = ("docs/*.md", "README.md")

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        expo = corpus.text(self.exposition)
        if expo is None:
            return [self.violation("no-exposition", self.exposition, 1,
                                   f"{self.exposition} not found")]

        # -- emitted snapshot keys (C++ JSON writers + Python dicts) ---
        emitted: Set[str] = set()
        paths: List[str] = []
        for pat in self.cpp_emitters:
            paths.extend(corpus.glob(pat))
        for path in sorted(set(paths)):
            raw = corpus.text(path)
            if raw:
                emitted.update(_CPP_JSON_KEY.findall(raw))
        py_paths: List[str] = []
        for pat in self.py_emitters:
            py_paths.extend(corpus.glob(pat))
        for path in sorted(set(py_paths)):
            raw = corpus.text(path)
            if raw:
                emitted.update(_PY_DICT_KEY.findall(raw))
                emitted.update(_PY_ASSIGN_KEY.findall(raw))

        # -- every key the exposition reads must be emitted ------------
        for m in _PY_READ.finditer(expo):
            key = m.group(1)
            if key in emitted:
                continue
            line = expo.count("\n", 0, m.start()) + 1
            v = self.violation(
                f"unread-key:{key}", self.exposition, line,
                f"{self.exposition} reads snapshot key {key!r} that no "
                f"C++ JSON emitter or Python dict literal produces — "
                f"renamed on one side only?")
            if v.key not in {x.key for x in out}:
                out.append(v)

        # -- Prometheus families <-> docs ------------------------------
        families = set(_TYPE_LINE.findall(expo))
        emitted_names = set(_FAMILY.findall(expo))
        doc_names: Dict[str, Tuple[str, int]] = {}
        doc_paths: List[str] = []
        for pat in self.doc_roots:
            doc_paths.extend(corpus.glob(pat))
        for path in sorted(set(doc_paths)):
            text = corpus.text(path)
            if text is None:
                continue
            for m in _FAMILY.finditer(text):
                doc_names.setdefault(m.group(0),
                                     (path, text.count("\n", 0,
                                                       m.start()) + 1))
        for fam in sorted(families):
            if fam in doc_names or any(
                    fam + s in doc_names for s in _HIST_SUFFIXES):
                continue
            out.append(self.violation(
                f"undocumented-family:{fam}", self.exposition,
                expo[:expo.index(fam)].count("\n") + 1,
                f"Prometheus family {fam} is emitted but not mentioned "
                f"in docs — add it to the metrics reference in "
                f"docs/observability.md"))
        for name, (path, line) in sorted(doc_names.items()):
            base = name
            for s in _HIST_SUFFIXES:
                if name.endswith(s) and name[:-len(s)] in families:
                    base = name[:-len(s)]
            if base in emitted_names:
                continue
            out.append(self.violation(
                f"docs-only-family:{name}", path, line,
                f"docs mention Prometheus family {name} but the "
                f"exposition ({self.exposition}) never emits it — "
                f"stale doc or renamed metric"))
        return out
