"""Schedule IR step-op coverage across the hand-written switches.

The schedule plane's StepOp enum (csrc/tpucoll/schedule/ir.h) is
consumed by three hand-spelled surfaces: the verifier's semantic
switches (verifier.cc), the interpreter's lowering switch
(interpreter.cc), and the JSON name table (ir.cc). Adding an op to the
enum without teaching every consumer compiles fine — switches carry a
default/throw arm precisely so malformed programs fail loudly — but the
new op then verifies or lowers as "bad step" at runtime instead of at
review time. This rule fails the build the moment an enumerator is
missing a `case StepOp::kX` in either switch file or a name mapping in
ir.cc, and flags cases for enumerators that no longer exist.

The same review-time gap exists for per-step ATTRIBUTES: a field added
to `struct Step` (ir.h) that toJson/fromJson never round-trip silently
drops to its default through the TPUCOLL_SCHEDULE_FILE interchange —
the schedule runs, just not the schedule that was written (the
pipeline-depth attribute is exactly this shape). So the rule also
requires every Step data member to appear as a quoted JSON key in
ir.cc at least twice: once emitted (toJson) and once parsed
(fromJson)."""

from __future__ import annotations

import re
from typing import List, Set

from ..engine import Corpus, Rule, Violation

_ENUM = re.compile(r"enum\s+class\s+StepOp[^{]*\{([^}]*)\}", re.S)
_ENUMERATOR = re.compile(r"\bk[A-Z]\w*")
_CASE = re.compile(r"\bcase\s+StepOp::(k\w+)")
# ir.cc's name table pairs each enumerator with its wire spelling.
_NAME_MAP = re.compile(r"StepOp::(k\w+)")
_STEP_STRUCT = re.compile(r"struct\s+Step\s*\{(.*?)\n\};", re.S)
# A data member: `Type name{...};` or `Type name = ...;` — constants
# (static constexpr) and comments are not serialized state.
_MEMBER = re.compile(
    r"^\s*(?!static\b)[A-Za-z_][\w:]*(?:<[^>]*>)?\s+"
    r"(\w+)\s*(?:\{[^;]*\}|=[^;]*)?;", re.M)


class ScheduleStepCoverageRule(Rule):
    name = "schedule-step-coverage"
    description = ("every StepOp enumerator is handled in the verifier "
                   "and interpreter switches and named in ir.cc")

    ir_header = "csrc/tpucoll/schedule/ir.h"
    consumers = ("csrc/tpucoll/schedule/verifier.cc",
                 "csrc/tpucoll/schedule/interpreter.cc")
    name_table = "csrc/tpucoll/schedule/ir.cc"

    def _enumerators(self, corpus: Corpus) -> Set[str]:
        raw = corpus.text(self.ir_header)
        if raw is None:
            return set()
        m = _ENUM.search(raw)
        if m is None:
            return set()
        return set(_ENUMERATOR.findall(m.group(1)))

    def _step_members(self, corpus: Corpus) -> Set[str]:
        raw = corpus.text(self.ir_header)
        if raw is None:
            return set()
        m = _STEP_STRUCT.search(raw)
        if m is None:
            return set()
        return set(_MEMBER.findall(m.group(1)))

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        ops = self._enumerators(corpus)
        if not ops:
            return [self.violation(
                "no-enum", self.ir_header, 1,
                f"could not parse `enum class StepOp` from "
                f"{self.ir_header} — moved without updating this rule?")]

        for path in self.consumers + (self.name_table,):
            raw = corpus.text(path)
            if raw is None:
                out.append(self.violation(
                    f"missing-file:{path}", path, 1,
                    f"{path} not found but the schedule IR exists"))
                continue
            pattern = _CASE if path in self.consumers else _NAME_MAP
            handled = set(pattern.findall(raw))
            for op in sorted(ops - handled):
                out.append(self.violation(
                    f"unhandled:{path}:{op}", self.ir_header, 1,
                    f"StepOp::{op} is declared in {self.ir_header} but "
                    f"{path} never handles it — new step ops must be "
                    f"taught to the verifier, the interpreter, and the "
                    f"JSON name table together"))
            for op in sorted(handled - ops):
                line = raw[:raw.index(op)].count("\n") + 1
                out.append(self.violation(
                    f"stale:{path}:{op}", path, line,
                    f"{path} handles StepOp::{op} which {self.ir_header} "
                    f"no longer declares — dead case from a removed op"))

        # ---- step-attribute JSON round-trip ----
        raw = corpus.text(self.name_table)
        if raw is not None:
            for member in sorted(self._step_members(corpus)):
                # Emitted keys live inside C++ string literals
                # (\"pipeline\"), parsed keys are plain ("pipeline");
                # a round-tripped attribute shows up at least twice.
                hits = len(re.findall(
                    r'\\?"' + re.escape(member) + r'\\?"', raw))
                if hits < 2:
                    out.append(self.violation(
                        f"unserialized:{member}", self.ir_header, 1,
                        f"Step::{member} is declared in {self.ir_header} "
                        f"but {self.name_table} round-trips it "
                        f"{hits} time(s) — a per-step attribute must be "
                        f"emitted by toJson AND parsed by fromJson or "
                        f"it silently drops to its default through the "
                        f"schedule file"))
        return out
