"""Lock discipline: extract every lexically-nested mutex acquisition
(lock B taken while lock A's guard is still in scope, within one
function body), build the static lock graph across the whole core, and
fail on (a) cycles — a static AB/BA deadlock candidate — and (b) any
nesting edge not listed in tools/check/config/lock_order.txt. The
config file IS the documented lock hierarchy: adding a new nesting
means writing down why it is safe, in order, next to the others.

Mutex identity is `Class::member` (from the qualified function name)
or `<file-stem>::name` for file-scope/global mutexes, so `mu_` in Pair
and `mu_` in Loop stay distinct."""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from ..engine import Corpus, Rule, Violation

CONFIG = "tools/check/config/lock_order.txt"

_GUARD = re.compile(
    r"std\s*::\s*(?:lock_guard|unique_lock|scoped_lock)\s*"
    r"(?:<[^;>]*>)?\s*\w+\s*[({]\s*([^,;({]+?)\s*[,)}]")
_MANUAL = re.compile(r"([\w.\->]+?)\s*\.\s*lock\s*\(\s*\)")


def _edge_list(text: str) -> Dict[Tuple[str, str], int]:
    """Parse the allowed-nesting config: one `A -> B` per line, comments
    with #."""
    out: Dict[Tuple[str, str], int] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        if "->" not in line:
            raise ValueError(f"lock_order.txt:{ln}: expected 'A -> B', "
                             f"got: {line}")
        a, b = (p.strip() for p in line.split("->", 1))
        out[(a, b)] = ln
    return out


class LockOrderRule(Rule):
    name = "lock-order"
    description = ("the static mutex-nesting graph is acyclic and every "
                   "nesting edge is documented in "
                   "tools/check/config/lock_order.txt")

    roots = ("csrc/tpucoll/**/*.cc", "csrc/tpucoll/**/*.h",
             "csrc/tpucoll/*.cc", "csrc/tpucoll/*.h")
    config_path = CONFIG

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        allowed: Dict[Tuple[str, str], int] = {}
        cfg = corpus.text(self.config_path)
        if cfg is not None:
            allowed = _edge_list(cfg)

        # edge -> (path, line, holder-fn) of first observation
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        paths: List[str] = []
        for pat in self.roots:
            paths.extend(corpus.glob(pat))
        for path in sorted(set(paths)):
            cpp = corpus.cpp(path)
            if cpp is None:
                continue
            stem = os.path.splitext(os.path.basename(path))[0]
            for fn in cpp.functions():
                scope = (fn.name.rsplit("::", 1)[0]
                         if "::" in fn.name else stem)
                acquisitions: List[Tuple[int, int, str]] = []
                for m in _GUARD.finditer(fn.body):
                    mu = self._canon(scope, m.group(1))
                    if mu is None:
                        continue
                    depth = fn.body.count("{", 0, m.start()) \
                        - fn.body.count("}", 0, m.start())
                    line = fn.body_line + fn.body.count("\n", 0,
                                                        m.start())
                    acquisitions.append((m.start(), depth, mu, line))
                for m in _MANUAL.finditer(fn.body):
                    mu = self._canon(scope, m.group(1))
                    if mu is None:
                        continue
                    depth = fn.body.count("{", 0, m.start()) \
                        - fn.body.count("}", 0, m.start())
                    line = fn.body_line + fn.body.count("\n", 0,
                                                        m.start())
                    acquisitions.append((m.start(), depth, mu, line))
                acquisitions.sort()
                held: List[Tuple[int, int, str]] = []  # (off,depth,mu)
                for off, depth, mu, line in acquisitions:
                    # pop guards whose brace scope closed before here
                    held = [
                        (o, d, h) for (o, d, h) in held
                        if not self._scope_closed(fn.body, o, d, off)
                    ]
                    for _, _, h in held:
                        if h != mu:
                            edges.setdefault((h, mu),
                                             (path, line, fn.name))
                    held.append((off, depth, mu))
        # -- cycle check (DFS) -----------------------------------------
        graph: Dict[str, List[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, []).append(b)
        seen_cycles: Set[Tuple[str, ...]] = set()
        for start in sorted(graph):
            stack: List[Tuple[str, List[str]]] = [(start, [start])]
            while stack:
                node, trail = stack.pop()
                for nxt in graph.get(node, []):
                    if nxt == start:
                        cyc = tuple(sorted(trail))
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        path, line, fnname = edges[(node, start)]
                        out.append(self.violation(
                            "cycle:" + "->".join(trail + [start]),
                            path, line,
                            f"lock-order cycle "
                            f"{' -> '.join(trail + [start])} (closing "
                            f"edge taken in {fnname}) — static "
                            f"deadlock candidate"))
                    elif nxt not in trail and len(trail) < 8:
                        stack.append((nxt, trail + [nxt]))
        # -- documentation check ---------------------------------------
        for (a, b), (path, line, fnname) in sorted(edges.items()):
            if (a, b) not in allowed:
                out.append(self.violation(
                    f"undocumented:{a}->{b}", path, line,
                    f"{fnname} acquires {b} while holding {a}; this "
                    f"nesting is not documented in {self.config_path} "
                    f"— add it (with why it is safe) or restructure"))
        for (a, b), ln in sorted(allowed.items()):
            if (a, b) not in edges:
                out.append(self.violation(
                    f"stale-edge:{a}->{b}", self.config_path, ln,
                    f"documented nesting {a} -> {b} no longer occurs "
                    f"in the code — delete the entry"))
        return out

    @staticmethod
    def _canon(scope: str, expr: str) -> str:
        """Normalize a mutex expression to a stable identity, or None
        for things that are clearly not mutexes (adopt_lock etc.)."""
        e = expr.strip().replace("this->", "")
        # Accept plain member/global expressions and no-arg accessor
        # calls (logMutex()); reject anything with spaces or arguments.
        if not e or not re.fullmatch(r"[\w.>\-\[\]]+(?:\(\))?", e):
            return None
        # Heuristic: project mutexes are named ...mu / ...Mu_ / ...mutex.
        if not re.search(r"(?i)mu(?:tex)?_?(?:\(\))?$", e):
            return None
        if e.startswith("g_"):
            return "::" + e
        return f"{scope}::{e}"

    @staticmethod
    def _scope_closed(body: str, acq_off: int, acq_depth: int,
                      now_off: int) -> bool:
        """Did the brace scope the guard was constructed in close
        between its acquisition and `now_off`?"""
        depth = acq_depth
        for i in range(acq_off, now_off):
            c = body[i]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth < acq_depth:
                    return True
        return False
