"""Env hygiene, two invariants:

1. No raw getenv outside common/env.h. Every TPUCOLL_* knob must go
   through the strict parsers (envBytes/envCount/envFlag/envChoice/
   envString) so malformed values throw loudly instead of atoll-ing
   "8MB" into 8 — the exact misconfiguration class PR 6 made the
   transport knobs immune to.

2. Code <-> docs agreement on the TPUCOLL_* surface: every variable the
   code reads is documented somewhere under docs/ (the matrix lives in
   docs/env.md), and every variable the docs name is actually read by
   code — a doc describing a deleted knob is worse than no doc.
"""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from ..engine import Corpus, Rule, Violation

ENV_HEADER = "csrc/tpucoll/common/env.h"

# The strict accessors defined by common/env.h; reads through these are
# the sanctioned way to consult the environment from C++.
ACCESSORS = ("envBytes", "envCount", "envFlag", "envChoice", "envString")

_PY_READ = re.compile(
    r"""(?:os\.environ(?:\.get)?|os\.getenv|environ(?:\.get)?
        |\benv(?:\.get)?)\s*
        [\(\[]\s*f?['"](TPUCOLL_\w+)""", re.X)
_DOC_VAR = re.compile(r"\b(TPUCOLL_\w+)\b")


class EnvHygieneRule(Rule):
    name = "env-hygiene"
    description = ("no raw getenv outside common/env.h; the TPUCOLL_* "
                   "surface read by code and the one described in docs/ "
                   "are the same set")

    env_header = ENV_HEADER
    cpp_roots = ("csrc/tpucoll/**/*.cc", "csrc/tpucoll/**/*.h",
                 "csrc/tpucoll/*.cc", "csrc/tpucoll/*.h")
    py_roots = ("gloo_tpu/**/*.py", "gloo_tpu/*.py", "bench.py",
                "tools/*.py")
    doc_roots = ("docs/*.md", "README.md")

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        code_vars: Dict[str, Tuple[str, int]] = {}   # var -> first site

        cpp_paths: List[str] = []
        for pat in self.cpp_roots:
            cpp_paths.extend(corpus.glob(pat))
        for path in sorted(set(cpp_paths)):
            cpp = corpus.cpp(path)
            if cpp is None:
                continue
            # (1) raw getenv bans. ::getenv, std::getenv, secure_getenv
            # all count; common/env.h is the single sanctioned caller.
            if path != self.env_header:
                for m in re.finditer(r"\b(?:secure_)?getenv\s*\(",
                                     cpp.code):
                    line = cpp.line_of(m.start())
                    if line in cpp.if0_lines:
                        continue
                    fn = self._enclosing(cpp, line)
                    out.append(self.violation(
                        f"raw-getenv:{path}:{fn}", path, line,
                        f"raw getenv in {fn} — route the read through "
                        f"the strict parsers in {self.env_header} "
                        f"(envBytes/envCount/envFlag/envChoice/"
                        f"envString)"))
            # (2a) vars read through the sanctioned accessors.
            for acc in ACCESSORS:
                for line, var in cpp.string_args(acc):
                    if var.startswith("TPUCOLL_"):
                        code_vars.setdefault(var, (path, line))
            # Raw getenv reads still contribute to the doc cross-check
            # (the var is real even while the accessor is wrong).
            for m in re.finditer(
                    r'getenv\s*\(\s*"(TPUCOLL_\w+)"',
                    cpp.code_keep_strings):
                code_vars.setdefault(m.group(1),
                                     (path, cpp.line_of(m.start())))

        py_paths: List[str] = []
        for pat in self.py_roots:
            py_paths.extend(corpus.glob(pat))
        for path in sorted(set(py_paths)):
            text = corpus.text(path)
            if text is None:
                continue
            for m in _PY_READ.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                code_vars.setdefault(m.group(1), (path, line))

        doc_vars: Dict[str, Tuple[str, int]] = {}
        doc_paths: List[str] = []
        for pat in self.doc_roots:
            doc_paths.extend(corpus.glob(pat))
        for path in sorted(set(doc_paths)):
            text = corpus.text(path)
            if text is None:
                continue
            for m in _DOC_VAR.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                doc_vars.setdefault(m.group(1), (path, line))

        for var in sorted(set(code_vars) - set(doc_vars)):
            path, line = code_vars[var]
            out.append(self.violation(
                f"undocumented:{var}", path, line,
                f"{var} is read by code but appears nowhere under "
                f"docs/ — add it to the env matrix (docs/env.md)"))
        for var in sorted(set(doc_vars) - set(code_vars)):
            path, line = doc_vars[var]
            out.append(self.violation(
                f"docs-only:{var}", path, line,
                f"{var} is documented but never read by csrc/ or "
                f"gloo_tpu/ — stale doc, or the knob lost its reader"))
        return out

    @staticmethod
    def _enclosing(cpp, line: int) -> str:
        best = "<file scope>"
        for fn in cpp.functions():
            if fn.line <= line and cpp.line_of(
                    len(cpp.code)) >= line:
                # closest preceding definition whose body spans the line
                body_start = fn.body_line
                body_end = body_start + fn.body.count("\n")
                if body_start <= line <= body_end + 1:
                    best = fn.name
        return best
