"""Flight-recorder coverage: the always-on post-mortem story (PR 4)
only works if EVERY public collective entry stamps a FlightRecOp and
every capi p2p post registers its ring seq (frPush) — one unstamped
entry and the cross-rank desync comparison silently skips that op,
turning a schedule mismatch into an unexplained hang.

Entry points are not hardcoded: the rule reads the declarations out of
collectives/collectives.h, so a new collective is covered the moment it
is declared."""

from __future__ import annotations

import re
from typing import Dict, List

from ..engine import Corpus, Rule, Violation

COLLECTIVES_H = "csrc/tpucoll/collectives/collectives.h"
CAPI = "csrc/tpucoll/capi.cc"

# capi entries that post user-facing p2p ops; each must push its flight-
# recorder seq so the matching wait completes the right ring entry.
P2P_POSTS = ("tc_buffer_send", "tc_buffer_recv", "tc_buffer_recv_any",
             "tc_buffer_put", "tc_buffer_get")

_DECL = re.compile(r"^\s*void\s+(\w+)\s*\(\s*\w*Options\s*&\s*\w+\s*\)\s*;",
                   re.M)


class FlightrecRule(Rule):
    name = "flightrec-coverage"
    description = ("every public collective entry stamps FlightRecOp "
                   "and every capi p2p post registers its seq (frPush)")

    collectives_h = COLLECTIVES_H
    capi_path = CAPI
    p2p_posts = P2P_POSTS

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        header = corpus.text(self.collectives_h)
        if header is None:
            return [self.violation("no-header", self.collectives_h, 1,
                                   f"{self.collectives_h} not found")]
        entries = _DECL.findall(header)
        if not entries:
            out.append(self.violation(
                "no-entries", self.collectives_h, 1,
                f"no `void name(XOptions&)` declarations found in "
                f"{self.collectives_h} — rule cannot see the public "
                f"surface"))
        # Find each entry's definition across the collectives TUs.
        impl_dir = self.collectives_h.rsplit("/", 1)[0]
        impls = corpus.glob(impl_dir + "/*.cc")
        defs: Dict[str, tuple] = {}
        for path in impls:
            cpp = corpus.cpp(path)
            if cpp is None:
                continue
            for fn in cpp.functions():
                base = fn.name.split("::")[-1]
                if base in entries and "Options" in fn.params:
                    defs.setdefault(base, (path, fn))
        for entry in entries:
            if entry not in defs:
                out.append(self.violation(
                    f"no-definition:{entry}", self.collectives_h, 1,
                    f"{entry} is declared in {self.collectives_h} but "
                    f"no definition was found under {impl_dir}/"))
                continue
            path, fn = defs[entry]
            if "FlightRecOp" not in fn.body:
                out.append(self.violation(
                    f"unstamped:{entry}", path, fn.line,
                    f"{entry} does not stamp a FlightRecOp — its ops "
                    f"never enter the flight-recorder ring, so desync "
                    f"detection and stall post-mortems skip them"))
        capi = corpus.cpp(self.capi_path)
        if capi is not None:
            for name in self.p2p_posts:
                fn = capi.function(name)
                if fn is None:
                    continue   # abi rules own existence
                if "frPush(" not in fn.body:
                    out.append(self.violation(
                        f"unstamped-p2p:{name}", self.capi_path, fn.line,
                        f"{name} posts a p2p op without frPush — the "
                        f"wait side can never complete its flight-"
                        f"recorder entry"))
        return out
