"""C-ABI drift: every `extern "C"` symbol defined in capi.cc must have a
matching ctypes prototype in gloo_tpu/_lib.py — same set, same arity,
same types — and vice-versa. The ctypes layer is the repo's pybind
equivalent; nothing checks it at build time, so a drifted argtype
corrupts arguments silently at runtime (a size_t read as int32 truncates
byte counts on every collective)."""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..engine import Corpus, Rule, Violation

CAPI = "csrc/tpucoll/capi.cc"
LIB = "gloo_tpu/_lib.py"

# C parameter/return type -> canonical ctypes spelling. Keys are
# normalized ("const" dropped, one space before '*'s collapsed away).
_C_TO_CTYPES = {
    "void": None,
    "void*": "c_void_p",
    "void**": "POINTER(c_void_p)",
    "char*": "c_char_p",
    "uint8_t*": "POINTER(c_uint8)",
    "uint8_t**": "POINTER(POINTER(c_uint8))",
    "size_t": "c_size_t",
    "size_t*": "POINTER(c_size_t)",
    "int64_t": "c_int64",
    "int64_t*": "POINTER(c_int64)",
    "uint64_t": "c_uint64",
    "uint64_t*": "POINTER(c_uint64)",
    "uint32_t": "c_uint32",
    "uint32_t*": "POINTER(c_uint32)",
    "uint16_t": "c_uint16",
    "int": "c_int",
    "int*": "POINTER(c_int)",
}


def normalize_c_type(decl: str) -> Optional[str]:
    """'const char* key' -> canonical ctypes spelling ('c_char_p')."""
    t = decl.strip()
    # Drop the parameter name (trailing identifier) when the remainder
    # still names a type.
    m = re.match(r"^(.*[\*\s])\s*\w+$", t)
    if m and m.group(1).strip():
        t = m.group(1).strip()
    t = re.sub(r"\bconst\b", "", t)
    t = re.sub(r"\s*\*\s*", "*", t).strip()
    t = re.sub(r"\s+", " ", t)
    # Function pointers (inline `void (*fn)(...)` or `*_fn` typedefs)
    # ride as opaque pointers on the Python side.
    if "(*" in decl or t.endswith("_fn"):
        return "c_void_p"
    return _C_TO_CTYPES.get(t, f"<unmapped:{t}>")


def parse_capi(corpus: Corpus,
               path: str = CAPI) -> Dict[str, Tuple[Optional[str],
                                                    List[Optional[str]]]]:
    """tc_* symbol -> (canonical restype, [canonical argtypes]) from the
    extern "C" block of capi.cc."""
    cpp = corpus.cpp(path)
    if cpp is None:
        return {}
    out = {}
    for fn in cpp.functions():
        if not fn.name.startswith("tc_"):
            continue
        params = fn.params.strip()
        args: List[Optional[str]] = []
        if params and params != "void":
            depth = 0
            start = 0
            parts = []
            for i, ch in enumerate(params):
                if ch in "(<":
                    depth += 1
                elif ch in ")>":
                    depth -= 1
                elif ch == "," and depth == 0:
                    parts.append(params[start:i])
                    start = i + 1
            parts.append(params[start:])
            args = [normalize_c_type(p) for p in parts]
        out[fn.name] = (normalize_c_type(fn.ret), args)
    return out


def parse_lib(corpus: Corpus,
              path: str = LIB) -> Dict[str, Tuple[Optional[str],
                                                  List[Optional[str]],
                                                  int]]:
    """tc_* symbol -> (canonical restype, [canonical argtypes], line)
    from the _PROTOTYPES dict, resolved through the module's ctypes
    aliases (_c, _sz, ...) via the AST — never imported/executed."""
    src = corpus.text(path)
    if src is None:
        return {}
    tree = ast.parse(src)
    aliases: Dict[str, str] = {}

    def canon(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and node.value is None:
            return None
        if isinstance(node, ast.Name):
            return canon_str(aliases.get(node.id, node.id))
        if isinstance(node, ast.Attribute):   # ctypes.c_void_p
            return canon_str(node.attr)
        if isinstance(node, ast.Call):        # ctypes.POINTER(X)
            fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                     else getattr(node.func, "id", "?"))
            inner = canon(node.args[0]) if node.args else "?"
            return f"{fname}({inner})"
        return "<unparsed>"

    def canon_str(name: str) -> str:
        return name[len("ctypes."):] if name.startswith("ctypes.") else name

    protos: Dict[str, Tuple[Optional[str], List[Optional[str]], int]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        if isinstance(target, ast.Name) and isinstance(node.value,
                                                       (ast.Attribute,
                                                        ast.Name)):
            aliases[target.id] = ast.unparse(node.value)
        if (isinstance(target, ast.Name) and target.id == "_PROTOTYPES"
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(v, ast.Tuple)
                        and len(v.elts) == 2):
                    continue
                restype = canon(v.elts[0])
                arglist = v.elts[1]
                argtypes = ([canon(a) for a in arglist.elts]
                            if isinstance(arglist, ast.List) else [])
                protos[k.value] = (restype, argtypes, k.lineno)
    return protos


class AbiDriftRule(Rule):
    name = "abi-drift"
    description = (
        "every extern-C tc_* symbol in capi.cc is mirrored in "
        "_lib.py's ctypes prototypes with matching arity and types")

    capi_path = CAPI
    lib_path = LIB

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        capi = parse_capi(corpus, self.capi_path)
        lib = parse_lib(corpus, self.lib_path)
        if not capi:
            return [self.violation("no-capi", self.capi_path, 1,
                                   f"{self.capi_path} missing or has no "
                                   f"extern-C tc_* definitions")]
        if not lib:
            return [self.violation("no-lib", self.lib_path, 1,
                                   f"{self.lib_path} missing or has no "
                                   f"_PROTOTYPES dict")]
        cpp = corpus.cpp(self.capi_path)
        for name in sorted(set(capi) - set(lib)):
            fn = cpp.function(name)
            out.append(self.violation(
                f"missing-in-lib:{name}", self.capi_path,
                fn.line if fn else 1,
                f"{name} is exported by capi.cc but has no ctypes "
                f"prototype in {self.lib_path} (calls through it get "
                f"default int/varargs marshalling)"))
        for name in sorted(set(lib) - set(capi)):
            out.append(self.violation(
                f"missing-in-capi:{name}", self.lib_path, lib[name][2],
                f"{name} is declared in {self.lib_path} but not defined "
                f"in capi.cc (AttributeError at import, or a stale "
                f"symbol)"))
        for name in sorted(set(capi) & set(lib)):
            c_ret, c_args = capi[name]
            py_ret, py_args, line = lib[name]
            fn = cpp.function(name)
            cline = fn.line if fn else 1
            if c_ret != py_ret:
                out.append(self.violation(
                    f"restype:{name}", self.lib_path, line,
                    f"{name}: restype mismatch — capi.cc returns "
                    f"{c_ret or 'void'}, _lib.py declares "
                    f"{py_ret or 'None'}"))
            if len(c_args) != len(py_args):
                out.append(self.violation(
                    f"arity:{name}", self.lib_path, line,
                    f"{name}: arity mismatch — capi.cc takes "
                    f"{len(c_args)} argument(s), _lib.py declares "
                    f"{len(py_args)}"))
                continue
            for i, (ca, pa) in enumerate(zip(c_args, py_args)):
                if ca != pa:
                    out.append(self.violation(
                        f"argtype:{name}:{i}", self.lib_path, line,
                        f"{name}: argument {i} mismatch — capi.cc "
                        f"({self.capi_path}:{cline}) has {ca}, _lib.py "
                        f"declares {pa}"))
        return out
