"""Rule registry for tpucoll-check. docs/check.md is the catalog."""

from __future__ import annotations

from typing import List

from ..engine import Rule
from .abi_drift import AbiDriftRule
from .abi_exceptions import AbiExceptionsRule
from .asserts import AssertsRule
from .atomics import AtomicsRule
from .env_hygiene import EnvHygieneRule
from .flightrec import FlightrecRule
from .lock_order import LockOrderRule
from .metrics_drift import MetricsDriftRule
from .schedule_step_coverage import ScheduleStepCoverageRule
from .span_coverage import SpanCoverageRule

ALL_RULES = (
    AbiDriftRule,
    AbiExceptionsRule,
    EnvHygieneRule,
    AtomicsRule,
    FlightrecRule,
    SpanCoverageRule,
    MetricsDriftRule,
    LockOrderRule,
    AssertsRule,
    ScheduleStepCoverageRule,
)


def make_rules(names: List[str] = None) -> List[Rule]:
    rules = [cls() for cls in ALL_RULES]
    if names:
        by_name = {r.name: r for r in rules}
        unknown = [n for n in names if n not in by_name]
        if unknown:
            raise SystemExit(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(have: {', '.join(sorted(by_name))})")
        rules = [by_name[n] for n in names]
    return rules
