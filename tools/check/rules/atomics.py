"""Explicit atomics: every std::atomic access in csrc/tpucoll/ names a
memory_order. Default (seq-cst) ordering is almost never what a hot-path
site means — and when seq-cst IS meant, writing it out is the evidence
someone decided. Three access forms are checked:

- method calls (load/store/fetch_*/exchange/compare_exchange_*): the
  argument list, joined across lines, must contain `memory_order`;
- operator stores (`flag_ = x`, `n_++`, `n_ += k`) on members declared
  std::atomic: implicit seq-cst RMW/stores, must become explicit calls;
- bare reads (`if (fd_ < 0)`) of such members: implicit seq-cst loads.

Operator/bare detection is scoped to atomics declared with the member
(`name_`) or global (`g_name`) naming convention in the file itself or
its paired header, so a local variable shadowing a generic word never
false-positives.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set

from ..engine import Corpus, Rule, Violation

_METHODS = ("load", "store", "fetch_add", "fetch_sub", "fetch_and",
            "fetch_or", "fetch_xor", "exchange", "compare_exchange_weak",
            "compare_exchange_strong")

_METHOD_CALL = re.compile(
    r"[\w\]\)]\s*(?:\.|->)\s*(" + "|".join(_METHODS) + r")\s*(\()")

# std::atomic<...> name; / std::atomic_bool name{...}; etc. Captures
# pointer declarators so pointer-to-atomic (accessed via explicit
# load/store through the method pass) is excluded from operator checks.
_ATOMIC_DECL = re.compile(
    r"std\s*::\s*atomic(?:_bool|_int|_uint|_flag|_size_t)?"
    r"\s*(?:<[^;{}=]*?>)?\s*(?P<ptr>\**)\s*(?P<name>\w+)\s*(?:[;{=\[])")


class AtomicsRule(Rule):
    name = "explicit-atomics"
    description = ("every std::atomic load/store/RMW in csrc/tpucoll/ "
                   "names an explicit memory_order")

    roots = ("csrc/tpucoll/**/*.cc", "csrc/tpucoll/**/*.h",
             "csrc/tpucoll/*.cc", "csrc/tpucoll/*.h")

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        paths: List[str] = []
        for pat in self.roots:
            paths.extend(corpus.glob(pat))
        counters: Dict[str, int] = {}

        def emit(kind: str, path: str, line: int, who: str,
                 message: str) -> None:
            base = f"{kind}:{path}:{who}"
            counters[base] = counters.get(base, 0) + 1
            key = base if counters[base] == 1 else \
                f"{base}#{counters[base]}"
            out.append(self.violation(key, path, line, message))

        for path in sorted(set(paths)):
            cpp = corpus.cpp(path)
            if cpp is None:
                continue
            # -- pass 1: explicit method calls without an order --------
            for m in _METHOD_CALL.finditer(cpp.code):
                line = cpp.line_of(m.start())
                if line in cpp.if0_lines:
                    continue
                args = cpp.call_argument_span(m.start(2))
                method = m.group(1)
                if "memory_order" in args:
                    continue
                # An atomic store/RMW always takes arguments; a no-arg
                # call of the same name is an unrelated accessor
                # (Context::store()). Only load() is validly empty.
                if method != "load" and not args.strip():
                    continue
                # a .load()/.lock-free probe on a non-atomic (e.g. a
                # shared_ptr helper) would be caught here too; the
                # codebase has none, and a false hit is baselineable.
                emit("default-order", path, line, method,
                     f".{method}({args.strip()[:40]}...) uses default "
                     f"seq-cst ordering — name the memory_order this "
                     f"site actually needs (comment it when weaker "
                     f"than seq-cst)")
            # -- pass 2: operator stores / bare reads of conventioned
            #            atomic members in this file + paired header ---
            names = self._conventioned_atomics(corpus, path)
            if not names:
                continue
            decl_spans = [m.span() for m in _ATOMIC_DECL.finditer(cpp.code)]
            for name in sorted(names):
                for m in re.finditer(r"(?<![\w.>])" + re.escape(name)
                                     + r"\b", cpp.code):
                    line = cpp.line_of(m.start())
                    if line in cpp.if0_lines:
                        continue
                    if any(a <= m.start() < b for a, b in decl_spans):
                        continue   # the declaration itself
                    before = cpp.code[max(0, m.start() - 2):m.start()]
                    after = cpp.code[m.end():m.end() + 24].lstrip()
                    if before.endswith((".", "->", "::", "&")):
                        continue
                    if after.startswith((".", "->", "{", "[")):
                        continue   # method call / init / element access
                    if re.match(r"=[^=]", after):
                        emit("implicit-store", path, line, name,
                             f"`{name} = ...` is an implicit seq-cst "
                             f"atomic store — use "
                             f"{name}.store(..., memory_order)")
                    elif after.startswith(("++", "--", "+=", "-=", "|=",
                                           "&=", "^=")):
                        emit("implicit-rmw", path, line, name,
                             f"`{name}{after[:2]}` is an implicit "
                             f"seq-cst atomic RMW — use an explicit "
                             f"fetch_* with a memory_order")
                    else:
                        emit("implicit-load", path, line, name,
                             f"bare read of atomic `{name}` is an "
                             f"implicit seq-cst load — use "
                             f"{name}.load(memory_order)")
        return out

    def _conventioned_atomics(self, corpus: Corpus,
                              path: str) -> Set[str]:
        """Member-convention (`x_`) and global-convention (`g_x`) atomic
        names declared in this file or its sibling .h/.cc."""
        names: Set[str] = set()
        stem, ext = os.path.splitext(path)
        siblings = [path] + [stem + e for e in (".h", ".cc")
                             if stem + e != path]
        for sib in siblings:
            cpp = corpus.cpp(sib)
            if cpp is None:
                continue
            for m in _ATOMIC_DECL.finditer(cpp.code):
                if m.group("ptr"):
                    continue
                name = m.group("name")
                if name.endswith("_") or name.startswith("g_"):
                    names.add(name)
        return names
