"""C-ABI exception tightness: a C++ exception crossing the extern "C"
boundary is undefined behavior (and in practice aborts the process out
from under the Python caller, taking the whole rank down with no
tc_last_error). Every tc_* body must therefore route through one of the
catch-at-boundary helpers (wrap / wrapPtr / wrapVoid / wrapVal /
submitWork) or carry its own try/catch."""

from __future__ import annotations

import re
from typing import List

from ..engine import Corpus, Rule, Violation

CAPI = "csrc/tpucoll/capi.cc"

_BOUNDARY = re.compile(
    r"\b(?:wrap|wrapPtr|wrapVoid|wrapVal|submitWork)\s*[(<]|\btry\s*\{")


class AbiExceptionsRule(Rule):
    name = "abi-exceptions"
    description = ("every extern-C tc_* body routes through a "
                   "catch-at-boundary helper (no exception may cross "
                   "the C ABI)")

    capi_path = CAPI

    def run(self, corpus: Corpus) -> List[Violation]:
        cpp = corpus.cpp(self.capi_path)
        if cpp is None:
            return [self.violation("no-capi", self.capi_path, 1,
                                   f"{self.capi_path} not found")]
        out: List[Violation] = []
        for fn in cpp.functions():
            if not fn.name.startswith("tc_"):
                continue
            if _BOUNDARY.search(fn.body):
                continue
            out.append(self.violation(
                f"unwrapped:{fn.name}", self.capi_path, fn.line,
                f"{fn.name} does not route through "
                f"wrap/wrapPtr/wrapVoid/wrapVal or a try/catch — an "
                f"exception here crosses the C ABI and aborts the "
                f"process"))
        return out
