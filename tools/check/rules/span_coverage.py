"""Span-scope coverage: the causal critical-path engine (PR 19,
docs/critpath.md) joins ranks' span streams by the flight recorder's
cseq, so a collective entry that stamps a FlightRecOp but never opens a
span::OpScope records NO spans for ops every other rank traces — the
cross-rank merge then sees one-sided wire edges and the critical path
silently detours around that rank's contribution.

Entry points are not hardcoded: like flightrec-coverage, the rule reads
the declarations out of collectives/collectives.h, so a new collective
is covered the moment it is declared. Only entries that stamp a
FlightRecOp are held to it (an entry missing even that is
flightrec-coverage's finding, reported once, there)."""

from __future__ import annotations

import re
from typing import Dict, List

from ..engine import Corpus, Rule, Violation

COLLECTIVES_H = "csrc/tpucoll/collectives/collectives.h"

_DECL = re.compile(r"^\s*void\s+(\w+)\s*\(\s*\w*Options\s*&\s*\w+\s*\)\s*;",
                   re.M)


class SpanCoverageRule(Rule):
    name = "span-coverage"
    description = ("every public collective entry that stamps a "
                   "FlightRecOp also opens a span::OpScope")

    collectives_h = COLLECTIVES_H

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        header = corpus.text(self.collectives_h)
        if header is None:
            return [self.violation("no-header", self.collectives_h, 1,
                                   f"{self.collectives_h} not found")]
        entries = _DECL.findall(header)
        impl_dir = self.collectives_h.rsplit("/", 1)[0]
        defs: Dict[str, tuple] = {}
        for path in corpus.glob(impl_dir + "/*.cc"):
            cpp = corpus.cpp(path)
            if cpp is None:
                continue
            for fn in cpp.functions():
                base = fn.name.split("::")[-1]
                if base in entries and "Options" in fn.params:
                    defs.setdefault(base, (path, fn))
        for entry in entries:
            if entry not in defs:
                continue  # flightrec-coverage owns missing definitions
            path, fn = defs[entry]
            if "FlightRecOp" not in fn.body:
                continue  # flightrec-coverage owns unstamped entries
            if "span::OpScope" not in fn.body:
                out.append(self.violation(
                    f"unspanned:{entry}", path, fn.line,
                    f"{entry} stamps a FlightRecOp but never opens a "
                    f"span::OpScope — its ops are invisible to the "
                    f"cross-rank critical-path merge (docs/critpath.md) "
                    f"while every peer traces them"))
        return out
