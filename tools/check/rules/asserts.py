"""Enforce-macro usage: no bare `assert` in non-test C++. NDEBUG builds
(-O3 release, which is what ships) compile assert away entirely, so a
bare assert is a check that exists only on a developer box. The project
contract is TC_ENFORCE / TC_THROW (common/logging.h): always-on, throws
with file:line context, and maps to a typed Python exception at the
ABI."""

from __future__ import annotations

import re
from typing import Dict, List

from ..engine import Corpus, Rule, Violation

# \b alone is not enough: static_assert ends in `assert` but its
# preceding char is `_` (a word char), which (?<!\w) excludes.
_ASSERT = re.compile(r"(?<![\w.])assert\s*\(")


class AssertsRule(Rule):
    name = "no-bare-assert"
    description = ("no bare assert() in non-test C++ — use TC_ENFORCE/"
                   "TC_THROW, which survive NDEBUG and cross the ABI "
                   "as typed errors")

    roots = ("csrc/tpucoll/**/*.cc", "csrc/tpucoll/**/*.h",
             "csrc/tpucoll/*.cc", "csrc/tpucoll/*.h")

    def run(self, corpus: Corpus) -> List[Violation]:
        out: List[Violation] = []
        paths: List[str] = []
        for pat in self.roots:
            paths.extend(corpus.glob(pat))
        counters: Dict[str, int] = {}
        for path in sorted(set(paths)):
            cpp = corpus.cpp(path)
            if cpp is None:
                continue
            for m in _ASSERT.finditer(cpp.code):
                line = cpp.line_of(m.start())
                if line in cpp.if0_lines:
                    continue
                counters[path] = counters.get(path, 0) + 1
                n = counters[path]
                key = f"assert:{path}" + ("" if n == 1 else f"#{n}")
                out.append(self.violation(
                    key, path, line,
                    "bare assert() — compiled out under NDEBUG; use "
                    "TC_ENFORCE (always-on, typed, file:line) instead"))
        return out
