#!/usr/bin/env python
"""Cross-rank phase-profile viewer: straggler attribution + leaderboard
(docs/profiling.md).

Point it at per-rank profile snapshots — JSON files written from
``Context.profile()``, a directory of ``profile-rank*.json``, or live
ranks' telemetry endpoints (``http://host:port`` fetches
``/profile.json``) — and it merges them by collective sequence number,
attributes each op's latency to self-time vs straggler-wait, and prints
the per-rank leaderboard of who the job waits for.

    python tools/profile_view.py prof-rank0.json prof-rank1.json
    python tools/profile_view.py profile-dump/
    python tools/profile_view.py http://127.0.0.1:9401 http://127.0.0.1:9402
    python tools/profile_view.py profile-dump/ --perfetto phases.json
    python tools/profile_view.py profile-dump/ --ops 10
    python tools/profile_view.py http://10.0.0.1:9401 --fleet

With ``--fleet`` the sources are rank 0 endpoints (or saved fleet
documents) and the merged in-band ``/fleet`` view is rendered instead —
coverage, health, straggler leaderboard, slow links, anomalies
(docs/fleet.md). Endpoint handling (timeout, auth token) is shared with
flightrec_view via tools/_telemetry_client.py.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _telemetry_client  # noqa: E402
from gloo_tpu.utils import profile  # noqa: E402


def load_source(src: str, timeout: float = 10.0, token=None) -> list:
    """One source -> list of profile snapshot dicts. Never raises for a
    single bad source; reports and returns []."""
    try:
        if _telemetry_client.is_url(src):
            snap = _telemetry_client.fetch(src, "/profile.json",
                                           timeout=timeout, token=token)
            return [snap] if snap is not None else []
        if os.path.isdir(src):
            out = []
            for path in sorted(glob.glob(
                    os.path.join(src, "profile-rank*.json"))):
                out.extend(load_source(path))
            return out
        with open(src) as f:
            return [json.load(f)]
    except Exception as exc:  # noqa: BLE001 - CLI degrades per source
        print(f"warning: cannot load {src}: {exc}", file=sys.stderr)
        return []


def fmt_us(us: int) -> str:
    if us >= 1_000_000:
        return f"{us / 1e6:.2f}s"
    if us >= 1000:
        return f"{us / 1e3:.1f}ms"
    return f"{us}us"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("sources", nargs="+",
                    help="profile JSON files, a dump directory, or "
                         "http://host:port telemetry endpoints")
    ap.add_argument("--ops", type=int, default=15,
                    help="worst ops to print (by straggler excess; "
                         "default 15)")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="write per-rank phase tracks (Chrome trace "
                         "JSON) here")
    ap.add_argument("--json", action="store_true",
                    help="print the full attribution as JSON instead of "
                         "the table")
    _telemetry_client.add_endpoint_args(ap)
    args = ap.parse_args()

    if args.fleet:
        return _telemetry_client.run_fleet_mode(
            args.sources, timeout=args.timeout, token=args.token)

    snaps = []
    for src in args.sources:
        snaps.extend(load_source(src, timeout=args.timeout,
                                 token=args.token))
    if not snaps:
        print("no usable profile snapshots", file=sys.stderr)
        return 1

    # Partition by communicator group FIRST (split sub-groups / epochs
    # renumber ranks and run independent schedules — their cseq axes
    # must never be compared; same rule as flightrec_view).
    groups = profile.merge_by_group(snaps)
    if args.json:
        print(json.dumps({g: profile.attribute(m)
                          for g, m in groups.items()}, indent=2))
    for tag, merged in groups.items() if not args.json else ():
        attributed = profile.attribute(merged)
        label = f" [group {tag}]" if tag else ""
        print(f"ranks{label}: {merged['ranks']} of {merged['size']}  "
              f"collectives merged: {len(merged['ops'])}")
        if merged.get("duplicates"):
            print(f"warning: several snapshots for rank(s) "
                  f"{merged['duplicates']} — kept the last given "
                  f"source per rank", file=sys.stderr)
        print(f"\nstraggler leaderboard{label} (time the OTHER ranks "
              "spent waiting for this one):")
        for row in profile.leaderboard(attributed):
            print(f"  rank {row['rank']}: blamed for "
                  f"{fmt_us(row['blamed_us'])} across "
                  f"{row['blamed_ops']} ops  "
                  f"(self {fmt_us(row['self_us'])}, "
                  f"waited-on-others {fmt_us(row['excess_us'])})")
        worst = sorted(attributed["ops"], key=lambda o: -o["excess_us"])
        print(f"\nworst ops{label} (top {args.ops} by straggler "
              "excess):")
        for op in worst[:args.ops]:
            if op["excess_us"] <= 0:
                continue
            print(f"  cseq {op['cseq']:>5}  {op['op']}"
                  f"{'[' + op['algo'] + ']' if op['algo'] else ''}  "
                  f"{op['bytes']}B  straggler=rank {op['straggler']}  "
                  f"excess {fmt_us(op['excess_us'])}")
            for r, st in sorted(op["ranks"].items()):
                phases = " ".join(
                    f"{k}={fmt_us(v)}"
                    for k, v in sorted(st["phases"].items()))
                print(f"      rank {r}: total {fmt_us(st['total_us'])} "
                      f"(self {fmt_us(st['self_us'])}, excess "
                      f"{fmt_us(st['excess_us'])})  {phases}")
        print()

    if args.perfetto:
        # Same rails as the attribution path: one trace per group (pid
        # = rank is only unique within a communicator) and one snapshot
        # per rank (last wins), so unrelated spans never share a track.
        by_group = {}
        for snap in snaps:
            if not isinstance(snap, dict) or "ops" not in snap:
                continue
            tag = str(snap.get("group", "") or "")
            by_group.setdefault(tag, {})[int(snap.get("rank", -1))] = snap
        for tag, rank_snaps in sorted(by_group.items()):
            out = args.perfetto if not tag else \
                f"{args.perfetto}.{tag.replace('/', '.')}"
            with open(out, "w") as f:
                f.write(profile.to_perfetto(rank_snaps.values()))
            print(f"wrote {out} (open in ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
