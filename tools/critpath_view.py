#!/usr/bin/env python
"""Cross-rank causal critical-path viewer (docs/critpath.md).

Point it at per-rank span snapshots — JSON files written from
``Context.spans()``, a directory of ``spans-rank*.json``, or live ranks'
telemetry endpoints (``http://host:port`` fetches ``/spans``) — and it
merges them by collective sequence number, matches send->recv wire
edges by FIFO ordinal, extracts each op's longest weighted path, and
prints the critical path as a rank->step chain with each span's share
of the op's latency, plus the slack leaderboard (spans whose finish
could slip furthest before the op notices).

    python tools/critpath_view.py spans-rank0.json spans-rank1.json
    python tools/critpath_view.py spans-dump/
    python tools/critpath_view.py http://127.0.0.1:9401 http://127.0.0.1:9402
    python tools/critpath_view.py spans-dump/ --perfetto crit.json
    python tools/critpath_view.py spans-dump/ --check 1=send:0.8

``--check RANK=KIND:FRAC`` turns the viewer into an assertion: on the
slowest merged op, does rank RANK's spans of kind KIND own at least
FRAC of the critical-path time? Exit 0 when the check passes, 3 when it
fails, 1 when there is no usable data — so chaos tests and CI gates can
pin blame without parsing the table.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _telemetry_client  # noqa: E402
from gloo_tpu.utils import critpath  # noqa: E402


def load_source(src: str, timeout: float = 10.0, token=None) -> list:
    """One source -> list of span snapshot dicts. Never raises for a
    single bad source; reports and returns []."""
    try:
        if _telemetry_client.is_url(src):
            snap = _telemetry_client.fetch(src, "/spans",
                                           timeout=timeout, token=token)
            return [snap] if snap is not None else []
        if os.path.isdir(src):
            out = []
            for path in sorted(glob.glob(
                    os.path.join(src, "spans-rank*.json"))):
                out.extend(load_source(path))
            return out
        with open(src) as f:
            return [json.load(f)]
    except Exception as exc:  # noqa: BLE001 - CLI degrades per source
        print(f"warning: cannot load {src}: {exc}", file=sys.stderr)
        return []


def fmt_us(us: int) -> str:
    if us >= 1_000_000:
        return f"{us / 1e6:.2f}s"
    if us >= 1000:
        return f"{us / 1e3:.1f}ms"
    return f"{us}us"


def parse_check(spec: str):
    """``RANK=KIND:FRAC`` -> (rank, kind, frac). Raises ValueError."""
    rank_s, _, rest = spec.partition("=")
    kind, _, frac_s = rest.partition(":")
    rank, frac = int(rank_s), float(frac_s)
    if kind not in ("send", "recv", "wait", "local"):
        raise ValueError(f"unknown span kind {kind!r}")
    if not 0.0 < frac <= 1.0:
        raise ValueError(f"fraction must be in (0, 1], got {frac}")
    return rank, kind, frac


def run_check(analysis: dict, spec: str) -> int:
    """Evaluate --check against the slowest analyzed op."""
    rank, kind, frac = parse_check(spec)
    ops = [op for op in analysis.get("ops", []) if op["total_us"] > 0]
    if not ops:
        print("check: no analyzable ops", file=sys.stderr)
        return 1
    op = max(ops, key=lambda o: o["total_us"])
    owned = op["attribution"].get(rank, {}).get(kind, 0)
    share = owned / op["total_us"]
    verdict = "PASS" if share >= frac else "FAIL"
    print(f"check {verdict}: cseq {op['cseq']} ({op['op']}, "
          f"{fmt_us(op['total_us'])}) — rank {rank} {kind} spans own "
          f"{fmt_us(owned)} = {share:.0%} of the critical "
          f"path (need >= {frac:.0%})")
    return 0 if share >= frac else 3


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("sources", nargs="+",
                    help="span JSON files, a dump directory, or "
                         "http://host:port telemetry endpoints")
    ap.add_argument("--ops", type=int, default=5,
                    help="slowest ops to print the path for (default 5)")
    ap.add_argument("--slack", type=int, default=8,
                    help="slack leaderboard rows per op (default 8)")
    ap.add_argument("--clock", choices=("auto", "raw", "align"),
                    default="auto",
                    help="cross-rank clock handling (default auto: raw "
                         "when per-rank origins agree, else align)")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="write per-rank span tracks with the critical "
                         "path flagged (Chrome trace JSON) here")
    ap.add_argument("--json", action="store_true",
                    help="print the full analysis as JSON instead of "
                         "the table")
    ap.add_argument("--check", metavar="RANK=KIND:FRAC",
                    help="assert rank RANK's KIND spans own >= FRAC of "
                         "the slowest op's critical path; exit 0 pass, "
                         "3 fail, 1 no data")
    _telemetry_client.add_endpoint_args(ap)
    args = ap.parse_args()

    if args.check:
        try:
            parse_check(args.check)
        except ValueError as exc:
            ap.error(f"--check: {exc}")

    snaps = []
    for src in args.sources:
        snaps.extend(load_source(src, timeout=args.timeout,
                                 token=args.token))
    snaps = [s for s in snaps
             if isinstance(s, dict) and "spans" in s]
    if not snaps:
        print("no usable span snapshots", file=sys.stderr)
        return 1

    # One communicator group per analysis (split sub-groups renumber
    # ranks and run independent cseq axes; same rail as profile_view).
    groups = critpath.merge_by_group(snaps)
    analyses = {tag: critpath.analyze(m, clock=args.clock)
                for tag, m in groups.items()}

    if args.check:
        if len(analyses) != 1:
            print(f"check: need exactly one group, got "
                  f"{sorted(analyses)}", file=sys.stderr)
            return 1
        return run_check(next(iter(analyses.values())), args.check)

    if args.json:
        print(json.dumps(analyses, indent=2))
    for tag, merged in groups.items() if not args.json else ():
        analysis = analyses[tag]
        label = f" [group {tag}]" if tag else ""
        print(f"ranks{label}: {merged['ranks']} of {merged['size']}  "
              f"collectives merged: {len(merged['ops'])}  "
              f"clock: {analysis['clock']}")
        if merged.get("duplicates"):
            print(f"warning: several snapshots for rank(s) "
                  f"{merged['duplicates']} — kept the last given "
                  f"source per rank", file=sys.stderr)
        slowest = sorted(analysis["ops"], key=lambda o: -o["total_us"])
        for op in slowest[:args.ops]:
            un = op["unmatched"]
            un_note = ""
            if un["sends"] or un["recvs"] or un["mismatched"]:
                un_note = (f"  [unmatched: {un['sends']} sends, "
                           f"{un['recvs']} recvs, "
                           f"{un['mismatched']} slot/bytes mismatches]")
            print(f"\ncseq {op['cseq']}  {op['op']}  {op['bytes']}B  "
                  f"total {fmt_us(op['total_us'])}{un_note}")
            print("  critical path (origin -> finish):")
            for row in op["path"]:
                if row["contrib_us"] <= 0:
                    continue
                peer = (f" peer={row['peer']}"
                        if row.get("peer") is not None else "")
                pct = 100.0 * row["contrib_us"] / max(op["total_us"], 1)
                print(f"    rank {row['rank']} step {row['id']:>3} "
                      f"{row['kind']:<5}{peer:<9} "
                      f"{fmt_us(row['contrib_us']):>9}  {pct:5.1f}%")
            by_rank = []
            for r, kinds in sorted(op["attribution"].items()):
                total = sum(kinds.values())
                detail = " ".join(f"{k}={fmt_us(v)}"
                                  for k, v in sorted(kinds.items()))
                by_rank.append(f"rank {r} {fmt_us(total)} ({detail})")
            print("  attribution: " + "; ".join(by_rank))
            loose = [r for r in op["slack"] if r["slack_us"] > 0]
            loose.sort(key=lambda r: -r["slack_us"])
            if loose:
                print(f"  most slack (top {args.slack} — could slip "
                      "without extending the op):")
                for row in loose[:args.slack]:
                    print(f"    rank {row['rank']} step {row['id']:>3} "
                          f"{row['kind']:<5} slack "
                          f"{fmt_us(row['slack_us'])}")
        print()

    if args.perfetto:
        for tag, merged in sorted(groups.items()):
            out = args.perfetto if not tag else \
                f"{args.perfetto}.{tag.replace('/', '.')}"
            with open(out, "w") as f:
                f.write(critpath.to_perfetto(merged, analyses[tag],
                                             clock=args.clock))
            print(f"wrote {out} (open in ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
