"""Device-plane benchmark: the north star's `--device tpu` sweep.

Measures compiled mesh collectives (the XLA/ICI path) and the Pallas ring
kernels over whatever devices are visible — a real TPU slice in
production, or a forced CPU mesh for functional runs:

    python tools/tpu_bench.py --op allreduce --elements 1024,1048576
    JAX_PLATFORMS_FORCE_CPU=8 python tools/tpu_bench.py --op all

Reports the same min/p50/p99/algbw table as tpucoll_bench. On a single
device, collectives compile and execute but involve no inter-chip
traffic; numbers then measure dispatch + on-chip bandwidth only (noted
in the header).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--op", default="allreduce",
                        choices=["allreduce", "allgather", "reduce_scatter",
                                 "alltoall", "ppermute", "pallas_ring",
                                 "pallas_ring_hbm", "flash_attention",
                                 "flash_attention_bwd", "overlap",
                                 "tp_step", "all"])
    parser.add_argument("--tp-shape", default="2048x4096x4096",
                        help="MxDxF for --op tp_step (seq x model x ffn)")
    parser.add_argument("--elements", default="1024,65536,1048576,16777216")
    parser.add_argument("--min-time", type=float, default=1.0)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--flash-blocks", default=None,
                        help="comma list of BQxBK pairs to sweep, e.g. "
                             "128x128,512x1024 (default: kernel defaults)")
    parser.add_argument("--overlap-shapes", default="4096x2048,2048x4096,"
                        "4096x4096",
                        help="MxK list for --op overlap (cols==K)")
    parser.add_argument("--overlap-ranks", type=int, default=8,
                        help="virtual ring size for --op overlap")
    args = parser.parse_args()

    if args.op in ("overlap", "tp_step"):
        # The overlap kernels keep x, w and 4 staging buffers resident in
        # VMEM; the default 16 MiB scoped-vmem budget rejects realistic TP
        # shard shapes. Must be set before libtpu loads — and ONLY for
        # this op, so the other rows stay comparable with prior runs
        # (the flag can shift XLA's fusion/tiling choices). `--op all`
        # re-execs overlap as a subprocess for the same reason.
        cur = os.environ.get("LIBTPU_INIT_ARGS", "")
        if "scoped_vmem_limit" not in cur:
            os.environ["LIBTPU_INIT_ARGS"] = (
                cur + " --xla_tpu_scoped_vmem_limit_kib=114688").strip()
    elif args.op == "all":
        # BEFORE this process initializes JAX: once the parent grabs the
        # chip's exclusive libtpu lock, a child could only fall back to
        # CPU and print interpreter numbers that look like results.
        import subprocess
        subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--op", "overlap",
             "--overlap-shapes", args.overlap_shapes,
             "--overlap-ranks", str(args.overlap_ranks),
             "--warmup", str(args.warmup)], check=False)
        subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--op", "tp_step",
             "--tp-shape", args.tp_shape,
             "--overlap-ranks", str(args.overlap_ranks),
             "--warmup", str(args.warmup)], check=False)

    force_cpu = os.environ.get("JAX_PLATFORMS_FORCE_CPU")
    if force_cpu:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count="
                                   f"{force_cpu}").strip()
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from gloo_tpu.tpu import make_mesh, spmd

    mesh = make_mesh()
    n = int(np.prod(list(mesh.shape.values())))
    axis = mesh.axis_names[0]
    platform = jax.devices()[0].platform
    print(f"# tpu_bench devices={n}x{platform} mesh={dict(mesh.shape)}"
          + (" (single device: dispatch/on-chip only)" if n == 1 else ""))
    print(f"{'op':>16} {'bytes':>12} {'elements':>12} {'min(us)':>9} "
          f"{'p50(us)':>9} {'p99(us)':>9} {'algbw(GB/s)':>12} {'iters':>7}")

    def build(op, elements):
        per = max(elements // n, 1)
        if op in ("pallas_ring", "pallas_ring_hbm"):
            from gloo_tpu.ops import ring_allreduce, ring_allreduce_hbm
            base = (ring_allreduce if op == "pallas_ring"
                    else ring_allreduce_hbm)
            # CPU backends only run pallas through the interpreter.
            interp = jax.devices()[0].platform == "cpu"
            kern = lambda s, a: base(s, a, interpret=interp)  # noqa: E731
            rows = max(per // 128, n)
            rows -= rows % n or 0
            rows = max(rows, n)
            if op == "pallas_ring_hbm" and (rows // n) > 256:
                rows -= rows % (256 * n)
            x = jnp.ones((n * rows, 128), jnp.float32)
            fn = jax.jit(jax.shard_map(lambda s: kern(s, axis), mesh=mesh,
                                       in_specs=P(axis), out_specs=P(axis),
                                       check_vma=False))
            nbytes = rows * 128 * 4  # per-shard payload
            return fn, (x,), nbytes
        x = jnp.ones((n, per), jnp.float32)
        shard_ops = {
            "allreduce": lambda s: spmd.allreduce(s, axis),
            "allgather": lambda s: spmd.allgather(s[0], axis)[None],
            "reduce_scatter": lambda s: spmd.reduce_scatter(
                s[0].reshape(n, -1) if per >= n else s, axis)[None],
            "alltoall": lambda s: spmd.alltoall(
                s[0].reshape(n, -1), axis)[None] if per >= n else s,
            "ppermute": lambda s: spmd.shift(s, axis, 1),
        }
        fn = jax.jit(jax.shard_map(shard_ops[op], mesh=mesh,
                                   in_specs=P(axis), out_specs=P(axis)))
        return fn, (x,), per * 4

    ops = (["allreduce", "allgather", "reduce_scatter", "alltoall",
            "ppermute", "pallas_ring", "pallas_ring_hbm",
            "flash_attention", "flash_attention_bwd", "overlap"]
           if args.op == "all" else [args.op])
    elements_list = [int(e) for e in args.elements.split(",")]

    for mode in ("flash_attention", "flash_attention_bwd"):
        if mode in ops:
            bench_flash_attention(args, jax, jnp, elements_list,
                                  backward=mode.endswith("bwd"))
            ops = [o for o in ops if o != mode]
    if "overlap" in ops:
        if args.op == "overlap":
            bench_overlap(args, jax, jnp, mesh, axis)
        # else: already ran as a pre-JAX-init subprocess above
        ops = [o for o in ops if o != "overlap"]
    if "tp_step" in ops:
        bench_tp_step(args, jax, jnp, axis)
        ops = [o for o in ops if o != "tp_step"]
    for op in ops:
        for elements in elements_list:
            try:
                fn, fargs, nbytes = build(op, elements)
                out = fn(*fargs)
                jax.block_until_ready(out)
            except Exception as exc:  # noqa: BLE001
                print(f"{op:>16} {'-':>12} {elements:>12}   skipped: "
                      f"{str(exc)[:50]}")
                continue
            for _ in range(args.warmup):
                jax.block_until_ready(fn(*fargs))
            samples = []
            t_start = time.perf_counter()
            while time.perf_counter() - t_start < args.min_time:
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*fargs))
                samples.append(time.perf_counter() - t0)
            samples.sort()
            p = lambda q: samples[min(len(samples) - 1,
                                      int(q * len(samples)))] * 1e6
            algbw = nbytes / (p(0.5) / 1e6) / 1e9
            print(f"{op:>16} {nbytes:>12} {elements:>12} {p(0):>9.1f} "
                  f"{p(0.5):>9.1f} {p(0.99):>9.1f} {algbw:>12.3f} "
                  f"{len(samples):>7}")


def bench_flash_attention(args, jax, jnp, elements_list, backward=False):
    """MXU kernel timing that survives remote-tunnel backends where
    block_until_ready does not synchronize: chain K kernel applications
    inside ONE jitted fori_loop (output feeds the next query, defeating
    DCE), force completion with a scalar fetch, and difference a K=1 run
    to cancel the fetch round-trip. algbw column = achieved GFLOP/s.

    backward=True times fwd+bwd via jax.grad (flops counted 3.5x fwd:
    one forward pass plus the fused one-pass backward kernel, whose
    ideal matmul work is ~2.5x forward). --flash-blocks sweeps tile
    sizes."""
    import time as _time

    from jax import lax

    from gloo_tpu.ops import flash_attention

    interp = jax.devices()[0].platform == "cpu"
    h, d = 8, 128
    label = "flash_bwd" if backward else "flash_attention"
    print(f"# {label} rows: the last column is GFLOP/s, not GB/s")
    if args.flash_blocks:
        block_list = [tuple(int(x) for x in pair.split("x"))
                      for pair in args.flash_blocks.split(",")]
    else:
        block_list = [(None, None)]

    seen = set()
    for elements in elements_list:
        t = max(elements // (h * d) // 128 * 128, 128)
        if interp:
            # The interpreter executes each grid step in Python; large t
            # means (t/128)^2 * h invocations per call — cap it.
            t = min(t, 256)
        if t in seen:  # small elements values clamp to the same config
            continue
        seen.add(t)
        for bq, bk in block_list:
            tag = label if bq is None else f"{label}:{bq}x{bk}"
            try:
                q = jnp.ones((1, h, t, d), jnp.bfloat16)

                def apply(c):
                    return flash_attention(c, c, c, causal=True,
                                           block_q=bq, block_k=bk,
                                           interpret=interp)

                if backward:
                    step = jax.grad(
                        lambda c: jnp.sum(apply(c).astype(jnp.float32) ** 2))
                else:
                    step = apply

                def chain(k):
                    def body(i, c):
                        return step(c).astype(c.dtype)
                    return jax.jit(lambda q: lax.fori_loop(0, k, body, q))

                per_iter, k_iters = _chain_rate(args, jax, chain, q,
                                                interp, _time, k0=64)
            except Exception as exc:  # noqa: BLE001 — skip row, sweep on
                print(f"{tag:>16} {'-':>12} {elements:>12}   "
                      f"skipped: {str(exc)[:50]}")
                continue
            if per_iter is None:
                print(f"{tag:>16} {'-':>12} {h * t * d:>12}   "
                      "skipped: timing noise exceeded kernel time "
                      "(t too small to difference)")
                continue
            fwd_flops = 2 * h * (t * t // 2) * d * 2
            flops = int(fwd_flops * 3.5) if backward else fwd_flops
            nbytes = 3 * h * t * d * 2
            if backward:
                # + dO/O/lse/delta reads and three f32 gradient writes.
                nbytes = nbytes + 2 * h * t * d * 2 + 3 * h * t * d * 4
            # Chained differenced timing: one per-iteration figure
            # (best-of-reps min), not a percentile.
            print(f"{tag:>16} {nbytes:>12} {h * t * d:>12} "
                  f"{per_iter * 1e6:>9.1f} {'-':>9} "
                  f"{'-':>9} {flops / per_iter / 1e9:>12.3f} {k_iters:>7}")


def bench_overlap(args, jax, jnp, mesh, axis):
    """Real-chip proof of the collective-matmul kernels' compute pipeline.

    On one chip the ring runs with self-loop neighbors (virtual_ranks):
    every hop's async copy lands in the local comm slot, so the kernel
    executes its full P-step schedule — per-chunk MXU matmuls, staged
    copies, semaphore waits — with the ICI leg replaced by on-chip DMA.
    Comparing against a plain jnp.dot of the same [M,K]@[K,K] answers the
    question that matters before any multi-chip run: how much MXU
    throughput does the fused schedule's chunking give up? (The ICI leg
    itself needs a multi-chip slice; tests/test_overlap.py covers ring
    correctness on the interpret mesh.)

    Timing is the tunnel-safe chained fori_loop (see
    bench_flash_attention): the output feeds the next input, and the
    chain grows until the differenced time exceeds 250 ms. The GFLOP/s
    column counts 2*M*K*K per iteration for all three variants.
    """
    import time as _time

    from jax import lax
    from jax.sharding import PartitionSpec as P

    from gloo_tpu.ops.overlap import _ag_matmul_shard, _matmul_rs_shard

    import numpy as np
    from jax.sharding import Mesh

    interp = jax.devices()[0].platform == "cpu"
    V = args.overlap_ranks
    # Self-loop mode needs a 1-device axis regardless of the full mesh.
    mesh = Mesh(np.asarray(jax.devices()[:1], dtype=object), (axis,))
    shapes = [tuple(int(v) for v in s.split("x"))
              for s in args.overlap_shapes.split(",")]
    print(f"# overlap: virtual ring V={V} (self-loop RDMA), cols=K; "
          f"last columns are GFLOP/s and fused/plain ratio")
    seen = set()
    for m, k in shapes:
        if interp:
            m, k = min(m, 256), min(k, 256)  # functional smoke only
        if (m, k) in seen:  # interp clamp collapses shapes
            continue
        seen.add((m, k))
        chunk = m // V
        if chunk == 0 or chunk % 8:
            print(f"{'overlap':>16} {'-':>12} {m}x{k}   skipped: "
                  f"M/V={m}/{V} not a usable chunk")
            continue
        w = jnp.full((k, k), 1.0 / k, jnp.bfloat16)
        flops = 2 * m * k * k

        def plain_body(c):
            return jnp.dot(c, w, preferred_element_type=jnp.float32
                           ).astype(c.dtype)

        def mmrs_body(c):
            y = _matmul_rs_shard(c, w, axis_name=axis, mesh_axes=None,
                                 collective_id=21, interpret=interp,
                                 virtual_ranks=V)
            return c.at[:chunk, :].set(y)

        def agmm_body(c):
            y, _ = _ag_matmul_shard(c, w, axis_name=axis, mesh_axes=None,
                                    collective_id=23, interpret=interp,
                                    virtual_ranks=V)
            return y[:chunk, :]

        variants = [("plain_dot", plain_body, (m, k)),
                    ("matmul_rs", mmrs_body, (m, k)),
                    ("ag_matmul", agmm_body, (chunk, k))]
        rates = {}
        for name, body, xshape in variants:
            x = jnp.ones(xshape, jnp.bfloat16)

            def make_chain(n_iter, body=body):
                def outer(xv):
                    return lax.fori_loop(0, n_iter,
                                         lambda i, c: body(c), xv)
                return jax.jit(jax.shard_map(outer, mesh=mesh,
                                             in_specs=P(), out_specs=P(),
                                             check_vma=False))

            try:
                per, _k = _chain_rate(args, jax, make_chain, x, interp,
                                      _time)
            except Exception as exc:  # noqa: BLE001 — skip row, sweep on
                print(f"{name:>16} {'-':>12} {m}x{k}   skipped: "
                      f"{str(exc)[:60]}")
                continue
            if per is None:
                print(f"{name:>16} {'-':>12} {m}x{k}   skipped: timing "
                      "noise exceeded kernel time")
                continue
            rates[name] = flops / per / 1e9
            ratio = (f"{rates[name] / rates['plain_dot']:>8.2f}"
                     if name != "plain_dot" and "plain_dot" in rates
                     else f"{'-':>8}")
            # Chained differenced timing yields one per-iteration figure
            # (best-of-reps); it is a min, not a percentile.
            print(f"{name:>16} {m * k * 2:>12} {f'{m}x{k}':>12} "
                  f"{per * 1e6:>9.1f} {'-':>9} {'-':>9} "
                  f"{rates[name]:>12.3f} {ratio}")


def bench_tp_step(args, jax, jnp, axis):
    """End-to-end fused-TP training-step A/B on one chip (VERDICT r3 #8).

    The integration proof the kernel microbenches don't give: a full
    forward + backward + SGD update through the Megatron-SP MLP pair,
    with BOTH collectives fused into their matmuls (allgather_matmul up,
    matmul_reduce_scatter down; each kernel is the other's VJP seed), vs
    the identical-FLOP unfused step (plain dots — on ONE chip the
    collectives are free, so plain dots are exactly the unfused math).
    Virtual-ring mode: the fused path executes its full V-step schedule
    with self-loop RDMA, so parity here means the pod-scale win (hidden
    comm) costs nothing when there is nothing to hide.
    """
    import time as _time

    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from gloo_tpu.ops.overlap import _ag_matmul_shard, _matmul_rs_shard

    interp = jax.devices()[0].platform == "cpu"
    V = args.overlap_ranks
    mesh = Mesh(np.asarray(jax.devices()[:1], dtype=object), (axis,))
    m, d, f = (int(v) for v in args.tp_shape.split("x"))
    if interp:
        m, d, f, = 256, 256, 256
    chunk = m // V
    assert chunk and chunk % 8 == 0, f"M/V={m}/{V} not a usable chunk"

    # Bench-local custom-vjp wrappers threading virtual_ranks through the
    # same fused-dual structure as the public ops (overlap.py).
    def make_fused_pair():
        kw = dict(axis_name=axis, mesh_axes=None, interpret=interp,
                  virtual_ranks=V)

        @jax.custom_vjp
        def ag_mm(xv, wv):
            y, _ = _ag_matmul_shard(xv, wv, collective_id=23, **kw)
            return y

        def ag_fwd(xv, wv):
            y, gx = _ag_matmul_shard(xv, wv, collective_id=23, **kw)
            return y, (gx, wv)

        def ag_bwd(res, g):
            gx, wv = res
            dx = _matmul_rs_shard(g, wv.T, collective_id=21, **kw)
            dw = jnp.dot(gx.T, g, preferred_element_type=jnp.float32
                         ).astype(wv.dtype)
            return dx, dw

        ag_mm.defvjp(ag_fwd, ag_bwd)

        @jax.custom_vjp
        def rs_mm(av, wv):
            return _matmul_rs_shard(av, wv, collective_id=25, **kw)

        def rs_fwd(av, wv):
            return rs_mm(av, wv), (av, wv)

        def rs_bwd(res, g):
            av, wv = res
            # dual: da = gather(g) @ w^T via the fused allgather kernel
            da, gfull = _ag_matmul_shard(g, wv.T, collective_id=27, **kw)
            dw = jnp.dot(av.T, gfull, preferred_element_type=jnp.float32
                         ).astype(wv.dtype)
            return da, dw

        rs_mm.defvjp(rs_fwd, rs_bwd)
        return ag_mm, rs_mm

    ag_mm, rs_mm = make_fused_pair()
    lr = 1e-3

    def fused_loss(params, x_loc):
        h = ag_mm(x_loc, params["up"])          # [m, f]
        a = jax.nn.gelu(h)
        y = rs_mm(a, params["down"])            # [chunk, d]
        return jnp.mean(jnp.square(y.astype(jnp.float32)))

    def plain_loss(params, x_full):
        h = jnp.dot(x_full, params["up"], preferred_element_type=jnp.float32
                    ).astype(x_full.dtype)
        a = jax.nn.gelu(h)
        y = jnp.dot(a, params["down"], preferred_element_type=jnp.float32)
        # Loss over ALL rows: slicing to [chunk] here would let XLA
        # dead-code-eliminate most of the down-projection and its
        # backward (measured >peak "FLOP/s"), biasing the baseline. A
        # real unfused TP rank computes the full [m,f]@[f,d] partial and
        # reduce-scatters it; the fused path does the same work inside
        # the kernel, so full-row loss is the equal-FLOPs comparison.
        return jnp.mean(jnp.square(y))

    def make_step(loss_fn):
        def step(params, x):
            # Grad w.r.t. x too: a real TP block sits in a stack and
            # always produces dx for the layer below. Without this the
            # plain path DCEs its dx matmul while the fused path's
            # side-effecting kernels cannot — a structural 6-vs-5-matmul
            # bias. The tiny x update keeps dx live in the chain.
            g, gx = jax.grad(loss_fn, argnums=(0, 1))(params, x)
            new_params = jax.tree.map(lambda p, gg: (p - lr * gg.astype(
                jnp.float32)).astype(p.dtype), params, g)
            return new_params, (x - 1e-6 * gx.astype(jnp.float32)).astype(
                x.dtype)
        return step

    params = {"up": jnp.full((d, f), 1.0 / d, jnp.bfloat16),
              "down": jnp.full((f, d), 1.0 / f, jnp.bfloat16)}
    # fwd 2 matmuls + bwd 4 (dx, dw each layer) of m*d*f MACs.
    flops = 2 * m * d * f * 6
    print(f"# tp_step: Megatron-SP MLP pair, M={m} D={d} F={f}, virtual "
          f"ring V={V}; full train step (fwd+bwd+sgd), GFLOP/s and ratio")
    rates = {}
    for name, loss_fn, xshape in (
            ("unfused_step", plain_loss, (m, d)),
            ("fused_step", fused_loss, (chunk, d))):
        step = make_step(loss_fn)
        x = jnp.ones(xshape, jnp.bfloat16)

        def make_chain(n_iter, step=step):
            def outer(pv):
                fin = lax.fori_loop(0, n_iter,
                                    lambda i, c: step(c[0], c[1]),
                                    (pv, x))
                return fin[0]["up"]  # array probe for _chain_rate's fetch
            return jax.jit(jax.shard_map(outer, mesh=mesh, in_specs=P(),
                                         out_specs=P(), check_vma=False))

        try:
            per, _k = _chain_rate(args, jax,
                                  lambda n, mk=make_chain: mk(n), params,
                                  interp, _time)
        except Exception as exc:  # noqa: BLE001 — report and continue
            print(f"{name:>16}   failed: {str(exc)[:80]}")
            continue
        if per is None:
            print(f"{name:>16}   skipped: timing noise exceeded step time")
            continue
        rates[name] = flops / per / 1e9
        ratio = ("" if "unfused_step" not in rates or name == "unfused_step"
                 else f" {rates[name] / rates['unfused_step']:>8.2f}")
        print(f"{name:>16} {per * 1e6:>12.1f} us/step "
              f"{rates[name]:>12.1f} GFLOP/s{ratio}")

    # Dispatcher check (r5): on one chip comm is free (share=0), so
    # use_fused_overlap must pick unfused for this shape — and the
    # measured ratio tells whether the model's flip threshold (1-ratio)
    # brackets reality. Printed so sweep logs double as calibration
    # evidence for gloo_tpu.parallel.use_fused_overlap.
    if "unfused_step" in rates and "fused_step" in rates:
        from gloo_tpu.parallel import fused_compute_ratio
        measured = rates["fused_step"] / rates["unfused_step"]
        model = fused_compute_ratio(m, f, V)
        # The model decision directly (share=0 > 1-ratio), NOT
        # use_fused_overlap: that honors TPUCOLL_TP_OVERLAP, and a
        # forced env would mislabel these calibration logs.
        picks_fused = 0.0 > 1.0 - model
        winner_ok = picks_fused == (measured > 1.0)
        print(f"# dispatch: model ratio {model:.2f} (measured {measured:.2f},"
              f" flip at comm>{1 - model:.0%}); share=0 picks "
              f"{'fused' if picks_fused else 'unfused'} -> "
              f"{'MATCHES' if winner_ok else 'CONTRADICTS'} measured winner")


def _chain_rate(args, jax, make_chain, x, interp, _time, k0=32):
    """(seconds-per-chained-iteration, chain length) — differenced
    against a 1-iteration run to cancel the tunnel round-trip. Small
    kernels: k0 chained iterations are dwarfed by tunnel round-trip
    variance, so the chain keeps growing until the measured difference
    exceeds 250 ms of work (a single re-estimate can itself be
    noise-inflated), with an iteration cap as the stop. Returns
    (None, k) when even the longest chain is inside the noise."""
    k_iters = 2 if interp else k0
    f1, fk = make_chain(1), make_chain(k_iters)

    def run(f):
        out = f(x)
        _ = float(out.ravel()[0])  # forces completion + fetch

    for _ in range(max(1, args.warmup)):
        run(f1), run(fk)
    reps = 1 if interp else 5
    t1 = min(_timeit(run, f1, _time) for _ in range(reps))
    tk = min(_timeit(run, fk, _time) for _ in range(reps))
    while not interp and tk - t1 < 0.25 and k_iters < 16384:
        per_est = max((tk - t1) / (k_iters - 1), 5e-7)
        k_iters = min(max(int(0.25 / per_est) + k0, k_iters * 4), 16384)
        fk = make_chain(k_iters)
        run(fk)  # compile
        tk = min(_timeit(run, fk, _time) for _ in range(reps))
    if tk <= t1:
        return None, k_iters
    return (tk - t1) / (k_iters - 1), k_iters


def _timeit(run, f, _time):
    t0 = _time.perf_counter()
    run(f)
    return _time.perf_counter() - t0


if __name__ == "__main__":
    main()
