"""Discriminate the overlap-kernel bimodality (BASELINE.md r4/r5 lead).

The 2048x4096 collective-matmul cells are bimodal ACROSS PROCESS
RESTARTS (fast ~0.87-0.88x of plain dot, slow ~0.79-0.80x) while plain
dot varies <1%. Three candidate causes, separated by this harness:

  run noise        — same compiled executable re-timed twice differs
  compile draw     — two fresh compiles of identical HLO in ONE process
                     differ (Mosaic scheduling nondeterminism)
  process state    — in-process compiles agree, only restarts differ
                     (per-process seed / allocator layout)

Method per trial: clear the jit cache; time plain dot; time fused
compile A; re-time compile A's SAME objects (run-noise bound); time a
second fresh compile B (in-process compile-draw bound). Chains are
sized to >0.25 s of differenced work so the tunnel round-trip noise
cancels. The chain length is FIXED (unlike tpu_bench's adaptive
`_chain_rate`, deliberately): compiles A and B must be timed over
identical chain lengths or the comparison confounds chain growth with
the compile draw it exists to isolate.

Run several times from fresh processes to capture the cross-restart
axis:  for i in 1 2 3; do python tools/overlap_probe.py; done
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

cur = os.environ.get("LIBTPU_INIT_ARGS", "")
if "scoped_vmem_limit" not in cur:
    os.environ["LIBTPU_INIT_ARGS"] = (
        cur + " --xla_tpu_scoped_vmem_limit_kib=114688").strip()

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="2048x4096", help="MxK (cols=K)")
    ap.add_argument("--ranks", type=int, default=8)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--chain", type=int, default=700)
    ap.add_argument("--smoke", action="store_true",
                    help="CPU + Pallas interpreter + tiny shape: proves "
                         "the harness executes end-to-end where no TPU "
                         "is reachable (timing columns meaningless)")
    args = ap.parse_args()
    if args.chain < 2:
        ap.error("--chain must be >= 2")

    import jax

    if args.smoke:
        # Force CPU through jax.config: site customization may pin the
        # platform before this script runs, and with the TPU tunnel
        # down the pinned backend hangs in connect retries.
        jax.config.update("jax_platforms", "cpu")
        args.shape, args.ranks, args.chain = "32x64", 4, 3
        args.trials = min(args.trials, 1)

    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from gloo_tpu.ops.overlap import _matmul_rs_shard

    m, k = (int(v) for v in args.shape.split("x"))
    V, N = args.ranks, args.chain
    chunk = m // V
    mesh = Mesh(np.asarray(jax.devices()[:1], dtype=object), ("x",))
    w = jnp.full((k, k), 1.0 / k, jnp.bfloat16)
    x = jnp.ones((m, k), jnp.bfloat16)

    def mmrs_body(c):
        y = _matmul_rs_shard(c, w, axis_name="x", mesh_axes=None,
                             collective_id=21, interpret=args.smoke,
                             virtual_ranks=V)
        return c.at[:chunk, :].set(y)

    def plain_body(c):
        return jnp.dot(c, w, preferred_element_type=jnp.float32
                       ).astype(c.dtype)

    def chain(body):
        # Traced trip count: one executable serves both chain lengths,
        # so t1/tk difference the SAME schedule draw.
        def outer(xv, n):
            return lax.fori_loop(0, n, lambda i, c: body(c), xv)
        return jax.jit(jax.shard_map(outer, mesh=mesh,
                                     in_specs=(P(), P()), out_specs=P(),
                                     check_vma=False))

    def run(f, n):
        _ = float(np.asarray(f(x, jnp.int32(n))).ravel()[0])

    def timeit(f, n):
        t0 = time.perf_counter()
        run(f, n)
        return time.perf_counter() - t0

    def measure(f, reps=5):
        run(f, 1), run(f, N)
        t1 = min(timeit(f, 1) for _ in range(reps))
        tk = min(timeit(f, N) for _ in range(reps))
        return (tk - t1) / (N - 1)

    # Caveat: a compilation cache that dedupes by HLO fingerprint (e.g.
    # JAX_COMPILATION_CACHE_DIR, or a remote-compile service that
    # caches) makes compile B an alias of compile A and the A-vs-B
    # column vacuously equal — clear_caches() below handles the
    # in-process caches, but an external cache must be disabled for the
    # discrimination to mean anything.
    print(f"# overlap_probe {m}x{k} V={V} chain={N} pid={os.getpid()}")
    print("trial  plain_us  cmpA_us  cmpA2_us  cmpB_us  ratioA  ratioB")
    for trial in range(args.trials):
        jax.clear_caches()
        p = measure(chain(plain_body))
        fA = chain(mmrs_body)
        fa = measure(fA)
        fa2 = measure(fA)       # same executable: run-noise bound
        # Fresh compile of identical HLO. clear_caches drops the
        # in-process jit/executable caches so B really recompiles;
        # fA's live executable keeps working for reference.
        jax.clear_caches()
        fb = measure(chain(mmrs_body))
        print(f"{trial:>5}  {p*1e6:8.1f} {fa*1e6:8.1f}  {fa2*1e6:8.1f} "
              f"{fb*1e6:8.1f}   {p/fa:5.2f}   {p/fb:5.2f}", flush=True)


if __name__ == "__main__":
    main()
