"""Shared telemetry-endpoint client for the ``tools/`` viewers.

``profile_view.py`` and ``flightrec_view.py`` both accept live
``http://host:port`` sources next to dump files; this module is the one
place their endpoint handling lives so it cannot drift: a bounded
connect timeout (a dead rank must degrade to a warning, not hang the
viewer), the ``X-TpuColl-Token`` auth header for token-guarded
endpoints (``--token`` / ``TPUCOLL_TELEMETRY_TOKEN``), and the shared
``--fleet`` source mode that renders rank 0's merged ``/fleet``
document (docs/fleet.md) instead of the per-rank view.

Import AFTER the caller's ``sys.path`` bootstrap (the viewers insert
the repo root before their gloo_tpu imports).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from gloo_tpu.utils import fleet as fleet_util
from gloo_tpu.utils.telemetry import fetch_route


def is_url(source: str) -> bool:
    return source.startswith("http://") or source.startswith("https://")


def add_endpoint_args(ap: argparse.ArgumentParser) -> None:
    """The endpoint flags both viewers share."""
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="per-endpoint connect/read timeout in seconds "
                         "(default 10; a dead rank degrades to a "
                         "warning instead of hanging the viewer)")
    ap.add_argument("--token", default=None,
                    help="telemetry auth token sent as X-TpuColl-Token "
                         "(default: TPUCOLL_TELEMETRY_TOKEN)")
    ap.add_argument("--fleet", action="store_true",
                    help="fetch /fleet from the source(s) (rank 0's "
                         "merged fleet-observability document) and "
                         "render coverage, stragglers, slow links and "
                         "anomalies instead of the per-rank view")


def fetch(source: str, route: str, timeout: float = 10.0,
          token: Optional[str] = None):
    """Fetch ``route`` from one live endpoint; warn + return None on
    any failure (absence is evidence — the viewers treat an
    unreachable rank like a missing dump file)."""
    try:
        return fetch_route(source, route, timeout=timeout, token=token)
    except Exception as exc:  # noqa: BLE001 - CLI degrades per source
        print(f"warning: cannot fetch {source}{route}: {exc}",
              file=sys.stderr)
        return None


def run_fleet_mode(sources, timeout: float = 10.0,
                   token: Optional[str] = None) -> int:
    """The shared ``--fleet`` entry point: each source is a live
    endpoint (fetches ``/fleet``) or a saved fleet-document JSON file;
    render each. Exit 0 when every source yielded a document AND no
    document shows missing coverage or recent anomalies; 1 otherwise
    (scriptable, like flightrec_view --check)."""
    status = 0
    for src in sources:
        if is_url(src):
            doc = fetch(src, "/fleet", timeout=timeout, token=token)
        else:
            try:
                with open(src) as f:
                    doc = json.load(f)
            except Exception as exc:  # noqa: BLE001 - degrade per source
                print(f"warning: cannot load {src}: {exc}",
                      file=sys.stderr)
                doc = None
        if doc is None:
            status = 1
            continue
        if len(sources) > 1:
            print(f"== {src}")
        sys.stdout.write(fleet_util.render(doc))
        summary = fleet_util.summarize(doc)
        if (summary["coverage"]["missing"]
                or summary["recent_anomalies_by_kind"]):
            status = 1
    return status
