#!/usr/bin/env python
"""Diff two committed bench JSONs cell-by-cell and gate on regressions.

The repo commits its performance evidence as JSON — headline medians
(``BENCH_r*.json``), sweep grids (``BASELINE_sweep*.json``), and
per-round metric lines (``PROF_r15.json``, ``OBS_r16.json``,
``CRIT_r19.json``, ...). This tool joins two such files by cell key,
prints per-cell ratios, and exits nonzero when any cell regressed by
more than the threshold (default 25%) BEYOND the spread the baseline
itself recorded — a cell whose own noise floor is 10% must move 35%
before it counts.

    python tools/bench_compare.py BENCH_r11.json BENCH_r12.json
    python tools/bench_compare.py BASELINE_sweep_r5.json BASELINE_sweep_r11.json
    python tools/bench_compare.py PROF_r15.json fresh-profile.json --threshold 0.4

Accepted shapes (auto-detected, mixable):

- a single JSON object with a ``cells`` list (sweep files) — cell key
  is the metadata tuple (op/bytes/ranks/plane/engine/...), value is
  ``p50_us`` (lower is better);
- one JSON object per line (round metric files) — cell key is
  ``metric`` plus discriminators (algorithm/elements/ranks/...), value
  is ``value`` (direction from ``unit``: rates are higher-better) or
  ``p50_us``/``wall_ms`` (lower-better); a recorded ``spread``
  (relative) or ``runs`` series widens that cell's allowance.

Cells present on only one side are reported but never gate (grids grow
between rounds); cells whose payload carries ``ok: false`` are skipped.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# Key fields that discriminate cells of the same metric; everything
# else in a row is payload.
KEY_FIELDS = ("metric", "op", "algorithm", "collective", "elements",
              "bytes", "ranks", "hosts", "nranks", "plane", "engine",
              "schedule", "world", "unit", "arm", "codec_threads")
# Lower-is-better value fields, in preference order. The *_on fields
# pick the instrumented arm out of overhead A/B rows so observability
# rounds stay comparable across rounds.
TIME_FIELDS = ("p50_us", "wall_ms", "p50_ms", "mean_total_us",
               "exchange_ms", "publish_ms", "p50_us_spans_on",
               "p50_us_profile_on", "p50_us_fleetobs_on")


class Cell:
    __slots__ = ("key", "value", "higher_better", "rel_spread")

    def __init__(self, key: str, value: float, higher_better: bool,
                 rel_spread: float):
        self.key = key
        self.value = value
        self.higher_better = higher_better
        self.rel_spread = rel_spread


def _rel_spread(row: dict, value: float) -> float:
    if value <= 0:
        return 0.0
    spread = row.get("spread")
    if isinstance(spread, (int, float)):
        # BENCH rows record (max - min) / median already; older rows
        # recorded it absolute. Values > 1 are clearly absolute.
        return float(spread) if spread <= 1 else float(spread) / value
    runs = row.get("runs") or row.get("runs_on_us") or row.get("runs_us")
    if isinstance(runs, list) and len(runs) >= 2 and \
            all(isinstance(r, (int, float)) for r in runs):
        return (max(runs) - min(runs)) / value
    return 0.0


def _row_cell(row: dict, prefix: str = "") -> Optional[Cell]:
    if not isinstance(row, dict) or row.get("ok") is False:
        return None
    key = prefix + " ".join(
        f"{k}={row[k]}" for k in KEY_FIELDS if k in row)
    if not key:
        return None
    if isinstance(row.get("value"), (int, float)):
        unit = str(row.get("unit", ""))
        return Cell(key, float(row["value"]), "/s" in unit,
                    _rel_spread(row, float(row["value"])))
    for f in TIME_FIELDS:
        if isinstance(row.get(f), (int, float)) and row[f] > 0:
            return Cell(f"{key} [{f}]", float(row[f]), False,
                        _rel_spread(row, float(row[f])))
    return None


def load_cells(path: str) -> List[Cell]:
    with open(path) as f:
        text = f.read()
    docs: List[dict] = []
    try:
        doc = json.loads(text)
        docs = doc if isinstance(doc, list) else [doc]
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if line:
                docs.append(json.loads(line))
    cells: List[Cell] = []
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        if isinstance(doc.get("cells"), list):
            for row in doc["cells"]:
                cell = _row_cell(row)
                if cell is not None:
                    cells.append(cell)
            continue
        cell = _row_cell(doc)
        if cell is not None:
            cells.append(cell)
            continue
        # Sectioned round files (BENCH_r11+): named sub-objects each
        # carrying their own metric row. Rows with their own "metric"
        # field keep it as identity (so a sectioned headline still joins
        # a flat one across rounds); anonymous rows take the section
        # name as key prefix.
        for name, sub in doc.items():
            if isinstance(sub, dict):
                cell = _row_cell(
                    sub, prefix="" if "metric" in sub else f"{name}: ")
                if cell is not None:
                    cells.append(cell)
    return cells


def compare(old: List[Cell], new: List[Cell], threshold: float,
            ) -> Tuple[List[dict], List[str]]:
    """Join by key; return (joined rows, one-sided keys)."""
    old_by: Dict[str, Cell] = {c.key: c for c in old}
    new_by: Dict[str, Cell] = {c.key: c for c in new}
    rows = []
    for key in old_by:
        if key not in new_by:
            continue
        o, n = old_by[key], new_by[key]
        ratio = n.value / o.value if o.value else float("inf")
        # Regression = the "worse" direction, beyond threshold plus the
        # baseline cell's own recorded noise.
        worse = ratio < 1.0 if o.higher_better else ratio > 1.0
        magnitude = abs(ratio - 1.0)
        allowance = threshold + o.rel_spread
        rows.append({"key": key, "old": o.value, "new": n.value,
                     "ratio": round(ratio, 3),
                     "allowance": round(allowance, 3),
                     "regressed": worse and magnitude > allowance})
    only = ([f"only in old: {k}" for k in old_by if k not in new_by] +
            [f"only in new: {k}" for k in new_by if k not in old_by])
    return rows, only


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline bench JSON (committed)")
    ap.add_argument("new", help="candidate bench JSON")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="regression gate beyond recorded spread "
                         "(default 0.25 = 25%%)")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON")
    args = ap.parse_args()

    old = load_cells(args.old)
    new = load_cells(args.new)
    if not old or not new:
        print(f"no comparable cells ({len(old)} old, {len(new)} new)",
              file=sys.stderr)
        return 1
    rows, only = compare(old, new, args.threshold)
    if not rows:
        print("no overlapping cells between the two files",
              file=sys.stderr)
        return 1

    regressed = [r for r in rows if r["regressed"]]
    if args.json:
        print(json.dumps({"rows": rows, "unmatched": only,
                          "regressed": len(regressed)}, indent=2))
    else:
        width = max(len(r["key"]) for r in rows)
        for r in sorted(rows, key=lambda r: r["key"]):
            flag = "  REGRESSED" if r["regressed"] else ""
            print(f"{r['key']:<{width}}  {r['old']:>12.3f} -> "
                  f"{r['new']:>12.3f}  x{r['ratio']:.3f} "
                  f"(allow ±{r['allowance']:.0%}){flag}")
        for line in only:
            print(f"note: {line}", file=sys.stderr)
    if regressed:
        print(f"{len(regressed)} cell(s) regressed beyond threshold",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
