"""Machine-readable benchmark sweep -> BASELINE_sweep.json.

The reference's benchmark harness is built for reproducible comparison
(gloo/benchmark/runner.cc:475-516: timed iterations, percentile
summaries, one line per config). This sweep is the repo's equivalent
artifact: every cell of workload x payload x ranks x payload-plane
{plain TCP, shm, encrypted} x event engine {epoll, uring} measured with
the SAME multi-process methodology (FileStore rendezvous, one OS
process per rank — the deployment shape, not the thread harness), so
BASELINE.md tables can cite committed JSON instead of hand-transcribed
prose, and round-over-round regressions are a `diff` away.

Usage: python tools/bench_sweep.py [--quick] [--out BASELINE_sweep.json]
Each cell records p50/p99/min latency (us), algorithm bandwidth at p50,
and iteration count, straight from tpucoll_bench --json.
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "build", "tpucoll_bench")

OPS = ["allreduce", "reduce_scatter", "broadcast"]
ELEMENTS = [1024, 262144, 4194304]  # 4 KiB, 1 MiB, 16 MiB of f32
RANKS = [2, 4]
# (label, env overrides, extra argv) — the payload-plane tiers.
PLANES = [
    ("plain", {"TPUCOLL_SHM": "0"}, []),
    # Pinned to "1" so an inherited TPUCOLL_SHM=0 cannot silently turn
    # the shm cells into plain-TCP measurements labeled "shm".
    ("shm", {"TPUCOLL_SHM": "1"}, []),
    ("encrypted", {"TPUCOLL_SHM": "0"},
     ["--auth-key", "sweep-key", "--encrypt"]),
]
ENGINES = ["epoll", "uring"]


def run_cell(op, elements, ranks, plane, engine, min_time):
    """One measurement cell. Fault-isolated: a hung/crashed/garbled cell
    returns {"error": ...} instead of aborting the sweep, and its rank
    processes and rendezvous dir are always reaped."""
    label, env_over, extra = plane
    store = tempfile.mkdtemp(prefix="tcsweep-")
    env = dict(os.environ, TPUCOLL_ENGINE=engine, **env_over)
    base = [BENCH, "--size", str(ranks), "--store", f"file:{store}",
            "--op", op, "--elements", str(elements),
            "--min-time", str(min_time), "--json", *extra]
    procs = []
    try:
        procs = [subprocess.Popen(base + ["--rank", str(r)], env=env,
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.DEVNULL, text=True)
                 for r in range(1, ranks)]
        out = subprocess.run(base + ["--rank", "0"], env=env,
                             capture_output=True, text=True, timeout=120)
        for p in procs:
            p.communicate(timeout=120)
        if out.returncode != 0:
            return {"error": out.stderr.strip()[-200:]}
        # A non-rank-0 worker can fail after rank 0 finishes (e.g. a
        # teardown crash); numbers from such a cell are not trustworthy.
        bad = [p for p in procs if p.returncode != 0]
        if bad:
            return {"error": f"{len(bad)} worker(s) exited non-zero: "
                             f"{[p.returncode for p in bad]}"}
        d = json.loads(out.stdout.splitlines()[0])
        return {"p50_us": d["p50_us"], "p99_us": d["p99_us"],
                "min_us": d["min_us"], "algbw_gbps": d["algbw_gbps"],
                "iters": d["iters"]}
    except subprocess.TimeoutExpired as exc:
        # Structured kind, not just prose: the rep loop branches on this
        # flag (substring-matching "Timeout" in a truncated message was
        # fragile — the type name can be cut off at the 200-char cap or
        # appear inside an unrelated worker error).
        return {"error": f"{type(exc).__name__}: {exc}"[:200],
                "timeout": True}
    except (json.JSONDecodeError, IndexError, KeyError) as exc:
        return {"error": f"{type(exc).__name__}: {exc}"[:200]}
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
        shutil.rmtree(store, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="output path (default: BASELINE_sweep.json; "
                         "--quick defaults elsewhere so smoke runs never "
                         "clobber the committed regression baseline)")
    ap.add_argument("--quick", action="store_true",
                    help="0.5s cells instead of 2s (smoke runs)")
    ap.add_argument("--reps", type=int, default=1,
                    help="repetitions per cell; >1 records the "
                         "median-p50 rep (plus every rep's p50) so a "
                         "single scheduler transient cannot fabricate a "
                         "3x regression — the r5 sweep hit exactly that "
                         "(BASELINE.md 'r5 regression sweep')")
    args = ap.parse_args()
    if args.reps < 1:
        ap.error("--reps must be >= 1")
    if args.out is None:
        args.out = ("/tmp/BASELINE_sweep_quick.json" if args.quick
                    else os.path.join(REPO, "BASELINE_sweep.json"))
    if not os.path.exists(BENCH):
        sys.exit("build/tpucoll_bench missing - run `make native` first")
    min_time = 0.5 if args.quick else 2.0

    cells = []
    t0 = time.time()
    total = len(OPS) * len(ELEMENTS) * len(RANKS) * len(PLANES) * \
        len(ENGINES)
    n = 0
    for op in OPS:
        for elements in ELEMENTS:
            for ranks in RANKS:
                for plane in PLANES:
                    for engine in ENGINES:
                        n += 1
                        runs = []
                        for _ in range(args.reps):
                            r = run_cell(op, elements, ranks, plane,
                                         engine, min_time)
                            runs.append(r)
                            if r.get("timeout"):
                                # A 120s timeout is a hang (cells run
                                # 0.5-2s), not a transient: don't burn
                                # reps x 2min on a dead config.
                                break
                        ok = [r for r in runs if "p50_us" in r]
                        if not ok:
                            res = runs[0]
                            if len(runs) > 1:
                                # All reps failed: keep every rep's
                                # error, not just the first (failure
                                # modes can differ across reps).
                                res = dict(res,
                                           rep_errors=[r.get("error")
                                                       for r in runs])
                        else:
                            # Lower median: with an even rep count the
                            # upper-middle pick would select the SLOWER
                            # rep — the transient this flag suppresses.
                            res = sorted(ok, key=lambda r: r["p50_us"])[
                                (len(ok) - 1) // 2]
                            if args.reps > 1:
                                res = dict(res,
                                           rep_p50s=[r["p50_us"]
                                                     for r in ok])
                                errs = [r["error"] for r in runs
                                        if "error" in r]
                                if errs:
                                    # Flaky cell: keep the evidence in
                                    # the artifact, not just the
                                    # surviving rep's numbers.
                                    res["rep_errors"] = errs
                        cell = {"op": op, "elements": elements,
                                "bytes": elements * 4, "ranks": ranks,
                                "plane": plane[0], "engine": engine,
                                **res}
                        cells.append(cell)
                        print(f"[{n}/{total}] {op} {elements * 4 >> 10}KiB "
                              f"P={ranks} {plane[0]}/{engine}: "
                              f"{res.get('p50_us', res)} us p50",
                              file=sys.stderr)

    doc = {
        "methodology": "multi-process (one OS process per rank), "
                       "FileStore rendezvous, tpucoll_bench --json; "
                       "p50/p99/min over timed iterations after warmup; "
                       f"min-time {min_time}s per cell; "
                       f"reps {args.reps} (lower-median-p50 rep kept)",
        "reps": args.reps,
        "host": "single shared core (BASELINE.md: +/-15% run-to-run); "
                "treat cross-cell ratios, not absolutes, as the signal",
        "timestamp_unix": int(t0),
        "cells": cells,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"wrote {args.out}: {len(cells)} cells in "
          f"{time.time() - t0:.0f}s", file=sys.stderr)


if __name__ == "__main__":
    main()
