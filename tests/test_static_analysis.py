"""Tier-1 test of the tpucoll-check static-analysis suite (tools/check/,
docs/check.md).

Two halves:

- the REAL repo must be clean: the full rule suite over csrc/ +
  gloo_tpu/ + docs/ exits 0 with empty-or-justified baselines, inside
  the 30 s budget (`make check` is this, as CI runs it);
- each rule must demonstrably FIRE: deliberately broken snippets under
  tests/fixtures/check/ reproduce every violation class, so a rule that
  silently rots into a no-op fails here, not in review.

Plus the baseline machinery: suppression round-trips, a stale baseline
entry (violation fixed but still listed) is itself an error, and
malformed baseline lines are loud.
"""

import json
import os
import subprocess
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "check")
sys.path.insert(0, _REPO)

from tools.check.engine import Baseline, Corpus, run_rules  # noqa: E402
from tools.check.rules import ALL_RULES, make_rules  # noqa: E402
from tools.check.rules.abi_drift import parse_capi, parse_lib  # noqa: E402


def _fixture_report(fixture, rule_names, baseline_dir=None):
    return run_rules(os.path.join(_FIXTURES, fixture),
                     make_rules(rule_names), baseline_dir=baseline_dir)


def _keys(report):
    return {v.key for r in report.results for v in r.violations}


# -- the real repo is clean ---------------------------------------------


def test_repo_is_clean_and_fast():
    """The whole suite over the actual codebase: no unsuppressed
    violations, no stale baseline entries, < 30 s on a 2-core host."""
    t0 = time.monotonic()
    report = run_rules(
        _REPO, make_rules(),
        baseline_dir=os.path.join(_REPO, "tools", "check", "baselines"))
    elapsed = time.monotonic() - t0
    problems = [v.render() for r in report.results for v in r.violations]
    problems += [f"stale baseline entry {k!r} ({r.rule})"
                 for r in report.results for k in r.stale]
    assert report.ok, "\n".join(problems)
    assert len(report.results) == len(ALL_RULES)
    assert elapsed < 30, f"suite took {elapsed:.1f}s (budget 30s)"


def test_repo_suppressions_are_justified():
    """Every shipped baseline entry carries a non-empty one-line
    justification (Baseline.load enforces the format; this pins that
    the shipped files parse and stay small)."""
    bdir = os.path.join(_REPO, "tools", "check", "baselines")
    total = 0
    for fn in sorted(os.listdir(bdir)):
        b = Baseline.load(os.path.join(bdir, fn))
        for key, why in b.entries.items():
            assert why.strip(), (fn, key)
        total += len(b.entries)
    # The point of the PR was to FIX the violations, not baseline them.
    assert total <= 5, f"{total} suppressions — fix, don't mute"


def test_abi_surface_fully_mirrored():
    """The tc_* surface is large and fully mirrored: both parsers see
    the same symbol set (the abi-drift rule's clean run is the real
    assertion; this pins the surface didn't silently shrink)."""
    corpus = Corpus(_REPO)
    capi = parse_capi(corpus)
    lib = parse_lib(corpus)
    assert len(capi) >= 90, len(capi)
    assert set(capi) == set(lib), (
        set(capi) ^ set(lib))


def test_make_check_json_report(tmp_path):
    """`make check` / the CLI end-to-end: exit 0 on the clean repo and
    a machine-readable --json report with one entry per rule."""
    out = tmp_path / "check.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.check", "--json", str(out)],
        cwd=_REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["tool"] == "tpucoll-check" and doc["ok"] is True
    assert {r["rule"] for r in doc["rules"]} == \
        {cls.name for cls in ALL_RULES}
    for r in doc["rules"]:
        assert r["ok"] is True, r


# -- every rule fires on its fixture ------------------------------------


def test_fixture_abi_drift():
    """Removed symbol, arity mismatch, missing restype, argtype
    mismatch, and a lib-only ghost symbol are each caught; the correctly
    mirrored symbol is not flagged."""
    keys = _keys(_fixture_report("abi_drift", ["abi-drift"]))
    assert "missing-in-lib:tc_removed" in keys
    assert "missing-in-capi:tc_ghost" in keys
    assert "arity:tc_arity" in keys
    assert "restype:tc_restype" in keys
    assert "argtype:tc_argtype:1" in keys
    assert not any("tc_good" in k for k in keys), keys


def test_fixture_abi_exceptions():
    keys = _keys(_fixture_report("abi_exceptions", ["abi-exceptions"]))
    assert keys == {"unwrapped:tc_naked"}, keys


def test_fixture_env_hygiene():
    keys = _keys(_fixture_report("env_hygiene", ["env-hygiene"]))
    assert "raw-getenv:csrc/tpucoll/transport/knob.cc:rawRead" in keys
    assert "undocumented:TPUCOLL_UNDOCUMENTED" in keys
    assert "docs-only:TPUCOLL_GHOST" in keys
    # getenv inside common/env.h itself is sanctioned.
    assert not any("env.h" in k for k in keys), keys


def test_fixture_atomics():
    path = "csrc/tpucoll/counter.cc"
    keys = _keys(_fixture_report("atomics", ["explicit-atomics"]))
    assert f"default-order:{path}:load" in keys
    assert f"implicit-store:{path}:n_" in keys
    assert f"implicit-rmw:{path}:n_" in keys
    assert f"implicit-load:{path}:n_" in keys
    # The fully annotated accesses contribute nothing.
    assert len(keys) == 4, keys


def test_fixture_flightrec():
    keys = _keys(_fixture_report("flightrec", ["flightrec-coverage"]))
    assert "unstamped:naked" in keys
    assert "no-definition:orphan" in keys
    assert "unstamped-p2p:tc_buffer_send" in keys
    assert not any("stamped" in k and "unstamped" not in k
                   for k in keys), keys


def test_fixture_span_coverage():
    """An entry stamping FlightRecOp without a span::OpScope is caught;
    the fully traced entry is clean, and the entry missing even the
    FlightRecOp is left to flightrec-coverage (reported once, there)."""
    keys = _keys(_fixture_report("span_coverage", ["span-coverage"]))
    assert "unspanned:blind" in keys
    assert not any("traced" in k for k in keys), keys
    assert not any("unstamped" in k for k in keys), keys


def test_fixture_metrics_drift():
    keys = _keys(_fixture_report("metrics_drift", ["metrics-drift"]))
    assert "unread-key:ghost_key" in keys
    assert "undocumented-family:gloo_tpu_undoc_total" in keys
    assert "docs-only-family:gloo_tpu_stale_total" in keys
    assert not any("good_key" in k or "documented_total" in k
                   for k in keys), keys


def test_fixture_lock_order():
    """The AB/BA cycle is a violation, the undocumented reverse edge is
    a violation, and the config's ghost edge is reported stale."""
    keys = _keys(_fixture_report("lock_order", ["lock-order"]))
    assert any(k.startswith("cycle:") for k in keys), keys
    assert "undocumented:Striper::bMu_->Striper::aMu_" in keys
    assert "stale-edge:Striper::ghostMu_->Striper::bMu_" in keys


def test_fixture_schedule_step_coverage():
    """A declared op the interpreter never lowers (or ir.cc never
    names) fires; a case for a removed op is reported stale; handled
    ops stay quiet. Step attributes: a member ir.cc only half
    round-trips (parse without emit) or never touches fires; a fully
    round-tripped member and a static constexpr constant stay quiet."""
    keys = _keys(_fixture_report("schedule_step_coverage",
                                 ["schedule-step-coverage"]))
    assert ("unhandled:csrc/tpucoll/schedule/interpreter.cc:kDecode"
            in keys)
    assert "unhandled:csrc/tpucoll/schedule/ir.cc:kDecode" in keys
    assert "stale:csrc/tpucoll/schedule/verifier.cc:kGhost" in keys
    assert not any("kSend" in k or "kRecv" in k for k in keys), keys
    assert "unserialized:pipeline" in keys
    assert "unserialized:ghost_attr" in keys
    assert "unserialized:op" not in keys, keys
    assert "unserialized:flags" not in keys, keys
    assert "unserialized:kFlagToSlot" not in keys, keys


def test_fixture_asserts():
    """Bare assert fires; static_assert does not."""
    keys = _keys(_fixture_report("asserts", ["no-bare-assert"]))
    assert keys == {"assert:csrc/tpucoll/checks.cc"}, keys


# -- baseline machinery -------------------------------------------------


def test_baseline_suppression_round_trip(tmp_path):
    """A baselined violation is suppressed (run goes clean), carries its
    justification in the report, and survives the JSON round trip."""
    bdir = tmp_path / "baselines"
    bdir.mkdir()
    (bdir / "no-bare-assert.txt").write_text(
        "# fixture baseline\n"
        "assert:csrc/tpucoll/checks.cc -- fixture: demonstrates "
        "suppression\n")
    report = _fixture_report("asserts", ["no-bare-assert"],
                             baseline_dir=str(bdir))
    assert report.ok
    (result,) = report.results
    assert not result.violations and not result.stale
    ((viol, why),) = result.suppressed
    assert viol.key == "assert:csrc/tpucoll/checks.cc"
    assert "demonstrates suppression" in why
    doc = json.loads(report.to_json())
    assert doc["rules"][0]["suppressed"][0]["justification"] == why


def test_stale_baseline_entry_is_an_error(tmp_path):
    """A baseline entry whose violation was fixed must be deleted: the
    run fails and names the stale key."""
    bdir = tmp_path / "baselines"
    bdir.mkdir()
    (bdir / "no-bare-assert.txt").write_text(
        "assert:csrc/tpucoll/checks.cc -- real suppression\n"
        "assert:csrc/tpucoll/gone.cc -- this violation no longer "
        "exists\n")
    report = _fixture_report("asserts", ["no-bare-assert"],
                             baseline_dir=str(bdir))
    assert not report.ok
    (result,) = report.results
    assert result.stale == ["assert:csrc/tpucoll/gone.cc"]
    assert "delete the entry" in report.render()


def test_malformed_baseline_is_loud(tmp_path):
    """Entries without ' -- ' or without a justification are format
    errors, not silently ignored lines."""
    p = tmp_path / "no-bare-assert.txt"
    p.write_text("assert:csrc/tpucoll/checks.cc\n")
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(p))
    p.write_text("assert:csrc/tpucoll/checks.cc -- \n")
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(p))


def test_cli_fixture_failure_exit_code(tmp_path):
    """The CLI exits nonzero on violations and its --json report
    carries them (what CI annotations consume)."""
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.check",
         "--root", os.path.join(_FIXTURES, "asserts"),
         "--rules", "no-bare-assert", "--json", str(out)],
        cwd=_REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["ok"] is False
    (rule,) = doc["rules"]
    assert rule["violations"][0]["key"] == \
        "assert:csrc/tpucoll/checks.cc"
