"""Fused matmul+collective kernels (gloo_tpu/ops/overlap.py), validated on
the distributed-interpreter CPU mesh against reference einsums, including
their transposed-dual VJPs."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from gloo_tpu.ops import allgather_matmul, matmul_reduce_scatter  # noqa: E402


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.asarray(devs[:n], dtype=object), ("x",))


def _rand(shape, seed, dtype=np.float32):
    return np.random.RandomState(seed).uniform(-1, 1, shape).astype(dtype)


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_matmul_reduce_scatter_forward(n):
    mesh = _mesh(n)
    m, k_total, cols = 8 * n, 16 * n, 128
    x = _rand((m, k_total), 0)          # global X, k sharded
    w = _rand((k_total, cols), 1)       # global W, k sharded

    fn = jax.jit(jax.shard_map(
        lambda xs, ws: matmul_reduce_scatter(xs, ws, "x", interpret=True),
        mesh=mesh, in_specs=(P(None, "x"), P("x", None)),
        out_specs=P("x", None), check_vma=False))
    out = np.asarray(fn(x, w))          # [m, cols]: rank r rows stacked
    expected = x.astype(np.float64) @ w.astype(np.float64)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_allgather_matmul_forward(n):
    mesh = _mesh(n)
    m_total, k, cols = 8 * n, 32, 128
    x = _rand((m_total, k), 2)          # global X, rows sharded
    w = _rand((k, cols), 3)             # replicated W

    fn = jax.jit(jax.shard_map(
        lambda xs, ws: allgather_matmul(xs, ws, "x", interpret=True),
        mesh=mesh, in_specs=(P("x", None), P(None, None)),
        out_specs=P(None, None), check_vma=False))
    # Every device computes the FULL product; out_specs=P(None) asserts
    # replication and returns one copy.
    out = np.asarray(fn(x, w))
    expected = x.astype(np.float64) @ w.astype(np.float64)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_allgather_matmul_column_sharded_w(n=4):
    """w column-sharded (true column-parallel): each device computes its
    own output columns for ALL rows."""
    mesh = _mesh(n)
    m_total, k, cols = 8 * n, 32, 128 * n
    x = _rand((m_total, k), 4)
    w = _rand((k, cols), 5)

    fn = jax.jit(jax.shard_map(
        lambda xs, ws: allgather_matmul(xs, ws, "x", interpret=True),
        mesh=mesh, in_specs=(P("x", None), P(None, "x")),
        out_specs=P(None, "x"), check_vma=False))
    out = np.asarray(fn(x, w))
    expected = x.astype(np.float64) @ w.astype(np.float64)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_matmul_reduce_scatter_grads(n=4):
    """VJP against the unfused reference: dx and dw must match the plain
    einsum composition's grads (the duality allgather <-> reduce-scatter)."""
    mesh = _mesh(n)
    m, k_total, cols = 8 * n, 16 * n, 128
    x = _rand((m, k_total), 6)
    w = _rand((k_total, cols), 7)

    def fused_loss(xv, wv):
        def shard(xs, ws):
            y = matmul_reduce_scatter(xs, ws, "x", interpret=True)
            return y
        y = jax.shard_map(shard, mesh=mesh,
                          in_specs=(P(None, "x"), P("x", None)),
                          out_specs=P("x", None), check_vma=False)(xv, wv)
        return jnp.sum(jnp.sin(y))

    def ref_loss(xv, wv):
        return jnp.sum(jnp.sin(xv @ wv))

    gx_f, gw_f = jax.grad(fused_loss, argnums=(0, 1))(x, w)
    gx_r, gw_r = jax.grad(ref_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r),
                               rtol=2e-4, atol=2e-5)


def test_allgather_matmul_grads(n=4):
    """Backward of the gather-side op runs the fused dual
    (matmul_reduce_scatter) — grads must match the plain composition."""
    mesh = _mesh(n)
    m_total, k, cols = 8 * n, 32, 128
    x = _rand((m_total, k), 8)
    w = _rand((k, cols), 9)

    def fused_loss(xv, wv):
        def shard(xs, ws):
            return allgather_matmul(xs, ws, "x", interpret=True)
        y = jax.shard_map(shard, mesh=mesh,
                          in_specs=(P("x", None), P(None, None)),
                          out_specs=P(None, None), check_vma=False)(xv, wv)
        return jnp.sum(jnp.cos(y))

    def ref_loss(xv, wv):
        return jnp.sum(jnp.cos(xv @ wv))

    gx_f, gw_f = jax.grad(fused_loss, argnums=(0, 1))(x, w)
    gx_r, gw_r = jax.grad(ref_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r),
                               rtol=2e-4, atol=2e-5)


def test_matmul_reduce_scatter_bf16(n=4):
    mesh = _mesh(n)
    m, k_total, cols = 8 * n, 16 * n, 128
    x = _rand((m, k_total), 10).astype(jnp.bfloat16)
    w = _rand((k_total, cols), 11).astype(jnp.bfloat16)
    fn = jax.jit(jax.shard_map(
        lambda xs, ws: matmul_reduce_scatter(xs, ws, "x", interpret=True),
        mesh=mesh, in_specs=(P(None, "x"), P("x", None)),
        out_specs=P("x", None), check_vma=False))
    out = np.asarray(fn(x, w).astype(jnp.float32))
    expected = np.asarray(x.astype(np.float32)) @ np.asarray(
        w.astype(np.float32))
    np.testing.assert_allclose(out, expected, rtol=0.1, atol=0.1)


def test_megatron_sp_roundtrip_fused(n=4):
    """The Megatron sequence-parallel loop with BOTH collectives fused:
    sequence-sharded x -> allgather_matmul_dense (gather fused into the
    up-projection) -> gelu -> row_parallel_dense_scattered (reduce-scatter
    fused into the down-projection) -> sequence-sharded y. Must match the
    plain dense MLP."""
    from gloo_tpu.parallel.tp import (allgather_matmul_dense,
                                      row_parallel_dense_scattered)

    mesh = _mesh(n)
    seq, d, h = 8 * n, 32, 16 * n
    x = _rand((seq, d), 20)
    w_up = _rand((d, h), 21)      # columns sharded over the axis
    w_down = _rand((h, d), 22)    # rows sharded over the axis

    def shard(xs, wu, wd):
        hidden = allgather_matmul_dense(xs, wu, "x", interpret=True)
        hidden = jax.nn.gelu(hidden)
        return row_parallel_dense_scattered(hidden, wd, "x", interpret=True)

    fn = jax.jit(jax.shard_map(
        shard, mesh=mesh,
        in_specs=(P("x", None), P(None, "x"), P("x", None)),
        out_specs=P("x", None), check_vma=False))
    out = np.asarray(fn(x, w_up, w_down))
    expected = np.asarray(jax.nn.gelu(jnp.asarray(x @ w_up))) @ w_down
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-5)


def test_matmul_reduce_scatter_multi_axis_mesh():
    """2x2 mesh, ring over the minor 'model' axis: mesh_axes routes the
    RDMA by flattened logical device id (omitting it would misroute)."""
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:4], dtype=object).reshape(2, 2),
                ("data", "model"))
    n = 2
    m, k_total, cols = 8 * n, 16 * n, 128
    x = _rand((m, k_total), 30)
    w = _rand((k_total, cols), 31)

    fn = jax.jit(jax.shard_map(
        lambda xs, ws: matmul_reduce_scatter(
            xs, ws, "model", interpret=True,
            mesh_axes=("data", "model")),
        mesh=mesh, in_specs=(P(None, "model"), P("model", None)),
        out_specs=P("model", None), check_vma=False))
    out = np.asarray(fn(x, w))
    expected = x.astype(np.float64) @ w.astype(np.float64)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_virtual_ring_selfloop_bench_path():
    """The single-chip bench mode (virtual_ranks on a 1-device axis,
    tools/tpu_bench.py --op overlap): self-loop RDMA means every hop
    adds this rank's own staged sum, so matmul_rs degenerates to
    sum over row-blocks of X_b @ W and allgather_matmul's own chunk
    is exact. Guards the timing harness against schedule rot."""
    from gloo_tpu.ops.overlap import _ag_matmul_shard, _matmul_rs_shard

    mesh = _mesh(1)
    V, m, k, cols = 4, 64, 32, 128
    chunk = m // V
    x = _rand((m, k), 7)
    w = _rand((k, cols), 8)

    out = jax.jit(jax.shard_map(
        lambda xs, ws: _matmul_rs_shard(
            xs, ws, axis_name="x", mesh_axes=None, collective_id=21,
            interpret=True, virtual_ranks=V),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False))(
            x, w)
    expected = sum(x[b * chunk:(b + 1) * chunk].astype(np.float64)
                   @ w.astype(np.float64) for b in range(V))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=2e-5,
                               atol=2e-5)

    xs = _rand((chunk, k), 9)
    y, _gx = jax.jit(jax.shard_map(
        lambda xv, ws: _ag_matmul_shard(
            xv, ws, axis_name="x", mesh_axes=None, collective_id=23,
            interpret=True, virtual_ranks=V),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False))(
            xs, w)
    # Only this rank's own chunk (row-block 0 for rank 0) is defined in
    # self-loop mode; the rest of gx is never received.
    np.testing.assert_allclose(np.asarray(y)[:chunk],
                               xs.astype(np.float64) @ w.astype(np.float64),
                               rtol=2e-5, atol=2e-5)


def test_fori_fallback_matches_unrolled(n=4, monkeypatch=None):
    """Pod-size rings (> _kMaxUnrollRing) take the fori_loop form of the
    ring walk instead of the static unroll; force it and pin both
    kernels against the same oracles the unrolled path satisfies."""
    import gloo_tpu.ops.overlap as ov

    saved = ov._kMaxUnrollRing
    ov._kMaxUnrollRing = 1  # every ring takes the fallback
    # New jit cache keys: bump collective ids so cached unrolled
    # executables are not reused.
    try:
        mesh = _mesh(n)
        m, k_total, cols = 8 * n, 16 * n, 128
        x = _rand((m, k_total), 20)
        w = _rand((k_total, cols), 21)
        fn = jax.jit(jax.shard_map(
            lambda xs, ws: matmul_reduce_scatter(
                xs, ws, "x", interpret=True, collective_id=41),
            mesh=mesh, in_specs=(P(None, "x"), P("x", None)),
            out_specs=P("x", None), check_vma=False))
        np.testing.assert_allclose(
            np.asarray(fn(x, w)),
            x.astype(np.float64) @ w.astype(np.float64),
            rtol=2e-5, atol=2e-5)

        x2 = _rand((8 * n, 32), 22)
        w2 = _rand((32, cols), 23)
        fn2 = jax.jit(jax.shard_map(
            lambda xs, ws: allgather_matmul(
                xs, ws, "x", interpret=True, collective_id=43),
            mesh=mesh, in_specs=(P("x", None), P(None, None)),
            out_specs=P(None, None), check_vma=False))
        np.testing.assert_allclose(
            np.asarray(fn2(x2, w2)),
            x2.astype(np.float64) @ w2.astype(np.float64),
            rtol=2e-5, atol=2e-5)
    finally:
        ov._kMaxUnrollRing = saved
