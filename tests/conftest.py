import os
import subprocess
import sys

# Device-plane tests run on a virtual 8-device CPU mesh. The environment may
# pin JAX_PLATFORMS to a TPU plugin (e.g. axon) at interpreter start, so
# override via jax.config before any backend is initialized.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def pytest_configure(config):
    # Build (or rebuild) the native core once per session. Sanitizer runs
    # set TPUCOLL_SKIP_BUILD=1 (the toolchain cannot run under LD_PRELOADed
    # sanitizer runtimes) and point TPUCOLL_LIB at a prebuilt library.
    if os.environ.get("TPUCOLL_SKIP_BUILD"):
        return
    subprocess.run(["make", "native"], cwd=_REPO_ROOT, check=True,
                   capture_output=True)
