"""Tracing subsystem: host-plane collective spans + merged timelines."""

import json

import numpy as np

from gloo_tpu.utils import merge_traces
from tests.harness import spawn


def test_collective_spans_recorded():
    size = 2

    def fn(ctx, rank):
        ctx.trace_start()
        x = np.ones(1000, dtype=np.float32)
        ctx.allreduce(x)
        ctx.broadcast(x, root=0)
        ctx.barrier()
        ctx.trace_stop()
        ctx.allreduce(x)  # after stop: must not be recorded
        return ctx.trace_json()

    results = spawn(size, fn)
    for rank, doc in enumerate(results):
        events = json.loads(doc)
        names = [e["name"] for e in events]
        assert names == ["allreduce", "broadcast", "barrier"], names
        for e in events:
            assert e["ph"] == "X"
            assert e["dur"] >= 0
            assert e["pid"] == rank
        assert events[0]["args"]["bytes"] == 4000
        assert events[0]["args"]["detail"] in (
                "ring", "halving_doubling", "recursive_doubling")
        assert events[1]["args"]["peer"] == 0  # broadcast root


def test_trace_drains():
    def fn(ctx, rank):
        ctx.trace_start()
        ctx.barrier()
        first = ctx.trace_json()
        second = ctx.trace_json()
        return json.loads(first), json.loads(second)

    first, second = spawn(2, fn)[0]
    assert len(first) == 1
    assert second == []


def test_p2p_wait_spans_recorded():
    """The p2p path records spans too, not just collectives: wait_send on
    the sender, wait_recv (with the resolved source peer) on the
    receiver."""

    def fn(ctx, rank):
        ctx.trace_start()
        x = np.arange(16, dtype=np.float32)
        if rank == 0:
            ctx.send(x, 1, slot=5)
        else:
            ctx.recv(x, 0, slot=5)
        ctx.trace_stop()
        return ctx.trace_json()

    docs = spawn(2, fn)
    sender = json.loads(docs[0])
    assert [e["name"] for e in sender] == ["wait_send"]
    assert sender[0]["args"]["bytes"] == 64  # registered buffer size
    receiver = json.loads(docs[1])
    assert [e["name"] for e in receiver] == ["wait_recv"]
    assert receiver[0]["args"]["peer"] == 0  # resolved source rank


def test_merge_traces():
    def fn(ctx, rank):
        ctx.trace_start()
        ctx.barrier()
        return ctx.trace_json()

    docs = spawn(2, fn)
    merged = json.loads(merge_traces(docs))
    meta = [e for e in merged if e["ph"] == "M"]
    data = [e for e in merged if e["ph"] != "M"]
    assert len(data) == 2
    assert sorted(e["pid"] for e in data) == [0, 1]
    # Per-rank labeled rows: process_name + process_sort_index metadata
    # for every pid, so Perfetto renders "rank N" lanes.
    assert {(e["name"], e["pid"]) for e in meta} == {
        ("process_name", 0), ("process_name", 1),
        ("process_sort_index", 0), ("process_sort_index", 1)}
    name_meta = {e["pid"]: e["args"]["name"] for e in meta
                 if e["name"] == "process_name"}
    assert name_meta == {0: "rank 0", 1: "rank 1"}
    # Data events come out globally time-ordered.
    ts = [e["ts"] for e in data]
    assert ts == sorted(ts)
    # Metadata survives a re-merge without duplicating.
    again = json.loads(merge_traces([json.dumps(merged)]))
    assert len([e for e in again if e["ph"] == "M"]) == len(meta)


def test_merge_traces_edge_cases():
    """Satellite: merge must degrade gracefully over a crashed rank's
    leavings — empty documents, truncated JSON, a missing rank — and
    re-sort inputs whose timestamps arrive unsorted."""
    good = json.dumps([
        {"name": "allreduce", "ph": "X", "ts": 300, "dur": 5, "pid": 0,
         "tid": 0, "args": {}},
        {"name": "barrier", "ph": "X", "ts": 100, "dur": 5, "pid": 0,
         "tid": 0, "args": {}},  # unsorted on purpose
    ])
    other = json.dumps([
        {"name": "allreduce", "ph": "X", "ts": 200, "dur": 5, "pid": 2,
         "tid": 0, "args": {}},
    ])
    # rank 1 crashed: its trace is empty; another file is truncated junk.
    merged = json.loads(merge_traces([good, "", '[{"name": "tru', other]))
    data = [e for e in merged if e["ph"] != "M"]
    assert [e["ts"] for e in data] == [100, 200, 300]
    # Rows exist only for ranks that contributed events (0 and 2): the
    # absent rank is visible by its missing lane, not a crash here.
    meta_pids = {e["pid"] for e in merged if e["ph"] == "M"}
    assert meta_pids == {0, 2}
    # All-empty input produces an empty (but valid) document.
    assert json.loads(merge_traces(["", None])) == []
