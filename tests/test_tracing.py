"""Tracing subsystem: host-plane collective spans + merged timelines."""

import json

import numpy as np

from gloo_tpu.utils import merge_traces
from tests.harness import spawn


def test_collective_spans_recorded():
    size = 2

    def fn(ctx, rank):
        ctx.trace_start()
        x = np.ones(1000, dtype=np.float32)
        ctx.allreduce(x)
        ctx.broadcast(x, root=0)
        ctx.barrier()
        ctx.trace_stop()
        ctx.allreduce(x)  # after stop: must not be recorded
        return ctx.trace_json()

    results = spawn(size, fn)
    for rank, doc in enumerate(results):
        events = json.loads(doc)
        names = [e["name"] for e in events]
        assert names == ["allreduce", "broadcast", "barrier"], names
        for e in events:
            assert e["ph"] == "X"
            assert e["dur"] >= 0
            assert e["pid"] == rank
        assert events[0]["args"]["bytes"] == 4000
        assert events[0]["args"]["detail"] in (
                "ring", "halving_doubling", "recursive_doubling")
        assert events[1]["args"]["peer"] == 0  # broadcast root


def test_trace_drains():
    def fn(ctx, rank):
        ctx.trace_start()
        ctx.barrier()
        first = ctx.trace_json()
        second = ctx.trace_json()
        return json.loads(first), json.loads(second)

    first, second = spawn(2, fn)[0]
    assert len(first) == 1
    assert second == []


def test_merge_traces():
    def fn(ctx, rank):
        ctx.trace_start()
        ctx.barrier()
        return ctx.trace_json()

    docs = spawn(2, fn)
    merged = json.loads(merge_traces(docs))
    assert len(merged) == 2
    assert sorted(e["pid"] for e in merged) == [0, 1]
