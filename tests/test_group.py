"""Process-group subsystem (ISSUE 13): topology discovery, native
Context.split sub-communicators, and the topology-aware hierarchical
(kHier) collectives — plus the store-key hygiene and post-mortem
partitioning contracts that ride on the group tags.

Topology simulation: each rank overrides its host fingerprint
(Context.set_host_id) so one machine presents as H simulated hosts; the
shm payload plane then negotiates only between co-"hosted" ranks, which
is both the observable proof of the grouping and what makes the mixed
shm+TCP fabric real (docs/topology.md).
"""

import json
import os
import subprocess
import sys
import tempfile
import textwrap
import threading

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu.utils import flightrec as frmod

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_topo(size, rph, fn, timeout=60.0, context_timeout=30.0,
               host_of=None):
    """harness.spawn with a simulated topology: rank r presents host
    fingerprint grp-host<host_of(r)> (default r // rph)."""
    store = gloo_tpu.HashStore()
    results = [None] * size
    errors = []
    lock = threading.Lock()

    def worker(rank):
        ctx = None
        try:
            device = gloo_tpu.Device()
            ctx = gloo_tpu.Context(rank, size, timeout=context_timeout)
            host = host_of(rank) if host_of is not None else rank // rph
            ctx.set_host_id(f"grp-host{host}")
            ctx.connect_full_mesh(store, device)
            results[rank] = fn(ctx, rank)
        except BaseException as exc:  # noqa: BLE001 - surfaced to caller
            with lock:
                errors.append((rank, exc))
        finally:
            if ctx is not None:
                try:
                    ctx.close()
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"rank thread did not finish in {timeout}s")
    if errors:
        rank, exc = errors[0]
        raise AssertionError(f"rank {rank} failed: {exc!r}") from exc
    return results


# ---------------------------------------------------------------------------
# topology discovery
# ---------------------------------------------------------------------------

def test_topology_discovery_and_shm_grouping():
    """2 simulated hosts x 3 ranks: every rank derives the same
    ranks-per-host map, local coordinates, and leader; and the shm plane
    negotiated with exactly the co-hosted peers (cross-host pairs pinned
    to TCP by the topology mask)."""
    def fn(ctx, rank):
        topo = ctx.topology()
        assert topo["n_hosts"] == 2 and topo["non_flat"] is True, topo
        assert topo["hosts"][0]["ranks"] == [0, 1, 2]
        assert topo["hosts"][1]["ranks"] == [3, 4, 5]
        assert topo["host_index"] == rank // 3
        assert topo["local_rank"] == rank % 3
        assert topo["local_size"] == 3
        assert topo["leader"] == (rank // 3) * 3
        assert topo["is_leader"] == (rank % 3 == 0)
        # Force traffic so shm negotiation evidence exists.
        ctx.allreduce(np.ones(1 << 14, np.float32))
        return ctx.shm_stats()["active_pairs"]

    pairs = spawn_topo(6, 3, fn)
    assert pairs == [2] * 6, pairs  # only the 2 co-hosted peers


def test_topology_flat_without_override():
    """No overrides: in-process ranks share the real host fingerprint —
    one host, flat topology, every pair shm-eligible."""
    from tests.harness import spawn

    def fn(ctx, rank):
        topo = ctx.topology()
        assert topo["n_hosts"] == 1 and topo["non_flat"] is False, topo
        assert topo["local_size"] == 3
        return True

    assert all(spawn(3, fn))


# ---------------------------------------------------------------------------
# Context.split
# ---------------------------------------------------------------------------

def test_split_colors_keys_and_optout():
    """MPI_Comm_split semantics: same color groups; ranks ordered by
    (key, parent rank) — keys reverse the order here; negative color
    yields None but still participates in the exchange."""
    def fn(ctx, rank):
        # colors: even/odd; keys: descending => new ranks reversed
        sub = ctx.split(rank % 2, key=-rank, tag=3)
        members = [r for r in range(6) if r % 2 == rank % 2]
        expect_rank = list(reversed(members)).index(rank)
        assert sub.size == 3 and sub.rank == expect_rank, \
            (rank, sub.rank)
        x = np.full(7, float(rank), np.float32)
        sub.allreduce(x)
        assert x[0] == sum(members), (rank, x[0])
        # subgroup identity
        assert f"s3.1.c{rank % 2}" in sub.group_tag()
        # opt-out: rank 5 sits this one out
        solo = ctx.split(-1 if rank == 5 else 0, tag=5)
        if rank == 5:
            assert solo is None
        else:
            assert solo.size == 5
            solo.barrier()
            solo.close()
        sub.close()
        return True

    assert all(spawn_topo(6, 3, fn))


def test_split_subgroup_full_stack():
    """A split subgroup is a full communicator: all collectives, fresh
    tag/slot namespace, working plan cache, and async-engine lanes."""
    def fn(ctx, rank):
        sub = ctx.split_by_host(tag=1)
        assert sub.size == 2 and sub.rank == rank % 2
        base = (rank // 2) * 2
        # collectives battery
        x = np.full(64, float(rank + 1), np.float32)
        sub.allreduce(x)
        assert x[0] == (base + 1) + (base + 2)
        b = np.full(8, float(rank), np.float32)
        sub.broadcast(b, root=1)
        assert b[0] == base + 1
        g = sub.allgather(np.full(4, float(rank), np.float32))
        assert g.shape == (2, 4) and g[1][0] == base + 1
        rs = sub.reduce_scatter(np.arange(6, dtype=np.float32))
        sub.barrier()
        assert rs.size == 3
        # plan cache lives per sub-context
        p = sub.allreduce_plan(x, tag=9)
        for _ in range(3):
            x[:] = 1.0
            p()
            assert x[0] == 2.0
        snap = sub.metrics()
        assert snap["plan_hits"] >= 2, snap["plan_hits"]
        assert snap["group"] == sub.group_tag()
        # async lanes fork from the split group
        with sub.async_engine(lanes=2) as eng:
            works = [eng.allreduce_async(
                np.full(32, float(sub.rank + 1), np.float32))
                for _ in range(4)]
            for w in works:
                out = w.wait(timeout=30)
                assert out[0] == 3.0, out[0]
        sub.close()
        return True

    assert all(spawn_topo(4, 2, fn, timeout=90))


def test_split_of_split_nested():
    """Nested splits: split a 2x3 world by host, then split each host
    group again; tags nest in the group namespace."""
    def fn(ctx, rank):
        host = ctx.split_by_host(tag=2)
        pair = host.split(0 if host.rank < 2 else 1, tag=4)
        assert "/" in pair.group_tag(), pair.group_tag()
        x = np.full(5, 1.0, np.float32)
        pair.allreduce(x)
        assert x[0] == pair.size
        pair.close()
        host.close()
        return True

    assert all(spawn_topo(6, 3, fn))


def test_concurrent_splits_store_key_hygiene():
    """Satellite (store key hygiene): two SIMULTANEOUS split() calls per
    rank — different tags, one shared physical store — must never read
    each other's color/bootstrap keys. Both resulting subgroups verify a
    collective."""
    def fn(ctx, rank):
        results = {}
        errors = []

        def do_split(name, color, tag):
            try:
                sub = ctx.split(color, key=rank, tag=tag)
                x = np.full(16, float(rank + 1), np.float32)
                sub.allreduce(x)
                results[name] = (sub.size, float(x[0]), sub.group_tag())
                sub.close()
            except BaseException as e:  # noqa: BLE001
                errors.append((name, e))

        # rows: {0,1,2} x {3,4,5}; cols: {0,3} x {1,4} x {2,5} — issued
        # CONCURRENTLY from two threads over the same HashStore.
        t1 = threading.Thread(target=do_split,
                              args=("row", rank // 3, 100))
        t2 = threading.Thread(target=do_split,
                              args=("col", rank % 3, 200))
        t1.start(); t2.start(); t1.join(60); t2.join(60)
        assert not errors, errors
        row_base = (rank // 3) * 3
        assert results["row"] == (
            3, float(sum(r + 1 for r in range(row_base, row_base + 3))),
            f"s100.1.c{rank // 3}")
        assert results["col"][0] == 2
        assert results["col"][1] == float((rank % 3 + 1) +
                                          (rank % 3 + 4)), results["col"]
        return True

    assert all(spawn_topo(6, 3, fn, timeout=120, context_timeout=60))


def test_sequential_same_tag_splits_fresh_generation():
    """Same tag reused sequentially: the per-tag generation advances, so
    the second split reads fresh keys (stale-key reuse would deliver the
    FIRST split's colors)."""
    def fn(ctx, rank):
        a = ctx.split(rank % 2, tag=7)
        b = ctx.split(rank // 2, tag=7)  # different grouping, same tag
        assert "s7.1." in a.group_tag() and "s7.2." in b.group_tag()
        x = np.full(4, 1.0, np.float32)
        b.allreduce(x)
        assert x[0] == b.size
        a.close(); b.close()
        return True

    assert all(spawn_topo(4, 2, fn))


def test_split_tuning_election_scoped_per_group():
    """Two sibling subgroups run tune() concurrently over one shared
    store: the election keys are scoped by the group tag, so each group
    installs its own (size-consistent) table instead of racing for
    'tpucoll/tuning/<gen>'."""
    from gloo_tpu import tuning

    def fn(ctx, rank):
        sub = ctx.split_by_host(tag=11)
        table = tuning.tune(sub, min_bytes=1 << 10, max_bytes=1 << 12,
                            iters=2, warmup=1)
        installed = tuning.installed_table(sub)
        assert installed, "no table installed on the subgroup"
        x = np.full(256, 1.0, np.float32)
        sub.allreduce(x)  # dispatch off the installed table
        assert x[0] == sub.size
        sub.close()
        return json.dumps(table)[:1]

    assert all(spawn_topo(4, 2, fn, timeout=120, context_timeout=60))


# ---------------------------------------------------------------------------
# hierarchical collectives
# ---------------------------------------------------------------------------

def _hier_battery(ctx, rank, size, rph):
    hosts = size // rph
    # allreduce: consensus + equality with the flat ring on exact ints
    z = np.arange(1 << 10, dtype=np.float32) + rank
    flat = z.copy()
    ctx.allreduce(z, algorithm="hier", tag=1)
    ctx.allreduce(flat, algorithm="ring", tag=2)
    np.testing.assert_array_equal(z, flat)
    # ops other than sum
    m = np.full(17, float(rank), np.float32)
    ctx.allreduce(m, op="max", algorithm="hier", tag=3)
    assert m[0] == size - 1
    # broadcast from a non-leader root and from a leader root
    for root in (rph - 1, 0):
        b = np.full(33, float(rank * 10), np.float32)
        ctx.broadcast(b, root=root, algorithm="hier", tag=4)
        assert np.all(b == root * 10), (rank, root, b[0])
    # allgather ordering
    g = ctx.allgather(np.full(3, float(rank), np.float32),
                      algorithm="hier", tag=5)
    assert g.shape == (size, 3)
    assert [g[r][0] for r in range(size)] == list(map(float, range(size)))
    # ragged reduce_scatter vs flat
    counts = [i + 1 for i in range(size)]
    src = np.arange(sum(counts), dtype=np.float32) * (rank + 1)
    out_h = ctx.reduce_scatter(src, recv_counts=counts, algorithm="hier",
                               tag=6)
    out_f = ctx.reduce_scatter(src, recv_counts=counts, algorithm="ring",
                               tag=7)
    np.testing.assert_array_equal(out_h, out_f)
    ctx.barrier(algorithm="hier", tag=8)
    return hosts


def test_hier_collectives_p4():
    assert all(spawn_topo(
        4, 2, lambda c, r: _hier_battery(c, r, 4, 2), timeout=90))


def test_hier_collectives_p6():
    assert all(spawn_topo(
        6, 3, lambda c, r: _hier_battery(c, r, 6, 3), timeout=120,
        context_timeout=60))


def test_hier_interleaved_host_assignment():
    """Ranks NOT grouped contiguously by host (round-robin placement):
    the grouped-order permutations in hier allgather/reduce_scatter must
    still produce global-rank-ordered results."""
    assert all(spawn_topo(
        6, 3, lambda c, r: _hier_battery(c, r, 6, 3), timeout=120,
        context_timeout=60, host_of=lambda r: r % 2))


def test_hier_degrades_on_flat_topology():
    """kHier on a flat topology (no overrides => one host) dispatches
    the flat schedule — same results, no error, and the flight recorder
    shows the degraded (non-hier) algorithm."""
    from tests.harness import spawn

    def fn(ctx, rank):
        x = np.full(512, float(rank + 1), np.float32)
        ctx.allreduce(x, algorithm="hier", tag=1)
        assert x[0] == 6.0, x[0]
        algos = [e.get("algo") for e in ctx.flightrec()["events"]
                 if e.get("op") == "allreduce"]
        assert algos and algos[-1] != "hier", algos
        ctx.barrier(algorithm="hier")
        return True

    assert all(spawn(3, fn))


def test_hier_auto_election_from_tuned_table():
    """A tuned table whose hier arm measures cheapest is elected by
    plain kAuto on a non-flat topology (flight recorder shows the
    resolved algorithm), and stays un-elected under TPUCOLL_HIER_AUTO=0
    (subprocess arm)."""
    table = {"version": 1, "entries": [
        {"collective": "allreduce", "algorithm": "hier", "world_size": 4,
         "dtype": "float32", "bucket": 12, "cost_us": 1.0},
        {"collective": "allreduce", "algorithm": "ring", "world_size": 4,
         "dtype": "float32", "bucket": 12, "cost_us": 1000.0},
    ]}

    def fn(ctx, rank):
        from gloo_tpu import tuning
        tuning.install_table(ctx, table)
        x = np.full(1024, 1.0, np.float32)  # 4 KiB = bucket 12
        ctx.allreduce(x, tag=1)
        assert x[0] == 4.0
        algos = [e.get("algo") for e in ctx.flightrec()["events"]
                 if e.get("op") == "allreduce"]
        assert algos[-1] == "hier", algos
        return True

    assert all(spawn_topo(4, 2, fn))

    # TPUCOLL_HIER_AUTO=0: the hier arm leaves the electable set.
    body = textwrap.dedent(f"""
        import sys, threading
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        import gloo_tpu
        from gloo_tpu import tuning
        table = {table!r}
        store = gloo_tpu.HashStore()
        def worker(rank, errs):
            try:
                ctx = gloo_tpu.Context(rank, 4, timeout=30)
                ctx.set_host_id("h%d" % (rank // 2))
                ctx.connect_full_mesh(store, gloo_tpu.Device())
                tuning.install_table(ctx, table)
                x = np.full(1024, 1.0, np.float32)
                ctx.allreduce(x, tag=1)
                algos = [e.get("algo") for e in ctx.flightrec()["events"]
                         if e.get("op") == "allreduce"]
                assert algos[-1] != "hier", algos
                ctx.close()
            except BaseException as e:
                errs.append((rank, e))
        errs = []
        ts = [threading.Thread(target=worker, args=(r, errs))
              for r in range(4)]
        [t.start() for t in ts]; [t.join(60) for t in ts]
        assert not errs, errs
        print("HIER-AUTO-OFF-OK")
    """)
    result = subprocess.run(
        [sys.executable, "-c", body], capture_output=True, text=True,
        timeout=180, env=dict(os.environ, TPUCOLL_HIER_AUTO="0"))
    assert result.returncode == 0, (result.stdout, result.stderr[-2000:])
    assert "HIER-AUTO-OFF-OK" in result.stdout


def test_hier_failure_names_subgroup(tmp_path):
    """A peer death mid-kHier surfaces TYPED with the failing phase, the
    subgroup tag, and the subgroup->global rank map in the message."""
    def fn(ctx, rank):
        # One healthy pass first, so the hier sub-groups exist before
        # the death (their creation is a collective of its own).
        warm = np.ones(64, np.float32)
        ctx.allreduce(warm, algorithm="hier", tag=1)
        if rank == 3:
            # Die mid-schedule: close the transport (and the split
            # sub-meshes with it) under the other ranks' feet.
            ctx.close()
            return "closed"
        try:
            x = np.full(1 << 12, 1.0, np.float32)
            ctx.allreduce(x, algorithm="hier", tag=2, timeout=5.0)
        except gloo_tpu.IoError as e:
            msg = str(e)
            assert "hier allreduce" in msg, msg
            assert "subgroup" in msg, msg
            assert "->" in msg, msg  # the rank map
            return "failed-typed"
        return "no-error"

    out = spawn_topo(4, 2, fn, timeout=60)
    assert out[3] == "closed"
    # rank 2 shares a host with the dead rank: its intra-host phase (or
    # leader phase) must fail typed naming the subgroup.
    assert out[2] == "failed-typed", out


# ---------------------------------------------------------------------------
# flightrec group partitioning (satellite)
# ---------------------------------------------------------------------------

def test_flightrec_groups_no_cross_group_desync(tmp_path):
    """Two disjoint split groups legitimately run DIFFERENT schedules.
    Partitioned by group tag (merge_by_tag), each analyzes clean; a
    naive merge of the same docs WOULD report a desync — the regression
    this partitioning exists to prevent."""
    dumps = str(tmp_path)

    def fn(ctx, rank):
        sub = ctx.split(rank // 2, key=rank, tag=21)
        if rank < 2:   # group A: allreduces
            for i in range(4):
                sub.allreduce(np.ones(64, np.float32), tag=i)
        else:          # group B: broadcasts + barrier (different fps)
            for i in range(3):
                sub.broadcast(np.ones(32, np.float32), root=0, tag=i)
            sub.barrier(tag=9)
        tag = sub.group_tag().replace("/", ".")
        sub.flightrec_dump(os.path.join(
            dumps, f"flightrec-rank{sub.rank}-g{tag}.json"))
        sub.close()
        return sub.group_tag()

    tags = spawn_topo(4, 2, fn)
    groups = frmod.merge_by_tag(dumps)
    assert len(groups) == 2, list(groups)
    for tag, merged in groups.items():
        verdict = frmod.analyze(merged)
        assert verdict["kind"] == "ok", (tag, verdict)
        assert not verdict["desync"], (tag, verdict)
    # The control: comparing ACROSS the partitions reintroduces the
    # false positive (rank r of A vs rank r of B ran different
    # schedules, same cseq range — the fingerprints diverge).
    tails = {}
    for gi, merged in enumerate(groups.values()):
        for r, doc in merged["ranks"].items():
            tails[gi * 2 + r] = doc.get("events", [])
    assert frmod.detect_desync(tails) is not None
    # Dump docs carry the group tag.
    assert all(doc.get("group") for m in groups.values()
               for doc in m["ranks"].values())
    # The CLI viewer partitions the same way and exits clean.
    view = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "flightrec_view.py"),
         dumps, "--check"], capture_output=True, text=True, timeout=60)
    assert view.returncode == 0, (view.stdout, view.stderr)
    assert "group" in view.stdout


def test_metrics_group_label():
    """Subgroup snapshots carry the group tag; the Prometheus exposition
    labels every family with it."""
    from gloo_tpu.utils.metrics import to_prometheus

    def fn(ctx, rank):
        sub = ctx.split_by_host(tag=31)
        sub.allreduce(np.ones(32, np.float32))
        snap = sub.metrics()
        assert snap["group"] == sub.group_tag() != ""
        expo = to_prometheus(snap)
        assert f'group="{snap["group"]}"' in expo
        # root context stays unlabeled
        root_expo = to_prometheus(ctx.metrics())
        assert 'group=' not in root_expo
        sub.close()
        return True

    assert all(spawn_topo(4, 2, fn))
