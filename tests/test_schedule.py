"""Collective schedule plane (csrc/tpucoll/schedule/): IR round trips,
the static verifier's typed rejections, generator families proven
byte-identical to the native algorithms through real multiprocess
groups, plan-cache integration (zero-allocation warm replays, install/
clear invalidation including async-lane sub-contexts), election
dispatch observed through the tracer and flight recorder, the
TPUCOLL_SCHEDULE_FILE hook, the sweep smoke, and same-seed chaos
determinism with schedules installed.

Dispatch decisions are asserted through the tracer/flightrec algorithm
labels ("sched:<name>"), so these tests observe the native dispatcher
itself, not a Python re-implementation of it.
"""

from __future__ import annotations

import contextlib
import json
import os

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import _lib, schedule
from tests.harness import spawn


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _spans(events, name):
    return [e["args"].get("detail") for e in events if e["name"] == name]


def _elect(table, collective, world, nbytes, dtype=""):
    """Add a single election for (collective, world, bucket(nbytes))."""
    name = table["schedules"][0]["name"]
    table = json.loads(json.dumps(table))
    table["elections"] = [{
        "collective": collective, "world_size": world, "dtype": dtype,
        "bucket": nbytes.bit_length() - 1, "schedule": name,
    }]
    return table


RING = {"kind": "ring", "a": 1}


def _fixture(steps, chunks=2, scratch=2, collective="allreduce", world=2):
    return {"version": 1, "schedules": [{
        "name": "fix", "collective": collective, "world_size": world,
        "chunks": chunks, "scratch": scratch, "steps": steps}]}


# A correct staged P=2 exchange of chunk 0 (send + recv-to-slot + fold).
_GOOD_C0 = [
    {"op": "send", "peer": RING, "chunk": 0},
    {"op": "recv", "peer": RING, "chunk": 0, "slot": 0},
    {"op": "reduce_local", "chunk": 0, "slot": 0, "deps": [0, 1]},
]


# ---- generators + verifier (context-free) ----------------------------------


def test_generator_families_verify():
    """Every family generates + statically verifies across a world grid
    (tc_schedule_generate runs the verifier before returning)."""
    fams = schedule.families()
    assert {"ring", "hd", "bcube", "ring_bf16", "hier",
            "ring_rs", "ring_ag", "hd_rs", "hd_ag"} <= set(fams)
    for world in (1, 2, 3, 4, 6, 8):
        for fam in fams:
            if fam.startswith("hd") and world & (world - 1):
                with pytest.raises(gloo_tpu.Error, match="power of two"):
                    schedule.generate(fam, world)
                continue
            t = schedule.generate(fam, world)
            s = t["schedules"][0]
            assert s["world_size"] == world
            assert s["name"]


def test_generator_params():
    """Pipelined-ring depth and hier ranks_per_host parameterize the
    emitted program; unknown params and families fail loudly."""
    flat = schedule.generate("ring", 4, {"depth": 1})["schedules"][0]
    deep = schedule.generate("ring", 4, {"depth": 4})["schedules"][0]
    assert len(deep["steps"]) > len(flat["steps"])
    h = schedule.generate("hier", 6, {"ranks_per_host": 3})["schedules"][0]
    assert h["name"] == "hier_p6_h3"
    with pytest.raises(gloo_tpu.Error, match="no param"):
        schedule.generate("ring", 4, {"bogus": 1})
    with pytest.raises(gloo_tpu.Error, match="unknown schedule family"):
        schedule.generate("nope", 4)
    with pytest.raises(gloo_tpu.Error, match="divide"):
        schedule.generate("hier", 6, {"ranks_per_host": 4})


def test_json_round_trip():
    """generate -> serialize -> parse -> serialize is a fixed point."""
    for fam in ("ring", "hd", "bcube", "ring_bf16", "hier"):
        t = schedule.generate(fam, 4)
        once = json.dumps(t, sort_keys=True)
        ctx = gloo_tpu.Context(0, 4)  # install needs no transport
        schedule.install(ctx, t)
        again = schedule.installed(ctx)
        assert json.dumps(again, sort_keys=True) == once, fam


def test_verifier_rejects_chunk_reduced_twice():
    bad = _fixture(_GOOD_C0 + [
        {"op": "reduce_local", "chunk": 0, "slot": 0, "deps": [2],
         "note": "double_fold"}])
    with pytest.raises(gloo_tpu.Error) as ei:
        schedule.verify(bad)
    assert "chunk_reduced_twice" in str(ei.value)
    assert "double_fold" in str(ei.value)  # errors name the step


def test_verifier_rejects_undelivered():
    with pytest.raises(gloo_tpu.Error) as ei:
        schedule.verify(_fixture(list(_GOOD_C0)))  # chunk 1 never moves
    assert "undelivered" in str(ei.value)
    assert "chunk 1" in str(ei.value)


def test_verifier_rejects_dependency_cycle():
    bad = _fixture([
        {"op": "send", "peer": RING, "chunk": 0, "deps": [1]},
        {"op": "recv", "peer": RING, "chunk": 0, "slot": 0, "deps": [0]},
        {"op": "reduce_local", "chunk": 0, "slot": 0, "deps": [0, 1]},
    ])
    with pytest.raises(gloo_tpu.Error, match="dependency_cycle"):
        schedule.verify(bad)


def test_verifier_rejects_unsynchronized_wire_hazard():
    """A fold racing an in-flight send with no dependency path is the
    hazard class the closure rule exists for."""
    bad = _fixture([
        {"op": "send", "peer": RING, "chunk": 0},
        {"op": "recv_reduce", "peer": RING, "chunk": 0, "slot": 0},
    ] + [
        {"op": "send", "peer": RING, "chunk": 1, "deps": [1]},
        {"op": "recv", "peer": RING, "chunk": 1, "slot": 1, "deps": [1]},
        {"op": "reduce_local", "chunk": 1, "slot": 1, "deps": [2, 3]},
    ])
    with pytest.raises(gloo_tpu.Error, match="hazard"):
        schedule.verify(bad)


def test_verifier_rejects_pipeline_on_non_codec_step():
    """The pipeline-depth attribute names a codec sub-block walk; on a
    wire or fold step there is nothing to split, so the verifier
    rejects it instead of silently ignoring the attribute."""
    bad = _fixture(_GOOD_C0 + [
        {"op": "send", "peer": RING, "chunk": 1, "pipeline": 4,
         "note": "piped_send"},
        {"op": "recv", "peer": RING, "chunk": 1, "slot": 1},
        {"op": "reduce_local", "chunk": 1, "slot": 1, "deps": [3, 4]},
    ])
    with pytest.raises(gloo_tpu.Error) as ei:
        schedule.verify(bad)
    assert "pipeline depth only applies to encode/decode" in str(ei.value)
    assert "piped_send" in str(ei.value)


def test_verifier_rejects_pipeline_out_of_range():
    """Depth 0 and depths beyond the engine ceiling (kMaxPipelineDepth
    = 32) fail at parse/verify, not at lowering."""
    for depth in (0, 33):
        bad = _fixture(_GOOD_C0 + [
            {"op": "send", "peer": RING, "chunk": 1},
            {"op": "recv", "peer": RING, "chunk": 1, "slot": 1,
             "pipeline": depth},
            {"op": "reduce_local", "chunk": 1, "slot": 1, "deps": [3, 4]},
        ])
        with pytest.raises(gloo_tpu.Error, match="pipeline"):
            schedule.verify(bad)


def test_pipeline_attribute_round_trips_on_codec_steps():
    """pipeline > 1 on encode/decode verifies and survives the JSON
    round trip (omit-default emit: depth 1 disappears)."""
    t = schedule.generate("ring_bf16", 2)
    piped = 0
    for st in t["schedules"][0]["steps"]:
        if st["op"] in ("encode", "decode"):
            st["pipeline"] = 4
            piped += 1
    assert piped > 0
    schedule.verify(t)
    ctx = gloo_tpu.Context(0, 2)
    schedule.install(ctx, t)
    back = schedule.installed(ctx)
    for st in back["schedules"][0]["steps"]:
        if st["op"] in ("encode", "decode"):
            assert st["pipeline"] == 4
        else:
            assert "pipeline" not in st


def test_verify_accepts_correct_fixture():
    full = _GOOD_C0 + [
        {"op": "send", "peer": RING, "chunk": 1},
        {"op": "recv", "peer": RING, "chunk": 1, "slot": 1},
        {"op": "reduce_local", "chunk": 1, "slot": 1, "deps": [3, 4]},
    ]
    schedule.verify(_fixture(full))


def test_duplicate_json_key_rejected_with_path():
    """Strict parsing (common/json.h): duplicate object keys fail
    loudly, naming the offending key's dotted path."""
    t = schedule.generate("ring", 2)
    raw = json.dumps(t)
    # Duplicate a step-level key: "op" appears twice in steps[0].
    needle = '"op": "send"'
    assert needle in raw
    dup = raw.replace(needle, '"op": "send", "op": "send"', 1)
    with pytest.raises(gloo_tpu.Error) as ei:
        schedule.verify(dup)
    msg = str(ei.value)
    assert "duplicate key" in msg
    assert "steps[0].op" in msg
    # Top-level duplicate too.
    dup2 = raw[:-1] + ', "version": 1}'
    with pytest.raises(gloo_tpu.Error, match="duplicate key"):
        schedule.verify(dup2)


def test_install_requires_connect_worthy_table():
    """Malformed tables and semantically invalid schedules never
    install — and a failed install leaves the previous plane intact."""
    ctx = gloo_tpu.Context(0, 2)
    good = schedule.generate("ring", 2)
    schedule.install(ctx, good)
    assert schedule.installed(ctx) is not None
    with pytest.raises(gloo_tpu.Error):
        schedule.install(ctx, "{not json")
    with pytest.raises(gloo_tpu.Error, match="undelivered"):
        schedule.install(ctx, _fixture(list(_GOOD_C0)))
    still = schedule.installed(ctx)
    assert still["schedules"][0]["name"] == good["schedules"][0]["name"]
    schedule.clear(ctx)
    assert schedule.installed(ctx) is None


def test_list_and_describe():
    ctx = gloo_tpu.Context(0, 2)
    t = schedule.merge(schedule.generate("ring", 2),
                       schedule.generate("hd", 4))
    schedule.install(ctx, t)
    listing = {s["name"]: s for s in schedule.list_schedules(ctx)}
    assert listing["ring_p2"]["resolved"] == 1
    assert listing["hd_p4"]["resolved"] == 0  # wrong world: carried only
    assert listing["ring_p2"]["collective"] == "allreduce"
    d = schedule.describe(ctx, "ring_p2")
    assert d["schedules"][0]["steps"]
    with pytest.raises(gloo_tpu.Error, match="no installed"):
        schedule.describe(ctx, "nope")


# ---- equivalence vs native (real groups) -----------------------------------


ALLREDUCE_FAMILIES = [
    ("ring", {}),
    ("ring", {"depth": 2}),
    ("ring", {"depth": 4}),
    ("hd", {}),
    ("bcube", {}),
    ("hier", {"ranks_per_host": 2}),
]


@pytest.mark.parametrize("fam,params", ALLREDUCE_FAMILIES,
                         ids=lambda v: str(v))
@pytest.mark.parametrize("world", [2, 3, 4])
def test_allreduce_matches_native(fam, params, world):
    """Interpreter replays are byte-identical to the native dispatch,
    consensus-asserted: every rank compares its scheduled result to its
    native result AND all ranks' bytes agree. Integer-valued payloads
    make float addition exact, so fold order cannot blur the check."""
    if fam == "hd" and world & (world - 1):
        pytest.skip("hd needs a power-of-two world")
    if fam == "hier" and world % params["ranks_per_host"]:
        pytest.skip("ranks_per_host must divide world")

    def fn(ctx, rank):
        digests = []
        for count, dtype in ((1536, np.float32), (1000, np.int32),
                             (9, np.float64), (256, np.uint8)):
            base = (np.random.RandomState(77 + rank)
                    .randint(0, 50, size=count).astype(dtype))
            native = base.copy()
            ctx.allreduce(native)
            t = _elect(schedule.generate(fam, world, params), "allreduce",
                       world, count * base.itemsize)
            schedule.install(ctx, t)
            got = base.copy()
            ctx.allreduce(got)
            warm = base.copy()
            ctx.allreduce(warm)
            schedule.clear(ctx)
            assert np.array_equal(native, got), (fam, world, dtype)
            assert np.array_equal(native, warm), (fam, world, dtype)
            digests.append(got.tobytes())
        return digests

    results = spawn(world, fn, timeout=90)
    for per_rank in zip(*results):
        assert len(set(per_rank)) == 1  # consensus across ranks


@pytest.mark.parametrize("fam", ["ring_rs", "hd_rs"])
@pytest.mark.parametrize("world", [2, 3, 4])
def test_reduce_scatter_matches_native(fam, world):
    if fam == "hd_rs" and world & (world - 1):
        pytest.skip("hd needs a power-of-two world")

    def fn(ctx, rank):
        per = 96
        base = (np.random.RandomState(3 + rank)
                .randint(0, 40, size=per * world).astype(np.float32))
        native = ctx.reduce_scatter(base.copy())
        t = _elect(schedule.generate(fam, world), "reduce_scatter",
                   world, per * world * 4)
        schedule.install(ctx, t)
        got = ctx.reduce_scatter(base.copy())
        warm = ctx.reduce_scatter(base.copy())
        schedule.clear(ctx)
        assert np.array_equal(native, got)
        assert np.array_equal(native, warm)
        return got.tobytes()

    spawn(world, fn, timeout=60)


@pytest.mark.parametrize("fam", ["ring_ag", "hd_ag"])
@pytest.mark.parametrize("world", [2, 3, 4])
def test_allgather_matches_native(fam, world):
    if fam == "hd_ag" and world & (world - 1):
        pytest.skip("hd needs a power-of-two world")

    def fn(ctx, rank):
        per = 128
        base = (np.random.RandomState(11 + rank)
                .randint(0, 90, size=per).astype(np.int32))
        native = ctx.allgather(base)
        t = _elect(schedule.generate(fam, world), "allgather",
                   world, per * world * 4)
        schedule.install(ctx, t)
        got = ctx.allgather(base)
        warm = ctx.allgather(base)
        schedule.clear(ctx)
        assert np.array_equal(native, got)
        assert np.array_equal(native, warm)
        return got.tobytes()

    results = spawn(world, fn, timeout=60)
    assert len(set(results)) == 1


@pytest.mark.parametrize("world", [2, 3, 4])
def test_bf16_coded_schedule_needs_lossy_opt_in(world):
    """The generated bf16-wire ring only fires under the same
    float32+sum+wire="lossy" opt-in as the native coded arms; a plain
    allreduce with the same election falls through to native dispatch.
    Small-integer payloads round-trip bf16 exactly, so even the coded
    path must be byte-exact here."""
    def fn(ctx, rank):
        count = 384
        base = (np.random.RandomState(21 + rank)
                .randint(0, 60, size=count).astype(np.float32))
        expected = np.zeros(count, dtype=np.float32)
        for r in range(world):
            expected += (np.random.RandomState(21 + r)
                         .randint(0, 60, size=count).astype(np.float32))
        t = _elect(schedule.generate("ring_bf16", world), "allreduce",
                   world, count * 4)
        schedule.install(ctx, t)
        ctx.trace_start()
        coded = base.copy()
        ctx.allreduce(coded, wire="lossy")
        plain = base.copy()
        ctx.allreduce(plain)
        spans = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        schedule.clear(ctx)
        assert np.array_equal(expected, coded)
        assert np.array_equal(expected, plain)
        name = t["schedules"][0]["name"]
        assert f"sched:{name}" in spans
        # The plain call must NOT have used the coded schedule.
        assert spans.count(f"sched:{name}") == 1
        return True

    assert spawn(world, fn, timeout=60) == [True] * world


def test_uneven_recv_counts_fall_back_to_native():
    """Generated reduce-scatter schedules assume even chunk geometry;
    uneven recvCounts must ignore the election and still be correct."""
    def fn(ctx, rank):
        counts = [100, 156]
        base = (np.arange(256) % 13 + rank).astype(np.float32)
        native = ctx.reduce_scatter(base.copy(), recv_counts=counts)
        t = _elect(schedule.generate("ring_rs", 2), "reduce_scatter",
                   2, 256 * 4)
        schedule.install(ctx, t)
        got = ctx.reduce_scatter(base.copy(), recv_counts=counts)
        schedule.clear(ctx)
        assert np.array_equal(native, got)
        return True

    assert spawn(2, fn, timeout=30) == [True, True]


# ---- dispatch observability + elections ------------------------------------


def test_election_dispatch_visible_in_tracer_and_flightrec():
    def fn(ctx, rank):
        count = 512
        base = np.full(count, float(rank + 1), dtype=np.float32)
        t = _elect(schedule.generate("ring", 2, {"depth": 2}), "allreduce",
                   2, count * 4)
        name = t["schedules"][0]["name"]
        schedule.install(ctx, t)
        ctx.trace_start()
        x = base.copy()
        ctx.allreduce(x)
        spans = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        algos = [e["algo"] for e in ctx.flightrec()["events"]
                 if e["op"] == "allreduce"]
        schedule.clear(ctx)
        # After clear, native dispatch returns.
        y = base.copy()
        ctx.allreduce(y)
        assert np.array_equal(x, y)
        assert spans == [f"sched:{name}"]
        assert algos[-1] == f"sched:{name}"
        return True

    assert spawn(2, fn, timeout=30) == [True, True]


def test_election_exact_dtype_beats_wildcard():
    def fn(ctx, rank):
        count = 512
        nbytes = count * 4
        ring = schedule.generate("ring", 2)
        hd = schedule.generate("hd", 2)
        t = schedule.merge(ring, hd)
        t["elections"] = [
            {"collective": "allreduce", "world_size": 2, "dtype": "",
             "bucket": nbytes.bit_length() - 1, "schedule": "ring_p2"},
            {"collective": "allreduce", "world_size": 2,
             "dtype": "float32", "bucket": nbytes.bit_length() - 1,
             "schedule": "hd_p2"},
        ]
        schedule.install(ctx, t)
        ctx.trace_start()
        x = np.full(count, 1.0, dtype=np.float32)
        ctx.allreduce(x)            # exact float32 cell -> hd_p2
        y = np.full(count, 1, dtype=np.int32)
        ctx.allreduce(y)            # wildcard cell -> ring_p2
        spans = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        schedule.clear(ctx)
        assert spans == ["sched:hd_p2", "sched:ring_p2"], spans
        return True

    assert spawn(2, fn, timeout=30) == [True, True]


def test_unelected_sizes_use_native_dispatch():
    """An election binds ONE log2 bucket; other sizes stay native."""
    def fn(ctx, rank):
        t = _elect(schedule.generate("ring", 2), "allreduce", 2, 4096)
        schedule.install(ctx, t)
        ctx.trace_start()
        small = np.full(16, 1.0, dtype=np.float32)    # 64 B: not elected
        ctx.allreduce(small)
        hit = np.full(1024, 1.0, dtype=np.float32)    # 4 KiB: elected
        ctx.allreduce(hit)
        spans = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        schedule.clear(ctx)
        assert spans[0] != "sched:ring_p2"
        assert spans[1] == "sched:ring_p2"
        return True

    assert spawn(2, fn, timeout=30) == [True, True]


# ---- plan-cache integration ------------------------------------------------


def test_warm_replay_zero_registrations():
    """The acceptance headline: scheduled replays reach the identical
    zero-allocation steady state as native plans — ubuf_creates delta
    is 0 across a warm loop and plan hits accrue 1:1."""
    def fn(ctx, rank):
        x = np.full(2048, float(rank + 1), dtype=np.float32)
        t = _elect(schedule.generate("ring", 2, {"depth": 2}), "allreduce",
                   2, x.nbytes)
        schedule.install(ctx, t)
        ctx.allreduce(x, tag=1)  # builds the plan (miss)
        before = ctx.metrics()
        for _ in range(50):
            x[:] = rank + 1
            ctx.allreduce(x, tag=1)
        after = ctx.metrics()
        schedule.clear(ctx)
        assert x[0] == 3.0
        assert after["ubuf_creates"] == before["ubuf_creates"], \
            "scheduled steady-state loop registered buffers"
        assert after["plan_hits"] - before["plan_hits"] == 50
        assert after["plan_misses"] == before["plan_misses"]
        return True

    assert spawn(2, fn, timeout=60) == [True, True]


def test_install_and_clear_invalidate_plan_cache():
    """Schedule install/clear drops every cached plan, exactly like
    setTuningTable: a cached kAuto plan may embed a dispatch decision
    the new plane would make differently."""
    def fn(ctx, rank):
        x = np.full(1024, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)
        assert ctx.plan_cache_size() >= 1
        schedule.install(ctx, schedule.generate("ring", 2))
        assert ctx.plan_cache_size() == 0
        x[:] = rank + 1
        ctx.allreduce(x, tag=1)
        assert ctx.plan_cache_size() >= 1
        schedule.clear(ctx)
        assert ctx.plan_cache_size() == 0
        x[:] = rank + 1
        ctx.allreduce(x, tag=1)
        assert x[0] == 3.0
        return True

    assert spawn(2, fn) == [True, True]


def test_install_invalidates_async_lane_caches():
    """Async lanes are forked sub-contexts with their own plan caches;
    installing a schedule plane on a lane's context clears that lane's
    cache through the same setScheduleTable path."""
    def fn(ctx, rank):
        eng = ctx.async_engine(lanes=2)
        try:
            x = np.full(512, float(rank + 1), dtype=np.float32)
            eng.allreduce_async(x).wait()
            lane_handles = [eng._lane_handle(k) for k in range(2)]
            filled = [h for h in lane_handles
                      if _lib.lib.tc_plan_cache_size(h) > 0]
            assert filled  # at least one lane built a plan
            payload = json.dumps(schedule.generate("ring", 2)).encode()
            for h in lane_handles:
                _lib.check(_lib.lib.tc_schedule_install(h, payload))
                assert _lib.lib.tc_plan_cache_size(h) == 0
            # lanes still work under the installed plane
            y = np.full(512, float(rank + 1), dtype=np.float32)
            eng.allreduce_async(y).wait()
            assert y[0] == 3.0
            for h in lane_handles:
                _lib.check(_lib.lib.tc_schedule_install(h, None))
        finally:
            eng.shutdown()
        return True

    assert spawn(2, fn, timeout=60) == [True, True]


# ---- TPUCOLL_SCHEDULE_FILE -------------------------------------------------


def test_schedule_file_env_installs_at_connect(tmp_path):
    path = os.path.join(tmp_path, "sched.json")
    t = _elect(schedule.generate("ring", 2, {"depth": 2}), "allreduce",
               2, 2048 * 4)
    name = t["schedules"][0]["name"]
    schedule.save(t, path)

    def fn(ctx, rank):
        inst = schedule.installed(ctx)
        assert inst is not None
        assert inst["schedules"][0]["name"] == name
        ctx.trace_start()
        x = np.full(2048, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        spans = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        assert x[0] == 3.0
        assert spans == [f"sched:{name}"]
        return True

    with _env(TPUCOLL_SCHEDULE_FILE=path):
        assert spawn(2, fn, timeout=30) == [True, True]


def test_schedule_file_env_malformed_is_loud(tmp_path):
    path = os.path.join(tmp_path, "bad.json")
    with open(path, "w") as f:
        f.write('{"version": 1, "schedules": [')  # truncated

    def fn(ctx, rank):  # pragma: no cover - must not connect
        return True

    with _env(TPUCOLL_SCHEDULE_FILE=path):
        with pytest.raises(AssertionError, match="schedule"):
            spawn(2, fn, timeout=30)
    missing = os.path.join(tmp_path, "nope.json")
    with _env(TPUCOLL_SCHEDULE_FILE=missing):
        with pytest.raises(AssertionError, match="cannot read"):
            spawn(2, fn, timeout=30)


# ---- sweep -----------------------------------------------------------------


def test_sweep_smoke_elects_consistently():
    """A tiny sweep runs real measurements, installs rank-identical
    bytes on every rank, and every elected cell names an installed,
    resolvable schedule."""
    def fn(ctx, rank):
        table = schedule.sweep(
            ctx, min_bytes=1 << 10, max_bytes=1 << 12, iters=2, warmup=1,
            candidates=[("ring", {"depth": 2}), ("hd", {})])
        inst = schedule.installed(ctx)
        names = {s["name"] for s in table.get("schedules", [])}
        for e in table.get("elections", []):
            assert e["schedule"] in names
            assert e["world_size"] == 2
        schedule.clear(ctx)
        return (json.dumps(table, sort_keys=True),
                json.dumps(inst, sort_keys=True))

    results = spawn(2, fn, timeout=120)
    tables = {r[0] for r in results}
    installs = {r[1] for r in results}
    assert len(tables) == 1  # rank-identical election
    assert len(installs) == 1


# ---- chaos determinism -----------------------------------------------------


def test_same_seed_chaos_identical_streams_with_schedules():
    """Schedules must not change wire determinism: the same-seed chaos
    workload produces identical per-rank (seq, op, fp) flightrec
    streams across two runs with a schedule plane installed."""
    from gloo_tpu import fault

    chaos = {"seed": 17, "faults": [
        {"when": {"rank": 1, "opcode": "data"},
         "action": "delay", "ms": 1, "prob": 0.5, "seed": 9}]}

    def workload():
        def fn(ctx, rank):
            t = schedule.merge(
                schedule.generate("ring", 2, {"depth": 2}),
                schedule.generate("ring_rs", 2))
            t["elections"] = [
                {"collective": "allreduce", "world_size": 2, "dtype": "",
                 "bucket": 12, "schedule": "ring_p2_k2"},
                {"collective": "reduce_scatter", "world_size": 2,
                 "dtype": "", "bucket": 12, "schedule": "ring_rs_p2"},
            ]
            schedule.install(ctx, t)
            x = np.arange(1024, dtype=np.float32)  # 4 KiB: bucket 12
            for i in range(5):
                x[:] = rank + i
                ctx.allreduce(x, tag=2 * i)
                ctx.reduce_scatter(x.copy(), tag=100 + i)
            ctx.barrier(tag=999)
            return [(e["seq"], e["op"], e["fp"])
                    for e in ctx.flightrec()["events"]]

        return spawn(2, fn, timeout=60)

    fault.install(chaos)
    try:
        first = workload()
        fault.install(chaos)  # reset firing state for the replay
        second = workload()
    finally:
        fault.clear()
    assert first == second
