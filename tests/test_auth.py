"""PSK-authenticated transport (the TLS-tier analog): matching keys form a
mesh; mismatched or missing keys are rejected at the handshake."""

import threading

import numpy as np
import pytest

import gloo_tpu


def _spawn_group(size, device_fn, timeout=5.0):
    store = gloo_tpu.HashStore()
    results = [None] * size
    errors = [None] * size

    def worker(rank):
        try:
            ctx = gloo_tpu.Context(rank, size, timeout=timeout)
            ctx.connect_full_mesh(store, device_fn(rank))
            x = np.full(100, float(rank + 1), dtype=np.float32)
            ctx.allreduce(x)
            results[rank] = float(x[0])
            ctx.close()
        except BaseException as exc:  # noqa: BLE001
            errors[rank] = exc

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    return results, errors


def test_matching_keys_connect():
    results, errors = _spawn_group(
        3, lambda rank: gloo_tpu.Device(auth_key="sesame-open"))
    assert errors == [None, None, None], errors
    assert results == [6.0, 6.0, 6.0]


def test_mismatched_key_rejected():
    def device_fn(rank):
        key = "right-key" if rank == 0 else "wrong-key"
        return gloo_tpu.Device(auth_key=key)

    results, errors = _spawn_group(2, device_fn, timeout=3.0)
    assert all(r is None for r in results)
    assert all(isinstance(e, gloo_tpu.IoError) for e in errors), errors


def test_plain_client_rejected_by_authenticated_mesh():
    def device_fn(rank):
        return gloo_tpu.Device(auth_key="secret" if rank == 0 else None)

    results, errors = _spawn_group(2, device_fn, timeout=3.0)
    assert all(r is None for r in results)
    assert all(e is not None for e in errors), errors


def test_connect_debug_records():
    """Every outbound connect attempt produces a structured record
    (reference: tcp/debug_data.h ConnectDebugData -> DebugLogger): a
    healthy 2-rank mesh logs the initiator's successful attempt with
    addresses and attempt=1."""
    records = []
    lock = threading.Lock()

    def logger(rec):
        with lock:
            records.append(rec)

    gloo_tpu.set_connect_debug_logger(logger)
    try:
        results, errors = _spawn_group(2, lambda rank: gloo_tpu.Device())
        assert errors == [None, None], errors
    finally:
        gloo_tpu.set_connect_debug_logger(None)

    ok = [r for r in records if r["ok"]]
    assert ok, records
    rec = ok[0]
    assert rec["self_rank"] == 1 and rec["peer_rank"] == 0
    assert rec["attempt"] == 1 and rec["error"] == ""
    assert rec["remote"].startswith("127.0.0.1:")
    assert rec["local"].startswith("127.0.0.1:")
