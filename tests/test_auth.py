"""PSK-authenticated transport (the TLS-tier analog): matching keys form a
mesh; mismatched or missing keys are rejected at the handshake."""

import threading

import numpy as np
import pytest

import gloo_tpu


def _spawn_group(size, device_fn, timeout=5.0):
    store = gloo_tpu.HashStore()
    results = [None] * size
    errors = [None] * size

    def worker(rank):
        try:
            ctx = gloo_tpu.Context(rank, size, timeout=timeout)
            ctx.connect_full_mesh(store, device_fn(rank))
            x = np.full(100, float(rank + 1), dtype=np.float32)
            ctx.allreduce(x)
            results[rank] = float(x[0])
            ctx.close()
        except BaseException as exc:  # noqa: BLE001
            errors[rank] = exc

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    return results, errors


def test_matching_keys_connect():
    results, errors = _spawn_group(
        3, lambda rank: gloo_tpu.Device(auth_key="sesame-open"))
    assert errors == [None, None, None], errors
    assert results == [6.0, 6.0, 6.0]


def test_mismatched_key_rejected():
    def device_fn(rank):
        key = "right-key" if rank == 0 else "wrong-key"
        return gloo_tpu.Device(auth_key=key)

    results, errors = _spawn_group(2, device_fn, timeout=3.0)
    assert all(r is None for r in results)
    assert all(isinstance(e, gloo_tpu.IoError) for e in errors), errors


def test_plain_client_rejected_by_authenticated_mesh():
    def device_fn(rank):
        return gloo_tpu.Device(auth_key="secret" if rank == 0 else None)

    results, errors = _spawn_group(2, device_fn, timeout=3.0)
    assert all(r is None for r in results)
    assert all(e is not None for e in errors), errors


def test_connect_debug_records():
    """Every outbound connect attempt produces a structured record
    (reference: tcp/debug_data.h ConnectDebugData -> DebugLogger): a
    healthy 2-rank mesh logs the initiator's successful attempt with
    addresses and attempt=1."""
    records = []
    lock = threading.Lock()

    def logger(rec):
        with lock:
            records.append(rec)

    gloo_tpu.set_connect_debug_logger(logger)
    try:
        results, errors = _spawn_group(2, lambda rank: gloo_tpu.Device())
        assert errors == [None, None], errors
    finally:
        gloo_tpu.set_connect_debug_logger(None)

    ok = [r for r in records if r["ok"]]
    assert ok, records
    rec = ok[0]
    assert rec["self_rank"] == 1 and rec["peer_rank"] == 0
    assert rec["attempt"] == 1 and rec["error"] == ""
    assert rec["remote"].startswith("127.0.0.1:")
    assert rec["local"].startswith("127.0.0.1:")


# ---- per-rank identity keyrings (docs/transport.md "Per-rank identity";
# reference analog: per-process TLS key/cert, tls/context.h:25-42) ----


ROOT = "launcher-root-secret"


def test_keyring_mesh_connects():
    rings = [gloo_tpu.derive_keyring(ROOT, r, 3) for r in range(3)]
    results, errors = _spawn_group(
        3, lambda rank: gloo_tpu.Device(keyring=rings[rank]))
    assert errors == [None, None, None], errors
    assert results == [6.0, 6.0, 6.0]


def test_keyring_mesh_encrypted_connects():
    rings = [gloo_tpu.derive_keyring(ROOT, r, 3) for r in range(3)]
    results, errors = _spawn_group(
        3, lambda rank: gloo_tpu.Device(keyring=rings[rank], encrypt=True))
    assert errors == [None, None, None], errors
    assert results == [6.0, 6.0, 6.0]


def test_keyring_for_wrong_rank_refused_locally():
    """The initiator refuses to use a keyring derived for a different
    rank — the cheapest impersonation (pass rank 1's keyring to a rank-2
    context) dies before any bytes hit the wire."""
    ring1 = gloo_tpu.derive_keyring(ROOT, 1, 3)

    def device_fn(rank):
        return gloo_tpu.Device(
            keyring=ring1 if rank == 2 else gloo_tpu.derive_keyring(
                ROOT, rank, 3))

    results, errors = _spawn_group(3, device_fn, timeout=3.0)
    assert all(r is None for r in results)
    assert all(e is not None for e in errors), errors


def test_keyring_credential_cannot_claim_another_rank():
    """THE leak-containment property: a forged keyring that claims rank 2
    but carries rank 1's pairwise keys cannot connect anywhere — rank 1's
    credential does not let its holder authenticate as rank 2 (the
    listener keys the challenge off the claimed rank, and K[0,2] is not
    derivable from rank 1's keyring)."""
    ring1 = gloo_tpu.derive_keyring(ROOT, 1, 3)
    assert ring1.startswith("tcring1:1:3:")
    forged = ring1.replace("tcring1:1:3:", "tcring1:2:3:", 1)

    def device_fn(rank):
        return gloo_tpu.Device(
            keyring=forged if rank == 2 else gloo_tpu.derive_keyring(
                ROOT, rank, 3))

    results, errors = _spawn_group(3, device_fn, timeout=3.0)
    # Ranks 0 and 1 talk to each other fine but never see a valid rank 2;
    # the forger is rejected at every handshake. Nobody hangs.
    assert results[2] is None
    assert errors[2] is not None, errors
    assert errors[0] is not None and errors[1] is not None, errors


def test_keyring_vs_psk_tier_rejected():
    def device_fn(rank):
        if rank == 0:
            return gloo_tpu.Device(keyring=gloo_tpu.derive_keyring(
                ROOT, 0, 2))
        return gloo_tpu.Device(auth_key=ROOT)

    results, errors = _spawn_group(2, device_fn, timeout=3.0)
    assert all(r is None for r in results)
    assert all(e is not None for e in errors), errors


def test_keyring_different_roots_rejected():
    def device_fn(rank):
        root = ROOT if rank == 0 else "some-other-root"
        return gloo_tpu.Device(keyring=gloo_tpu.derive_keyring(root, rank, 2))

    results, errors = _spawn_group(2, device_fn, timeout=3.0)
    assert all(r is None for r in results)
    assert all(e is not None for e in errors), errors


def test_keyring_valid_key_wrong_slot_rejected_at_routing():
    """A possessed key must not open a different rank's slot: a raw-wire
    client holding rank 1's REAL credential authenticates as rank 1 but
    targets the pairId rank 0 allocated for rank 2. The HMAC handshake
    succeeds (the key is genuine); the listener's routing check must then
    drop the connection instead of delivering it to the rank-2 pair."""
    import hashlib
    import hmac as pyhmac
    import socket
    import struct
    import tempfile
    import time

    store_dir = tempfile.mkdtemp()
    store = gloo_tpu.FileStore(store_dir)
    ring0 = gloo_tpu.derive_keyring(ROOT, 0, 3)
    ring1 = gloo_tpu.derive_keyring(ROOT, 1, 3)
    k01 = bytes.fromhex(ring1.split(":", 3)[3])[:32]  # slot 0 = K[0,1]

    state = {}

    def rank0():
        ctx = gloo_tpu.Context(0, 3, timeout=8.0)
        try:
            ctx.connect_full_mesh(store, gloo_tpu.Device(keyring=ring0))
            state["rank0"] = "connected"  # must NOT happen
        except gloo_tpu.Error:
            state["rank0"] = "timed out"  # ranks 1/2 never join the mesh

    # Play along with topology discovery: rank 0's connect_full_mesh
    # blocks on every rank's host fingerprint BEFORE it publishes its
    # rank blob (docs/topology.md), so the fake peers must answer.
    store.set("tc/topo/1", b"fake-host-1")
    store.set("tc/topo/2", b"fake-host-2")

    t0 = threading.Thread(target=rank0, daemon=True)
    t0.start()

    # Read rank 0's published blob: [u32 n][u32 addrLen][addr][u64 ids[n]]
    # where addr = [socklen][sockaddr_storage prefix] (address.cc).
    blob = None
    for _ in range(100):
        try:
            blob = bytes(store.get("tc/rank/0", timeout=0.1))
            break
        except gloo_tpu.Error:
            time.sleep(0.05)
    assert blob is not None
    n, alen = struct.unpack_from("<II", blob, 0)
    assert n == 3
    ab = blob[8:8 + alen]
    fam = struct.unpack_from("<H", ab, 4)[0]
    assert fam == socket.AF_INET, fam
    port = struct.unpack_from(">H", ab, 6)[0]
    host = socket.inet_ntoa(ab[8:12])
    ids = struct.unpack_from("<3Q", blob, 8 + alen)
    pair_for_rank2 = ids[2]

    # Raw keyring-tier handshake: claim rank 1 (we DO hold K[0,1]), but
    # target the slot rank 0 reserved for rank 2.
    s = socket.create_connection((host, int(port)), timeout=5)
    s.sendall(struct.pack("<IIQ", 0x7C011008, 0, pair_for_rank2))
    s.sendall(struct.pack("<I", 1))  # claimed rank
    nonce_i = b"\x11" * 16
    s.sendall(nonce_i)
    reply = b""
    while len(reply) < 48:
        chunk = s.recv(48 - len(reply))
        assert chunk, "listener closed before the challenge reply"
        reply += chunk
    nonce_l, srv_mac = reply[:16], reply[16:]
    transcript = (struct.pack("<Q", pair_for_rank2) +
                  struct.pack("<ii", 1, 0) + nonce_i + nonce_l)
    expect = pyhmac.new(k01, b"srv" + transcript, hashlib.sha256).digest()
    assert srv_mac == expect, "listener keyed the challenge off K[0,1]"
    s.sendall(pyhmac.new(k01, b"cli" + transcript, hashlib.sha256).digest())
    # Authentication succeeded — but routing must reject the identity/slot
    # mismatch by closing the connection (EOF), not delivering it.
    s.settimeout(5)
    assert s.recv(1) == b"", "expected EOF after routing rejection"
    s.close()

    t0.join(20)
    assert state.get("rank0") == "timed out", state
