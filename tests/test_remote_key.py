"""Host-plane one-sided put/get over serialized RemoteKeys.

Port of the reference's remote-key scenarios
(gloo/test/remote_key_test.cc:62-164: Get, Put, and bounds rejection)
onto this transport: keys are allgathered, gets pull every peer's region,
puts scatter one byte into every peer's region with no posted receive on
the target, and out-of-bounds put/get raise synchronously. Runs in
threads (mode 1) and across real processes (mode 2), plaintext and
encrypted.
"""

import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

import gloo_tpu
from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _exchange_keys(ctx, key: bytes):
    mine = np.frombuffer(key, dtype=np.uint8).copy()
    all_keys = ctx.allgather(mine)
    return [all_keys[r].tobytes() for r in range(ctx.size)]


@pytest.mark.parametrize("data_size", [1, 1024, 1000000])
@pytest.mark.parametrize("size", [2, 4])
def test_get(size, data_size):
    """Reference Get scenario: every rank pulls every peer's region."""

    def fn(ctx, rank):
        shared = np.full(data_size, rank, dtype=np.uint8)
        shared_buf = ctx.register(shared)
        local = np.zeros(data_size, dtype=np.uint8)
        local_buf = ctx.register(local)
        keys = _exchange_keys(ctx, shared_buf.get_remote_key())
        for i in range(ctx.size):
            if i == rank:
                continue
            local_buf.get(keys[i], slot=ctx.next_slot(), offset=0,
                          roffset=0, nbytes=data_size)
            local_buf.wait_recv()
            assert (local == i).all(), f"get from {i} corrupted"
        ctx.barrier()
        return True

    assert all(spawn(size, fn))


@pytest.mark.parametrize("size", [2, 4])
def test_put(size):
    """Reference Put scenario: rank r writes byte r at position r of every
    peer's exported region; targets post nothing."""

    def fn(ctx, rank):
        export = np.zeros(ctx.size, dtype=np.uint8)
        export_buf = ctx.register(export)
        local = np.full(ctx.size, rank, dtype=np.uint8)
        local_buf = ctx.register(local)
        keys = _exchange_keys(ctx, export_buf.get_remote_key())
        for i in range(ctx.size):
            if i == rank:
                continue
            local_buf.put(keys[i], offset=rank, roffset=rank, nbytes=1)
            local_buf.wait_send()
        ctx.barrier()
        # One-sided delivery is not ordered with the barrier message on
        # OTHER pairs, so poll briefly for the last writes.
        import time
        deadline = time.monotonic() + 5.0
        want = np.arange(ctx.size, dtype=np.uint8)
        want[rank] = 0
        while time.monotonic() < deadline:
            if all(export[j] == j for j in range(ctx.size) if j != rank):
                return True
            time.sleep(0.01)
        raise AssertionError(f"puts not delivered: {export}")

    assert all(spawn(size, fn))


def test_bounds_rejected():
    """Reference bounds checks: oversized offset/roffset/nbytes raise
    synchronously, before anything hits the wire."""

    def fn(ctx, rank):
        shared = np.zeros(128, dtype=np.uint8)
        shared_buf = ctx.register(shared)
        local = np.zeros(128, dtype=np.uint8)
        local_buf = ctx.register(local)
        keys = _exchange_keys(ctx, shared_buf.get_remote_key())
        peer = (rank + 1) % ctx.size
        for kwargs in ({"offset": 1_000_000_000, "nbytes": 1},
                       {"roffset": 1_000_000_000, "nbytes": 1},
                       {"nbytes": 1_000_000_000}):
            with pytest.raises(gloo_tpu.Error):
                local_buf.get(keys[peer], slot=ctx.next_slot(), **kwargs)
            with pytest.raises(gloo_tpu.Error):
                local_buf.put(keys[peer], **kwargs)
        ctx.barrier()
        return True

    assert all(spawn(2, fn))


def test_self_put_get():
    """Local put/get against a rank's own region short-circuits."""

    def fn(ctx, rank):
        region = np.zeros(16, dtype=np.uint8)
        region_buf = ctx.register(region)
        key = region_buf.get_remote_key()
        local = np.arange(16, dtype=np.uint8)
        local_buf = ctx.register(local)
        local_buf.put(key, offset=0, roffset=0, nbytes=16)
        local_buf.wait_send()
        assert (region == np.arange(16)).all()
        back = np.zeros(16, dtype=np.uint8)
        back_buf = ctx.register(back)
        back_buf.get(key, slot=ctx.next_slot(), nbytes=16)
        back_buf.wait_recv()
        assert (back == np.arange(16)).all()
        return True

    assert all(spawn(2, fn))


def test_get_encrypted():
    """One-sided reads ride the encrypted framing unchanged."""

    def fn(ctx, rank):
        shared = np.full(4096, rank + 10, dtype=np.uint8)
        shared_buf = ctx.register(shared)
        local = np.zeros(4096, dtype=np.uint8)
        local_buf = ctx.register(local)
        keys = _exchange_keys(ctx, shared_buf.get_remote_key())
        peer = (rank + 1) % ctx.size
        local_buf.get(keys[peer], slot=ctx.next_slot(), nbytes=4096)
        local_buf.wait_recv()
        assert (local == peer + 10).all()
        ctx.barrier()
        return True

    assert all(spawn(2, fn,
                     device_kwargs={"auth_key": "rk", "encrypt": True}))


def test_put_get_across_processes():
    """Mode 2: the full get+put dance across real OS processes."""
    store = tempfile.mkdtemp()
    size = 3

    def worker(rank):
        prog = textwrap.dedent("""
            import sys, time
            sys.path.insert(0, {repo!r})
            import numpy as np
            import gloo_tpu

            rank = {rank}; size = {size}
            store = gloo_tpu.FileStore({store!r})
            ctx = gloo_tpu.Context(rank, size, timeout=15.0)
            ctx.connect_full_mesh(store, gloo_tpu.Device())

            shared = np.full(65536, rank, dtype=np.uint8)
            shared_buf = ctx.register(shared)
            export = np.zeros(size, dtype=np.uint8)
            export_buf = ctx.register(export)
            k1 = np.frombuffer(shared_buf.get_remote_key(),
                               np.uint8).copy()
            k2 = np.frombuffer(export_buf.get_remote_key(),
                               np.uint8).copy()
            keys1 = ctx.allgather(k1)
            keys2 = ctx.allgather(k2)

            local = np.zeros(65536, dtype=np.uint8)
            local_buf = ctx.register(local)
            for i in range(size):
                if i == rank:
                    continue
                local_buf.get(keys1[i].tobytes(), slot=ctx.next_slot(),
                              nbytes=65536)
                local_buf.wait_recv()
                assert (local == i).all(), f"get from {{i}}"

            mine = np.full(size, rank, dtype=np.uint8)
            mine_buf = ctx.register(mine)
            for i in range(size):
                if i == rank:
                    continue
                mine_buf.put(keys2[i].tobytes(), offset=rank,
                             roffset=rank, nbytes=1)
                mine_buf.wait_send()
            ctx.barrier()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if all(export[j] == j for j in range(size) if j != rank):
                    break
                time.sleep(0.01)
            else:
                raise AssertionError(f"puts missing: {{export}}")
            ctx.close()
            print("OK")
        """).format(repo=_REPO, rank=rank, size=size, store=store)
        return subprocess.Popen([sys.executable, "-c", prog],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    procs = [worker(r) for r in range(size)]
    outs = [p.communicate(timeout=90) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, (out, err)
        assert "OK" in out


def test_put_notify_bound_buffer_semantics():
    """put(notify=True) is the reference BOUND-buffer contract
    (gloo/transport/buffer.h:16-41): a one-sided write into registered
    memory that completes a wait_recv on the exporting buffer — no recv
    ever posted. Ring exchange: rank r puts to its right neighbor."""

    def fn(ctx, rank):
        inbox = np.zeros(64, dtype=np.float64)
        inbox_buf = ctx.register(inbox)
        keys = _exchange_keys(ctx, inbox_buf.get_remote_key())
        right = (rank + 1) % ctx.size
        left = (rank - 1) % ctx.size

        payload = np.full(64, float(rank), dtype=np.float64)
        out_buf = ctx.register(payload)
        out_buf.put(keys[right], nbytes=64 * 8, notify=True)
        out_buf.wait_send()

        src = inbox_buf.wait_put()  # completes on the notify arrival
        assert src == left, (src, left)
        np.testing.assert_array_equal(inbox, np.full(64, float(left)))
        ctx.barrier()
        return True

    assert all(spawn(4, fn))


def test_put_notify_self():
    def fn(ctx, rank):
        region = np.zeros(8, dtype=np.float32)
        region_buf = ctx.register(region)
        key = region_buf.get_remote_key()
        src_buf = ctx.register(np.arange(8, dtype=np.float32))
        src_buf.put(key, nbytes=32, notify=True)
        src_buf.wait_send()
        assert region_buf.wait_put() == rank
        np.testing.assert_array_equal(region, np.arange(8))
        return True

    assert all(spawn(2, fn))
