"""Hierarchical DCN x ICI collectives (gloo_tpu/tpu/hierarchical.py).

Simulates H hosts x L chips inside the test environment two ways:
- threads: each "host" thread owns a disjoint subset of the virtual
  8-device CPU mesh plus its own host-plane Context (loopback + shm);
- processes: each subprocess forces its own private 4-device CPU
  platform and rendezvouses over a FileStore — the honest multi-host
  shape (separate runtimes, separate address spaces, DCN-analog TCP).

Reference analog: the host-workspace CUDA algorithms
(gloo/cuda_collectives_host.h local reduce -> CPU schedule -> local
broadcast; gloo/cuda_workspace.h:17-27 staging split)."""

import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu.tpu import HierarchicalGroup, make_hierarchical_ddp
from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _local_devices(rank: int, per_host: int):
    import jax
    devs = jax.devices()
    return devs[rank * per_host:(rank + 1) * per_host]


def test_hierarchical_allreduce_partials():
    """2 hosts x 4 devices: per-device partials reduce on-device, hosts
    combine over the host plane, result lands replicated."""
    hosts, per_host, n = 2, 4, 1 << 14

    def fn(ctx, rank):
        import jax
        devs = _local_devices(rank, per_host)
        group = HierarchicalGroup(ctx, devices=devs)
        # partial on device d (global index g): full of (g+1)
        partials = [jax.device_put(
            np.full(n, rank * per_host + d + 1, np.float32), devs[d])
            for d in range(per_host)]
        out = group.allreduce(partials)
        expect = sum(range(1, hosts * per_host + 1))  # 36
        assert isinstance(out, list) and len(out) == per_host
        for o in out:
            arr = np.asarray(o)
            assert arr.shape == (n,) and arr[0] == expect and \
                arr[-1] == expect
        return True

    assert all(spawn(hosts, fn, timeout=90, context_timeout=60))


def test_hierarchical_allreduce_single_array_and_ops():
    hosts = 2

    def fn(ctx, rank):
        import jax
        devs = _local_devices(rank, 4)
        group = HierarchicalGroup(ctx, devices=devs)
        x = jax.device_put(np.full(64, float(rank + 1), np.float32),
                           devs[0])
        out = group.allreduce(x, op="max")
        assert np.asarray(out)[0] == 2.0
        # numpy in -> numpy out
        y = np.full(64, float(rank + 2), np.float32)
        out2 = group.allreduce(y, op="sum")
        assert isinstance(out2, np.ndarray) and out2[0] == 5.0
        return True

    assert all(spawn(hosts, fn, timeout=60, context_timeout=40))


def test_hierarchical_rejects_data_sharded():
    def fn(ctx, rank):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        devs = _local_devices(rank, 4)
        group = HierarchicalGroup(ctx, devices=devs)
        mesh = Mesh(np.asarray(devs), ("local",))
        x = jax.device_put(np.arange(16, dtype=np.float32),
                           NamedSharding(mesh, PartitionSpec("local")))
        try:
            group.allreduce(x)
            return "no-error"
        except ValueError as e:
            return "rejected" if "PARTIALS" in str(e) else str(e)
        finally:
            group.barrier()

    assert spawn(2, fn, timeout=60) == ["rejected", "rejected"]


def test_hierarchical_mean_uneven_counts():
    """Host 0 contributes 3 partials, host 1 contributes 2: mean divides
    by the true global count (5), not hosts x fixed-L."""
    def fn(ctx, rank):
        import jax
        devs = _local_devices(rank, 4)
        group = HierarchicalGroup(ctx, devices=devs)
        nlocal = 3 if rank == 0 else 2
        partials = [jax.device_put(np.full(8, 10.0, np.float32), devs[d])
                    for d in range(nlocal)]
        out = group.mean(partials)
        assert np.allclose(np.asarray(out[0]), 10.0)
        return True

    assert all(spawn(2, fn, timeout=60))


def test_hierarchical_broadcast_allgather():
    def fn(ctx, rank):
        import jax
        devs = _local_devices(rank, 4)
        group = HierarchicalGroup(ctx, devices=devs)
        x = jax.device_put(np.full(32, float(rank + 1), np.float32),
                           devs[0])
        b = group.broadcast(x, root=1)
        assert np.asarray(b)[0] == 2.0
        g = group.allgather(x)
        assert g.shape == (2, 32)
        assert g[0, 0] == 1.0 and g[1, 0] == 2.0
        return True

    assert all(spawn(2, fn, timeout=60))


def test_hierarchical_ddp_training():
    """Two-level DDP: per-host 2-device mesh + cross-host grad averaging.
    Params must stay bit-identical across hosts and the loss must drop."""
    hosts, per_host = 2, 2

    def fn(ctx, rank):
        import jax
        import jax.numpy as jnp
        import optax
        devs = _local_devices(rank, per_host)
        group = HierarchicalGroup(ctx, devices=devs)

        def loss_fn(params, batch):
            x, y = batch
            pred = x @ params["w"] + params["b"]
            return jnp.mean((pred - y) ** 2)

        opt = optax.sgd(0.1)
        params = {"w": jnp.zeros((4, 1)), "b": jnp.zeros((1,))}
        opt_state = opt.init(params)
        step = make_hierarchical_ddp(loss_fn, opt, group)

        rng = np.random.RandomState(rank)
        w_true = np.arange(1.0, 5.0).reshape(4, 1).astype(np.float32)
        losses = []
        for it in range(30):
            x = rng.rand(8, 4).astype(np.float32)
            y = x @ w_true + 0.5
            params, opt_state, loss = step(params, opt_state, (x, y))
            losses.append(float(loss))
        group.barrier()
        return losses[0], losses[-1], np.asarray(params["w"]).ravel()

    results = spawn(hosts, fn, timeout=120, context_timeout=60)
    for first, last, _ in results:
        assert last < first * 0.1, (first, last)
    # Cross-host replica consistency: the whole point of the DCN hop.
    np.testing.assert_array_equal(results[0][2], results[1][2])


def test_hierarchical_cross_process():
    """Real separate runtimes: each subprocess forces a private 4-device
    CPU platform; the DCN analog is loopback TCP via FileStore. This is
    the deployment shape jax.distributed cannot cover (independent
    processes, no global mesh)."""
    store = tempfile.mkdtemp()
    body = textwrap.dedent("""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {repo!r})
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import gloo_tpu
        from gloo_tpu.tpu import HierarchicalGroup

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, 2, timeout=60)
        ctx.connect_full_mesh(gloo_tpu.FileStore({store!r}),
                              gloo_tpu.Device())
        devs = jax.devices()
        assert len(devs) == 4, devs
        group = HierarchicalGroup(ctx, devices=devs)
        partials = [jax.device_put(
            np.full(1 << 16, rank * 4 + d + 1, np.float32), devs[d])
            for d in range(4)]
        out = group.allreduce(partials)
        assert float(np.asarray(out[0])[0]) == 36.0
        # 256 KiB payload: the cross-"host" hop rode the shm plane.
        assert ctx.shm_stats()["tx_bytes"] > 0
        group.barrier()
        ctx.close()
        print("HIER-OK")
    """).format(repo=_REPO, store=store)
    procs = [subprocess.Popen([sys.executable, "-c", body, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for r in range(2)]
    outs = [p.communicate(timeout=180) for p in procs]
    for (stdout, stderr), p in zip(outs, procs):
        assert p.returncode == 0, (stdout, stderr[-3000:])
        assert "HIER-OK" in stdout


def test_hierarchical_host_plane_on_native_splits():
    """ISSUE 13: the host plane runs on NATIVE splits. Four host
    processes presenting as 2 simulated hosts x 2: HierarchicalGroup
    routes its collectives through the native kHier schedules (intra-
    host shm plane, leaders-only exchange) and exposes the intra-host /
    leader sub-communicators via Context.split — no ad-hoc per-group
    store bootstrap anywhere (the split's color exchange and subset
    mesh ride the context's own rendezvous namespace)."""
    from tests.test_group import spawn_topo

    def fn(ctx, rank):
        group = HierarchicalGroup(ctx, devices=[])
        assert group._hier_algo == "hier"
        # numpy path: the host hop IS the native hier allreduce.
        out = group.allreduce(np.full(512, float(rank + 1), np.float32))
        assert isinstance(out, np.ndarray) and out[0] == 10.0, out[0]
        b = group.broadcast(np.full(16, float(rank), np.float32), root=3)
        assert b[0] == 3.0
        g = group.allgather(np.full(4, float(rank), np.float32))
        assert g.shape == (4, 4) and g[2][0] == 2.0
        group.barrier()
        # native split planes, no side stores
        local = group.local_group()
        leaders = group.leader_group()
        assert local.size == 2 and local.group_tag() != ""
        x = np.full(8, 1.0, np.float32)
        local.allreduce(x)
        assert x[0] == 2.0
        if ctx.topology()["is_leader"]:
            assert leaders is not None and leaders.size == 2
            y = np.full(8, 1.0, np.float32)
            leaders.allreduce(y)
            assert y[0] == 2.0
        else:
            assert leaders is None
        return True

    assert all(spawn_topo(4, 2, fn, timeout=90))
