"""int8 block-quantized wire collectives (AllreduceAlgorithm kRingQ8Wire,
ISSUE 11): codec round-trip error bounds, per-hop error growth, the
cross-rank consensus contract (all ranks byte-identical), the q8
reduce_scatter variant, the wire= opt-in surface, lossy auto dispatch,
and same-seed fault-plane determinism over the new wire format.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu._lib import Error

from tests.harness import spawn

BLOCK = 256  # default TPUCOLL_Q8_BLOCK; tests that change it use subprocesses


# ---------------------------------------------------------------------------
# Codec properties (tc_q8_encode / tc_q8_decode round trips)
# ---------------------------------------------------------------------------

def test_q8_block_default():
    assert gloo_tpu.q8_block() == BLOCK


def test_q8_wire_bytes_layout():
    # One f32 scale per block plus one int8 code per element; ragged tail
    # unpadded.
    assert gloo_tpu.q8_wire_bytes(0) == 0
    assert gloo_tpu.q8_wire_bytes(1) == 4 + 1
    assert gloo_tpu.q8_wire_bytes(BLOCK) == 4 + BLOCK
    assert gloo_tpu.q8_wire_bytes(BLOCK + 1) == 2 * 4 + BLOCK + 1
    assert gloo_tpu.q8_wire_bytes(10 * BLOCK) == 10 * (4 + BLOCK)


@pytest.mark.parametrize("n", [1, 7, BLOCK - 1, BLOCK, BLOCK + 1,
                               4 * BLOCK + 13])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_q8_roundtrip_error_bound(n, seed):
    """Property: per element, |x - decode(encode(x))| <= max|block|/254
    (half a quantization step at scale = max|block|/127), modulo one ulp
    of slack for the scale division rounding."""
    rng = np.random.default_rng(seed)
    # Mix magnitudes so blocks see wide dynamic range.
    x = (rng.standard_normal(n) *
         10.0 ** rng.integers(-3, 4, size=n)).astype(np.float32)
    wire = gloo_tpu.q8_encode(x)
    assert wire.nbytes == gloo_tpu.q8_wire_bytes(n)
    y = gloo_tpu.q8_decode(wire, n)
    for start in range(0, n, BLOCK):
        blk = x[start:start + BLOCK]
        bound = np.abs(blk).max() / 254.0 * (1 + 1e-6)
        err = np.abs(blk - y[start:start + BLOCK]).max()
        assert err <= bound, (start, err, bound)


def test_q8_roundtrip_idempotent_and_zero_block():
    """decode(encode(x)) is a fixed point of the codec only up to scale
    re-derivation (the *127/127 roundtrip double-rounds — the reason the
    allgather phase forwards wire bytes verbatim); an all-zero block is
    exactly representable either way."""
    z = np.zeros(2 * BLOCK + 5, dtype=np.float32)
    assert np.array_equal(gloo_tpu.q8_decode(gloo_tpu.q8_encode(z), z.size),
                          z)
    # The decoded values stay within one further quantization step of a
    # second round trip even when not bit-identical.
    rng = np.random.default_rng(3)
    x = rng.standard_normal(3 * BLOCK).astype(np.float32)
    y1 = gloo_tpu.q8_decode(gloo_tpu.q8_encode(x), x.size)
    y2 = gloo_tpu.q8_decode(gloo_tpu.q8_encode(y1), x.size)
    for start in range(0, x.size, BLOCK):
        blk = y1[start:start + BLOCK]
        bound = np.abs(blk).max() / 254.0 * (1 + 1e-6)
        assert np.abs(blk - y2[start:start + BLOCK]).max() <= bound


def test_q8_hop_error_growth_bound():
    """Property: h requantization hops of a running sum stay within the
    sum of per-hop half-step bounds (the precision contract documented
    in docs/algorithms.md: error grows linearly with hop count)."""
    rng = np.random.default_rng(7)
    parts = [rng.standard_normal(4 * BLOCK).astype(np.float32)
             for _ in range(6)]
    exact = np.zeros(4 * BLOCK, dtype=np.float64)
    acc = parts[0].copy()
    bound = np.zeros(4 * BLOCK, dtype=np.float64)
    exact += parts[0].astype(np.float64)
    for part in parts[1:]:
        # One ring hop: quantize the running sum, peer dequantizes and
        # adds its own contribution.
        wire = gloo_tpu.q8_encode(acc)
        for start in range(0, acc.size, BLOCK):
            blk = acc[start:start + BLOCK]
            bound[start:start + BLOCK] += np.abs(blk).max() / 254.0
        acc = gloo_tpu.q8_decode(wire, acc.size) + part
        exact += part.astype(np.float64)
    # Final allgather quantization of the result.
    wire = gloo_tpu.q8_encode(acc)
    for start in range(0, acc.size, BLOCK):
        blk = acc[start:start + BLOCK]
        bound[start:start + BLOCK] += np.abs(blk).max() / 254.0
    final = gloo_tpu.q8_decode(wire, acc.size).astype(np.float64)
    slack = 1 + 1e-4  # f32 accumulation noise atop the quantization bound
    assert np.all(np.abs(final - exact) <= bound * slack + 1e-6)


def test_q8_hop_error_growth_bound_with_error_feedback():
    """Error-feedback variant of the hop walk above (the recurrence
    wire_ring.cc applies at every origin encode under TPUCOLL_WIRE_EF):
    each hop encodes (input + residual) and carries the new residual.
    Errors telescope — the residual itself IS the deviation, so the
    running sum stays within ~one hop's half-step of exact instead of
    the h-hop linear bound, no matter how many hops the walk takes."""
    rng = np.random.default_rng(7)
    parts = [rng.standard_normal(4 * BLOCK).astype(np.float32)
             for _ in range(24)]

    def walk(with_ef):
        exact = parts[0].astype(np.float64).copy()
        acc = parts[0].copy()
        res = np.zeros_like(acc)
        worst = 0.0
        for part in parts[1:]:
            t = acc + res if with_ef else acc
            decoded = gloo_tpu.q8_decode(gloo_tpu.q8_encode(t), t.size)
            if with_ef:
                res = t - decoded
            acc = decoded + part
            exact += part.astype(np.float64)
            worst = max(worst, np.abs(acc - exact).max())
        return worst

    one_hop = max(np.abs(np.sum(parts[:k], axis=0)).max() / 254.0
                  for k in range(1, len(parts) + 1))
    ef_worst = walk(True)
    plain_worst = walk(False)
    # EF: bounded by ~2 half-steps of the largest magnitude seen,
    # independent of hop count (residual + current hop's rounding).
    assert ef_worst <= 2.5 * one_hop, (ef_worst, one_hop)
    # And measurably tighter than the unfed walk over 23 hops.
    assert ef_worst < plain_worst / 2, (ef_worst, plain_worst)


def test_q8_encode_type_checks():
    with pytest.raises(Error):
        gloo_tpu.q8_encode(np.zeros(8, dtype=np.float64))
    with pytest.raises(Error):
        gloo_tpu.q8_decode(np.zeros(8, dtype=np.float32), 4)


def test_q8_block_env_knob():
    """TPUCOLL_Q8_BLOCK resolves strictly (malformed throws, range
    enforced) and changes the wire layout. Subprocesses: the knob is
    cached once per process."""
    code = ("import gloo_tpu, sys; "
            "b = gloo_tpu.q8_block(); "
            "w = gloo_tpu.q8_wire_bytes(1000); "
            "print(b, w)")
    env = dict(os.environ, TPUCOLL_Q8_BLOCK="512", TPUCOLL_SKIP_BUILD="1")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, check=True)
    block, wire = map(int, out.stdout.split())
    assert block == 512 and wire == 2 * 4 + 1000

    for bad in ("0", "7", "4096", "banana", "-8"):
        env = dict(os.environ, TPUCOLL_Q8_BLOCK=bad, TPUCOLL_SKIP_BUILD="1")
        r = subprocess.run(
            [sys.executable, "-c",
             "import gloo_tpu; gloo_tpu.q8_block()"],
            env=env, capture_output=True, text=True)
        assert r.returncode != 0, bad
        assert "TPUCOLL_Q8_BLOCK" in r.stderr, r.stderr[-300:]


# ---------------------------------------------------------------------------
# Collective correctness + consensus
# ---------------------------------------------------------------------------

def _allreduce_group(size, count, algorithm=None, wire=None, seed=11):
    def fn(ctx, rank):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal(count).astype(np.float32) * (rank + 1)
        kwargs = {"wire": wire} if wire else {"algorithm": algorithm}
        ctx.allreduce(x, **kwargs)
        return x

    return spawn(size, fn, timeout=90)


@pytest.mark.parametrize("size,count", [
    (2, 1000),                # ragged blocks, P=2
    (3, 3 * BLOCK * 11),      # block-aligned (fused-eligible), P=3
    (3, 10_007),              # prime count: ragged + uneven blocks
    (4, BLOCK // 2),          # blocks smaller than one q8 block
])
def test_q8_allreduce_accuracy_and_consensus(size, count):
    """Accuracy: within the per-hop bound of the exact sum. Consensus:
    ALL ranks byte-identical (the acceptance criterion — the allgather
    phase forwards the quantized stream verbatim)."""
    results = _allreduce_group(size, count, algorithm="ring_q8_wire")
    scale = sum(r + 1 for r in range(size))
    exact = (np.random.default_rng(11).standard_normal(count)
             .astype(np.float32) * scale)
    rel = (np.abs(results[0] - exact).max() /
           max(np.abs(exact).max(), 1e-9))
    # (P-1) reduce-scatter hops + 1 allgather quantization, each within
    # max/254 of the running max; 1% headroom covers P<=4 comfortably.
    assert rel < 0.01 * size, rel
    for r in range(1, size):
        assert np.array_equal(results[0], results[r]), f"rank {r} differs"


def test_q8_allreduce_zero_and_tiny():
    # count < P: some ranks own zero-byte blocks.
    results = _allreduce_group(3, 2, algorithm="ring_q8_wire")
    for r in range(1, 3):
        assert np.array_equal(results[0], results[r])
    # all-zero payload is exact.
    def fn(ctx, rank):
        x = np.zeros(5000, dtype=np.float32)
        ctx.allreduce(x, algorithm="ring_q8_wire")
        return x

    for out in spawn(3, fn, timeout=60):
        assert np.array_equal(out, np.zeros(5000, dtype=np.float32))


def test_q8_allreduce_wire_kwarg_and_conflicts():
    results = _allreduce_group(2, 5000, wire="q8")
    assert np.array_equal(results[0], results[1])

    def fn(ctx, rank):
        x = np.ones(16, dtype=np.float32)
        with pytest.raises(Error):
            ctx.allreduce(x, wire="q8", algorithm="ring")
        with pytest.raises(Error):
            ctx.allreduce(x, wire="zstd")
        # f32-only, sum-only contract fails loudly.
        with pytest.raises(Error):
            ctx.allreduce(np.ones(16, dtype=np.int32), wire="q8")
        with pytest.raises(Error):
            ctx.allreduce(x, op="max", wire="q8")
        with pytest.raises(Error):
            ctx.allreduce(x, op=lambda a, b: None, algorithm="ring_q8_wire")

    spawn(2, fn, timeout=60)


def test_q8_reduce_scatter():
    """q8 reduce_scatter: each rank's block approximates the exact sum
    segment; result blocks are the float32 accumulator (only hops are
    quantized)."""
    counts = [700, 600, 749]

    def fn(ctx, rank):
        x = np.arange(sum(counts), dtype=np.float32) * (rank + 1) / 100.0
        return ctx.reduce_scatter(x, recv_counts=counts, wire="q8")

    results = spawn(3, fn, timeout=90)
    full = np.arange(sum(counts), dtype=np.float32) * 6 / 100.0
    offs = np.cumsum([0] + counts)
    for r in range(3):
        seg = full[offs[r]:offs[r + 1]]
        rel = (np.abs(results[r] - seg).max() /
               max(np.abs(seg).max(), 1e-9))
        assert rel < 0.02, (r, rel)

    def bad(ctx, rank):
        with pytest.raises(Error):
            ctx.reduce_scatter(np.ones(9, dtype=np.int64), wire="q8")
        with pytest.raises(Error):
            ctx.reduce_scatter(np.ones(9, dtype=np.float32), wire="bf16")

    spawn(3, bad, timeout=60)


def test_q8_fused_vs_staged_identical():
    """The fused typed-receive arm (TPUCOLL_RECV_REDUCE=1) and the staged
    arm (=0) must produce IDENTICAL bytes — both run the same
    dequantize-accumulate kernel, just at different layers. Block-aligned
    count so the fused arm actually engages."""
    count = 3 * BLOCK * 7
    code = f"""
import json, sys, threading
import numpy as np
import gloo_tpu
store = gloo_tpu.HashStore()
out = [None] * 3
def worker(rank):
    ctx = gloo_tpu.Context(rank, 3, timeout=60)
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    x = (np.random.default_rng(5).standard_normal({count})
         .astype(np.float32) * (rank + 1))
    ctx.allreduce(x, algorithm="ring_q8_wire")
    out[rank] = x
    ctx.barrier(); ctx.close()
ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
[t.start() for t in ts]; [t.join(90) for t in ts]
assert all(o is not None for o in out)
assert np.array_equal(out[0], out[1]) and np.array_equal(out[0], out[2])
sys.stdout.buffer.write(out[0].tobytes())
"""
    blobs = {}
    for mode in ("0", "1"):
        env = dict(os.environ, TPUCOLL_RECV_REDUCE=mode,
                   TPUCOLL_SKIP_BUILD="1")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, timeout=180)
        assert r.returncode == 0, r.stderr[-500:]
        blobs[mode] = r.stdout
    assert blobs["0"] == blobs["1"]


def test_q8_auto_lossy_dispatch():
    """auto_lossy_wire: lossless tiers for small/non-f32 payloads, the q8
    ring for the untuned bandwidth tier — asserted from the flight
    recorder's per-op resolved algorithm."""
    def fn(ctx, rank):
        small = np.ones(256, dtype=np.float32)
        big = np.ones(1 << 19, dtype=np.float32)  # 2 MiB > HD_MAX
        iv = np.ones(256, dtype=np.int32)
        ctx.allreduce(small, algorithm="auto_lossy_wire", tag=1)
        ctx.allreduce(big, wire="lossy", tag=2)
        ctx.allreduce(iv, algorithm="auto_lossy_wire", tag=3)
        algos = [e.get("algo") for e in ctx.flightrec()["events"]
                 if e.get("op") == "allreduce"]
        return algos, float(small[0]), int(iv[0])

    for algos, small0, iv0 in spawn(2, fn, timeout=60):
        assert algos[1] == "ring_q8_wire", algos
        assert algos[0] != "ring_q8_wire" and algos[0] != "ring_bf16_wire"
        assert algos[2] != "ring_q8_wire" and algos[2] != "ring_bf16_wire"
        assert small0 == 2.0 and iv0 == 2  # lossless tiers stay exact


def test_q8_bucketer_wire():
    """GradientBucketer(wire="q8"): float32 buckets ride the q8 wire,
    non-float32 buckets stay lossless-exact."""
    def fn(ctx, rank):
        with ctx.async_engine(lanes=2) as engine:
            bucketer = gloo_tpu.GradientBucketer(engine, wire="q8",
                                                 average=True)
            f32 = [np.full(4096, float(rank + 1) + 0.25 * i,
                           dtype=np.float32) for i in range(4)]
            i64 = [np.full(128, rank + 1, dtype=np.int64)]
            for t in f32 + i64:
                bucketer.add(t)
            bucketer.finish()
            return [t.copy() for t in f32], i64[0].copy()

    results = spawn(2, fn, timeout=90)
    for rank_out in results:
        f32s, i64 = rank_out
        assert np.array_equal(i64, np.full(128, 1, dtype=np.int64))
        for i, t in enumerate(f32s):
            expect = (1.0 + 0.25 * i + 2.0 + 0.25 * i) / 2
            assert abs(float(t[0]) - expect) <= expect / 100
    # Consensus across ranks for the f32 buckets.
    for a, b in zip(results[0][0], results[1][0]):
        assert np.array_equal(a, b)

    def bad(ctx, rank):
        with ctx.async_engine(lanes=1) as engine:
            with pytest.raises(Error):
                gloo_tpu.GradientBucketer(engine, wire="q8", op="max")
            with pytest.raises(Error):
                gloo_tpu.GradientBucketer(engine, wire="zstd")

    spawn(2, bad, timeout=60)


def test_q8_wire_byte_reduction_observable():
    """The whole point, observable in the metrics plane: the q8 ring
    moves ~1/4 the channel bytes of the plain f32 ring (and ~1/2 of
    bf16) for the same payload. TPUCOLL_SHM=0 keeps payloads on the
    counted TCP channel."""
    count = 1 << 18  # 1 MiB f32
    code = """
import json, sys, threading
import numpy as np
import gloo_tpu
algo = sys.argv[1]
store = gloo_tpu.HashStore()
out = [None]
def worker(rank):
    ctx = gloo_tpu.Context(rank, 2, timeout=60)
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    ctx.metrics_enable(True)
    ctx.barrier()
    before = ctx.metrics()["channels"]["0"]["tx_bytes"]
    x = np.ones(%d, dtype=np.float32) * (rank + 1)
    ctx.allreduce(x, algorithm=algo)
    after = ctx.metrics()["channels"]["0"]["tx_bytes"]
    if rank == 0:
        out[0] = after - before
    ctx.barrier(); ctx.close()
ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
[t.start() for t in ts]; [t.join(90) for t in ts]
print("TXBYTES", out[0])
""" % count
    tx = {}
    for algo in ("ring", "ring_bf16_wire", "ring_q8_wire"):
        env = dict(os.environ, TPUCOLL_SHM="0", TPUCOLL_SKIP_BUILD="1")
        r = subprocess.run([sys.executable, "-c", code, algo], env=env,
                           capture_output=True, text=True, timeout=180)
        assert r.returncode == 0, r.stderr[-500:]
        tx[algo] = int(r.stdout.split("TXBYTES", 1)[1].split()[0])
    # Each rank sends ~payload bytes total across both ring phases at
    # P=2 (one block out per phase); codec ratios within 15% of ideal
    # (headers + wire framing).
    assert 0.85 < tx["ring_bf16_wire"] / (tx["ring"] / 2) < 1.15, tx
    assert 0.85 < tx["ring_q8_wire"] / (tx["ring"] / 4) < 1.15, tx


# ---------------------------------------------------------------------------
# Fault-plane determinism over the q8 wire format
# ---------------------------------------------------------------------------

def test_q8_chaos_same_seed_determinism():
    """Same-seed chaos over kRingQ8Wire: the fault plane treats q8
    payloads as ordinary data — a probabilistic delay/dup schedule fires
    the byte-identical sequence across two runs, and the collective's
    results stay within the precision contract under fault pressure."""
    from gloo_tpu import fault

    schedule = {"seed": 1111, "faults": [
        {"when": {"rank": 1, "opcode": "data", "min_bytes": 64},
         "action": "delay", "ms": 1, "prob": 0.5, "seed": 77},
        {"when": {"rank": 0, "opcode": "data", "min_bytes": 64},
         "action": "dup", "prob": 0.25, "seed": 78},
    ]}

    def workload():
        def fn(ctx, rank):
            rng = np.random.default_rng(4)
            base = rng.standard_normal(3 * BLOCK * 4).astype(np.float32)
            outs = []
            for i in range(6):
                x = base * (rank + 1 + i)
                ctx.allreduce(x, algorithm="ring_q8_wire", tag=10 + i)
                outs.append(x)
            return outs

        results = spawn(3, fn, timeout=120)
        # Consensus holds under fault pressure.
        for i in range(6):
            assert np.array_equal(results[0][i], results[1][i])
            assert np.array_equal(results[0][i], results[2][i])
        report = [json.dumps(fault.report(rank=r), sort_keys=True)
                  for r in range(3)]
        return report, results[0]

    fault.install(schedule)
    try:
        rep1, out1 = workload()
        fault.install(schedule)
        rep2, out2 = workload()
    finally:
        fault.clear()
    assert rep1 == rep2
    fired = json.loads(rep1[0]) + json.loads(rep1[1]) + json.loads(rep1[2])
    assert any(e["action"] in ("delay", "dup") for e in fired), \
        "schedule never fired — the workload no longer exercises it"
    # Same-seed chaos reruns of the same deterministic workload produce
    # byte-identical collective results too.
    for a, b in zip(out1, out2):
        assert np.array_equal(a, b)
