"""Async collective engine + gradient bucketer (docs/async.md).

Covers the tentpole contracts: correctness and out-of-order completion
across lanes, deterministic round-robin lane assignment (the property
that keeps per-lane flight-recorder streams cross-rank comparable),
bucketer coalescing/unflattening over heterogeneous dtypes, the
lifecycle contract (close()/teardown with work in flight fails loudly
and typed, naming the blamed lane/op — never a hang or a segfault), and
per-lane flightrec merges with no spurious desync.
"""

import gc
import json
import os
import subprocess
import sys
import tempfile
import textwrap
import time

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import GradientBucketer
from gloo_tpu.utils import flightrec

from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_async_allreduce_battery():
    """Mixed async collectives across 2 lanes at P=3: results correct,
    waits complete out of submission order, lane assignment is strict
    round-robin, and the engine gauges settle to zero in flight."""

    def fn(ctx, rank):
        with ctx.async_engine(lanes=2) as eng:
            works, arrays = [], []
            for i in range(8):
                x = np.full(500 + 321 * i, float(rank + 1 + i),
                            dtype=np.float32)
                works.append(eng.allreduce_async(x))
                arrays.append(x)
            # Reverse-order waits: completion order is decoupled from
            # issue order (the GC3 framing the tentpole implements).
            for i in reversed(range(8)):
                works[i].wait()
                expect = 3 * (i + 2)  # sum over ranks of (rank+1+i)
                assert arrays[i][0] == expect, (i, arrays[i][0])
            assert all(w.test() for w in works)
            assert all(w.error() is None for w in works)

            g = eng.allgather_async(np.full(16, float(rank), np.float64))
            rs = eng.reduce_scatter_async(
                np.arange(12, dtype=np.float32) * (rank + 1))
            mn = eng.allreduce_async(
                np.array([float(rank)], dtype=np.float64), op="min")
            gout = g.wait()
            assert gout.shape == (3, 16) and gout[2][0] == 2.0, gout[2][0]
            rsout = rs.wait()
            # sum over ranks of i*(rank+1) = 6i; rank owns its block of 4
            assert rsout[0] == 6.0 * (4 * rank), rsout
            assert mn.wait()[0] == 0.0

            st = eng.stats()
            assert st["lanes"] == 2
            assert st["submitted"] == 11 and st["in_flight"] == 0, st
            assert st["completed"] == 11 and st["errors"] == 0, st
            # Round-robin: submission i -> lane i % 2, on every rank.
            assert st["per_lane"][0]["submitted"] == 6, st
            assert st["per_lane"][1]["submitted"] == 5, st
            assert not st["per_lane"][0]["poisoned"]

            # Async ops are recorded on the lane contexts.
            ops = eng.lane_metrics(0)["ops"]
            assert ops.get("allreduce", {}).get("calls", 0) >= 4, ops
        return True

    assert spawn(3, fn, timeout=60) == [True] * 3


def test_async_callable_reduction_rejected():
    def fn(ctx, rank):
        with ctx.async_engine(lanes=1) as eng:
            with pytest.raises(gloo_tpu.Error, match="callable"):
                eng.allreduce_async(np.ones(4, np.float32),
                                    op=lambda a, b: None)
        return True

    assert spawn(2, fn, timeout=30) == [True, True]


def test_bucketer_coalesces_and_unflattens():
    """Heterogeneous shapes and dtypes coalesce into per-dtype flat
    buckets, results land back in the original tensors, the bucketer is
    reusable across steps, and oversized tensors ride as their own
    in-place bucket (no pack copy)."""

    def fn(ctx, rank):
        eng = ctx.async_engine(lanes=2)
        b = GradientBucketer(eng, bucket_bytes=64 << 10)
        shapes = [(3, 5), (128,), (17, 31), (2, 2, 2), (4096,), (63,)]
        for step in range(3):
            tensors = []
            for i, shape in enumerate(shapes * 4):
                dtype = [np.float32, np.float64, np.int32][i % 3]
                t = np.full(shape, rank + 1 + step, dtype=dtype)
                tensors.append(t)
            big = np.full(100_000, float(rank + 1), np.float32)  # own bucket
            for t in tensors:
                b.add(t)
            b.add(big)
            assert b.in_flight > 0
            b.finish()
            assert b.in_flight == 0
            for t in tensors:
                assert t.flat[0] == 2 * (1 + step) + 1, (step, t.flat[0])
            assert big[0] == 3.0
        # average=True divides by world size after the wait.
        avg = GradientBucketer(eng, bucket_bytes=1 << 20, average=True)
        grads = [np.full(100, float(rank + 1), np.float32)
                 for _ in range(5)]
        for g in grads:
            avg.add(g)
        avg.finish()
        for g in grads:
            assert g[0] == 1.5, g[0]  # (1 + 2) / 2
        return True

    assert spawn(2, fn, timeout=60) == [True, True]


def test_bucketer_rejects_bad_config():
    class FakeEngine:
        pass

    with pytest.raises(gloo_tpu.Error, match="callable"):
        GradientBucketer(FakeEngine(), op=lambda a, b: None)
    with pytest.raises(gloo_tpu.Error, match="sum"):
        GradientBucketer(FakeEngine(), op="max", average=True)


def test_close_with_work_in_flight_fails_loudly():
    """The lifecycle regression: Context.close() with async work still
    in flight must surface typed errors at wait() — the running op
    aborted via its lane (IoError/TimeoutError), queued ops failed as
    Aborted — all naming the blamed lane/op, with no hang and no
    segfault. Rank 1 never enters the collectives, so without the
    shutdown path rank 0's waits would sit out their full timeouts."""

    def fn(ctx, rank):
        eng = ctx.async_engine(lanes=1)
        works = []
        if rank == 0:
            for _ in range(3):
                works.append(
                    eng.allreduce_async(np.ones(200_000, np.float32)))
            time.sleep(0.2)  # let seq 0 reach its blocking wait
            t0 = time.time()
            ctx.close()
            closed_in = time.time() - t0
            assert closed_in < 5.0, f"close took {closed_in}s"
            # seq 0 was mid-collective: aborted through the lane context.
            with pytest.raises(gloo_tpu.IoError) as excinfo:
                works[0].wait(timeout=5)
            msg = str(excinfo.value)
            assert "lane 0" in msg and "allreduce" in msg, msg
            # seq 1/2 were still queued: failed loudly, never ran.
            for w in works[1:]:
                with pytest.raises(gloo_tpu.Aborted) as excinfo:
                    w.wait(timeout=5)
                msg = str(excinfo.value)
                assert "never ran" in msg and "allreduce" in msg, msg
                assert "lane 0" in msg, msg
            # The engine is down: new submissions fail loudly too
            # (handle-constructor path, so the base Error type).
            with pytest.raises(gloo_tpu.Error, match="shutdown"):
                eng.allreduce_async(np.ones(4, np.float32))
        else:
            time.sleep(1.5)  # keep the peer mesh alive while rank 0
            ctx.close()      # closes with its work genuinely in flight
        return True

    assert spawn(2, fn, timeout=60) == [True, True]


def test_teardown_with_work_in_flight_never_hangs():
    """Interpreter teardown (__del__ path, no explicit close/shutdown)
    with async work in flight: the child process must exit 0 promptly —
    no hang joining lane threads, no segfault from lanes outliving the
    contexts."""
    store = tempfile.mkdtemp()
    body = textwrap.dedent("""
        import sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, 2, timeout=20.0)
        ctx.connect_full_mesh(gloo_tpu.FileStore({store!r}),
                              gloo_tpu.Device())
        eng = ctx.async_engine(lanes=2)
        if rank == 0:
            # Rank 1 never joins: these stay in flight at exit.
            works = [eng.allreduce_async(np.ones(50_000, np.float32))
                     for _ in range(4)]
        else:
            time.sleep(0.5)
        print("EXITING")
        # Fall off the end: only __del__ / interpreter teardown runs.
    """).format(repo=_REPO, store=store)
    procs = [subprocess.Popen([sys.executable, "-c", body, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for r in range(2)]
    outs = [p.communicate(timeout=60) for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (r, p.returncode, out)
        assert "EXITING" in out[0], (r, out)


def test_flightrec_lane_streams_merge_clean():
    """Per-lane flight-recorder merges across ranks: deterministic
    round-robin assignment keeps each lane's cseq/fingerprint stream
    identical on every rank, so the desync detector reports OK for
    every lane — no spurious desync from async interleaving — while the
    per-lane streams really did record the async ops."""
    dumps = tempfile.mkdtemp()

    def fn(ctx, rank):
        with ctx.async_engine(lanes=2) as eng:
            works = []
            for i in range(10):
                # Heterogeneous ops and sizes, identical order per rank.
                if i % 3 == 2:
                    w = eng.allgather_async(
                        np.full(50 + i, float(rank), np.float32))
                else:
                    w = eng.allreduce_async(
                        np.full(1000 + 100 * i, 1.0, np.float32))
                works.append(w)
            for w in works:
                w.wait()
            eng.flightrec_dump(dumps)
        return True

    assert spawn(3, fn, timeout=60) == [True] * 3
    for lane in range(2):
        merged = flightrec.merge(os.path.join(dumps, f"lane{lane}"))
        assert sorted(merged["ranks"]) == [0, 1, 2], merged["missing"]
        verdict = flightrec.analyze(merged)
        assert verdict["kind"] == "ok", verdict
        assert flightrec.detect_desync(
            {r: d["events"] for r, d in merged["ranks"].items()}) is None
        # Each lane recorded its own 5-op collective stream.
        events = merged["ranks"][0]["events"]
        cseqs = [e["cseq"] for e in events if e.get("cseq") is not None]
        assert len(cseqs) == 5 and cseqs == sorted(cseqs), cseqs


def test_async_metrics_surface():
    """Parent metrics carry the engine gauges; lane metrics and the
    Prometheus exposition include the async series."""

    def fn(ctx, rank):
        eng = ctx.async_engine(lanes=2)
        ws = [eng.allreduce_async(np.ones(100, np.float32))
              for _ in range(4)]
        for w in ws:
            w.wait()
        snap = ctx.metrics()
        assert snap["async"]["in_flight"] == 0, snap["async"]
        assert snap["async"]["engines"][0]["submitted"] == 4
        from gloo_tpu.utils.metrics import to_prometheus

        text = to_prometheus(snap)
        assert "gloo_tpu_async_in_flight" in text
        assert 'gloo_tpu_async_lane_submitted_total' in text
        return True

    assert spawn(2, fn, timeout=30) == [True, True]
