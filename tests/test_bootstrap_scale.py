"""Scalable bootstrap plane (docs/bootstrap.md): lazy pair
establishment with the LRU-capped broker, leader-relayed rendezvous
over the host topology, and per-host lease aggregation for the elastic
coordinator — the P>=512 bring-up story, exercised here at CI scale
with simulated hosts (TPUCOLL_HOST_ID / set_host_id).

The native choreography curves live in BOOT_r18.json (bench.py
--bootstrap-sweep); these tests pin the *semantics*: every algorithm
family (and the PR 17 schedule interpreter) runs unchanged over a
broker-dialed mesh, the steady-state broker pair count respects
TPUCOLL_MAX_PAIRS, first-use dial failures surface as typed errors
naming the peer, and a 4x4 simulated grid rebuilds through a SIGKILL
with aggregated leases on."""

import json
import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import schedule

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Process-global env drives the boot plane (read once per
# connect_full_mesh), so lazy-mode spawns serialize behind this lock.
_ENV_MU = threading.Lock()


def _spawn_lazy(size, rph, fn, cap=None, timeout=90.0,
                context_timeout=30.0, extra_env=None):
    """Threaded lazy-mode grid: rank r presents host lazyhost<r//rph>,
    connects with TPUCOLL_BOOT_MODE=lazy (plus TPUCOLL_MAX_PAIRS=cap
    when given), runs fn(ctx, rank), restores the environment."""
    store = gloo_tpu.HashStore()
    results = [None] * size
    errors = []
    lock = threading.Lock()

    def worker(rank):
        ctx = None
        try:
            ctx = gloo_tpu.Context(rank, size, timeout=context_timeout)
            ctx.set_host_id(f"lazyhost{rank // rph}")
            ctx.connect_full_mesh(store, gloo_tpu.Device())
            results[rank] = fn(ctx, rank)
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            with lock:
                errors.append((rank, exc))
        finally:
            if ctx is not None:
                try:
                    ctx.close()
                except Exception:
                    pass

    env = {"TPUCOLL_BOOT_MODE": "lazy"}
    if cap is not None:
        env["TPUCOLL_MAX_PAIRS"] = str(cap)
    if extra_env:
        env.update(extra_env)
    with _ENV_MU:
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            threads = [threading.Thread(target=worker, args=(r,),
                                        daemon=True)
                       for r in range(size)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout)
                if t.is_alive():
                    raise TimeoutError(f"lazy rank hung past {timeout}s")
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    if errors:
        rank, exc = errors[0]
        raise AssertionError(f"rank {rank} failed: {exc!r}") from exc
    return results


# ---- lazy mesh is transparent to every algorithm family --------------------


def test_lazy_bootstrap_all_families():
    """8 ranks / 2 simulated hosts come up lazy; every collective
    family computes its closed form over broker-dialed pairs, and the
    boot metrics carry the relayed-rendezvous story (lazy flag on,
    store traffic far under the full-mesh O(N^2) exchange)."""
    size, rph = 8, 4

    def fn(ctx, rank):
        topo = ctx.topology()
        assert topo["n_hosts"] == 2, topo
        x = np.full(512, float(rank + 1), dtype=np.float32)
        for algo in ("auto", "ring", "hd", "bcube", "hier"):
            x[:] = float(rank + 1)
            ctx.allreduce(x, algorithm=algo)
            assert x[0] == size * (size + 1) / 2, (algo, x[0])
        g = np.full(32, float(rank), dtype=np.float64)
        out = ctx.allgather(g, tag=1)
        assert [int(out[r][0]) for r in range(size)] == list(range(size))
        b = np.full(64, float(rank == 3), dtype=np.float32)
        ctx.broadcast(b, root=3, tag=2)
        assert b[0] == 1.0, b[0]
        r = np.full(128, 1.0, dtype=np.float32)
        red = ctx.reduce(r, root=5, tag=3)
        if rank == 5:
            assert red[0] == size, red[0]
        else:
            assert red is None
        rs = np.arange(size * 16, dtype=np.float32)
        block = ctx.reduce_scatter_inplace(rs, tag=4)
        assert block[0] == size * (rank * 16), block[0]
        a2a = np.full((size, 4), float(rank), dtype=np.float32)
        a2a_out = ctx.alltoall(a2a, tag=5)
        assert [int(a2a_out[s][0]) for s in range(size)] == \
            list(range(size))
        ctx.barrier(tag=6)
        boot = ctx.metrics()["boot"]
        assert boot["lazy"] is True, boot
        # Relayed rendezvous: per-rank store traffic stays O(1)-ish
        # (publish + topo + leader relay) vs the 2(N-1) gets every rank
        # performs in the seed's full-mesh exchange.
        assert boot["store_ops"] < 2 * size * (size - 1), boot
        assert boot["lazy_dials"] > 0, boot
        return boot["pairs_connected"]

    connected = _spawn_lazy(size, rph, fn)
    # Nobody needed a full mesh to run all of the above.
    assert all(c <= size - 1 for c in connected), connected


def test_lazy_bootstrap_schedule_interpreter():
    """The PR 17 interpreter replays a generated schedule over a lazy
    mesh byte-identically to the native dispatch: broker-dialed pairs
    are indistinguishable from eager ones to the schedule plane."""
    size, rph = 4, 2

    def fn(ctx, rank):
        base = (np.random.RandomState(7 + rank)
                .randint(0, 50, size=1536).astype(np.float32))
        native = base.copy()
        ctx.allreduce(native)
        t = schedule.generate("ring", size, {"depth": 2})
        t = json.loads(json.dumps(t))
        t["elections"] = [{
            "collective": "allreduce", "world_size": size, "dtype": "",
            "bucket": (1536 * 4).bit_length() - 1,
            "schedule": t["schedules"][0]["name"],
        }]
        schedule.install(ctx, t)
        got = base.copy()
        ctx.allreduce(got)
        schedule.clear(ctx)
        assert np.array_equal(native, got)
        return got.tobytes()

    results = _spawn_lazy(size, rph, fn)
    assert len(set(results)) == 1  # consensus across ranks


# ---- LRU broker cap --------------------------------------------------------


def test_lazy_broker_cap_and_lru_eviction():
    """TPUCOLL_MAX_PAIRS=1 under a mixed soak: in-flight pairs may pin
    past the cap, but a dial with the mesh quiesced trims the broker
    back to <= cap — and the evicted-then-redialed peers still compute
    correct results (the LRU churn is invisible to callers)."""
    size, rph, cap = 8, 4, 1

    def fn(ctx, rank):
        eager = ctx.metrics()["boot"]["pairs_connected"]
        for i in range(6):
            a2a = np.full((size, 4), float(rank), dtype=np.float32)
            out = ctx.alltoall(a2a, tag=1)
            assert out[rank][0] == float(rank), out[rank][0]
            y = np.ones(128, dtype=np.float32)
            ctx.allreduce(y)
            assert y[0] == size, y[0]
        ctx.barrier(tag=2)
        # Quiesced single dial: the cap is enforced at dial time.
        z = np.full(8, float(rank), dtype=np.float32)
        ctx.send(z, (rank + 3) % size, slot=9)
        w = np.empty(8, dtype=np.float32)
        ctx.recv(w, (rank - 3) % size, slot=9)
        assert w[0] == float((rank - 3) % size), w[0]
        boot = ctx.metrics()["boot"]
        broker = boot["pairs_connected"] - eager
        assert broker <= cap, (rank, broker, boot)
        return boot["pairs_evicted"]

    evictions = _spawn_lazy(size, rph, fn, cap=cap)
    assert sum(evictions) > 0, evictions


# ---- typed first-use dial failure ------------------------------------------


def test_lazy_first_use_dial_failure_names_peer():
    """A peer that died between rendezvous and first use: the broker's
    on-demand dial fails with a typed IoError naming the peer rank —
    not a hang, not an anonymous socket error."""
    size = 3
    store_dir = tempfile.mkdtemp()
    body = textwrap.dedent("""
        import os, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1])
        store = gloo_tpu.FileStore({store!r})
        ctx = gloo_tpu.Context(rank, {size}, timeout=8.0)
        ctx.set_host_id("deadhost%d" % rank)  # one rank per host
        # TPUCOLL_BOOT_EAGER=none: nothing is dialed at connect, so the
        # dial below is genuinely first-use.
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        if rank == 2:
            # Vanish before anyone broker-dials us. os._exit skips the
            # orderly goodbye: the listener socket just disappears.
            store.set("rank2_gone", b"1")
            os._exit(0)
        store.get("rank2_gone", timeout=10.0)
        time.sleep(0.3)
        if rank == 0:
            err = None
            try:
                z = np.ones(8, dtype=np.float32)
                ctx.send(z, 2, slot=5)
            except gloo_tpu.IoError as exc:
                err = str(exc)
            assert err is not None, "dial to a dead rank succeeded?"
            assert "rank 2" in err, err
            print("TYPED-ERR-OK")
        ctx.close()
    """).format(repo=_REPO, store=store_dir, size=size)
    env = dict(os.environ, TPUCOLL_BOOT_MODE="lazy",
               TPUCOLL_BOOT_EAGER="none")
    procs = [subprocess.Popen([sys.executable, "-c", body, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for r in range(size)]
    outs = [p.communicate(timeout=60) for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (r, p.returncode, out)
    assert "TYPED-ERR-OK" in outs[0][0], outs[0]


# ---- native rendezvous choreography ----------------------------------------


def test_relayed_rendezvous_store_op_scaling():
    """tc_boot_rendezvous_bench, 32 thread-ranks over a shared
    FileStore: the full-mesh arm performs exactly its closed-form
    2N + 2N(N-1) store ops; the relayed arm stays an order of magnitude
    under it (O(hosts^2 + N)) while moving the same address bytes."""
    from gloo_tpu import _lib

    n, rph = 32, 8
    ops = {}
    for arm, lazy in (("lazy", 1), ("full", 0)):
        d = tempfile.mkdtemp()
        raw = _lib.copy_out(_lib.lib.tc_boot_rendezvous_bench,
                            d.encode(), n, rph, 8, lazy, 64, 60000)
        ops[arm] = json.loads(raw)
    assert ops["full"]["store_ops"] == 2 * n + 2 * n * (n - 1)
    assert ops["lazy"]["store_ops"] * 10 <= ops["full"]["store_ops"], ops
    assert ops["lazy"]["nranks"] == n


def test_rendezvous_bench_validates_arguments():
    from gloo_tpu import _lib

    d = tempfile.mkdtemp()
    with pytest.raises(gloo_tpu.Error):
        _lib.copy_out(_lib.lib.tc_boot_rendezvous_bench, d.encode(),
                      0, 8, 8, 1, 64, 1000)
    with pytest.raises(gloo_tpu.Error):
        _lib.copy_out(_lib.lib.tc_boot_rendezvous_bench, d.encode(),
                      8, 8, 8, 1, 1 << 21, 1000)


# ---- boot env validation ---------------------------------------------------


def test_boot_env_validation():
    """Malformed boot knobs fail loudly at connect time (strict env
    parsing, common/env.h discipline) — never a silent fallback."""
    cases = [{"TPUCOLL_BOOT_MODE": "eager"},
             {"TPUCOLL_BOOT_MODE": "lazy", "TPUCOLL_BOOT_EAGER": "all"},
             {"TPUCOLL_BOOT_MODE": "lazy", "TPUCOLL_BOOT_SHARDS": "0"},
             {"TPUCOLL_BOOT_MODE": "lazy", "TPUCOLL_MAX_PAIRS": "-2"}]
    for env in cases:
        with _ENV_MU:
            saved = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            try:
                ctx = gloo_tpu.Context(0, 1)
                with pytest.raises(gloo_tpu.Error):
                    ctx.connect_full_mesh(gloo_tpu.HashStore(),
                                          gloo_tpu.Device())
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v


def test_lazy_single_rank_world():
    """The degenerate world still bootstraps lazily (leader of its own
    one-host topology, zero pairs)."""

    def fn(ctx, rank):
        x = np.full(16, 3.0, dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == 3.0
        return ctx.metrics()["boot"]["pairs_connected"]

    assert _spawn_lazy(1, 1, fn) == [0]


# ---- elastic: SIGKILL -> rebuild on a 4x4 grid with aggregated leases ------


def test_elastic_sigkill_4x4_grid_agg_leases():
    """16 workers across 4 simulated hosts, lazy bootstrap AND
    per-host lease aggregation on: SIGKILL one member mid-step; the
    survivors detect via the aggregate scan (O(hosts) per coordinator
    pass), agree the next epoch, and rebuild at size 15 within the
    lease-grace-bounded window. Every worker's final agent status must
    show the aggregation plane actually ran."""
    hosts, rph = 4, 4
    size = hosts * rph
    store_dir = tempfile.mkdtemp()
    body = textwrap.dedent("""
        import json, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu
        from gloo_tpu import elastic

        rank = int(sys.argv[1])
        store = gloo_tpu.FileStore({store!r})

        def step_fn(ectx, step, state):
            flag = np.zeros(1, dtype=np.float32)
            if ectx.rank == 0:
                try:
                    store.get("grid_stop", timeout=0.001)
                    flag[0] = 1.0
                except gloo_tpu.Error:
                    pass
            ectx.allreduce(flag, tag=0)
            if flag[0] > 0:
                raise StopIteration
            n = ectx.size
            x = np.full(1024, float(ectx.rank + 1), dtype=np.float32)
            ectx.allreduce(x, tag=1)
            assert x[0] == n * (n + 1) / 2, (step, x[0], n)
            state["i"] += 1
            return state

        res = elastic.run_elastic(
            step_fn, store=store, device=gloo_tpu.Device(), rank=rank,
            world_size={size}, min_size={min_size},
            host_id="gridhost%d" % (rank // {rph}),
            state={{"i": 0}}, timeout=120.0)
        res.pop("state")
        print("OK", json.dumps(res))
    """).format(repo=_REPO, store=store_dir, size=size, rph=rph,
                min_size=size - 1)
    env = dict(os.environ, TPUCOLL_LEASE_AGG="1",
               TPUCOLL_BOOT_MODE="lazy",
               TPUCOLL_LEASE_MS="200", TPUCOLL_LEASE_GRACE="1200")
    procs = [subprocess.Popen([sys.executable, "-c", body, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for r in range(size)]
    victim = 5
    try:
        time.sleep(6.0)  # founders up + a few steps
        t_kill = time.monotonic()
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=30)
        # Bounded recovery: detect (lease grace) + agree + rebuild.
        deadline = time.monotonic() + 30.0
        time.sleep(4.0)
    finally:
        gloo_tpu.FileStore(store_dir).set("grid_stop", b"1")
    summaries = []
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=90)
        if r == victim:
            assert p.returncode == -signal.SIGKILL
            continue
        assert p.returncode == 0, (r, p.returncode, err[-800:])
        line = [ln for ln in out.splitlines() if ln.startswith("OK ")]
        assert line, (r, out, err[-500:])
        summaries.append(json.loads(line[0][3:]))
    assert len(summaries) == size - 1
    for s in summaries:
        final = s["epochs"][-1]
        assert final["size"] == size - 1, s["epochs"]
        assert final["epoch"] >= 2
        assert s["elastic"]["lease_agg"] is True, s["elastic"]
        assert s["rebuilds"] >= 1
        # The rebuild itself stays in the small-N regime: the grace
        # window owns detection, the rebuild must not add seconds.
        assert min(s["rebuild_ms"]) < 10000, s["rebuild_ms"]
    # At least the four host leaders published aggregates.
    agg_pubs = sum(s["elastic"]["agg_publishes"] for s in summaries)
    assert agg_pubs >= hosts, agg_pubs
    assert time.monotonic() <= deadline
