"""Checkpoint/resume: durable step store + elastic-training integration.

Beyond-reference coverage (the reference has no checkpoint story): state
survives process death, restores onto DIFFERENT mesh shardings, and
composes with resilience.rebuild_after_failure so a shrunken group
resumes from the last committed step instead of from scratch.
"""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from gloo_tpu.checkpoint import StepCheckpointer  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_roundtrip_and_gc(tmp_path):
    ckpt = StepCheckpointer(str(tmp_path), keep=2)
    assert ckpt.load_latest() == (None, None)
    for step in (1, 5, 9):
        ckpt.save(step, {"w": jnp.arange(8.0) * step,
                         "step": np.int64(step)})
    assert ckpt.steps() == [5, 9]  # keep=2 garbage-collected step 1
    step, state = ckpt.load_latest()
    assert step == 9
    np.testing.assert_array_equal(state["w"], np.arange(8.0) * 9)
    assert int(state["step"]) == 9


def test_restore_onto_different_sharding(tmp_path):
    """The post-failure story: state saved on an 8-way mesh restores onto
    a 4-way mesh via the template's shardings."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh8 = Mesh(np.asarray(devs[:8], dtype=object), ("x",))
    mesh4 = Mesh(np.asarray(devs[:4], dtype=object), ("x",))
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(mesh8, P("x")))

    ckpt = StepCheckpointer(str(tmp_path))
    ckpt.save(3, {"x": x})

    template = {"x": jax.ShapeDtypeStruct(
        (8, 8), jnp.float32, sharding=NamedSharding(mesh4, P("x")))}
    step, state = ckpt.load_latest(template)
    assert step == 3
    assert state["x"].sharding.mesh.shape["x"] == 4
    np.testing.assert_array_equal(np.asarray(state["x"]),
                                  np.arange(64.0).reshape(8, 8))


def test_elastic_resume_from_checkpoint():
    """SIGKILL a rank mid-training; survivors rebuild the group AND
    resume from the last committed checkpoint — the step counter and the
    weights both come back, and training keeps converging."""
    store = tempfile.mkdtemp()
    ckdir = tempfile.mkdtemp()

    body = """
import os, signal, sys, time
sys.path.insert(0, {repo!r})
# Host-plane worker: orbax imports jax, and initializing the pinned TPU
# plugin in every subprocess is slow (tens of seconds through the
# tunnel) — force the CPU platform first, as any host-side trainer
# process would.
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import gloo_tpu
from gloo_tpu.checkpoint import StepCheckpointer
from gloo_tpu.resilience import rebuild_after_failure

rank, size = {rank}, 3
store = gloo_tpu.FileStore({store!r})
ctx = gloo_tpu.Context(rank, size, timeout=10.0)
ctx.connect_full_mesh(store, gloo_tpu.Device())
ckpt = StepCheckpointer({ckdir!r}, keep=2)

rng = np.random.RandomState(0)
X = rng.randn(240, 6).astype(np.float32)
y = X @ np.arange(6, dtype=np.float32)
w = np.zeros(6, dtype=np.float32)
step = 0
gen = 1

while step < 80:
    lo = rank * (240 // size); hi = lo + 240 // size
    err = X[lo:hi] @ w - y[lo:hi]
    grad = 2.0 * X[lo:hi].T @ err / len(err)
    if rank == 2 and step == 20:
        os.kill(os.getpid(), signal.SIGKILL)
    try:
        # Timeout sized above rank 0's worst-case synchronous orbax
        # save (its peers sit in this allreduce while it commits).
        ctx.allreduce(grad, timeout=8.0)
    except gloo_tpu.IoError:
        # settle must exceed the op timeout above: the slowest survivor
        # only detects the death when ITS allreduce times out, and the
        # membership roll call has to wait for it (resilience.py
        # docstring invariant).
        ctx, rank, size = rebuild_after_failure(
            store, gloo_tpu.Device(), old_rank=rank, old_size=size,
            generation=gen, settle=10.0, timeout=60.0)
        assert ctx is not None
        gen += 1
        # Elastic resume: everyone reloads the last committed state so
        # the shrunken group restarts from a CONSISTENT (step, w), not
        # from whatever divergent point each survivor reached.
        got_step, state = ckpt.load_latest()
        assert got_step is not None, "no checkpoint to resume from"
        step = int(state["step"])
        w = np.asarray(state["w"])
        continue
    w -= 0.02 * grad / size
    step += 1
    if rank == 0 and step % 10 == 0:
        ckpt.save(step, {{"w": w, "step": np.int64(step)}})

final_loss = float(np.mean((X @ w - y) ** 2))
assert final_loss < 1.0, final_loss
print(f"RESUMED final={{final_loss:.4f}}")
"""

    # Not reusing test_multiproc._spawn_worker: the CPU-platform force
    # must run IN-PROCESS before jax's first backend init (the
    # JAX_PLATFORMS env var does not override this environment's plugin
    # pin), so this worker owns its prelude.
    def worker(rank):
        prog = textwrap.dedent(body).format(repo=_REPO, rank=rank,
                                            store=store, ckdir=ckdir)
        return subprocess.Popen([sys.executable, "-c", prog],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    procs = [worker(r) for r in range(3)]
    outs = [p.communicate(timeout=180) for p in procs]
    codes = [p.returncode for p in procs]
    assert codes[2] == -signal.SIGKILL
    for r in (0, 1):
        assert codes[r] == 0, (codes, outs[r])
        assert "RESUMED" in outs[r][0], outs[r]
