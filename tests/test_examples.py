"""Smoke the runnable examples: they are the first code a new user
executes, and nothing else in CI runs them (r5 found two silently
broken under a platform-pinning site customization — exactly the rot
this file prevents). Each runs as the README documents it, on the
virtual CPU mesh, asserting the script's own success line."""

import os
import subprocess
import sys

import pytest

pytest.importorskip("jax")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EX = os.path.join(_REPO, "examples")


def _run(name, timeout=420, env_extra=None):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               **(env_extra or {}))
    proc = subprocess.run([sys.executable, os.path.join(_EX, name)],
                          env=env, capture_output=True, text=True,
                          timeout=timeout)
    assert proc.returncode == 0, (name, proc.stdout[-1500:],
                                  proc.stderr[-1500:])
    return proc.stdout


def test_example_fused_tp():
    out = _run("example_fused_tp.py")
    assert "fused tensor-parallel example OK" in out
    assert "auto dispatcher" in out


def test_example_device_plane():
    out = _run("example_device_plane.py")
    assert "done" in out


def test_example_fsdp_long_context():
    out = _run("example_fsdp_long_context.py")
    assert "fsdp + long-context example OK" in out


def test_example_observability():
    out = _run("example_observability.py", timeout=180)
    assert "observability example OK" in out
    assert "[watchdog] rank0 was blocked" in out
    assert "labeled rank rows" in out


def test_example_chaos():
    out = _run("example_chaos.py", timeout=180)
    assert "chaos example: OK" in out
    assert "fault firing sequence:" in out
    assert '"action": "stall"' in out and '"action": "kill"' in out
    assert "rebuilt OK" in out
    assert "[watchdog] rank0 was blocked" in out
    assert "merged chaos trace" in out


def test_example_flightrec():
    out = _run("example_flightrec.py", timeout=180)
    assert "flightrec example: OK" in out
    assert "reason=stall blamed_peer=1" in out
    assert "desync verdict: collective desync" in out
    assert "merged Perfetto timeline" in out


def test_bench_autotune_smoke(tmp_path):
    """bench.py --autotune smoke cell (tiny sizes, 2 ranks): the sweep
    must elect a table all ranks agree on, persist it, and the tuned
    dispatch must not lose to the better fixed ring/HD arm beyond the
    noise floor (aggregate check — per-cell timings on this shared-core
    host swing +/-15%, BASELINE.md)."""
    import json
    import math

    table_path = os.path.join(tmp_path, "table.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--autotune",
         "--autotune-quick", "--autotune-out", table_path],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "allreduce_autotune_2rank_host"
    assert line["ranks_agree"] is True
    assert line["cells"], "no swept sizes reported"
    # Acceptance: tuned dispatch >= the better fixed arm minus noise, at
    # every swept size in aggregate (geomean absorbs per-cell jitter).
    ratios = [c["tuned_vs_best_fixed"] for c in line["cells"]]
    geomean = math.exp(sum(math.log(r) for r in ratios) / len(ratios))
    assert geomean <= 1.5, (geomean, line["cells"])
    # The emitted table is a valid TPUCOLL_TUNING_FILE payload.
    with open(table_path) as f:
        table = json.load(f)
    assert table["version"] == 1 and table["entries"]


def test_bench_channel_sweep_smoke():
    """bench.py --channel-sweep --quick (2 ranks): every grid point must
    produce a valid JSON measurement line — the data the tuning plane's
    transport hints (tuning.set_transport_hints) are picked from. Values
    are not compared: on a shared-core CI host the multi-channel arm can
    legitimately lose; the sweep's job is producing trustworthy points."""
    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--channel-sweep", "--quick"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    assert len(lines) >= 2, proc.stdout
    seen = set()
    for line in lines:
        assert line["metric"] == "channel_sweep"
        assert line["ok"] is True, line
        assert line["value"] > 0
        seen.add((line["loops"], line["channels"], line["stripe_bytes"]))
    assert (1, 1, 1 << 20) in seen and (2, 2, 1 << 20) in seen


def test_bench_hier_sweep_smoke():
    """bench.py --hier-sweep --quick (4 ranks, 2 simulated hosts): one
    valid JSON cell comparing flat vs hierarchical allreduce over the
    mixed shm+TCP fabric. The ratio is not asserted — the committed
    HIER_r13.json records the measured grid; the smoke proves the cell
    machinery (topology simulation, consensus check, shm-grouping
    assertion inside the workers) holds together."""
    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--hier-sweep", "--quick"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    assert len(lines) == 1, proc.stdout
    line = lines[0]
    assert line["metric"] == "hier_sweep" and line["ok"] is True, line
    assert line["hosts"] == 2 and line["ranks_per_host"] == 2
    assert line["flat_gbps"] > 0 and line["hier_gbps"] > 0
    assert line["hier_vs_flat"] > 0


def test_bench_latency_smoke():
    """bench.py --latency --quick (2 ranks, TPUCOLL_SHM=0): one JSON
    line per (op, size, plans on/off) cell plus a summary line. The
    on-arm must prove the zero-registration steady state
    (ubuf_creates_steady_delta == 0); speedups are NOT asserted — a
    shared-core CI host's scheduler noise owns that margin, and the
    committed LAT_r12.json records the measured run."""
    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--latency", "--quick"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    cells = [l for l in lines if l.get("bench") == "latency"]
    summaries = [l for l in lines if l.get("bench") == "latency_summary"]
    # 4 quick sizes x 2 ops x 2 arms.
    assert len(cells) == 16, proc.stdout
    assert len(summaries) == 1, proc.stdout
    for cell in cells:
        assert cell["p50_us"] > 0 and cell["p99_us"] >= cell["p50_us"]
        if cell["plans"]:
            assert cell["ubuf_creates_steady_delta"] == 0, cell
            assert cell["plan_hits"] > 0, cell
    assert summaries[0]["geomean_p50_speedup_le_64KiB"] is not None


def test_bench_elastic_soak_smoke():
    """bench.py --elastic-soak --quick (3 workers, 1 SIGKILL + 1
    rejoin): the soak must come back at FULL size with every mixed-
    workload step verified, epochs covering the shrink + grow
    transitions, and rebuild-latency percentiles measured — the
    committed ELASTIC_r14.json records the longer run. Latency values
    are not ranked (shared-core CI host); ok=True already asserts the
    end-to-end recovery contract inside the driver."""
    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--elastic-soak", "20", "--quick"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    assert len(lines) == 1, proc.stdout
    line = lines[0]
    assert line["metric"] == "elastic_soak_3rank_host"
    assert line["ok"] is True, line
    assert line["kills"] == 1 and line["rejoins"] == 1
    # One kill forces at least shrink + grow past the founding epoch.
    assert line["value"] >= 3, line
    assert line["steps"] > 0
    assert line["rebuild_ms_p50"] > 0
    assert line["rebuild_ms_p99"] >= line["rebuild_ms_p50"]


def test_bench_profile_smoke():
    """bench.py --profile --quick (2 ranks): one per-phase breakdown
    JSON line per (size x algorithm) cell plus the profiler overhead
    A/B line (docs/profiling.md). Each cell must profile its timed ops
    and the breakdown must carry canonical phase names."""
    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--profile", "--quick"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    cells = [l for l in lines if l["metric"] == "profile_phases"]
    abs_ = [l for l in lines if l["metric"] == "profile_overhead_ab"]
    assert len(cells) == 3 and len(abs_) == 1, proc.stdout
    phase_names = {"pack", "post", "wire_wait", "reduce", "unpack",
                   "intra", "inter", "fanout"}
    for cell in cells:
        assert cell["ok"] is True, cell
        assert cell["profiled_ops"] > 0, cell
        assert cell["mean_phase_us"], cell
        assert set(cell["mean_phase_us"]) <= phase_names, cell
        assert "wire_wait" in cell["mean_phase_us"], cell
    ab = abs_[0]
    assert ab["ok"] is True, ab
    assert ab["p50_us_profile_on"] > 0 and ab["p50_us_profile_off"] > 0


def test_bench_wire_sweep_smoke():
    """bench.py --wire-sweep --quick (2 ranks): the four sections the
    committed WIRE_r20.json is built from — the wire grid (one line per
    codec arm, the crossover data auto_lossy_wire is elected from), the
    pipelined-vs-serial interleaved A/B, the codec-thread scaling curve,
    and the phase-attribution A/B with its pack+unpack cut line. Values
    are not ranked: on a shared-core CI host the codec arms' CPU cost
    can legitimately beat their wire savings; each run self-verifies
    its reduced values before timing."""
    import json

    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--wire-sweep", "--quick"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    by_metric = {}
    for line in lines:
        assert line["ok"] is True, line
        by_metric.setdefault(line["metric"], []).append(line)
    grid = by_metric.pop("wire_sweep")
    assert {c["algorithm"] for c in grid} == {
        "ring", "ring_bf16_wire", "ring_q8_wire", "ring_q4_wire"}
    assert all(c["value"] > 0 for c in grid)
    ab = by_metric.pop("wire_pipeline_ab")
    assert {(c["algorithm"], c["arm"]) for c in ab} == {
        (a, arm) for a in ("ring_q8_wire", "ring_q4_wire")
        for arm in ("serial", "pipelined")}
    threads = by_metric.pop("wire_codec_threads")
    assert sorted(c["codec_threads"] for c in threads) == [1, 2, 4]
    phases = by_metric.pop("wire_phase_ab")
    assert {c["arm"] for c in phases} == {"serial", "pipelined"}
    assert all(c["mean_phase_us"] for c in phases)
    (cut,) = by_metric.pop("wire_phase_cut")
    assert cut["pack_unpack_us"]["serial"] > 0
    assert cut["pack_unpack_us"]["pipelined"] > 0
    assert not by_metric, by_metric


def test_bench_bootstrap_sweep_smoke():
    """bench.py --bootstrap-sweep --quick: the choreography cells run
    both rendezvous arms at N in {8, 32}, the real 8-rank lazy vs full
    bring-up verifies its collectives and holds the broker cap under a
    mixed soak, and the aggregated-lease elastic probe rebuilds — the
    committed BOOT_r18.json records the full N<=512 curves (where the
    lazy arm's win is ranked; quick Ns sit below the crossover, so
    wall ratios are not asserted here)."""
    import json
    import tempfile

    out = os.path.join(tempfile.mkdtemp(), "boot_sweep.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"),
         "--bootstrap-sweep", "--quick", "--bootstrap-out", out],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    with open(out) as f:
        doc = json.load(f)
    assert doc["metric"] == "bootstrap_scale_sweep"
    assert doc["ok"] is True, doc
    assert [c["nranks"] for c in doc["choreography"]] == [8, 32]
    for cell in doc["choreography"]:
        # The relayed protocol's structural win holds at any N.
        assert cell["ops_ratio"] > 1.0, cell
    e2e = doc["e2e_8rank"]
    assert e2e["ok"] is True, e2e
    assert max(e2e["soak"]["broker_pairs_end"]) <= e2e["cap"]
    assert e2e["soak"]["evictions"] > 0
    assert doc["elastic_rebuild"]["ok"] is True, doc["elastic_rebuild"]
