"""TcpStore: in-process contract tests + cross-process rendezvous."""

import os
import subprocess
import sys
import textwrap
import threading

import pytest

import gloo_tpu

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def server():
    return gloo_tpu.TcpStoreServer("127.0.0.1")


def test_set_get_add(server):
    a = gloo_tpu.TcpStore("127.0.0.1", server.port)
    b = gloo_tpu.TcpStore("127.0.0.1", server.port)
    a.set("k", b"\x00binary\xff")
    assert b.get("k") == b"\x00binary\xff"
    a.set("empty", b"")
    assert b.get("empty") == b""
    assert a.add("n", 7) == 7
    assert b.add("n", -2) == 5


def test_blocking_get(server):
    a = gloo_tpu.TcpStore("127.0.0.1", server.port)
    b = gloo_tpu.TcpStore("127.0.0.1", server.port)
    out = {}
    t = threading.Thread(target=lambda: out.update(v=b.get("wait", 5.0)))
    t.start()
    a.set("wait", b"x")
    t.join(5)
    assert out["v"] == b"x"


def test_get_timeout(server):
    a = gloo_tpu.TcpStore("127.0.0.1", server.port)
    with pytest.raises(gloo_tpu.TimeoutError):
        a.get("missing", timeout=0.2)


def test_prefix_over_tcp(server):
    base = gloo_tpu.TcpStore("127.0.0.1", server.port)
    p1 = gloo_tpu.PrefixStore(base, "g1")
    p1.set("k", b"v1")
    assert p1.get("k") == b"v1"
    base2 = gloo_tpu.TcpStore("127.0.0.1", server.port)
    assert base2.get("g1/k") == b"v1"


def test_cross_process_rendezvous(server):
    """Full-mesh bootstrap + allreduce across real processes, TcpStore
    rendezvous (the no-shared-filesystem multi-host story)."""
    size = 3
    prog = textwrap.dedent("""
        import sys
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu
        rank = int(sys.argv[1])
        store = gloo_tpu.TcpStore("127.0.0.1", {port})
        ctx = gloo_tpu.Context(rank, {size}, timeout=15.0)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        x = np.full(100, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == 6.0, x[0]
        ctx.close()
        print("OK")
    """).format(repo=_REPO, port=server.port, size=size)
    procs = [subprocess.Popen([sys.executable, "-c", prog, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for r in range(size)]
    outs = [p.communicate(timeout=60) for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK" in out[0]
