"""In-band fleet observability plane (ISSUE 16, docs/fleet.md).

The plane folds every rank's metrics / profile / health report up the
host topology over the collective transport itself — members to their
host leader, leaders to rank 0 — so rank 0 serves one merged ``/fleet``
document with O(hosts) inbound traffic and NO side-channel: members
never open a telemetry connection to rank 0 (the only HTTP server in
these tests runs on rank 0, and the in-band document is complete
regardless).

Topology simulation follows test_group.py: each rank overrides its host
fingerprint (Context.set_host_id) so one machine presents as H
simulated hosts, which makes the member -> leader -> rank 0 relay real.
"""

import json
import os
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu.utils import fleet as fleet_util
from gloo_tpu.utils.telemetry import fetch_route, serve_telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_hosts(size, rph, fn, timeout=90.0, context_timeout=45.0):
    """Threaded grid with a simulated multi-host topology: rank r
    presents host fingerprint fleet-host<r // rph>."""
    store = gloo_tpu.HashStore()
    results = [None] * size
    errors = []
    lock = threading.Lock()

    def worker(rank):
        ctx = None
        try:
            device = gloo_tpu.Device()
            ctx = gloo_tpu.Context(rank, size, timeout=context_timeout)
            ctx.set_host_id(f"fleet-host{rank // rph}")
            ctx.connect_full_mesh(store, device)
            results[rank] = fn(ctx, rank)
        except BaseException as exc:  # noqa: BLE001 - surfaced to caller
            with lock:
                errors.append((rank, exc))
        finally:
            if ctx is not None:
                try:
                    ctx.close()
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"rank thread did not finish in {timeout}s")
    if errors:
        rank, exc = errors[0]
        raise AssertionError(f"rank {rank} failed: {exc!r}") from exc
    return results


def _poll(predicate, deadline_s, interval_s=0.05):
    """Poll predicate() until truthy or the deadline; returns the last
    value either way (callers assert on it for a useful message)."""
    deadline = time.monotonic() + deadline_s
    value = predicate()
    while not value and time.monotonic() < deadline:
        time.sleep(interval_s)
        value = predicate()
    return value


def _sync_until(ctx, rank, done_fn, deadline_s=30.0):
    """Keep ALL ranks alive (and their planes relaying) until rank 0's
    done_fn() is truthy: every iteration is one tiny allreduce where
    rank 0 contributes 1.0 once done — so the whole grid agrees on the
    exit round and nobody tears down the mesh under a live tick."""
    deadline = time.monotonic() + deadline_s
    while True:
        flag = np.zeros(1, dtype=np.float32)
        if rank == 0 and done_fn():
            flag[0] = 1.0
        ctx.allreduce(flag)
        if flag[0] > 0:
            return True
        if time.monotonic() > deadline:
            return False
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# hierarchical aggregation: the grid acceptance (P >= 8, simulated hosts)
# ---------------------------------------------------------------------------

def test_fleet_covers_all_ranks_over_simulated_hosts(monkeypatch):
    """8 ranks across 4 simulated hosts: rank 0's /fleet document (both
    Context.fleet() and the HTTP route) reaches complete coverage with
    every rank's report relayed in-band through its host leader — no
    member ever opens a telemetry connection (the sole HTTP server runs
    on rank 0, started after coverage already completed in-band)."""
    monkeypatch.setenv("TPUCOLL_FLEETOBS_INTERVAL_MS", "80")
    monkeypatch.setenv("TPUCOLL_FLEETOBS_WINDOW", "5")
    size, rph = 8, 2

    def fn(ctx, rank):
        ctx.fleetobs_start()
        assert ctx.fleetobs_running()
        # Some collective traffic so reports carry ops + link stats.
        x = np.ones(256, dtype=np.float32)
        for _ in range(5):
            ctx.allreduce(x.copy())

        out = {}
        if rank == 0:
            def complete():
                doc = ctx.fleet()
                return (doc if fleet_util.coverage(doc)["complete"]
                        else None)
            doc = _poll(complete, 25.0)
            assert doc, f"no full coverage: {ctx.fleet()}"
            out["doc"] = doc
            # The HTTP route serves the very same merged document.
            with serve_telemetry(ctx, port=0) as srv:
                served = fetch_route(srv.url, "/fleet", timeout=5.0)
            out["served"] = served
        else:
            out["doc"] = ctx.fleet()
        ok = _sync_until(ctx, rank, lambda: "doc" in out)
        assert ok, "grid did not agree on completion"
        ctx.fleetobs_stop()
        assert not ctx.fleetobs_running()
        return out

    results = spawn_hosts(size, rph, fn)

    doc = results[0]["doc"]
    assert doc["kind"] == "fleet" and doc["enabled"] is True
    cov = fleet_util.coverage(doc)
    assert cov == {"expected": size, "reported": size, "missing": [],
                   "complete": True}
    reps = fleet_util.reports(doc)
    assert sorted(reps) == list(range(size))
    assert len(doc["hosts"]) == size // rph
    for host in doc["hosts"]:
        # Host docs carry their leader and only their own members.
        member_ranks = sorted(int(r) for r in host["ranks"])
        assert member_ranks == [host["host_index"] * rph,
                                host["host_index"] * rph + 1]
        assert host["leader"] == member_ranks[0]
    for rank, rep in reps.items():
        assert rep["rank"] == rank
        assert rep["ok"] is True and rep["errors"] == 0
        assert rep["calls"] > 0, f"rank {rank} report carried no ops"
    # Link telemetry made it into the reports (tentpole a -> b).
    assert any(rep.get("links") for rep in reps.values())
    # The HTTP route returned the same aggregation (round advances
    # between the two snapshots; coverage must not regress).
    served = results[0]["served"]
    assert fleet_util.coverage(served)["complete"]

    # Non-root ranks answer with an honest stub pointing at rank 0.
    for rank in range(1, size):
        stub = results[rank]["doc"]
        assert stub["enabled"] in (True, False)
        assert stub["role"] == ("leader" if rank % rph == 0 else "member")
        assert stub["hosts"] == []
        assert "rank 0" in stub["note"]


def test_fleetobs_disabled_by_env(monkeypatch):
    """TPUCOLL_FLEETOBS=0: start() is a no-op — no thread, no wire
    buffers, and fleet() says so instead of serving stale data."""
    monkeypatch.setenv("TPUCOLL_FLEETOBS", "0")

    def fn(ctx, rank):
        ctx.fleetobs_start()
        assert not ctx.fleetobs_running()
        return ctx.fleet()

    docs = spawn_hosts(2, 1, fn)
    for doc in docs:
        assert doc["enabled"] is False
        assert doc["hosts"] == []


def test_fleetobs_not_started_stub():
    """fleet() before fleetobs_start(): a stub document, not an error
    (dashboards probe /fleet on every rank unconditionally)."""
    def fn(ctx, rank):
        return ctx.fleet()

    docs = spawn_hosts(2, 2, fn)
    for rank, doc in enumerate(docs):
        assert doc["enabled"] is False
        assert doc["rank"] == rank
        assert "note" in doc


# ---------------------------------------------------------------------------
# continuous anomaly detection (tentpole c)
# ---------------------------------------------------------------------------

def test_chaos_delayed_rank_trips_persistent_straggler(monkeypatch):
    """Chaos acceptance: one rank sleeps before every collective; the
    in-band detector on rank 0 must blame exactly that rank with a
    persistent_straggler anomaly visible in ALL THREE mirrors — the
    /fleet document, rank 0's flight-recorder ring, and the
    gloo_tpu_anomaly_total metrics counter."""
    monkeypatch.setenv("TPUCOLL_FLEETOBS_INTERVAL_MS", "80")
    monkeypatch.setenv("TPUCOLL_FLEETOBS_WINDOW", "40")
    monkeypatch.setenv("TPUCOLL_FLEETOBS_STRAGGLER_MS", "50")
    size, rph, laggard = 4, 2, 3

    def fn(ctx, rank):
        ctx.fleetobs_start()
        x = np.ones(64, dtype=np.float32)
        for _ in range(12):
            if rank == laggard:
                time.sleep(0.03)
            ctx.allreduce(x.copy())

        out = {}
        if rank == 0:
            def fired():
                doc = ctx.fleet()
                hits = [ev for ev
                        in doc.get("anomalies", {}).get("recent", [])
                        if ev["kind"] == "persistent_straggler"]
                return (doc, hits) if hits else None
            got = _poll(fired, 25.0)
            assert got, f"no straggler anomaly: {ctx.fleet()}"
            out["doc"], out["hits"] = got
            out["flightrec"] = ctx.flightrec()
            out["metrics"] = ctx.metrics()
        ok = _sync_until(ctx, rank, lambda: "doc" in out)
        assert ok, "grid did not agree on completion"
        ctx.fleetobs_stop()
        return out

    results = spawn_hosts(size, rph, fn)
    doc, hits = results[0]["doc"], results[0]["hits"]

    # 1) the /fleet document blames the delayed rank...
    assert all(ev["rank"] == laggard for ev in hits), hits
    assert doc["anomalies"]["total"] >= len(hits)
    # ...and its leaderboard agrees on who the fleet waits for.
    board = doc["straggler"]["leaderboard"]
    assert board and board[0]["rank"] == laggard, board
    assert board[0]["blamed_us"] >= 50_000

    # 2) the flight recorder carries the same event in-ring.
    anomaly_events = [e for e in results[0]["flightrec"]["events"]
                      if e["op"] == "anomaly:persistent_straggler"]
    assert anomaly_events, "anomaly missing from the flight recorder"
    assert all(e["peer"] == laggard for e in anomaly_events)

    # 3) the metrics registry counted it under the blamed rank.
    kinds = results[0]["metrics"]["anomalies"]["kinds"]
    assert kinds.get("persistent_straggler", {}).get(str(laggard), 0) >= 1


def test_lease_jitter_detector_fires_from_aux(monkeypatch):
    """A member publishing an elastic aux whose renewal counter never
    advances (agent wedged) must trip lease_jitter for that rank once
    the observation span covers >= 4 lease periods."""
    monkeypatch.setenv("TPUCOLL_FLEETOBS_INTERVAL_MS", "60")
    monkeypatch.setenv("TPUCOLL_FLEETOBS_WINDOW", "50")

    def fn(ctx, rank):
        ctx.fleetobs_start()
        if rank == 1:
            # A wedged agent: the renewal counter never advances.
            ctx.fleetobs_set_aux(
                {"elastic": {"lease_ms": 20, "leases_renewed": 7}})
        out = {}
        if rank == 0:
            def fired():
                doc = ctx.fleet()
                hits = [ev for ev
                        in doc.get("anomalies", {}).get("recent", [])
                        if ev["kind"] == "lease_jitter"]
                return hits or None
            hits = _poll(fired, 20.0)
            assert hits, f"no lease_jitter anomaly: {ctx.fleet()}"
            out["hits"] = hits
        ok = _sync_until(ctx, rank, lambda: "hits" in out)
        assert ok, "grid did not agree on completion"
        ctx.fleetobs_stop()
        return out

    results = spawn_hosts(2, 2, fn)
    assert all(ev["rank"] == 1 for ev in results[0]["hits"])


def test_set_aux_rejects_malformed_json():
    from gloo_tpu import _lib

    def fn(ctx, rank):
        ctx.fleetobs_start()
        with pytest.raises(gloo_tpu.Error):
            _lib.check(_lib.lib.tc_fleetobs_set_aux(
                ctx._handle, b"{not json"))
        ctx.fleetobs_stop()

    spawn_hosts(1, 1, fn)


# ---------------------------------------------------------------------------
# document consumers: utils.fleet helpers + the shared tools client
# ---------------------------------------------------------------------------

_SYNTH_FLEET = {
    "version": 1, "kind": "fleet", "rank": 0, "size": 4, "enabled": True,
    "round": 9, "interval_ms": 1000,
    "hosts": [
        {"host_index": 0, "leader": 0, "ranks": {
            "0": {"rank": 0, "ok": True, "stalls": 0, "errors": 0},
            "1": {"rank": 1, "ok": False, "failure_peer": 2,
                  "stalls": 2, "errors": 1}}},
        {"host_index": 1, "leader": 2, "ranks": {
            "2": {"rank": 2, "ok": True, "stalls": 0, "errors": 0}}},
    ],
    "coverage": {"expected": 4, "reported": 3, "missing": [3]},
    "straggler": {"window_rounds": 30, "ops_window": 64,
                  "leaderboard": [{"rank": 1, "blamed_us": 120000,
                                   "blamed_ops": 8}]},
    "slow_links": [{"rank": 2, "peer": 0, "bw_bps": 1e6,
                    "median_bps": 2e7}],
    "anomalies": {"total": 3, "recent": [
        {"kind": "persistent_straggler", "rank": 1, "t_us": 1,
         "detail": 120000}]},
}


def test_fleet_helpers_on_synthetic_document():
    assert fleet_util.reports(_SYNTH_FLEET).keys() == {0, 1, 2}
    cov = fleet_util.coverage(_SYNTH_FLEET)
    assert cov["missing"] == [3] and not cov["complete"]

    bad = fleet_util.unhealthy(_SYNTH_FLEET)
    assert [e["rank"] for e in bad] == [1]
    assert len(bad[0]["reasons"]) == 3  # failure + stalls + errors

    s = fleet_util.summarize(_SYNTH_FLEET)
    assert s["hosts"] == 2 and s["anomalies_total"] == 3
    assert s["recent_anomalies_by_kind"] == {"persistent_straggler": 1}

    text = fleet_util.render(_SYNTH_FLEET)
    assert "coverage 3/4" in text and "missing: [3]" in text
    assert "unhealthy rank 1" in text
    assert "slow link 2->0" in text
    assert "persistent_straggler" in text

    # Coverage recomputes from the embedded reports when the document
    # lost its own coverage section (truncated relay).
    clipped = {k: v for k, v in _SYNTH_FLEET.items() if k != "coverage"}
    assert fleet_util.coverage(clipped)["missing"] == [3]

    # Stub documents render as an explicit "not here" line.
    stub = {"enabled": False, "note": "fleet view is aggregated at rank 0"}
    assert "disabled/stub" in fleet_util.render(stub)


def test_tools_fleet_mode_renders_saved_document(tmp_path):
    """Both viewers expose the shared --fleet source mode; exercised via
    the profile viewer CLI against a saved document (exit 1: the synth
    document has a coverage hole and recent anomalies)."""
    path = tmp_path / "fleet.json"
    path.write_text(json.dumps(_SYNTH_FLEET))
    for tool in ("profile_view.py", "flightrec_view.py"):
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", tool),
             str(path), "--fleet"],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1, (tool, proc.stderr)
        assert "coverage 3/4" in proc.stdout, (tool, proc.stdout)


# ---------------------------------------------------------------------------
# telemetry server hardening (satellite: close() joins + rebind)
# ---------------------------------------------------------------------------

class _StubCtx:
    rank = 0

    def metrics(self, drain=False):
        return {"rank": 0, "ops": {}, "transport": {}, "watchdog": {}}

    def profile(self):
        return {"rank": 0, "ops": []}

    def flightrec(self):
        return {"rank": 0, "events": []}


def test_telemetry_close_frees_port_for_rebind():
    """Regression (satellite 2): close() joins the serving thread and
    releases the socket, and SO_REUSEADDR is pinned on — a restarting
    rank rebinds its fixed TPUCOLL_TELEMETRY_PORT immediately, even
    with the old sockets in TIME_WAIT."""
    first = serve_telemetry(_StubCtx(), port=0)
    port = first.port
    assert fetch_route(first.url, "/healthz", timeout=5.0)["ok"]
    first.close()
    first.close()  # idempotent, not an error

    second = serve_telemetry(_StubCtx(), port=port)
    try:
        assert second.port == port
        assert fetch_route(second.url, "/healthz", timeout=5.0)["ok"]
    finally:
        second.close()


# ---------------------------------------------------------------------------
# mode-2 smoke: real processes over a FileStore (per-process host ids)
# ---------------------------------------------------------------------------

_PROC_BODY = """
ctx.fleetobs_start()
x = np.ones(128, dtype=np.float32)
for _ in range(6):
    ctx.allreduce(x.copy())
deadline = time.monotonic() + 30.0
done = False
while True:
    flag = np.zeros(1, dtype=np.float32)
    if rank == 0:
        from gloo_tpu.utils import fleet as fleet_util
        if fleet_util.coverage(ctx.fleet())["complete"]:
            done = True
            flag[0] = 1.0
    ctx.allreduce(flag)
    if flag[0] > 0:
        break
    if time.monotonic() > deadline:
        print("TIMEOUT", ctx.fleet())
        sys.exit(4)
    time.sleep(0.05)
if rank == 0:
    print("FLEET-COMPLETE")
ctx.fleetobs_stop()
ctx.close()
sys.exit(0)
"""


def test_multiproc_filestore_fleet_smoke():
    """Real child processes (one per rank, TPUCOLL_HOST_ID per process,
    FileStore rendezvous): rank 0's in-band document reaches full
    coverage — the same smoke CI runs, kept in-tree so it reproduces
    locally with plain pytest."""
    size, rph = 4, 2
    store = tempfile.mkdtemp()
    procs = []
    for rank in range(size):
        prog = textwrap.dedent("""
            import os, sys, time
            sys.path.insert(0, {repo!r})
            import numpy as np
            import gloo_tpu

            rank = {rank}; size = {size}
            store = gloo_tpu.FileStore({store!r})
            ctx = gloo_tpu.Context(rank, size, timeout=30.0)
            ctx.connect_full_mesh(store, gloo_tpu.Device())
        """).format(repo=_REPO, rank=rank, size=size, store=store) \
            + textwrap.dedent(_PROC_BODY)
        env = dict(os.environ,
                   TPUCOLL_HOST_ID=f"flthost{rank // rph}",
                   TPUCOLL_FLEETOBS_INTERVAL_MS="80")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", prog], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=120) for p in procs]
    codes = [p.returncode for p in procs]
    assert codes == [0] * size, (codes, outs)
    assert "FLEET-COMPLETE" in outs[0][0], outs[0]
