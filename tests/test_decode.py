"""Incremental decoding: the KV-cached step must reproduce the full
forward exactly, and greedy generate must be self-consistent."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

from gloo_tpu.models import Transformer, TransformerConfig  # noqa: E402


def _model(n_kv_heads=None, use_rope=False):
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq_len=32,
                            n_kv_heads=n_kv_heads, use_rope=use_rope,
                            dtype=jnp.float32)
    m = Transformer(cfg)
    return m, m.init(jax.random.PRNGKey(0))


@pytest.mark.parametrize("n_kv_heads,use_rope",
                         [(None, False), (2, True), (1, False)])
def test_decode_step_matches_full_forward(n_kv_heads, use_rope):
    m, p = _model(n_kv_heads, use_rope)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 12)))
    full = m.apply(p, toks)
    cache = m.init_cache(2, 12)
    outs = []
    for i in range(12):
        logits, cache = m.decode_step(p, cache, toks[:, i])
        outs.append(logits)
    np.testing.assert_allclose(np.asarray(jnp.stack(outs, axis=1)),
                               np.asarray(full), rtol=1e-4, atol=1e-4)


def test_generate_greedy_consistent():
    m, p = _model(n_kv_heads=2, use_rope=True)
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 4)))
    gen = m.generate(p, prompt, max_new=6)
    assert gen.shape == (2, 10)
    assert np.array_equal(np.asarray(gen[:, :4]), np.asarray(prompt))
    # re-scoring the output reproduces every greedy choice
    logits = m.apply(p, gen[:, :-1])
    greedy = jnp.argmax(logits[:, 3:], axis=-1)
    assert bool(jnp.all(greedy == gen[:, 4:]))


def test_gqa_cache_is_smaller():
    m_full, _ = _model(None)
    m_gqa, _ = _model(1)
    full = m_full.init_cache(1, 32)["k"][0]
    mqa = m_gqa.init_cache(1, 32)["k"][0]
    assert full.shape[1] == 4 and mqa.shape[1] == 1


def test_generate_zero_new_tokens():
    m, p = _model()
    prompt = jnp.asarray(np.random.RandomState(2).randint(0, 64, (1, 4)))
    out = m.generate(p, prompt, max_new=0)
    assert np.array_equal(np.asarray(out), np.asarray(prompt))


def test_init_cache_rejects_overlong_learned_positions():
    m, _ = _model(use_rope=False)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        m.init_cache(1, 64)  # max_seq_len is 32
    # RoPE has no table: long caches are fine
    m2, _ = _model(use_rope=True)
    m2.init_cache(1, 64)


def test_generate_sampling():
    m, p = _model(use_rope=True)
    prompt = jnp.asarray(np.random.RandomState(3).randint(0, 64, (2, 4)))
    a = m.generate(p, prompt, 8, temperature=1.0, top_k=8,
                   key=jax.random.PRNGKey(1))
    b = m.generate(p, prompt, 8, temperature=1.0, top_k=8,
                   key=jax.random.PRNGKey(2))
    c = m.generate(p, prompt, 8, temperature=1.0, top_k=8,
                   key=jax.random.PRNGKey(1))
    assert a.shape == (2, 12)
    assert np.array_equal(np.asarray(a), np.asarray(c))  # deterministic
    assert not np.array_equal(np.asarray(a), np.asarray(b))  # keyed
    with pytest.raises(ValueError, match="requires `key`"):
        m.generate(p, prompt, 4, temperature=0.7)


def test_generate_rejects_bad_sampling_args():
    m, p = _model()
    prompt = jnp.asarray(np.random.RandomState(4).randint(0, 64, (1, 4)))
    with pytest.raises(ValueError, match="temperature"):
        m.generate(p, prompt, 2, temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        m.generate(p, prompt, 2, temperature=1.0, top_k=0,
                   key=jax.random.PRNGKey(0))
