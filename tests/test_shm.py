"""Shared-memory payload plane (csrc/tpucoll/transport/shm.{h,cc}).

Same-host pairs negotiate a pair-private shm segment at connect time and
move large payloads through lock-free rings while the TCP stream stays the
control plane. The reference only records intra-host awareness
(gloo/transport/pair.h:79-100 localRank); this is the NCCL-style fast path
built on it. Covered here: engagement + correctness over threads and real
processes, the small-message TCP path, ring-wrap/credit flow control under
a tiny ring, one-sided put/get payloads, the encrypted tier, kill-a-rank
failure handling, and the TPUCOLL_SHM=0 opt-out."""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_shm_engages_for_large_payloads():
    """Same-host pairs negotiate shm and big collectives ride it."""
    size = 3
    n = 1 << 20  # 4 MiB f32, far above the 32 KiB threshold

    def fn(ctx, rank):
        x = np.full(n, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == 6.0 and x[-1] == 6.0
        return ctx.shm_stats()

    for stats in spawn(size, fn):
        assert stats["active_pairs"] == size - 1
        assert stats["tx_bytes"] > 0
        assert stats["rx_bytes"] > 0


def test_small_messages_stay_on_tcp():
    """Below the threshold the eager TCP path is untouched (no chunk
    round trips on the latency path)."""
    def fn(ctx, rank):
        x = np.full(256, float(rank + 1), dtype=np.float32)  # 1 KiB
        ctx.allreduce(x)
        assert x[0] == 3.0
        return ctx.shm_stats()

    for stats in spawn(2, fn):
        assert stats["active_pairs"] == 1  # negotiated...
        assert stats["tx_bytes"] == 0      # ...but unused below threshold


def test_shm_mixed_sizes_and_recv_any():
    """Interleaved small (TCP) and large (shm) tagged traffic, including a
    recv-from-any that matches a large shm message, lands correctly."""
    big = 1 << 18  # 1 MiB f32

    def fn(ctx, rank):
        if rank == 0:
            small = np.array([7.0], dtype=np.float32)
            large = np.arange(big, dtype=np.float32)
            sb = ctx.register(small)
            lb = ctx.register(large)
            sb.send(1, slot=1)
            lb.send(1, slot=2)
            sb.wait_send()
            lb.wait_send()
            return None
        small = np.zeros(1, dtype=np.float32)
        large = np.zeros(big, dtype=np.float32)
        sb = ctx.register(small)
        lb = ctx.register(large)
        lb.recv([0], slot=2)  # recv-from-any (singleton source set)
        sb.recv(0, slot=1)
        assert sb.wait_recv() == 0
        assert lb.wait_recv() == 0
        assert small[0] == 7.0
        assert large[0] == 0.0 and large[-1] == big - 1
        assert np.array_equal(large, np.arange(big, dtype=np.float32))
        return ctx.shm_stats()

    results = spawn(2, fn)
    assert results[1]["rx_bytes"] >= big * 4


def test_shm_onesided_put_get():
    """One-sided put (with notify) and get payloads above the threshold
    ride the ring straight into/out of the registered region."""
    n = 1 << 17  # 512 KiB
    # Keys cross ranks through the thread harness's shared list.
    keys = [None, None]
    import threading
    barrier = threading.Barrier(2)

    def fn2(ctx, rank):
        region = np.full(n, float(rank), dtype=np.float32)
        buf = ctx.register(region)
        keys[rank] = buf.get_remote_key()
        barrier.wait()
        peer = 1 - rank
        if rank == 0:
            src = np.arange(n, dtype=np.float32)
            sbuf = ctx.register(src)
            sbuf.put(keys[peer], notify=True)
            sbuf.wait_send()
            # Read the peer's (now overwritten) region back.
            dst = np.zeros(n, dtype=np.float32)
            dbuf = ctx.register(dst)
            dbuf.get(keys[peer], slot=99)
            dbuf.wait_recv()
            assert np.array_equal(dst, src)
        else:
            buf.wait_put()
            assert region[0] == 0.0 and region[-1] == n - 1
        barrier.wait()
        ctx.barrier()
        return ctx.shm_stats()

    results = spawn(2, fn2, timeout=60)
    assert results[0]["tx_bytes"] >= n * 4
    # The get response (an op-owned data payload) rode the ring too.
    assert results[0]["rx_bytes"] >= n * 4


def test_shm_encrypted_tier():
    """shm engages under Device(encrypt=True): headers stay sealed on the
    wire while payloads ride the same-host ring."""
    def fn(ctx, rank):
        x = np.full(1 << 18, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == 3.0
        return ctx.shm_stats()

    results = spawn(2, fn, device_kwargs={
        "auth_key": "shm-test-key", "encrypt": True})
    assert all(s["tx_bytes"] > 0 for s in results)


def _run_subprocess_case(body: str, env: dict) -> None:
    """Env-sensitive cases need a fresh process: the shm config is latched
    on first use (process-wide statics)."""
    prog = textwrap.dedent("""
        import sys
        sys.path.insert(0, {repo!r})
        import numpy as np
        import threading
        import gloo_tpu

        store = gloo_tpu.HashStore()
        results = [None, None]
        def worker(rank):
            ctx = gloo_tpu.Context(rank, 2, timeout=20)
            ctx.connect_full_mesh(store, gloo_tpu.Device())
            try:
    """).format(repo=_REPO) + textwrap.indent(textwrap.dedent(body), " " * 16) + \
        textwrap.dedent("""
            finally:
                ctx.close()
        ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
        [t.start() for t in ts]
        [t.join(60) for t in ts]
        assert all(r == "ok" for r in results), results
        print("SUBPROC-OK")
    """)
    full_env = dict(os.environ)
    full_env.update(env)
    out = subprocess.run([sys.executable, "-c", prog], env=full_env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "SUBPROC-OK" in out.stdout


def test_shm_opt_out():
    """TPUCOLL_SHM=0 keeps every payload on TCP."""
    _run_subprocess_case("""
        x = np.full(1 << 18, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == 3.0
        stats = ctx.shm_stats()
        assert stats["active_pairs"] == 0, stats
        assert stats["tx_bytes"] == 0, stats
        results[rank] = "ok"
    """, {"TPUCOLL_SHM": "0"})


def test_shm_tiny_ring_wraps_and_credits():
    """A 64 KiB ring forces many chunk/credit cycles and ring wraparound
    for a 4 MiB payload; data must still land intact (random pattern)."""
    _run_subprocess_case("""
        rng = np.random.RandomState(rank)
        n = 1 << 20
        if rank == 0:
            src = rng.rand(n).astype(np.float32)
            expect_sum = float(src.sum())
            buf = ctx.register(src)
            buf.send(1, slot=5)
            buf.wait_send()
            meta = np.array([expect_sum], dtype=np.float64)
            mbuf = ctx.register(meta)
            mbuf.send(1, slot=6)
            mbuf.wait_send()
        else:
            dst = np.zeros(n, dtype=np.float32)
            buf = ctx.register(dst)
            buf.recv(0, slot=5)
            buf.wait_recv()
            meta = np.zeros(1, dtype=np.float64)
            mbuf = ctx.register(meta)
            mbuf.recv(0, slot=6)
            mbuf.wait_recv()
            assert abs(float(dst.sum()) - meta[0]) < 1e-3, "payload corrupt"
            stats = ctx.shm_stats()
            assert stats["rx_bytes"] >= n * 4, stats
        results[rank] = "ok"
    """, {"TPUCOLL_SHM_RING": "65536", "TPUCOLL_SHM_THRESHOLD": "1024"})


def test_shm_bidirectional_saturation():
    """Both directions streaming at once with a small ring: exercises the
    credit-bypass path (control frames preempting at message boundaries)
    without deadlock."""
    _run_subprocess_case("""
        n = 1 << 19
        peer = 1 - rank
        src = np.full(n, float(rank + 1), dtype=np.float32)
        dst = np.zeros(n, dtype=np.float32)
        for it in range(4):
            sb = ctx.register(src)
            rb = ctx.register(dst)
            sb.send(peer, slot=10 + it)
            rb.recv(peer, slot=10 + it)
            sb.wait_send()
            rb.wait_recv()
            assert dst[0] == float(peer + 1) and dst[-1] == float(peer + 1)
        results[rank] = "ok"
    """, {"TPUCOLL_SHM_RING": "131072", "TPUCOLL_SHM_THRESHOLD": "1024"})


def _spawn_proc(body: str, rank: int, size: int, store: str, env=None):
    prog = textwrap.dedent("""
        import os, signal, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = {rank}; size = {size}
        store = gloo_tpu.FileStore({store!r})
        ctx = gloo_tpu.Context(rank, size, timeout=10.0)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
    """).format(repo=_REPO, rank=rank, size=size, store=store) + \
        textwrap.dedent(body)
    full_env = dict(os.environ)
    full_env.update(env or {})
    return subprocess.Popen([sys.executable, "-c", prog], env=full_env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def test_shm_cross_process():
    """Real processes (separate address spaces): the segment actually
    shares memory and the allreduce is correct; stats confirm the ring
    carried the payload."""
    store = tempfile.mkdtemp()
    body = """
x = np.full(1 << 20, float(rank + 1), dtype=np.float32)
ctx.allreduce(x)
assert x[0] == 3.0 and x[-1] == 3.0
stats = ctx.shm_stats()
assert stats["active_pairs"] == 1, stats
assert stats["tx_bytes"] > 0, stats
print("PROC-OK")
ctx.close()
"""
    procs = [_spawn_proc(body, r, 2, store) for r in range(2)]
    outs = [p.communicate(timeout=60) for p in procs]
    for (stdout, stderr), p in zip(outs, procs):
        assert p.returncode == 0, (stdout, stderr)
        assert "PROC-OK" in stdout


def test_shm_peer_killed_mid_stream():
    """SIGKILL a rank mid-shm-traffic: survivors get a fast IoError (the
    TCP control plane detects the death; nothing blocks on the ring)."""
    store = tempfile.mkdtemp()
    killer = """
os.kill(os.getpid(), signal.SIGKILL)
"""
    victim = """
x = np.ones(1 << 21, dtype=np.float32)
t0 = time.monotonic()
try:
    for _ in range(50):
        ctx.allreduce(x)
    print("UNEXPECTED-SUCCESS")
    sys.exit(3)
except gloo_tpu.IoError:
    print(f"IOERROR {time.monotonic() - t0:.3f}")
    sys.exit(10)
"""
    procs = [_spawn_proc(killer if r == 1 else victim, r, 2, store)
             for r in range(2)]
    outs = [p.communicate(timeout=60) for p in procs]
    assert procs[1].returncode == -signal.SIGKILL
    assert procs[0].returncode == 10, outs[0]
    assert "IOERROR" in outs[0][0]


@pytest.mark.parametrize("seed", [0, 2])
def test_shm_stress_fuzz(seed):
    """Re-run the randomized collective-sequence fuzz with a 64-byte
    threshold and a 64 KiB ring: virtually every message rides shm, with
    constant wraparound and credit traffic — the chunk/credit machinery's
    soak test, verified against numpy by the fuzz's own oracle."""
    env = dict(os.environ)
    env.update({"TPUCOLL_SHM_THRESHOLD": "64", "TPUCOLL_SHM_RING": "65536",
                "TPUCOLL_SKIP_BUILD": "1"})
    out = subprocess.run(
        [sys.executable, "-m", "pytest",
         f"tests/test_fuzz.py::test_fuzz_collective_sequences[{seed}]",
         "-q", "-p", "no:cacheprovider"],
        env=env, capture_output=True, text=True, timeout=300, cwd=_REPO)
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-2000:])


def test_shm_no_segment_leak():
    """Segments are unlinked as soon as both sides hold mappings: nothing
    named tpucoll-* survives a connect/teardown cycle."""
    before = {f for f in os.listdir("/dev/shm") if f.startswith("tpucoll-")}

    def fn(ctx, rank):
        x = np.full(1 << 16, 1.0, dtype=np.float32)
        ctx.allreduce(x)
        return None

    spawn(2, fn)
    after = {f for f in os.listdir("/dev/shm") if f.startswith("tpucoll-")}
    assert after - before == set(), after - before
