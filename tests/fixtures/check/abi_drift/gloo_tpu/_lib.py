"""abi-drift fixture: the Python half of the drifted ABI surface."""

import ctypes

_c = ctypes.c_void_p
_int = ctypes.c_int
_sz = ctypes.c_size_t

_PROTOTYPES = {
    "tc_good": (_int, [_c, _sz]),
    # tc_removed intentionally absent (simulates a removed symbol).
    "tc_arity": (_int, [_c]),            # C side takes 3 arguments
    "tc_restype": (None, [_c]),          # C side returns const char*
    "tc_argtype": (_int, [_c, _int]),    # C side's arg 1 is size_t
    "tc_ghost": (_int, [_c]),            # never defined in capi.cc
}
