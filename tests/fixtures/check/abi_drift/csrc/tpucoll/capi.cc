// abi-drift fixture: a deliberately drifted C ABI. Never compiled —
// only scanned by tools/check (tests/test_static_analysis.py).
#include <cstddef>
#include <cstdint>

extern "C" {

// Mirrored correctly in _lib.py: no violation.
int tc_good(void* h, size_t n) {
  return wrap([&] { use(h, n); });
}

// Exported here but removed from _lib.py: missing-in-lib.
int tc_removed(void* h) {
  return wrap([&] { use(h); });
}

// _lib.py declares one argument: arity mismatch.
int tc_arity(void* h, size_t n, int flag) {
  return wrap([&] { use(h, n, flag); });
}

// _lib.py declares restype None: missing/mismatched restype.
const char* tc_restype(void* h) {
  return lastError(h);
}

// _lib.py declares argument 1 as c_int where this is size_t.
int tc_argtype(void* h, size_t n) {
  return wrap([&] { use(h, n); });
}

}  // extern "C"
