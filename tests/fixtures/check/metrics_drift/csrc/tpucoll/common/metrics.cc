// metrics-drift fixture emitter: produces only "good_key" in its
// snapshot JSON; the exposition also reads "ghost_key". Never compiled.

namespace tpucoll {

void snapshotJson(std::string& out) {
  out += "{\"good_key\":1}";
}

}  // namespace tpucoll
