"""metrics-drift fixture exposition: reads one key nobody emits and
emits one Prometheus family the docs never mention."""


def exposition(snap):
    lines = []
    lines.append("# TYPE gloo_tpu_documented_total counter")
    lines.append("gloo_tpu_documented_total %d" % snap.get("good_key", 0))
    lines.append("# TYPE gloo_tpu_undoc_total counter")
    lines.append("gloo_tpu_undoc_total %d" % snap.get("ghost_key", 0))
    return "\n".join(lines)
