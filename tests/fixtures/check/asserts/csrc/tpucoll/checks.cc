// no-bare-assert fixture: one bare assert (violation) and one
// static_assert (allowed). Never compiled.
#include <cassert>

namespace tpucoll {

int clampNonNegative(int v) {
  static_assert(sizeof(int) >= 4, "int width assumption");
  assert(v >= 0);  // compiled out under NDEBUG: violation
  return v;
}

}  // namespace tpucoll
