// explicit-atomics fixture: each implicit-ordering access form once,
// plus fully-annotated accesses that must NOT fire. Never compiled.
#include <atomic>

namespace tpucoll {

class Counter {
 public:
  void annotated();
  void defaultOrderLoad();
  void implicitStore();
  void implicitRmw();
  int implicitLoad();

 private:
  std::atomic<int> n_{0};
};

void Counter::annotated() {
  n_.store(1, std::memory_order_release);
  (void)n_.load(std::memory_order_acquire);
  n_.fetch_add(1, std::memory_order_relaxed);
}

void Counter::defaultOrderLoad() {
  (void)n_.load();  // default-order method call
}

void Counter::implicitStore() {
  n_ = 7;  // implicit seq-cst store
}

void Counter::implicitRmw() {
  n_++;  // implicit seq-cst RMW
}

int Counter::implicitLoad() {
  return n_ < 3 ? 1 : 0;  // bare read = implicit seq-cst load
}

}  // namespace tpucoll
