#include "tpucoll/schedule/ir.h"

namespace tpucoll {
namespace schedule {

const char* stepOpName(StepOp op) {
  if (op == StepOp::kSend) return "send";
  if (op == StepOp::kRecv) return "recv";
  // kDecode missing from the name table: the violation under test.
  return "?";
}

}  // namespace schedule
}  // namespace tpucoll
