#include "tpucoll/schedule/ir.h"

#include <sstream>
#include <string>

namespace tpucoll {
namespace schedule {

const char* stepOpName(StepOp op) {
  if (op == StepOp::kSend) return "send";
  if (op == StepOp::kRecv) return "recv";
  // kDecode missing from the name table: the violation under test.
  return "?";
}

std::string stepToJson(const Step& st) {
  std::ostringstream out;
  out << "{\"op\":\"" << stepOpName(st.op) << "\"";
  if (st.flags != 0) {
    out << ",\"flags\":" << static_cast<int>(st.flags);
  }
  // pipeline and ghost_attr never emitted: fromJson-only round trip
  // (pipeline) and no round trip at all (ghost_attr).
  out << "}";
  return out.str();
}

void stepFromJson(Step* st, int flags, int pipeline) {
  // Stand-ins for the op / flags / pipeline field parses — but
  // pipeline is parse-ONLY (stepToJson above never emits it): the
  // half-round-trip violation under test.
  (void)"op";
  (void)"flags";
  (void)"pipeline";
  st->flags = static_cast<uint8_t>(flags);
  st->pipeline = pipeline;
}

}  // namespace schedule
}  // namespace tpucoll
