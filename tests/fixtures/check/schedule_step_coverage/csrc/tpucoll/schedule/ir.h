// Fixture: kDecode is declared but interpreter.cc never lowers it and
// ir.cc never names it; verifier.cc handles a kGhost op that no longer
// exists.
#pragma once
#include <cstdint>

namespace tpucoll {
namespace schedule {

enum class StepOp : uint8_t {
  kSend = 0,
  kRecv = 1,
  kDecode = 2,
};

}  // namespace schedule
}  // namespace tpucoll
