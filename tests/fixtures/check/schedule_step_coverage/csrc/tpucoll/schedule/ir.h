// Fixture: kDecode is declared but interpreter.cc never lowers it and
// ir.cc never names it; verifier.cc handles a kGhost op that no longer
// exists. Step::pipeline is declared but ir.cc only parses it (no
// toJson emit), and Step::ghost_attr is never round-tripped at all;
// Step::flags is emitted AND parsed, so it stays quiet.
#pragma once
#include <cstdint>

namespace tpucoll {
namespace schedule {

enum class StepOp : uint8_t {
  kSend = 0,
  kRecv = 1,
  kDecode = 2,
};

struct Step {
  StepOp op{StepOp::kSend};
  static constexpr uint8_t kFlagToSlot = 1;  // constant: not state
  uint8_t flags{0};
  int32_t pipeline{1};
  int32_t ghost_attr{0};
};

}  // namespace schedule
}  // namespace tpucoll
