#include "tpucoll/schedule/ir.h"

namespace tpucoll {
namespace schedule {

int lower(StepOp op) {
  switch (op) {
    case StepOp::kSend:
      return 0;
    case StepOp::kRecv:
      return 1;
    // kDecode missing: the violation under test.
    default:
      return -1;
  }
}

}  // namespace schedule
}  // namespace tpucoll
