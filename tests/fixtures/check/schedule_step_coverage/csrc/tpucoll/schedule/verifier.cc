#include "tpucoll/schedule/ir.h"

namespace tpucoll {
namespace schedule {

int classify(StepOp op) {
  switch (op) {
    case StepOp::kSend:
      return 0;
    case StepOp::kRecv:
      return 1;
    case StepOp::kDecode:
      return 2;
    case StepOp::kGhost:  // removed from the enum: stale case
      return 3;
  }
  return -1;
}

}  // namespace schedule
}  // namespace tpucoll
