// lock-order fixture: an AB/BA cycle between two member mutexes, only
// one direction of which is documented in the config. Never compiled.
#include <mutex>

namespace tpucoll {

class Striper {
 public:
  void ab();
  void ba();

 private:
  std::mutex aMu_;
  std::mutex bMu_;
};

void Striper::ab() {
  std::lock_guard<std::mutex> g1(aMu_);
  std::lock_guard<std::mutex> g2(bMu_);
}

void Striper::ba() {
  std::lock_guard<std::mutex> g1(bMu_);
  std::lock_guard<std::mutex> g2(aMu_);
}

}  // namespace tpucoll
