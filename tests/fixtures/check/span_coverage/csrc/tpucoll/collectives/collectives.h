// span-coverage fixture public surface. Never compiled.
#pragma once

namespace tpucoll {

struct TracedOptions { int x; };
struct BlindOptions { int x; };
struct UnstampedOptions { int x; };

void traced(TracedOptions& opts);
void blind(BlindOptions& opts);
void unstamped(UnstampedOptions& opts);

}  // namespace tpucoll
