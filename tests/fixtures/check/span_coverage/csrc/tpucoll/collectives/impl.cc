// span-coverage fixture: `traced` stamps both scopes (clean), `blind`
// stamps the FlightRecOp but no span scope (violation), `unstamped`
// stamps neither (flightrec-coverage's finding, not this rule's).
#include "tpucoll/collectives/collectives.h"

namespace tpucoll {

void traced(TracedOptions& opts) {
  FlightRecOp frOp(opts.x);
  span::OpScope spanOp(nullptr, "traced", frOp.cseq());
  run(opts);
}

void blind(BlindOptions& opts) {
  FlightRecOp frOp(opts.x);
  run(opts);  // no span::OpScope: violation
}

void unstamped(UnstampedOptions& opts) {
  run(opts);
}

}  // namespace tpucoll
