// flightrec-coverage fixture public surface. Never compiled.
#pragma once

namespace tpucoll {

struct StampedOptions { int x; };
struct NakedOptions { int x; };
struct OrphanOptions { int x; };

void stamped(StampedOptions& opts);
void naked(NakedOptions& opts);
void orphan(OrphanOptions& opts);

}  // namespace tpucoll
