// flightrec-coverage fixture: one stamped entry, one naked one, and
// `orphan` declared in the header with no definition at all.
#include "tpucoll/collectives/collectives.h"

namespace tpucoll {

void stamped(StampedOptions& opts) {
  FlightRecOp frOp(opts.x);
  run(opts);
}

void naked(NakedOptions& opts) {
  run(opts);  // no FlightRecOp stamp: violation
}

}  // namespace tpucoll
