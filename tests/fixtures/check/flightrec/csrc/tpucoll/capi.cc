// flightrec-coverage fixture capi: a p2p post that never registers its
// flight-recorder seq (no frPush). Never compiled.

extern "C" {

int tc_buffer_send(void* buf, int dst) {
  return wrap([&] { post(buf, dst); });
}

}  // extern "C"
