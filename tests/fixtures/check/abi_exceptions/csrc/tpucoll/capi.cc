// abi-exceptions fixture: one tc_* body per boundary style, plus one
// with no boundary at all. Never compiled — only scanned.

extern "C" {

int tc_wrapped(void* h) {
  return wrap([&] { use(h); });
}

void* tc_wrapped_ptr(void* h) {
  return wrapPtr([&] { return make(h); });
}

int tc_trycatch(void* h) {
  try {
    use(h);
    return 0;
  } catch (...) {
    return 1;
  }
}

// No wrap/try: an exception thrown by use() crosses the C ABI.
int tc_naked(void* h) {
  use(h);
  return 0;
}

}  // extern "C"
