// env-hygiene fixture: the sanctioned accessor header. getenv here is
// allowed — it IS the strict-parser home.
#pragma once

#include <cstdlib>

namespace tpucoll {

inline bool envFlag(const char* name, bool dflt) {
  const char* v = std::getenv(name);
  return v != nullptr ? v[0] == '1' : dflt;
}

}  // namespace tpucoll
