// env-hygiene fixture: one raw-getenv offender and one undocumented
// knob. Never compiled — only scanned.
#include <cstdlib>

#include "tpucoll/common/env.h"

namespace tpucoll {

bool rawRead() {
  // Raw getenv outside common/env.h: violation. The var itself is
  // documented, so only the access path is wrong.
  return std::getenv("TPUCOLL_RAW_KNOB") != nullptr;
}

bool undocumentedRead() {
  // Strict accessor, but the var appears nowhere under docs/.
  return envFlag("TPUCOLL_UNDOCUMENTED", false);
}

}  // namespace tpucoll
