"""Persistent collective plans (csrc/tpucoll/collectives/plan.{h,cc}).

Covers the PR's acceptance surface: the zero-allocation/zero-registration
steady state (ubuf_creates delta == 0 across a warm loop), every
invalidation edge (tuning-table install, close, changed pointers, LRU
capacity, poisoned-by-exception entries, fork), the strict env knobs,
the in-place / persistent-handle Python paths' result equality against
the classic API, and same-seed chaos determinism with the cache on vs
off (plans must not change the wire schedule by a single post).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import _lib
from tests.harness import spawn


def _env(**kv):
    """Context manager: set TPUCOLL_* vars for the duration (the plan
    knobs are read at Context construction, so tests toggle them
    between spawns)."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        old = {k: os.environ.get(k) for k in kv}
        os.environ.update({k: str(v) for k, v in kv.items()})
        try:
            yield
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    return cm()


def test_steady_state_zero_registrations():
    """The headline contract: after the first (miss) call, a repeated
    allreduce replays its cached plan — plan hits accrue 1:1 and NOT
    ONE new UnboundBuffer is registered across 100 iterations."""
    def fn(ctx, rank):
        x = np.full(4096, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)  # builds the plan (miss)
        before = ctx.metrics()
        for _ in range(100):
            x[:] = rank + 1
            ctx.allreduce(x, tag=1)
        after = ctx.metrics()
        assert x[0] == 3.0
        assert after["ubuf_creates"] == before["ubuf_creates"], \
            "steady-state loop registered buffers"
        assert after["plan_hits"] - before["plan_hits"] == 100
        assert after["plan_misses"] == before["plan_misses"]
        assert ctx.plan_cache_size() >= 1
        return True

    assert spawn(2, fn) == [True, True]


def test_steady_state_covers_all_algorithms():
    """Every allreduce algorithm (and ring reduce_scatter/allgather)
    reaches the zero-registration steady state — the arena conversion
    covered hd/rd/bcube/bf16/q8 scratches, not just the ring."""
    algos = ["ring", "halving_doubling", "hd_blocks", "recursive_doubling",
             "bcube", "ring_bf16_wire", "ring_q8_wire"]

    def fn(ctx, rank):
        for i, algo in enumerate(algos):
            x = np.full(2048, float(rank + 1), dtype=np.float32)
            ctx.allreduce(x, algorithm=algo, tag=10 + i)
            ub0 = ctx.metrics()["ubuf_creates"]
            for _ in range(3):
                x[:] = rank + 1
                ctx.allreduce(x, algorithm=algo, tag=10 + i)
            assert ctx.metrics()["ubuf_creates"] == ub0, algo
            assert x[0] == pytest.approx(3.0, rel=1e-2), (algo, x[0])
        # reduce_scatter + allgather with STABLE input and output
        # buffers (a fresh input copy per call would be a fresh key —
        # the cache correctly treats a different pointer as a miss).
        src = np.full(2048, float(rank + 1), dtype=np.float32)
        x = np.empty_like(src)
        out = np.empty(1024, dtype=np.float32)
        gout = np.empty(2 * 2048, dtype=np.float32)
        x[:] = src
        ctx.reduce_scatter(x, tag=40, output=out)
        ctx.allgather(src, tag=41, output=gout)
        ub0 = ctx.metrics()["ubuf_creates"]
        for _ in range(3):
            x[:] = src
            ctx.reduce_scatter(x, tag=40, output=out)
            ctx.allgather(src, tag=41, output=gout)
        assert ctx.metrics()["ubuf_creates"] == ub0
        return True

    assert spawn(2, fn, timeout=90) == [True, True]


def test_plan_cache_disabled_by_env():
    """TPUCOLL_PLAN_CACHE=0: the transient (pre-plan) path — no cache
    entries, no hit/miss traffic, results unchanged."""
    def fn(ctx, rank):
        x = np.full(1024, float(rank + 1), dtype=np.float32)
        for _ in range(5):
            x[:] = rank + 1
            ctx.allreduce(x, tag=1)
        snap = ctx.metrics()
        assert x[0] == 3.0
        assert ctx.plan_cache_size() == 0
        assert snap["plan_hits"] == 0 and snap["plan_misses"] == 0
        return True

    with _env(TPUCOLL_PLAN_CACHE="0"):
        assert spawn(2, fn) == [True, True]


def test_env_knobs_are_strict():
    """Malformed plan knobs throw at Context construction (env.h
    contract), never silently run the wrong arm."""
    with _env(TPUCOLL_PLAN_CACHE="banana"):
        with pytest.raises(gloo_tpu.Error, match="TPUCOLL_PLAN_CACHE"):
            gloo_tpu.Context(0, 1)
    with _env(TPUCOLL_PLAN_LRU="0"):
        with pytest.raises(gloo_tpu.Error, match="TPUCOLL_PLAN_LRU"):
            gloo_tpu.Context(0, 1)
    with _env(TPUCOLL_PLAN_LRU="8MB"):
        with pytest.raises(gloo_tpu.Error, match="TPUCOLL_PLAN_LRU"):
            gloo_tpu.Context(0, 1)


def test_invalidation_on_tuning_install():
    """Installing (or clearing) a tuning table drops every plan: kAuto
    keys embed the RESOLVED algorithm, and the new table may elect a
    different one."""
    from gloo_tpu import tuning

    def fn(ctx, rank):
        x = np.full(1024, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)
        assert ctx.plan_cache_size() >= 1
        # Clearing the installed table goes through setTuningTable too.
        tuning.clear_table(ctx)
        assert ctx.plan_cache_size() == 0
        # The next call simply misses and rebuilds.
        x[:] = rank + 1
        ctx.allreduce(x, tag=1)
        assert x[0] == 3.0
        assert ctx.plan_cache_size() >= 1
        return True

    assert spawn(2, fn) == [True, True]


def test_invalidation_on_close_and_explicit_clear():
    def fn(ctx, rank):
        x = np.full(512, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)
        ctx.reduce_scatter(x.copy(), tag=2)
        assert ctx.plan_cache_size() >= 2
        ctx.plan_cache_clear()
        assert ctx.plan_cache_size() == 0
        x[:] = rank + 1
        ctx.allreduce(x, tag=1)
        assert x[0] == 3.0
        n = ctx.plan_cache_size()
        ctx.barrier(tag=9)
        ctx.close()
        assert n >= 1
        assert ctx.plan_cache_size() == 0  # close() dropped the plans
        return True

    assert spawn(2, fn) == [True, True]


def test_changed_pointer_or_size_misses():
    """A different buffer (or size) is a different key — it misses and
    ages the old entry; the old entry still hits afterwards."""
    def fn(ctx, rank):
        a = np.full(1024, 1.0, dtype=np.float32)
        b = np.full(1024, 1.0, dtype=np.float32)
        c = np.full(2048, 1.0, dtype=np.float32)
        ctx.allreduce(a, tag=1)
        m0 = ctx.metrics()["plan_misses"]
        ctx.allreduce(b, tag=1)  # same shape, different pointer: miss
        ctx.allreduce(c, tag=1)  # different size: miss
        assert ctx.metrics()["plan_misses"] - m0 == 2
        h0 = ctx.metrics()["plan_hits"]
        a[:] = 1.0
        ctx.allreduce(a, tag=1)  # original entry still cached
        assert ctx.metrics()["plan_hits"] - h0 == 1
        return True

    assert spawn(2, fn) == [True, True]


def test_lru_eviction_at_capacity():
    def fn(ctx, rank):
        bufs = [np.full(256, 1.0, dtype=np.float32) for _ in range(4)]
        for i, x in enumerate(bufs):
            ctx.allreduce(x, tag=1)
        snap = ctx.metrics()
        assert ctx.plan_cache_size() <= 2
        assert snap["plan_evictions"] >= 2
        return True

    with _env(TPUCOLL_PLAN_LRU="2"):
        assert spawn(2, fn) == [True, True]


def test_exception_drops_poisoned_plan():
    """An exception unwinding through a planned collective drops that
    plan: its buffers may carry in-flight ops only the destructor can
    drain, so it must never serve another call."""
    def fn(ctx, rank):
        x = np.full(1024, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)
        n0 = ctx.plan_cache_size()
        if rank == 0:
            # Rank 1 never joins tag 77, so this must time out; the
            # poisoned plan is dropped on unwind.
            with pytest.raises(gloo_tpu.TimeoutError):
                ctx.allreduce(x, tag=77, timeout=0.3)
            assert ctx.plan_cache_size() == n0
        ctx.barrier(tag=9)
        # The healthy entry still replays.
        x[:] = rank + 1
        ctx.allreduce(x, tag=1)
        assert x[0] == 3.0
        return True

    assert spawn(2, fn, timeout=60) == [True, True]


def test_fork_gets_fresh_cache():
    def fn(ctx, rank):
        x = np.full(512, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)
        child = ctx.fork()
        assert child.plan_cache_size() == 0
        y = np.full(512, float(rank + 1), dtype=np.float32)
        child.allreduce(y, tag=1)
        assert y[0] == 3.0
        assert child.plan_cache_size() >= 1
        child.close()
        return True

    assert spawn(2, fn, timeout=60) == [True, True]


@pytest.mark.parametrize("dtype", ["float32", "float64", "int32", "uint8"])
def test_inplace_and_plan_paths_match_classic(dtype):
    """Result equality across the Python surfaces: classic allreduce,
    the persistent CollectivePlan handle, reduce_scatter with a
    preallocated output, and the zero-copy reduce_scatter_inplace all
    produce identical bytes."""
    def fn(ctx, rank):
        base = (np.arange(512) % 7 + rank + 1).astype(dtype)

        classic = base.copy()
        ctx.allreduce(classic, tag=1)

        planned = base.copy()
        p = ctx.allreduce_plan(planned, tag=2)
        got = p()
        assert got is planned
        np.testing.assert_array_equal(planned, classic)
        # Replay: refill and run the same plan again.
        planned[:] = base
        p()
        np.testing.assert_array_equal(planned, classic)

        rs_classic = ctx.reduce_scatter(base.copy(), tag=3)
        out = np.empty(256, dtype=dtype)
        rs_out = ctx.reduce_scatter(base.copy(), tag=4, output=out)
        assert rs_out is out
        np.testing.assert_array_equal(rs_classic, out)

        scratch = base.copy()
        rs_inplace = ctx.reduce_scatter_inplace(scratch, tag=5)
        np.testing.assert_array_equal(rs_classic, rs_inplace)
        assert rs_inplace.base is scratch  # a view, not a copy

        rsp = ctx.reduce_scatter_plan(base.copy(), tag=6)
        np.testing.assert_array_equal(rsp(), rs_classic)

        agp = ctx.allgather_plan(base, tag=7)
        ag = agp()
        np.testing.assert_array_equal(ag, ctx.allgather(base, tag=8))
        return True

    assert spawn(2, fn, timeout=60) == [True, True]


def test_same_seed_chaos_identical_streams_cache_on_vs_off():
    """Plans must not change the wire schedule by a single post: the
    same-seed chaos workload produces byte-identical per-rank
    (seq, op, fingerprint) flightrec streams with the cache on vs off."""
    from gloo_tpu import fault

    schedule = {"seed": 13, "faults": [
        {"when": {"rank": 1, "opcode": "data"},
         "action": "delay", "ms": 1, "prob": 0.5, "seed": 5}]}

    def workload():
        def fn(ctx, rank):
            x = np.arange(1024, dtype=np.float32)
            for i in range(6):
                x[:] = rank + i
                ctx.allreduce(x, tag=2 * i)
                ctx.reduce_scatter(x.copy(), tag=100 + i)
            ctx.barrier(tag=999)
            return [(e["seq"], e["op"], e["fp"])
                    for e in ctx.flightrec()["events"]]

        return spawn(2, fn, timeout=60)

    fault.install(schedule)
    try:
        with _env(TPUCOLL_PLAN_CACHE="1"):
            on = workload()
        fault.install(schedule)  # reset firing state for the replay
        with _env(TPUCOLL_PLAN_CACHE="0"):
            off = workload()
    finally:
        fault.clear()
    assert on == off
