"""Causal critical-path engine (ISSUE 19, docs/critpath.md):

- span streams carry send/recv/wait/local kinds with per-op emission
  ordinals; merged wire edges match FIFO-exactly with no orphans;
- the default is OFF and records nothing; the runtime toggle works;
- the bounded ring drops oldest and reports the count;
- strict env knob matrix (TPUCOLL_SPANS, TPUCOLL_SPANS_RING);
- the telemetry endpoint serves /spans;
- chaos-grounded attribution: a fault schedule delaying rank 1's sends
  50 ms must hand rank 1's send spans >= 80% of the critical path on
  BOTH the native ring and an elected interpreter schedule, asserted
  through `tools/critpath_view.py --check` exit codes;
- same-seed chaos produces identical per-rank wire-span sequences;
- the fleet plane's /fleet document grows a critpath section from the
  ranks' in-band causal votes.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import fault, schedule
from gloo_tpu.utils import critpath as critpath_util
from gloo_tpu.utils.telemetry import fetch_route, serve_telemetry
from harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_VIEW = os.path.join(_REPO, "tools", "critpath_view.py")

WIRE_KINDS = {"send", "recv"}
ALL_KINDS = {"send", "recv", "wait", "local"}


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _dump(snaps, directory):
    os.makedirs(directory, exist_ok=True)
    for snap in snaps:
        path = os.path.join(directory, f"spans-rank{snap['rank']}.json")
        with open(path, "w") as f:
            json.dump(snap, f)


def _view(*args):
    return subprocess.run(
        [sys.executable, _VIEW, *args],
        capture_output=True, text=True, timeout=120)


# ---- span stream shape + cross-rank merge ------------------------------


def test_span_stream_shape_and_matched_wire_edges():
    """Three ranks of ring allreduces: every span carries the full
    schema, per-op emission ordinals are strictly increasing per rank,
    wire kinds appear on every rank, and the cross-rank merge matches
    every send->recv edge with zero orphans while the extracted path
    explains a meaningful share of each op's latency."""
    with _env(TPUCOLL_SPANS="1"):
        def body(ctx, rank):
            x = np.ones(1 << 16, dtype=np.float32)
            for _ in range(3):
                ctx.allreduce(x, algorithm="ring")
                x[:] = 1.0
            return ctx.spans()

        snaps = spawn(3, body)

    for snap in snaps:
        assert snap["kind"] == "tpucoll_spans" and snap["enabled"]
        assert snap["spans"], f"rank {snap['rank']} recorded nothing"
        kinds = {s["kind"] for s in snap["spans"]}
        assert kinds <= ALL_KINDS, kinds
        assert WIRE_KINDS <= kinds, (snap["rank"], kinds)
        per_op = {}
        for s in snap["spans"]:
            assert s["t1_us"] >= s["t0_us"] >= 0
            assert s["op"] == "allreduce" and s["cseq"] is not None
            if s["kind"] in WIRE_KINDS:
                assert s["peer"] is not None and s["bytes"] > 0, s
            per_op.setdefault(s["cseq"], []).append(s["id"])
        for cseq, ids in per_op.items():
            assert ids == sorted(ids), (cseq, ids)
            assert len(set(ids)) == len(ids), (cseq, ids)

    merged = critpath_util.merge(snaps)
    assert merged["ranks"] == [0, 1, 2] and len(merged["ops"]) == 3
    analysis = critpath_util.analyze(merged)
    assert len(analysis["ops"]) == 3
    cross_rank = 0
    for op in analysis["ops"]:
        assert op["unmatched"] == {"sends": 0, "recvs": 0,
                                   "mismatched": 0}, op["unmatched"]
        assert op["path"], op
        covered = sum(r["contrib_us"] for r in op["path"])
        # Path segments are disjoint and clipped by construction.
        assert covered <= op["total_us"], (covered, op["total_us"])
        if len({r["rank"] for r in op["path"]}) >= 2:
            cross_rank += 1
        # Slack rows cover every span; path spans have zero slack.
        assert len(op["slack"]) == sum(
            len(v) for v in merged["ops"][op["cseq"]].values())
    # A single-rank path is legitimate for one op (that rank was its
    # own bottleneck throughout), but three ops of a 3-rank ring with
    # never a wire hop would mean send->recv matching is not wiring
    # the graph at all.
    assert cross_rank >= 1, analysis["ops"]


def test_spans_default_off_records_nothing():
    """TPUCOLL_SPANS defaults to 0: the snapshot says disabled, holds
    zero spans, and never advances its ring cursor."""
    def body(ctx, rank):
        x = np.ones(1 << 14, dtype=np.float32)
        for _ in range(3):
            ctx.allreduce(x)
        return ctx.spans()

    for snap in spawn(2, body):
        assert snap["enabled"] is False
        assert snap["spans"] == [] and snap["next_seq"] == 0
        assert snap["dropped"] == 0


def test_runtime_toggle():
    """spans_enable() flips recording between ops: off -> nothing,
    on -> spans, off again -> the stream freezes."""
    def body(ctx, rank):
        x = np.ones(1 << 14, dtype=np.float32)
        ctx.allreduce(x, algorithm="ring")
        assert ctx.spans()["spans"] == []
        assert ctx.spans_enabled() is False
        ctx.spans_enable(True)
        assert ctx.spans_enabled() is True
        ctx.allreduce(x, algorithm="ring")
        n = len(ctx.spans()["spans"])
        assert n > 0
        ctx.spans_enable(False)
        ctx.allreduce(x, algorithm="ring")
        assert len(ctx.spans()["spans"]) == n
        return True

    assert all(spawn(2, body))


def test_bounded_ring_drops_oldest():
    """TPUCOLL_SPANS_RING=8: the ring keeps the 8 newest spans and the
    snapshot reports how many older ones were overwritten."""
    with _env(TPUCOLL_SPANS="1", TPUCOLL_SPANS_RING="8"):
        def body(ctx, rank):
            x = np.ones(1 << 14, dtype=np.float32)
            for _ in range(6):
                ctx.allreduce(x, algorithm="ring")
            return ctx.spans()

        for snap in spawn(2, body):
            assert snap["capacity"] == 8
            assert len(snap["spans"]) <= 8
            assert snap["next_seq"] > 8
            assert snap["dropped"] == snap["next_seq"] - len(snap["spans"])
            # The survivors are the newest seqs, contiguous to the head.
            seqs = sorted(s["seq"] for s in snap["spans"])
            assert seqs[-1] == snap["next_seq"] - 1


@pytest.mark.parametrize("var,value", [
    ("TPUCOLL_SPANS", "banana"),
    ("TPUCOLL_SPANS", "2"),
    ("TPUCOLL_SPANS_RING", "0"),
    ("TPUCOLL_SPANS_RING", "many"),
    ("TPUCOLL_SPANS_RING", "-4"),
])
def test_strict_env_knobs(monkeypatch, var, value):
    """Malformed span knobs fail loudly at Context construction
    (common/env.h strict parsers), never silently fall back."""
    monkeypatch.setenv(var, value)
    with pytest.raises(gloo_tpu.Error, match=var):
        gloo_tpu.Context(0, 1)


def test_telemetry_spans_route():
    """GET /spans serves the same document Context.spans() returns."""
    with _env(TPUCOLL_SPANS="1"):
        def body(ctx, rank):
            x = np.ones(1 << 14, dtype=np.float32)
            ctx.allreduce(x, algorithm="ring")
            if rank != 0:
                ctx.barrier()
                return True
            with serve_telemetry(ctx) as srv:
                doc = fetch_route(srv.url, "/spans", timeout=10.0)
            ctx.barrier()
            assert doc["kind"] == "tpucoll_spans"
            assert doc["rank"] == 0 and doc["enabled"] is True
            assert doc["spans"], doc
            return True

        assert all(spawn(2, body))


# ---- chaos-grounded attribution (both execution arms) ------------------


CHAOS = {"seed": 7, "faults": [
    {"when": {"rank": 1, "opcode": "data", "min_bytes": 1024},
     "action": "delay", "ms": 50, "count": 6}]}


def _elect(table, collective, world, nbytes):
    name = table["schedules"][0]["name"]
    table = json.loads(json.dumps(table))
    table["elections"] = [{
        "collective": collective, "world_size": world, "dtype": "",
        "bucket": nbytes.bit_length() - 1, "schedule": name,
    }]
    return table


def _run_chaos_arm(scheduled):
    """Delay rank 1's data sends 50 ms mid-allreduce at P=3 and return
    every rank's span snapshot (native ring or elected schedule)."""
    with _env(TPUCOLL_SPANS="1"):
        fault.install(CHAOS)
        try:
            def body(ctx, rank):
                x = np.ones(1 << 18, dtype=np.float32)  # 1 MiB
                if scheduled:
                    t = _elect(schedule.generate("ring", 3),
                               "allreduce", 3, 1 << 20)
                    schedule.install(ctx, t)
                for _ in range(4):
                    if scheduled:
                        ctx.allreduce(x)   # elected interpreter path
                    else:
                        ctx.allreduce(x, algorithm="ring")
                    x[:] = 1.0
                if scheduled:
                    schedule.clear(ctx)
                return ctx.spans()

            snaps = spawn(3, body, timeout=120, context_timeout=60)
        finally:
            fired = fault.report()
            fault.clear()
    assert any(e["action"] == "delay" and e["rank"] == 1
               for e in fired), fired
    return snaps


@pytest.mark.parametrize("scheduled", [False, True],
                         ids=["native_ring", "elected_schedule"])
def test_chaos_attribution_blames_delayed_sender(tmp_path, scheduled):
    """The injected 50 ms send delays run on rank 1's posting thread,
    inside its annotated send spans — so the causal critical path of
    the slowest op must route through rank 1's sends for >= 80% of the
    op's latency, on the native ring AND the elected schedule, asserted
    via the CLI's --check exit-code contract (0 pass / 3 fail)."""
    snaps = _run_chaos_arm(scheduled)
    dump = str(tmp_path / "spans")
    _dump(snaps, dump)

    passing = _view(dump, "--check", "1=send:0.8")
    assert passing.returncode == 0, (passing.stdout, passing.stderr)
    assert "PASS" in passing.stdout

    # The same threshold pinned on an innocent rank must FAIL (3).
    failing = _view(dump, "--check", "2=send:0.8")
    assert failing.returncode == 3, (failing.stdout, failing.stderr)

    # And no data is its own, distinct exit code (1).
    empty = str(tmp_path / "empty")
    os.makedirs(empty, exist_ok=True)
    nodata = _view(empty, "--check", "1=send:0.8")
    assert nodata.returncode == 1, (nodata.stdout, nodata.stderr)


def test_same_seed_chaos_identical_wire_span_streams():
    """Same seed + schedule + workload => every rank's (cseq, kind,
    peer, slot, bytes) wire-span sequence is identical across runs.
    Wire spans only: drain-wait spans may interleave differently (a
    waitRecv can observe another step's arrival first), but the
    annotated send/recv scopes are program-ordered and must replay."""
    chaos = {"seed": 21, "faults": [
        {"when": {"rank": 1, "opcode": "data"},
         "action": "delay", "ms": 5, "prob": 0.5, "count": 8}]}

    def run_once():
        with _env(TPUCOLL_SPANS="1"):
            fault.install(chaos)
            try:
                def body(ctx, rank):
                    x = np.ones(1 << 14, dtype=np.float32)
                    for _ in range(3):
                        ctx.allreduce(x, algorithm="ring")
                    ctx.barrier()
                    return [(s["cseq"], s["kind"], s["peer"],
                             s["slot"], s["bytes"])
                            for s in ctx.spans()["spans"]
                            if s["kind"] in WIRE_KINDS]

                return spawn(3, body)
            finally:
                fault.clear()

    assert run_once() == run_once()


# ---- fleet-plane causal votes ------------------------------------------


def test_fleet_document_grows_critpath_section(monkeypatch):
    """With spans enabled, every rank's in-band report carries a causal
    critical-edge vote per recent op; rank 0's merged /fleet document
    serves the aggregated critpath section (voted ops + owner
    leaderboard). Votes are structural evidence, not a blame assertion:
    at P=3 a symmetric ring can split the vote, so the test pins the
    section's shape and that votes flowed, not a specific owner."""
    from tests.test_fleet import _poll, _sync_until, spawn_hosts

    monkeypatch.setenv("TPUCOLL_FLEETOBS_INTERVAL_MS", "80")
    monkeypatch.setenv("TPUCOLL_FLEETOBS_WINDOW", "10")

    with _env(TPUCOLL_SPANS="1"):
        def fn(ctx, rank):
            ctx.fleetobs_start()
            x = np.ones(1 << 14, dtype=np.float32)
            for _ in range(8):
                ctx.allreduce(x.copy(), algorithm="ring")

            out = {}
            if rank == 0:
                def voted():
                    doc = ctx.fleet()
                    crit = doc.get("critpath")
                    return doc if crit and crit["voted_ops"] > 0 else None
                doc = _poll(voted, 25.0)
                assert doc, f"no causal votes aggregated: {ctx.fleet()}"
                out["doc"] = doc
            ok = _sync_until(ctx, rank, lambda: "doc" in out)
            assert ok, "grid did not agree on completion"
            ctx.fleetobs_stop()
            return out

        results = spawn_hosts(4, 2, fn)

    crit = results[0]["doc"]["critpath"]
    assert crit["voted_ops"] > 0
    assert crit["owners"], crit
    for row in crit["owners"]:
        assert 0 <= row["rank"] < 4
        assert 0 < row["ops"] <= crit["voted_ops"]
