"""Elastic membership plane (docs/elastic.md): the contract is that the
SYSTEM detects membership changes — no application-level rebuild call
appears anywhere in these worker bodies. Multiprocess over a FileStore
like test_chaos.py (real processes, real sockets, real SIGKILLs), with
fast lease knobs (TPUCOLL_LEASE_MS=200 / TPUCOLL_LEASE_GRACE=1200) so
detection latency is test-sized.

Covered transitions:
- SIGKILL mid-allreduce auto-detected by lease expiry alone (survivors
  resume in a new epoch within the grace window, epoch-tagged flight
  recorder + metrics()["elastic"] assert every transition);
- coordinator death and re-election (next-lowest wid publishes);
- replacement-rank rejoin back to the original world size;
- shrink below min_size fails loudly and typed on every survivor;
- same-seed fault-plane determinism across an epoch transition;
- graceful leave (deleted lease: immediate shrink, no grace wait).
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LEASE_ENV = {"TPUCOLL_LEASE_MS": "200", "TPUCOLL_LEASE_GRACE": "1200"}


def _spawn(body, rank, size, store, extra_env=None):
    env = dict(os.environ, **_LEASE_ENV)
    env.pop("TPUCOLL_FAULT_FILE", None)
    if extra_env:
        env.update(extra_env)
    prog = textwrap.dedent("""
        import json, os, signal, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu
        from gloo_tpu import elastic, fault

        rank = {rank}; size = {size}
        store = gloo_tpu.FileStore({store!r})
        device = gloo_tpu.Device()
    """).format(repo=_REPO, rank=rank, size=size, store=store) + \
        textwrap.dedent(body)
    return subprocess.Popen([sys.executable, "-c", prog],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)


def _summary(out):
    line = [ln for ln in out[0].splitlines() if ln.startswith("OK ")]
    assert line, out
    return json.loads(line[0][3:])


# A verified elastic workload: every step allreduces a consensus stop
# flag (so ranks end at the same step even across membership changes),
# then a payload allreduce checked against the CURRENT size. `victim`
# SIGKILLs itself mid-run; survivors recover with no manual rebuild.
_STEP_BODY = """
victim = {victim}
target_steps = {target_steps}
stop_at_size = {stop_at_size}

def step_fn(ectx, step, state):
    if rank == victim and step == 3:
        os.kill(os.getpid(), signal.SIGKILL)
    flag = np.zeros(1, dtype=np.float32)
    if ectx.rank == 0 and state["done"] >= target_steps and \\
            ectx.size == stop_at_size:
        flag[0] = 1.0
    ectx.allreduce(flag, tag=0)
    if flag[0] > 0:
        raise StopIteration
    x = np.full(1 << 14, float(ectx.rank + 1), dtype=np.float32)
    ectx.allreduce(x, tag=1)
    n = ectx.size
    assert x[0] == n * (n + 1) / 2, (step, x[0], n)
    state["done"] += 1
    return state

t0 = time.time()
res = elastic.run_elastic(step_fn, store=store, device=device,
                          rank=rank, world_size=size, min_size={min_size},
                          join={join}, state={{"done": 0}}, timeout=90.0)
res["wall_s"] = round(time.time() - t0, 2)
res.pop("state")
print("OK", json.dumps(res))
"""


def test_sigkill_mid_allreduce_auto_recovery():
    """Acceptance core: SIGKILL of one rank mid-collective is detected
    by lease expiry ALONE — survivors resume collectives in a new epoch
    within the grace window, with metrics()["elastic"] counters and
    epoch-tagged contexts asserting the transition, and no manual
    rebuild call anywhere in the worker body."""
    store = tempfile.mkdtemp()
    body = _STEP_BODY.format(victim=2, target_steps=6, stop_at_size=2,
                             min_size=2, join=False)
    procs = [_spawn(body, r, 3, store) for r in range(3)]
    outs = [p.communicate(timeout=180) for p in procs]
    assert procs[2].returncode == -signal.SIGKILL
    for r in (0, 1):
        assert procs[r].returncode == 0, (r, outs[r])
        res = _summary(outs[r])
        # One lease-expiry transition: epoch 1 (size 3) -> epoch 2
        # (size 2), epoch-tagged group namespaces on both sides.
        assert res["rebuilds"] == 1, res
        assert [(e["epoch"], e["size"], e["group"]) for e in
                res["epochs"]] == [(1, 3, "e1"), (2, 2, "e2")], res
        st = res["elastic"]
        assert st["epoch"] == 2 and st["size"] == 2, st
        assert st["members"] == [0, 1], st
        assert st["leases_renewed"] >= 2, st
        assert st["rebuilds"] == 2, st  # founding bind + the recovery
        # Detection + rebuild bounded by the grace window: the whole
        # run — including ~6 pre/post steps — stays far under the
        # watchdog-free hang the old world would have suffered.
        assert res["rebuild_ms"][0] < 6 * 1200, res
        assert res["wall_s"] < 60, res
    # Exactly one bump, published by the surviving coordinator (wid 0).
    assert _summary(outs[0])["elastic"]["bumps_published"] == 1
    assert _summary(outs[1])["elastic"]["bumps_published"] == 0


def test_epoch_tagged_flightrec_dumps():
    """Every epoch's context carries its epoch as the flight-recorder
    group tag: explicit dumps from both sides of a transition are
    partitionable by epoch before any cross-rank comparison (the
    merge_by_tag contract, docs/flightrec.md)."""
    store = tempfile.mkdtemp()
    dumps = tempfile.mkdtemp()
    body = """
dumps = {dumps!r}

def step_fn(ectx, step, state):
    if rank == 2 and step == 2:
        os.kill(os.getpid(), signal.SIGKILL)
    x = np.full(1024, 1.0, dtype=np.float32)
    ectx.allreduce(x, tag=1)
    assert x[0] == float(ectx.size), x[0]
    ectx.flightrec_dump(os.path.join(
        dumps, "flightrec-rank%d-%s.json" % (rank, ectx.group_tag())))
    if ectx.size == 2 and state["post"] >= 2:
        raise StopIteration
    if ectx.size == 2:
        state["post"] += 1
    return state

res = elastic.run_elastic(step_fn, store=store, device=device,
                          rank=rank, world_size=size, min_size=2,
                          state={{"post": 0}}, timeout=90.0)
print("OK", json.dumps({{"epochs": [e["group"] for e in res["epochs"]]}}))
""".format(dumps=dumps)
    procs = [_spawn(body, r, 3, store) for r in range(3)]
    outs = [p.communicate(timeout=180) for p in procs]
    assert procs[2].returncode == -signal.SIGKILL
    for r in (0, 1):
        assert procs[r].returncode == 0, (r, outs[r])
        assert _summary(outs[r])["epochs"] == ["e1", "e2"], outs[r]
    # Both epochs dumped, and every dump document is stamped with its
    # epoch's group tag (the filename-safe and in-document forms).
    for r in (0, 1):
        for epoch in ("e1", "e2"):
            path = os.path.join(dumps, f"flightrec-rank{r}-{epoch}.json")
            assert os.path.exists(path), sorted(os.listdir(dumps))
            with open(path) as f:
                doc = json.load(f)
            assert doc["group"] == epoch, (path, doc.get("group"))
            assert doc["events"], path


def test_coordinator_death_reelection():
    """SIGKILL the coordinator (wid 0): the next-lowest live wid takes
    over, publishes the shrink epoch, and reports coordinator=True —
    the lowest-live-rank re-election the protocol promises."""
    store = tempfile.mkdtemp()
    body = _STEP_BODY.format(victim=0, target_steps=6, stop_at_size=2,
                             min_size=2, join=False)
    procs = [_spawn(body, r, 3, store) for r in range(3)]
    outs = [p.communicate(timeout=180) for p in procs]
    assert procs[0].returncode == -signal.SIGKILL
    for r in (1, 2):
        assert procs[r].returncode == 0, (r, outs[r])
    st1 = _summary(outs[1])["elastic"]
    st2 = _summary(outs[2])["elastic"]
    assert st1["members"] == [1, 2] and st2["members"] == [1, 2]
    # wid 1 is the re-elected coordinator (new rank 0) and published
    # the bump; wid 2 followed.
    assert st1["coordinator"] is True and st1["rank"] == 0, st1
    assert st2["coordinator"] is False and st2["rank"] == 1, st2
    assert st1["bumps_published"] >= 1 and st2["bumps_published"] == 0


def test_replacement_rank_rejoins_to_full_size():
    """Grow path: after the SIGKILL shrink, a respawned replacement
    (join=True — fresh wid, no rank argument) enqueues on the join
    queue and is admitted at the next epoch boundary back to the
    ORIGINAL world size; all three then run verified collectives."""
    store = tempfile.mkdtemp()
    body = _STEP_BODY.format(victim=2, target_steps=6, stop_at_size=3,
                             min_size=2, join=False)
    procs = [_spawn(body, r, 3, store) for r in range(3)]
    # Wait for the victim to die, then spawn the replacement.
    assert procs[2].wait(timeout=60) == -signal.SIGKILL
    time.sleep(0.5)
    joiner_body = _STEP_BODY.format(victim=-1, target_steps=6,
                                    stop_at_size=3, min_size=2, join=True)
    joiner = _spawn(joiner_body, 9, 3, store)
    outs = [p.communicate(timeout=240) for p in procs[:2]]
    jout = joiner.communicate(timeout=240)
    for r in (0, 1):
        assert procs[r].returncode == 0, (r, outs[r])
        res = _summary(outs[r])
        # epoch 1 (3) -> shrink epoch (2) -> join epoch (3 again).
        assert [e["size"] for e in res["epochs"]] == [3, 2, 3], res
        assert res["elastic"]["members"] == [0, 1, 3], res
    assert joiner.returncode == 0, jout
    jres = _summary(jout)
    st = jres["elastic"]
    assert st["wid"] == 3 and st["rank"] == 2 and st["size"] == 3, st
    assert jres["epochs"][0]["size"] == 3, jres


def test_shrink_below_min_size_fails_loudly():
    """With min_size == world_size, losing one rank cannot be recovered
    from: every survivor's run_elastic raises the typed BelowMinSize —
    loudly, not a hang, not a silent small group."""
    store = tempfile.mkdtemp()
    body = """
def step_fn(ectx, step, state):
    if rank == 2 and step == 2:
        os.kill(os.getpid(), signal.SIGKILL)
    x = np.full(1024, 1.0, dtype=np.float32)
    ectx.allreduce(x, tag=1)
    return state

try:
    elastic.run_elastic(step_fn, store=store, device=device, rank=rank,
                        world_size=size, min_size=3, steps=50,
                        timeout=90.0)
    print("UNEXPECTED-SUCCESS"); sys.exit(3)
except elastic.BelowMinSize as e:
    assert "below min_size 3" in str(e), e
    print("OK", json.dumps({"typed": True, "message": str(e)[:120]}))
"""
    procs = [_spawn(body, r, 3, store) for r in range(3)]
    outs = [p.communicate(timeout=180) for p in procs]
    assert procs[2].returncode == -signal.SIGKILL
    for r in (0, 1):
        assert procs[r].returncode == 0, (r, outs[r])
        assert _summary(outs[r])["typed"] is True


def test_graceful_leave_is_immediate():
    """ElasticContext.leave() deletes the lease: peers shrink at the
    NEXT monitor poll without waiting out the grace — clean departures
    must be cheaper than crashes."""
    store = tempfile.mkdtemp()
    body = """
def step_fn(ectx, step, state):
    if rank == 2 and step == 3:
        ectx.leave()
    flag = np.zeros(1, dtype=np.float32)
    if ectx.rank == 0 and ectx.size == 2 and state["post"] >= 2:
        flag[0] = 1.0
    ectx.allreduce(flag, tag=0)
    if flag[0] > 0:
        raise StopIteration
    x = np.full(1024, float(ectx.rank + 1), dtype=np.float32)
    ectx.allreduce(x, tag=1)
    n = ectx.size
    assert x[0] == n * (n + 1) / 2, (step, x[0], n)
    if ectx.size == 2:
        state["post"] += 1
    return state

t0 = time.time()
res = elastic.run_elastic(step_fn, store=store, device=device, rank=rank,
                          world_size=size, min_size=2,
                          state={"post": 0}, timeout=90.0)
res["wall_s"] = round(time.time() - t0, 2)
res.pop("state")
print("OK", json.dumps(res))
"""
    procs = [_spawn(body, r, 3, store) for r in range(3)]
    outs = [p.communicate(timeout=180) for p in procs]
    for r in range(3):
        assert procs[r].returncode == 0, (r, outs[r])
    for r in (0, 1):
        res = _summary(outs[r])
        assert res["elastic"]["members"] == [0, 1], res
        assert res["elastic"]["epoch"] == 2, res
    assert _summary(outs[2])["left"] is True, outs[2]


def test_same_seed_fault_determinism_across_epoch_transition():
    """Same-seed fault-plane determinism ACROSS an epoch transition:
    a probabilistic delay rule fires inside both epochs' fault domains
    (hash of the "e<N>" group tag, >= 1000), and the post-transition
    epoch's per-(rank, domain) firing subsequence is byte-identical
    across two runs. (The failing epoch's own tail is timing-truncated
    — the abort cuts its schedule at a scheduling-dependent point — so
    the deterministic unit is the completed epoch's stream.)"""
    schedule = {"seed": 31, "faults": [
        {"when": {"opcode": "data"},
         "action": "delay", "ms": 1, "prob": 0.4, "seed": 77}]}
    body = """
def step_fn(ectx, step, state):
    if rank == 2 and step == 3:
        ectx.leave()   # deterministic departure point (no mid-op kill)
    flag = np.zeros(1, dtype=np.float32)
    if ectx.rank == 0 and ectx.size == 2 and state["post"] >= 4:
        flag[0] = 1.0
    ectx.allreduce(flag, tag=0)
    if flag[0] > 0:
        raise StopIteration
    x = np.full(4096, float(ectx.rank + 1), dtype=np.float32)
    ectx.allreduce(x, tag=1)
    if ectx.size == 2:
        state["post"] += 1
    return state

res = elastic.run_elastic(step_fn, store=store, device=device, rank=rank,
                          world_size=size, min_size=2,
                          state={"post": 0}, timeout=90.0)
fired = [(e["domain"], e["n"], e["action"], e["peer"], e["nbytes"])
         for e in fault.report(rank=rank)]
fired.sort()
print("OK", json.dumps({
    "fired": fired,
    "e2_domain": res["elastic"]["fault_domain"],
    "epochs": [e["group"] for e in res["epochs"]]}))
"""
    runs = []
    for attempt in range(2):
        store = tempfile.mkdtemp()
        path = os.path.join(store, "schedule.json")
        with open(path, "w") as f:
            json.dump(schedule, f)
        procs = [_spawn(body, r, 3, store,
                        extra_env={"TPUCOLL_FAULT_FILE": path})
                 for r in range(3)]
        outs = [p.communicate(timeout=240) for p in procs]
        for r in range(3):
            assert procs[r].returncode == 0, (r, outs[r])
        runs.append([_summary(outs[r]) for r in range(3)])
    for r in (0, 1):
        assert runs[0][r]["epochs"] == runs[1][r]["epochs"] == ["e1", "e2"]
        e2 = runs[0][r]["e2_domain"]
        assert e2 == runs[1][r]["e2_domain"]
        assert e2 >= 1000, e2  # a group domain, not the root's
        first = [e for e in runs[0][r]["fired"] if e[0] == e2]
        second = [e for e in runs[1][r]["fired"] if e[0] == e2]
        assert first, "no faults fired in the post-transition epoch"
        assert first == second, (r, first, second)


def test_run_elastic_restores_from_checkpointer():
    """run_elastic with a StepCheckpointer: after the shrink, every
    survivor resumes from the newest COMMITTED checkpoint's (step,
    state) — the step counter rewinds to ck_step + 1 and the restored
    accumulator is identical across survivors (the post-failure state
    agreement in-memory retry cannot give, since a failed in-place
    collective leaves buffers undefined)."""
    pytest.importorskip("orbax.checkpoint")
    store = tempfile.mkdtemp()
    ckdir = tempfile.mkdtemp()
    body = """
import jax
jax.config.update("jax_platforms", "cpu")
from gloo_tpu.checkpoint import StepCheckpointer

ckpt = StepCheckpointer({ckdir!r}, keep=3)

def step_fn(ectx, step, state):
    if rank == 2 and step == 4:
        os.kill(os.getpid(), signal.SIGKILL)
    x = np.ones(256, dtype=np.float32)
    ectx.allreduce(x, tag=1)
    state = {{"acc": float(state["acc"]) + float(x[0])}}
    if ectx.rank == 0:
        ckpt.save(step, {{"acc": np.array(state["acc"],
                                          dtype=np.float64)}})
    return state

res = elastic.run_elastic(
    step_fn, store=store, device=device, rank=rank, world_size=size,
    min_size=2, steps=8, state={{"acc": 0.0}},
    checkpointer=ckpt,
    template={{"acc": np.zeros((), dtype=np.float64)}},
    timeout=90.0)
print("OK", json.dumps({{"acc": float(res["state"]["acc"]),
                         "rebuilds": res["rebuilds"],
                         "sizes": [e["size"] for e in res["epochs"]]}}))
""".format(ckdir=ckdir)
    procs = [_spawn(body, r, 3, store,
                    extra_env={"JAX_PLATFORMS": "cpu"}) for r in range(3)]
    outs = [p.communicate(timeout=240) for p in procs]
    assert procs[2].returncode == -signal.SIGKILL
    results = []
    for r in (0, 1):
        assert procs[r].returncode == 0, (r, outs[r])
        results.append(_summary(outs[r]))
    for res in results:
        assert res["rebuilds"] == 1 and res["sizes"] == [3, 2], res
    # Both survivors restored the same committed accumulator and then
    # advanced it identically through the remaining steps.
    assert results[0]["acc"] == results[1]["acc"], results
    assert results[0]["acc"] > 0, results


def test_rebuild_after_failure_reaps_store_keys():
    """Satellite: rebuild_after_failure used to leave every
    rebuild/<gen>/* key in the store forever; on success the new rank 0
    now reaps the mesh bootstrap + roll-call keys — while KEEPING the
    stall/<rank> evidence, which is the post-mortem record
    stall_reports reads after the fact."""
    import gloo_tpu
    from gloo_tpu.resilience import stall_reports

    store_dir = tempfile.mkdtemp()
    body = """
from gloo_tpu.resilience import rebuild_after_failure
x = np.full(1 << 16, float(rank + 1), dtype=np.float32)
ctx = gloo_tpu.Context(rank, size, timeout=10.0)
ctx.connect_full_mesh(store, device)
if rank == 2:
    os.kill(os.getpid(), signal.SIGKILL)
try:
    ctx.allreduce(x, tag=1, timeout=3.0)
    sys.exit(3)
except gloo_tpu.IoError:
    pass
new_ctx, new_rank, new_size = rebuild_after_failure(
    store, gloo_tpu.Device(), old_rank=rank, old_size=size, generation=1,
    settle=3.0, timeout=60.0, failed_context=ctx)
assert new_ctx is not None and new_size == 2
y = np.full(64, 1.0, dtype=np.float32)
new_ctx.allreduce(y, tag=2)
new_ctx.close()
print("OK {}")
"""
    procs = [_spawn(body, r, 3, store_dir) for r in range(3)]
    outs = [p.communicate(timeout=180) for p in procs]
    assert procs[2].returncode == -signal.SIGKILL
    for r in (0, 1):
        assert procs[r].returncode == 0, (r, outs[r])
    store = gloo_tpu.FileStore(store_dir)
    # The O(n^2) mesh-bootstrap namespace and the roll-call keys are
    # gone; the stall evidence survives and still names the dead rank.
    assert store.list("rebuild/1/mesh") == []
    assert store.list("rebuild/1/alive/") == []
    reports = stall_reports(store, generation=1, old_size=3)
    assert reports, "stall evidence must survive the reap"
    suspects = [rep.get("suspect") for rep in reports.values()]
    assert max(set(suspects), key=suspects.count) == 2, reports


def test_lease_knobs_are_strict():
    """TPUCOLL_LEASE_MS / TPUCOLL_LEASE_GRACE take the strict env
    parsers: malformed values and a grace that cannot span two renewal
    periods fail loudly at agent construction."""
    prog = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {_REPO!r})
        import gloo_tpu
        from gloo_tpu import elastic
        try:
            elastic.ElasticAgent(gloo_tpu.HashStore(), gloo_tpu.Device(),
                                 rank=0, world_size=1)
            print("UNEXPECTED"); sys.exit(3)
        except gloo_tpu.Error as e:
            assert "TPUCOLL_LEASE" in str(e), e
            print("LOUD")
    """)
    for env_extra in ({"TPUCOLL_LEASE_MS": "fast"},
                      {"TPUCOLL_LEASE_MS": "500",
                       "TPUCOLL_LEASE_GRACE": "600"}):
        env = dict(os.environ, **env_extra)
        p = subprocess.run([sys.executable, "-c", prog],
                           capture_output=True, text=True, env=env,
                           timeout=120)
        assert p.returncode == 0 and "LOUD" in p.stdout, (
            env_extra, p.stdout, p.stderr)


def test_store_delete_and_list():
    """Satellite: delete(key) + list(prefix) across every store flavor
    (the ops lease reaping and namespace hygiene ride)."""
    import gloo_tpu

    def exercise(store):
        store.set("lease/1", b"a")
        store.set("lease/2", b"b")
        store.set("doc", b"c")
        assert sorted(store.list("lease/")) == ["lease/1", "lease/2"]
        assert sorted(store.list("")) == ["doc", "lease/1", "lease/2"]
        assert store.list("nope/") == []
        assert store.delete("lease/1") is True
        assert store.delete("lease/1") is False
        assert sorted(store.list("lease/")) == ["lease/2"]
        # A counter key (different file layout on FileStore) deletes too.
        store.add("ctr", 5)
        assert store.delete("ctr") is True
        assert store.add("ctr", 1) == 1  # recreated from zero
        # Namespaced view: list is relative to the prefix and delete
        # composes with it.
        p = gloo_tpu.PrefixStore(store, "lease")
        assert sorted(p.list("")) == ["2"]
        assert p.delete("2") is True
        assert store.list("lease/") == []

    exercise(gloo_tpu.HashStore())
    exercise(gloo_tpu.FileStore(tempfile.mkdtemp()))
    server = gloo_tpu.TcpStoreServer("127.0.0.1")
    exercise(gloo_tpu.TcpStore("127.0.0.1", server.port))
