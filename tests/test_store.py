"""Rendezvous store tests (reference analog: store usage in
gloo/rendezvous/* and gloo/test/ store paths)."""

import threading

import pytest

import gloo_tpu


def _exercise_store(store):
    store.set("alpha", b"1")
    store.set("beta", b"\x00\xffbin")
    assert store.get("alpha") == b"1"
    assert store.get("beta") == b"\x00\xffbin"
    # Overwrite
    store.set("alpha", b"2")
    assert store.get("alpha") == b"2"
    # Empty value is valid
    store.set("empty", b"")
    assert store.get("empty") == b""
    # Atomic counter
    assert store.add("ctr", 1) == 1
    assert store.add("ctr", 41) == 42


def test_hash_store():
    _exercise_store(gloo_tpu.HashStore())


def test_file_store(tmp_path):
    _exercise_store(gloo_tpu.FileStore(str(tmp_path)))


def test_file_store_cross_instance(tmp_path):
    a = gloo_tpu.FileStore(str(tmp_path))
    b = gloo_tpu.FileStore(str(tmp_path))
    a.set("key", b"value")
    assert b.get("key") == b"value"


def test_prefix_store_namespacing():
    base = gloo_tpu.HashStore()
    p1 = gloo_tpu.PrefixStore(base, "ctx1")
    p2 = gloo_tpu.PrefixStore(base, "ctx2")
    p1.set("k", b"one")
    p2.set("k", b"two")
    assert p1.get("k") == b"one"
    assert p2.get("k") == b"two"


def test_get_timeout():
    store = gloo_tpu.HashStore()
    with pytest.raises(gloo_tpu.TimeoutError):
        store.get("missing", timeout=0.1)


def test_get_blocks_until_set():
    store = gloo_tpu.HashStore()
    result = {}

    def reader():
        result["value"] = store.get("later", timeout=5.0)

    t = threading.Thread(target=reader)
    t.start()
    store.set("later", b"done")
    t.join(5.0)
    assert result["value"] == b"done"
