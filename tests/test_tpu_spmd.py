"""Device-plane collectives on a virtual 8-device CPU mesh.

The device plane is validated the way the reference validates CUDA paths
with multi-GPU fixtures (gloo/test/cuda_allreduce_test.cc): deterministic
per-rank inputs, closed-form expectations, every collective in the suite.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from gloo_tpu.tpu import TpuProcessGroup, make_mesh  # noqa: E402


@pytest.fixture(scope="module")
def pg():
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    return TpuProcessGroup(make_mesh())


def rows(pg, cols=16):
    rng = np.arange(pg.size * cols, dtype=np.float32).reshape(pg.size, cols)
    return rng + 1.0


def test_allreduce_sum(pg):
    x = rows(pg)
    out = pg.unshard(pg.allreduce(pg.shard(x)))
    expected = x.sum(axis=0)
    for r in range(pg.size):
        np.testing.assert_allclose(out[r], expected, rtol=1e-6)


@pytest.mark.parametrize("op,np_red", [("max", np.max), ("min", np.min),
                                       ("product", np.prod)])
def test_allreduce_ops(pg, op, np_red):
    x = rows(pg) * 0.5
    out = pg.unshard(pg.allreduce(pg.shard(x), op=op))
    expected = np_red(x, axis=0)
    for r in range(pg.size):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)


def test_broadcast(pg):
    x = rows(pg)
    out = pg.unshard(pg.broadcast(pg.shard(x), root=2))
    for r in range(pg.size):
        np.testing.assert_array_equal(out[r], x[2])


def test_reduce_root_only(pg):
    x = rows(pg)
    out = pg.unshard(pg.reduce(pg.shard(x), root=1))
    np.testing.assert_allclose(out[1], x.sum(axis=0), rtol=1e-6)
    for r in range(pg.size):
        if r != 1:
            np.testing.assert_array_equal(out[r], np.zeros_like(x[0]))


def test_allgather(pg):
    x = rows(pg)
    out = pg.unshard(pg.allgather(pg.shard(x)))
    assert out.shape == (pg.size, pg.size, x.shape[1])
    for r in range(pg.size):
        np.testing.assert_array_equal(out[r], x)


def test_reduce_scatter(pg):
    per = 4
    x = rows(pg, cols=1)[:, :1] * np.ones(
        (pg.size, pg.size * per), np.float32)
    out = pg.unshard(pg.reduce_scatter(pg.shard(x[..., None])))
    total = x.sum(axis=0)
    for r in range(pg.size):
        np.testing.assert_allclose(
            out[r, :, 0], total[r * per:(r + 1) * per], rtol=1e-6)


def test_alltoall(pg):
    p = pg.size
    # x[i, j] = i * 100 + j; after alltoall out[i, j] = j * 100 + i.
    x = (np.arange(p)[:, None] * 100 + np.arange(p)[None, :]).astype(
        np.float32)[..., None] * np.ones((p, p, 8), np.float32)
    out = pg.unshard(pg.alltoall(pg.shard(x)))
    expected = x.transpose(1, 0, 2)
    np.testing.assert_array_equal(out, expected)


def test_scatter(pg):
    p = pg.size
    x = rows(pg, cols=p * 3).reshape(p, p, 3)
    out = pg.unshard(pg.scatter(pg.shard(x), root=0))
    for r in range(p):
        np.testing.assert_array_equal(out[r, 0], x[0, r])


def test_shift(pg):
    x = rows(pg)
    out = pg.unshard(pg.shift(pg.shard(x), offset=1))
    for r in range(pg.size):
        np.testing.assert_array_equal(out[r], x[(r - 1) % pg.size])


def test_barrier(pg):
    pg.barrier()  # just must not deadlock or crash


def test_grad_through_allreduce(pg):
    """Collectives must be differentiable for DDP-style training."""
    from jax.sharding import PartitionSpec as P
    from gloo_tpu.tpu import spmd

    mesh = pg.mesh

    def loss(x):
        def shard_fn(s):
            return spmd.allreduce((s ** 2), pg.axis, "sum")
        y = jax.shard_map(shard_fn, mesh=mesh, in_specs=P(pg.axis),
                          out_specs=P(pg.axis))(x)
        return y.sum()

    x = pg.shard(rows(pg))
    g = pg.unshard(jax.jit(jax.grad(loss))(x))
    # d/dx_i sum over ranks of P * x_i^2-ish: each element contributes to
    # P rows of the output: grad = 2 * x * P.
    np.testing.assert_allclose(g, 2 * rows(pg) * pg.size, rtol=1e-6)


def test_group_compile_cache_no_retrace(pg, monkeypatch):
    """Repeat calls with the same shape/dtype/op must not re-trace.

    The per-shard function only runs at trace time, so counting its
    invocations counts traces (reference analog: CUDA algorithm ctors
    compile once, run() many — gloo/cuda_allreduce_ring.cc:14-100).
    """
    from gloo_tpu.tpu import spmd
    from gloo_tpu.tpu.group import TpuProcessGroup

    fresh = TpuProcessGroup(pg.mesh, pg.axis)
    traces = {"n": 0}
    real_allreduce = spmd.allreduce

    def counting(*args, **kwargs):
        traces["n"] += 1
        return real_allreduce(*args, **kwargs)

    monkeypatch.setattr(spmd, "allreduce", counting)
    x = fresh.shard(rows(pg))
    fresh.allreduce(x)
    assert traces["n"] == 1
    fresh.allreduce(x)
    fresh.allreduce(fresh.shard(rows(pg) * 2.0))
    assert traces["n"] == 1, "same shape/dtype/op re-traced"

    # Different shape or different op is a legitimate new trace.
    fresh.allreduce(fresh.shard(rows(pg, cols=32)))
    assert traces["n"] == 2
    fresh.allreduce(x, op="max")
    assert traces["n"] == 3
    fresh.allreduce(x, op="max")
    assert traces["n"] == 3
