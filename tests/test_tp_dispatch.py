"""Shape-aware fused/unfused TP dispatch (gloo_tpu/parallel/tp.py r5).

Pins the deployment rule from BASELINE.md "End-to-end fused-TP" in code:
fused wins iff the collective's share of the unfused step exceeds the
fused kernels' measured compute penalty (share > 1 - ratio). The two
measured shape families are the calibration points — M=4096/K=2048
(fused step 0.93x of unfused on one chip) and M=2048/K=4096 (0.68x) —
and the dispatcher must pick the measured winner in both.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from gloo_tpu.parallel import (allgather_matmul_dense_auto,  # noqa: E402
                               estimate_comm_share, fused_compute_ratio,
                               measure_fused_ratio,
                               row_parallel_dense_scattered_auto,
                               use_fused_overlap)

V = 8  # ring size of the measured calibration points


def test_ratio_matches_measured_families():
    """The ratio model reproduces the two end-to-end measurements
    (BASELINE.md: 0.93 at M=4096/K=2048, 0.68 at M=2048/K=4096) within
    a few points, conservative side."""
    fast = fused_compute_ratio(4096, 2048, V)   # 512-row chunks, K=2048
    slow = fused_compute_ratio(2048, 4096, V)   # 256-row chunks, K=4096
    assert abs(fast - 0.93) < 0.05, fast
    assert abs(slow - 0.68) < 0.05, slow
    assert slow < fast


def test_dispatch_picks_winner_both_families(monkeypatch):
    """The decision at the calibration points, across comm-share
    regimes. On one chip (share=0) fused always loses -> unfused both
    families; in the fast family a token 10% share flips it to fused;
    in the slow family 10% stays unfused (the 0.68x trap this
    dispatcher exists to avoid) and only >32% flips it."""
    monkeypatch.delenv("TPUCOLL_TP_OVERLAP", raising=False)
    # single chip / free collective: never fuse
    assert not use_fused_overlap(4096, 2048, 2048, V, comm_share=0.0)
    assert not use_fused_overlap(2048, 4096, 4096, V, comm_share=0.0)
    # fast family: penalty ~7%, 10% comm share already pays for it
    assert use_fused_overlap(4096, 2048, 2048, V, comm_share=0.10)
    # slow family: penalty ~32%, 10% must NOT fuse, 40% must
    assert not use_fused_overlap(2048, 4096, 4096, V, comm_share=0.10)
    assert use_fused_overlap(2048, 4096, 4096, V, comm_share=0.40)


def test_env_override_forces_both_ways(monkeypatch):
    monkeypatch.setenv("TPUCOLL_TP_OVERLAP", "fused")
    assert use_fused_overlap(2048, 4096, 4096, V, comm_share=0.0)
    monkeypatch.setenv("TPUCOLL_TP_OVERLAP", "unfused")
    assert not use_fused_overlap(4096, 2048, 2048, V, comm_share=0.99)
    monkeypatch.setenv("TPUCOLL_TP_OVERLAP", "bogus")
    with pytest.raises(ValueError, match="TPUCOLL_TP_OVERLAP"):
        use_fused_overlap(4096, 2048, 2048, V)


def test_estimate_comm_share_sanity(monkeypatch):
    monkeypatch.delenv("TPUCOLL_TP_ICI_GBPS", raising=False)
    monkeypatch.delenv("TPUCOLL_TP_TFLOPS", raising=False)
    assert estimate_comm_share(4096, 2048, 2048, 1) == 0.0
    s = estimate_comm_share(4096, 2048, 2048, 8)
    assert 0.0 < s < 1.0
    # halving the modeled ICI bandwidth must raise the share
    monkeypatch.setenv("TPUCOLL_TP_ICI_GBPS", "45")
    assert estimate_comm_share(4096, 2048, 2048, 8) > s
    # K-thin shards (less matmul per byte moved) -> larger share
    assert (estimate_comm_share(4096, 256, 2048, 8)
            > estimate_comm_share(4096, 2048, 2048, 8))
    # Gather-side wire sizing: the allgather moves the INPUT [m, k],
    # not the output [m, cols]. For an up-projection (cols = 4k) the
    # input-sized estimate must be ~4x smaller than the (wrong)
    # output-sized one.
    k, cols = 2048, 8192
    out_sized = estimate_comm_share(4096, k, cols, 8)
    in_sized = estimate_comm_share(4096, k, cols, 8,
                                   wire_elems=4096 * k)
    # share is t_comm/(t_comm+t_mm): compare the implied t_comm odds,
    # which ARE linear in wire bytes — input-sized must be cols/k = 4x
    # smaller.
    odds = lambda s: s / (1.0 - s)  # noqa: E731
    assert abs(odds(out_sized) / odds(in_sized) - cols / k) < 0.01


def test_measured_ratio_overrides_model(monkeypatch):
    """The bimodality mitigation: a process that measured a SLOW fused
    compile draw must fall back to unfused even where the shape model
    would fuse. Fast-family shape (model ratio 0.95, flip at 5%) with
    a 15% comm share: model fuses; a measured slow draw (0.79) does
    not; a measured fast draw (0.93) does."""
    monkeypatch.delenv("TPUCOLL_TP_OVERLAP", raising=False)
    assert use_fused_overlap(4096, 2048, 2048, V, comm_share=0.15)
    assert not use_fused_overlap(4096, 2048, 2048, V, comm_share=0.15,
                                 ratio=0.79)
    assert use_fused_overlap(4096, 2048, 2048, V, comm_share=0.15,
                             ratio=0.93)


def test_measure_fused_ratio_mechanism():
    """Probe mechanism under the interpreter (timing values are
    meaningless on CPU; shape checks, execution, and caching are not)."""
    from gloo_tpu.parallel import tp

    tp._PROBE_CACHE.clear()
    r = measure_fused_ratio(32, 64, 4, chain=3, reps=1, interpret=True)
    assert isinstance(r, float) and r > 0.0
    # interpreter-mode timings are never cached: a CPU smoke run must
    # not poison a later real measurement of the same shape
    assert len(tp._PROBE_CACHE) == 0
    # real measurements cache; simulate one by seeding the cache
    tp._PROBE_CACHE[(32, 64, 4, str(jnp.bfloat16))] = 0.5
    assert measure_fused_ratio(32, 64, 4) == 0.5
    tp._PROBE_CACHE.clear()
    with pytest.raises(ValueError, match="divisible"):
        measure_fused_ratio(30, 64, 4, interpret=True)
    with pytest.raises(ValueError, match="chain"):
        measure_fused_ratio(32, 64, 4, chain=1, interpret=True)


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.asarray(devs[:n], dtype=object), ("x",))


def _rand(shape, seed, dtype=np.float32):
    return np.random.RandomState(seed).uniform(-1, 1, shape).astype(dtype)


@pytest.mark.parametrize("force", ["fused", "unfused"])
def test_row_parallel_auto_both_paths_match_reference(force, monkeypatch):
    """Both dispatch arms of row_parallel_dense_scattered_auto compute
    the same row-scattered product (fused arm under the interpreter)."""
    monkeypatch.setenv("TPUCOLL_TP_OVERLAP", force)
    n = 4
    mesh = _mesh(n)
    m, k_total, cols = 8 * n, 16 * n, 128
    x = _rand((m, k_total), 0)
    w = _rand((k_total, cols), 1)

    fn = jax.jit(jax.shard_map(
        lambda xs, ws: row_parallel_dense_scattered_auto(
            xs, ws, "x", interpret=True),
        mesh=mesh, in_specs=(P(None, "x"), P("x", None)),
        out_specs=P("x", None), check_vma=False))
    out = np.asarray(fn(x, w))
    expected = x.astype(np.float64) @ w.astype(np.float64)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("force", ["fused", "unfused"])
def test_allgather_auto_both_paths_match_reference(force, monkeypatch):
    monkeypatch.setenv("TPUCOLL_TP_OVERLAP", force)
    n = 4
    mesh = _mesh(n)
    m_total, k, cols = 8 * n, 32, 128
    x = _rand((m_total, k), 2)
    w = _rand((k, cols), 3)

    fn = jax.jit(jax.shard_map(
        lambda xs, ws: allgather_matmul_dense_auto(
            xs, ws, "x", interpret=True),
        mesh=mesh, in_specs=(P("x", None), P(None, None)),
        out_specs=P(None, None), check_vma=False))
    out = np.asarray(fn(x, w))
    expected = x.astype(np.float64) @ w.astype(np.float64)
    np.testing.assert_allclose(out, expected, rtol=2e-5, atol=2e-5)


def test_auto_unfused_on_single_device_mesh(monkeypatch):
    """With auto dispatch and an estimated share, a 1-device axis (share
    0) must take the unfused path and still be correct — the common
    single-chip developer loop."""
    monkeypatch.delenv("TPUCOLL_TP_OVERLAP", raising=False)
    mesh = _mesh(1)
    x = _rand((64, 32), 4)
    w = _rand((32, 16), 5)
    fn = jax.jit(jax.shard_map(
        lambda xs, ws: row_parallel_dense_scattered_auto(xs, ws, "x"),
        mesh=mesh, in_specs=(P(None, "x"), P("x", None)),
        out_specs=P("x", None), check_vma=False))
    out = np.asarray(fn(x, w))
    np.testing.assert_allclose(
        out, x.astype(np.float64) @ w.astype(np.float64),
        rtol=2e-5, atol=2e-5)
