"""Randomized soak: sequences of mixed collectives with random shapes,
dtypes, ops, and algorithms, all mirrored against numpy. A last line of
defense for matcher/schedule interactions no targeted test covers."""

import numpy as np
import pytest

from tests.harness import spawn

DTYPES = [np.float32, np.float64, np.int32, np.int64]


def _tol(dtype):
    """Cross-rank float sums are order-dependent; tolerances scale with
    dtype precision (random inputs cancel, inflating relative error)."""
    if dtype == np.float32:
        return dict(rtol=1e-4, atol=1e-5)
    if dtype == np.float64:
        return dict(rtol=1e-9, atol=1e-12)
    return dict(rtol=0, atol=0)


def _expected_reduce(inputs, op):
    acc = inputs[0].astype(np.float64)
    for x in inputs[1:]:
        x = x.astype(np.float64)
        acc = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op](acc, x)
    return acc


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_collective_sequences(seed):
    rng = np.random.RandomState(seed)
    size = int(rng.choice([2, 3, 4, 8]))
    steps = 12
    # Pre-generate the shared schedule (every rank must agree).
    schedule = []
    for i in range(steps):
        kind = rng.choice(["allreduce", "broadcast", "allgather",
                           "reduce_scatter", "alltoall", "barrier"])
        count = int(rng.randint(1, 20000))
        dtype = DTYPES[rng.randint(len(DTYPES))]
        op = str(rng.choice(["sum", "min", "max"]))
        algo = str(rng.choice(["ring", "halving_doubling", "bcube"]))
        root = int(rng.randint(size))
        schedule.append((kind, count, dtype, op, algo, root))

    def make_input(rank, i, count, dtype):
        r = np.random.RandomState(1000 * i + rank)
        if np.issubdtype(dtype, np.integer):
            return r.randint(-50, 50, count).astype(dtype)
        return (r.randn(count) * 3).astype(dtype)

    def fn(ctx, rank):
        outs = []
        for i, (kind, count, dtype, op, algo, root) in enumerate(schedule):
            x = make_input(rank, i, count, dtype)
            if kind == "allreduce":
                ctx.allreduce(x, op=op, algorithm=algo, tag=i)
                outs.append(x)
            elif kind == "broadcast":
                ctx.broadcast(x, root=root, tag=i)
                outs.append(x)
            elif kind == "allgather":
                outs.append(ctx.allgather(x, tag=i))
            elif kind == "reduce_scatter":
                counts = [count // size] * size
                counts[-1] += count % size
                outs.append(ctx.reduce_scatter(x, recv_counts=counts,
                                               op=op, tag=i))
            elif kind == "alltoall":
                per = max(count // size, 1)
                a = make_input(rank, i, per * size, dtype).reshape(size, per)
                outs.append(ctx.alltoall(a, tag=i))
            else:
                ctx.barrier(tag=i)
                outs.append(None)
        return outs

    results = spawn(size, fn, timeout=120)

    for i, (kind, count, dtype, op, algo, root) in enumerate(schedule):
        ins = [make_input(r, i, count, dtype) for r in range(size)]
        for rank in range(size):
            got = results[rank][i]
            if kind == "allreduce":
                np.testing.assert_allclose(
                    got.astype(np.float64), _expected_reduce(ins, op),
                    err_msg=f"step {i} {kind} {algo}", **_tol(dtype))
            elif kind == "broadcast":
                np.testing.assert_array_equal(got, ins[root],
                                              err_msg=f"step {i}")
            elif kind == "allgather":
                np.testing.assert_array_equal(got, np.stack(ins),
                                              err_msg=f"step {i}")
            elif kind == "reduce_scatter":
                counts = [count // size] * size
                counts[-1] += count % size
                off = sum(counts[:rank])
                np.testing.assert_allclose(
                    got.astype(np.float64),
                    _expected_reduce(ins, op)[off:off + counts[rank]],
                    err_msg=f"step {i}", **_tol(dtype))
            elif kind == "alltoall":
                per = max(count // size, 1)
                a2a_ins = [make_input(r, i, per * size, dtype)
                           .reshape(size, per) for r in range(size)]
                expected = np.stack([a2a_ins[src][rank]
                                     for src in range(size)])
                np.testing.assert_array_equal(got, expected,
                                              err_msg=f"step {i}")


@pytest.mark.parametrize("seed", [10, 11])
def test_fuzz_one_sided_mixed(seed):
    """Mixed one-sided traffic interleaved with collectives, optionally
    encrypted: random puts (some with notify), gets, and allreduces on
    one context must never corrupt each other — one-sided frames bypass
    the matcher while collectives ride it, so slot/stash interactions get
    a randomized workout here."""
    rng = np.random.RandomState(seed)
    size = int(rng.choice([2, 3, 4]))
    encrypted = bool(rng.randint(2))
    steps = 10
    region_words = 4096
    schedule = []
    for i in range(steps):
        kind = rng.choice(["put", "put_notify", "get", "allreduce"])
        peer_off = int(rng.randint(1, size))
        count = int(rng.randint(1, 1024))
        roffset = int(rng.randint(0, region_words - count))
        schedule.append((str(kind), peer_off, count, roffset))

    def fn(ctx, rank):
        region = np.zeros(region_words, dtype=np.float64)
        region_buf = ctx.register(region)
        keys = None
        mine = np.frombuffer(region_buf.get_remote_key(),
                             dtype=np.uint8).copy()
        keys = [k.tobytes() for k in ctx.allgather(mine)]
        outs = []
        for i, (kind, peer_off, count, roffset) in enumerate(schedule):
            peer = (rank + peer_off) % ctx.size
            if kind in ("put", "put_notify"):
                payload = np.full(count, 100.0 * rank + i, np.float64)
                pbuf = ctx.register(payload)
                pbuf.put(keys[peer], roffset=roffset * 8,
                         nbytes=count * 8, notify=kind == "put_notify")
                pbuf.wait_send()
                outs.append(None)
            elif kind == "get":
                got = np.zeros(count, dtype=np.float64)
                gbuf = ctx.register(got)
                gbuf.get(keys[peer], slot=1000 + i,
                         roffset=roffset * 8, nbytes=count * 8)
                gbuf.wait_recv()
                outs.append(got)
            else:
                x = np.full(1000, float(rank + 1), np.float32)
                ctx.allreduce(x, tag=100 + i)
                outs.append(x[0])
        # Every notify-put that targeted this rank must produce exactly
        # one arrival (drained here so nothing leaks across tests).
        expect_arrivals = sum(
            1 for src in range(ctx.size)
            for (k, off, c, ro) in schedule
            if k == "put_notify" and (src + off) % ctx.size == rank)
        for _ in range(expect_arrivals):
            assert region_buf.wait_put(timeout=10.0) is not None
        ctx.barrier(tag=999)
        return outs

    kwargs = ({"auth_key": "fuzz", "encrypt": True} if encrypted else {})
    results = spawn(size, fn, timeout=120, device_kwargs=kwargs)
    expect_ar = sum(r + 1 for r in range(size))
    for rank in range(size):
        for i, (kind, peer_off, count, roffset) in enumerate(schedule):
            if kind == "allreduce":
                assert results[rank][i] == expect_ar
