"""Multi-channel transport: loop-thread pool + striped pair connections
(csrc/tpucoll/transport/{device,pair,context}.cc, wire.h kStripe).

With TPUCOLL_LOOP_THREADS > 1 a Device runs a pool of event-loop threads
(listener on loop 0, pairs sharded round-robin) and with
TPUCOLL_CHANNELS > 1 each logical pair opens extra data connections:
payloads at or above TPUCOLL_STRIPE_BYTES split into deterministic
contiguous stripes sent concurrently, one per channel, each with its own
handshake/encryption state. Covered here: collective + p2p correctness
across the channel matrix (plain / authKey / encrypt tiers, P=3),
striping engagement evidence via the per-channel metrics counters, the
shm-bypass interaction, one-sided put striping, same-seed chaos
determinism across channels, flight-recorder sanity when stripes land
out of order, loud channel-count mismatch at bootstrap, and the strict
env parsing of every transport knob.

The knobs are resolved per process (env at Device/Context construction,
with function-local-static caches elsewhere in the shm plane), so every
configuration point runs in fresh subprocesses over a FileStore.
"""

import json
import os
import subprocess
import sys
import tempfile
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Worker battery: bulk striped allreduce, sub-threshold allreduce,
# allgather, reduce_scatter, tagged send/recv, barrier — then print the
# per-channel byte counters for the parent to assert on.
_BATTERY = textwrap.dedent("""
    import json, sys
    sys.path.insert(0, __REPO__)
    import numpy as np
    import gloo_tpu

    rank = int(sys.argv[1])
    size = int(sys.argv[2])
    dev_kwargs = json.loads(sys.argv[4])
    ctx = gloo_tpu.Context(rank, size, timeout=60)
    ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                          gloo_tpu.Device(**dev_kwargs))
    total = size * (size + 1) // 2

    x = np.full(1 << 20, float(rank + 1), dtype=np.float32)  # 4 MiB
    ctx.allreduce(x)
    assert x[0] == total and x[-1] == total, x[:4]

    small = np.full(64, float(rank + 1), dtype=np.float32)
    ctx.allreduce(small)
    assert small[0] == total, small[0]

    g = ctx.allgather(np.full(1 << 18, float(rank), dtype=np.float32))
    for r in range(size):
        assert g[r][0] == float(r) and g[r][-1] == float(r)

    rs = ctx.reduce_scatter(
        np.full(size * (1 << 17), float(rank + 1), dtype=np.float32))
    assert rs[0] == total and rs[-1] == total

    peer = (rank + 1) % size
    src = (rank - 1) % size
    buf = np.arange(1 << 19, dtype=np.float32) + rank
    out = np.zeros(1 << 19, dtype=np.float32)
    ctx.send(buf, peer, 500 + rank)
    ctx.recv(out, src, 500 + src)
    assert out[1] == 1.0 + src, (out[1], src)

    ctx.barrier()
    print("CHANNELS", json.dumps(ctx.metrics().get("channels", {})))
    print("LOOPS", json.dumps(ctx.metrics().get("loops", {})))
    ctx.barrier()
    ctx.close()
    print("BATTERY-OK")
""").replace("__REPO__", repr(_REPO))


def _spawn(size, env_extra, body=_BATTERY, dev_kwargs=None, per_rank_env=None,
           timeout=120):
    store = tempfile.mkdtemp()
    procs = []
    for r in range(size):
        env = dict(os.environ, **env_extra)
        if per_rank_env is not None:
            env.update(per_rank_env[r])
        procs.append(subprocess.Popen(
            [sys.executable, "-c", body, str(r), str(size), store,
             json.dumps(dev_kwargs or {})],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env))
    outs = [p.communicate(timeout=timeout) for p in procs]
    return procs, outs


def _assert_battery(procs, outs, channels):
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "BATTERY-OK" in out, \
            (r, p.returncode, out[-300:], err[-1500:])
        ch = json.loads(out.split("CHANNELS", 1)[1].splitlines()[0])
        # Striping engaged: every extra channel moved payload bytes.
        for c in range(1, channels):
            assert str(c) in ch and ch[str(c)]["tx_bytes"] > 0, (r, c, ch)
            assert ch[str(c)]["rx_bytes"] > 0, (r, c, ch)
    return outs


# channels x loop-threads x security tier, all with shm disabled so the
# bulk payloads actually ride the striped TCP plane (same-host shm would
# bypass striping — covered separately below).
_TIERS = {
    "plain": {},
    "auth": {"auth_key": "mc-test-key"},
    "encrypt": {"auth_key": "mc-test-key", "encrypt": True},
}

_MATRIX = [(2, 2, "plain"), (3, 2, "plain"), (4, 2, "plain"),
           (2, 2, "auth"), (2, 2, "encrypt")]


@pytest.mark.parametrize("channels,loops,tier", _MATRIX,
                         ids=[f"ch{c}-loops{l}-{t}" for c, l, t in _MATRIX])
def test_multichannel_collectives(channels, loops, tier):
    """All collectives at P=3 across the channel matrix, with striping
    engagement asserted from the per-channel byte counters."""
    procs, outs = _spawn(3, {
        "TPUCOLL_SHM": "0",
        "TPUCOLL_CHANNELS": str(channels),
        "TPUCOLL_LOOP_THREADS": str(loops),
        "TPUCOLL_STRIPE_BYTES": str(64 << 10),
    }, dev_kwargs=_TIERS[tier])
    _assert_battery(procs, outs, channels)


def test_multichannel_loop_pool_progress():
    """With a 2-thread loop pool both loops actually dispatch I/O (the
    per-loop progress stamps in the metrics registry are the evidence)."""
    procs, outs = _spawn(3, {
        "TPUCOLL_SHM": "0",
        "TPUCOLL_CHANNELS": "2",
        "TPUCOLL_LOOP_THREADS": "2",
        "TPUCOLL_STRIPE_BYTES": str(64 << 10),
    })
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (r, out[-300:], err[-1500:])
        loops = json.loads(out.split("LOOPS", 1)[1].splitlines()[0])
        assert "0" in loops and "1" in loops, (r, loops)
        assert loops["0"]["events"] > 0 and loops["1"]["events"] > 0


def test_multichannel_with_shm_active():
    """Channels + same-host shm coexist: bulk payloads keep the shm fast
    path (striping bypassed, extra channels idle), everything correct."""
    procs, outs = _spawn(3, {
        "TPUCOLL_CHANNELS": "2",
        "TPUCOLL_LOOP_THREADS": "2",
        "TPUCOLL_STRIPE_BYTES": str(64 << 10),
    })
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "BATTERY-OK" in out, \
            (r, out[-300:], err[-1500:])
        ch = json.loads(out.split("CHANNELS", 1)[1].splitlines()[0])
        # The 4 MiB payloads rode shm, so channel 1 carried at most
        # handshake-free residue (nothing at all today).
        assert ch.get("1", {}).get("tx_bytes", 0) == 0, (r, ch)


def test_put_striping():
    """One-sided non-notify puts stripe across channels and land whole."""
    body = textwrap.dedent("""
        import json, sys
        sys.path.insert(0, __REPO__)
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                              gloo_tpu.Device())
        n = 1 << 20
        region = np.zeros(n, dtype=np.uint8)
        export = ctx.register(region)
        key = np.frombuffer(export.get_remote_key(), dtype=np.uint8)
        out = ctx.allgather(key)
        keys = [out[r].tobytes() for r in range(size)]
        src = np.arange(n, dtype=np.uint8) % 251
        local = ctx.register(src)
        peer = (rank + 1) % size
        local.put(keys[peer], offset=0, roffset=0, nbytes=n)
        local.wait_send()
        ctx.barrier()
        # The barrier orders only channel-0 traffic; a NON-notify put's
        # extra-channel stripes carry no arrival signal (that is what
        # notify=True is for — docs/transport.md), so poll for landing
        # with a bounded deadline instead of asserting instantly.
        import time
        expected = np.arange(n, dtype=np.uint8) % 251
        deadline = time.time() + 20
        while not np.array_equal(region, expected) and \\
                time.time() < deadline:
            time.sleep(0.01)
        assert np.array_equal(region, expected), region[:8]
        ch = ctx.metrics().get("channels", {})
        assert ch.get("1", {}).get("tx_bytes", 0) > 0, ch
        ctx.barrier()
        ctx.close()
        print("PUT-OK")
    """).replace("__REPO__", repr(_REPO))
    procs, outs = _spawn(2, {
        "TPUCOLL_SHM": "0",
        "TPUCOLL_CHANNELS": "2",
        "TPUCOLL_LOOP_THREADS": "2",
        "TPUCOLL_STRIPE_BYTES": str(64 << 10),
    }, body=body)
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "PUT-OK" in out, \
            (r, out[-300:], err[-1500:])


def test_chaos_same_seed_determinism_across_channels():
    """Two same-seed chaos runs with striped traffic produce byte-identical
    per-rank fault firing sequences (per-(rule, rank, channel) state)."""
    schedule = {"seed": 11, "faults": [
        {"when": {"opcode": "data", "min_bytes": 64 << 10},
         "action": "delay", "ms": 1, "prob": 0.5},
        {"when": {"opcode": "data", "max_bytes": 1024, "nth": 3},
         "action": "dup"},
    ]}
    body = textwrap.dedent("""
        import json, sys
        sys.path.insert(0, __REPO__)
        import numpy as np
        import gloo_tpu
        from gloo_tpu import fault

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                              gloo_tpu.Device())
        for i in range(6):
            x = np.full(1 << 19, float(rank + 1 + i), dtype=np.float32)
            ctx.allreduce(x, tag=2 * i)
            small = np.full(8, 1.0, dtype=np.float32)
            ctx.allreduce(small, tag=2 * i + 1)
        ctx.barrier()
        mine = [e for e in fault.report() if e["rank"] == rank]
        print("REPORT", json.dumps(mine, sort_keys=True))
        ctx.barrier()
        ctx.close()
    """).replace("__REPO__", repr(_REPO))

    def run_once():
        fd, sched_path = tempfile.mkstemp(suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump(schedule, f)
        procs, outs = _spawn(3, {
            "TPUCOLL_SHM": "0",
            "TPUCOLL_CHANNELS": "2",
            "TPUCOLL_LOOP_THREADS": "2",
            "TPUCOLL_STRIPE_BYTES": str(64 << 10),
            "TPUCOLL_FAULT_FILE": sched_path,
        }, body=body)
        reports = {}
        for r, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, (r, out[-300:], err[-1500:])
            reports[r] = out.split("REPORT", 1)[1].splitlines()[0]
        return reports

    first = run_once()
    second = run_once()
    assert first == second
    # The delay rule actually hit striped traffic on both channels.
    fired = [e for r in first.values() for e in json.loads(r)]
    assert any(e["channel"] == 1 for e in fired), fired
    assert any(e["channel"] == 0 for e in fired), fired


def test_flightrec_no_spurious_desync_with_stripes():
    """Stripes completing out of order across channels must not shift the
    flight recorder's cross-rank schedule comparison: a clean multi-
    channel run merges with no desync verdict."""
    body = textwrap.dedent("""
        import json, os, sys
        sys.path.insert(0, __REPO__)
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                              gloo_tpu.Device())
        for i in range(8):
            x = np.full(1 << 19, float(rank + 1), dtype=np.float32)
            ctx.allreduce(x, tag=3 * i)
            g = ctx.allgather(np.full(1 << 17, float(rank), np.float32))
            assert g[rank][0] == float(rank)
            ctx.barrier(tag=3 * i + 2)
        ctx.flightrec_dump(os.environ["MC_FR_DIR"] +
                           "/flightrec-rank%d.json" % rank)
        ctx.barrier(tag=999)
        ctx.close()
        print("FR-OK")
    """).replace("__REPO__", repr(_REPO))
    fr_dir = tempfile.mkdtemp()
    procs, outs = _spawn(3, {
        "TPUCOLL_SHM": "0",
        "TPUCOLL_CHANNELS": "3",
        "TPUCOLL_LOOP_THREADS": "2",
        "TPUCOLL_STRIPE_BYTES": str(64 << 10),
        "MC_FR_DIR": fr_dir,
    }, body=body)
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "FR-OK" in out, \
            (r, out[-300:], err[-1500:])
    from gloo_tpu.utils import flightrec
    merged = flightrec.merge(fr_dir)
    assert len(merged["ranks"]) == 3, merged.get("missing")
    verdict = flightrec.analyze(merged)
    assert verdict["kind"] != "desync", verdict
    assert flightrec.detect_desync(
        {r: doc.get("events", []) for r, doc in merged["ranks"].items()}
    ) is None


def test_channel_count_mismatch_fails_loudly():
    """Ranks disagreeing on TPUCOLL_CHANNELS must fail the bootstrap with
    a message naming the knob — never hang the mesh."""
    body = textwrap.dedent("""
        import sys
        sys.path.insert(0, __REPO__)
        import gloo_tpu

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        ctx = gloo_tpu.Context(rank, size, timeout=15)
        try:
            ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                                  gloo_tpu.Device())
        except gloo_tpu.Error as e:
            assert "TPUCOLL_CHANNELS" in str(e), e
            print("MISMATCH-CAUGHT")
            sys.exit(0)
        print("UNEXPECTED-CONNECT")
        sys.exit(1)
    """).replace("__REPO__", repr(_REPO))
    procs, outs = _spawn(
        2, {"TPUCOLL_SHM": "0", "TPUCOLL_LOOP_THREADS": "1"}, body=body,
        per_rank_env=[{"TPUCOLL_CHANNELS": "2"},
                      {"TPUCOLL_CHANNELS": "1"}])
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "MISMATCH-CAUGHT" in out, \
            (r, p.returncode, out[-200:], err[-1000:])


@pytest.mark.parametrize("var,value,ctor", [
    ("TPUCOLL_CHANNELS", "banana", "context"),
    ("TPUCOLL_CHANNELS", "0", "context"),
    ("TPUCOLL_CHANNELS", "99", "context"),
    ("TPUCOLL_STRIPE_BYTES", "-1", "context"),
    ("TPUCOLL_STRIPE_BYTES", "8MB", "context"),
    ("TPUCOLL_MAX_STASH_BYTES", "lots", "context"),
    ("TPUCOLL_LOOP_THREADS", "many", "device"),
    ("TPUCOLL_LOOP_THREADS", "0", "device"),
    ("TPUCOLL_SHM_RING", "big", "shm"),
    ("TPUCOLL_SHM_THRESHOLD", "1e6", "shm"),
    # Knobs migrated off raw getenv by the env-hygiene pass
    # (docs/check.md): each historically atoll'd/strcmp'd its value into
    # silence; all now throw through common/env.h strict parsers.
    ("TPUCOLL_ENGINE", "kqueue", "device"),
    ("TPUCOLL_LOG_LEVEL", "debgu", "device"),
    ("TPUCOLL_NO_AVX512", "true", "device"),
    ("TPUCOLL_WATCHDOG_MS", "never", "shm"),
    ("TPUCOLL_FLIGHTREC_EVENTS", "banana", "shm"),
    ("TPUCOLL_FLIGHTREC_SIGNALS", "yes", "shm"),
    ("TPUCOLL_TRACE_MAX_EVENTS", "-5", "shm"),
    ("TPUCOLL_DISABLE_CONNECTION_RETRIES", "2", "shm"),
    ("TPUCOLL_SHM", "yes", "shm"),
    # Collective-time knobs: read at the first schedule that consults
    # them — a ring-sized allreduce for the fuse policy, a forced-hd
    # non-power-of-2 group for the fold/blocks strategy.
    ("TPUCOLL_RECV_REDUCE", "maybe", "ring"),
    ("TPUCOLL_HD_NP2", "folded", "hd3"),
])
def test_strict_env_parsing(var, value, ctor):
    """Malformed transport knobs throw loudly at configuration time
    (common/env.h) instead of silently running with atoll fallbacks."""
    body = textwrap.dedent("""
        import sys
        sys.path.insert(0, __REPO__)
        import numpy as np
        import gloo_tpu

        var = sys.argv[1]
        ctor = sys.argv[2]
        try:
            dev = gloo_tpu.Device()     # TPUCOLL_LOOP_THREADS reads here
            ctx = gloo_tpu.Context(0, 1, timeout=10)
            ctx.connect_full_mesh(gloo_tpu.HashStore(), dev)
            # Group- and collective-time knobs resolve lazily; a 1-rank
            # group never connects a pair, so force the reads through an
            # in-process group shaped for the knob: 2 ranks for the
            # transport/shm/context-lifecycle knobs, a ring-sized
            # payload for the fuse policy, 3 ranks + algorithm="hd" for
            # the non-power-of-2 fold/blocks strategy.
            if ctor in ("shm", "ring", "hd3"):
                import threading
                nranks = 3 if ctor == "hd3" else 2
                nelems = (1 << 20) if ctor == "ring" else 64 << 10
                kwargs = {"algorithm": "hd"} if ctor == "hd3" else {}
                store = gloo_tpu.HashStore()
                errs = []
                def w(rank):
                    try:
                        d = gloo_tpu.Device()
                        c = gloo_tpu.Context(rank, nranks, timeout=10)
                        c.connect_full_mesh(store, d)
                        x = np.full(nelems, 1.0, dtype=np.float32)
                        c.allreduce(x, **kwargs)
                        c.close()
                    except Exception as e:
                        errs.append(e)
                ts = [threading.Thread(target=w, args=(r,))
                      for r in range(nranks)]
                [t.start() for t in ts]
                [t.join(60) for t in ts]
                if errs:
                    raise errs[0]
        except Exception as e:
            assert var in str(e), (var, e)
            print("STRICT-OK")
            sys.exit(0)
        print("NO-ERROR")
        sys.exit(1)
    """).replace("__REPO__", repr(_REPO))
    env = dict(os.environ, **{var: value})
    proc = subprocess.run([sys.executable, "-c", body, var, ctor],
                          capture_output=True, text=True, env=env,
                          timeout=120)
    assert proc.returncode == 0 and "STRICT-OK" in proc.stdout, \
        (var, value, proc.stdout[-200:], proc.stderr[-1000:])


def test_tuning_table_transport_hints_configure_channels():
    """A tuning table's {"transport": {...}} hints (docs/tuning.md)
    configure the mesh at connect when the env knobs are unset — and a
    hinted table round-trips through the native JSON parser."""
    body = textwrap.dedent("""
        import json, sys
        sys.path.insert(0, __REPO__)
        import numpy as np
        import gloo_tpu
        from gloo_tpu import tuning

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                              gloo_tpu.Device())
        x = np.full(1 << 20, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == size * (size + 1) // 2
        ch = ctx.metrics().get("channels", {})
        assert ch.get("1", {}).get("tx_bytes", 0) > 0, ch
        # Round trip: the installed table still carries the hints.
        installed = tuning.installed_table(ctx)
        assert installed["transport"]["channels"] == 2, installed
        ctx.barrier()
        ctx.close()
        print("HINTS-OK")
    """).replace("__REPO__", repr(_REPO))
    fd, table_path = tempfile.mkstemp(suffix=".json")
    with os.fdopen(fd, "w") as f:
        json.dump({"version": 1, "entries": [],
                   "transport": {"channels": 2, "stripe_bytes": 64 << 10}},
                  f)
    procs, outs = _spawn(2, {
        "TPUCOLL_SHM": "0",
        "TPUCOLL_TUNING_FILE": table_path,
    }, body=body)
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "HINTS-OK" in out, \
            (r, out[-300:], err[-1500:])


def test_channel_failure_poisons_logical_pair():
    """A kill fault on striped traffic fails the whole logical pair: the
    sender's collective raises, the receiver's claimed/posted receives
    error instead of hanging (the stripe-reassembly poisoning path)."""
    schedule = {"seed": 3, "faults": [
        {"when": {"rank": 0, "opcode": "data", "min_bytes": 64 << 10,
                  "nth": 2},
         "action": "kill"}]}
    body = textwrap.dedent("""
        import json, sys
        sys.path.insert(0, __REPO__)
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        ctx = gloo_tpu.Context(rank, size, timeout=20)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                              gloo_tpu.Device())
        try:
            for i in range(4):
                x = np.full(1 << 19, float(rank + 1), dtype=np.float32)
                ctx.allreduce(x, tag=i)
            print("UNEXPECTED-SURVIVED")
            sys.exit(1)
        except gloo_tpu.Error as e:
            print("FAILED-LOUDLY", repr(str(e)))
            sys.exit(0)
    """).replace("__REPO__", repr(_REPO))
    fd, sched_path = tempfile.mkstemp(suffix=".json")
    with os.fdopen(fd, "w") as f:
        json.dump(schedule, f)
    procs, outs = _spawn(2, {
        "TPUCOLL_SHM": "0",
        "TPUCOLL_CHANNELS": "2",
        "TPUCOLL_LOOP_THREADS": "2",
        "TPUCOLL_STRIPE_BYTES": str(64 << 10),
        "TPUCOLL_FAULT_FILE": sched_path,
    }, body=body)
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "FAILED-LOUDLY" in out, \
            (r, p.returncode, out[-300:], err[-1500:])


def test_unmatched_stripe_flood_bounded():
    """An unmatched striped flood with a tiny stash watermark: in-flight
    reassembly stages count against the watermark and pause only the
    "ahead" channels, so memory stays bounded while every open entry can
    still complete — a bug in that backpressure (pausing a channel an
    open entry needs) deadlocks this test instead of passing it."""
    body = textwrap.dedent("""
        import sys, time
        sys.path.insert(0, __REPO__)
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[3]),
                              gloo_tpu.Device())
        n = 8
        if rank == 0:
            # Each message is 2 MiB (two 1 MiB stripes), 2x the watermark:
            # a single open stage already crosses it.
            for i in range(n):
                ctx.send(np.full(1 << 19, float(i + 1), dtype=np.float32),
                         1, i)
        else:
            time.sleep(1.0)  # let the flood arrive unmatched
            for i in range(n):
                out = np.zeros(1 << 19, dtype=np.float32)
                ctx.recv(out, 0, i)
                assert out[0] == i + 1 and out[-1] == i + 1, (i, out[:2])
        x = np.full(1 << 19, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=99)
        assert x[0] == 3.0, x[0]
        ctx.barrier(tag=100)
        ctx.close()
        print("FLOOD-OK")
    """).replace("__REPO__", repr(_REPO))
    procs, outs = _spawn(2, {
        "TPUCOLL_SHM": "0",
        "TPUCOLL_CHANNELS": "2",
        "TPUCOLL_LOOP_THREADS": "2",
        "TPUCOLL_STRIPE_BYTES": str(64 << 10),
        "TPUCOLL_MAX_STASH_BYTES": str(1 << 20),
    }, body=body)
    for r, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "FLOOD-OK" in out, \
            (r, p.returncode, out[-300:], err[-1500:])
