"""Pipelined wire codec (ISSUE 20): the sharded codec pool's
byte-identity contract across shard counts and ragged tails, the q4
packed-nibble codec's layout and round-trip error bound, error-feedback
residual convergence, pipelined-vs-serial A/B byte identity, P=2..4
consensus for the new arms (ring_q4_wire allreduce + reduce_scatter,
pipelined q8), and same-seed chaos determinism with the codec pool on.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import _lib
from gloo_tpu._lib import Error

from tests.harness import spawn

BLOCK = 256  # default TPUCOLL_Q4_BLOCK / TPUCOLL_Q8_BLOCK

# Codec kinds of the sharded capi surface (wire_codec.h ids).
KIND_BF16, KIND_Q8, KIND_Q4 = 0, 1, 2


def _ptr(a):
    return a.ctypes.data


# ---------------------------------------------------------------------------
# Sharded codec surface: byte identity against the serial walk
# ---------------------------------------------------------------------------

def _serial_encode(kind, x):
    if kind == KIND_Q8:
        return gloo_tpu.q8_encode(x)
    if kind == KIND_Q4:
        return gloo_tpu.q4_encode(x)
    # bf16: round-to-nearest-even via float32 truncation-with-rounding —
    # jax/ml_dtypes-free reference: float32 -> uint32 -> rounded high half.
    u = x.view(np.uint32)
    rounded = (u + 0x7FFF + ((u >> 16) & 1)) >> 16
    return rounded.astype(np.uint16).view(np.uint8).copy()


@pytest.mark.parametrize("kind", [KIND_BF16, KIND_Q8, KIND_Q4])
@pytest.mark.parametrize("n", [1, 7, BLOCK - 1, BLOCK, BLOCK + 1,
                               4 * BLOCK + 13, 16 * BLOCK + 255])
def test_sharded_encode_byte_identity(kind, n):
    """tc_codec_encode_sharded output is byte-identical to the serial
    codec for EVERY shard count, including shards > units and ragged
    tails — the contract the pipelined rings ride on."""
    rng = np.random.default_rng(n)
    x = (rng.standard_normal(n) *
         10.0 ** rng.integers(-2, 3, size=n)).astype(np.float32)
    ref = _serial_encode(kind, x)
    for shards in (1, 2, 3, 4, 7, 16, 64):
        dst = np.zeros(ref.nbytes, dtype=np.uint8)
        rc = _lib.lib.tc_codec_encode_sharded(
            kind, _ptr(x), n, _ptr(dst), dst.nbytes, shards)
        assert rc == 0, _lib.last_error()
        assert bytes(dst) == bytes(ref), (kind, n, shards)


@pytest.mark.parametrize("kind", [KIND_Q8, KIND_Q4])
@pytest.mark.parametrize("n", [1, BLOCK, 4 * BLOCK + 13])
def test_sharded_accumulate_byte_identity(kind, n):
    """tc_codec_accumulate_sharded == decode + add, bit-exactly, for any
    shard count (the fused dequant-accumulate the RS hops run)."""
    rng = np.random.default_rng(n + 1)
    x = rng.standard_normal(n).astype(np.float32)
    base = rng.standard_normal(n).astype(np.float32)
    wire = gloo_tpu.q8_encode(x) if kind == KIND_Q8 else gloo_tpu.q4_encode(x)
    decoded = (gloo_tpu.q8_decode(wire, n) if kind == KIND_Q8
               else gloo_tpu.q4_decode(wire, n))
    ref = base + decoded
    for shards in (1, 2, 5, 32):
        acc = base.copy()
        rc = _lib.lib.tc_codec_accumulate_sharded(
            kind, _ptr(acc), _ptr(wire), n, wire.nbytes, shards)
        assert rc == 0, _lib.last_error()
        assert np.array_equal(acc, ref), (kind, n, shards)


def test_sharded_surface_size_echo_and_kind_checks():
    x = np.ones(100, dtype=np.float32)
    dst = np.zeros(gloo_tpu.q8_wire_bytes(100), dtype=np.uint8)
    # Wrong dstBytes echo fails loudly (stale-caller guard, q8 idiom).
    assert _lib.lib.tc_codec_encode_sharded(
        KIND_Q8, _ptr(x), 100, _ptr(dst), dst.nbytes - 1, 1) != 0
    # Unknown kind fails loudly.
    assert _lib.lib.tc_codec_encode_sharded(
        9, _ptr(x), 100, _ptr(dst), dst.nbytes, 1) != 0
    assert int(_lib.lib.tc_codec_threads()) >= 1
    assert 1 <= int(_lib.lib.tc_codec_pipeline()) <= 32


def test_codec_knob_resolution():
    """TPUCOLL_CODEC_THREADS defaults to TPUCOLL_LOOP_THREADS; both it
    and TPUCOLL_CODEC_PIPELINE resolve strictly in range."""
    code = ("import gloo_tpu; "
            "print(gloo_tpu.codec_threads(), gloo_tpu.codec_pipeline())")
    env = dict(os.environ, TPUCOLL_LOOP_THREADS="3",
               TPUCOLL_CODEC_PIPELINE="8", TPUCOLL_SKIP_BUILD="1")
    env.pop("TPUCOLL_CODEC_THREADS", None)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, check=True)
    threads, depth = map(int, out.stdout.split())
    assert threads == 3 and depth == 8

    env = dict(os.environ, TPUCOLL_CODEC_THREADS="5",
               TPUCOLL_SKIP_BUILD="1")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, check=True)
    assert int(out.stdout.split()[0]) == 5

    for knob, bad in (("TPUCOLL_CODEC_THREADS", "0"),
                      ("TPUCOLL_CODEC_THREADS", "banana"),
                      ("TPUCOLL_CODEC_PIPELINE", "0"),
                      ("TPUCOLL_CODEC_PIPELINE", "33")):
        env = dict(os.environ, TPUCOLL_SKIP_BUILD="1", **{knob: bad})
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True)
        assert r.returncode != 0, (knob, bad)
        assert knob in r.stderr, r.stderr[-300:]


# ---------------------------------------------------------------------------
# q4 codec properties
# ---------------------------------------------------------------------------

def test_q4_block_default_and_layout():
    assert gloo_tpu.q4_block() == BLOCK
    # One f32 scale per block plus one byte per element PAIR (dangling
    # odd element still costs a byte, high nibble zero).
    assert gloo_tpu.q4_wire_bytes(0) == 0
    assert gloo_tpu.q4_wire_bytes(1) == 4 + 1
    assert gloo_tpu.q4_wire_bytes(2) == 4 + 1
    assert gloo_tpu.q4_wire_bytes(3) == 4 + 2
    assert gloo_tpu.q4_wire_bytes(BLOCK) == 4 + BLOCK // 2
    assert gloo_tpu.q4_wire_bytes(BLOCK + 1) == 2 * 4 + BLOCK // 2 + 1
    assert gloo_tpu.q4_wire_bytes(10 * BLOCK) == 10 * (4 + BLOCK // 2)
    # Half of q8's wire for block-aligned streams.
    assert (gloo_tpu.q4_wire_bytes(8 * BLOCK) - 8 * 4 ==
            (gloo_tpu.q8_wire_bytes(8 * BLOCK) - 8 * 4) // 2)


@pytest.mark.parametrize("n", [1, 2, 7, BLOCK - 1, BLOCK, BLOCK + 1,
                               4 * BLOCK + 13])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_q4_roundtrip_error_bound(n, seed):
    """Property: per element, |x - decode(encode(x))| <= max|block|/14
    (half a quantization step at scale = max|block|/7), modulo one ulp
    of slack for the scale division rounding."""
    rng = np.random.default_rng(seed)
    x = (rng.standard_normal(n) *
         10.0 ** rng.integers(-3, 4, size=n)).astype(np.float32)
    wire = gloo_tpu.q4_encode(x)
    assert wire.nbytes == gloo_tpu.q4_wire_bytes(n)
    y = gloo_tpu.q4_decode(wire, n)
    for start in range(0, n, BLOCK):
        blk = x[start:start + BLOCK]
        bound = np.abs(blk).max() / 14.0 * (1 + 1e-6)
        err = np.abs(blk - y[start:start + BLOCK]).max()
        assert err <= bound, (start, err, bound)


def test_q4_zero_block_exact_and_type_checks():
    z = np.zeros(2 * BLOCK + 5, dtype=np.float32)
    assert np.array_equal(gloo_tpu.q4_decode(gloo_tpu.q4_encode(z), z.size),
                          z)
    with pytest.raises(Error):
        gloo_tpu.q4_encode(np.zeros(8, dtype=np.float64))
    with pytest.raises(Error):
        gloo_tpu.q4_decode(np.zeros(8, dtype=np.float32), 4)


def test_q4_block_env_knob():
    code = ("import gloo_tpu; "
            "print(gloo_tpu.q4_block(), gloo_tpu.q4_wire_bytes(1000))")
    env = dict(os.environ, TPUCOLL_Q4_BLOCK="512", TPUCOLL_SKIP_BUILD="1")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, check=True)
    block, wire = map(int, out.stdout.split())
    assert block == 512 and wire == 2 * 4 + 500
    for bad in ("0", "7", "4096", "banana"):
        env = dict(os.environ, TPUCOLL_Q4_BLOCK=bad, TPUCOLL_SKIP_BUILD="1")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True)
        assert r.returncode != 0, bad
        assert "TPUCOLL_Q4_BLOCK" in r.stderr, r.stderr[-300:]


# ---------------------------------------------------------------------------
# Error-feedback residuals
# ---------------------------------------------------------------------------

def test_error_feedback_telescopes_repeated_encodes():
    """The EF recurrence, proven on the codec itself: encoding the SAME
    vector k times without feedback accumulates k independent rounding
    errors in the summed stream, while with feedback (encode x + res,
    res = input - decoded) the summed decodes telescope to within ONE
    rounding of k*x — the mechanism wire_ring.cc applies per hop."""
    rng = np.random.default_rng(42)
    x = rng.standard_normal(4 * BLOCK).astype(np.float32)
    k = 32
    step_bound = np.abs(x).max() / 254.0

    plain = np.zeros_like(x, dtype=np.float64)
    for _ in range(k):
        plain += gloo_tpu.q8_decode(gloo_tpu.q8_encode(x), x.size)
    plain_err = np.abs(plain - k * x.astype(np.float64)).max()

    ef = np.zeros_like(x, dtype=np.float64)
    res = np.zeros_like(x)
    for _ in range(k):
        t = x + res
        d = gloo_tpu.q8_decode(gloo_tpu.q8_encode(t), x.size)
        res = t - d
        ef += d
    ef_err = np.abs(ef - k * x.astype(np.float64)).max()

    # Same input every round -> the plain rounding error is deterministic
    # and accumulates linearly (unless x happens to be exactly
    # representable); EF stays within ~2 single-step bounds no matter
    # how large k grows.
    assert ef_err <= 2.5 * step_bound, (ef_err, step_bound)
    assert ef_err < plain_err / 4, (ef_err, plain_err)


def test_error_feedback_tightens_native_allreduce():
    """The native engine, A/B over TPUCOLL_WIRE_EF: repeated q8
    allreduces of the same gradient on a cached plan accumulate bias
    without EF and telescope with it. Measured end to end through the
    collective, not the codec. The buffer is reused so the plan (and
    its slot-3 residual) survives between calls — the SGD regime EF
    targets; a fresh buffer per call makes EF a deliberate no-op."""
    code = """
import sys, threading
import numpy as np
import gloo_tpu
store = gloo_tpu.HashStore()
out = [None]
STEPS, COUNT = 24, 4 * 256
def worker(rank):
    ctx = gloo_tpu.Context(rank, 2, timeout=60)
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    g = (np.random.default_rng(9).standard_normal(COUNT)
         .astype(np.float32) * (rank + 1))
    x = np.empty_like(g)  # ONE buffer: cached plan keeps the residual
    total = np.zeros(COUNT, dtype=np.float64)
    for _ in range(STEPS):
        x[:] = g
        ctx.allreduce(x, algorithm="ring_q8_wire")
        total += x
    if rank == 0:
        exact = (np.random.default_rng(9).standard_normal(COUNT)
                 .astype(np.float64) * 3 * STEPS)
        print("ERR", np.abs(total - exact).max())
    ctx.barrier(); ctx.close()
ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
[t.start() for t in ts]; [t.join(120) for t in ts]
"""
    errs = {}
    for ef in ("0", "1"):
        env = dict(os.environ, TPUCOLL_WIRE_EF=ef, TPUCOLL_SKIP_BUILD="1")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=180)
        assert r.returncode == 0, r.stderr[-500:]
        errs[ef] = float(r.stdout.split("ERR", 1)[1].split()[0])
    # EF must measurably tighten the accumulated error (acceptance
    # criterion); 2x is conservative — the bias mechanism gives ~10x+.
    assert errs["1"] < errs["0"] / 2, errs


# ---------------------------------------------------------------------------
# Pipelined hop: A/B byte identity + consensus for the new arms
# ---------------------------------------------------------------------------

_AB_CODE = """
import sys, threading
import numpy as np
import gloo_tpu
algo = sys.argv[1]
count = int(sys.argv[2])
store = gloo_tpu.HashStore()
out = [None] * 3
def worker(rank):
    ctx = gloo_tpu.Context(rank, 3, timeout=60)
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    x = (np.random.default_rng(5).standard_normal(count)
         .astype(np.float32) * (rank + 1))
    ctx.allreduce(x, algorithm=algo)
    out[rank] = x
    ctx.barrier(); ctx.close()
ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
[t.start() for t in ts]; [t.join(120) for t in ts]
assert all(o is not None for o in out)
assert np.array_equal(out[0], out[1]) and np.array_equal(out[0], out[2])
sys.stdout.buffer.write(out[0].tobytes())
"""


@pytest.mark.parametrize("algo,count", [
    ("ring_q8_wire", 3 * BLOCK * 7),   # block-aligned: fused arm engages
    ("ring_q8_wire", 10_007),          # ragged sub-spans
    ("ring_q4_wire", 3 * BLOCK * 7),
    ("ring_bf16_wire", 9_999),
])
def test_pipeline_depth_is_invisible_in_the_bytes(algo, count):
    """The pipelined engine's core contract: depth (and codec pool
    width) change WHO computes and WHEN bytes move, never the bytes.
    Depth 1 serial vs depth 8 with a 4-wide pool: identical results."""
    blobs = {}
    for label, extra in (
            ("serial", {"TPUCOLL_CODEC_PIPELINE": "1",
                        "TPUCOLL_CODEC_THREADS": "1"}),
            ("piped", {"TPUCOLL_CODEC_PIPELINE": "8",
                       "TPUCOLL_CODEC_THREADS": "4"})):
        env = dict(os.environ, TPUCOLL_SKIP_BUILD="1", **extra)
        r = subprocess.run(
            [sys.executable, "-c", _AB_CODE, algo, str(count)],
            env=env, capture_output=True, timeout=240)
        assert r.returncode == 0, r.stderr[-500:]
        blobs[label] = r.stdout
    assert blobs["serial"] == blobs["piped"]


@pytest.mark.parametrize("size", [2, 3, 4])
def test_q4_allreduce_accuracy_and_consensus(size):
    """ring_q4_wire at P=2..4: within the q4 per-hop bound of the exact
    sum, and ALL ranks byte-identical (verbatim allgather forwarding)."""
    count = 10_007

    def fn(ctx, rank):
        rng = np.random.default_rng(11)
        x = rng.standard_normal(count).astype(np.float32) * (rank + 1)
        ctx.allreduce(x, algorithm="ring_q4_wire")
        return x

    results = spawn(size, fn, timeout=90)
    scale = sum(r + 1 for r in range(size))
    exact = (np.random.default_rng(11).standard_normal(count)
             .astype(np.float32) * scale)
    rel = (np.abs(results[0] - exact).max() /
           max(np.abs(exact).max(), 1e-9))
    # Per-hop bound is max/14 (~7%); P-1 hops + final quantization.
    assert rel < 0.2 * size, rel
    for r in range(1, size):
        assert np.array_equal(results[0], results[r]), f"rank {r} differs"


@pytest.mark.parametrize("size", [2, 3, 4])
def test_q4_reduce_scatter_consensus(size):
    """ring_q4_wire reduce_scatter at P=2..4 (wire="q4" shorthand):
    result blocks approximate the exact segment (f32 accumulator, only
    hops quantize)."""
    counts = [700 + 13 * r for r in range(size)]

    def fn(ctx, rank):
        x = np.arange(sum(counts), dtype=np.float32) * (rank + 1) / 100.0
        return ctx.reduce_scatter(x, recv_counts=counts, wire="q4")

    results = spawn(size, fn, timeout=90)
    total = sum(r + 1 for r in range(size))
    full = np.arange(sum(counts), dtype=np.float32) * total / 100.0
    offs = np.cumsum([0] + counts)
    for r in range(size):
        seg = full[offs[r]:offs[r + 1]]
        rel = (np.abs(results[r] - seg).max() /
               max(np.abs(seg).max(), 1e-9))
        assert rel < 0.1 * size, (r, rel)


@pytest.mark.parametrize("size", [2, 3, 4])
def test_pipelined_q8_allreduce_consensus(size):
    """The default (pipelined, depth 4) q8 hop across P=2..4 — the
    engine rewrite must preserve the q8 consensus contract unchanged."""
    count = 4 * BLOCK * size + 17

    def fn(ctx, rank):
        rng = np.random.default_rng(3)
        x = rng.standard_normal(count).astype(np.float32) * (rank + 1)
        ctx.allreduce(x, algorithm="ring_q8_wire")
        return x

    results = spawn(size, fn, timeout=90)
    for r in range(1, size):
        assert np.array_equal(results[0], results[r]), f"rank {r} differs"


def test_q4_wire_kwarg_and_conflicts():
    def fn(ctx, rank):
        x = np.ones(5000, dtype=np.float32)
        ctx.allreduce(x, wire="q4")
        out = x.copy()
        with pytest.raises(Error):
            ctx.allreduce(x, wire="q4", algorithm="ring")
        with pytest.raises(Error):
            ctx.allreduce(np.ones(16, dtype=np.int32), wire="q4")
        with pytest.raises(Error):
            ctx.allreduce(np.ones(16, dtype=np.float32), op="max",
                          wire="q4")
        return out

    results = spawn(2, fn, timeout=60)
    assert np.array_equal(results[0], results[1])
    # ones are exactly representable at any block scale: lossless here.
    assert np.array_equal(results[0], np.full(5000, 2.0, dtype=np.float32))


def test_q4_swept_but_not_auto_elected():
    """The tuner sweeps ring_q4_wire (headroom data) and the table JSON
    round-trips the new algorithm id, but plain kAuto never elects a
    lossy arm — q4 is reachable only through the lossy opt-in."""
    def fn(ctx, rank):
        table = gloo_tpu.tuning.tune(ctx, min_bytes=1 << 10,
                                     max_bytes=1 << 12, iters=1, warmup=0)
        ctx.allreduce(np.ones(256, dtype=np.float32), tag=7)
        algos = [e.get("algo") for e in ctx.flightrec()["events"]
                 if e.get("op") == "allreduce"]
        return table, algos

    table, algos = spawn(2, fn, timeout=240)[0]
    swept = {e["algorithm"] for e in table["entries"]
             if e["collective"] == "allreduce"}
    assert "ring_q4_wire" in swept, swept
    rs_swept = {e["algorithm"] for e in table["entries"]
                if e["collective"] == "reduce_scatter"}
    assert "ring_q4_wire" in rs_swept, rs_swept
    # The post-tune dispatch (plain auto) stayed lossless.
    assert algos[-1] not in ("ring_q4_wire", "ring_q8_wire",
                             "ring_bf16_wire"), algos


# ---------------------------------------------------------------------------
# Chaos determinism with the codec pool on
# ---------------------------------------------------------------------------

def test_chaos_same_seed_determinism_with_codec_pool():
    """Same-seed chaos with a 4-wide codec pool and a deep pipeline:
    worker threads shard the codec dynamically, but shard boundaries are
    deterministic, so two runs produce byte-identical fault reports AND
    byte-identical collective results."""
    code = """
import json, sys
import numpy as np
import gloo_tpu
from gloo_tpu import fault
from tests.harness import spawn

schedule = {"seed": 2222, "faults": [
    {"when": {"rank": 1, "opcode": "data", "min_bytes": 64},
     "action": "delay", "ms": 1, "prob": 0.5, "seed": 77},
]}

def workload():
    def fn(ctx, rank):
        rng = np.random.default_rng(4)
        base = rng.standard_normal(3 * 256 * 4).astype(np.float32)
        outs = []
        for i in range(4):
            x = base * (rank + 1 + i)
            ctx.allreduce(x, algorithm="ring_q8_wire", tag=10 + i)
            outs.append(x)
        return outs
    results = spawn(3, fn, timeout=120)
    for i in range(4):
        assert np.array_equal(results[0][i], results[1][i])
        assert np.array_equal(results[0][i], results[2][i])
    rep = [json.dumps(fault.report(rank=r), sort_keys=True)
           for r in range(3)]
    return rep, results[0]

fault.install(schedule)
rep1, out1 = workload()
fault.install(schedule)
rep2, out2 = workload()
fault.clear()
assert rep1 == rep2
for a, b in zip(out1, out2):
    assert np.array_equal(a, b)
print("CHAOS_OK")
"""
    env = dict(os.environ, TPUCOLL_SKIP_BUILD="1",
               TPUCOLL_CODEC_THREADS="4", TPUCOLL_CODEC_PIPELINE="6")
    env["PYTHONPATH"] = os.getcwd()
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=os.getcwd())
    assert r.returncode == 0, r.stderr[-800:]
    assert "CHAOS_OK" in r.stdout
